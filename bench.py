"""Headline benchmark: GBM histogram-tree training throughput (rows/sec/chip).

Mirrors the reference's north-star config (BASELINE.json: "GBM on HIGGS 11M,
hex.tree.gbm histogram aggregation on TPU"). Data is synthetic HIGGS-shaped
(28 float features, binary response) because the 11M-row dataset is not
shipped in-image; throughput is feature-count/row-count bound, not
data-distribution bound, so the synthetic proxy is faithful for rows/sec.

vs_baseline anchor: the reference has no committed GBM rows/sec (BASELINE.md);
we anchor against 1.0M rows/sec/device — the order of magnitude of XGBoost
`gpu_hist` on HIGGS-class data on a modern accelerator, which BASELINE.json
names as the parity target ("XGBoost-TPU matching gpu_hist A100 rows/sec").

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

ROWS = int(sys.argv[1]) if len(sys.argv) > 1 else 2_000_000
NFEAT = 28
NTREES = 20
DEPTH = 6
NBINS = 64
ANCHOR_ROWS_PER_SEC = 1.0e6  # gpu_hist-class anchor (see module docstring)


def main() -> None:
    import jax
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import GBM

    rng = np.random.default_rng(11)
    X = rng.normal(size=(ROWS, NFEAT)).astype(np.float32)
    logit = X[:, :4] @ np.array([1.2, -0.8, 0.5, 0.3], np.float32) + 0.2 * X[:, 4] * X[:, 5]
    y = (rng.random(ROWS) < 1.0 / (1.0 + np.exp(-logit))).astype(np.int32)

    cols = {f"x{i}": X[:, i] for i in range(NFEAT)}
    cols["y"] = np.where(y == 1, "s", "b")
    fr = Frame.from_arrays(cols)

    def train():
        return GBM(ntrees=NTREES, max_depth=DEPTH, nbins=NBINS,
                   learn_rate=0.1, seed=42).train(y="y", training_frame=fr)

    train()  # warm-up: compile every level program
    jax.effects_barrier()
    t0 = time.perf_counter()
    model = train()
    jax.effects_barrier()
    dt = time.perf_counter() - t0

    ndev = max(1, len(jax.devices()))
    rows_per_sec_chip = ROWS * NTREES / dt / ndev
    print(json.dumps({
        "metric": "gbm_hist_train_rows_per_sec_per_chip",
        "value": round(rows_per_sec_chip, 1),
        "unit": "rows*trees/sec/chip",
        "vs_baseline": round(rows_per_sec_chip / ANCHOR_ROWS_PER_SEC, 3),
    }))
    # secondary detail on stderr (not parsed by the driver)
    auc = getattr(model.training_metrics, "auc", None)
    print(f"# trained {NTREES} trees depth {DEPTH} on {ROWS} rows in {dt:.2f}s; "
          f"train AUC={auc}", file=sys.stderr)


if __name__ == "__main__":
    main()
