"""Headline benchmarks at BASELINE.json spec scale.

All five BASELINE.json configs run:

1. **GBM on HIGGS-shaped 11M rows** (primary metric) — histogram-tree
   training rows*trees/sec/chip. vs_baseline anchor: 1.0M rows·trees/sec/
   device. Context (see ROOFLINE.md for the full accounting): published
   A100 `gpu_hist` rides hardware atomic adds at ~the HBM floor
   (~50-150M rows·trees/s on HIGGS); a v5e has no scatter hardware, and
   the MXU one-hot formulation measured in ROOFLINE.md is its ceiling —
   the anchor marks the competitive-on-this-silicon line, not A100 parity.
2. **XGBoost config** — same data, 256 bins / depth 6 (the reference's
   `tree_method=hist` defaults; h2o-extensions/xgboost).
3. **GLM logistic regression, airlines-scale** — 1M×12 IRLS to
   convergence, rows·iters/sec/chip (BASELINE config 1).
4. **DeepLearning MLP** — MNIST-shaped 784-50-50-10 Rectifier, samples/sec/
   chip (reference: 294 samples/s on 1× i7-5820k, dlperf.Rmd:375).
5. **AutoML leaderboard** — wall-clock for a 5-model leaderboard on 100k
   rows (reference config: "AutoML leaderboard on Lending Club").

Prints ONE JSON line: the primary GBM metric with the other configs under
"extra". Data is synthetic (zero-egress image): throughput is shape-bound,
not distribution-bound, so rows/sec is faithful. Reported AUCs are on the
synthetic task (not comparable to published HIGGS numbers); model QUALITY
at this scale is pinned separately by ``tests/test_accuracy_1m.py``, which
holds holdout AUC within 3e-3 of sklearn's HistGradientBoosting on 1M rows.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# Set (to the preflight diagnostic) when the TPU backend was found sick and
# the bench re-exec'd itself on CPU at reduced scale — see _probe_backend().
CPU_FALLBACK = os.environ.get("_H2O3TPU_BENCH_CPU_FALLBACK", "")

# Smoke mode (tests/test_entry.py): every config at toy scale so the whole
# bench pipeline — preflight, fallback re-exec, JSON emission — runs in
# seconds on CPU. Numbers are meaningless; the artifact shape is the point.
SMOKE = os.environ.get("H2O3TPU_BENCH_SMOKE", "") == "1"

ROWS = int(sys.argv[1]) if len(sys.argv) > 1 else (4_000 if SMOKE else 11_000_000)
NFEAT = 28
NTREES = 3 if SMOKE else 20
DEPTH = 3 if SMOKE else 6
NBINS = 16 if SMOKE else 64
ANCHOR_ROWS_PER_SEC = 1.0e6  # gpu_hist-class anchor (see module docstring)
DL_REF_SAMPLES_PER_SEC = 294.0  # dlperf.Rmd:375 Rectifier on i7-5820k


def _hardware_fingerprint() -> dict:
    """``extra.hardware``: the exact silicon + software stack this artifact
    was measured on, so cross-round comparisons are self-explaining (the
    r03 no-TPU wobble took a VERDICT post-mortem to attribute; a stamped
    fingerprint makes it one diff). Fields mirror what the compute
    observatory keys its peak table on (utils/costs.py PEAK_TABLE)."""
    import jax
    try:
        import jaxlib
        jaxlib_ver = getattr(jaxlib, "__version__", None)
    except ImportError:   # pragma: no cover — jaxlib ships with jax
        jaxlib_ver = None
    devs = jax.devices()
    return {"backend": jax.default_backend(),
            "device_kind": devs[0].device_kind if devs else None,
            "devices": len(devs),
            "jax": jax.__version__, "jaxlib": jaxlib_ver}


def _steady_state_recompiles(scenario: str, sig0: int) -> dict:
    """Post-warmup recompile probe for a warm steady-state scenario:
    ``sig0`` is ``COSTS.signature_count()`` taken AFTER the scenario's
    warm-up call — any growth by now means the timed, shape-identical
    re-run compiled a fresh signature (the r04→r05 automl wobble class of
    regression). The compute gate refuses to stamp on it."""
    from h2o3_tpu.utils.costs import COSTS
    return {"scenario": scenario,
            "recompiles_steady_state": COSTS.signature_count() - sig0}


def _higgs_frame(rows: int):
    from h2o3_tpu.frame.frame import Frame
    rng = np.random.default_rng(11)
    X = rng.normal(size=(rows, NFEAT)).astype(np.float32)
    logit = X[:, :4] @ np.array([1.2, -0.8, 0.5, 0.3], np.float32) \
        + 0.2 * X[:, 4] * X[:, 5]
    y = (rng.random(rows) < 1.0 / (1.0 + np.exp(-logit))).astype(np.int32)
    cols = {f"x{i}": X[:, i] for i in range(NFEAT)}
    cols["y"] = np.where(y == 1, "s", "b")
    return Frame.from_arrays(cols)


def bench_gbm(fr, ndev: int) -> dict:
    import jax
    from h2o3_tpu.models.gbm import GBM

    def train():
        return GBM(ntrees=NTREES, max_depth=DEPTH, nbins=NBINS,
                   learn_rate=0.1, seed=42).train(y="y", training_frame=fr)

    from h2o3_tpu.utils.costs import COSTS
    train()  # warm-up: compile every level program
    jax.effects_barrier()
    sig0 = COSTS.signature_count()
    t0 = time.perf_counter()
    model = train()
    jax.effects_barrier()
    dt = time.perf_counter() - t0
    rps = fr.nrows * NTREES / dt / ndev
    return dict(rows_per_sec_chip=round(rps, 1), seconds=round(dt, 2),
                auc=round(float(model.training_metrics.auc), 4),
                **_steady_state_recompiles("gbm_higgs_11m", sig0))


def bench_xgboost(fr, ndev: int) -> dict:
    """XGBoost-config run: 256 bins, depth 6, eta 0.3 (hist defaults)."""
    import jax
    from h2o3_tpu.models.xgboost import XGBoost

    nt = 2 if SMOKE else 10
    bins, depth = (16, 3) if SMOKE else (256, 6)

    def train():
        return XGBoost(ntrees=nt, max_depth=depth, max_bin=bins, eta=0.3,
                       seed=42).train(y="y", training_frame=fr)

    from h2o3_tpu.utils.costs import COSTS
    train()
    jax.effects_barrier()
    sig0 = COSTS.signature_count()
    t0 = time.perf_counter()
    model = train()
    jax.effects_barrier()
    dt = time.perf_counter() - t0
    rps = fr.nrows * nt / dt / ndev
    return dict(rows_per_sec_chip=round(rps, 1), seconds=round(dt, 2),
                auc=round(float(model.training_metrics.auc), 4),
                **_steady_state_recompiles("xgboost_hist_11m", sig0))


def bench_glm(ndev: int) -> dict:
    """Airlines-scale logistic GLM (BASELINE config 1): 1M×12 binomial
    IRLS to convergence; metric = rows·iterations/sec/chip."""
    import jax
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.glm import GLM

    n = 5_000 if SMOKE else (200_000 if CPU_FALLBACK else 1_000_000)
    rng = np.random.default_rng(13)
    X = rng.normal(size=(n, 12)).astype(np.float32)
    logit = X[:, :5] @ np.array([0.8, -0.5, 0.3, -0.2, 0.4], np.float32)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit)))
    cols = {f"x{i}": X[:, i] for i in range(12)}
    cols["dep_delayed"] = np.where(y, "YES", "NO")
    fr = Frame.from_arrays(cols)

    def train():
        b = GLM(family="binomial", lambda_=1e-4, max_iterations=30)
        m = b.train(y="dep_delayed", training_frame=fr)
        return m, len(b._iter_devs)

    from h2o3_tpu.utils.costs import COSTS
    train()   # warm-up compiles
    jax.effects_barrier()
    sig0 = COSTS.signature_count()
    t0 = time.perf_counter()
    model, iters = train()
    jax.effects_barrier()
    dt = time.perf_counter() - t0
    return dict(rows_iters_per_sec_chip=round(n * iters / dt / ndev, 1),
                iterations=iters, seconds=round(dt, 2),
                auc=round(float(model.training_metrics.auc), 4),
                **_steady_state_recompiles("glm_airlines_1m", sig0))


def bench_dl(ndev: int) -> dict:
    """MNIST-shaped MLP 784-50-50-10 Rectifier (dlperf.Rmd config)."""
    import jax
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.deeplearning import DeepLearning

    n = 2_000 if SMOKE else (10_000 if CPU_FALLBACK else 60_000)
    rng = np.random.default_rng(5)
    X = rng.normal(size=(n, 784)).astype(np.float32)
    yv = rng.integers(0, 10, size=n)
    cols = {f"p{i}": X[:, i] for i in range(784)}
    cols["y"] = np.array([str(d) for d in yv], dtype=object)
    fr = Frame.from_arrays(cols)

    epochs = 1 if SMOKE else 3

    def train():
        return DeepLearning(hidden=[50, 50], activation="Rectifier",
                            epochs=epochs, mini_batch_size=128, seed=7).train(
            y="y", training_frame=fr)

    from h2o3_tpu.utils.costs import COSTS
    train()
    jax.effects_barrier()
    sig0 = COSTS.signature_count()
    t0 = time.perf_counter()
    train()
    jax.effects_barrier()
    dt = time.perf_counter() - t0
    sps = n * epochs / dt / ndev
    return dict(samples_per_sec_chip=round(sps, 1), seconds=round(dt, 2),
                vs_reference_cpu=round(sps / DL_REF_SAMPLES_PER_SEC, 1),
                **_steady_state_recompiles("dl_mlp_mnist", sig0))


def bench_automl(ndev: int) -> dict:
    """Leaderboard wall-clock: 5 models on 100k rows (Lending-Club-scale).
    Runs parallelism 1/2/4 — overlapped builds now lease DISJOINT mesh
    slices from the MeshScheduler (orchestration/scheduler.py), so par>1
    is real device concurrency, not just host-thread overlap. Per-run
    compile-cache hit/miss counts ride along: the r04→r05 wobble
    (32.6s→42.2s) was recompiles, and the artifact now attributes compile
    time vs overlap per parallelism level with data."""
    from h2o3_tpu.orchestration import AutoML
    from h2o3_tpu.orchestration.scheduler import SLICE_STATS
    from h2o3_tpu.utils import compile_cache

    fr = _higgs_frame(3_000 if SMOKE else (20_000 if CPU_FALLBACK else 100_000))
    out: dict = {}
    # single-device clouds degrade to one slice, so the par sweep only
    # measures host-thread overlap there — one overlapped pass suffices;
    # with >= 2 devices the sweep measures slice concurrency for real
    pars = (2,) if ndev < 2 else ((1, 2) if (SMOKE or ndev < 4) else (1, 2, 4))
    cc: dict = {}
    sl: dict = {}
    for par in pars:
        c0 = compile_cache.stats()
        SLICE_STATS.reset()
        t0 = time.perf_counter()
        aml = AutoML(max_models=2 if SMOKE else 5, nfolds=0, seed=1,
                     parallelism=par)
        aml.train(y="y", training_frame=fr)
        out[f"seconds_par{par}"] = round(time.perf_counter() - t0, 2)
        out["models"] = len(aml.leaderboard)
        c1 = compile_cache.stats()
        # by_site deltas (CostMeter scope attribution): the r04→r05 wobble
        # could only say "something recompiled" — this names WHICH loop
        by_site = {
            site: {k: st[k] - (c0["by_site"].get(site) or
                               {"hits": 0, "misses": 0})[k]
                   for k in ("hits", "misses")}
            for site, st in c1["by_site"].items()}
        cc[f"par{par}"] = {"cache_hits": c1["hits"] - c0["hits"],
                           "cache_misses": c1["misses"] - c0["misses"],
                           "by_site": {s: d for s, d in by_site.items()
                                       if d["hits"] or d["misses"]}}
        # keyed per par level like compile_cache_per_run — utilization and
        # queue wait are only comparable across par levels if each level
        # keeps its own snapshot
        sl[f"par{par}"] = SLICE_STATS.snapshot()
    out["compile_cache_per_run"] = cc
    out["slices"] = sl
    out["seconds"] = out["seconds_par2"]
    if "seconds_par1" in out:
        out["overlap_speedup"] = round(
            out["seconds_par1"] / max(out["seconds_par2"], 1e-9), 2)
    if "seconds_par4" in out:
        out["slice_speedup_par4"] = round(
            out["seconds_par1"] / max(out["seconds_par4"], 1e-9), 2)
    return out


def _slices_gate(out: dict) -> None:
    """Refuse to stamp when slice scheduling makes AutoML SLOWER: on a real
    multi-device run (>= 4 devices, not smoke/fallback), parallelism=4 on
    disjoint slices must not lose to sequential full-mesh builds — a
    regression here means leases serialize or resharding dominates."""
    aml = (out.get("extra") or {}).get("automl_leaderboard_100k") or {}
    p1, p4 = aml.get("seconds_par1"), aml.get("seconds_par4")
    if SMOKE or CPU_FALLBACK or p1 is None or p4 is None:
        return
    # 10% margin: AutoML wall clock is noisy (the r04→r05 recompile wobble
    # was 29%); the gate catches leases serializing or resharding
    # dominating, not jitter
    if p4 > p1 * 1.10:
        print(f"# bench: REFUSING artifact — automl par4 ({p4}s) slower "
              f"than par1 ({p1}s) on a {out['extra'].get('devices')}-device "
              "run (mesh-slice scheduling regressed)", file=sys.stderr)
        sys.exit(3)


def bench_scoring(ndev: int) -> dict:
    """Serving-path throughput: concurrent closed-loop clients against a
    trained GBM + GLM through ``POST /3/Score`` (compiled, micro-batched —
    docs/SERVING.md) vs the sequential per-request ``/3/Predictions`` path
    on the same 16-row payload. Emits qps, latency p50/p99, mean batch
    size, and the scorer-cache counters — the serving path's perf
    trajectory next to the training path's."""
    import threading

    from h2o3_tpu.api import H2OClient, H2OServer
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import GBM
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.serving import SCORING
    from h2o3_tpu.utils.registry import DKV

    n = 2_000 if SMOKE else 20_000
    rng = np.random.default_rng(31)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    logit = X[:, :3] @ np.array([1.0, -0.7, 0.4], np.float32)
    cols = {f"x{i}": X[:, i] for i in range(8)}
    cols["y"] = np.where(rng.random(n) < 1.0 / (1.0 + np.exp(-logit)),
                         "yes", "no")
    fr = Frame.from_arrays(cols, key="score_bench_frame")
    DKV.put("score_bench_frame", fr)
    gbm = GBM(ntrees=3 if SMOKE else 10, max_depth=4, seed=3,
              model_id="score_bench_gbm").train(y="y", training_frame=fr)
    glm = GLM(family="binomial", lambda_=1e-4,
              model_id="score_bench_glm").train(y="y", training_frame=fr)

    rows_per_req = 16
    payload = [{f"x{i}": float(X[r, i]) for i in range(8)}
               for r in range(rows_per_req)]
    seq_fr = Frame.from_arrays(
        {f"x{i}": X[:rows_per_req, i] for i in range(8)},
        key="score_bench_rows")
    DKV.put("score_bench_rows", seq_fr)

    server = H2OServer(port=0).start()
    try:
        client = H2OClient(server.url)
        duration = 0.5 if SMOKE else 2.0

        # sequential per-request predict path — the ONLY request-sized flow
        # the stack had before the serving tier (ISSUE 6 motivation): ship
        # the rows as a frame, run a full Model.predict, fetch the
        # prediction frame back, clean up. One closed-loop client.
        import csv
        import io
        import tempfile
        buf = io.StringIO()
        w = csv.writer(buf)
        w.writerow([f"x{i}" for i in range(8)])
        for r in range(rows_per_req):
            w.writerow([repr(float(X[r, i])) for i in range(8)])
        with tempfile.NamedTemporaryFile("w", suffix=".csv",
                                         delete=False) as tf:
            tf.write(buf.getvalue())
            seq_csv = tf.name

        def predict_roundtrip(i: int) -> None:
            fk = client.upload_file(seq_csv, destination_frame=f"seq_{i}")
            pk = client.predict(gbm.key, fk)
            client.frame(pk)                   # fetch predictions back
            client.rm(pk)
            client.rm(fk)

        predict_roundtrip(-1)                  # warm compile (self-cleaning)
        nseq, t0 = 0, time.perf_counter()
        while time.perf_counter() - t0 < duration:
            predict_roundtrip(nseq)
            nseq += 1
        seq_qps = nseq / (time.perf_counter() - t0)

        # the resident-frame variant (frame already in DKV — no upload, no
        # fetch) isolates the narrowed predict critical section; reported
        # for transparency, not the comparator a request-sized client sees
        DKV.remove(client.predict(gbm.key, "score_bench_rows"))  # warm
        pred_keys, t0 = [], time.perf_counter()
        while time.perf_counter() - t0 < duration:
            pred_keys.append(client.predict(gbm.key, "score_bench_rows"))
        resident_qps = len(pred_keys) / (time.perf_counter() - t0)
        for k in pred_keys:
            DKV.remove(k)

        # batched path: closed-loop thread-pool clients, both models hot.
        # Warm every bucket the pool can reach (nclients * rows_per_req
        # coalesced rows max), so the timed window asserts zero compiles.
        for nb in (1, 2, 4, 8):
            client.score(gbm.key, payload * nb)
            client.score(glm.key, payload * nb)
        cache0 = SCORING.cache.stats()
        from h2o3_tpu.utils.telemetry import SCORE_BATCH_SIZE
        bs0_sum, bs0_cnt = SCORE_BATCH_SIZE._default().sum, \
            SCORE_BATCH_SIZE._default().count
        nclients = 2 if SMOKE else 8
        lat_lock = threading.Lock()
        latencies: list[float] = []
        counts = [0] * nclients
        client_errors: list[BaseException] = []
        stop_at = time.perf_counter() + duration

        def work(i: int) -> None:
            cl = H2OClient(server.url)
            key = gbm.key if i % 2 == 0 else glm.key
            mine = []
            try:
                while time.perf_counter() < stop_at:
                    r0 = time.perf_counter()
                    cl.score(key, payload)
                    mine.append(time.perf_counter() - r0)
                    counts[i] += 1
            except BaseException as e:   # noqa: BLE001 — surfaced after join
                client_errors.append(e)
            finally:
                with lat_lock:
                    latencies.extend(mine)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(nclients)]
        bt0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        bt = time.perf_counter() - bt0
        if client_errors:
            # a dead client thread would silently distort the gated numbers
            raise RuntimeError(
                f"{len(client_errors)} scoring client(s) failed; first: "
                f"{client_errors[0]!r}") from client_errors[0]
        total = sum(counts)
        lat = np.sort(np.array(latencies)) * 1e3
        cache1 = SCORING.cache.stats()
        bs_cnt = SCORE_BATCH_SIZE._default().count - bs0_cnt
        bs_sum = SCORE_BATCH_SIZE._default().sum - bs0_sum
        qps = total / bt
        return dict(
            score_qps=round(qps, 1),
            rows_per_sec=round(qps * rows_per_req, 1),
            latency_ms=dict(
                p50=round(float(np.percentile(lat, 50)), 3),
                p99=round(float(np.percentile(lat, 99)), 3)),
            mean_batch_size=round(bs_sum / max(bs_cnt, 1), 2),
            clients=nclients, rows_per_request=rows_per_req,
            requests=total, seconds=round(bt, 2),
            seq_predict_qps=round(seq_qps, 1),
            predict_resident_qps=round(resident_qps, 1),
            speedup_vs_predict=round(qps / max(seq_qps, 1e-9), 2),
            cache_hits=cache1["hits"] - cache0["hits"],
            cache_misses=cache1["misses"] - cache0["misses"])
    finally:
        server.stop()
        SCORING.reset()
        import contextlib
        import os as _os
        with contextlib.suppress(OSError, NameError):
            _os.unlink(seq_csv)
        # nothing from this scenario stays registered: the later memory
        # section's DKV totals / leak pass must reflect the workloads, not
        # serving-bench residue
        for k in ("score_bench_rows", "score_bench_frame",
                  "score_bench_gbm", "score_bench_glm"):
            DKV.remove(k)


def bench_serving_slo(ndev: int) -> dict:
    """SLO-held serving under open-loop arrivals WITH a concurrent GBM
    build (ISSUE 13 acceptance; docs/SERVING.md "SLO & replicas"): a
    replica pool (slice-leased when the mesh allows) serves a trained GBM
    at a p99 latency target while a second GBM trains in the background
    on the same process, arrivals fire at a fixed rate regardless of
    completions (open loop — queue pressure is real), and a quarter of
    the traffic is LOW priority so the shedding estimator has someone to
    turn away first. Emits p50/p99 vs the target, shed/503 rates by
    priority, per-replica busy/queue-wait, and the warm-window compile
    accounting the gate refuses recompiles on."""
    import queue as _queue
    import threading

    from h2o3_tpu.api import H2OClient, H2OServer
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import GBM
    from h2o3_tpu.serving import SCORING
    from h2o3_tpu.utils.registry import DKV

    target_slo_ms = 500.0 if SMOKE else 250.0
    duration = 1.0 if SMOKE else 3.0
    hi_pri, lo_pri = 8, 1

    n = 2_000 if SMOKE else 20_000
    rng = np.random.default_rng(47)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    logit = X[:, :3] @ np.array([1.0, -0.7, 0.4], np.float32)
    cols = {f"x{i}": X[:, i] for i in range(8)}
    cols["y"] = np.where(rng.random(n) < 1.0 / (1.0 + np.exp(-logit)),
                         "yes", "no")
    fr = Frame.from_arrays(cols, key="slo_bench_frame")
    DKV.put("slo_bench_frame", fr)
    serve_gbm = GBM(ntrees=3 if SMOKE else 10, max_depth=4, seed=5,
                    model_id="slo_bench_gbm").train(y="y", training_frame=fr)

    SCORING.reset()
    scheduler = None
    if ndev >= 2:
        from h2o3_tpu.orchestration.scheduler import MeshScheduler
        scheduler = MeshScheduler(slices=2)
        SCORING.configure_replicas(2, scheduler=scheduler)
    else:
        SCORING.configure_replicas(1)

    rows_per_req = 16
    payload = [{f"x{i}": float(X[r, i]) for i in range(8)}
               for r in range(rows_per_req)]

    server = H2OServer(port=0).start()
    train_err: list = []
    train_done = threading.Event()
    try:
        client = H2OClient(server.url)
        # warm every bucket open-loop bursts can coalesce into (workers
        # cap the burst at nworkers * rows_per_req rows), THEN join the
        # admission pre-compiles, THEN snapshot miss counters: the timed
        # window must compile nothing
        for nb in (1, 2, 4, 8, 16):
            client.score(serve_gbm.key, payload * nb, slo_ms=target_slo_ms)
        entry = SCORING._resident[serve_gbm.key]
        pool = SCORING.pool
        for rep in pool.replicas:
            rep.precompile(entry, buckets=(16, 32, 64, 128, 256)) \
                .join(timeout=300)
        # admission fired its own fire-and-forget precompiles (default
        # buckets) — wait for EVERY warm-up to drain before snapshotting
        # the miss counter, or a straggling compile lands in the timed
        # window and the gate refuses a perfectly warm run
        wdl = time.perf_counter() + 300
        while any(r.warming() for r in pool.replicas) \
                and time.perf_counter() < wdl:
            time.sleep(0.05)
        # FREEZE scaling for the timed window: a mid-window scale-up
        # would precompile buckets into a fresh replica's cache and the
        # monotonic miss counter would read as a warm-path recompile,
        # refusing the artifact spuriously (the scale policy itself is
        # pinned by tests/test_serving_slo.py, not timed here)
        pool.min_replicas = pool.max_replicas = len(pool.replicas)

        def cache_misses() -> int:
            # the process-global MONOTONIC miss counter, not a sum over
            # live caches: a mid-window scale-down clears the retired
            # replica's cache and a per-cache sum would go backwards
            from h2o3_tpu.utils.telemetry import SCORER_CACHE
            return int(SCORER_CACHE.labels(event="miss").value)

        # calibration: sequential warm requests size the open-loop rate
        cal = []
        for _ in range(3 if SMOKE else 10):
            c0 = time.perf_counter()
            client.score(serve_gbm.key, payload)
            cal.append(time.perf_counter() - c0)
        mean_s = max(float(np.mean(cal)), 1e-4)
        # ~1.5x the serial capacity of one seat: enough pressure that the
        # controller and (multi-device) the second replica matter, not so
        # much that the whole window sheds
        rate = min(max(1.5 / mean_s, 10.0), 400.0)

        misses0 = cache_misses()

        # the concurrent GBM build: training contends for the process
        # (and, without slices, the devices) for the whole window
        def train():
            try:
                GBM(ntrees=4 if SMOKE else 12, max_depth=5, seed=9,
                    model_id="slo_bench_train").train(
                        y="y", training_frame=fr)
            except BaseException as e:   # noqa: BLE001 — gate checks
                train_err.append(e)
            finally:
                train_done.set()

        trainer = threading.Thread(target=train, daemon=True)

        # open-loop: a metronome enqueues arrival tokens at `rate`
        # regardless of completions; a worker pool fires them
        arrivals: "_queue.Queue" = _queue.Queue()
        res_lock = threading.Lock()
        lat_ok: list = []
        codes = {"ok_hi": 0, "ok_lo": 0, "shed_hi": 0, "shed_lo": 0,
                 "other": 0}
        stop = threading.Event()

        def worker():
            cl = H2OClient(server.url)
            while True:
                try:
                    pri = arrivals.get(timeout=0.25)
                except _queue.Empty:
                    if stop.is_set():
                        return
                    continue
                r0 = time.perf_counter()
                try:
                    cl.score(serve_gbm.key, payload, priority=pri,
                             slo_ms=target_slo_ms)
                    dt = time.perf_counter() - r0
                    with res_lock:
                        lat_ok.append(dt)
                        codes["ok_hi" if pri == hi_pri else "ok_lo"] += 1
                except RuntimeError as e:
                    with res_lock:
                        if "503" in str(e):
                            codes["shed_hi" if pri == hi_pri
                                  else "shed_lo"] += 1
                        else:
                            codes["other"] += 1
                except BaseException:   # noqa: BLE001 — accounted
                    with res_lock:
                        codes["other"] += 1

        nworkers = 4 if SMOKE else 16
        workers = [threading.Thread(target=worker, daemon=True)
                   for _ in range(nworkers)]
        trainer.start()
        for w in workers:
            w.start()
        period = 1.0 / rate
        t0 = time.perf_counter()
        i = 0
        narrivals = 0
        while True:
            now = time.perf_counter()
            if now - t0 >= duration:
                break
            due = t0 + i * period
            if now < due:
                time.sleep(min(due - now, 0.01))
                continue
            # every 4th arrival is low priority: the shed policy's fodder
            arrivals.put(lo_pri if i % 4 == 3 else hi_pri)
            narrivals += 1
            i += 1
        stop.set()
        for w in workers:
            w.join(timeout=60)
        misses_timed = cache_misses() - misses0
        train_done.wait(timeout=600)
        trainer.join(timeout=10)

        lat = np.sort(np.array(lat_ok)) * 1e3 if lat_ok else np.array([])
        served = codes["ok_hi"] + codes["ok_lo"]
        shed = codes["shed_hi"] + codes["shed_lo"]
        st = SCORING.stats()
        entry_row = next((r for r in st["resident"]
                          if r["model"] == serve_gbm.key), None)
        return dict(
            target_slo_ms=target_slo_ms,
            open_loop_rate_rps=round(rate, 1),
            arrivals=narrivals, served=served,
            latency_ms=dict(
                p50=(round(float(np.percentile(lat, 50)), 3)
                     if lat.size else None),
                p99=(round(float(np.percentile(lat, 99)), 3)
                     if lat.size else None)),
            slo=entry_row["slo"] if entry_row else None,
            shed_total=shed,
            shed_rate=round(shed / max(narrivals, 1), 4),
            shed_by_priority={
                str(hi_pri): codes["shed_hi"], str(lo_pri): codes["shed_lo"]},
            served_by_priority={
                str(hi_pri): codes["ok_hi"], str(lo_pri): codes["ok_lo"]},
            server_shed=st["shed"], server_shed_total=st["shed_total"],
            other_errors=codes["other"],
            replicas=st["replicas"],
            cache_misses_timed=misses_timed,
            concurrent_build_completed=train_done.is_set()
            and not train_err,
            concurrent_build_error=(repr(train_err[0]) if train_err
                                    else None))
    finally:
        server.stop()
        SCORING.reset()
        for k in ("slo_bench_frame", "slo_bench_gbm", "slo_bench_train"):
            DKV.remove(k)


def _serving_slo_gate(sl: dict, backend: str) -> None:
    """Refuse to stamp when the SLO serving scenario is broken: the
    concurrent GBM build must complete, shed accounting must not read
    hollow (client-observed 503s and server shed counters must agree
    that shedding did or did not happen), the warm window must compile
    nothing, and on REAL hardware the served p99 must hold the target
    (CPU rounds skip the latency assertion — scheduler noise)."""
    if sl.get("skipped"):
        return
    if sl.get("error"):
        print(f"# bench REFUSED: serving-slo section failed: {sl['error']}",
              file=sys.stderr)
        sys.exit(3)
    if not sl["concurrent_build_completed"]:
        print("# bench REFUSED: concurrent GBM build did not complete "
              f"during the serving window: {sl.get('concurrent_build_error')}",
              file=sys.stderr)
        sys.exit(3)
    if sl["cache_misses_timed"] > 0:
        print(f"# bench REFUSED: {sl['cache_misses_timed']} scorer compiles "
              "inside the timed SLO window — the warm path is recompiling",
              file=sys.stderr)
        sys.exit(3)
    hollow = (sl["shed_total"] > 0) != (sl["server_shed_total"] > 0)
    if hollow:
        print(f"# bench REFUSED: shed accounting reads hollow — clients saw "
              f"{sl['shed_total']} 503s but the server accounted "
              f"{sl['server_shed_total']} sheds", file=sys.stderr)
        sys.exit(3)
    if sl["served"] == 0:
        print("# bench REFUSED: serving-slo window served zero requests",
              file=sys.stderr)
        sys.exit(3)
    real = backend not in ("cpu",) and not CPU_FALLBACK
    if real and not SMOKE:
        p99 = (sl.get("latency_ms") or {}).get("p99")
        if p99 is None or p99 > sl["target_slo_ms"]:
            print(f"# bench REFUSED: served p99 {p99}ms violates the "
                  f"{sl['target_slo_ms']}ms SLO on a real run",
                  file=sys.stderr)
            sys.exit(3)


def _scoring_gate(sc: dict) -> None:
    """Refuse to stamp an artifact whose serving path regressed: under
    concurrent load the batched /3/Score tier must beat the sequential
    per-request predict path by ≥3× (ISSUE 6 acceptance), and warm-path
    requests must not recompile (signature-cache misses after warm-up
    mean the compile cache regressed)."""
    if sc.get("error"):
        print(f"# bench REFUSED: scoring section failed: {sc['error']}",
              file=sys.stderr)
        sys.exit(3)
    if sc["cache_misses"] > 0:
        print(f"# bench REFUSED: {sc['cache_misses']} scorer-cache misses "
              "after warm-up — same-signature requests are recompiling",
              file=sys.stderr)
        sys.exit(3)
    if SMOKE:
        return          # shape-proof only; a 0.5s window is scheduler noise
    if sc["speedup_vs_predict"] < 3.0:
        print(f"# bench REFUSED: batched scoring speedup "
              f"{sc['speedup_vs_predict']}x < 3x over the per-request "
              "predict path", file=sys.stderr)
        sys.exit(3)


def bench_chaos(ndev: int) -> dict:
    """Completion-under-faults (ISSUE 8 acceptance): with ``drop_rate=0.02``
    on the dispatch path, GLM and GBM builds must complete with results
    within 1e-6 of the fault-free run — the retry/backoff layer absorbs the
    injected faults. A dispatch storm under the same injector exercises the
    retry path at volume, and the whole faulted phase runs under a WATCHDOG:
    a deadlocked chaos run records ``completed: false`` (the gate refuses to
    stamp) instead of hanging the bench."""
    import threading

    import jax
    import jax.numpy as jnp

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import GBM
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.ops.map_reduce import map_reduce
    from h2o3_tpu.utils.registry import DKV
    from h2o3_tpu.utils.telemetry import DISPATCH_RETRIES
    from h2o3_tpu.utils.timeline import inject_faults

    n = 2_000 if SMOKE else 50_000
    rng = np.random.default_rng(41)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    logit = X[:, :3] @ np.array([1.0, -0.7, 0.4], np.float32)
    cols = {f"x{i}": X[:, i] for i in range(8)}
    cols["y"] = np.where(rng.random(n) < 1.0 / (1.0 + np.exp(-logit)),
                         "yes", "no")
    fr = Frame.from_arrays(cols)

    def builds():
        glm = GLM(family="binomial", lambda_=1e-4, max_iterations=15,
                  model_id="chaos_glm").train(y="y", training_frame=fr)
        gbm = GBM(ntrees=8, max_depth=4, seed=11, trees_per_dispatch=2,
                  model_id="chaos_gbm").train(y="y", training_frame=fr)
        pg = np.asarray(jax.device_get(glm._score_raw(fr)))
        pb = np.asarray(jax.device_get(gbm._score_raw(fr)))
        for k in ("chaos_glm", "chaos_gbm"):
            DKV.remove(k)
        return pg, pb

    t0 = time.perf_counter()
    clean_glm, clean_gbm = builds()         # fault-free reference (+ warm-up)
    clean_secs = time.perf_counter() - t0

    def retried_total():
        return sum(c.value for labels, c in DISPATCH_RETRIES.children()
                   if labels["outcome"] == "retried")

    storm = jnp.ones(256, jnp.float32)
    result: dict = {}

    def _storm_sum(s):
        return s.sum()

    def chaos_phase():
        try:
            # dispatch storm: enough dispatches that 2% drops MUST fire and
            # be absorbed (P(zero faults) < 1e-4 at 500 draws); one stable
            # map_fn so the compiled-program cache serves every call
            for _ in range(20 if SMOKE else 500):
                map_reduce(_storm_sum, storm)
            result["glm"], result["gbm"] = builds()
        except BaseException as e:   # noqa: BLE001 — the gate refuses on it
            result["error"] = f"{type(e).__name__}: {e}"

    r0 = retried_total()
    with inject_faults(drop_rate=0.02, delay_rate=0.02, delay_ms=1,
                       seed=17) as inj:
        worker = threading.Thread(target=chaos_phase, daemon=True)
        tc0 = time.perf_counter()
        worker.start()
        # watchdog: generous multiple of the clean wall — a faulted run
        # that exceeds it is treated as deadlocked and refused
        worker.join(timeout=max(20.0, 10.0 * clean_secs + 60.0))
        chaos_secs = time.perf_counter() - tc0
        completed = not worker.is_alive()
    faults = inj.dropped + inj.delayed
    if completed and result.get("error"):
        # the faulted run DIED rather than deadlocked — equally refusable
        return {"error": f"faulted run failed: {result['error']}",
                "faults_injected": faults}
    out = dict(completed=completed,
               faults_injected=faults,
               faults_dropped=inj.dropped, faults_delayed=inj.delayed,
               retries_absorbed=round(retried_total() - r0, 1),
               drop_rate=0.02,
               clean_seconds=round(clean_secs, 2),
               chaos_seconds=round(chaos_secs, 2))
    if completed:
        out["glm_divergence"] = float(np.abs(result["glm"]
                                             - clean_glm).max())
        out["gbm_divergence"] = float(np.abs(result["gbm"]
                                             - clean_gbm).max())
    return out


def _chaos_gate(ch: dict) -> None:
    """Refuse to stamp an artifact whose chaos run deadlocked or diverged:
    a faulted build that hangs means retry/backoff lost a failure (the
    exact regression this layer exists to prevent), and divergence beyond
    1e-6 means a retry re-ran a non-functional dispatch."""
    if ch.get("error"):
        print(f"# bench REFUSED: chaos section failed: {ch['error']}",
              file=sys.stderr)
        sys.exit(3)
    if not ch["completed"]:
        print("# bench REFUSED: chaos run DEADLOCKED — faulted builds did "
              "not complete within the watchdog budget", file=sys.stderr)
        sys.exit(3)
    if ch["glm_divergence"] > 1e-6 or ch["gbm_divergence"] > 1e-6:
        print(f"# bench REFUSED: faulted builds diverged from the "
              f"fault-free run (glm {ch['glm_divergence']}, gbm "
              f"{ch['gbm_divergence']} > 1e-6)", file=sys.stderr)
        sys.exit(3)
    if not SMOKE and ch["faults_injected"] == 0:
        print("# bench REFUSED: chaos phase injected zero faults — the "
              "harness is hollow", file=sys.stderr)
        sys.exit(3)


def bench_elastic(ndev: int) -> dict:
    """Elastic local-SGD under a mid-epoch worker kill (ISSUE 12 / ROADMAP
    item 3 acceptance): a k-worker elastic DL run where one worker is
    stalled dead mid-run must COMPLETE with exactly one ejection, the dead
    worker's shard reassigned to survivors, and the kill costing less than
    the dead worker's throughput share (slowdown < 1/k vs the uninterrupted
    k-worker run — enforced on real hardware; CPU-fallback rounds enforce
    completion + bounded wall only, the same policy as the slices gate)."""
    import threading

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.deeplearning import DeepLearning
    from h2o3_tpu.parallel import elastic as _el
    from h2o3_tpu.utils.registry import DKV
    from h2o3_tpu.utils.timeline import inject_faults

    k = 4 if ndev % 4 == 0 else (2 if ndev % 2 == 0 else max(ndev, 2))
    n = 2_000 if SMOKE else 60_000
    epochs, local_steps = (2, 1) if SMOKE else (8, 1)
    rng = np.random.default_rng(23)
    X = rng.normal(size=(n, 8)).astype(np.float32)
    logit = X[:, :3] @ np.array([1.0, -0.7, 0.4], np.float32)
    cols = {f"x{i}": X[:, i] for i in range(8)}
    cols["y"] = np.where(rng.random(n) < 1.0 / (1.0 + np.exp(-logit)),
                         "yes", "no")
    fr = Frame.from_arrays(cols)

    def run(model_id, eps=None):
        b = DeepLearning(hidden=[16], epochs=eps or epochs, elastic=k,
                         local_steps=local_steps, mini_batch_size=64,
                         seed=9, model_id=model_id)
        t0 = time.perf_counter()
        m = b.train(y="y", training_frame=fr)
        return m, b.job, time.perf_counter() - t0

    # warm-up pass: compiles every per-slice signature so BOTH timed runs
    # below are warm — without it the clean run carries the one-time
    # compile cost and the slowdown ratio under-reads
    warm_model, _, _ = run("elastic_warm", eps=1)
    spw = warm_model.output["elastic"]["shards_per_worker"]
    # uninterrupted k-worker reference
    clean_model, _, clean_secs = run("elastic_clean")
    clean_rounds = clean_model.output["elastic"]["rounds"]
    round_wall = clean_secs / max(clean_rounds, 1)

    # tight-but-safe membership knobs derived from the measured cadence:
    # the stall outlives the whole run (a dead worker, not a hiccup); the
    # deadline ejects it within <2 rounds BUT must clear the post-ejection
    # round wall — survivors carry ceil(spw·k/(k-1))/spw ≈ 1.33x compute
    # per round after the kill, and a deadline below that would
    # mass-suspect the survivors themselves. The kill lands MID-RUN
    # (worker 1's first sub-shard of round ~mid): `after` counts that
    # worker's own dl_epochs calls, spw per round
    stall_s = max(10.0 * clean_secs, 60.0)
    deadline_s = max(1.75 * round_wall, 1.0)
    kill_round = max(clean_rounds // 2, 1)
    env_save = {kk: os.environ.get(kk) for kk in
                ("H2O3TPU_ELASTIC_ROUND_DEADLINE_SECS",
                 "H2O3TPU_ELASTIC_LEASE_SECS")}
    os.environ["H2O3TPU_ELASTIC_ROUND_DEADLINE_SECS"] = str(deadline_s)
    os.environ["H2O3TPU_ELASTIC_LEASE_SECS"] = str(max(deadline_s / 2, 0.5))
    result: dict = {}

    def killed_phase():
        try:
            result["model"], result["job"], result["secs"] = \
                run("elastic_killed")
        except BaseException as e:   # noqa: BLE001 — the gate refuses on it
            result["error"] = f"{type(e).__name__}: {e}"

    try:
        with inject_faults(worker_rates={1: {"stall_rate": 1.0,
                                             "stall_ms": stall_s * 1e3,
                                             "after": kill_round * spw}}
                           ) as inj:
            worker = threading.Thread(target=killed_phase, daemon=True)
            worker.start()
            # watchdog: a wedged elastic run is the exact regression this
            # layer exists to prevent — refuse instead of hanging the bench
            worker.join(timeout=max(30.0, 5.0 * clean_secs + stall_s / 2))
            completed = not worker.is_alive()
    finally:
        _el.drain(60.0)
        for kk, v in env_save.items():
            if v is None:
                os.environ.pop(kk, None)
            else:
                os.environ[kk] = v

    for key in ("elastic_warm", "elastic_clean", "elastic_killed"):
        DKV.remove(key)
    if result.get("error"):
        return {"error": f"killed run failed: {result['error']}",
                "stalls_injected": inj.stalled}
    out = dict(workers=k, rounds=clean_rounds, local_steps=local_steps,
               shards_per_worker=spw, kill_round=kill_round,
               completed=completed, stalls_injected=inj.stalled,
               clean_seconds=round(clean_secs, 2))
    if completed:
        el = result["model"].output["elastic"]
        killed_secs = result["secs"]
        slowdown = (killed_secs - clean_secs) / max(clean_secs, 1e-9)
        out.update(
            killed_status=result["job"].status,
            killed_seconds=round(killed_secs, 2),
            # what the kill actually cost, vs the dead worker's share
            slowdown_frac=round(slowdown, 4),
            dead_worker_share=round(1.0 / k, 4),
            recovery_latency_s=round(max(killed_secs - clean_secs, 0.0), 2),
            workers_ejected=int(result["job"].workers_ejected),
            ejections_by_reason=el["ejections_by_reason"],
            rounds_killed_run=el["rounds"],
            # per-worker throughput: averaging rounds carried / busy wall
            per_worker={w: {"rounds_done": v["rounds_done"],
                            "busy_seconds": v["busy_seconds"],
                            "rounds_per_sec": round(
                                v["rounds_done"]
                                / max(v["busy_seconds"], 1e-9), 3),
                            "state": v["state"]}
                        for w, v in el["per_worker"].items()},
            final_loss_clean=clean_model.output["score_history"][-1]
            ["train_loss"] if clean_model.output["score_history"] else None,
            final_loss_killed=result["model"].output["score_history"][-1]
            ["train_loss"] if result["model"].output["score_history"]
            else None)
    return out


def _elastic_gate(el: dict, backend: str) -> None:
    """Refuse to stamp when the elastic chaos scenario wedged, ejected the
    wrong number of workers, or (on real hardware) the kill cost more than
    the dead worker's throughput share — ROADMAP item 3's acceptance bar."""
    if el.get("skipped"):
        return
    if el.get("error"):
        print(f"# bench REFUSED: elastic section failed: {el['error']}",
              file=sys.stderr)
        sys.exit(3)
    if not el["completed"]:
        print("# bench REFUSED: elastic killed-worker run WEDGED — the "
              "dead worker stalled the cloud", file=sys.stderr)
        sys.exit(3)
    if el.get("workers_ejected") != 1:
        print(f"# bench REFUSED: elastic kill ejected "
              f"{el.get('workers_ejected')} workers (expected exactly 1) — "
              "the harness is hollow or membership over-reacted",
              file=sys.stderr)
        sys.exit(3)
    if el.get("stalls_injected", 0) < 1:
        print("# bench REFUSED: elastic scenario injected zero stalls",
              file=sys.stderr)
        sys.exit(3)
    if el.get("killed_status") != "DONE":
        # a quorum-cancelled partial would otherwise read as a pass with a
        # trivially-negative slowdown (it trained fewer epochs)
        print(f"# bench REFUSED: killed run ended {el.get('killed_status')} "
              "— survivors did not finish the build", file=sys.stderr)
        sys.exit(3)
    if el.get("rounds_killed_run") != el.get("rounds"):
        print(f"# bench REFUSED: killed run carried "
              f"{el.get('rounds_killed_run')} rounds vs the clean run's "
              f"{el.get('rounds')} — membership over-reacted (mass-suspect "
              "or early exit), the epochs were not all trained",
              file=sys.stderr)
        sys.exit(3)
    real = backend not in ("cpu",) and not CPU_FALLBACK
    if real and el["slowdown_frac"] >= el["dead_worker_share"]:
        print(f"# bench REFUSED: killing 1/{el['workers']} workers cost "
              f"{el['slowdown_frac']:.1%} of throughput (>= its "
              f"{el['dead_worker_share']:.1%} share)", file=sys.stderr)
        sys.exit(3)


def bench_tracing(ndev: int) -> dict:
    """Trace-store overhead + the slowest trace's critical path.

    Trains the same GLM with the tracer ON (under a root span, so every
    IRLS iteration and dispatch records) and OFF (``H2O3TPU_TRACE_OFF=1``),
    min-of-2 each; the ratio is the tracer's wall-time overhead. The
    slowest completed trace's critical path is embedded so the artifact
    carries per-request causality, not just aggregate counters."""
    import jax

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.utils import tracing as tr

    # real runs time at the 1M airlines scale so the 2% gate compares
    # seconds, not scheduler noise; smoke/fallback only prove the plumbing
    n = 3_000 if SMOKE else (50_000 if CPU_FALLBACK else 1_000_000)
    iters = 10 if SMOKE else 25
    rng = np.random.default_rng(23)
    X = rng.normal(size=(n, 12)).astype(np.float32)
    logit = X[:, :5] @ np.array([0.8, -0.5, 0.3, -0.2, 0.4], np.float32)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit)))
    cols = {f"x{i}": X[:, i] for i in range(12)}
    cols["resp"] = np.where(y, "YES", "NO")
    fr = Frame.from_arrays(cols)

    def train():
        GLM(family="binomial", lambda_=1e-4, max_iterations=iters).train(
            y="resp", training_frame=fr)

    def timed(traced: bool) -> float:
        t0 = time.perf_counter()
        if traced:
            with tr.TRACER.span("bench:glm_traced", kind="bench", root=True):
                train()
        else:
            os.environ["H2O3TPU_TRACE_OFF"] = "1"
            try:
                train()
            finally:
                os.environ.pop("H2O3TPU_TRACE_OFF", None)
        return time.perf_counter() - t0

    train()                       # warm-up: compiles out of the timed region
    jax.effects_barrier()
    reps = 1 if SMOKE else 2      # min-of-2 damps scheduler noise
    t_on = min(timed(True) for _ in range(reps))
    t_off = min(timed(False) for _ in range(reps))
    overhead = t_on / max(t_off, 1e-9) - 1.0

    traces = tr.TRACER.list_traces()
    bench_traces = [t for t in traces if t["name"] == "bench:glm_traced"]
    out = dict(seconds_traced=round(t_on, 3), seconds_untraced=round(t_off, 3),
               overhead_pct=round(overhead * 100, 2),
               trace_count=len(traces))
    if bench_traces:
        slowest = max(bench_traces, key=lambda t: t["dur_ns"])
        full = tr.TRACER.get_trace(slowest["trace_id"])
        out["slowest_trace"] = dict(
            trace_id=slowest["trace_id"], nspans=slowest["nspans"],
            dur_ms=round(slowest["dur_ns"] / 1e6, 2))
        out["critical_path"] = [
            dict(name=e["name"], kind=e["kind"],
                 dur_ms=round(e["dur_ns"] / 1e6, 2),
                 self_ms=round(e["self_ns"] / 1e6, 2))
            for e in tr.critical_path(full)]
    return out


def bench_ingest(ndev: int) -> dict:
    """Out-of-core ingest proof (ROADMAP item 4, docs/INGEST.md): generate
    a gzip CSV whose UNCOMPRESSED size exceeds a capped host budget, parse
    it through the streaming pipeline (compressed chunks, lazy device
    views), train a GLM on the result, and cycle a spill/fault-in.

    ``extra.ingest`` embeds: peak host RSS growth vs the cap
    (`H2O3TPU_INGEST_RAM_BUDGET` overrides the default of ~60% of the
    dataset's text size), the achieved compression ratio, spill/fault-in
    counters, and a bit-identity check of streamed-vs-eager predictions.
    The gate refuses to stamp a real-run artifact whose ingest RSS growth
    exceeded the cap or whose predictions diverged."""
    import gzip
    import tempfile
    import threading

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.ingest import stream_import
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.utils import memory as _mem
    from h2o3_tpu.utils.cleaner import (CLEANER, disable_cleaner,
                                        enable_cleaner)
    from h2o3_tpu.utils.registry import DKV

    rows = 30_000 if SMOKE else (1_500_000 if CPU_FALLBACK else 8_000_000)
    bytes_per_row = 25            # "123,45,67,0.123456,yes" ≈ 25B
    cap = int(os.environ.get("H2O3TPU_INGEST_RAM_BUDGET",
                             str(int(rows * bytes_per_row * 0.6))))
    rng = np.random.default_rng(17)
    tmp = tempfile.mkdtemp(prefix="h2o3_ingest_bench_")
    big = os.path.join(tmp, "big.csv.gz")
    # generate in bounded chunks — the GENERATOR must not hold O(file) either
    text_bytes = 0
    with gzip.open(big, "wt", compresslevel=1) as f:
        f.write("a,b,c,x,y\n")
        left = rows
        while left:
            n = min(left, 100_000)
            a = rng.integers(0, 100, size=n)
            b = rng.integers(-30, 30, size=n)
            c = rng.integers(0, 7, size=n)
            x = rng.normal(size=n)
            ylab = np.where(rng.random(n) < 1 / (1 + np.exp(
                -(0.02 * a - 0.05 * b + 0.3 * x))), "yes", "no")
            block = "\n".join(
                f"{ai},{bi},{ci},{xi:.6f},{yi}"
                for ai, bi, ci, xi, yi in zip(a, b, c, x, ylab)) + "\n"
            text_bytes += len(block)
            f.write(block)
            left -= n

    # RSS sampler: VmHWM is process-lifetime, so sample the live RSS at
    # 50ms cadence across parse+train to get THIS scenario's peak delta
    rss0 = _mem.host_stats()["rss_bytes"]
    peak = {"rss": rss0}
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            peak["rss"] = max(peak["rss"], _mem.host_stats()["rss_bytes"])
            stop.wait(timeout=0.05)

    smp = threading.Thread(target=sampler, daemon=True)
    smp.start()
    out: dict = {"rows": rows, "text_bytes": text_bytes,
                 "gz_bytes": os.path.getsize(big), "cap_bytes": cap,
                 "dataset_exceeds_cap": text_bytes > cap}
    try:
        t0 = time.perf_counter()
        fr = stream_import(big, key="bench_ingest.hex")
        dt = time.perf_counter() - t0
        out["parse_seconds"] = round(dt, 2)
        out["parse_rows_per_sec"] = round(rows / max(dt, 1e-9), 1)
        st = fr._ingest_stats
        out["compression_ratio"] = st["compression_ratio"]
        out["chunks"] = st["chunks"]
        out["inflight_peak_bytes"] = st["inflight_peak_bytes"]
        out["restarts"] = st["restarts"]
        t0 = time.perf_counter()
        model = GLM(family="binomial", lambda_=1e-4, max_iterations=10,
                    seed=5).train(y="y", training_frame=fr)
        out["train_seconds"] = round(time.perf_counter() - t0, 2)
        out["auc"] = round(float(model.training_metrics.auc), 4)
        # the RSS cap covers PARSE+TRAIN — stop sampling before the forced
        # spill cycle below: tier-3 save_frame decodes every column into
        # one npz write (a documented O(file) limitation of the snapshot
        # format, ROADMAP item 4), which would trip the gate on a spike
        # that is not an ingest regression
        stop.set()
        smp.join(timeout=5.0)
        # spill/fault-in cycle: a budget well under even the COMPRESSED
        # payload forces a disk spill (view drops alone can't satisfy it);
        # the re-get faults the frame back in
        sp0 = CLEANER.stats()
        enable_cleaner(max(fr.nbytes // 16, 1), ice_root=os.path.join(
            tmp, "ice"))
        try:
            DKV.put("bench_ingest_hot.hex",
                    Frame.from_arrays({"z": np.zeros(1024, np.float32)},
                                      key="bench_ingest_hot.hex"))
            _ = DKV["bench_ingest.hex"]     # transparent fault-in
        finally:
            disable_cleaner()
        sp1 = CLEANER.stats()
        out["spills"] = sp1["spill_count"] - sp0["spill_count"]
        out["fault_ins"] = sp1["restore_count"] - sp0["restore_count"]
        out["view_drops"] = sp1["view_drops"] - sp0["view_drops"]
    finally:
        stop.set()
        smp.join(timeout=5.0)
    out["rss_peak_delta_bytes"] = max(peak["rss"] - rss0, 0)
    out["under_cap"] = out["rss_peak_delta_bytes"] <= cap

    # bit-identity: streamed+compressed vs eager resident on a subset file
    sub = os.path.join(tmp, "sub.csv")
    with gzip.open(big, "rt") as fin, open(sub, "w") as fout:
        for i, line in enumerate(fin):
            if i > 50_000:
                break
            fout.write(line)
    fs = stream_import(sub, key="bench_ingest_s.hex", chunk_rows=8192)
    fe = import_file(sub, key="bench_ingest_e.hex")
    kw = dict(family="binomial", lambda_=1e-4, max_iterations=8, seed=5)
    ps = GLM(**kw).train(y="y", training_frame=fs).predict(fs) \
        .vec("pyes").to_numpy()
    pe = GLM(**kw).train(y="y", training_frame=fe).predict(fe) \
        .vec("pyes").to_numpy()
    out["bit_identical"] = bool(np.array_equal(ps, pe))
    for k in ("bench_ingest.hex", "bench_ingest_hot.hex",
              "bench_ingest_s.hex", "bench_ingest_e.hex"):
        DKV.remove(k)
    import shutil
    shutil.rmtree(tmp, ignore_errors=True)
    return out


def _ingest_gate(ing: dict) -> None:
    """Refuse to stamp when the out-of-core contract broke: streamed/
    compressed predictions diverging from the eager path is a correctness
    regression on ANY backend; a real run whose ingest RSS growth exceeded
    the configured cap lost the O(chunk)+compressed memory story the
    subsystem exists for (CPU fallback annotates only — device arrays live
    in RSS there, so the cap is not meaningful)."""
    if ing.get("error"):
        print(f"# bench REFUSED: ingest section failed: {ing['error']}",
              file=sys.stderr)
        sys.exit(3)
    if not ing.get("bit_identical"):
        print("# bench REFUSED: streamed/compressed GLM predictions "
              "diverge from the eager resident path", file=sys.stderr)
        sys.exit(3)
    if SMOKE or CPU_FALLBACK:
        return
    if not ing.get("dataset_exceeds_cap"):
        print("# bench REFUSED: ingest dataset no longer exceeds the RAM "
              "cap — the out-of-core scenario proves nothing",
              file=sys.stderr)
        sys.exit(3)
    if not ing.get("under_cap"):
        print(f"# bench REFUSED: ingest host RSS growth "
              f"{ing['rss_peak_delta_bytes']} exceeds the "
              f"H2O3TPU_INGEST_RAM_BUDGET cap {ing['cap_bytes']}",
              file=sys.stderr)
        sys.exit(3)


def bench_memory() -> dict:
    """Memory accounting for the artifact: host/device watermarks over the
    whole bench run, DKV byte totals by kind, and a leak-detector pass over
    the workload's resident keys (enough sweeps for the detector to express
    an opinion; nothing should flag on a clean run)."""
    from h2o3_tpu.utils import memory as _mem

    _mem.MEMORY.refresh()      # reconcile in-place mutation before sweeping
    rss, dev = _mem.MEMORY.sample()
    # one more observation of the final state, then capture GROWTH flags
    # BEFORE the idle passes below: a static post-workload sweep resets
    # growth streaks by definition, so reading them later would make the
    # gate unreachable
    _mem.MEMORY.leak_sweep()
    growing = [f for f in _mem.MEMORY.leak_report()["flagged"]
               if "growing" in f["reasons"]]
    for _ in range(_mem.MEMORY.detector.sweeps + 1):
        _mem.MEMORY.leak_sweep()
    rep = _mem.MEMORY.leak_report()
    wm = _mem.MEMORY.watermarks
    total, by_kind, nkeys = _mem.MEMORY.dkv_totals()
    return dict(host_rss_bytes=rss,
                host_rss_peak_bytes=wm["host_rss_peak_bytes"],
                device_bytes_in_use=dev,
                device_peak_bytes=wm["device_peak_bytes"],
                device_source=_mem.device_stats()["source"],
                dkv_bytes=total, dkv_by_kind=by_kind, dkv_keys=nkeys,
                leak_sweeps=rep["sweeps"],
                leak_growing=growing,
                leak_flagged=rep["flagged"])


def _memory_gate(memsec: dict) -> None:
    """Refuse to stamp an artifact when the leak detector fires on a real
    run (keys growing or idle-resident above the floor across sweeps are
    exactly what pages someone at 3am), or when the meter itself reads
    hollow — a zero host watermark means the accounting regressed."""
    if memsec.get("error"):
        print(f"# bench REFUSED: memory section failed: {memsec['error']}",
              file=sys.stderr)
        sys.exit(3)
    if SMOKE or CPU_FALLBACK:
        return          # annotate-only (smoke proves shape; /proc may be absent)
    if memsec["host_rss_peak_bytes"] <= 0:
        print("# bench REFUSED: memory meter reports a zero host watermark "
              "— byte accounting is broken", file=sys.stderr)
        sys.exit(3)
    # gate on GROWTH flags only (captured by bench_memory BEFORE its idle
    # passes — those manufacture idle streaks by construction and would
    # reset growth streaks): bytes that kept rising across the interleaved
    # workload sweeps are the real signal; idle-only flags still ride in
    # the artifact for inspection.
    growing = memsec["leak_growing"]
    if growing:
        for f in growing:
            print(f"# leak: {f}", file=sys.stderr)
        print(f"# bench REFUSED: leak detector flagged {len(growing)} "
              "growing key(s) on a real run", file=sys.stderr)
        sys.exit(3)


def bench_health(ndev: int) -> dict:
    """Ops-plane proof (ISSUE 15): the health evaluator watching a CLEAN
    GLM run must report every subsystem healthy and open ZERO incidents
    (a trip here means a rule's threshold pages on normal operation — the
    boy-who-cried-wolf failure), the sweep thread must have actually swept
    (a hollow watchdog that never ran also reads "healthy"), and the
    evaluator's wall overhead vs ``H2O3TPU_HEALTH_OFF=1`` must stay under
    the same 2% always-on budget the tracer holds."""
    import jax

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.utils.health import HealthEvaluator
    from h2o3_tpu.utils.incidents import INCIDENTS

    n = 3_000 if SMOKE else (50_000 if CPU_FALLBACK else 1_000_000)
    iters = 10 if SMOKE else 25
    rng = np.random.default_rng(31)
    X = rng.normal(size=(n, 12)).astype(np.float32)
    logit = X[:, :5] @ np.array([0.8, -0.5, 0.3, -0.2, 0.4], np.float32)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit)))
    cols = {f"x{i}": X[:, i] for i in range(12)}
    cols["resp"] = np.where(y, "YES", "NO")
    fr = Frame.from_arrays(cols)

    def train():
        GLM(family="binomial", lambda_=1e-4, max_iterations=iters).train(
            y="resp", training_frame=fr)

    train()                       # warm-up: compiles out of the timed region
    jax.effects_barrier()
    # the watched/off comparison needs the knob in both positions; an
    # operator-exported H2O3TPU_HEALTH_OFF=1 must come back afterwards
    saved_off = os.environ.pop("H2O3TPU_HEALTH_OFF", None)

    def timed_watched() -> tuple:
        ev = HealthEvaluator(interval_s=0.05)
        opened0 = INCIDENTS.opened_total()
        ev.evaluate()             # baseline window deltas pre-run
        ev.start()
        t0 = time.perf_counter()
        train()
        wall = time.perf_counter() - t0
        # hollow-watchdog proof: the THREAD must demonstrably sweep (the
        # two inline evaluate() calls here don't count) — a bounded wait
        # OUTSIDE the timed window so sub-interval smoke runs still see it
        deadline = time.monotonic() + 5.0
        while ev.thread_sweeps() < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        verdict = ev.evaluate()   # one final sweep over the finished run
        ev.stop()
        return (wall, verdict, INCIDENTS.opened_total() - opened0,
                ev.thread_sweeps())

    def timed_off() -> float:
        os.environ["H2O3TPU_HEALTH_OFF"] = "1"
        try:
            t0 = time.perf_counter()
            train()
            return time.perf_counter() - t0
        finally:
            os.environ.pop("H2O3TPU_HEALTH_OFF", None)

    reps = 1 if SMOKE else 2      # min-of-N damps scheduler noise
    try:
        watched = [timed_watched() for _ in range(reps)]
        t_on = min(w[0] for w in watched)
        t_off = min(timed_off() for _ in range(reps))
    finally:
        if saved_off is not None:
            os.environ["H2O3TPU_HEALTH_OFF"] = saved_off
    # the gate must see EVERY rep, not the last: an incident tripped in
    # rep 1 that clears by rep 2 is still a rule paging on normal
    # operation — sum the opens, keep the WORST verdict, and require the
    # thread to have swept in every rep
    rank = {"healthy": 0, "degraded": 1, "unhealthy": 2}
    verdict = max((w[1] for w in watched), key=lambda v: rank[v["status"]])
    opened = sum(w[2] for w in watched)
    thread_sweeps = min(w[3] for w in watched)
    overhead = t_on / max(t_off, 1e-9) - 1.0
    return dict(
        seconds_watched=round(t_on, 3), seconds_off=round(t_off, 3),
        overhead_pct=round(overhead * 100, 2),
        status=verdict["status"],
        subsystems={s: v["status"]
                    for s, v in verdict["subsystems"].items()},
        findings=verdict["findings"],
        sweeps=thread_sweeps, incidents_opened=opened,
        open_incidents=verdict["open_incidents"],
        rules=len(verdict["rules"]))


def _health_gate(hl: dict) -> None:
    """Refuse to stamp when the ops plane is hollow or noisy: a clean run
    that trips ANY incident means a rule pages on normal operation; a
    sweep count of zero means the watchdog thread never actually watched;
    >2% overhead on real runs breaks the always-on budget."""
    if hl.get("error"):
        print(f"# bench REFUSED: health section failed: {hl['error']}",
              file=sys.stderr)
        sys.exit(3)
    if hl["sweeps"] <= 0:
        # thread-driven sweeps only — the section's own inline evaluate()
        # calls don't count as the watchdog having watched
        print("# bench REFUSED: health sweep thread never swept — the "
              "watchdog is hollow", file=sys.stderr)
        sys.exit(3)
    if hl["incidents_opened"] > 0 or hl["status"] != "healthy":
        for f in hl["findings"]:
            print(f"# health finding: {f}", file=sys.stderr)
        print(f"# bench REFUSED: clean run reads {hl['status']} with "
              f"{hl['incidents_opened']} incident(s) opened — a health "
              "rule pages on normal operation", file=sys.stderr)
        sys.exit(3)
    if not SMOKE and not CPU_FALLBACK and hl["overhead_pct"] > 2.0:
        print(f"# bench REFUSED: health evaluator overhead "
              f"{hl['overhead_pct']}% exceeds the 2% always-on budget",
              file=sys.stderr)
        sys.exit(3)


def bench_ops(ndev: int) -> dict:
    """Self-driving ops proof (ISSUE 16): replay the three chaos classes
    with remediation switched to ACT mode — each must heal with NO human
    intervention: the health rule trips, the incident rising edge fires
    the engine, exactly ONE bounded audited action of the right class
    lands on the live target, and the incident resolves on the next clean
    sweep. Then a CLEAN GLM run under the same act mode must take ZERO
    actions — an engine that remediates normal operation is worse than no
    engine. Spill-thrash and the stalled worker run fully live (real
    Cleaner/DKV ping-pong, real ElasticGroup with a wedged thread); the
    serving replay injects the shed counters but the action still lands
    on the REAL scoring tier's admission targets."""
    import shutil
    import tempfile
    import threading

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.ops_plane.actions import ActionLog
    from h2o3_tpu.ops_plane.remediate import RemediationEngine
    from h2o3_tpu.utils import health as hm
    from h2o3_tpu.utils.health import HealthEvaluator
    from h2o3_tpu.utils.incidents import IncidentLog
    from h2o3_tpu.utils.registry import DKV

    saved_env = {k: os.environ.get(k) for k in
                 ("H2O3TPU_REMEDIATE", "H2O3TPU_OPS_COOLDOWN_SECS",
                  "H2O3TPU_HEALTH_HEARTBEAT_GAP_SECS")}
    os.environ["H2O3TPU_REMEDIATE"] = "act"
    os.environ["H2O3TPU_OPS_COOLDOWN_SECS"] = "0"

    def rig():
        ev = HealthEvaluator(interval_s=9.0,
                             incidents=IncidentLog(capacity=16))
        eng = RemediationEngine(actions=ActionLog())
        eng.install(ev.incidents)
        return ev, eng

    def outcome(ev, eng, rule):
        applied = [r for r in eng.actions.list()
                   if r["outcome"] == "applied"]
        resolved = [r for r in ev.incidents.list(state="resolved")
                    if r["rule"] == rule]
        return dict(
            rule=rule,
            applied_actions=[r["action"] for r in applied],
            healed=bool(resolved) and not ev.incidents.list(state="open"),
            action_stamped=bool(resolved)
            and resolved[0]["action_id"] is not None,
            records=eng.actions.recorded_total())

    out: dict = {}

    # -- chaos 1: spill-thrash, fully live -----------------------------------
    # two frames + a budget that fits only one → every touch of the cold
    # one restores it and spills the other; the remediation's 1.5× budget
    # raise makes BOTH fit, so the ping-pong goes quiet and the incident
    # resolves on the evidence of the real Cleaner counters
    from h2o3_tpu.utils.cleaner import CLEANER, disable_cleaner, enable_cleaner
    ice = tempfile.mkdtemp(prefix="ops_bench_ice_")
    rng = np.random.default_rng(61)
    ev, eng = rig()
    try:
        frames = {}
        for key in ("ops_thrash_a", "ops_thrash_b"):
            fr = Frame.from_arrays(
                {f"c{i}": rng.normal(size=20_000).astype(np.float32)
                 for i in range(4)}, key=key)
            DKV.put(key, fr)
            frames[key] = fr
        one = frames["ops_thrash_a"].nbytes
        enable_cleaner(int(one * 1.5), ice_root=ice)
        CLEANER.sweep()
        ev.evaluate()                             # window baseline
        for _ in range(4):                        # the thrash
            DKV.get("ops_thrash_a"); CLEANER.sweep()
            DKV.get("ops_thrash_b"); CLEANER.sweep()
        ev.evaluate()                             # trips → engine → budget up
        budget_after = CLEANER.budget
        for _ in range(2):                        # working set fits now
            DKV.get("ops_thrash_a"); CLEANER.sweep()
            DKV.get("ops_thrash_b"); CLEANER.sweep()
        ev.evaluate()                             # quiet window → resolve
        out["spill_thrash"] = dict(
            outcome(ev, eng, "memory_spill_thrash"),
            budget_before=int(one * 1.5), budget_after=budget_after,
            budget_raised=budget_after is not None
            and budget_after > int(one * 1.5))
    finally:
        eng.uninstall()
        for key in ("ops_thrash_a", "ops_thrash_b"):
            try:
                DKV.remove(key)
            except KeyError:
                pass
        disable_cleaner()
        shutil.rmtree(ice, ignore_errors=True)

    # -- chaos 2: serving overload — replayed counters, live admission -------
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.serving.service import SCORING
    SCORING.reset()
    n = 300
    X = rng.normal(size=(n, 3)).astype(np.float32)
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = np.where(X[:, 0] > 0, "yes", "no")
    fr = Frame.from_arrays(cols, key="ops_serve_train")
    glm = GLM(family="binomial", lambda_=1e-4,
              model_id="ops_serve_glm").train(y="y", training_frame=fr)
    rows = [{f"x{i}": float(X[r, i]) for i in range(3)} for r in range(4)]
    SCORING.score(glm.key, rows, slo_ms=50.0)     # resident, target 50ms
    orig_stats, orig_total = hm._serving_stats, hm._score_requests_total
    shed, total = [0.0], [100.0]
    hm._serving_stats = lambda: {
        "shed_total": shed[0],
        "resident": [{"model": glm.key,
                      "slo": {"target_ms": 50.0, "p99_ms": 20.0}}]}
    hm._score_requests_total = lambda: total[0]
    ev, eng = rig()
    try:
        ev.evaluate()                             # baseline
        shed[0], total[0] = 40.0, 200.0           # 40% shed this window
        ev.evaluate()                             # trips → widen admission
        live = orig_stats()                       # REAL tier, post-action
        target_after = next(
            (m["slo"]["target_ms"] for m in live["resident"]
             if m["model"] == glm.key and m.get("slo")), None)
        ev.evaluate()                             # traffic drained → resolve
        out["serving_overload"] = dict(
            outcome(ev, eng, "serving_shed_rate"),
            target_ms_after=target_after,
            admission_widened=bool(target_after and target_after > 50.0))
    finally:
        eng.uninstall()
        hm._serving_stats, hm._score_requests_total = orig_stats, orig_total
        SCORING.reset()
        try:
            DKV.remove("ops_serve_train")
        except KeyError:
            pass

    # -- chaos 3: stalled elastic worker, fully live -------------------------
    # worker 1 wedges mid-round (blocked thread, heartbeat silent); the
    # engine must preempt-reassign its shards BEFORE the 120s lease would
    # have noticed, after which the probe no longer counts the ejected
    # slot and the incident resolves
    from h2o3_tpu.parallel import elastic
    from h2o3_tpu.parallel.elastic import ElasticGroup
    os.environ["H2O3TPU_HEALTH_HEARTBEAT_GAP_SECS"] = "1"
    stall = threading.Event()
    g = ElasticGroup(3, lease_secs=120.0, round_deadline_secs=300.0,
                     group_id="ops_bench_elastic").start()
    thunks = {0: lambda: time.sleep(0.01),
              1: lambda: stall.wait(timeout=60.0),
              2: lambda: time.sleep(0.01)}
    runner = threading.Thread(target=g.run_round, args=(1, thunks),
                              daemon=True)
    ev, eng = rig()
    try:
        runner.start()
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:       # healthy slots heartbeat;
            g.heartbeat(0); g.heartbeat(2)       # the wedged one is silent
            time.sleep(0.1)
        ev.evaluate()                             # gap > 1s → preempt
        membership = g.membership()
        g.heartbeat(0); g.heartbeat(2)
        ev.evaluate()                             # ejected slot not counted
        out["stalled_worker"] = dict(
            outcome(ev, eng, "elastic_heartbeat_gap"),
            worker_ejected=membership.get(1) == "EJECTED",
            survivors=[w for w, s in membership.items() if s == "ACTIVE"])
    finally:
        eng.uninstall()
        stall.set()
        runner.join(timeout=30.0)
        g.shutdown()
        elastic.drain(timeout=10.0)
        if saved_env["H2O3TPU_HEALTH_HEARTBEAT_GAP_SECS"] is None:
            os.environ.pop("H2O3TPU_HEALTH_HEARTBEAT_GAP_SECS", None)

    # -- the negative: a clean run must take ZERO actions --------------------
    nclean = 2_000 if SMOKE else 20_000
    Xc = rng.normal(size=(nclean, 8)).astype(np.float32)
    colsc = {f"x{i}": Xc[:, i] for i in range(8)}
    colsc["y"] = np.where(Xc[:, 0] - Xc[:, 1] > 0, "Y", "N")
    frc = Frame.from_arrays(colsc)

    def clean_train():
        GLM(family="binomial", lambda_=1e-4, max_iterations=8).train(
            y="y", training_frame=frc)

    clean_train()      # warm-up: compiles land OUTSIDE the watched window
    ev, eng = rig()
    try:
        ev.evaluate()                             # baseline
        clean_train()
        ev.evaluate()
        out["clean_run"] = dict(
            actions_taken=eng.actions.recorded_total(),
            incidents_opened=ev.incidents.opened_total())
    finally:
        eng.uninstall()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


_OPS_EXPECTED = {"spill_thrash": "raise_cleaner_budget",
                 "serving_overload": "serving_relief",
                 "stalled_worker": "reassign_shards"}


def _ops_gate(op: dict) -> None:
    """Refuse to stamp unless the remediation engine healed every chaos
    class hands-off — exactly one applied action of the RIGHT class per
    incident, the incident resolved and stamped with the action id — and
    took zero actions on the clean run (a trigger-happy engine pages ops
    with changes nobody asked for)."""
    if op.get("error"):
        print(f"# bench REFUSED: ops section failed: {op['error']}",
              file=sys.stderr)
        sys.exit(3)
    for name, want in _OPS_EXPECTED.items():
        sc = op.get(name) or {}
        if sc.get("applied_actions") != [want]:
            print(f"# bench REFUSED: ops chaos '{name}' applied "
                  f"{sc.get('applied_actions')} — expected exactly one "
                  f"'{want}' action", file=sys.stderr)
            sys.exit(3)
        if not sc.get("healed") or not sc.get("action_stamped"):
            print(f"# bench REFUSED: ops chaos '{name}' did not heal "
                  f"hands-off (healed={sc.get('healed')}, "
                  f"stamped={sc.get('action_stamped')}) — a human would "
                  "have had to step in", file=sys.stderr)
            sys.exit(3)
    clean = op.get("clean_run") or {}
    if clean.get("actions_taken", 1) != 0:
        print(f"# bench REFUSED: remediation took "
              f"{clean.get('actions_taken')} action(s) on a CLEAN run — "
              "the engine remediates normal operation", file=sys.stderr)
        sys.exit(3)


def bench_flight(ndev: int) -> dict:
    """Flight-recorder proof (ISSUE 17): the always-on sampler watching a
    warm GLM must stay under the same 2% overhead budget as the tracer and
    health evaluator (vs ``H2O3TPU_FLIGHT_OFF=1``), its thread must
    demonstrably tick (a hollow recorder also costs 0%), a clean run must
    open ZERO trend incidents and write ZERO post-mortems, an injected
    RSS-growth trend must open exactly ONE trend incident whose context
    carries a non-empty series window, and an injected sweep wedge must
    produce exactly ONE on-disk post-mortem that unpacks with every
    member."""
    import shutil
    import tarfile
    import tempfile

    import jax

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.utils import blackbox as _bb
    from h2o3_tpu.utils import flight as _fl
    from h2o3_tpu.utils.blackbox import DUMP_MEMBERS, BlackBox
    from h2o3_tpu.utils.health import (HealthEvaluator, default_rules,
                                       trend_window)
    from h2o3_tpu.utils.incidents import IncidentLog
    from h2o3_tpu.utils.timeline import inject_faults

    n = 3_000 if SMOKE else (50_000 if CPU_FALLBACK else 1_000_000)
    iters = 10 if SMOKE else 25
    rng = np.random.default_rng(47)
    X = rng.normal(size=(n, 12)).astype(np.float32)
    logit = X[:, :5] @ np.array([0.8, -0.5, 0.3, -0.2, 0.4], np.float32)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit)))
    cols = {f"x{i}": X[:, i] for i in range(12)}
    cols["resp"] = np.where(y, "YES", "NO")
    fr = Frame.from_arrays(cols)

    def train():
        GLM(family="binomial", lambda_=1e-4, max_iterations=iters).train(
            y="resp", training_frame=fr)

    train()                       # warm-up: compiles out of the timed region
    jax.effects_barrier()
    trend_rules = [r for r in default_rules()
                   if r.name.startswith("trend_")]
    # the recorded/off comparison needs the knob in both positions, and the
    # sampler runs at bench cadence; operator exports must come back after
    saved = {k: os.environ.pop(k, None)
             for k in ("H2O3TPU_FLIGHT_OFF", "H2O3TPU_FLIGHT_INTERVAL_SECS",
                       "H2O3TPU_BLACKBOX_STALL_SECS",
                       "H2O3TPU_BLACKBOX_CHECK_SECS")}
    os.environ["H2O3TPU_FLIGHT_INTERVAL_SECS"] = "0.05"
    clean_dir = tempfile.mkdtemp(prefix="h2o3_bench_bb_clean_")
    wedge_dir = tempfile.mkdtemp(prefix="h2o3_bench_bb_wedge_")

    def timed_recorded() -> tuple:
        """One watched rep: global recorder sampling at 20Hz, the four
        trend rules sweeping against it, and an armed black box watching
        the sweep — a clean run must end with zero of each."""
        _fl.FLIGHT.reset()
        _fl.FLIGHT.start()
        ilog = IncidentLog(capacity=8)
        ev = HealthEvaluator(interval_s=0.05, rules=trend_rules,
                             incidents=ilog)
        bb = BlackBox(dump_dir=clean_dir)
        prev_bb = _bb.BLACKBOX
        _bb.BLACKBOX = bb
        try:
            bb.arm()
            bb.watch("health_sweep", period_s=0.05)
            ev.start()
            t0 = time.perf_counter()
            train()
            wall = time.perf_counter() - t0
            # hollow-recorder proof: the sampler THREAD must have ticked;
            # bounded wait OUTSIDE the timed window for sub-interval smokes
            deadline = time.monotonic() + 5.0
            while _fl.FLIGHT.ticks() < 1 and time.monotonic() < deadline:
                time.sleep(0.02)
            ev.evaluate()         # one final sweep over the finished run
            ev.stop()
            bb.disarm()           # ORDERLY shutdown: must never dump
            _fl.FLIGHT.stop()
            return (wall, _fl.FLIGHT.ticks(), _fl.FLIGHT.stats(),
                    ilog.opened_total(), int(bb.fired()))
        finally:
            _bb.BLACKBOX = prev_bb

    def timed_off() -> float:
        os.environ["H2O3TPU_FLIGHT_OFF"] = "1"
        try:
            t0 = time.perf_counter()
            train()
            return time.perf_counter() - t0
        finally:
            os.environ.pop("H2O3TPU_FLIGHT_OFF", None)

    reps = 1 if SMOKE else 2      # min-of-N damps scheduler noise
    try:
        recorded = [timed_recorded() for _ in range(reps)]
        t_on = min(r[0] for r in recorded)
        t_off = min(timed_off() for _ in range(reps))

        # -- injected trend: a rising RSS series must trip exactly one
        # trend incident whose context carries the series window --------
        _fl.FLIGHT.reset()
        nwin = trend_window()
        for i in range(nwin):
            _fl.FLIGHT.ingest("derived.host_rss_bytes", 1e9 * (1 + 0.02 * i),
                              now=float(i))
        tlog = IncidentLog(capacity=8)
        tev = HealthEvaluator(
            interval_s=60.0, incidents=tlog,
            rules=[r for r in trend_rules if r.name == "trend_rss_growth"])
        tev.evaluate()
        tev.evaluate()            # steady state: the edge must not re-fire
        trend_incidents = tlog.opened_total()
        window_points = 0
        for inc in tlog.export():
            win = (inc.get("context") or {}).get("flight_window") or {}
            window_points += len(win.get("samples") or [])
        _fl.FLIGHT.reset()

        # -- injected wedge: a stalled sweep must produce exactly one
        # on-disk post-mortem with every member -------------------------
        os.environ["H2O3TPU_BLACKBOX_STALL_SECS"] = "0.3"
        os.environ["H2O3TPU_BLACKBOX_CHECK_SECS"] = "0.05"
        wb = BlackBox(dump_dir=wedge_dir)
        prev_bb = _bb.BLACKBOX
        _bb.BLACKBOX = wb
        wlog = IncidentLog(capacity=8)
        wev = HealthEvaluator(interval_s=0.05, rules=[], incidents=wlog)
        try:
            wb.arm()
            wb.watch("health_sweep", period_s=0.05)
            with inject_faults(site_rates={"health.sweep": {
                    "stall_rate": 1.0, "stall_ms": 5_000}}):
                wev.start()
                deadline = time.monotonic() + 10.0
                while not wb.fired() and time.monotonic() < deadline:
                    time.sleep(0.05)
            wev.stop()
            wb.disarm()
        finally:
            _bb.BLACKBOX = prev_bb
        wedge_dumps = sorted(os.listdir(wedge_dir))
        wedge_members: list = []
        if len(wedge_dumps) == 1:
            with tarfile.open(os.path.join(wedge_dir, wedge_dumps[0])) as tf:
                # entries are h2o3_postmortem/<member> — compare bare names
                wedge_members = sorted(m.name.split("/", 1)[-1]
                                       for m in tf.getmembers())
    finally:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v
            else:
                os.environ.pop(k, None)
    clean_dumps = sorted(os.listdir(clean_dir))
    shutil.rmtree(clean_dir, ignore_errors=True)
    shutil.rmtree(wedge_dir, ignore_errors=True)
    stats = recorded[0][2]
    overhead = t_on / max(t_off, 1e-9) - 1.0
    return dict(
        seconds_recorded=round(t_on, 3), seconds_off=round(t_off, 3),
        overhead_pct=round(overhead * 100, 2),
        ticks=min(r[1] for r in recorded),
        series=stats.get("series"), samples_total=stats.get("samples_total"),
        dropped_series=stats.get("dropped_series"),
        clean_trend_incidents=sum(r[3] for r in recorded),
        clean_postmortems=len(clean_dumps) + sum(r[4] for r in recorded),
        trend_incidents=trend_incidents,
        trend_window_points=window_points,
        wedge_postmortems=len(wedge_dumps),
        wedge_members=wedge_members,
        expected_members=sorted(["reason.json"]
                                + [name for name, _ in DUMP_MEMBERS]))


def _flight_gate(fl: dict) -> None:
    """Refuse to stamp when the flight recorder is hollow, noisy, or
    blind: zero sampler ticks means nothing was recorded; any trend
    incident or post-mortem on a CLEAN run means the recorder pages on
    normal operation; the injected trend must trip exactly once WITH its
    series window; the injected wedge must leave exactly one complete
    post-mortem; >2% overhead breaks the always-on budget."""
    if fl.get("error"):
        print(f"# bench REFUSED: flight section failed: {fl['error']}",
              file=sys.stderr)
        sys.exit(3)
    if fl["ticks"] <= 0:
        print("# bench REFUSED: flight sampler never ticked — the recorder "
              "is hollow", file=sys.stderr)
        sys.exit(3)
    if fl["clean_trend_incidents"] > 0 or fl["clean_postmortems"] > 0:
        print(f"# bench REFUSED: clean run opened "
              f"{fl['clean_trend_incidents']} trend incident(s) and wrote "
              f"{fl['clean_postmortems']} post-mortem(s) — the recorder "
              "pages on normal operation", file=sys.stderr)
        sys.exit(3)
    if fl["trend_incidents"] != 1 or fl["trend_window_points"] <= 0:
        print(f"# bench REFUSED: injected RSS-growth trend opened "
              f"{fl['trend_incidents']} incident(s) with "
              f"{fl['trend_window_points']} window point(s) — expected "
              "exactly one with a non-empty series window",
              file=sys.stderr)
        sys.exit(3)
    missing = set(fl["expected_members"]) - set(fl["wedge_members"])
    if fl["wedge_postmortems"] != 1 or missing:
        print(f"# bench REFUSED: injected sweep wedge produced "
              f"{fl['wedge_postmortems']} post-mortem(s), missing members "
              f"{sorted(missing)} — expected exactly one with every member",
              file=sys.stderr)
        sys.exit(3)
    if not SMOKE and not CPU_FALLBACK and fl["overhead_pct"] > 2.0:
        print(f"# bench REFUSED: flight recorder overhead "
              f"{fl['overhead_pct']}% exceeds the 2% always-on budget",
              file=sys.stderr)
        sys.exit(3)


def _tracing_gate(trc: dict) -> None:
    """Refuse to stamp an artifact whose tracing section is hollow: an
    empty trace store after an instrumented run means the span plumbing
    regressed, and >2% tracer overhead on the traced GLM breaks the
    always-on contract (enforced on real runs; smoke/fallback captures
    annotate only — sub-second CPU runs put 2% under scheduler noise)."""
    if trc.get("error"):
        print(f"# bench REFUSED: tracing section failed: {trc['error']}",
              file=sys.stderr)
        sys.exit(3)
    if trc["trace_count"] == 0 or not trc.get("critical_path"):
        print("# bench REFUSED: trace store empty after an instrumented "
              "run — span recording is broken", file=sys.stderr)
        sys.exit(3)
    if not SMOKE and not CPU_FALLBACK and trc["overhead_pct"] > 2.0:
        print(f"# bench REFUSED: tracer overhead {trc['overhead_pct']}% "
              "exceeds the 2% always-on budget", file=sys.stderr)
        sys.exit(3)


def _probe_backend(timeout_s: float | None = None):
    """Initialize the default JAX backend in a THROWAWAY subprocess so a
    sick TPU runtime cannot wedge or crash the bench parent (round 3 lost
    BENCH_r03.json to exactly that: `jax.devices()` raised UNAVAILABLE and
    the artifact recorded a 40-line traceback, rc=1 — VERDICT r3 weak #1).

    Returns ``(ndev, backend_name)`` on success, ``(None, diagnostic)`` on
    failure/hang. On hang the child gets SIGTERM first — a SIGKILL mid-TPU
    initialization can wedge the chip for subsequent processes.
    """
    import subprocess

    if timeout_s is None:
        timeout_s = float(os.environ.get("H2O3TPU_BENCH_PREFLIGHT_TIMEOUT",
                                         "240"))
    code = "import jax; d = jax.devices(); print(jax.default_backend(), len(d))"
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=dict(os.environ))
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.terminate()                  # SIGTERM only — never SIGKILL a
        try:                              # process mid-TPU-init: a hard kill
            proc.communicate(timeout=30)  # mid-dispatch wedges the chip for
        except subprocess.TimeoutExpired:  # every later process on the host;
            pass                          # an abandoned probe exits on its own
        return None, (f"backend probe hung > {timeout_s:.0f}s "
                      "(TPU runtime unresponsive)")
    if proc.returncode != 0:
        tail = (err or "").strip().splitlines()
        return None, ("backend probe failed: "
                      + (tail[-1][:300] if tail else f"rc={proc.returncode}"))
    try:
        # plugins may print informational lines first; ours is the last line
        backend, ndev = out.strip().splitlines()[-1].split()
        return int(ndev), backend
    except (ValueError, IndexError):
        return None, f"backend probe produced unparseable output: {out!r}"


def _lint_gate() -> None:
    """Refuse to stamp a perf artifact from a tree carrying non-baselined
    graftlint findings: a new host-sync / lock-discipline / REST violation
    is exactly the class of regression the numbers are meant to certify
    against. Override with H2O3TPU_BENCH_SKIP_LINT=1 (diagnostics only)."""
    if os.environ.get("H2O3TPU_BENCH_SKIP_LINT", "") == "1":
        return
    from pathlib import Path

    from h2o3_tpu.tools.lint import (DEFAULT_BASELINE, load_baseline,
                                     run_lint, split_findings)
    pkg_root = Path(__file__).resolve().parent / "h2o3_tpu"
    new, _old = split_findings(run_lint(pkg_root),
                               load_baseline(DEFAULT_BASELINE))
    if new:
        for f in new:
            print(f"# graftlint: {f.render()}", file=sys.stderr)
        print(f"# bench REFUSED: {len(new)} non-baselined graftlint "
              "finding(s) — fix or baseline them before stamping an "
              "artifact", file=sys.stderr)
        sys.exit(3)


def _latest_prior_artifact(backend: str):
    """(filename, artifact-dict) of the most recent prior ``BENCH_r*.json``
    stamped on the same backend (honoring H2O3TPU_BENCH_BASELINE_EXCLUDE so
    a re-run never self-compares), or ``(None, None)``. Shared by the
    vs_baseline continuity path and the dispatch-audit regression gate."""
    import glob
    import re
    here = os.path.dirname(os.path.abspath(__file__))
    prior = (None, None)
    exclude = os.environ.get("H2O3TPU_BENCH_BASELINE_EXCLUDE", "")
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json")),
                       key=lambda p: [int(s) for s in re.findall(r"\d+", p)]):
        if exclude and os.path.basename(path) == exclude:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        art = doc.get("parsed", doc)   # driver wrapper or raw artifact
        if not isinstance(art, dict):
            continue
        val = art.get("value")
        ext = art.get("extra") or {}
        if isinstance(val, (int, float)) and val > 0 \
                and ext.get("backend") == backend:
            prior = (os.path.basename(path), art)
    return prior


def _resolve_vs_baseline(out: dict) -> None:
    """Baseline continuity (BENCH_r05 stamped ``vs_baseline: null``): a TPU
    run rates against the per-chip anchor; a CPU run must NEVER read as an
    anchor ratio (VERDICT r4 weak #6), so it rates against the most recent
    PRIOR ARTIFACT on the same backend instead — the trajectory stays
    comparable round over round whatever hardware the round drew.
    ``baseline_source`` names which comparator was used."""
    backend = out["extra"]["backend"]
    if SMOKE:
        out["vs_baseline"] = None      # toy-scale numbers rate nothing
        out["baseline_source"] = "none (smoke mode)"
        return
    if backend != "cpu" and not CPU_FALLBACK:
        out["baseline_source"] = \
            f"anchor {ANCHOR_ROWS_PER_SEC:.1e} rows*trees/sec/chip"
        return                         # anchor ratio already stamped
    # a manual RE-run after the driver already stamped this round's file
    # would otherwise self-compare (ratio ~1.0 masking a regression):
    # baseline_source names the comparator so that reads loudly, and the
    # rerunner can exclude the current round's file explicitly
    fname, art = _latest_prior_artifact(backend)
    if art is None:
        out["vs_baseline"] = None
        out["baseline_source"] = f"none (no prior {backend} artifact)"
        return
    pval = float(art["value"])
    out["vs_baseline"] = round(out["value"] / pval, 3)
    out["baseline_source"] = f"{fname} ({backend} prior artifact, {pval})"
    # differing hardware fingerprints make the ratio a hardware diff, not a
    # code diff — the artifact says so instead of leaving it to archaeology
    mine = out["extra"].get("hardware") or {}
    theirs = (art.get("extra") or {}).get("hardware")
    if theirs is None:
        out["baseline_hardware_mismatch"] = (
            f"{fname} predates hardware fingerprints — comparability "
            "unknown")
        return
    diffs = [f"{k}: {theirs.get(k)} -> {mine.get(k)}"
             for k in sorted(set(mine) | set(theirs))
             if mine.get(k) != theirs.get(k)]
    if diffs:
        out["baseline_hardware_mismatch"] = "; ".join(diffs)
        print(f"# bench WARNING: comparing against {fname} across a "
              f"hardware/software change ({'; '.join(diffs)}) — the "
              "vs_baseline ratio mixes code and platform effects",
              file=sys.stderr)


def _compute_section(extra: dict) -> dict:
    """``extra.compute`` — the observatory's view of the run the bench just
    measured (utils/costs.py, ``GET /3/Compute``): per-loop achieved FLOP/s
    and utilization (null off the peak table — every CPU round), per-site
    compile counts/seconds, recompile totals, and the per-scenario
    steady-state recompile probes collected above. The ROOFLINE.md
    arithmetic, stamped automatically every round."""
    from h2o3_tpu.utils.costs import COSTS, backend_peak
    snap = COSTS.snapshot()
    steady = {sec["scenario"]: sec["recompiles_steady_state"]
              for sec in extra.values()
              if isinstance(sec, dict) and "recompiles_steady_state" in sec}
    return {
        "peak": backend_peak(),
        "loops": snap["loops"],
        "sites": {s["site"]: {"compiles": s["compiles"],
                              "compile_seconds": s["compile_seconds"],
                              "flops": s["flops"], "bytes": s["bytes"],
                              "signatures": len(s["signatures"]),
                              "recompile_events": len(s["recompile_events"])}
                  for s in snap["sites"]},
        "recompile_events": snap["recompile_events"],
        "steady_state_recompiles": steady,
    }


def _compute_gate(out: dict) -> None:
    """Refuse to stamp when a warm steady-state scenario recompiled after
    its warm-up phase: the timed re-run is shape-identical by construction,
    so signature growth there means executables are churning — the exact
    recompile class behind the r04→r05 automl wobble, now caught at stamp
    time instead of in the next round's VERDICT."""
    if SMOKE:
        return
    steady = out["extra"]["compute"]["steady_state_recompiles"]
    churned = {k: v for k, v in steady.items() if v > 0}
    if churned:
        for scenario, n in churned.items():
            print(f"# steady-state recompile: {scenario} compiled {n} new "
                  "signature(s) during its shape-identical timed run",
                  file=sys.stderr)
        print(f"# bench REFUSED: {len(churned)} warm scenario(s) recompiled "
              "after warm-up — executables churn in steady state",
              file=sys.stderr)
        sys.exit(3)


def _dispatch_audit_section(backend: str) -> dict:
    """Host-sync economy of the convergence loops this bench just ran:
    blocking device→host fetches per logical iteration (GLM IRLS iteration,
    GBM boosting round, DL epoch), read from the
    ``h2o3_dispatches_per_iteration`` gauges the drivers publish, with a
    ``vs_prior`` comparison against the latest prior same-backend artifact
    so the CPU trajectory keeps rating the sync economy round over round."""
    from h2o3_tpu.utils.telemetry import DISPATCHES_PER_ITER
    current = {labels["loop"]: round(child.value, 4)
               for labels, child in DISPATCHES_PER_ITER.children()}
    sec: dict = {"syncs_per_step": current}
    fname, art = _latest_prior_artifact(backend)
    prior = ((art or {}).get("extra") or {}).get("dispatch_audit") or {}
    prior_steps = prior.get("syncs_per_step") or {}
    if prior_steps:
        sec["vs_prior"] = {
            loop: {"prior": prior_steps[loop], "current": cur,
                   "ratio": round(cur / max(prior_steps[loop], 1e-9), 3)}
            for loop, cur in current.items() if loop in prior_steps}
        sec["baseline_source"] = fname
    else:
        sec["vs_prior"] = None
        sec["baseline_source"] = (f"none (no prior {backend} artifact with "
                                  "a dispatch audit)")
    return sec


def _dispatch_gate(out: dict) -> None:
    """Refuse to stamp a real-run artifact whose syncs-per-step count
    REGRESSED versus the previous same-backend round: a loop paying more
    blocking host fetches per iteration than it used to means a
    per-iteration fetch crept back into a hot path — exactly what the
    megastep refactor (ISSUE 7) exists to prevent."""
    if SMOKE:
        return          # toy scale proves artifact shape only
    audit = (out["extra"].get("dispatch_audit") or {})
    regressed = [
        (loop, cmp["prior"], cmp["current"])
        for loop, cmp in (audit.get("vs_prior") or {}).items()
        if cmp["current"] > cmp["prior"] + 1e-6]
    if regressed:
        for loop, prior, cur in regressed:
            print(f"# dispatch regression: {loop} now pays {cur} host "
                  f"syncs/step (prior round: {prior})", file=sys.stderr)
        print(f"# bench REFUSED: {len(regressed)} loop(s) regressed their "
              "syncs-per-step vs the prior same-backend artifact",
              file=sys.stderr)
        sys.exit(3)


def main() -> None:
    _lint_gate()
    # -- TPU preflight ------------------------------------------------------
    # One clear diagnostic line + a CPU re-exec at reduced scale beats a
    # traceback in the artifact: the driver still gets rc=0 and a parsed
    # number, explicitly annotated as a fallback measurement.
    if not CPU_FALLBACK and os.environ.get("H2O3TPU_BENCH_PREFLIGHT", "1") != "0":
        ndev_probe, diag = _probe_backend()
        if ndev_probe is None:
            print(f"# TPU preflight FAILED: {diag} — re-running on CPU at "
                  "reduced scale (result annotated backend_fallback)",
                  file=sys.stderr)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["_H2O3TPU_BENCH_CPU_FALLBACK"] = diag
            rows = str(min(ROWS, 200_000))
            os.execve(sys.executable,
                      [sys.executable, os.path.abspath(__file__), rows], env)

    import jax

    # the environment's sitecustomize registers the TPU plugin even when
    # JAX_PLATFORMS=cpu is set (see tests/conftest.py); force the platform
    # in-config or the fallback run would initialize the sick backend anyway
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")

    # persistent XLA compilation cache (the standard TPU production setup):
    # AutoML's many model configs are compile-bound on a cold process; the
    # cache cuts repeat runs to pure compute. Timed regions below still
    # include a warm-up call, so cold-vs-warm compile state never leaks
    # into the reported rows/sec. Default ON under bench (H2O3TPU_COMPILE_CACHE
    # overrides); hit/miss counts land in the artifact below.
    from h2o3_tpu.utils import compile_cache
    compile_cache.enable(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"),
        default_on=True)
    ndev = max(1, len(jax.devices()))

    extra: dict = {}
    fr = _higgs_frame(ROWS)
    gbm = bench_gbm(fr, ndev)

    # smoke mode proves the artifact SHAPE (preflight, fallback, JSON); the
    # secondary configs only add CPU compile minutes there
    secondary = () if SMOKE else (
        ("xgboost_hist_11m", bench_xgboost, (fr, ndev)),
        ("glm_airlines_1m", bench_glm, (ndev,)),
        ("dl_mlp_mnist", bench_dl, (ndev,)),
        ("automl_leaderboard_100k", bench_automl, (ndev,)))
    # leak-detector generations interleave with the workloads (without an
    # HBM budget the Cleaner never sweeps): a key whose bytes keep RISING
    # across configs accumulates a growth streak that the memory gate
    # refuses — post-hoc back-to-back sweeps alone could never see growth
    from h2o3_tpu.utils.memory import MEMORY
    MEMORY.refresh()
    MEMORY.leak_sweep()
    for name, fn, args in secondary:
        t0 = time.perf_counter()
        try:
            extra[name] = fn(*args)
        except Exception as e:   # noqa: BLE001 — secondary configs best-effort
            extra[name] = {"error": f"{type(e).__name__}: {e}"}
        print(f"# bench: {name} done in {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        MEMORY.refresh()        # catch in-place growth, not just re-puts
        MEMORY.leak_sweep()

    out = {
        "metric": "gbm_hist_train_rows_per_sec_per_chip",
        "value": gbm["rows_per_sec_chip"],
        "unit": "rows*trees/sec/chip",
        "vs_baseline": round(gbm["rows_per_sec_chip"] / ANCHOR_ROWS_PER_SEC, 3),
        "extra": {"gbm_higgs_11m": gbm, **extra,
                  "backend": jax.default_backend(), "devices": ndev,
                  "rows": fr.nrows, "hardware": _hardware_fingerprint()},
    }
    if CPU_FALLBACK:
        out["extra"]["backend_fallback"] = (
            f"TPU unavailable ({CPU_FALLBACK}); CPU at reduced scale — "
            "NOT comparable to per-chip baselines")
    _resolve_vs_baseline(out)
    # dispatch accounting: blocking host syncs per GLM iteration / GBM round
    # / DL epoch, gated against the prior same-backend round (ISSUE 7 — a
    # reintroduced per-iteration fetch refuses to stamp)
    out["extra"]["dispatch_audit"] = _dispatch_audit_section(
        out["extra"]["backend"])
    _dispatch_gate(out)
    # mesh-slice scheduling: par4 on disjoint slices must beat (or match)
    # sequential full-mesh builds on a real multi-device run
    _slices_gate(out)
    # chaos: completion-under-faults with retry absorption (ISSUE 8) —
    # refuses to stamp when a faulted run deadlocks or diverges
    try:
        ch = bench_chaos(ndev)
    except Exception as e:   # noqa: BLE001 — gate reports, then refuses
        ch = {"error": f"{type(e).__name__}: {e}"}
    out["extra"]["chaos"] = ch
    _chaos_gate(ch)
    # elastic local-SGD: kill 1 of k workers mid-epoch — must complete with
    # exactly one ejection, and on real hardware the kill must cost less
    # than the dead worker's throughput share (ROADMAP item 3)
    if SMOKE:
        el: dict = {"skipped": "smoke"}
    else:
        try:
            el = bench_elastic(ndev)
        except Exception as e:   # noqa: BLE001 — gate reports, then refuses
            el = {"error": f"{type(e).__name__}: {e}"}
    out["extra"]["elastic"] = el
    _elastic_gate(el, out["extra"]["backend"])
    # serving path: score_qps through the compiled/batched /3/Score tier
    # vs the per-request predict path (ISSUE 6: the scoring tier gets the
    # same perf trajectory the training path has)
    try:
        sc = bench_scoring(ndev)
    except Exception as e:   # noqa: BLE001 — gate reports, then refuses
        sc = {"error": f"{type(e).__name__}: {e}"}
    out["extra"]["scoring"] = sc
    _scoring_gate(sc)
    # SLO-adaptive serving: hold a p99 target under open-loop arrivals
    # with a concurrent GBM build, shed low priority first (ISSUE 13);
    # rides inside extra.scoring as the `slo` block
    try:
        sl = bench_serving_slo(ndev)
    except Exception as e:   # noqa: BLE001 — gate reports, then refuses
        sl = {"error": f"{type(e).__name__}: {e}"}
    sc["slo"] = sl
    _serving_slo_gate(sl, out["extra"]["backend"])
    # compute observatory: achieved FLOP/s + utilization-or-null per loop,
    # compile/recompile accounting, and the steady-state recompile gate —
    # a warm scenario that recompiled after its warm-up refuses to stamp
    out["extra"]["compute"] = _compute_section(out["extra"])
    _compute_gate(out)
    # out-of-core ingest: streaming-parse + GLM-train a dataset larger than
    # the capped host budget, with a spill/fault-in cycle and a streamed-
    # vs-eager bit-identity check (ISSUE 14; docs/INGEST.md) — the gate
    # refuses divergence anywhere and a blown cap on real runs
    try:
        ing = bench_ingest(ndev)
    except Exception as e:   # noqa: BLE001 — gate reports, then refuses
        ing = {"error": f"{type(e).__name__}: {e}"}
    out["extra"]["ingest"] = ing
    _ingest_gate(ing)
    MEMORY.refresh()
    MEMORY.leak_sweep()
    # compile-cache effectiveness this round (satellite of ROADMAP item 5:
    # the automl wobble is recompiles; the trajectory now records hit rate)
    out["extra"]["compile_cache"] = compile_cache.stats()
    # tracing: overhead measurement + the slowest trace's critical path;
    # gates below refuse to stamp when the span plumbing is broken
    try:
        trc = bench_tracing(ndev)
    except Exception as e:   # noqa: BLE001 — gate reports, then refuses
        trc = {"error": f"{type(e).__name__}: {e}"}
    out["extra"]["tracing"] = trc
    _tracing_gate(trc)
    # memory: host/device watermarks + DKV byte totals + leak-detector pass
    # over the bench's resident keys; the gate refuses to stamp when the
    # detector fires on a real run (docs/OBSERVABILITY.md "Memory")
    try:
        memsec = bench_memory()
    except Exception as e:   # noqa: BLE001 — gate reports, then refuses
        memsec = {"error": f"{type(e).__name__}: {e}"}
    out["extra"]["memory"] = memsec
    _memory_gate(memsec)
    # ops plane: the health evaluator watching a clean GLM run must stay
    # healthy with zero incidents (hollow-watchdog guard: it must also
    # have actually swept) and under the 2% always-on overhead budget vs
    # H2O3TPU_HEALTH_OFF=1 (ISSUE 15; docs/OBSERVABILITY.md "Health &
    # incidents")
    try:
        hl = bench_health(ndev)
    except Exception as e:   # noqa: BLE001 — gate reports, then refuses
        hl = {"error": f"{type(e).__name__}: {e}"}
    out["extra"]["health"] = hl
    _health_gate(hl)
    # self-driving ops: replay the chaos classes with remediation in ACT
    # mode — the gate refuses unless every class heals hands-off via one
    # audited action of the right class and the clean run takes none
    # (ISSUE 16; docs/OPERATIONS.md)
    try:
        op = bench_ops(ndev)
    except Exception as e:   # noqa: BLE001 — gate reports, then refuses
        op = {"error": f"{type(e).__name__}: {e}"}
    out["extra"]["ops"] = op
    _ops_gate(op)
    # flight recorder: always-on sampling must stay under the 2% budget vs
    # H2O3TPU_FLIGHT_OFF=1 (hollow-recorder guard: the thread must tick),
    # the injected RSS trend must open exactly one windowed trend incident,
    # the injected sweep wedge exactly one complete post-mortem, and the
    # clean run neither (ISSUE 17; docs/OBSERVABILITY.md "Flight recorder
    # & post-mortems")
    try:
        flr = bench_flight(ndev)
    except Exception as e:   # noqa: BLE001 — gate reports, then refuses
        flr = {"error": f"{type(e).__name__}: {e}"}
    out["extra"]["flight"] = flr
    _flight_gate(flr)
    # metrics snapshot rides along in the artifact (dispatch counts, parse
    # bytes, model-build latencies) so the perf trajectory carries telemetry;
    # buckets omitted to keep the JSON line compact
    from h2o3_tpu.utils.telemetry import METRICS
    out["extra"]["telemetry"] = METRICS.snapshot(include_buckets=False)
    print(json.dumps(out))
    print(f"# detail: {json.dumps(extra)}", file=sys.stderr)


if __name__ == "__main__":
    main()
