# End-to-end smoke of the h2o3tpu R client (run with the server URL as arg):
#   Rscript clients/r/run_smoke.R http://127.0.0.1:54321 /path/to/train.csv
# Mirrors the canonical h2o-r session: init -> importFile -> splitFrame ->
# gbm/glm -> predict -> performance -> rm.

for (f in list.files("clients/r/h2o3tpu/R", full.names = TRUE)) source(f)

args <- commandArgs(trailingOnly = TRUE)
url <- args[1]
csv <- args[2]

h2o.connect(url = url)
stopifnot(h2o.clusterStatus()$cloud_healthy)

fr <- h2o.importFile(csv, destination_frame = "r_train")
parts <- h2o.splitFrame(fr, ratios = 0.8, seed = 42,
                        destination_frames = c("r_tr", "r_te"))
tr <- parts[[1]]
te <- parts[[2]]

gbm <- h2o.gbm(y = "y", training_frame = tr, ntrees = 5, max_depth = 3)
perf <- h2o.performance(gbm, newdata = te)
cat("GBM AUC:", h2o.auc(perf), "\n")
stopifnot(h2o.auc(perf) > 0.7)

pred <- h2o.predict(gbm, te)
pdf_ <- as.data.frame(pred)
stopifnot(nrow(pdf_) >= 1, "predict" %in% names(pdf_))

glm <- h2o.glm(y = "y", training_frame = tr, family = "binomial")
cat("GLM logloss:",
    h2o.logloss(h2o.performance(glm, newdata = te)), "\n")

# round-3 verbs: xgboost, scoring history, grid, automl, save/load, ensemble
xgb <- h2o.xgboost(y = "y", training_frame = tr, ntrees = 4, max_depth = 3)
sh <- h2o.scoreHistory(xgb)
stopifnot(nrow(sh) == 4)

grid <- h2o.grid("gbm", y = "y", training_frame = tr, ntrees = 3,
                 hyper_params = list(max_depth = c(2, 3)))
stopifnot(length(grid$model_ids) == 2)

aml <- h2o.automl(y = "y", training_frame = tr, max_models = 2, nfolds = 0,
                  seed = 1, include_algos = '["GLM","GBM"]',
                  project_name = "r_smoke_aml")
stopifnot(nrow(aml$leaderboard) >= 2)
lb <- h2o.get_leaderboard(aml, extra_columns = "ALL")
stopifnot("algo" %in% names(lb))

saved <- h2o.saveModel(xgb, tempdir())
back <- h2o.loadModel(saved)
stopifnot(back$model_id == xgb$model_id)

b1 <- h2o.gbm(y = "y", training_frame = tr, ntrees = 3, max_depth = 2,
              nfolds = 3, seed = 1,
              keep_cross_validation_predictions = TRUE)
b2 <- h2o.gbm(y = "y", training_frame = tr, ntrees = 5, max_depth = 3,
              nfolds = 3, seed = 2,
              keep_cross_validation_predictions = TRUE)
se <- h2o.stackedEnsemble(y = "y", training_frame = tr,
                          base_models = list(b1, b2))
stopifnot(h2o.auc(h2o.performance(se, newdata = te)) > 0.7)

stopifnot(length(h2o.ls()) >= 3)
h2o.rm(pred)
h2o.removeAll()
cat("R_CLIENT_SMOKE_OK\n")
