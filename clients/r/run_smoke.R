# End-to-end smoke of the h2o3tpu R client (run with the server URL as arg):
#   Rscript clients/r/run_smoke.R http://127.0.0.1:54321 /path/to/train.csv
# Mirrors the canonical h2o-r session: init -> importFile -> splitFrame ->
# gbm/glm -> predict -> performance -> rm.

for (f in list.files("clients/r/h2o3tpu/R", full.names = TRUE)) source(f)

args <- commandArgs(trailingOnly = TRUE)
url <- args[1]
csv <- args[2]

h2o.connect(url = url)
stopifnot(h2o.clusterStatus()$cloud_healthy)

fr <- h2o.importFile(csv, destination_frame = "r_train")
parts <- h2o.splitFrame(fr, ratios = 0.8, seed = 42,
                        destination_frames = c("r_tr", "r_te"))
tr <- parts[[1]]
te <- parts[[2]]

gbm <- h2o.gbm(y = "y", training_frame = tr, ntrees = 5, max_depth = 3)
perf <- h2o.performance(gbm, newdata = te)
cat("GBM AUC:", h2o.auc(perf), "\n")
stopifnot(h2o.auc(perf) > 0.7)

pred <- h2o.predict(gbm, te)
pdf_ <- as.data.frame(pred)
stopifnot(nrow(pdf_) >= 1, "predict" %in% names(pdf_))

glm <- h2o.glm(y = "y", training_frame = tr, family = "binomial")
cat("GLM logloss:",
    h2o.logloss(h2o.performance(glm, newdata = te)), "\n")

stopifnot(length(h2o.ls()) >= 3)
h2o.rm(pred)
h2o.removeAll()
cat("R_CLIENT_SMOKE_OK\n")
