# h2o3tpu — R client for the h2o3_tpu server (reference surface:
# /root/reference/h2o-r/h2o-package/R/; this package mirrors the h2o.* verbs
# h2o-r users call most: init/connect, importFile, gbm/glm/randomForest/
# deeplearning/kmeans, predict, performance, splitFrame, ls/rm).
#
# Dependency-free by design: the image this framework targets carries no
# CRAN mirror, so HTTP is hand-rolled over base-R socketConnection and JSON
# is parsed by a small recursive-descent reader (both ~a page each). The
# wire format is the same V3 schema JSON h2o-py consumes.

.h2o3tpu <- new.env(parent = emptyenv())

# ---------------------------------------------------------------------------
# minimal JSON reader (objects, arrays, strings, numbers, true/false/null)

.json_parse <- function(txt) {
  pos <- 1L
  n <- nchar(txt)
  peek <- function() substr(txt, pos, pos)
  skip_ws <- function() {
    while (pos <= n && peek() %in% c(" ", "\t", "\n", "\r")) pos <<- pos + 1L
  }
  parse_value <- function() {
    skip_ws()
    ch <- peek()
    if (ch == "{") return(parse_object())
    if (ch == "[") return(parse_array())
    if (ch == '"') return(parse_string())
    if (ch == "t") { pos <<- pos + 4L; return(TRUE) }
    if (ch == "f") { pos <<- pos + 5L; return(FALSE) }
    if (ch == "n") { pos <<- pos + 4L; return(NULL) }
    parse_number()
  }
  parse_object <- function() {
    pos <<- pos + 1L  # {
    out <- list()
    skip_ws()
    if (peek() == "}") { pos <<- pos + 1L; return(out) }
    repeat {
      skip_ws()
      key <- parse_string()
      skip_ws()
      pos <<- pos + 1L  # :
      val <- parse_value()
      out[[key]] <- val
      skip_ws()
      ch <- peek()
      pos <<- pos + 1L
      if (ch == "}") return(out)
    }
  }
  parse_array <- function() {
    pos <<- pos + 1L  # [
    out <- list()
    skip_ws()
    if (peek() == "]") { pos <<- pos + 1L; return(out) }
    repeat {
      out[[length(out) + 1L]] <- parse_value()
      skip_ws()
      ch <- peek()
      pos <<- pos + 1L
      if (ch == "]") return(out)
    }
  }
  parse_string <- function() {
    pos <<- pos + 1L  # opening quote
    start <- pos
    buf <- character(0)
    repeat {
      ch <- peek()
      if (ch == "\\") {
        buf <- c(buf, substr(txt, start, pos - 1L))
        esc <- substr(txt, pos + 1L, pos + 1L)
        buf <- c(buf, switch(esc, n = "\n", t = "\t", r = "\r",
                             b = "\b", f = "\f", u = {
                               code <- substr(txt, pos + 2L, pos + 5L)
                               pos <<- pos + 4L
                               intToUtf8(strtoi(code, 16L))
                             }, esc))
        pos <<- pos + 2L
        start <- pos
      } else if (ch == '"') {
        buf <- c(buf, substr(txt, start, pos - 1L))
        pos <<- pos + 1L
        return(paste0(buf, collapse = ""))
      } else {
        pos <<- pos + 1L
      }
    }
  }
  parse_number <- function() {
    start <- pos
    while (pos <= n && peek() %in% c("-", "+", ".", "e", "E",
                                     as.character(0:9))) pos <<- pos + 1L
    as.numeric(substr(txt, start, pos - 1L))
  }
  parse_value()
}

.json_escape <- function(s) {
  s <- gsub("\\\\", "\\\\\\\\", s)
  s <- gsub('"', '\\\\"', s)
  s <- gsub("\n", "\\\\n", s)
  s
}

# ---------------------------------------------------------------------------
# HTTP over socketConnection (the server is HTTP/1.1 with Content-Length)

.http <- function(method, path, body = NULL) {
  host <- .h2o3tpu$host
  port <- .h2o3tpu$port
  if (is.null(host)) stop("not connected: call h2o.init()/h2o.connect() first")
  payload <- ""
  ctype <- ""
  if (!is.null(body)) {
    kv <- vapply(names(body), function(k) {
      v <- body[[k]]
      if (is.list(v) || length(v) > 1) {
        v <- paste0("[", paste0(
          vapply(v, function(x) if (is.character(x))
            paste0('"', .json_escape(x), '"') else as.character(x),
            character(1)), collapse = ","), "]")
      } else if (is.logical(v)) {
        v <- if (v) "true" else "false"
      }
      paste0(URLencode(k, reserved = TRUE), "=",
             URLencode(as.character(v), reserved = TRUE))
    }, character(1))
    payload <- paste0(kv, collapse = "&")
    ctype <- "Content-Type: application/x-www-form-urlencoded\r\n"
  }
  req <- paste0(method, " ", path, " HTTP/1.1\r\n",
                "Host: ", host, ":", port, "\r\n",
                "Connection: close\r\n", ctype,
                "Content-Length: ", nchar(payload, type = "bytes"), "\r\n",
                "\r\n", payload)
  con <- socketConnection(host = host, port = port, open = "r+b",
                          blocking = TRUE)
  on.exit(close(con))
  writeBin(charToRaw(req), con)
  raw <- raw(0)
  repeat {
    chunk <- readBin(con, what = "raw", n = 65536L)
    if (length(chunk) == 0) break
    raw <- c(raw, chunk)
  }
  resp <- rawToChar(raw)
  split_at <- regexpr("\r\n\r\n", resp, fixed = TRUE)
  headers <- substr(resp, 1, split_at - 1)
  body_txt <- substr(resp, split_at + 4, nchar(resp))
  status <- as.integer(strsplit(headers, " ")[[1]][2])
  parsed <- tryCatch(.json_parse(body_txt), error = function(e) body_txt)
  if (status >= 400) {
    msg <- if (is.list(parsed) && !is.null(parsed$msg)) parsed$msg else body_txt
    stop(sprintf("%s %s -> HTTP %d: %s", method, path, status, msg))
  }
  parsed
}

.poll_job <- function(job_key) {
  repeat {
    j <- .http("GET", paste0("/3/Jobs/", job_key))$jobs[[1]]
    if (j$status %in% c("DONE", "FAILED", "CANCELLED")) {
      if (j$status == "FAILED")
        stop("job failed: ", if (is.null(j$exception)) "" else j$exception)
      return(j)
    }
    Sys.sleep(0.2)
  }
}

# ---------------------------------------------------------------------------
# public surface (names match h2o-r)

h2o.connect <- function(ip = "localhost", port = 54321, url = NULL) {
  if (!is.null(url)) {
    m <- regmatches(url, regexec("^https?://([^:/]+):([0-9]+)", url))[[1]]
    ip <- m[2]
    port <- as.integer(m[3])
  }
  .h2o3tpu$host <- ip
  .h2o3tpu$port <- as.integer(port)
  st <- .http("GET", "/3/Cloud")
  message(sprintf("Connected to h2o3_tpu cloud '%s' (%d device(s), version %s)",
                  st$cloud_name, st$cloud_size, st$version))
  invisible(st)
}

h2o.init <- function(ip = "localhost", port = 54321, url = NULL, ...) {
  # attach-only (the server is a python process); mirrors h2o.init's
  # connect-if-running behavior
  h2o.connect(ip = ip, port = port, url = url)
}

h2o.clusterStatus <- function() .http("GET", "/3/Cloud")

h2o.cloud <- function() .http("GET", "/3/Cloud")

h2o.meshSlices <- function() {
  # mesh-slice scheduler utilization (slice layout, busy seconds, builds,
  # queue wait) — served inside /3/Cloud (docs/ORCHESTRATION.md)
  .http("GET", "/3/Cloud")$mesh_slices
}

h2o.workers <- function() {
  # elastic local-SGD membership: per-worker state / round / last-heartbeat
  # rows of recent elastic groups (docs/RELIABILITY.md "Elastic training")
  .http("GET", "/3/Cloud")$workers
}

h2o.importFile <- function(path, destination_frame = NULL) {
  body <- list(path = path)
  if (!is.null(destination_frame)) body$destination_frame <- destination_frame
  # a nonexistent/unreadable server path is a structured 400 whose msg
  # .http() raises via stop() — never a 500 traceback; per-file fails from
  # ImportFilesMulti-shaped replies surface the same way
  out <- .http("POST", "/3/ImportFiles", body)
  if (length(out$fails) > 0)
    stop("importFile failed: ", paste(unlist(out$fails), collapse = "; "))
  if (length(out$destination_frames) == 0)
    stop("importFile: server imported no frames for ", path)
  key <- out$destination_frames[[1]]
  structure(list(frame_id = key), class = "H2OFrame")
}

h2o.getFrame <- function(id) structure(list(frame_id = id), class = "H2OFrame")

.frame_info <- function(fr) {
  .http("GET", paste0("/3/Frames/", fr$frame_id))$frames[[1]]
}

as.data.frame.H2OFrame <- function(x, ...) {
  info <- .frame_info(x)
  cols <- info$columns
  out <- list()
  for (col in cols) {
    vals <- col$data
    if (!is.null(col$string_data)) vals <- col$string_data
    v <- unlist(lapply(vals, function(z) if (is.null(z)) NA else z))
    if (!is.null(col$domain) && length(col$domain) > 0 && is.numeric(v)) {
      v <- unlist(col$domain)[v + 1]
    }
    out[[col$label]] <- v
  }
  as.data.frame(out, stringsAsFactors = FALSE)
}

h2o.ls <- function() {
  frames <- .http("GET", "/3/Frames")$frames
  vapply(frames, function(f) f$frame_id$name, character(1))
}

h2o.rm <- function(id) {
  if (inherits(id, "H2OFrame")) id <- id$frame_id
  if (inherits(id, "H2OModel")) id <- id$model_id
  invisible(.http("DELETE", paste0("/3/DKV/", id)))
}

h2o.removeAll <- function() invisible(.http("DELETE", "/3/DKV"))

h2o.splitFrame <- function(data, ratios = 0.75, destination_frames = NULL,
                           seed = -1) {
  n <- length(ratios) + 1
  if (is.null(destination_frames))
    destination_frames <- paste0(data$frame_id, "_part", seq_len(n) - 1)
  out <- .http("POST", "/3/SplitFrame",
               list(dataset = data$frame_id, ratios = as.list(ratios),
                    destination_frames = as.list(destination_frames)))
  .poll_job(out$key$name)
  lapply(destination_frames, h2o.getFrame)
}

.train <- function(algo, x, y, training_frame, validation_frame = NULL, ...) {
  body <- list(training_frame = training_frame$frame_id)
  if (!is.null(y)) body$response_column <- y
  if (!is.null(x)) body$x <- as.list(x)
  if (!is.null(validation_frame))
    body$validation_frame <- validation_frame$frame_id
  extra <- list(...)
  for (k in names(extra)) body[[k]] <- extra[[k]]
  out <- .http("POST", paste0("/3/ModelBuilders/", algo), body)
  job <- .poll_job(out$job$key$name)
  model_id <- job$dest$name
  mj <- .http("GET", paste0("/3/Models/", model_id))$models[[1]]
  structure(list(model_id = model_id, algo = algo, json = mj),
            class = "H2OModel")
}

h2o.gbm <- function(x = NULL, y, training_frame, ...)
  .train("gbm", x, y, training_frame, ...)

h2o.glm <- function(x = NULL, y, training_frame, ...)
  .train("glm", x, y, training_frame, ...)

h2o.randomForest <- function(x = NULL, y, training_frame, ...)
  .train("drf", x, y, training_frame, ...)

h2o.deeplearning <- function(x = NULL, y, training_frame, ...)
  .train("deeplearning", x, y, training_frame, ...)

h2o.kmeans <- function(training_frame, x = NULL, ...)
  .train("kmeans", x, NULL, training_frame, ...)

h2o.xgboost <- function(x = NULL, y, training_frame, ...)
  .train("xgboost", x, y, training_frame, ...)

h2o.naiveBayes <- function(x = NULL, y, training_frame, ...)
  .train("naivebayes", x, y, training_frame, ...)

h2o.isolationForest <- function(training_frame, x = NULL, ...)
  .train("isolationforest", x, NULL, training_frame, ...)

h2o.prcomp <- function(training_frame, x = NULL, k = 2, ...)
  .train("pca", x, NULL, training_frame, k = k, ...)

# -- long-tail estimator verbs (reference h2o-r surface; each maps onto the
# -- same ModelBuilders POST + job-poll machinery) ---------------------------

h2o.coxph <- function(x = NULL, event_column, stop_column, training_frame,
                      ...)
  .train("coxph", x, event_column, training_frame,
         stop_column = stop_column, ...)

h2o.gam <- function(x = NULL, y, training_frame, gam_columns = NULL, ...) {
  if (is.null(gam_columns))
    .train("gam", x, y, training_frame, ...)
  else
    .train("gam", x, y, training_frame,
           gam_columns = as.list(gam_columns), ...)
}

h2o.glrm <- function(training_frame, k = 2, ...)
  .train("glrm", NULL, NULL, training_frame, k = k, ...)

h2o.svd <- function(training_frame, nv = 2, ...)
  .train("svd", NULL, NULL, training_frame, nv = nv, ...)

h2o.rulefit <- function(x = NULL, y, training_frame, ...)
  .train("rulefit", x, y, training_frame, ...)

h2o.psvm <- function(x = NULL, y, training_frame, ...)
  .train("psvm", x, y, training_frame, ...)

h2o.isotonicregression <- function(x = NULL, y, training_frame, ...)
  .train("isotonicregression", x, y, training_frame, ...)

h2o.targetencoder <- function(x = NULL, y, training_frame, ...)
  .train("targetencoder", x, y, training_frame, ...)

h2o.extendedIsolationForest <- function(training_frame, x = NULL, ...)
  .train("extendedisolationforest", x, NULL, training_frame, ...)

h2o.upliftRandomForest <- function(x = NULL, y, training_frame,
                                   treatment_column, ...)
  .train("upliftdrf", x, y, training_frame,
         treatment_column = treatment_column, ...)

h2o.decision_tree <- function(x = NULL, y, training_frame, ...)
  .train("decisiontree", x, y, training_frame, ...)

h2o.aggregator <- function(training_frame, x = NULL, ...)
  .train("aggregator", x, NULL, training_frame, ...)

h2o.infogram <- function(x = NULL, y, training_frame, ...)
  .train("infogram", x, y, training_frame, ...)

h2o.anovaglm <- function(x = NULL, y, training_frame, ...)
  .train("anovaglm", x, y, training_frame, ...)

h2o.modelSelection <- function(x = NULL, y, training_frame, ...)
  .train("modelselection", x, y, training_frame, ...)

h2o.word2vec <- function(training_frame, ...)
  .train("word2vec", NULL, NULL, training_frame, ...)

# -- MOJO migration (reference h2o-r h2o.import_mojo / h2o.upload_mojo) ------

h2o.import_mojo <- function(mojo_file_path, model_id = NULL) {
  body <- list(path = mojo_file_path)
  if (!is.null(model_id)) body$model_id <- model_id
  out <- .http("POST", "/3/ModelBuilders/generic", body)
  job <- .poll_job(out$job$key$name)
  h2o.getModel(job$dest$name)
}

h2o.varimp <- function(object) {
  vi <- object$json$output$variable_importances
  if (is.null(vi)) return(NULL)
  .table_to_df(vi)
}

h2o.mse <- function(perf) perf$MSE
h2o.aucpr <- function(perf) perf$pr_auc

h2o.stackedEnsemble <- function(x = NULL, y, training_frame, base_models,
                                ...) {
  ids <- vapply(base_models, function(m)
    if (inherits(m, "H2OModel")) m$model_id else as.character(m), "")
  .train("stackedensemble", x, y, training_frame,
         base_models = paste0("[", paste(ids, collapse = ","), "]"), ...)
}

h2o.getModel <- function(model_id) {
  mj <- .http("GET", paste0("/3/Models/", model_id))$models[[1]]
  structure(list(model_id = model_id, algo = mj$algo, json = mj),
            class = "H2OModel")
}

# -- TwoDimTable (reference: water/api/schemas3/TwoDimTableV3) ---------------

.table_to_df <- function(tbl) {
  cols <- tbl$columns
  data <- tbl$data
  keep <- which(vapply(cols, function(c) !identical(c$name, ""), TRUE))
  out <- lapply(keep, function(i) {
    col <- data[[i]]
    col[vapply(col, is.null, TRUE)] <- NA
    v <- unlist(col, use.names = FALSE)
    if (identical(cols[[i]]$type, "double") ||
        identical(cols[[i]]$type, "long")) suppressWarnings(as.numeric(v))
    else v
  })
  names(out) <- vapply(keep, function(i) cols[[i]]$name, "")
  as.data.frame(out, stringsAsFactors = FALSE, check.names = FALSE)
}

h2o.scoreHistory <- function(model) {
  sh <- model$json$output$scoring_history
  if (is.null(sh)) return(NULL)
  .table_to_df(sh)
}

# -- AutoML (reference: h2o-r h2o.automl / water/automl/api) -----------------

h2o.automl <- function(x = NULL, y, training_frame, max_models = 0,
                       max_runtime_secs = 0, nfolds = -1, seed = -1,
                       project_name = NULL, ...) {
  body <- list(training_frame = training_frame$frame_id,
               response_column = y, max_models = max_models,
               max_runtime_secs = max_runtime_secs, nfolds = nfolds,
               seed = seed)
  if (!is.null(project_name)) body$project_name <- project_name
  extra <- list(...)
  for (k in names(extra)) body[[k]] <- extra[[k]]
  out <- .http("POST", "/99/AutoMLBuilder", body)
  .poll_job(out$job$key$name)
  project <- out$build_control$project_name
  state <- .http("GET", paste0("/99/AutoML/", project))
  leader_id <- if (length(state$leaderboard$models))
    state$leaderboard$models[[1]]$name else NULL
  structure(list(project_name = project,
                 leader = if (!is.null(leader_id)) h2o.getModel(leader_id),
                 leaderboard = .table_to_df(state$leaderboard_table),
                 event_log = .table_to_df(state$event_log_table)),
            class = "H2OAutoML")
}

h2o.get_leaderboard <- function(object, extra_columns = NULL) {
  path <- paste0("/99/Leaderboards/", object$project_name)
  if (!is.null(extra_columns))
    path <- paste0(path, "?extensions=",
                   paste(extra_columns, collapse = ","))
  .table_to_df(.http("GET", path)$table)
}

# -- Grid search (reference: h2o-r h2o.grid) ---------------------------------

h2o.grid <- function(algorithm, x = NULL, y = NULL, training_frame,
                     hyper_params, search_criteria = NULL, ...) {
  .json_val <- function(v) {
    if (is.character(v)) paste0("\"", .json_escape(v), "\"")
    else if (is.logical(v)) tolower(as.character(v))
    else as.character(v)
  }
  .json_obj <- function(lst) {
    paste0("{", paste(vapply(names(lst), function(k) {
      v <- lst[[k]]
      val <- if (length(v) > 1 || is.list(v))
        paste0("[", paste(vapply(unlist(v), .json_val, ""),
                          collapse = ","), "]")
      else .json_val(v)
      paste0("\"", k, "\":", val)
    }, "")), collapse = ","), "}")
  }
  body <- list(training_frame = training_frame$frame_id,
               hyper_parameters = .json_obj(hyper_params))
  if (!is.null(y)) body$response_column <- y
  if (!is.null(search_criteria))
    body$search_criteria <- .json_obj(search_criteria)
  extra <- list(...)
  for (k in names(extra)) body[[k]] <- extra[[k]]
  out <- .http("POST", paste0("/99/Grid/", algorithm), body)
  job <- .poll_job(out$job$key$name)
  grid_id <- job$dest$name
  g <- .http("GET", paste0("/99/Grids/", grid_id))
  structure(list(grid_id = grid_id,
                 model_ids = vapply(g$model_ids, function(m) m$name, "")),
            class = "H2OGrid")
}

h2o.getGrid <- function(grid_id) {
  g <- .http("GET", paste0("/99/Grids/", grid_id))
  structure(list(grid_id = grid_id,
                 model_ids = vapply(g$model_ids, function(m) m$name, "")),
            class = "H2OGrid")
}

# -- model persistence (reference: h2o-r h2o.saveModel/h2o.loadModel) --------

h2o.saveModel <- function(object, path) {
  out <- .http("GET", paste0("/99/Models.bin/", object$model_id,
                             "?dir=", utils::URLencode(path, reserved = TRUE)))
  out$dir
}

h2o.loadModel <- function(path) {
  out <- .http("POST", "/99/Models.bin/", list(dir = path))
  h2o.getModel(out$models[[1]]$model_id$name)
}

h2o.predict <- function(object, newdata) {
  out <- .http("POST", paste0("/3/Predictions/models/", object$model_id,
                              "/frames/", newdata$frame_id))
  h2o.getFrame(out$predictions_frame$name)
}

# -- batched request-sized scoring (server /3/Score; docs/SERVING.md) --------

.json_write <- function(x) {
  # minimal JSON writer (the package is dependency-free; see .json_parse):
  # named list -> object, unnamed list / length>1 vector -> array
  if (is.factor(x)) x <- as.character(x)   # enum columns arrive as factors
  if (is.null(x) || (length(x) == 1 && is.na(x))) return("null")
  if (is.list(x)) {
    nm <- names(x)
    if (!is.null(nm) && all(nzchar(nm))) {
      return(paste0("{", paste0(
        vapply(nm, function(k) paste0('"', .json_escape(k), '":',
                                      .json_write(x[[k]])), character(1)),
        collapse = ","), "}"))
    }
    return(paste0("[", paste0(
      vapply(x, .json_write, character(1)), collapse = ","), "]"))
  }
  if (length(x) > 1) {
    return(paste0("[", paste0(
      vapply(x, .json_write, character(1)), collapse = ","), "]"))
  }
  if (is.character(x)) return(paste0('"', .json_escape(x), '"'))
  if (is.logical(x)) return(if (x) "true" else "false")
  as.character(x)
}

h2o.score <- function(object, rows, columns = NULL, priority = NULL,
                      slo_ms = NULL) {
  # request-sized scoring through the compiled, batched serving tier:
  # `rows` is a data.frame or a list of named lists; no DKV frame
  # round-trip. `priority` (0-9, default 5) orders shedding under
  # overload (low priority is turned away first with 503+Retry-After);
  # `slo_ms` overrides the model's latency target at admit. Returns the
  # ScoreV3 payload (predictions column lists + the batch shape the
  # request rode in + the serving replica when a pool is routing).
  model_id <- if (is.list(object) && !is.null(object$model_id)) object$model_id else object
  if (is.data.frame(rows)) {
    columns <- names(rows)
    rows <- lapply(seq_len(nrow(rows)), function(i) {
      r <- as.list(rows[i, , drop = FALSE])
      stats::setNames(r, columns)
    })
  }
  body <- list(rows = .json_write(rows))
  if (!is.null(columns)) body$columns <- .json_write(as.character(columns))
  if (!is.null(priority)) body$priority <- as.integer(priority)
  if (!is.null(slo_ms)) body$slo_ms <- as.numeric(slo_ms)
  .http("POST", paste0("/3/Score/", model_id), body)
}

h2o.serving <- function() {
  # scoring-tier state (GET /3/Score): residency + compiled-scorer cache
  # counters, per-model SLO controller state (target/window/p50/p99),
  # shed accounting by reason/priority, and the replica-pool view
  # (slice leases, per-replica busy/queue-wait, scale events)
  .http("GET", "/3/Score")
}

h2o.performance <- function(model, newdata = NULL) {
  if (is.null(newdata)) {
    mm <- model$json$output$training_metrics
  } else {
    out <- .http("POST", paste0("/3/ModelMetrics/models/", model$model_id,
                                "/frames/", newdata$frame_id))
    mm <- out$model_metrics[[1]]
  }
  structure(mm, class = "H2OModelMetrics")
}

h2o.auc <- function(perf) perf$auc
h2o.rmse <- function(perf) perf$rmse
h2o.logloss <- function(perf) perf$logloss

# -- distributed tracing (server /3/Traces*; docs/OBSERVABILITY.md) ----------

h2o.traces <- function() {
  # completed-trace summaries, newest first (trace_id/name/dur_ns/status)
  .http("GET", "/3/Traces")$traces
}

h2o.trace <- function(trace_id) {
  # full span tree + computed critical path for one trace
  .http("GET", paste0("/3/Traces/", trace_id))
}

h2o.traceExport <- function(trace_id) {
  # Chrome trace-event JSON (as a parsed list); the Python client or a
  # plain curl of /3/Traces/{id}/export writes the file Perfetto loads
  .http("GET", paste0("/3/Traces/", trace_id, "/export"))
}

# -- memory/thread observability (server /3/Memory, /3/JStack, /3/Profiler;
#    docs/OBSERVABILITY.md "Memory") ----------------------------------------

h2o.memory <- function(top = 10) {
  # device/host byte accounting: host RSS, per-device HBM stats, DKV bytes
  # by kind with the top-N keys, watermarks, and the leak-detector report
  .http("GET", paste0("/3/Memory?top=", as.integer(top)))
}

h2o.job <- function(job_key) {
  # one job's JobV3: status/progress plus the reliability surface —
  # retries (dispatch retries absorbed), max_runtime_secs/deadline_exceeded
  # (deadline budget), auto_recoverable/auto_recovery_dir (crash-resume
  # snapshot state; docs/RELIABILITY.md)
  .http("GET", paste0("/3/Jobs/", job_key))$jobs[[1]]
}

h2o.jstack <- function() {
  # all server thread stacks (reference: h2o-r h2o.killMinus3 analog reads)
  .http("GET", "/3/JStack")$traces
}

# -- compute observatory (server /3/Compute, /3/Profiler/capture;
#    docs/OBSERVABILITY.md "Compute") ----------------------------------------

h2o.compute <- function() {
  # XLA cost accounting: per-site compiled signatures, compile seconds,
  # cost_analysis FLOPs/bytes, recompile events with signature diffs, and
  # per-loop achieved FLOP/s + utilization (NULL on backends outside the
  # peak table, e.g. CPU)
  .http("GET", "/3/Compute")
}

h2o.profilerCapture <- function(duration_ms = 500) {
  # bounded jax.profiler.trace window with span-derived annotations;
  # returns the capture record — fetch the Perfetto artifact via
  # GET /3/Profiler/captures/{capture_id}/download (a plain curl works).
  # A concurrent capture gets a structured 409.
  .http("POST", paste0("/3/Profiler/capture?duration_ms=",
                       as.integer(duration_ms)))
}

h2o.profilerCaptures <- function() {
  # registry of recent captures, oldest first
  .http("GET", "/3/Profiler/captures")$captures
}

h2o.profiler <- function(depth = 5) {
  # sampled stack profile, hottest-first (reference ProfilerHandler)
  .http("GET", paste0("/3/Profiler?depth=", as.integer(depth)))
}

# -- ops plane (server /3/Health, /3/Incidents, /3/Diagnostics/bundle;
#    docs/OBSERVABILITY.md "Health & incidents") ------------------------------

h2o.health <- function() {
  # subsystem-scored verdict (healthy/degraded/unhealthy per subsystem:
  # elastic/serving/memory/compute/dispatch); every finding carries the
  # tripping rule, the observed value, and the threshold
  .http("GET", "/3/Health")
}

h2o.incidents <- function(state = NULL) {
  # bounded incident ring, newest first (one open incident per rule);
  # state = "open"|"resolved" filters; fetch one with h2o.incident(id)
  # for its trip-time context
  path <- "/3/Incidents"
  if (!is.null(state))
    path <- paste0(path, "?state=", URLencode(state, reserved = TRUE))
  .http("GET", path)$incidents
}

h2o.incident <- function(incident_id) {
  # one incident with correlated context captured at trip time: trace
  # ids, log tail, memory top-keys, compute loop rows, observed series
  .http("GET", paste0("/3/Incidents/", incident_id))
}

h2o.timeseries <- function(name = NULL, labels = NULL, since = NULL) {
  # the flight recorder's retained metric series (GET /3/TimeSeries):
  # per series the raw [t, value] tail and min/max/mean/last rollup
  # windows, plus recorder stats; `name` matches exactly or as a
  # prefix, `labels` is a named list matched as a subset, `since` is
  # epoch seconds (docs/OBSERVABILITY.md "Flight recorder & post-mortems")
  q <- c()
  if (!is.null(name))
    q <- c(q, paste0("name=", URLencode(name, reserved = TRUE)))
  if (!is.null(labels)) {
    ks <- sort(names(labels))
    pairs <- paste0(ks, "=", unlist(labels[ks]), collapse = ",")
    q <- c(q, paste0("labels=", URLencode(pairs, reserved = TRUE)))
  }
  if (!is.null(since))
    q <- c(q, paste0("since=", as.numeric(since)))
  path <- "/3/TimeSeries"
  if (length(q)) path <- paste0(path, "?", paste(q, collapse = "&"))
  .http("GET", path)
}

h2o.ops <- function() {
  # the self-driving ops surface: remediation policy (mode/cooldown/
  # bounds), the append-only ActionLog (newest first, rollback tokens),
  # and per-tenant quota usage (docs/OPERATIONS.md)
  .http("GET", "/3/Ops")
}

h2o.setQuota <- function(tenant, qps = NULL, device_seconds = NULL,
                         bytes = NULL) {
  # install/update a tenant admission budget; over-quota requests shed
  # with HTTP 429 + Retry-After, never silently dropped
  body <- list(tenant = tenant)
  if (!is.null(qps)) body$qps <- qps
  if (!is.null(device_seconds)) body$device_seconds <- device_seconds
  if (!is.null(bytes)) body$bytes <- bytes
  .http("POST", "/3/Ops", body)
}

h2o.removeQuota <- function(tenant) {
  # drop a tenant's budget (back to unlimited admission)
  .http("POST", "/3/Ops", list(remove_quota = tenant))$removed
}

h2o.diagnosticsBundle <- function(path) {
  # the `h2o logs download` analog: one gzip tar of all four pillar
  # snapshots + health verdict + incident ring + logs + hardware
  # fingerprint + secrets-redacted config dump, saved to `path`
  # (the route serves GET for plain downloaders like this one, and POST
  # for API symmetry with the Python client)
  host <- .h2o3tpu$host
  if (is.null(host)) stop("not connected: call h2o.init()/h2o.connect() first")
  url <- paste0("http://", host, ":", .h2o3tpu$port, "/3/Diagnostics/bundle")
  utils::download.file(url, destfile = path, mode = "wb", quiet = TRUE)
  invisible(path)
}

h2o.shutdown <- function(prompt = FALSE) {
  invisible(tryCatch(.http("POST", "/3/Shutdown"), error = function(e) NULL))
}
