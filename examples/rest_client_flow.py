"""Drive a running server over REST with the stdlib client — the same
endpoints h2o-py uses.

    python -m h2o3_tpu.api.server &          # on the server host
    JAX_PLATFORMS=cpu python examples/rest_client_flow.py http://host:54321
"""
import os

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the TPU image sitecustomize force-registers the axon backend; honor
    # an explicit CPU request the same way tests/conftest.py does
    import jax
    jax.config.update("jax_platforms", "cpu")

import sys

import numpy as np

from h2o3_tpu.api import H2OClient, H2OServer


def main(url: str | None):
    server = None
    if url is None:                 # self-contained demo: embed a server
        server = H2OServer(port=0).start()
        url = server.url
    c = H2OClient(url)
    print("cloud:", c.cloud_status()["cloud_name"])

    import os
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".csv", delete=False) as f:
        rng = np.random.default_rng(2)
        f.write("a,b,y\n")
        for i in range(500):
            a, b = rng.normal(), rng.normal()
            f.write(f"{a},{b},{'t' if a + b > 0 else 'f'}\n")
        path = f.name
    try:
        # upload_file ships the CLIENT-LOCAL csv through POST /3/PostFile,
        # so this works against a remote server too (import_file would
        # resolve the path on the SERVER's filesystem)
        key = c.upload_file(path)
        model = c.train("gbm", key, y="y", ntrees=10, max_depth=3)
        mm = model["output"]["training_metrics"]
        print("trained", model["model_id"]["name"], "auc:",
              round(mm["auc"], 4))
        pred_key = c.predict(model["model_id"]["name"], key)
        print("prediction frame:", pred_key)
    finally:
        os.unlink(path)
        if server is not None:
            server.stop()


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
