"""Migrating from H2O-3: load existing MOJO artifacts directly.

    JAX_PLATFORMS=cpu python examples/migrate_from_h2o3.py

A user arriving from the reference framework brings ``.zip`` MOJOs exported
by ``model.download_mojo()``. ``h2o.import_mojo`` reads them natively — GBM
and DRF tree bytecode, GLM, K-means, IsolationForest (+Extended),
StackedEnsemble archives with nested submodels, DeepLearning, PCA, GLRM,
CoxPH, Word2Vec, RuleFit, TargetEncoder, Isotonic, and XGBoost (the
embedded boosterBytes parsed natively) — so existing models score here
unchanged while retraining moves to the TPU-native builders.
"""
import os

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import h2o3_tpu as h2o

FIXTURES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "data", "ref_mojo")


def main():
    # a REAL H2O-3 artifact: 50-tree bernoulli GBM trained on prostate
    model = h2o.import_mojo(os.path.join(FIXTURES,
                                         "gbm_variable_importance.zip"))
    print("imported:", model.output["source_algo"],
          "response:", model.response_column)

    fr = h2o.import_file(os.path.join(FIXTURES, "prostate.csv"))
    preds = model.predict(fr)
    print("scored", preds.nrows, "rows; columns:", preds.names)

    perf = model.model_performance(fr)
    print(f"AUC {float(perf.auc):.4f}  logloss {float(perf.logloss):.4f} "
          "(matches the metrics stored inside the artifact)")

    # nested ensembles work the same way
    ens = h2o.import_mojo(os.path.join(FIXTURES, "ensemble_binomial.zip"))
    print("ensemble:", ens.output["source_algo"],
          "bases:", [b.algo for b in ens.output["mojo"].base_models])

    # XGBoost MOJOs too: the xgboost binary model inside is parsed
    # natively (no xgboost install), reproducing the artifact's own
    # stored training MSE on its training data
    xgb = h2o.import_mojo(os.path.join(FIXTURES, "xgboost_prostate_age.zip"))
    xp = xgb.predict(fr).vec("predict").to_numpy()[: fr.nrows]
    age = fr.vec("AGE").to_numpy()[: fr.nrows]
    print(f"xgboost MOJO: train MSE {((xp - age) ** 2).mean():.6f} "
          "(artifact stores 3.323258)")


if __name__ == "__main__":
    main()
