"""Train, evaluate, and export a GBM end-to-end (the h2o-samples analog).

    JAX_PLATFORMS=cpu python examples/quickstart_gbm.py
"""
import os

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the TPU image sitecustomize force-registers the axon backend; honor
    # an explicit CPU request the same way tests/conftest.py does
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import h2o3_tpu as h2o
from h2o3_tpu.models import GBM


def main():
    rng = np.random.default_rng(7)
    n = 5_000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    city = rng.choice(["sfo", "nyc", "chi"], size=n).astype(object)
    logit = 1.2 * X[:, 0] - X[:, 1] + (city == "sfo")
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    fr = h2o.Frame.from_arrays(
        {"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2], "x3": X[:, 3],
         "city": city, "y": y.astype(object)})
    tr, te = fr.split_frame([0.8], seed=1)

    model = GBM(ntrees=50, max_depth=5, stopping_rounds=3, seed=1).train(
        y="y", training_frame=tr, validation_frame=te)
    mm = model.model_performance(te)
    print("holdout AUC:", round(mm.auc, 4), "logloss:", round(mm.logloss, 4))
    cols, rows = model.scoring_history
    print("scoring history rows:", len(rows))

    model.download_mojo("/tmp/quickstart.mojo")
    from h2o3_tpu.genmodel.mojo import MojoModel
    offline = MojoModel.load("/tmp/quickstart.mojo")
    p = offline.predict(te)
    print("offline predictions:", p.nrows, "rows;",
          "first:", p.vec("predict").labels()[0])


if __name__ == "__main__":
    main()
