"""AutoML leaderboard + stacked ensembles in a few lines.

    JAX_PLATFORMS=cpu python examples/automl_leaderboard.py
"""
import os

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the TPU image sitecustomize force-registers the axon backend; honor
    # an explicit CPU request the same way tests/conftest.py does
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import h2o3_tpu as h2o
from h2o3_tpu.orchestration import AutoML


def main():
    rng = np.random.default_rng(1)
    n = 2_000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    logit = X[:, 0] - 0.5 * X[:, 1] + 0.25 * X[:, 2] ** 2
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "pos", "neg")
    fr = h2o.Frame.from_arrays(
        {**{f"x{i}": X[:, i] for i in range(5)}, "y": y.astype(object)})

    aml = AutoML(max_models=4, nfolds=3, seed=1)
    aml.train(y="y", training_frame=fr)
    for row in aml.leaderboard.table()[1]:
        print(row[0], "auc=", row[1])
    print("leader:", aml.leaderboard.leader.key)


if __name__ == "__main__":
    main()
