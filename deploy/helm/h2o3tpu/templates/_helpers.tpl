{{- define "h2o3tpu.fullname" -}}
{{- .Release.Name | trunc 52 | trimSuffix "-" -}}-h2o3tpu
{{- end -}}
{{- define "h2o3tpu.labels" -}}
app.kubernetes.io/name: h2o3tpu
app.kubernetes.io/instance: {{ .Release.Name }}
{{- end -}}
