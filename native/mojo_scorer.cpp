// Standalone MOJO v2 scorer — the cross-runtime proof for the artifact
// format (reference: h2o-genmodel's Java MojoModel runtime,
// hex/genmodel/ModelMojoReader.java — any runtime can score a MOJO without
// the training system). This binary reads an h2o3_tpu MOJO (zip of
// model.ini + structure.json + arrays.npz, see h2o3_tpu/genmodel/mojo.py)
// and scores a CSV with NO Python/JAX — only libc + zlib.
//
//   g++ -O2 -std=c++17 mojo_scorer.cpp -lz -o mojo_score
//   ./mojo_score model.mojo data.csv        # one prediction line per row
//
// Supported model families: GBM and DRF (regression, bernoulli,
// multinomial), including categorical group splits (left_mask bins,
// reference DHistogram enum subsets) and NA routing. Raw string
// categoricals in the CSV are mapped through the artifact's feat_domains.
// Mirrors h2o3_tpu/models/tree.py:_predict_raw_impl/_predict_raw_masked and
// cat_bins_for_codes exactly; parity pinned by tests/test_mojo_native.py.

#include <zlib.h>

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

// ---------------------------------------------------------------------- zip

static std::vector<uint8_t> read_file(const std::string &path) {
    std::ifstream f(path, std::ios::binary);
    if (!f) throw std::runtime_error("cannot open " + path);
    return std::vector<uint8_t>(std::istreambuf_iterator<char>(f), {});
}

static uint32_t rd32(const uint8_t *p) {
    return p[0] | (p[1] << 8) | (p[2] << 16) | ((uint32_t)p[3] << 24);
}
static uint16_t rd16(const uint8_t *p) { return p[0] | (p[1] << 8); }

static std::vector<uint8_t> inflate_raw(const uint8_t *src, size_t n,
                                        size_t out_n) {
    std::vector<uint8_t> out(out_n);
    z_stream zs{};
    if (inflateInit2(&zs, -MAX_WBITS) != Z_OK)
        throw std::runtime_error("inflateInit2 failed");
    zs.next_in = const_cast<Bytef *>(src);
    zs.avail_in = (uInt)n;
    zs.next_out = out.data();
    zs.avail_out = (uInt)out_n;
    int rc = inflate(&zs, Z_FINISH);
    inflateEnd(&zs);
    if (rc != Z_STREAM_END) throw std::runtime_error("inflate failed");
    return out;
}

// name -> uncompressed bytes, for every entry in the zip
static std::map<std::string, std::vector<uint8_t>> read_zip(
        const std::vector<uint8_t> &buf) {
    // end-of-central-directory: scan back for PK\x05\x06
    if (buf.size() < 22) throw std::runtime_error("not a zip");
    size_t eocd = std::string::npos;
    for (size_t i = buf.size() - 22;; --i) {
        if (buf[i] == 'P' && buf[i + 1] == 'K' && buf[i + 2] == 5 &&
            buf[i + 3] == 6) { eocd = i; break; }
        if (i == 0) break;
    }
    if (eocd == std::string::npos) throw std::runtime_error("not a zip");
    uint16_t count = rd16(&buf[eocd + 10]);
    uint32_t cd_off = rd32(&buf[eocd + 16]);
    std::map<std::string, std::vector<uint8_t>> out;
    size_t p = cd_off;
    for (int e = 0; e < count; ++e) {
        if (rd32(&buf[p]) != 0x02014b50)
            throw std::runtime_error("bad central directory");
        uint16_t method = rd16(&buf[p + 10]);
        uint32_t csize = rd32(&buf[p + 20]), usize = rd32(&buf[p + 24]);
        uint16_t nlen = rd16(&buf[p + 28]), xlen = rd16(&buf[p + 30]),
                 clen = rd16(&buf[p + 32]);
        uint32_t lho = rd32(&buf[p + 42]);
        std::string name((const char *)&buf[p + 46], nlen);
        // local header: its name/extra lengths differ from the CD's
        uint16_t lnlen = rd16(&buf[lho + 26]), lxlen = rd16(&buf[lho + 28]);
        const uint8_t *data = &buf[lho + 30 + lnlen + lxlen];
        if (method == 0)
            out[name] = std::vector<uint8_t>(data, data + usize);
        else if (method == 8)
            out[name] = inflate_raw(data, csize, usize);
        else
            throw std::runtime_error("unsupported zip method");
        p += 46 + nlen + xlen + clen;
    }
    return out;
}

// ---------------------------------------------------------------------- npy

struct Arr {
    std::vector<double> data;     // everything promoted to double
    std::vector<int64_t> shape;
    int64_t size() const {
        int64_t s = 1;
        for (auto d : shape) s *= d;
        return s;
    }
};

static Arr parse_npy(const std::vector<uint8_t> &b) {
    if (b.size() < 10 || memcmp(b.data(), "\x93NUMPY", 6) != 0)
        throw std::runtime_error("bad npy magic");
    int major = b[6];
    size_t hlen, hoff;
    if (major == 1) { hlen = rd16(&b[8]); hoff = 10; }
    else { hlen = rd32(&b[8]); hoff = 12; }
    std::string hdr((const char *)&b[hoff], hlen);
    auto get = [&](const std::string &key) {
        size_t k = hdr.find("'" + key + "'");
        if (k == std::string::npos) throw std::runtime_error("npy header");
        return k + key.size() + 2;
    };
    // descr
    size_t dp = hdr.find('\'', get("descr"));
    std::string descr = hdr.substr(dp + 1, hdr.find('\'', dp + 1) - dp - 1);
    size_t fv = hdr.find_first_not_of(": ", get("fortran_order"));
    bool fortran = hdr.compare(fv, 4, "True") == 0;
    if (fortran) throw std::runtime_error("fortran order unsupported");
    // shape tuple
    size_t sp = hdr.find('(', get("shape"));
    size_t se = hdr.find(')', sp);
    Arr a;
    {
        std::string s = hdr.substr(sp + 1, se - sp - 1);
        const char *c = s.c_str();
        while (*c) {
            char *end;
            long v = strtol(c, &end, 10);
            if (end == c) break;
            a.shape.push_back(v);
            c = end;
            while (*c == ',' || *c == ' ') ++c;
        }
        if (a.shape.empty()) a.shape.push_back(1);   // 0-d scalar
    }
    const uint8_t *d = &b[hoff + hlen];
    int64_t n = a.size();
    a.data.resize(n);
    auto load = [&](auto conv, size_t w) {
        for (int64_t i = 0; i < n; ++i) a.data[i] = conv(d + i * w);
    };
    if (descr == "<f4")
        load([](const uint8_t *p) { float v; memcpy(&v, p, 4); return (double)v; }, 4);
    else if (descr == "<f8")
        load([](const uint8_t *p) { double v; memcpy(&v, p, 8); return v; }, 8);
    else if (descr == "<i4")
        load([](const uint8_t *p) { int32_t v; memcpy(&v, p, 4); return (double)v; }, 4);
    else if (descr == "<i8")
        load([](const uint8_t *p) { int64_t v; memcpy(&v, p, 8); return (double)v; }, 8);
    else if (descr == "<i2")
        load([](const uint8_t *p) { int16_t v; memcpy(&v, p, 2); return (double)v; }, 2);
    else if (descr == "|b1" || descr == "|u1")
        load([](const uint8_t *p) { return (double)*p; }, 1);
    else
        throw std::runtime_error("unsupported npy dtype " + descr);
    return a;
}

// --------------------------------------------------------------------- json

struct JNode {
    enum Kind { NUL, BOOL, NUM, STR, ARR, OBJ } kind = NUL;
    bool b = false;
    double num = 0;
    std::string str;
    std::vector<JNode> arr;
    std::map<std::string, JNode> obj;
    const JNode *get(const std::string &k) const {
        auto it = obj.find(k);
        return it == obj.end() ? nullptr : &it->second;
    }
};

struct JParser {
    const char *p, *end;
    explicit JParser(const std::string &s) : p(s.data()), end(s.data() + s.size()) {}
    void ws() { while (p < end && (*p == ' ' || *p == '\n' || *p == '\t' || *p == '\r')) ++p; }
    JNode parse() { ws(); return value(); }
    JNode value() {
        ws();
        if (*p == '{') return object();
        if (*p == '[') return array();
        if (*p == '"') { JNode n; n.kind = JNode::STR; n.str = string(); return n; }
        if (!strncmp(p, "null", 4)) { p += 4; return JNode{}; }
        if (!strncmp(p, "true", 4)) { p += 4; JNode n; n.kind = JNode::BOOL; n.b = true; return n; }
        if (!strncmp(p, "false", 5)) { p += 5; JNode n; n.kind = JNode::BOOL; return n; }
        if (!strncmp(p, "NaN", 3)) { p += 3; JNode n; n.kind = JNode::NUM; n.num = NAN; return n; }
        if (!strncmp(p, "Infinity", 8)) { p += 8; JNode n; n.kind = JNode::NUM; n.num = INFINITY; return n; }
        if (!strncmp(p, "-Infinity", 9)) { p += 9; JNode n; n.kind = JNode::NUM; n.num = -INFINITY; return n; }
        char *e;
        JNode n; n.kind = JNode::NUM; n.num = strtod(p, &e);
        if (e == p) throw std::runtime_error("json parse error");
        p = e;
        return n;
    }
    std::string string() {
        std::string out;
        ++p;                       // opening quote
        while (p < end && *p != '"') {
            if (*p == '\\') {
                ++p;
                switch (*p) {
                    case 'n': out += '\n'; break;
                    case 't': out += '\t'; break;
                    case 'r': out += '\r'; break;
                    case 'b': out += '\b'; break;
                    case 'f': out += '\f'; break;
                    case 'u': {      // BMP only — enough for column names
                        unsigned cp = strtoul(std::string(p + 1, p + 5).c_str(), nullptr, 16);
                        if (cp < 0x80) out += (char)cp;
                        else if (cp < 0x800) {
                            out += (char)(0xC0 | (cp >> 6));
                            out += (char)(0x80 | (cp & 0x3F));
                        } else {
                            out += (char)(0xE0 | (cp >> 12));
                            out += (char)(0x80 | ((cp >> 6) & 0x3F));
                            out += (char)(0x80 | (cp & 0x3F));
                        }
                        p += 4;
                        break;
                    }
                    default: out += *p;
                }
                ++p;
            } else out += *p++;
        }
        ++p;                       // closing quote
        return out;
    }
    JNode array() {
        JNode n; n.kind = JNode::ARR;
        ++p; ws();
        if (*p == ']') { ++p; return n; }
        while (true) {
            n.arr.push_back(value());
            ws();
            if (*p == ',') { ++p; continue; }
            if (*p == ']') { ++p; break; }
            throw std::runtime_error("json array");
        }
        return n;
    }
    JNode object() {
        JNode n; n.kind = JNode::OBJ;
        ++p; ws();
        if (*p == '}') { ++p; return n; }
        while (true) {
            ws();
            std::string k = string();
            ws();
            if (*p != ':') throw std::runtime_error("json object");
            ++p;
            n.obj[k] = value();
            ws();
            if (*p == ',') { ++p; continue; }
            if (*p == '}') { ++p; break; }
            throw std::runtime_error("json object");
        }
        return n;
    }
};

// -------------------------------------------------------------------- model

struct Tree {
    Arr feat, tv, na_left, is_split, leaf;
    Arr left_mask;                 // optional [heap, B]; empty when absent
    bool has_mask = false;
};

struct Mojo {
    std::string algo, distribution, custom_link;
    double f0 = 0, learn_rate = 1;
    std::vector<double> f0_multi;
    std::vector<Tree> trees;                        // single-output
    std::vector<std::vector<Tree>> trees_multi;     // [K][ntrees]
    std::vector<std::string> x_cols, response_domain;
    std::map<std::string, std::vector<std::string>> feat_domains;
    std::vector<double> cat_card;                   // per feature, 0 = numeric
    int cat_bins = 0, ntrees = 0, nclasses = 1;
    bool drf = false, binomial = false;
};

static const Arr &resolve(const JNode *n,
                          const std::map<std::string, Arr> &arrays) {
    const JNode *a = n->get("$a");
    auto it = arrays.find(a->str);
    if (it == arrays.end()) throw std::runtime_error("missing array " + a->str);
    return it->second;
}

static Tree decode_tree(const JNode &t, const std::map<std::string, Arr> &arrays) {
    const JNode *spec = t.get("$tree");
    Tree out;
    out.feat = resolve(spec->get("feat"), arrays);
    out.tv = resolve(spec->get("thresh_val"), arrays);
    out.na_left = resolve(spec->get("na_left"), arrays);
    out.is_split = resolve(spec->get("is_split"), arrays);
    out.leaf = resolve(spec->get("leaf"), arrays);
    const JNode *lm = spec->get("left_mask");
    if (lm && lm->kind == JNode::OBJ && lm->get("$a")) {
        out.left_mask = resolve(lm, arrays);
        out.has_mask = true;
    }
    return out;
}

static std::vector<std::string> decode_strlist(const JNode *n) {
    const JNode *items = n;
    if (n->kind == JNode::OBJ && n->get("$t")) items = n->get("$t");
    std::vector<std::string> out;
    for (auto &v : items->arr) out.push_back(v.str);
    return out;
}

static Mojo load_mojo(const std::string &path) {
    auto zip = read_zip(read_file(path));
    // arrays.npz is itself a zip of .npy members
    auto npz = read_zip(zip.at("arrays.npz"));
    std::map<std::string, Arr> arrays;
    for (auto &kv : npz) {
        std::string name = kv.first;
        if (name.size() > 4 && name.substr(name.size() - 4) == ".npy")
            name = name.substr(0, name.size() - 4);
        arrays[name] = parse_npy(kv.second);
    }
    std::string sj((const char *)zip.at("structure.json").data(),
                   zip.at("structure.json").size());
    JNode root = JParser(sj).parse();

    Mojo m;
    m.algo = root.get("algo")->str;
    m.drf = m.algo == "drf";
    const JNode *out = root.get("output")->get("$d");
    auto num = [&](const char *k, double dflt) {
        const JNode *n = out->get(k);
        if (!n) return dflt;
        if (n->kind == JNode::NUM) return n->num;
        if (n->kind == JNode::OBJ && n->get("$f"))
            return strtod(n->get("$f")->str.c_str(), nullptr);
        return dflt;
    };
    m.learn_rate = num("learn_rate", 1.0);
    m.f0 = num("f0", 0.0);
    const JNode *dist = out->get("distribution");
    m.distribution = dist ? dist->str : "gaussian";
    const JNode *cl = out->get("custom_link");
    if (cl && cl->kind == JNode::STR) m.custom_link = cl->str;
    m.ntrees = (int)num("ntrees", 0);
    const JNode *bin = out->get("binomial");
    m.binomial = bin && bin->kind == JNode::BOOL && bin->b;
    m.x_cols = decode_strlist(out->get("x_cols"));
    const JNode *fd = out->get("feat_domains");
    if (fd && fd->get("$d"))
        for (auto &kv : fd->get("$d")->obj)
            m.feat_domains[kv.first] = decode_strlist(&kv.second);
    const JNode *cc = out->get("cat_card");
    if (cc && cc->kind == JNode::OBJ && cc->get("$a")) {
        m.cat_card = resolve(cc, arrays).data;
        m.cat_bins = (int)num("cat_bins", 0);
    }
    const JNode *tm = out->get("trees_multi");
    if (tm && tm->kind == JNode::ARR) {
        for (auto &cls : tm->arr) {
            std::vector<Tree> ts;
            for (auto &t : cls.arr) ts.push_back(decode_tree(t, arrays));
            m.trees_multi.push_back(std::move(ts));
            if (!m.ntrees) m.ntrees = (int)m.trees_multi.back().size();
        }
        const JNode *f0m = out->get("f0_multi");
        if (f0m && f0m->get("$a")) m.f0_multi = resolve(f0m, arrays).data;
        else m.f0_multi.assign(m.trees_multi.size(), 0.0);
        m.nclasses = (int)m.trees_multi.size();
    } else {
        for (auto &t : out->get("trees")->arr)
            m.trees.push_back(decode_tree(t, arrays));
        if (!m.ntrees) m.ntrees = (int)m.trees.size();
    }
    const JNode *rd = root.get("response_domain");
    if (rd && (rd->kind == JNode::ARR ||
               (rd->kind == JNode::OBJ && rd->get("$t"))))
        m.response_domain = decode_strlist(rd);
    if (m.response_domain.size() == 2 && m.nclasses == 1) m.nclasses = 2;
    return m;
}

// ---------------------------------------------------------------- traversal

// mirrors tree.py cat_bins_for_codes: identity when cardinality fits,
// contiguous range grouping otherwise
static int cat_bin_for_code(double x, double card, int n_bins) {
    int code = std::isnan(x) ? 0 : (int)x;
    if (card > n_bins) {
        int grouped = (int)((int64_t)code * n_bins / (int64_t)(card < 1 ? 1 : card));
        return grouped < 0 ? 0 : grouped >= n_bins ? n_bins - 1 : grouped;
    }
    return code < 0 ? 0 : code >= n_bins ? n_bins - 1 : code;
}

static double score_tree(const Tree &t, const std::vector<double> &row,
                         const std::vector<double> &cat_card, int cat_bins) {
    int depth = 0;                                 // heap 2^(depth+1)-1
    for (int64_t h = t.feat.size() + 1; h > 2; h /= 2) ++depth;
    int64_t idx = 0;
    for (int d = 0; d < depth; ++d) {
        if (t.is_split.data[idx] == 0) break;
        int f = (int)t.feat.data[idx];
        if (f < 0) f = 0;
        double x = row[f];
        bool left;
        if (std::isnan(x)) {
            left = t.na_left.data[idx] != 0;
        } else if (t.has_mask && !cat_card.empty() && cat_card[f] > 0) {
            int B = (int)(t.left_mask.shape[1]);
            int b = cat_bin_for_code(x, cat_card[f], cat_bins ? cat_bins : B);
            if (b >= B) b = B - 1;
            left = t.left_mask.data[idx * B + b] != 0;
        } else {
            left = x < t.tv.data[idx];
        }
        idx = idx * 2 + (left ? 1 : 2);
    }
    return t.leaf.data[idx];
}

// ---------------------------------------------------------------------- csv

static std::vector<std::string> split_csv_line(const std::string &line) {
    std::vector<std::string> out;
    std::string cur;
    bool q = false;
    for (size_t i = 0; i < line.size(); ++i) {
        char c = line[i];
        if (q) {
            if (c == '"' && i + 1 < line.size() && line[i + 1] == '"') { cur += '"'; ++i; }
            else if (c == '"') q = false;
            else cur += c;
        } else if (c == '"') q = true;
        else if (c == ',') { out.push_back(cur); cur.clear(); }
        else cur += c;
    }
    out.push_back(cur);
    return out;
}

// ---------------------------------------------------------------------- main

int main(int argc, char **argv) {
    if (argc < 3) {
        fprintf(stderr, "usage: %s model.mojo data.csv\n", argv[0]);
        return 2;
    }
    try {
        Mojo m = load_mojo(argv[1]);
        std::ifstream f(argv[2]);
        if (!f) throw std::runtime_error("cannot open csv");
        std::string line;
        std::getline(f, line);
        if (!line.empty() && line.back() == '\r') line.pop_back();
        auto header = split_csv_line(line);
        // column index per model feature
        std::vector<int> colidx(m.x_cols.size(), -1);
        for (size_t j = 0; j < m.x_cols.size(); ++j)
            for (size_t c = 0; c < header.size(); ++c)
                if (header[c] == m.x_cols[j]) { colidx[j] = (int)c; break; }
        for (size_t j = 0; j < m.x_cols.size(); ++j)
            if (colidx[j] < 0)
                throw std::runtime_error("csv lacks column " + m.x_cols[j]);

        while (std::getline(f, line)) {
            if (!line.empty() && line.back() == '\r') line.pop_back();
            if (line.empty()) continue;
            auto cells = split_csv_line(line);
            std::vector<double> row(m.x_cols.size(), NAN);
            for (size_t j = 0; j < m.x_cols.size(); ++j) {
                if ((size_t)colidx[j] >= cells.size()) continue;  // ragged: NA
                const std::string &cell = cells[colidx[j]];
                auto dom = m.feat_domains.find(m.x_cols[j]);
                if (dom != m.feat_domains.end()) {
                    row[j] = NAN;                  // unseen/missing level
                    for (size_t k = 0; k < dom->second.size(); ++k)
                        if (dom->second[k] == cell) { row[j] = (double)k; break; }
                } else if (cell.empty() || cell == "NA" || cell == "nan") {
                    row[j] = NAN;
                } else {
                    char *e;
                    row[j] = strtod(cell.c_str(), &e);
                    if (e == cell.c_str()) row[j] = NAN;
                }
            }
            if (!m.trees_multi.empty()) {          // multinomial
                std::vector<double> margin(m.nclasses);
                for (int k = 0; k < m.nclasses; ++k) {
                    double s = 0;
                    for (auto &t : m.trees_multi[k])
                        s += score_tree(t, row, m.cat_card, m.cat_bins);
                    margin[k] = m.drf ? s / (m.ntrees ? m.ntrees : 1)
                                      : m.f0_multi[k] + m.learn_rate * s;
                }
                std::vector<double> p(m.nclasses);
                double tot = 0;
                if (m.drf) {
                    for (int k = 0; k < m.nclasses; ++k) {
                        p[k] = margin[k] < 0 ? 0 : margin[k] > 1 ? 1 : margin[k];
                        tot += p[k];
                    }
                    for (auto &v : p) v /= tot > 1e-30 ? tot : 1e-30;
                } else {
                    double mx = margin[0];
                    for (double v : margin) mx = std::max(mx, v);
                    for (int k = 0; k < m.nclasses; ++k) {
                        p[k] = std::exp(margin[k] - mx);
                        tot += p[k];
                    }
                    for (auto &v : p) v /= tot;
                }
                int best = 0;
                for (int k = 1; k < m.nclasses; ++k)
                    if (p[k] > p[best]) best = k;
                printf("%s", m.response_domain[best].c_str());
                for (double v : p) printf(",%.9g", v);
                printf("\n");
                continue;
            }
            double s = 0;
            for (auto &t : m.trees)
                s += score_tree(t, row, m.cat_card, m.cat_bins);
            if (m.drf) {
                double mean = s / (m.ntrees ? m.ntrees : 1);
                if (m.binomial) {
                    double p1 = mean < 0 ? 0 : mean > 1 ? 1 : mean;
                    printf("%s,%.9g,%.9g\n",
                           m.response_domain[p1 >= 0.5 ? 1 : 0].c_str(),
                           1 - p1, p1);
                } else {
                    printf("%.9g\n", mean);
                }
                continue;
            }
            double fm = m.f0 + m.learn_rate * s;
            if (m.distribution == "bernoulli") {
                double p1 = 1.0 / (1.0 + std::exp(-fm));
                printf("%s,%.9g,%.9g\n",
                       m.response_domain[p1 >= 0.5 ? 1 : 0].c_str(),
                       1 - p1, p1);
            } else if (m.distribution == "poisson" ||
                       m.distribution == "gamma" ||
                       m.distribution == "tweedie" ||
                       (m.distribution == "custom" && m.custom_link == "log")) {
                printf("%.9g\n", std::exp(fm > 30 ? 30 : fm < -30 ? -30 : fm));
            } else if (m.distribution == "custom" && m.custom_link == "logit") {
                printf("%.9g\n", 1.0 / (1.0 + std::exp(-fm)));
            } else if (m.distribution == "custom" && m.custom_link == "inverse") {
                printf("%.9g\n", 1.0 / (std::fabs(fm) < 1e-30 ? 1e-30 : fm));
            } else {
                printf("%.9g\n", fm);
            }
        }
        return 0;
    } catch (const std::exception &e) {
        fprintf(stderr, "mojo_score: %s\n", e.what());
        return 1;
    }
}
