// Chunk-parallel CSV parser — the native ingest path.
//
// Reference: water/parser/ParseDataset.java:623 (MultiFileParseTask splits the
// input into chunks parsed in parallel, each running the per-byte CSV state
// machine of water/parser/CsvParser.java) and PackedDomains (categorical
// domain merge across chunks). Same architecture here: the buffer splits at
// newline boundaries into one chunk per thread, each thread tokenizes into
// per-chunk column accumulators (double or interned string), and a merge pass
// unifies types and sorts/unions categorical domains. Files containing quotes
// fall back to a single-threaded pass so quoted embedded newlines stay
// correct (the reference re-syncs heuristically; we prefer exactness).
//
// C ABI consumed via ctypes from h2o3_tpu/native/__init__.py.

#include <algorithm>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <map>
#include <unordered_map>
#include <memory>
#include <string>
#include <thread>
#include <vector>

namespace {

struct ChunkCol {
  std::vector<double> nums;        // parsed value or NaN
  std::vector<int32_t> strs;       // index into pool, -1 = NA/none
  std::vector<int64_t> offs;       // token offset into the source buffer
  std::vector<int32_t> lens;       // token length (-1 = NA/quoted)
  std::vector<std::string> pool;   // chunk-local interned strings
  std::unordered_map<std::string, int32_t> pool_idx;
  bool any_str = false;            // saw a non-numeric, non-NA token

  int32_t intern(const std::string& s) {
    auto it = pool_idx.find(s);
    if (it != pool_idx.end()) return it->second;
    int32_t id = (int32_t)pool.size();
    pool.push_back(s);
    pool_idx.emplace(s, id);
    return id;
  }
};

struct Chunk {
  std::vector<ChunkCol> cols;
  int64_t rows = 0;
};

bool is_na_token(const char* b, size_t n) {
  if (n == 0) return true;
  // pandas' default NA string set (so the fast path and the fallback agree)
  static const char* kNA[] = {"NA", "N/A", "n/a", "null", "NULL", "NaN",
                              "nan", "-NaN", "-nan", "None", "<NA>"};
  for (const char* s : kNA) {
    if (strlen(s) == n && memcmp(b, s, n) == 0) return true;
  }
  return false;
}

bool parse_double(const char* b, size_t n, double* out) {
  if (n && *b == '+') { ++b; --n; }   // from_chars rejects a leading '+'
  if (n == 0) return false;
  auto [ptr, ec] = std::from_chars(b, b + n, *out);
  return ec == std::errc() && ptr == b + n;
}

void trim(const char*& b, size_t& n) {
  while (n && (*b == ' ' || *b == '\t' || *b == '\r')) { ++b; --n; }
  while (n && (b[n - 1] == ' ' || b[n - 1] == '\t' || b[n - 1] == '\r')) --n;
}

// per-byte tokenizer for one [begin,end) slab; quote=true handles RFC quoting
// (only used single-threaded, where embedded newlines are safe)
void parse_slab(const char* base, const char* begin, const char* end, char sep,
                bool quotes, int ncols, Chunk* out) {
  out->cols.assign(ncols, ChunkCol());
  const char* p = begin;
  std::string qbuf;
  while (p < end) {
    if (*p == '\n') { ++p; continue; }
    if (*p == '\r' && p + 1 < end && p[1] == '\n') { p += 2; continue; }
    // row extent first (memchr beats a byte loop), then memchr per field —
    // valid only when the file has no quotes (parallel fast path)
    const char* row_end = end;
    if (!quotes) {
      const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
      row_end = nl ? nl : end;
    }
    for (int c = 0; c < ncols; ++c) {
      const char* tok = p;
      size_t n = 0;
      bool quoted = false;
      if (quotes && p < end && *p == '"') {
        quoted = true;
        qbuf.clear();
        ++p;
        while (p < end) {
          if (*p == '"') {
            if (p + 1 < end && p[1] == '"') { qbuf.push_back('"'); p += 2; }
            else { ++p; break; }
          } else qbuf.push_back(*p++);
        }
        tok = qbuf.data();
        n = qbuf.size();
        while (p < end && *p != sep && *p != '\n') ++p;   // junk after quote
      } else if (!quotes) {
        const char* s = (const char*)memchr(p, sep, (size_t)(row_end - p));
        p = s && s < row_end ? s : row_end;
        n = (size_t)(p - tok);
      } else {
        while (p < end && *p != sep && *p != '\n') ++p;
        n = (size_t)(p - tok);
      }
      const char* tb = tok;
      size_t tn = n;
      if (!quoted) trim(tb, tn);
      ChunkCol& col = out->cols[c];
      double v;
      if (!quoted && parse_double(tb, tn, &v)) {
        // numeric — but keep the exact source text reachable in case the
        // merge pass votes this column categorical
        col.nums.push_back(v);
        col.strs.push_back(-1);
        col.offs.push_back(tb - base);
        col.lens.push_back((int32_t)tn);
      } else if (!quoted && is_na_token(tb, tn)) {
        col.nums.push_back(std::numeric_limits<double>::quiet_NaN());
        col.strs.push_back(-1);
        col.offs.push_back(-1);
        col.lens.push_back(-1);
      } else {
        col.nums.push_back(std::numeric_limits<double>::quiet_NaN());
        col.strs.push_back(col.intern(std::string(tb, tn)));
        col.offs.push_back(-1);
        col.lens.push_back(-1);
        col.any_str = true;
      }
      if (p < end && *p == sep && c < ncols - 1) ++p;
    }
    while (p < end && *p != '\n') ++p;   // overflow columns dropped
    if (p < end) ++p;
    ++out->rows;
  }
}

struct Result {
  int64_t nrows = 0;
  int32_t ncols = 0;
  std::vector<std::string> names;
  std::vector<int32_t> types;                    // 0=num, 1=cat
  std::vector<std::vector<double>> data;         // value or level code (-1=NA)
  std::vector<std::vector<std::string>> domains; // per CAT column, sorted
};

}  // namespace

extern "C" {

// Parse a CSV buffer. Returns an opaque handle (nullptr on failure).
void* h2o3_parse_csv(const char* buf, int64_t len, int has_header, char sep,
                     int nthreads) {
  if (len <= 0) return nullptr;
  bool has_quotes = memchr(buf, '"', (size_t)len) != nullptr;

  // header + column count from the first line
  const char* p = buf;
  const char* bend = buf + len;
  const char* eol = (const char*)memchr(p, '\n', (size_t)(bend - p));
  if (!eol) eol = bend;
  // quoted header fields may hide separators — cheaper to let the caller
  // fall back than to special-case header quoting
  if (has_header && memchr(p, '"', (size_t)(eol - p)) != nullptr) return nullptr;
  std::vector<std::string> names;
  {
    const char* q = p;
    while (q <= eol) {
      const char* tok = q;
      while (q < eol && *q != sep) ++q;
      const char* tb = tok; size_t tn = (size_t)(q - tok);
      trim(tb, tn);
      if (tn >= 2 && tb[0] == '"' && tb[tn - 1] == '"') { ++tb; tn -= 2; }
      names.emplace_back(tb, tn);
      if (q >= eol) break;
      ++q;
    }
  }
  int ncols = (int)names.size();
  if (ncols == 0) return nullptr;
  const char* body = has_header ? (eol < bend ? eol + 1 : bend) : p;
  if (!has_header)
    for (int i = 0; i < ncols; ++i) names[i] = "C" + std::to_string(i + 1);

  // chunk boundaries at newlines (reference: file-chunk split)
  int nt = has_quotes ? 1 : std::max(1, nthreads);
  std::vector<const char*> bounds{body};
  int64_t blen = bend - body;
  for (int t = 1; t < nt; ++t) {
    const char* target = body + blen * t / nt;
    const char* nl = (const char*)memchr(target, '\n', (size_t)(bend - target));
    bounds.push_back(nl ? nl + 1 : bend);
  }
  bounds.push_back(bend);
  std::sort(bounds.begin(), bounds.end());

  std::vector<Chunk> chunks(nt);
  std::vector<std::thread> workers;
  for (int t = 0; t < nt; ++t) {
    const char* cb = bounds[t];
    const char* ce = bounds[t + 1];
    workers.emplace_back(parse_slab, buf, cb, ce, sep, has_quotes, ncols,
                         &chunks[t]);
  }
  for (auto& w : workers) w.join();

  // merge: type vote + categorical domain union (reference: PackedDomains)
  auto* res = new Result();
  res->ncols = ncols;
  for (auto& ch : chunks) res->nrows += ch.rows;
  res->names = std::move(names);
  res->types.assign(ncols, 0);
  res->data.resize(ncols);
  res->domains.resize(ncols);
  for (int c = 0; c < ncols; ++c) {
    bool any_str = false;
    for (auto& ch : chunks) any_str |= ch.cols[c].any_str;
    res->types[c] = any_str ? 1 : 0;
    auto& out = res->data[c];
    out.reserve((size_t)res->nrows);
    if (!any_str) {
      for (auto& ch : chunks)
        out.insert(out.end(), ch.cols[c].nums.begin(), ch.cols[c].nums.end());
    } else {
      // numeric tokens inside a categorical column become levels too
      // (reference: the whole column re-parses as enum once any chunk votes
      // string) — levels come from the EXACT source text via stored offsets
      auto raw_tok = [&](const ChunkCol& col, size_t r) {
        return std::string(buf + col.offs[r], (size_t)col.lens[r]);
      };
      std::map<std::string, int32_t> dom;   // sorted (parser contract)
      for (auto& ch : chunks) {
        for (auto& s : ch.cols[c].pool) dom.emplace(s, 0);
        for (size_t r = 0; r < (size_t)ch.rows; ++r)
          if (ch.cols[c].strs[r] < 0 && ch.cols[c].offs[r] >= 0)
            dom.emplace(raw_tok(ch.cols[c], r), 0);
      }
      {
        int32_t id = 0;
        for (auto& kv : dom) kv.second = id++;
      }
      auto& names_out = res->domains[c];
      names_out.reserve(dom.size());
      for (auto& kv : dom) names_out.push_back(kv.first);
      for (auto& ch : chunks) {
        std::vector<int32_t> remap(ch.cols[c].pool.size());
        for (size_t i = 0; i < ch.cols[c].pool.size(); ++i)
          remap[i] = dom[ch.cols[c].pool[i]];
        for (size_t r = 0; r < (size_t)ch.rows; ++r) {
          int32_t s = ch.cols[c].strs[r];
          if (s >= 0) out.push_back(remap[s]);
          else if (ch.cols[c].offs[r] >= 0)
            out.push_back(dom[raw_tok(ch.cols[c], r)]);
          else out.push_back(-1.0);
        }
      }
    }
  }
  return res;
}

int64_t h2o3_nrows(void* h) { return ((Result*)h)->nrows; }
int32_t h2o3_ncols(void* h) { return ((Result*)h)->ncols; }
const char* h2o3_col_name(void* h, int c) { return ((Result*)h)->names[c].c_str(); }
int32_t h2o3_col_type(void* h, int c) { return ((Result*)h)->types[c]; }
const double* h2o3_col_data(void* h, int c) { return ((Result*)h)->data[c].data(); }
int32_t h2o3_col_card(void* h, int c) { return (int32_t)((Result*)h)->domains[c].size(); }
const char* h2o3_col_level(void* h, int c, int i) {
  return ((Result*)h)->domains[c][i].c_str();
}
void h2o3_free(void* h) { delete (Result*)h; }

}  // extern "C"
