"""MOJO pipeline transform runtime (reference:
``h2o-genmodel-extensions/mojo-pipeline/.../transformers/*.java``)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.genmodel.pipeline import MojoPipeline, Transform


@pytest.fixture
def fr():
    return Frame.from_arrays({
        "a": np.float32([1.0, 4.0, 9.0, np.nan]),
        "b": np.float32([2.0, 2.0, 3.0, 4.0]),
        "s": np.array(["  Hello World ", "foo", None, "a b c"], dtype=object),
        "n": np.array(["1.5", "x", "3", None], dtype=object),
    }, types={"s": VecType.STR, "n": VecType.STR})


def test_math_unary_and_binary(fr):
    p = MojoPipeline([
        Transform("math_unary", "sqrt", ["a"], "sq"),
        Transform("math_binary", "*", ["sq", "b"], "prod"),
        Transform("math_binary", "+", ["a"], "plus5",
                  params={"constant": 5.0}),
    ])
    out = p.transform(fr)
    np.testing.assert_allclose(out.vec("sq").to_numpy()[:3], [1, 2, 3])
    np.testing.assert_allclose(out.vec("prod").to_numpy()[:3], [2, 4, 9])
    np.testing.assert_allclose(out.vec("plus5").to_numpy()[:3], [6, 9, 14])
    assert np.isnan(out.vec("sq").to_numpy()[3])


def test_string_transforms(fr):
    p = MojoPipeline([
        Transform("string_unary", "trim", ["s"], "t"),
        Transform("string_unary", "tolower", ["t"], "l"),
        Transform("string_prop", "length", ["l"], "len"),
        Transform("string_grep", "grep", ["s"], "has_o",
                  params={"regex": "o"}),
        Transform("to_numeric", "as.numeric", ["n"], "num"),
    ])
    out = p.transform(fr)
    assert out.vec("l").host_values[0] == "hello world"
    np.testing.assert_allclose(out.vec("len").to_numpy()[:2], [11, 3])
    np.testing.assert_allclose(out.vec("has_o").to_numpy()[[0, 1, 3]],
                               [1, 1, 0])
    got = out.vec("num").to_numpy()
    assert got[0] == pytest.approx(1.5) and got[2] == 3.0
    assert np.isnan(got[1]) and np.isnan(got[3])


def test_string_split(fr):
    p = MojoPipeline([Transform("string_split", "split", ["s"], "w",
                                params={"pattern": r"\s+"})])
    out = p.transform(fr)
    assert out.vec("w.1").host_values[3] == "b"


def test_time_unary():
    ts = np.array(["2024-02-29T13:45:30", "1999-12-31T23:59:59"],
                  dtype="datetime64[ms]")
    fr = Frame.from_arrays({"t": ts}, types={"t": VecType.TIME})
    out = MojoPipeline([Transform("time_unary", "year", ["t"], "yr"),
                        Transform("time_unary", "dayOfWeek", ["t"], "dw"),
                        ]).transform(fr)
    assert out.vec("yr").to_numpy().tolist() == [2024.0, 1999.0]


def test_pipeline_artifact_roundtrip(fr, tmp_path, rng):
    from h2o3_tpu.models.gbm import GBM

    n = 300
    x = rng.normal(size=(n, 2)).astype(np.float32)
    tf = Frame.from_arrays({
        "x0": x[:, 0], "x1": x[:, 1],
        "y": np.where(x[:, 0] * x[:, 0] + x[:, 1] > 1, "t", "f")})
    pre = [Transform("math_unary", "abs", ["x0"], "x0_abs"),
           Transform("math_binary", "*", ["x0", "x0"], "x0_sq")]
    train_fr = MojoPipeline(pre).transform(tf)
    m = GBM(ntrees=4, max_depth=3, seed=2).train(
        y="y", training_frame=train_fr)
    pipe = MojoPipeline(pre, model=m)
    p1 = pipe.predict(tf)

    path = str(tmp_path / "pipe.zip")
    pipe.save(path)
    loaded = MojoPipeline.load(path)
    assert len(loaded.transforms) == 2
    p2 = loaded.predict(tf)
    np.testing.assert_allclose(p2.vec("pt").to_numpy(),
                               p1.vec("pt").to_numpy(), rtol=0, atol=1e-6)


def test_unknown_op_rejected():
    with pytest.raises(ValueError, match="unsupported"):
        Transform("math_unary", "frobnicate", ["a"], "out")


def test_in_place_transform_replaces_column(fr):
    p = MojoPipeline([Transform("math_unary", "sqrt", ["a"], "a")])
    out = p.transform(fr)
    assert out.names.count("a") == 1
    np.testing.assert_allclose(out.vec("a").to_numpy()[:3], [1, 2, 3])
