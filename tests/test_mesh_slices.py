"""Mesh-slice scheduler: concurrent model builds on disjoint device slices
(parallel/mesh.py contextvar binding + slice_meshes, Frame.on_mesh resharded
views, orchestration/scheduler.py MeshScheduler; reference analog: MXNET-MPI
communicator groups — PAPERS.md)."""

import threading
import time

import jax
import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel import mesh as M


def _frame(rng, n=400, key=None):
    x = rng.normal(size=(n, 3)).astype(np.float32)
    return Frame.from_arrays({
        "a": x[:, 0], "b": x[:, 1], "c": x[:, 2],
        "y": np.where(x[:, 0] + x[:, 1] > 0, "t", "f")}, key=key)


# -- slice carving ------------------------------------------------------------

def test_slice_meshes_carves_disjoint_cover():
    g = M.global_mesh()
    ndev = g.shape[M.ROWS]
    assert ndev == 8                      # conftest virtual cloud
    slices = M.slice_meshes(2)
    assert len(slices) == 2
    ids = [set(M.mesh_device_ids(m)) for m in slices]
    assert ids[0].isdisjoint(ids[1])
    assert ids[0] | ids[1] == set(M.mesh_device_ids(g))
    assert all(m.shape[M.ROWS] == 4 for m in slices)


def test_slice_meshes_clamps_to_divisor_and_degrades():
    # 3 does not divide 8 -> largest divisor <= 3 is 2
    assert len(M.slice_meshes(3)) == 2
    # k=1 (and k<=0) = the global mesh itself: today's behavior
    assert M.slice_meshes(1) == [M.global_mesh()]
    assert M.slice_meshes(0) == [M.global_mesh()]
    # oversubscribed: clamped to one device per slice
    assert len(M.slice_meshes(64)) == 8


def test_get_mesh_prefers_bound_slice():
    s0 = M.slice_meshes(2)[0]
    assert M.get_mesh() is M.global_mesh()
    with M.bind_mesh(s0):
        assert M.get_mesh() is s0
        assert M.num_devices() == 4
        # frame padding stays a GLOBAL invariant inside a binding
        from h2o3_tpu.frame.vec import padded_len
        assert padded_len(100) % (8 * 8) == 0
    assert M.get_mesh() is M.global_mesh()


def test_mesh_context_concurrent_threads_no_clobber():
    """The old mesh_context swapped the process-global mesh: interleaved
    exits clobbered each other (last exit won). The contextvar delegate
    isolates per thread — each sees its own mesh, the global never moves."""
    s0, s1 = M.slice_meshes(2)
    g = M.global_mesh()
    inside = threading.Barrier(2, timeout=10)
    seen = {}
    errs = []

    def worker(name, mesh):
        try:
            with M.mesh_context(mesh):
                inside.wait()              # both bindings active at once
                seen[name] = M.get_mesh()
                inside.wait()              # interleave the exits
        except Exception as e:   # noqa: BLE001
            errs.append(e)

    t0 = threading.Thread(target=worker, args=("a", s0))
    t1 = threading.Thread(target=worker, args=("b", s1))
    t0.start(); t1.start(); t0.join(); t1.join()
    assert not errs
    assert seen["a"] is s0 and seen["b"] is s1
    # neither exit clobbered the process-global mesh
    assert M.get_mesh() is g and M.global_mesh() is g


def test_mesh_context_non_divisor_submesh_frame_creation():
    """Public mesh_context with an arbitrary submesh whose size (3) does not
    divide the global padded unit: padded_len widens to the lcm so frame
    creation shards cleanly on the bound mesh AND the result stays divisible
    by the global unit (pre-slice-scheduler behavior, kept working)."""
    from jax.sharding import Mesh

    from h2o3_tpu.frame.vec import padded_len
    sub = Mesh(np.array(jax.devices()[:3]), axis_names=(M.ROWS,))
    with M.mesh_context(sub):
        plen = padded_len(100)
        assert plen % (3 * 8) == 0 and plen % (8 * 8) == 0
        fr = Frame.from_arrays({"a": np.arange(100, dtype=np.float32)})
        assert {d.id for d in fr.vec("a").data.sharding.device_set} == \
            {0, 1, 2}
    np.testing.assert_array_equal(fr.vec("a").to_numpy(),
                                  np.arange(100, dtype=np.float32))


def test_rehome_decides_from_existing_sharding():
    from jax.sharding import NamedSharding, PartitionSpec as P
    g = M.global_mesh()
    s0 = M.slice_meshes(2)[0]
    # already on the target device set: untouched, even though the shape
    # satisfies the old divisibility guess that would have re-sharded it
    rep = jax.device_put(np.zeros((64, 2), np.float32), NamedSharding(g, P()))
    assert M.rehome(rep, g) is rep
    # slice-homed row-sharded array keeps its spec on the global mesh
    rs = jax.device_put(np.zeros(64, np.float32),
                        NamedSharding(s0, P(M.ROWS)))
    out = M.rehome(rs, g)
    assert {d.id for d in out.sharding.device_set} == \
        set(M.mesh_device_ids(g))
    assert out.sharding.spec == P(M.ROWS)
    # slice-homed replicated array stays replicated (never force-sharded)
    small = jax.device_put(np.zeros(3, np.float32), NamedSharding(s0, P()))
    assert M.rehome(small, g).sharding.spec == P()
    # a spec that no longer divides on the target mesh degrades to replicated
    nd = jax.device_put(np.zeros(4, np.float32),
                        NamedSharding(s0, P(M.ROWS)))
    assert M.rehome(nd, g).sharding.spec == P()


def test_rehome_aliased_tuple_gets_the_rebuilt_copy():
    """A tuple referenced from two places is rebuilt ONCE and both
    references get the re-homed copy — the second must not short-circuit
    to the original whose arrays still live on the slice devices."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    g = M.global_mesh()
    s0 = M.slice_meshes(2)[0]
    arr = jax.device_put(np.zeros(64, np.float32),
                         NamedSharding(s0, P(M.ROWS)))
    pair = (arr, arr)
    holder = {"a": pair, "b": pair}
    out = M.rehome(holder, g)
    assert out["a"] is out["b"]
    for ref in (out["a"], out["b"]):
        assert {d.id for d in ref[0].sharding.device_set} == \
            set(M.mesh_device_ids(g))


# -- Frame.on_mesh ------------------------------------------------------------

def test_on_mesh_reshards_batched_and_caches(rng):
    s0, s1 = M.slice_meshes(2)
    fr = _frame(rng)
    v0 = fr.on_mesh(s0)
    assert v0 is not fr
    devs = {d.id for d in v0.vec("a").data.sharding.device_set}
    assert devs == set(M.mesh_device_ids(s0))
    # cat column rides its own int stack; domain/type survive
    assert v0.vec("y").domain == fr.vec("y").domain
    assert v0.types == fr.types
    np.testing.assert_array_equal(v0.vec("a").to_numpy(),
                                  fr.vec("a").to_numpy())
    # cached per (device set, epoch); already-on-mesh returns self
    assert fr.on_mesh(s0) is v0
    assert v0.on_mesh(s0) is v0
    assert fr.on_mesh(M.global_mesh()) is fr
    # a second slice gets its own independent view
    v1 = fr.on_mesh(s1)
    assert {d.id for d in v1.vec("a").data.sharding.device_set} == \
        set(M.mesh_device_ids(s1))


def test_on_mesh_view_invalidated_on_mutation(rng):
    s0 = M.slice_meshes(2)[0]
    fr = _frame(rng)
    v0 = fr.on_mesh(s0)
    from h2o3_tpu.frame.vec import Vec
    fr.add("extra", Vec.from_numpy(np.arange(fr.nrows, dtype=np.float32)))
    v1 = fr.on_mesh(s0)
    assert v1 is not v0
    assert "extra" in v1.names and "extra" not in v0.names
    fr.remove("extra")
    assert fr.on_mesh(s0) is not v1


def test_on_mesh_view_invalidated_on_column_replacement(rng):
    """In-place column replacement (impute / pipeline transforms) goes
    through Frame.replace_vec, which bumps the view epoch — a slice-bound
    build can never reshard a pre-mutation column."""
    from h2o3_tpu.rapids import ops
    s0 = M.slice_meshes(2)[0]
    x = np.array([1.0, np.nan, 3.0, np.nan] * 100, dtype=np.float32)
    fr = Frame.from_arrays({"a": x, "y": np.where(
        np.arange(400) % 2, "t", "f")})
    v0 = fr.on_mesh(s0)
    assert np.isnan(v0.vec("a").to_numpy()).any()
    ops.impute(fr, "a", method="mean")
    v1 = fr.on_mesh(s0)
    assert v1 is not v0
    assert not np.isnan(v1.vec("a").to_numpy()).any()


def test_on_mesh_views_byte_accounted_in_dkv(rng):
    from h2o3_tpu.utils.memory import MEMORY
    from h2o3_tpu.utils.registry import DKV
    s0 = M.slice_meshes(2)[0]
    fr = _frame(rng, key="slice_src")
    DKV.put("slice_src", fr)
    v0 = fr.on_mesh(s0)
    assert v0._is_mesh_view
    vkeys = [k for k in DKV.keys() if k.startswith("slice_src::mesh[")]
    assert len(vkeys) == 1
    # registered bytes equal the view's own accounting (visible in /3/Memory)
    summary = MEMORY.summary(top_n=50)
    row = next(r for r in summary["top_keys"] if r["key"] == vkeys[0])
    assert row["kind"] == "frame" and row["bytes"] == v0.nbytes > 0
    # …but the view is NOT a user frame in the /3/Frames listing
    from h2o3_tpu.api import schemas
    listed = {f["frame_id"]["name"]
              for f in schemas.frames_list_v3(DKV)["frames"]}
    assert "slice_src" in listed and vkeys[0] not in listed
    # structural mutation drops the stale view (and its bytes) from the DKV
    from h2o3_tpu.frame.vec import Vec
    fr.add("extra", Vec.from_numpy(np.arange(fr.nrows, dtype=np.float32)))
    assert vkeys[0] not in DKV
    # an evicted/cleared view is rebuilt transparently on next use
    v1 = fr.on_mesh(s0)
    k1 = [k for k in DKV.keys() if k.startswith("slice_src::mesh[")][0]
    DKV.remove(k1)
    v2 = fr.on_mesh(s0)
    assert v2 is not v1 or v2 is v1  # no crash; fresh view served
    assert {d.id for d in v2.vec("a").data.sharding.device_set} == \
        set(M.mesh_device_ids(s0))


def test_frame_delete_cascades_to_mesh_views(rng):
    """DELETE /3/Frames/{key} (any DKV.remove of a frame) removes its
    registered mesh views too: after the source is gone they are
    unreachable yet would keep full-size device buffers in /3/Memory."""
    from h2o3_tpu.utils.memory import MEMORY
    from h2o3_tpu.utils.registry import DKV
    s0 = M.slice_meshes(2)[0]
    fr = _frame(rng, key="del_src")
    DKV.put("del_src", fr)
    fr.on_mesh(s0)
    vkey = next(k for k in DKV.keys() if k.startswith("del_src::mesh["))
    DKV.remove("del_src")
    assert vkey not in DKV
    assert all(r["key"] != vkey
               for r in MEMORY.summary(top_n=200)["top_keys"])


def test_frame_overwrite_and_spilled_remove_drop_mesh_views(rng):
    """Re-putting a key (replacement frame, spill stub, restore) and
    removing a SPILLED source both orphan the old frame's registered views
    — they must leave the DKV with it, not linger in /3/Memory."""
    from h2o3_tpu.utils.registry import DKV
    s0 = M.slice_meshes(2)[0]
    fr = _frame(rng, key="ovw_src")
    DKV.put("ovw_src", fr)
    fr.on_mesh(s0)
    vkey = next(k for k in DKV.keys() if k.startswith("ovw_src::mesh["))
    DKV.put("ovw_src", _frame(rng, key="ovw_src"))   # replacement frame
    assert vkey not in DKV
    DKV.remove("ovw_src")
    # spilled source: remove() sees the stub, not the Frame
    class SwappedFrame:                      # shape of cleaner's spill stub
        def __init__(self):
            self.path = "/nonexistent/spill"
    fr2 = _frame(rng, key="spill_src")
    DKV.put("spill_src", fr2)
    fr2.on_mesh(s0)
    vkey2 = next(k for k in DKV.keys() if k.startswith("spill_src::mesh["))
    with DKV._lock:                          # spill without put-cascade
        DKV._store["spill_src"] = SwappedFrame()
    assert vkey2 in DKV
    DKV.remove("spill_src")
    assert vkey2 not in DKV


# -- scheduler ---------------------------------------------------------------

def test_scheduler_packs_small_one_per_slice():
    from h2o3_tpu.orchestration.scheduler import MeshScheduler
    sched = MeshScheduler(slices=2)
    assert sched.n == 2
    got = {}
    inside = threading.Barrier(2, timeout=10)

    def worker(name):
        with sched.lease(rows=100, algo="gbm") as lease:
            inside.wait()                # both leases held at once
            got[name] = set(lease.devices)
            inside.wait()

    ts = [threading.Thread(target=worker, args=(n,)) for n in ("a", "b")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert got["a"].isdisjoint(got["b"])
    assert got["a"] | got["b"] == set(M.mesh_device_ids(M.global_mesh()))


def test_scheduler_big_build_takes_full_mesh():
    from h2o3_tpu.orchestration.scheduler import MeshScheduler
    sched = MeshScheduler(slices=2)
    order = []
    small_holding = threading.Event()
    release_small = threading.Event()

    def small():
        with sched.lease(rows=100):
            small_holding.set()
            assert release_small.wait(10)
            order.append("small_done")

    def big():
        with sched.lease(rows=10_000_000) as lease:   # >= threshold
            order.append("big_ran")
            assert lease.index == -1
            assert set(lease.devices) == \
                set(M.mesh_device_ids(M.global_mesh()))

    ts = threading.Thread(target=small)
    tb = threading.Thread(target=big)
    ts.start()
    assert small_holding.wait(10)
    tb.start()
    time.sleep(0.1)                      # big must be BLOCKED on the lease
    assert order == []
    release_small.set()
    ts.join(); tb.join()
    assert order == ["small_done", "big_ran"]


def test_scheduler_degrades_to_overlap_on_one_slice():
    """1 slice = today's behavior: concurrent leases do NOT serialize."""
    from h2o3_tpu.orchestration.scheduler import MeshScheduler
    sched = MeshScheduler(slices=1)
    assert sched.n == 1
    inside = threading.Barrier(3, timeout=10)

    def worker():
        with sched.lease(rows=100):
            inside.wait()                # all three leases held at once

    ts = [threading.Thread(target=worker) for _ in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()                         # barrier passed => no serialization


def test_two_schedulers_same_layout_share_lease_state():
    """Lease state is process-wide per layout: two INDEPENDENT runs (each
    with its own MeshScheduler) can never both hold the same slice."""
    from h2o3_tpu.orchestration.scheduler import MeshScheduler
    s_a, s_b = MeshScheduler(slices=2), MeshScheduler(slices=2)
    assert s_a._state is s_b._state
    got = {}
    inside = threading.Barrier(2, timeout=10)

    def worker(name, sched):
        with sched.lease(rows=100, algo="gbm") as lease:
            inside.wait()                # both leases held at once
            got[name] = set(lease.devices)
            inside.wait()

    ts = [threading.Thread(target=worker, args=("a", s_a)),
          threading.Thread(target=worker, args=("b", s_b))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert got["a"].isdisjoint(got["b"])


def test_cleaner_drops_mesh_views_instead_of_spilling(rng, tmp_path):
    """Under budget pressure a mesh view is REMOVED (it rebuilds from its
    source columns) — never spilled to disk as a SwappedFrame stub that
    would waste a snapshot write and pose as a user frame in /3/Frames."""
    from h2o3_tpu.api import schemas
    from h2o3_tpu.utils.cleaner import Cleaner
    from h2o3_tpu.utils.registry import DKV
    s0 = M.slice_meshes(2)[0]
    fr = _frame(rng, key="spill_src")
    DKV.put("spill_src", fr)
    fr.on_mesh(s0)
    vkey = next(k for k in DKV.keys() if k.startswith("spill_src::mesh["))
    cl = Cleaner(budget_bytes=1, ice_root=str(tmp_path))  # force all out
    cl.touch("spill_src")                        # view is LRU-first
    spilled = cl.sweep(protect="spill_src")
    assert vkey in spilled
    assert vkey not in DKV                       # dropped, not stubbed
    assert not list(tmp_path.iterdir())          # no orphan snapshot
    listed = {f["frame_id"]["name"]
              for f in schemas.frames_list_v3(DKV)["frames"]}
    assert vkey not in listed
    # the view transparently rebuilds on next use
    v2 = fr.on_mesh(s0)
    assert {d.id for d in v2.vec("a").data.sharding.device_set} == \
        set(M.mesh_device_ids(s0))
    DKV.remove("spill_src")


def test_scheduler_env_override(monkeypatch):
    from h2o3_tpu.orchestration.scheduler import MeshScheduler
    monkeypatch.setenv("H2O3TPU_MESH_SLICES", "4")
    sched = MeshScheduler(slices=1)      # env wins over the request
    assert sched.n == 4


# -- the regression the pins guarded against ---------------------------------

def test_concurrent_slice_builds_never_share_a_collective(rng):
    """Two builds at parallelism=2 run on DISJOINT device slices with
    overlapping execution: the span tree shows concurrent mesh_slice spans
    bound to non-intersecting device sets, so no collective of one build
    can rendezvous with the other's."""
    from h2o3_tpu.models.gbm import GBM
    from h2o3_tpu.orchestration.parallel_build import windowed_parallel
    from h2o3_tpu.orchestration.scheduler import MeshScheduler
    from h2o3_tpu.utils.tracing import TRACER

    fr = _frame(rng)
    sched = MeshScheduler(slices=2)

    def build(i):
        return GBM(ntrees=3, max_depth=3, seed=7).train(
            y="y", training_frame=fr)

    with TRACER.span("slice_regression", root=True) as root:
        results, _ = windowed_parallel(
            [0, 1], 2, lambda n: True, build,
            scheduler=sched, job_meta=lambda i: dict(rows=fr.nrows,
                                                     algo="gbm"))
    assert all(e is None for _, _, e in results)
    m0, m1 = results[0][1], results[1][1]
    # identical work on same-size slices -> bit-identical models
    assert float(m0.training_metrics.auc) == float(m1.training_metrics.auc)

    trace = TRACER.get_trace(root.trace_id)
    leases = [s for s in trace["spans"] if s["name"].startswith("mesh_slice:")]
    assert len(leases) == 2
    devsets = [set(s["attrs"]["devices"].split(",")) for s in leases]
    assert devsets[0].isdisjoint(devsets[1])
    # the fit spans OVERLAP in time (they really ran concurrently)
    (a0, a1), (b0, b1) = [(s["start_ns"], s["end_ns"]) for s in leases]
    assert max(a0, b0) < min(a1, b1), "slice-bound builds did not overlap"
    # each lease subtree carries that slice's devices on the build span
    steps = [s for s in trace["spans"]
             if s["attrs"].get("mesh_devices") is not None]
    assert len(steps) >= 2
    step_sets = {frozenset(s["attrs"]["mesh_devices"].split(","))
                 for s in steps}
    assert len(step_sets) == 2


def test_job_surfaces_user_frame_key_not_view_key(rng):
    """A slice-leased build's Job description and extension stream name the
    USER'S frame key, not the internal ``{key}::mesh[...]`` view key the
    entry reshard swaps in (which may even be evicted by the time the user
    reads GET /3/Jobs)."""
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.orchestration.scheduler import MeshScheduler
    from h2o3_tpu.utils.registry import DKV
    fr = _frame(rng, key="user_fr")
    DKV.put("user_fr", fr)
    try:
        est = GLM(family="binomial", lambda_=0.0)
        sched = MeshScheduler(slices=2)
        with sched.lease(rows=fr.nrows, algo="glm") as lease:
            assert lease.index >= 0          # actually slice-bound
            est.train(y="y", training_frame=fr)
        assert "user_fr" in est.job.description
        assert "::mesh[" not in est.job.description
    finally:
        DKV.remove("user_fr")


def test_automl_parallel_bit_identical_to_sequential(rng, monkeypatch):
    """Acceptance: at a FORCED slice layout, parallelism=2 AutoML produces
    per-model results bit-identical to parallelism=1 (every build binds a
    same-size slice either way), and models predict on global frames."""
    from h2o3_tpu.orchestration import AutoML

    monkeypatch.setenv("H2O3TPU_MESH_SLICES", "2")
    fr = _frame(rng, n=300)
    runs = []
    for par in (1, 2):
        aml = AutoML(max_models=2, nfolds=0, seed=7, parallelism=par,
                     include_algos=["GLM", "GBM"])
        aml.train(y="y", training_frame=fr)
        runs.append(aml.leaderboard.models)
    assert len(runs[0]) == len(runs[1]) >= 2
    for m1, m2 in zip(*runs):
        assert m1.algo == m2.algo
        assert float(m1.training_metrics.auc) == \
            float(m2.training_metrics.auc)
    # slice-built models were re-homed: scoring a GLOBAL-mesh frame works
    pred = runs[1][0].predict(fr)
    assert pred.nrows == fr.nrows


def test_cloud_v3_serves_mesh_slice_utilization(rng):
    from h2o3_tpu.api import schemas
    from h2o3_tpu.orchestration.scheduler import (MeshScheduler,
                                                  SLICE_STATS)
    SLICE_STATS.reset()
    sched = MeshScheduler(slices=2)
    with sched.lease(rows=10, algo="glm"):
        pass
    cloud = schemas.cloud_v3("0.0.0")
    ms = cloud["mesh_slices"]
    assert ms["count"] == 2
    used = [s for s in ms["slices"] if s["builds"]]
    assert used and used[0]["busy_seconds"] >= 0.0
    assert "queue_wait_seconds" in used[0]
    # telemetry rode along (h2o3_slice_* family)
    from h2o3_tpu.utils.telemetry import METRICS
    names = {r["name"] for r in METRICS.snapshot(include_buckets=False)}
    assert "h2o3_slice_count" in names
    assert "h2o3_slice_builds_total" in names


def test_slice_stats_full_row_never_counts_as_a_slice():
    """A whole-mesh (par=1) scheduler next to a 2-slice scheduler must not
    inflate the carving count to 3 — ``full`` overlaps every slice, so it
    reports as a separate utilization row, outside ``count``."""
    from h2o3_tpu.orchestration.scheduler import MeshScheduler, SLICE_STATS
    SLICE_STATS.reset()
    try:
        sliced = MeshScheduler(slices=2)
        full = MeshScheduler(slices=1)
        with full.lease(rows=10_000_000):
            pass
        snap = SLICE_STATS.snapshot()
        assert snap["count"] == 2
        labels = [s["slice"] for s in snap["slices"]]
        assert labels.count("full") == 1
        full_row = next(s for s in snap["slices"] if s["slice"] == "full")
        assert full_row["builds"] == 1 and full_row["devices"]
        # carved rows keep their disjoint device sets
        carved = [s for s in snap["slices"] if s["slice"] != "full"]
        assert len(carved) == 2
        assert not set(carved[0]["devices"]) & set(carved[1]["devices"])
        # a full-only process still reports one "slice": the whole mesh
        SLICE_STATS.reset()
        assert SLICE_STATS.configure(full.meshes) == 1
        assert SLICE_STATS.snapshot()["count"] == 1
    finally:
        SLICE_STATS.reset()


def test_full_lease_on_sliced_layout_reports_real_devices():
    """A big (whole-mesh) lease taken from a multi-slice scheduler reports
    the union of the layout's devices, not an empty set."""
    from h2o3_tpu.orchestration.scheduler import MeshScheduler, SLICE_STATS
    SLICE_STATS.reset()
    try:
        sched = MeshScheduler(slices=2)
        with sched.lease(rows=10_000_000):
            pass
        full_row = next(s for s in SLICE_STATS.snapshot()["slices"]
                        if s["slice"] == "full")
        assert sorted(full_row["devices"]) == \
            sorted(M.mesh_device_ids(M.global_mesh()))
    finally:
        SLICE_STATS.reset()


def test_scheduler_respects_callers_mesh_context():
    """A grid/AutoML run inside a user's ``mesh_context(submesh)`` stays
    confined to it: the scheduler carves the CALLER'S mesh, big leases take
    exactly it, and leases bind it even on pool threads (which don't
    inherit the caller's contextvars)."""
    from h2o3_tpu.orchestration.scheduler import MeshScheduler
    sub = M.slice_meshes(2)[1]               # a 4-device submesh
    sub_ids = set(M.mesh_device_ids(sub))
    with M.mesh_context(sub):
        sched = MeshScheduler(slices=2)
    assert set(M.mesh_device_ids(sched.base)) == sub_ids
    for m in sched.meshes:
        assert set(M.mesh_device_ids(m)) <= sub_ids
    assert len(sched.meshes) == 2
    # leases resolve inside the submesh even from a foreign thread
    seen = {}
    def worker():
        with sched.lease(rows=10):                   # small -> a sub-slice
            seen["small"] = set(M.mesh_device_ids(M.get_mesh()))
            # slice-built artifacts re-home onto the CALLER'S mesh
            seen["rehome_to"] = set(M.mesh_device_ids(M.rehome_target()))
        with sched.lease(rows=10_000_000):           # big -> the submesh
            seen["big"] = set(M.mesh_device_ids(M.get_mesh()))
    t = threading.Thread(target=worker)
    t.start(); t.join(timeout=30)
    assert seen["small"] < sub_ids
    assert seen["rehome_to"] == sub_ids
    assert seen["big"] == sub_ids
