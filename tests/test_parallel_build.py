"""Overlapped model builds (orchestration/parallel_build.py; reference
``hex/grid/GridSearch.java`` parallelism, ``water/ParallelizationTask.java``)."""

import threading
import time

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.orchestration.parallel_build import windowed_parallel


def test_results_in_submission_order():
    def run(i):
        time.sleep(0.02 * (5 - i))       # later items finish FIRST
        return i * 10

    out, exhausted = windowed_parallel(range(5), 3, lambda n: True, run)
    assert exhausted
    assert [item for item, _, _ in out] == [0, 1, 2, 3, 4]
    assert [res for _, res, _ in out] == [0, 10, 20, 30, 40]


def test_window_respects_parallelism():
    active, peak = [0], [0]
    lock = threading.Lock()

    def run(i):
        with lock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        time.sleep(0.02)
        with lock:
            active[0] -= 1
        return i

    windowed_parallel(range(8), 2, lambda n: True, run)
    assert peak[0] <= 2


def test_budget_gate_stops_submission():
    ran = []

    def run(i):
        ran.append(i)
        return i

    out, exhausted = windowed_parallel(range(100), 2,
                                       lambda n: n < 5, run)
    assert not exhausted                 # budget stop, not stream end
    assert len(out) == 5
    assert len(ran) == 5                 # stream never advanced past the gate


def test_failures_recorded_not_raised():
    def run(i):
        if i == 2:
            raise ValueError("boom")
        return i

    out, _ = windowed_parallel(range(4), 2, lambda n: True, run)
    assert [e is not None for _, _, e in out] == [False, False, True, False]
    assert isinstance(out[2][2], ValueError)


def test_grid_parallel_same_models_as_sequential(rng, monkeypatch):
    """Formerly hazard-prone: par>1 builds raced collectives on ONE global
    mesh (the documented rendezvous wedge). With the mesh-slice scheduler
    the overlapped builds lease disjoint slices; forcing the same slice
    layout on both runs makes per-model results BIT-identical across
    parallelism (same-size slices run the same deterministic programs)."""
    from h2o3_tpu.models.gbm import GBM
    from h2o3_tpu.orchestration.grid import GridSearch

    monkeypatch.setenv("H2O3TPU_MESH_SLICES", "2")
    n = 400
    x = rng.normal(size=(n, 3)).astype(np.float32)
    fr = Frame.from_arrays({
        "a": x[:, 0], "b": x[:, 1], "c": x[:, 2],
        "y": np.where(x[:, 0] + x[:, 1] > 0, "t", "f")})
    hyper = {"max_depth": [2, 3], "learn_rate": [0.1, 0.3]}

    g1 = GridSearch(GBM, hyper, grid_id="gseq", parallelism=1,
                    ntrees=3, seed=5).train(y="y", training_frame=fr)
    g2 = GridSearch(GBM, hyper, grid_id="gpar", parallelism=3,
                    ntrees=3, seed=5).train(y="y", training_frame=fr)
    assert len(g1.models) == len(g2.models) == 4
    # same combos in the same submission order, identical fitted trees:
    # slice-bound builds are deterministic per slice SIZE, so assignment
    # timing cannot perturb the models
    for m1, m2 in zip(g1.models, g2.models):
        assert m1.output["hyper_values"] == m2.output["hyper_values"]
        assert float(m1.training_metrics.auc) == \
            float(m2.training_metrics.auc)
