"""REST error-surface contract (VERDICT r2 weak #7).

Reference: the H2OError/H2OModelBuilderError schema contract — malformed
requests must come back as structured JSON errors with sane status codes,
never connection drops or server death.
"""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api import H2OServer
from h2o3_tpu.utils.registry import DKV


@pytest.fixture
def server():
    s = H2OServer(port=0).start()
    yield s
    s.stop()
    DKV.clear()


def _post(server, path, body):
    data = urllib.parse.urlencode(body).encode()
    return urllib.request.urlopen(
        urllib.request.Request(f"{server.url}{path}", data=data))


def _err(server, path, body=None, method="POST"):
    data = urllib.parse.urlencode(body).encode() if body is not None else b""
    try:
        urllib.request.urlopen(urllib.request.Request(
            f"{server.url}{path}", data=data if method == "POST" else None,
            method=method))
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())
    raise AssertionError("expected an HTTP error")


def test_malformed_rapids_is_structured_error(server):
    for ast in ["(unknown_op 1 2)", "(((", "(cols_py missing_frame 0)", ""]:
        code, body = _err(server, "/99/Rapids", {"ast": ast})
        assert code in (400, 404, 500), (ast, code)
        assert body["__meta"]["schema_type"] == "H2OErrorV3"
        assert body["msg"]
    # the server is still alive and serving
    with urllib.request.urlopen(f"{server.url}/3/Cloud") as r:
        assert r.status == 200


def test_unknown_keys_are_404(server):
    for path, method in [("/3/Frames/nope", "GET"),
                         ("/3/Models/nope", "GET"),
                         ("/3/Jobs/nope", "GET"),
                         ("/99/AutoML/nope", "GET"),
                         ("/99/Leaderboards/nope", "GET")]:
        code, body = _err(server, path, method=method)
        assert code == 404, (path, code)
        assert body["__meta"]["schema_type"] == "H2OErrorV3"


def test_oversized_param_body_rejected(server):
    big = b"x" * ((64 << 20) + 1024)
    req = urllib.request.Request(f"{server.url}/99/Rapids", data=big)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req)
    assert ei.value.code == 413
    body = json.loads(ei.value.read())
    assert "cap" in body["msg"]
    with urllib.request.urlopen(f"{server.url}/3/Cloud") as r:
        assert r.status == 200


def test_dart_checkpoint_resume_is_structured_400(server, rng):
    """Satellite (ISSUE 8): DART cannot resume a checkpoint (per-round
    renormalization rescales prior tree weights) — the REST layer must
    refuse the request UP FRONT with a structured 400, not hand back a
    background job that fails on the poller."""
    n = 200
    X = rng.normal(size=(n, 3))
    fr = Frame.from_arrays({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
        "y": np.where(X[:, 0] > 0, "p", "n")}, key="dart_fr")
    DKV.put("dart_fr", fr)
    with _post(server, "/3/ModelBuilders/xgboost",
               {"training_frame": "dart_fr", "response_column": "y",
                "ntrees": 2, "max_depth": 2,
                "model_id": "dart_cp_model"}) as r:
        job_key = json.loads(r.read())["job"]["key"]["name"]
    for _ in range(300):
        with urllib.request.urlopen(f"{server.url}/3/Jobs/{job_key}") as r:
            if json.loads(r.read())["jobs"][0]["status"] in (
                    "DONE", "FAILED", "CANCELLED"):
                break
        time.sleep(0.05)
    code, body = _err(server, "/3/ModelBuilders/xgboost",
                      {"training_frame": "dart_fr", "response_column": "y",
                       "booster": "dart", "ntrees": 4,
                       "checkpoint": "dart_cp_model"})
    assert code == 400
    assert "dart" in body["msg"].lower()
    assert "checkpoint" in body["msg"].lower()


def test_concurrent_job_cancellation(server, rng):
    n = 4000
    X = rng.normal(size=(n, 3))
    y = X[:, 0] > 0
    fr = Frame.from_arrays({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
        "y": np.array(["n", "p"], dtype=object)[y.astype(int)]},
        key="cancel_fr")
    DKV.put("cancel_fr", fr)
    with _post(server, "/3/ModelBuilders/gbm",
               {"training_frame": "cancel_fr", "response_column": "y",
                "ntrees": 200, "max_depth": 5}) as r:
        job_key = json.loads(r.read())["job"]["key"]["name"]
    # cancel from several clients at once while the build runs
    errs = []

    def cancel():
        try:
            _post(server, f"/3/Jobs/{job_key}/cancel", {}).read()
        except Exception as e:   # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=cancel) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    # budget covers a COLD compile of the fused boosting program (~40s on
    # this host): the whole ensemble is one dispatch, so a cancel can only
    # land once it returns (the job then reports DONE)
    for _ in range(900):
        with urllib.request.urlopen(f"{server.url}/3/Jobs/{job_key}") as r:
            st = json.loads(r.read())["jobs"][0]["status"]
        if st in ("CANCELLED", "DONE", "FAILED"):
            break
        time.sleep(0.1)
    assert st in ("CANCELLED", "DONE")   # DONE if it outran the cancel
    # a second cancel of a finished job is a no-op, not a crash
    _post(server, f"/3/Jobs/{job_key}/cancel", {}).read()
    with urllib.request.urlopen(f"{server.url}/3/Cloud") as r:
        assert r.status == 200
