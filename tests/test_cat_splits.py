"""Categorical group splits (reference: DHistogram enum bins +
DTree.findBestSplitPoint subset search; nbins_cats range grouping).

The canonical case ordinal thresholds CANNOT express: a categorical whose
predictive levels interleave with non-predictive ones in code order. A
group split separates them in ONE split; ordinal needs depth ~= levels.
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import GBM, DRF


def _interleaved(rng, n=2000):
    # levels a,c,e,g → 'yes'-ish; b,d,f,h → 'no'-ish; alternating in sorted
    # (code) order so no single threshold separates them
    levels = list("abcdefgh")
    codes = rng.integers(0, 8, size=n)
    p = np.where(codes % 2 == 0, 0.9, 0.1)
    y = rng.random(n) < p
    return Frame.from_arrays({
        "c": np.array(levels, dtype=object)[codes],
        "noise": rng.normal(size=n).astype(np.float32),
        "y": np.array(["no", "yes"], dtype=object)[y.astype(int)],
    })


def test_group_split_beats_ordinal_depth1(rng):
    fr = _interleaved(rng)
    kw = dict(ntrees=1, max_depth=1, learn_rate=1.0, seed=1, nbins=16)
    grouped = GBM(**kw).train(y="y", training_frame=fr)
    ordinal = GBM(**kw, categorical_encoding="ordinal").train(
        y="y", training_frame=fr)
    auc_g = grouped.training_metrics.auc
    auc_o = ordinal.training_metrics.auc
    # one group split nails the interleaved pattern; one threshold cannot
    assert auc_g > 0.85, auc_g
    assert auc_o < 0.75, auc_o
    assert grouped.output["trees"][0].left_mask is not None
    assert ordinal.output["trees"][0].left_mask is None


def test_group_split_predict_consistency(rng):
    """Training-time (binned) and scoring-time (raw) traversals agree."""
    fr = _interleaved(rng, 800)
    m = GBM(ntrees=5, max_depth=3, seed=2).train(y="y", training_frame=fr)
    p = m.predict(fr).vec("pyes").to_numpy()
    mm = m.model_performance(fr)
    assert mm.auc > 0.85
    # re-predict on a COPY of the frame (fresh domain-mapping path)
    fr2 = Frame.from_arrays({
        "c": fr.vec("c").labels(), "noise": fr.vec("noise").to_numpy(),
        "y": fr.vec("y").labels()})
    p2 = m.predict(fr2).vec("pyes").to_numpy()
    np.testing.assert_allclose(p, p2, rtol=1e-5)


def test_nbins_cats_range_grouping(rng):
    """Cardinality above nbins_cats range-groups levels instead of failing."""
    n = 1500
    codes = rng.integers(0, 40, size=n)         # 40 levels, nbins_cats=8
    y = rng.random(n) < np.where(codes < 20, 0.85, 0.15)
    fr = Frame.from_arrays({
        "c": np.array([f"lv{i:02d}" for i in range(40)], dtype=object)[codes],
        "y": np.array(["no", "yes"], dtype=object)[y.astype(int)],
    })
    m = GBM(ntrees=3, max_depth=2, nbins=16, nbins_cats=8, seed=3).train(
        y="y", training_frame=fr)
    assert int(m.output["cat_bins"]) == 8
    assert m.training_metrics.auc > 0.8


def test_group_split_pojo_and_shap(rng, tmp_path):
    fr = _interleaved(rng, 600)
    m = GBM(ntrees=4, max_depth=3, seed=4).train(y="y", training_frame=fr)

    # POJO module reproduces the grouped-split scoring
    path = m.download_pojo(str(tmp_path / "pj.py"))
    import importlib.util
    spec = importlib.util.spec_from_file_location("pj", path)
    pj = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pj)
    rows = fr.to_pandas().to_dict("records")[:50]
    ours = m.predict(fr).vec("pyes").to_numpy()[:50]
    theirs = np.array([pj.score(r)[1] for r in rows])
    np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-6)

    # TreeSHAP contributions still sum to the raw margin
    contrib = m.predict_contributions(fr)
    tot = sum(contrib.vec(nm).to_numpy() for nm in contrib.names)
    p = np.clip(m.predict(fr).vec("pyes").to_numpy(), 1e-12, 1 - 1e-12)
    margin = np.log(p / (1 - p))
    np.testing.assert_allclose(tot, margin, rtol=1e-3, atol=1e-3)


def test_drf_group_splits(rng):
    fr = _interleaved(rng, 1000)
    m = DRF(ntrees=10, max_depth=4, seed=5).train(y="y", training_frame=fr)
    assert m.training_metrics.auc > 0.85
