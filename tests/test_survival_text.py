"""Isotonic / CoxPH / Word2Vec tests (reference test model: h2o-py
``testdir_algos/{isotonic,coxph,word2vec}/pyunit_*``)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.models import CoxPH, IsotonicRegression, Word2Vec


# -- Isotonic ----------------------------------------------------------------

def test_isotonic_matches_sklearn(rng):
    n = 500
    x = rng.uniform(0, 10, n)
    y = np.sin(x / 3.5) * 2 + x * 0.5 + rng.normal(scale=0.4, size=n)
    f = Frame.from_arrays({"x": x, "y": y})
    m = IsotonicRegression().train(x=["x"], y="y", training_frame=f)
    pred = m.predict(f).vec("predict").to_numpy()

    from sklearn.isotonic import IsotonicRegression as SkIso
    sk = SkIso(out_of_bounds="clip").fit(x, y)
    np.testing.assert_allclose(pred, sk.predict(x), atol=1e-4)


def test_isotonic_monotone_and_oob(rng):
    n = 300
    x = rng.uniform(0, 1, n)
    y = x ** 2 + rng.normal(scale=0.05, size=n)
    f = Frame.from_arrays({"x": x, "y": y})
    m = IsotonicRegression(out_of_bounds="NA").train(x=["x"], y="y", training_frame=f)
    xs = np.sort(x)
    fs = Frame.from_arrays({"x": xs})
    ps = m.predict(fs).vec("predict").to_numpy()
    assert (np.diff(ps) >= -1e-6).all()
    # out-of-range rows → NA
    f2 = Frame.from_arrays({"x": np.array([-1.0, 2.0])})
    p2 = m.predict(f2).vec("predict").to_numpy()
    assert np.isnan(p2).all()
    m2 = IsotonicRegression(out_of_bounds="clip").train(x=["x"], y="y",
                                                        training_frame=f)
    p3 = m2.predict(f2).vec("predict").to_numpy()
    assert np.isfinite(p3).all()


def test_isotonic_weighted(rng):
    # two duplicated x values with conflicting y: weights decide the level
    x = np.array([1.0, 1.0, 2.0, 2.0])
    y = np.array([0.0, 10.0, 20.0, 0.0])
    w = np.array([9.0, 1.0, 1.0, 9.0])
    f = Frame.from_arrays({"x": x, "y": y, "w": w})
    m = IsotonicRegression(weights_column="w").train(x=["x"], y="y",
                                                     training_frame=f)
    pred = m.predict(Frame.from_arrays({"x": np.array([1.0, 2.0])}))
    p = pred.vec("predict").to_numpy()
    # weighted means: x=1 → 1.0, x=2 → 2.0 (already isotonic)
    np.testing.assert_allclose(p, [1.0, 2.0], atol=1e-5)


# -- CoxPH -------------------------------------------------------------------

def _cox_data(rng, n=800, beta=(0.8, -0.5)):
    X = rng.normal(size=(n, 2))
    lam = 0.1 * np.exp(X @ np.array(beta))
    t = rng.exponential(1.0 / lam)
    c = rng.exponential(1.0 / 0.05, size=n)   # censoring times
    time = np.minimum(t, c)
    event = (t <= c).astype(float)
    return Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1],
                              "time": time, "event": event}), X, time, event


def test_coxph_recovers_coefficients(rng):
    f, *_ = _cox_data(rng)
    m = CoxPH(stop_column="time", ties="breslow").train(
        x=["x0", "x1"], y="event", training_frame=f)
    coef = m.coefficients()
    assert abs(coef["x0"] - 0.8) < 0.15
    assert abs(coef["x1"] + 0.5) < 0.15
    assert np.isfinite(m.output["loglik"])


def test_coxph_efron_close_to_breslow_without_ties(rng):
    f, *_ = _cox_data(rng, n=400)
    mb = CoxPH(stop_column="time", ties="breslow").train(
        x=["x0", "x1"], y="event", training_frame=f)
    me = CoxPH(stop_column="time", ties="efron").train(
        x=["x0", "x1"], y="event", training_frame=f)
    # continuous times → essentially no ties → identical estimates
    np.testing.assert_allclose(
        np.asarray(me.output["coef"]), np.asarray(mb.output["coef"]), atol=1e-3)


def test_coxph_vs_lifelines_style_check(rng):
    # higher-risk rows should get larger linear predictors
    f, X, time, event = _cox_data(rng, n=600)
    m = CoxPH(stop_column="time").train(x=["x0", "x1"], y="event",
                                        training_frame=f)
    lp = m.predict(f).vec("lp").to_numpy()
    true_lp = X @ np.array([0.8, -0.5])
    assert np.corrcoef(lp, true_lp)[0, 1] > 0.97


# -- Word2Vec ----------------------------------------------------------------

def _toy_corpus(rng, n_sent=300):
    """Two topic clusters: {cat,dog,pet} and {car,bus,road} co-occur."""
    topics = [["cat", "dog", "pet", "fur", "paw"],
              ["car", "bus", "road", "wheel", "fuel"]]
    words = []
    for _ in range(n_sent):
        t = topics[rng.integers(0, 2)]
        for _ in range(rng.integers(4, 9)):
            words.append(t[rng.integers(0, len(t))])
        words.append(None)   # sentence delimiter
    return Frame.from_arrays({"words": np.array(words, dtype=object)},
                             types={"words": VecType.STR})


def test_word2vec_learns_topics(rng):
    f = _toy_corpus(rng)
    m = Word2Vec(vec_size=16, min_word_freq=2, epochs=25, window_size=3,
                 seed=11).train(training_frame=f)
    syn = m.find_synonyms("cat", 3)
    assert len(syn) == 3
    assert set(syn) <= {"dog", "pet", "fur", "paw"}


def test_word2vec_transform_average(rng):
    f = _toy_corpus(rng, n_sent=100)
    m = Word2Vec(vec_size=8, min_word_freq=2, epochs=5, seed=11,
                 ).train(training_frame=f)
    doc = m.transform(f, aggregate_method="AVERAGE")
    assert doc.names[0] == "C1"
    assert doc.nrows >= 100          # one row per sentence
    tab = m.to_frame()
    assert tab.names[0] == "Word"
    assert tab.nrows == len(m.output["vocab"])


def test_word2vec_transform_no_spurious_trailing_row(rng):
    f = _toy_corpus(rng, n_sent=20)   # corpus ends with the NA delimiter
    m = Word2Vec(vec_size=8, min_word_freq=2, epochs=3, seed=1,
                 ).train(training_frame=f)
    doc = m.transform(f, aggregate_method="AVERAGE")
    assert doc.nrows == 20


def test_gbm_explicit_bernoulli_multiclass_raises(rng):
    from h2o3_tpu.models import GBM
    n = 120
    f = Frame.from_arrays({"x": rng.normal(size=n),
                           "y": np.array(["a", "b", "c"], dtype=object)[
                               rng.integers(0, 3, n)]})
    with pytest.raises(ValueError, match="2-class"):
        GBM(distribution="bernoulli", ntrees=2).train(y="y", training_frame=f)


def test_coxph_builder_reusable(rng):
    f, *_ = _cox_data(rng, n=300)
    b = CoxPH(stop_column="time")
    b.train(x=["x0", "x1"], y="event", training_frame=f)
    b.train(x=["x0", "x1"], y="event", training_frame=f)
    assert b.params["ignored_columns"] is None


def test_coxph_baseline_hazard_and_survival(rng):
    """Breslow baseline hazard + survfit curves (reference: CoxPH baseline
    hazard output; S(t|x)=exp(-H0(t)e^lp))."""
    from h2o3_tpu.models import CoxPH
    n = 400
    x = rng.normal(size=n).astype(np.float32)
    # exponential hazards: rate = exp(0.8 x)
    t = rng.exponential(scale=1.0 / np.exp(0.8 * x)).astype(np.float32)
    event = (rng.random(n) < 0.8).astype(np.float32)
    fr = Frame.from_arrays({"x": x, "time": t,
                            "event": event.astype(np.float32)})
    m = CoxPH(stop_column="time").train(y="event", training_frame=fr)
    bh = m.baseline_hazard()
    tt = bh.vec("t").to_numpy()
    hh = bh.vec("cumhaz").to_numpy()
    assert (np.diff(tt) > 0).all()            # ascending times
    assert (np.diff(hh) >= -1e-9).all()       # cumhaz non-decreasing
    assert hh[-1] > hh[0] >= 0.0
    surv = m.predict_survival(fr, times=[np.median(t)])
    s = surv.vecs[0].to_numpy()
    assert ((s >= 0) & (s <= 1)).all()
    # higher-risk rows (larger x) must have LOWER survival
    assert s[x > 1.0].mean() < s[x < -1.0].mean()


def test_word2vec_hsm_objective_learns_topics(rng):
    """The reference's hierarchical-softmax objective (Word2Vec.java HSM;
    Huffman paths padded to fixed length for the fused scan)."""
    f = _toy_corpus(rng)
    m = Word2Vec(vec_size=16, min_word_freq=2, epochs=25, window_size=3,
                 objective="hsm", seed=11).train(training_frame=f)
    syn = m.find_synonyms("car", 3)
    assert len(syn) == 3
    assert set(syn) <= {"bus", "road", "wheel", "fuel"}


def test_word2vec_pre_trained_import(rng):
    """fromPretrainedModel (Word2Vec.java:123-145): external word->vector
    frame becomes a full model (synonyms + transform)."""
    f = _toy_corpus(rng, n_sent=80)
    trained = Word2Vec(vec_size=8, min_word_freq=2, epochs=5, seed=3,
                       ).train(training_frame=f)
    table = trained.to_frame()            # Word | V1..V8

    m = Word2Vec(pre_trained=table).train()
    assert m.output["vec_size"] == 8
    assert m.output["vocab"] == trained.output["vocab"]
    np.testing.assert_allclose(
        np.asarray(m.output["vectors"]),
        np.asarray(trained.output["vectors"]), rtol=0, atol=1e-6)
    # transform through the imported model matches the original
    d1 = trained.transform(f, aggregate_method="AVERAGE")
    d2 = m.transform(f, aggregate_method="AVERAGE")
    np.testing.assert_allclose(d2.vec("C1").to_numpy(),
                               d1.vec("C1").to_numpy(), rtol=0, atol=1e-6)


def test_word2vec_pre_trained_validation(rng):
    import pytest

    from h2o3_tpu.frame.frame import Frame as F
    bad = F.from_arrays({"a": np.float32([1, 2]), "b": np.float32([3, 4])})
    with pytest.raises(ValueError, match="STR words"):
        Word2Vec(pre_trained=bad).train()
