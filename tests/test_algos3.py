"""Third algo wave: GAM, ModelSelection, ANOVAGLM, UpliftDRF
(reference test model: ``h2o-py/tests/testdir_algos/{gam,modelselection,
anovaglm,uplift}/``)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models import ANOVAGLM, GAM, ModelSelection, UpliftDRF


def test_gam_captures_nonlinearity(rng):
    n = 2000
    x = rng.uniform(-3, 3, size=n)
    z = rng.normal(size=n)
    y = np.sin(x) * 2.0 + 0.5 * z + rng.normal(scale=0.1, size=n)
    f = Frame.from_arrays({"x": x, "z": z, "y": y})

    from h2o3_tpu.models import GLM
    lin = GLM(family="gaussian").train(y="y", training_frame=f)
    gam = GAM(gam_columns=["x"], num_knots=8).train(y="y", training_frame=f)
    # the spline must capture sin(x); a linear GLM cannot
    assert gam.training_metrics.r2 > 0.95
    assert gam.training_metrics.r2 > lin.training_metrics.r2 + 0.2
    # scoring a fresh frame re-expands the basis identically
    pred = gam.predict(f).vec("predict").to_numpy()
    assert np.corrcoef(pred, y)[0, 1] > 0.97


def test_gam_binomial(rng):
    n = 1500
    x = rng.uniform(-3, 3, size=n)
    p = 1 / (1 + np.exp(-3 * np.sin(x)))
    y = rng.uniform(size=n) < p
    f = Frame.from_arrays({"x": x,
                           "y": np.array(["t" if v else "f" for v in y],
                                         dtype=object)})
    gam = GAM(gam_columns=["x"], num_knots=8, family="binomial") \
        .train(y="y", training_frame=f)
    assert gam.training_metrics.auc > 0.8


def test_model_selection_maxr(rng):
    n = 1000
    X = rng.normal(size=(n, 4))
    y = 3.0 * X[:, 0] + 2.0 * X[:, 1] + rng.normal(scale=0.1, size=n)
    f = Frame.from_arrays({f"x{i}": X[:, i] for i in range(4)} | {"y": y})
    m = ModelSelection(mode="maxr", max_predictor_number=2) \
        .train(y="y", training_frame=f)
    res = m.result()
    assert res[0]["n_predictors"] == 1
    # best 1-predictor model must pick x0 (largest coefficient)
    assert res[0]["predictors"] == ["x0"]
    assert set(res[1]["predictors"]) == {"x0", "x1"}
    assert res[1]["r2"] > 0.99


def test_model_selection_forward_backward(rng):
    n = 800
    X = rng.normal(size=(n, 3))
    y = 2.0 * X[:, 0] - 1.0 * X[:, 2] + rng.normal(scale=0.1, size=n)
    f = Frame.from_arrays({f"x{i}": X[:, i] for i in range(3)} | {"y": y})
    fw = ModelSelection(mode="forward", max_predictor_number=2) \
        .train(y="y", training_frame=f)
    assert fw.result()[0]["predictors"] == ["x0"]
    assert set(fw.result()[1]["predictors"]) == {"x0", "x2"}
    bw = ModelSelection(mode="backward", min_predictor_number=2) \
        .train(y="y", training_frame=f)
    assert set(bw.result()[-1]["predictors"]) == {"x0", "x2"}


def test_anovaglm(rng):
    n = 1200
    X = rng.normal(size=(n, 3))
    y = 2.0 * X[:, 0] + rng.normal(scale=0.5, size=n)   # only x0 matters
    f = Frame.from_arrays({f"x{i}": X[:, i] for i in range(3)} | {"y": y})
    m = ANOVAGLM().train(y="y", training_frame=f)
    tab = {r["predictor"]: r for r in m.anova_table()}
    assert tab["x0"]["p_value"] < 1e-6
    assert tab["x1"]["p_value"] > 0.01
    assert tab["x2"]["p_value"] > 0.01


def test_uplift_drf(rng):
    n = 4000
    X = rng.normal(size=(n, 3))
    treat = rng.integers(0, 2, size=n)
    # true uplift depends on x0: treated units with x0>0 convert much more
    base = 0.2
    uplift = 0.4 * (X[:, 0] > 0)
    p = base + treat * uplift
    y = rng.uniform(size=n) < p
    f = Frame.from_arrays({
        "x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
        "treat": np.array(["control", "treatment"], dtype=object)[treat],
        "y": np.array(["no", "yes"], dtype=object)[y.astype(int)],
    })
    m = UpliftDRF(treatment_column="treat", ntrees=20, max_depth=4) \
        .train(y="y", training_frame=f)
    pred = m.predict(f).vec("uplift_predict").to_numpy()
    # predicted uplift separates the high-uplift segment
    hi = pred[X[:, 0] > 0].mean()
    lo = pred[X[:, 0] <= 0].mean()
    assert hi > lo + 0.15, (hi, lo)
    mm = m.training_metrics
    assert mm.auuc > 0 and mm.qini > 0
