"""graftlint analyzer tests — each rule family against known-bad /
known-good fixture snippets, suppression + baseline round-trips, and a
meta-test pinning the live package at zero non-baselined findings.

The fixtures are SOURCE-only mini packages written to tmp_path: graftlint
is pure-AST, nothing here is imported or executed.
"""

import json
import textwrap

import pytest

from h2o3_tpu.tools.lint import (DEFAULT_BASELINE, FAMILY_NAMES,
                                 load_baseline, load_reasons, main,
                                 run_lint, save_baseline, split_findings,
                                 stale_entries)


def make_pkg(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def rules_of(findings):
    return sorted({f.rule for f in findings})


# -- tracer-safety -----------------------------------------------------------

def test_trc001_host_sync_in_jit(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            s = jnp.sum(x)
            v = float(jax.device_get(s))      # sync inside trace
            t = s.item()                      # and another
            return v + t
    """})
    findings = run_lint(pkg)
    assert [f.rule for f in findings].count("TRC001") >= 2
    assert all(f.where == "step" for f in findings)


def test_trc001_reachable_helper_flagged(tmp_path):
    # helper is not decorated but is called from a jit root -> traced
    pkg = make_pkg(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        def helper(x):
            s = jnp.sum(x)
            return float(s)

        @jax.jit
        def step(x):
            return helper(x) + 1.0
    """})
    findings = run_lint(pkg)
    assert any(f.rule == "TRC001" and f.where == "helper" for f in findings)


def test_trc002_tracer_branch(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            s = jnp.sum(x)
            if s > 0:                         # trace break
                return s
            while jnp.max(x) > 0:             # and another
                x = x - 1
            return x
    """})
    findings = run_lint(pkg)
    assert [f.rule for f in findings].count("TRC002") == 2


def test_tracer_static_patterns_are_clean(tmp_path):
    # static param branch, .shape math, is-None tests, backend probe:
    # all legal trace-time work — zero findings
    pkg = make_pkg(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def step(x, mode, extra=None):
            k = int(np.log2(x.shape[1] + 1))
            if mode == "fast":
                x = x * 2
            if extra is not None:
                x = x + extra
            if jax.default_backend() != "tpu":
                k = k + 1
            return jnp.sum(x) * k
    """})
    assert run_lint(pkg) == []


def test_trc003_loop_sync_flagged_and_batched_clean(tmp_path):
    pkg = make_pkg(tmp_path, {"bad.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(b):
            nb = b + 1
            return nb, jnp.sum(nb), jnp.max(jnp.abs(nb - b))

        def fit(b):
            for _ in range(10):
                b, dev, delta = step(b)
                d = float(jax.device_get(dev))       # sync 1
                e = float(jax.device_get(delta))     # sync 2
                if e < 1e-6:
                    break
            return b, d
    """, "good.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(b):
            nb = b + 1
            return nb, jnp.sum(nb), jnp.max(jnp.abs(nb - b))

        def fit(b):
            devs = []
            for _ in range(10):
                b, dev, delta = step(b)
                devs.append(dev)
            return b, jax.device_get(devs)           # hoisted: one transfer
    """})
    findings = run_lint(pkg)
    assert [f.rule for f in findings] == ["TRC003", "TRC003"]
    assert all(f.path == "bad.py" for f in findings)


# -- lock-discipline ---------------------------------------------------------

def test_lck001_half_guarded_attr(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}

            def put(self, k, v):
                with self._lock:
                    self._data[k] = v

            def drop(self, k):
                self._data.pop(k, None)        # unguarded!
    """})
    findings = run_lint(pkg)
    assert rules_of(findings) == ["LCK001"]
    assert findings[0].where == "Store.drop"


def test_lck001_fully_guarded_clean(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}

            def put(self, k, v):
                with self._lock:
                    self._data[k] = v

            def drop(self, k):
                with self._lock:
                    self._data.pop(k, None)
    """})
    assert run_lint(pkg) == []


def test_lck002_thread_shared_unlocked(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import threading

        class Worker:
            def __init__(self):
                self.state = "idle"

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self.state = "running"         # unlocked, thread-shared
    """})
    findings = run_lint(pkg)
    assert rules_of(findings) == ["LCK002"]
    assert findings[0].detail == "state"


def test_lck003_singleton_private_mutation(tmp_path):
    pkg = make_pkg(tmp_path, {
        "owner.py": """
            class Cache:
                def __init__(self):
                    self._data = {}

            CACHE = Cache()
        """,
        "user.py": """
            from owner import CACHE

            def evict(k):
                CACHE._data.pop(k, None)       # reaches into private state
        """})
    findings = run_lint(pkg)
    assert rules_of(findings) == ["LCK003"]
    assert findings[0].path == "user.py"


# -- REST surface ------------------------------------------------------------

_REST_GOOD = {
    "api/__init__.py": "",
    "api/server.py": """
        from api import schemas

        class _Handler:
            def _reply(self, obj):
                pass

            def r_thing(self, key):
                self._reply(schemas.thing_v3(key))

            def r_list(self):
                self._reply({"__meta": {"schema_type": "ListV3"}})

        _ROUTES = [
            (r"/3/Things/([^/]+)", "GET", _Handler.r_thing),
            (r"/3/Things", "GET", _Handler.r_list),
        ]
    """,
    "api/schemas.py": """
        def thing_v3(key):
            return {"__meta": {"schema_type": "ThingV3"}, "key": key}
    """,
    "api/client.py": """
        class Client:
            def request(self, method, path, data=None):
                pass

            def thing(self, key):
                return self.request("GET", f"/3/Things/{key}")
    """,
}


def test_rest_consistent_surface_clean(tmp_path):
    assert run_lint(make_pkg(tmp_path, _REST_GOOD)) == []


def test_rest_drift_all_rules(tmp_path):
    files = dict(_REST_GOOD)
    files["api/server.py"] = """
        from api import schemas

        class _Handler:
            def _reply(self, obj):
                pass

            def r_thing(self, key):
                self._reply(schemas.thing_v3(key))

            def r_list(self):
                self._reply({"__meta": {"schema_type": "ListV3"}})

            def r_silent(self):
                x = 1                              # RST001: no reply at all

            def r_ghost(self):
                self._reply(schemas.ghost_v3())    # RST005: undefined schema

        _ROUTES = [
            (r"/3/Things/([^/]+)", "GET", _Handler.r_thing),
            (r"/3/Things", "GET", _Handler.r_list),
            (r"/3/Things", "GET", _Handler.r_list),      # RST004: duplicate
            (r"/3/Two/([^/]+)/([^/]+)", "GET", _Handler.r_thing),  # RST002
            (r"/3/Silent", "GET", _Handler.r_silent),
            (r"/3/Ghost", "GET", _Handler.r_ghost),
        ]
    """
    files["api/client.py"] = """
        class Client:
            def request(self, method, path, data=None):
                pass

            def thing(self, key):
                return self.request("GET", f"/3/Things/{key}")

            def nothing(self):
                return self.request("DELETE", "/3/Nothing")   # RST003
    """
    findings = run_lint(make_pkg(tmp_path, files))
    assert rules_of(findings) == ["RST001", "RST002", "RST003", "RST004",
                                  "RST005"]


# -- memory (MEM) ------------------------------------------------------------

def test_mem001_device_copy_in_timed_hot_loop(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import numpy as np
        from h2o3_tpu.utils.timeline import timed_event

        def fit(vec, iters):
            out = []
            for _ in range(iters):
                with timed_event("iteration", "demo:step"):
                    host = np.asarray(vec.data)      # 2x copy per iteration
                    out.append(host.sum())
            return out

        def fit_outer(cols):
            with timed_event("model", "demo:fit"):
                for c in cols:
                    arr = np.array(c.as_float())     # loop INSIDE the with
            return arr
    """})
    findings = run_lint(pkg)
    assert rules_of(findings) == ["MEM001"]
    assert len(findings) == 2
    assert all(f.detail in ("np.asarray", "np.array") for f in findings)


def test_mem001_clean_patterns(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import numpy as np
        from h2o3_tpu.utils.timeline import timed_event

        def one_time_copy(vec):
            with timed_event("model", "demo:fit"):
                host = np.asarray(vec.data)          # no loop: single copy
            return host

        def untimed_loop(vec, iters):
            for _ in range(iters):
                host = np.asarray(vec.data)          # not under timed_event
            return host

        def host_value(rows, iters):
            for _ in range(iters):
                with timed_event("iteration", "demo:step"):
                    host = np.asarray(rows)          # host arg: no device copy
            return host

        def hoisted_into_header(vec):
            with timed_event("model", "demo:fit"):
                # the For ITER expression runs once per loop entry — the
                # recommended hoisted-fetch form must not be flagged
                for row in np.asarray(vec.data):
                    pass
            return row
    """})
    assert run_lint(pkg) == []


def test_mem001_exempts_explicit_device_get(tmp_path):
    """np.asarray over jax.device_get is zero-copy — the transfer is
    explicit and sync PLACEMENT is TRC003's business, not MEM001's."""
    pkg = make_pkg(tmp_path, {"mod.py": """
        import numpy as np
        import jax
        from h2o3_tpu.utils.timeline import timed_event

        def explicit(vec, iters):
            for _ in range(iters):
                with timed_event("iteration", "demo:step"):
                    host = np.asarray(jax.device_get(vec))
            return host
    """})
    assert "MEM001" not in rules_of(run_lint(pkg))


# -- sync discipline (SYN) ---------------------------------------------------

def test_syn001_block_until_ready_flagged_both_forms(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        def dispatch(x):
            out = jnp.sum(x)
            jax.block_until_ready(out)           # library-code sync
            return out

        def method_form(x):
            return (x + 1).block_until_ready()   # and the method spelling
    """})
    findings = run_lint(pkg)
    syn = [f for f in findings if f.rule == "SYN001"]
    assert len(syn) == 2
    assert {f.where for f in syn} == {"dispatch", "method_form"}
    assert all(f.detail == "block_until_ready" for f in syn)


def test_syn001_telemetry_modules_exempt_and_suppressible(tmp_path):
    pkg = make_pkg(tmp_path, {"pkg/utils/telemetry.py": """
        import jax

        def probe(x):
            jax.block_until_ready(x)     # the sync IS the measurement
            return x
    """, "pkg/utils/tracing.py": """
        import jax

        def partition_probe(x):
            x.block_until_ready()
            return x
    """, "pkg/ops/dispatch.py": """
        import jax

        def sampled_probe(x):
            # graftlint: ok(sampled telemetry probe)
            jax.block_until_ready(x)
            return x
    """})
    assert "SYN001" not in rules_of(run_lint(pkg))


# -- mesh discipline (MSH) ---------------------------------------------------

def test_msh001_get_mesh_in_builder_flagged_both_forms(tmp_path):
    pkg = make_pkg(tmp_path, {"models/bad.py": """
        from h2o3_tpu.parallel.mesh import get_mesh
        from h2o3_tpu.parallel import mesh

        def fit(x):
            m = get_mesh()               # context lookup in a builder
            return m

        def fit_attr(x):
            return mesh.get_mesh()       # attribute spelling
    """})
    msh = [f for f in run_lint(pkg) if f.rule == "MSH001"]
    assert len(msh) == 2
    assert {f.where for f in msh} == {"fit", "fit_attr"}
    assert all(f.detail == "get_mesh" for f in msh)


def test_msh001_input_sharding_pattern_and_non_builders_clean(tmp_path):
    pkg = make_pkg(tmp_path, {"models/good.py": """
        def hist_mesh(arr):
            # the sanctioned pattern: the mesh comes from the DATA
            sharding = getattr(arr, "sharding", None)
            return getattr(sharding, "mesh", None)

        def fit(x, mesh):
            return mesh                  # threaded as an argument
    """, "ops/dispatch.py": """
        from h2o3_tpu.parallel.mesh import get_mesh

        def map_reduce(fn):
            return get_mesh()            # dispatch layer: context-aware
    """, "models/suppressed.py": """
        from h2o3_tpu.parallel.mesh import get_mesh

        def fit(x):
            return get_mesh()  # graftlint: ok(whole-frame op, no jit trace)
    """})
    assert "MSH001" not in rules_of(run_lint(pkg))


# -- retry discipline (RTY) --------------------------------------------------

def test_rty001_constant_sleep_retry_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import time

        def fetch(url):
            for attempt in range(5):
                try:
                    return do_request(url)
                except IOError:
                    time.sleep(0.5)          # constant: no backoff/jitter

        def fetch2(url):
            while True:
                try:
                    return do_request(url)
                except IOError:
                    pass
                time.sleep(2)                # same, while-loop spelling
    """})
    rty = [f for f in run_lint(pkg) if f.rule == "RTY001"]
    assert len(rty) == 2
    assert {f.where for f in rty} == {"fetch", "fetch2"}
    assert all(f.detail == "constant-sleep-retry" for f in rty)


def test_rty001_backoff_and_polling_clean(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import random
        import time

        def fetch(url):
            for attempt in range(5):
                try:
                    return do_request(url)
                except IOError:
                    # exponential backoff + jitter: computed, not constant
                    time.sleep(0.05 * 2 ** attempt * (0.5 + random.random()))

        def poll(job):
            # polling (no except in the loop) is not a retry loop
            while not job.done():
                time.sleep(0.2)
    """})
    assert "RTY001" not in rules_of(run_lint(pkg))


def test_rty002_swallowing_except_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import time

        def spin(op):
            while True:                      # retry loop
                try:
                    return op()
                except Exception:            # the failure vanishes
                    pass

        def sleepy_for(items):
            for it in items:
                try:
                    send(it)
                except:                      # bare + waits = retry in disguise
                    continue
                time.sleep(1.0)
    """})
    rty = [f for f in run_lint(pkg) if f.rule == "RTY002"]
    assert len(rty) == 2
    assert {f.where for f in rty} == {"spin", "sleepy_for"}


def test_rty002_recording_and_skip_patterns_clean(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        def robust(op, log):
            errs = []
            while True:                      # records the failure: fine
                try:
                    return op()
                except Exception as e:
                    errs.append(e)
                    if len(errs) > 3:
                        raise

        def skip_bad(items):
            out = []
            for it in items:                 # for + no sleep = skip-bad-items
                try:
                    out.append(parse(it))
                except Exception:
                    continue
            return out

        def narrow(op):
            while True:
                try:
                    return op()
                except KeyError:             # narrow type: fine
                    pass
    """})
    assert "RTY002" not in rules_of(run_lint(pkg))


# -- wait discipline ---------------------------------------------------------

def test_wtx001_unbounded_waits_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import queue
        import threading

        class Pool:
            def __init__(self):
                self._cond = threading.Condition()
                self._queue = queue.Queue()
                self.free = []

            def take(self):
                with self._cond:
                    while not self.free:
                        self._cond.wait()          # unbounded: dead notifier
                    return self.free.pop()

            def drain(self):
                return self._queue.get()           # unbounded queue read

            def park(self):
                threading.Event().wait()           # unbounded event wait
    """})
    wtx = [f for f in run_lint(pkg) if f.rule == "WTX001"]
    assert len(wtx) == 3
    assert {f.detail for f in wtx} == {"unbounded-wait",
                                       "unbounded-queue-get"}


def test_wtx001_bounded_and_nonqueue_clean(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import contextvars
        import queue
        import threading

        _CV = contextvars.ContextVar("x", default=None)

        class Pool:
            def __init__(self):
                self._cond = threading.Condition()
                self._inbox = queue.Queue()
                self.free = []

            def take(self):
                with self._cond:
                    # bounded wait + predicate recheck: the fixed shape
                    while not self.free:
                        self._cond.wait(timeout=1.0)
                    return self.free.pop()

            def drain(self):
                return self._inbox.get(timeout=0.25)

            def peek(self, d):
                # dict.get has an argument; ContextVar.get is not a queue
                return d.get("k"), _CV.get()

            def join_worker(self, t):
                t.join()          # join() is not wait()/get()
    """})
    assert "WTX001" not in rules_of(run_lint(pkg))


def test_wtx001_suppressible(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import threading

        def serve_forever():
            # graftlint: ok(serve forever - blocking IS the job)
            threading.Event().wait()
    """})
    assert "WTX001" not in rules_of(run_lint(pkg))



# -- ingest discipline (ING) -------------------------------------------------

def test_ing001_unbounded_reads_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"ingest/stage.py": """
        import numpy as np

        def read_stage(path, q):
            with open(path, "rb") as fh:
                data = fh.read()               # whole file at once
            q.put(data)

        def line_stage(fh):
            return fh.readlines()              # every line at once

        def bulk_stage(path):
            return np.loadtxt(path)            # whole-file loader
    """})
    ing = [f for f in run_lint(pkg) if f.rule == "ING001"]
    assert len(ing) == 3
    assert {f.detail for f in ing} == {"unbounded-read", "readlines",
                                       "whole-file-loader"}
    assert {f.where for f in ing} == {"read_stage", "line_stage",
                                      "bulk_stage"}


def test_ing001_bounded_and_outside_ingest_clean(tmp_path):
    pkg = make_pkg(tmp_path, {
        "ingest/stage.py": """
            def read_stage(path, q, abort):
                with open(path, "rb") as fh:
                    while True:
                        block = fh.read(1 << 20)    # bounded block
                        if not block:
                            break
                        q.put(block, timeout=1.0)

            def sized(fh, n):
                return fh.read(n)
        """,
        # the same unbounded read OUTSIDE ingest/ is another rule's problem
        "persist/io.py": """
            def slurp(path):
                with open(path, "rb") as fh:
                    return fh.read()
        """})
    assert "ING001" not in rules_of(run_lint(pkg))


def test_ing001_suppressible(tmp_path):
    pkg = make_pkg(tmp_path, {"ingest/stage.py": """
        def header_stage(path):
            with open(path, "rb") as fh:
                # graftlint: ok(sidecar header file is bytes-tiny)
                return fh.read()
    """})
    assert "ING001" not in rules_of(run_lint(pkg))


# -- metric documentation (MTR) ----------------------------------------------

def test_mtr001_undocumented_metric_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {
        "telemetry.py": """
            METRICS = object()
            DOCUMENTED = METRICS.counter("h2o3_documented", "d", ("k",))
            MISSING = METRICS.gauge("h2o3_missing_gauge", "m")
            MISSING_H = METRICS.histogram("h2o3_missing_seconds", "m")
        """,
        "docs/OBSERVABILITY.md": """
            | Name | Type | Labels | Meaning |
            |---|---|---|---|
            | `h2o3_documented_total` | counter | k | documented |
        """})
    mtr = [f for f in run_lint(pkg) if f.rule == "MTR001"]
    assert len(mtr) == 2
    assert {f.detail for f in mtr} == {
        "undocumented-metric:h2o3_missing_gauge",
        "undocumented-metric:h2o3_missing_seconds"}


def test_mtr001_total_suffix_dedupe_and_non_h2o3_clean(tmp_path):
    pkg = make_pkg(tmp_path, {
        "a.py": """
            def reg(m):
                # counters documented in exposition (_total) form match
                m.counter("h2o3_spills", "s", ("kind",))
                # one finding per NAME: a shared lazy registration is one
                # contract — the second call site must not double-report
                m.counter("h2o3_shared", "s", ("where",))
        """,
        "b.py": """
            def reg2(m):
                m.counter("h2o3_shared", "s", ("where",))
                m.gauge("internal_gauge", "not an h2o3_* family")
                other.counter(dynamic_name, "non-literal name: unknowable")
        """,
        "docs/OBSERVABILITY.md": """
            | `h2o3_spills_total` | counter | kind | documented as _total |
        """})
    mtr = [f for f in run_lint(pkg) if f.rule == "MTR001"]
    assert len(mtr) == 1
    assert mtr[0].detail == "undocumented-metric:h2o3_shared"


def test_mtr001_prefix_match_is_word_bounded(tmp_path):
    """`h2o3_spill` must NOT be satisfied by a doc row for
    `h2o3_spill_bytes_total` — only the exact name (± _total)."""
    pkg = make_pkg(tmp_path, {
        "a.py": 'M.counter("h2o3_spill", "s")\n',
        "docs/OBSERVABILITY.md": "| `h2o3_spill_bytes_total` | counter |\n"})
    mtr = [f for f in run_lint(pkg) if f.rule == "MTR001"]
    assert [f.detail for f in mtr] == ["undocumented-metric:h2o3_spill"]


def test_mtr001_prose_mention_is_not_a_row(tmp_path):
    """A narrative mention of the name outside a catalog table row does
    NOT satisfy the rule — the contract is a row, not a citation."""
    pkg = make_pkg(tmp_path, {
        "a.py": 'M.gauge("h2o3_foo", "f")\n',
        "docs/OBSERVABILITY.md":
            "Unlike `h2o3_foo`, this gauge resets on restart.\n"})
    mtr = [f for f in run_lint(pkg) if f.rule == "MTR001"]
    assert [f.detail for f in mtr] == ["undocumented-metric:h2o3_foo"]


def test_mtr001_no_docs_file_skips(tmp_path):
    """A tree without docs/OBSERVABILITY.md has nothing to drift with —
    the rule stays silent instead of flagging every registration."""
    pkg = make_pkg(tmp_path, {
        "a.py": 'M.counter("h2o3_orphan", "o")\n'})
    assert "MTR001" not in rules_of(run_lint(pkg))


def test_mtr001_suppressible(tmp_path):
    pkg = make_pkg(tmp_path, {
        "a.py": """
            # graftlint: ok(internal debug metric, deliberately uncataloged)
            M.counter("h2o3_debug_only", "d")
        """,
        "docs/OBSERVABILITY.md": "| nothing |\n"})
    assert "MTR001" not in rules_of(run_lint(pkg))


# -- remediation audit (ACT) -------------------------------------------------

def test_act001_unaudited_mutation_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"ops_plane/sneaky.py": """
        def tune(scoring, cleaner):
            scoring.configure_replicas(2)     # policy setter, no audit
            cleaner.budget = 1 << 20          # foreign .budget store
    """})
    acts = [f for f in run_lint(pkg) if f.rule == "ACT001"]
    assert {f.detail for f in acts} == {
        "unaudited-mutation:configure_replicas",
        "unaudited-mutation:.budget"}
    assert all(f.where == "tune" for f in acts)


def test_act001_act_rooted_and_self_state_clean(tmp_path):
    # the catalog shape: mutations (and rollback closures) rooted in a
    # top-level act_* function; self.budget is an object's own field
    pkg = make_pkg(tmp_path, {"ops_plane/actions.py": """
        def act_serving_relief(ctx):
            scoring = get_scoring()
            scoring.configure_replicas(2)
            def rollback():
                scoring.configure_replicas(1)
            return rollback

        def act_raise_budget(ctx):
            cleaner = get_cleaner()
            cleaner.budget = 1 << 30
            return lambda: cleaner.force_spill(["k"], limit=2)

        class QuotaExceeded(Exception):
            def __init__(self, budget):
                self.budget = budget
    """})
    assert "ACT001" not in rules_of(run_lint(pkg))


def test_act001_direct_action_call_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"ops_plane/engine.py": """
        from h2o3_tpu.ops_plane.actions import act_serving_relief

        class ActionLog:
            def record(self, action, rule, incident_id, mode):
                fn = self._catalog[action]
                return fn({"id": incident_id})   # audited execution: fine

        def panic(ctx):
            act_serving_relief(ctx)              # bypasses the ActionLog
    """})
    acts = [f for f in run_lint(pkg) if f.rule == "ACT001"]
    assert [f.detail for f in acts] == \
        ["direct-action-call:act_serving_relief"]
    assert acts[0].where == "panic"


def test_act001_outside_ops_plane_never_flagged(tmp_path):
    # the setters are legitimate API everywhere else — tests, REST
    # handlers, operators; only the automation must be audited
    pkg = make_pkg(tmp_path, {"serving/admin.py": """
        def resize(scoring, cleaner):
            scoring.configure_replicas(4)
            cleaner.budget = None
    """})
    assert "ACT001" not in rules_of(run_lint(pkg))


def test_act001_suppressible(tmp_path):
    pkg = make_pkg(tmp_path, {"ops_plane/boot.py": """
        def bootstrap(group, wid):
            # graftlint: ok(startup join precedes any audit surface)
            group.request_join(wid)
    """})
    assert "ACT001" not in rules_of(run_lint(pkg))


# -- metric cardinality (CRD) ------------------------------------------------

def test_crd001_unbounded_label_values_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"serving/meter.py": """
        def score(model_key, dest_path, m):
            m.WINDOW.labels(model=model_key).set(1.0)
            m.WRITES.labels(file=dest_path).inc()
            m.HITS.labels(user=f"tenant:{raw_user}").inc()
    """})
    crd = [f for f in run_lint(pkg) if f.rule == "CRD001"]
    assert {f.detail for f in crd} == {
        "unbounded-label:model=model_key",
        "unbounded-label:file=dest_path",
        "unbounded-label:user=raw_user"}


def test_crd001_bounded_and_sanitized_values_clean(tmp_path):
    pkg = make_pkg(tmp_path, {"serving/meter.py": """
        def record(m, kind, outcome, tenant_raw):
            m.SPILLS.labels(kind=kind).inc()              # closed-set var
            m.REQS.labels(route="/3/Score", outcome=outcome).inc()
            # sanitizer-shaped call: the bounded-label helper fix shape
            m.TENANTS.labels(tenant=tenant_label(tenant_raw)).inc()
            m.SHEDS.labels(reason=bounded_bucket(reason_key)).inc()
    """})
    assert "CRD001" not in rules_of(run_lint(pkg))


def test_crd001_vec_labels_accessor_never_matches(tmp_path):
    # Frame/Vec categorical accessors are argument-free .labels() calls —
    # only keyword-form metric calls are examined
    pkg = make_pkg(tmp_path, {"frame/utils.py": """
        def decode(v, frame_key):
            vals = v.labels()
            return vals, frame_key
    """})
    assert "CRD001" not in rules_of(run_lint(pkg))


def test_crd001_suppressible(tmp_path):
    pkg = make_pkg(tmp_path, {"serving/meter.py": """
        def record(m, model_key):
            m.WINDOW.labels(model=model_key).set(1.0)  # graftlint: ok(LRU-bounded residency)
    """})
    assert "CRD001" not in rules_of(run_lint(pkg))


# -- profiling attribution (PRF) ---------------------------------------------

def test_prf001_anonymous_jit_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import jax
        from functools import partial

        def loss(b):
            return (b * b).sum()

        def fit(b):
            g = jax.jit(jax.grad(loss))            # transform: unnamed
            s = jax.jit(lambda x: x + 1)           # lambda: unnamed
            p = jax.jit(partial(loss))             # partial: unnamed
            return g(b) + s(b) + p(b)
    """})
    findings = [f for f in run_lint(pkg) if f.rule == "PRF001"]
    assert len(findings) == 3
    assert all(f.where == "fit" for f in findings)
    assert "stable name" in findings[0].message


def test_prf001_named_forms_clean(tmp_path):
    # decorators (incl. @partial(jax.jit, ...)) and calls on named
    # references all keep a stable __name__ — zero findings
    pkg = make_pkg(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp
        from functools import partial

        @jax.jit
        def step(x):
            return x + 1

        @partial(jax.jit, static_argnames=("k",))
        def megastep(x, k):
            return x * k

        def fit(x):
            f = jax.jit(step)                  # named def reference
            m = jax.jit(jnp.matmul)            # named attribute reference
            return f(x) + m(x, x)
    """})
    assert [f.rule for f in run_lint(pkg) if f.rule == "PRF001"] == []


def test_prf001_suppressible(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import jax

        def fit(b):
            g = jax.jit(lambda x: x + 1)  # graftlint: ok(throwaway probe)
            return g(b)
    """})
    assert [f.rule for f in run_lint(pkg) if f.rule == "PRF001"] == []


# -- env-discipline (ENV) -----------------------------------------------------

def test_env001_import_time_reads_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import os
        from os import environ

        WINDOW_S = float(os.environ.get("H2O3TPU_SCORE_WINDOW_MS", "1")) / 1e3
        TIMEOUT = os.getenv("H2O3TPU_SCORE_TIMEOUT_S", "30")
        BUDGET = environ["H2O3TPU_SERVE_BUDGET_BYTES"]

        class Config:
            slices = int(os.environ.get("H2O3TPU_MESH_SLICES", "1"))

        def serve(window=os.environ.get("H2O3TPU_SCORE_WINDOW_MS")):
            # the DEFAULT evaluates at def time -> import-time capture too
            return window
    """})
    env = [f for f in run_lint(pkg) if f.rule == "ENV001"]
    assert len(env) == 5
    assert {f.detail for f in env} == {
        "import-time-env:H2O3TPU_SCORE_WINDOW_MS",
        "import-time-env:H2O3TPU_SCORE_TIMEOUT_S",
        "import-time-env:H2O3TPU_SERVE_BUDGET_BYTES",
        "import-time-env:H2O3TPU_MESH_SLICES"}


def test_env001_runtime_reads_clean(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import os

        HOME = os.environ.get("HOME")       # not an H2O3TPU_* tunable

        def window_s_from_env():
            # the fix shape: resolved per call, late env changes land
            return float(os.environ.get("H2O3TPU_SCORE_WINDOW_MS", "1")) / 1e3

        class Batcher:
            def __init__(self):
                self.window = window_s_from_env()
                self.budget = os.getenv("H2O3TPU_SERVE_BUDGET_BYTES")

        probe = lambda: os.environ.get("H2O3TPU_SCORE_SLO_MS")
    """})
    assert "ENV001" not in rules_of(run_lint(pkg))


def test_env001_suppressible(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import os

        # graftlint: ok(deliberate one-shot capture - documented)
        FROZEN = os.environ.get("H2O3TPU_SCORE_MAX_BUCKET", "4096")
    """})
    assert "ENV001" not in rules_of(run_lint(pkg))


# -- suppression + baseline --------------------------------------------------

def test_inline_suppression(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(b):
            return b + 1, jnp.sum(b)

        def fit(b):
            for _ in range(10):
                b, dev = step(b)
                d = float(  # graftlint: ok(deliberate convergence fetch)
                    jax.device_get(dev))
            return b, d
    """})
    assert run_lint(pkg) == []


def test_suppression_does_not_leak_to_next_statement(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(b):
            return b + 1, jnp.sum(b), jnp.max(b)

        def fit(b):
            for _ in range(10):
                b, dev, mx = step(b)
                d = float(jax.device_get(dev))  # graftlint: ok(reason)
                e = float(jax.device_get(mx))
            return b, d, e
    """})
    findings = run_lint(pkg)
    # the annotated statement is suppressed; the unannotated one right
    # below it is NOT
    assert [f.rule for f in findings] == ["TRC003"]


def test_baseline_roundtrip(tmp_path):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}

            def put(self, k, v):
                with self._lock:
                    self._data[k] = v

            def drop(self, k):
                self._data.pop(k, None)
    """})
    findings = run_lint(pkg)
    assert findings
    bl_path = tmp_path / "baseline.json"
    save_baseline(bl_path, findings)
    baseline = load_baseline(bl_path)
    new, old = split_findings(run_lint(pkg), baseline)
    assert new == [] and len(old) == len(findings)
    # fingerprints are line-number-free: prepending code must not churn
    src = (pkg / "mod.py").read_text()
    (pkg / "mod.py").write_text("import os\n\n" + src)
    new, old = split_findings(run_lint(pkg), baseline)
    assert new == []
    # but an ADDITIONAL occurrence of the same defect is new
    (pkg / "mod.py").write_text(src.replace(
        "self._data.pop(k, None)",
        "self._data.pop(k, None)\n        self._data.clear()"))
    new, _ = split_findings(run_lint(pkg), baseline)
    assert len(new) == 1


def test_cli_exit_codes(tmp_path, capsys):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import threading

        class Worker:
            def __init__(self):
                self.state = "idle"

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self.state = "running"
    """})
    assert main([str(pkg), "--no-baseline"]) == 1
    bl = tmp_path / "bl.json"
    assert main([str(pkg), "--baseline", str(bl), "--update-baseline"]) == 0
    assert main([str(pkg), "--baseline", str(bl)]) == 0
    out = capsys.readouterr().out
    assert "baselined" in out
    assert main([str(tmp_path / "nope"), "--no-baseline"]) == 2


def test_cli_json_output(tmp_path, capsys):
    pkg = make_pkg(tmp_path, {"mod.py": "x = 1\n"})
    assert main([str(pkg), "--json", "--no-baseline"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["new"] == [] and doc["baselined"] == []
    # per-family wall time: one non-negative number per family run
    assert set(doc["timings"]) == set(FAMILY_NAMES)
    assert all(isinstance(v, float) and v >= 0
               for v in doc["timings"].values())


def test_cli_rules_filter(tmp_path, capsys):
    pkg = make_pkg(tmp_path, {"mod.py": """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.state = "idle"

            def start(self):
                threading.Thread(target=self._run).start()

            def _run(self):
                self.state = "running"
    """})
    # unfiltered: the LCK002 unlocked-shared-state finding is present
    assert main([str(pkg), "--json", "--no-baseline"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert any(f["rule"].startswith("LCK") for f in doc["new"])
    # --rules DLK: the LCK family never runs, and only DLK is timed
    assert main([str(pkg), "--json", "--no-baseline", "--rules", "DLK"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["new"] == []
    assert set(doc["timings"]) == {"DLK"}
    # unknown family name is a usage error, not a silent no-op
    assert main([str(pkg), "--rules", "NOPE", "--no-baseline"]) == 2


# -- lock-order analysis (DLK) -----------------------------------------------

def test_dlk001_three_lock_cycle(tmp_path):
    """A three-lock cycle with one interprocedural hop is detected, and
    the finding carries the full cycle path (ISSUE 18 acceptance)."""
    pkg = make_pkg(tmp_path, {"pipe.py": """
        import threading

        class Pipeline:
            def __init__(self):
                self._head_lock = threading.Lock()
                self._mid_lock = threading.Lock()
                self._tail_lock = threading.Lock()

            def stage_one(self):
                with self._head_lock:
                    with self._mid_lock:
                        pass

            def stage_two(self):
                with self._mid_lock:
                    self._finish()

            def _finish(self):
                with self._tail_lock:
                    pass

            def stage_three(self):
                with self._tail_lock:
                    with self._head_lock:
                        pass
    """})
    findings = run_lint(pkg, families=("DLK",))
    cyc = [f for f in findings if f.rule == "DLK001"]
    assert len(cyc) == 1
    msg = cyc[0].message
    for ident in ("pipe.Pipeline._head_lock", "pipe.Pipeline._mid_lock",
                  "pipe.Pipeline._tail_lock"):
        assert ident in msg
    assert "->" in msg and "cycle" in msg


def test_dlk001_consistent_order_clean(tmp_path):
    """The same locks nested in one consistent global order are not a
    cycle — order discipline, not nesting, is what DLK001 checks."""
    pkg = make_pkg(tmp_path, {"pipe.py": """
        import threading

        class Pipeline:
            def __init__(self):
                self._head_lock = threading.Lock()
                self._tail_lock = threading.Lock()

            def stage_one(self):
                with self._head_lock:
                    with self._tail_lock:
                        pass

            def stage_two(self):
                with self._head_lock:
                    self._finish()

            def _finish(self):
                with self._tail_lock:
                    pass
    """})
    assert [f for f in run_lint(pkg, families=("DLK",))
            if f.rule == "DLK001"] == []


def test_dlk002_blocking_under_lock_flagged(tmp_path):
    """Event-wait, blocking queue get, and an HTTP round-trip (direct or
    through a helper) while a lock is held are each one DLK002."""
    pkg = make_pkg(tmp_path, {"worker.py": """
        import queue
        import threading
        from urllib.request import urlopen

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._done = threading.Event()
                self._q = queue.Queue()

            def bad_wait(self):
                with self._lock:
                    self._done.wait()

            def bad_get(self):
                with self._lock:
                    return self._q.get()

            def bad_http(self):
                with self._lock:
                    return urlopen("http://h/metrics")

            def bad_nested(self):
                with self._lock:
                    self._fetch()

            def _fetch(self):
                return urlopen("http://h/health")
    """})
    hits = [f for f in run_lint(pkg, families=("DLK",))
            if f.rule == "DLK002"]
    wheres = sorted(f.where for f in hits)
    assert wheres == ["Worker.bad_get", "Worker.bad_http",
                      "Worker.bad_nested", "Worker.bad_wait"]
    slugs = {f.where: f.detail.split("-under-")[0] for f in hits}
    assert slugs["Worker.bad_wait"] == "cond-wait"
    assert slugs["Worker.bad_get"] == "queue-get"
    assert slugs["Worker.bad_http"] == "urlopen"
    assert slugs["Worker.bad_nested"] == "urlopen"


def test_dlk002_timeout_loop_clean(tmp_path):
    """The sanctioned coordination shape — condition-wait with a timeout
    on the SAME lock the waiter holds, in a recheck loop — is clean: the
    waiter releasing its own lock while waiting is how conditions work."""
    pkg = make_pkg(tmp_path, {"batcher.py": """
        import threading

        class Batcher:
            def __init__(self):
                self._cond = threading.Condition()
                self._items = []

            def wait_for_batch(self):
                with self._cond:
                    while not self._items:
                        self._cond.wait(timeout=0.25)
                    return list(self._items)
    """})
    assert [f for f in run_lint(pkg, families=("DLK",))
            if f.rule == "DLK002"] == []


def test_dlk003_callback_under_lock(tmp_path):
    """Invoking user-supplied listeners while holding a lock is DLK003;
    registering them under the lock, or snapshotting the list under the
    lock and invoking outside it, is the clean pattern."""
    pkg = make_pkg(tmp_path, {"pub.py": """
        import threading

        class Publisher:
            def __init__(self):
                self._lock = threading.Lock()
                self._listeners = []

            def add_listener(self, cb):
                with self._lock:
                    self._listeners.append(cb)

            def publish_bad(self, event):
                with self._lock:
                    for cb in self._listeners:
                        cb(event)

            def publish_good(self, event):
                with self._lock:
                    pending = list(self._listeners)
                for cb in pending:
                    cb(event)
    """})
    hits = [f for f in run_lint(pkg, families=("DLK",))
            if f.rule == "DLK003"]
    assert [f.where for f in hits] == ["Publisher.publish_bad"]


def test_dlk_suppressible(tmp_path):
    pkg = make_pkg(tmp_path, {"worker.py": """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._done = threading.Event()

            def drain(self):
                with self._lock:
                    self._done.wait(0.1)   # graftlint: ok(drain is shutdown-only, nothing else can want the lock)
    """})
    assert [f for f in run_lint(pkg, families=("DLK",))
            if f.rule == "DLK002"] == []


def test_cli_graph_emits_dot(tmp_path, capsys):
    pkg = make_pkg(tmp_path, {"pipe.py": """
        import threading

        class Pipeline:
            def __init__(self):
                self._head_lock = threading.Lock()
                self._tail_lock = threading.Lock()

            def run(self):
                with self._head_lock:
                    with self._tail_lock:
                        pass
    """})
    assert main([str(pkg), "--graph"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("digraph lockorder")
    assert '"pipe.Pipeline._head_lock" -> "pipe.Pipeline._tail_lock"' in out


def test_cli_prune_baseline(tmp_path, capsys):
    """--prune-baseline drops fingerprints (and their reasons) no current
    finding matches, and keeps live entries with their reasons."""
    pkg = make_pkg(tmp_path, {"mod.py": """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._data = {}

            def put(self, k, v):
                with self._lock:
                    self._data[k] = v

            def drop(self, k):
                self._data.pop(k, None)
    """})
    bl = tmp_path / "bl.json"
    assert main([str(pkg), "--baseline", str(bl), "--update-baseline"]) == 0
    doc = json.loads(bl.read_text())
    live_fp = next(iter(doc["fingerprints"]))
    doc["fingerprints"]["LCK001:gone.py:Gone.stale:attr"] = 2
    doc["reasons"] = {
        live_fp: "documented live reason",
        "LCK001:gone.py:Gone.stale:attr": "stale reason",
    }
    bl.write_text(json.dumps(doc))
    assert main([str(pkg), "--baseline", str(bl), "--prune-baseline"]) == 0
    out = capsys.readouterr().out
    assert "pruned 2 stale" in out
    after = json.loads(bl.read_text())
    assert "LCK001:gone.py:Gone.stale:attr" not in after["fingerprints"]
    assert after["reasons"] == {live_fp: "documented live reason"}
    assert live_fp in after["fingerprints"]
    # and the pruned baseline still accepts the live findings
    assert main([str(pkg), "--baseline", str(bl)]) == 0


# -- the live package --------------------------------------------------------

@pytest.fixture(scope="module")
def live_findings():
    """One full-package scan shared by the meta-tests (the AST walk +
    call-graph build is the expensive part; tier-1 should pay it once)."""
    return run_lint(DEFAULT_BASELINE.parent.parent)   # .../h2o3_tpu


def test_package_has_no_new_findings(live_findings):
    """The repo ships lint-clean: every remaining finding is explicitly
    baselined (h2o3_tpu/tools/baseline.json) or inline-suppressed with a
    reason. A failure here means a NEW tracer-safety / lock-discipline /
    REST-surface violation entered the tree."""
    new, _old = split_findings(live_findings, load_baseline(DEFAULT_BASELINE))
    assert new == [], "new graftlint findings:\n" + "\n".join(
        f.render() for f in new)


def test_package_has_no_prf001_findings(live_findings):
    """Every executable in the live package is attributable: zero PRF001
    findings, baselined or not — the compute observatory (ISSUE 10) relies
    on stable names to credit compiles, FLOPs, and profiler events to
    sites, so anonymous jits don't get grandfathered into the baseline."""
    hits = [f for f in live_findings if f.rule == "PRF001"]
    assert hits == [], "\n".join(f.render() for f in hits)


def test_package_has_no_wtx001_findings(live_findings):
    """Every thread-coordination wait in the live package is bounded: zero
    WTX001 findings, baselined or not — the elastic membership layer
    (ISSUE 12) makes dead workers an EXPECTED event, so an unbounded wait
    anywhere is a deadlock waiting for one; the five pre-existing sites
    were fixed with timeout+recheck loops, not grandfathered."""
    hits = [f for f in live_findings if f.rule == "WTX001"]
    assert hits == [], "\n".join(f.render() for f in hits)


def test_elastic_module_scans_clean(live_findings):
    """The new membership layer ships lint-clean across every rule family
    (ISSUE 12 acceptance: graftlint scans the new module clean)."""
    hits = [f for f in live_findings
            if f.path in ("parallel/elastic.py", "tools/waits.py")]
    assert hits == [], "\n".join(f.render() for f in hits)


def test_slo_serving_modules_scan_clean(live_findings):
    """The SLO serving layer (ISSUE 13) ships lint-clean across every
    rule family — including ENV001, whose bug class (import-time env
    capture) is exactly what serving/slo.py's *_from_env() helpers and
    the batcher's construction-time window exist to avoid."""
    hits = [f for f in live_findings
            if f.path in ("serving/slo.py", "serving/replicas.py",
                          "serving/batcher.py", "serving/service.py",
                          "tools/envs.py")]
    assert hits == [], "\n".join(f.render() for f in hits)


def test_ops_plane_modules_scan_clean(live_findings):
    """The ops plane (ISSUE 15) ships lint-clean across every rule family
    — including MTR001, whose doc-drift contract the new
    h2o3_incidents_total / h2o3_telemetry_rejected_total registrations
    must themselves satisfy."""
    hits = [f for f in live_findings
            if f.path in ("utils/health.py", "utils/incidents.py",
                          "tools/metrics.py")]
    assert hits == [], "\n".join(f.render() for f in hits)


def test_remediation_modules_scan_clean(live_findings):
    """The remediation engine + tenancy layer (ISSUE 16) ships lint-clean
    across every rule family — including ACT001, whose audit contract the
    ops_plane package must itself satisfy (every policy mutation rooted in
    an act_* catalog function, executed only through ActionLog.record)."""
    hits = [f for f in live_findings
            if f.path.startswith("ops_plane/") or f.path == "tools/acts.py"]
    assert hits == [], "\n".join(f.render() for f in hits)


def test_package_has_no_act001_findings(live_findings):
    """Zero ACT001 findings, baselined or not — unaudited automation
    doesn't get grandfathered: the ActionLog is only an audit trail if it
    is the ONLY path from the engine to live policy."""
    hits = [f for f in live_findings if f.rule == "ACT001"]
    assert hits == [], "\n".join(f.render() for f in hits)


def test_package_has_no_mtr001_findings(live_findings):
    """Every h2o3_* metric registered in the live package has a row in
    docs/OBSERVABILITY.md — zero MTR001 findings, baselined or not: the
    metric catalog is the operator contract, and undocumented instruments
    don't get grandfathered."""
    hits = [f for f in live_findings if f.rule == "MTR001"]
    assert hits == [], "\n".join(f.render() for f in hits)


def test_package_fix_targets_stay_clean(live_findings):
    """The hot paths fixed alongside the analyzer must not regress into
    the baseline: no findings at all (baselined or new) in the GLM/GBM/DL
    loops, Job, and the DKV registry."""
    fixed = {"models/glm.py", "models/glm_sparse.py", "models/gbm.py",
             "models/deeplearning.py", "models/job.py", "utils/registry.py"}
    hits = [f for f in live_findings if f.path in fixed]
    assert hits == [], "\n".join(f.render() for f in hits)


def test_package_has_no_dlk001_findings(live_findings):
    """Zero lock-order cycles anywhere, baselined or not — a cycle is a
    deadlock waiting for the right interleaving, and the one live cycle
    the analyzer found (Cleaner.sweep holding DKV._lock across the remove
    cascade into _io_lock, vs fault-in's _io_lock -> DKV._lock) was FIXED
    (KeyedStore.remove(only_if=...)), not grandfathered."""
    hits = [f for f in live_findings if f.rule == "DLK001"]
    assert hits == [], "\n".join(f.render() for f in hits)


def test_thread_heavy_packages_dlk_clean_or_baselined(live_findings):
    """ISSUE 18 satellite: every DLK finding in the thread-heavy packages
    is either absent or explicitly baselined WITH a documented reason —
    an unexplained suppression in serving/ops-plane/elastic/cleaner
    territory is a silenced deadlock."""
    baseline = load_baseline(DEFAULT_BASELINE)
    reasons = load_reasons(DEFAULT_BASELINE)

    def thread_heavy(path):
        return (path.startswith("serving/") or path.startswith("ops_plane/")
                or path in ("parallel/elastic.py", "utils/cleaner.py",
                            "utils/health.py", "utils/flight.py",
                            "utils/incidents.py"))

    for f in live_findings:
        if not f.rule.startswith("DLK") or not thread_heavy(f.path):
            continue
        assert f.fingerprint in baseline, f"unbaselined: {f.render()}"
        assert reasons.get(f.fingerprint, "").strip(), \
            f"baselined without a documented reason: {f.fingerprint}"


def test_dlk_baseline_entries_have_reasons():
    """Every DLK fingerprint in the shipped baseline carries a non-empty
    documented reason (the acceptance bar: baselined == by-design, with
    the invariant written down)."""
    baseline = load_baseline(DEFAULT_BASELINE)
    reasons = load_reasons(DEFAULT_BASELINE)
    dlk = [fp for fp in baseline if fp.startswith("DLK")]
    assert dlk, "expected the triaged DLK002 invariants in the baseline"
    for fp in dlk:
        assert reasons.get(fp, "").strip(), \
            f"DLK baseline entry without a reason: {fp}"


def test_no_stale_baseline_entries(live_findings):
    """ISSUE 18 satellite: zero stale baseline entries — every fingerprint
    count in baseline.json is backed by a live finding, so dead
    suppressions cannot accumulate (`--prune-baseline` is the fix when
    this fails)."""
    stale = stale_entries(load_baseline(DEFAULT_BASELINE), live_findings)
    assert stale == {}, f"stale baseline entries (run --prune-baseline): " \
                        f"{stale}"
