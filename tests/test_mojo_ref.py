"""Reference-format MOJO importer parity (VERDICT r3 missing #1).

Fixtures are REAL reference-generated artifacts committed under
``tests/data/ref_mojo/``:

- ``gbm_variable_importance.zip`` — a 50-tree bernoulli GBM trained by H2O-3
  3.32 on prostate.csv (provenance:
  ``h2o-genmodel/src/test/resources/hex/genmodel/algos/gbm/``); its
  ``experimental/modelDetails.json`` stores the exact training metrics
  (MSE 0.07338612397, logloss 0.26757239086), giving row-identical-strength
  ground truth without a JVM: one mis-routed row among the 380 shifts
  logloss by ~1e-3, nine orders above the asserted tolerance.
- ``glm_model.zip`` — a gaussian GLM with one categorical (7-level CLUSTER),
  mean imputation, mojo v1.00 (provenance: ``.../algos/pipeline/``).
- ``prostate.csv`` — the training data (``h2o-py/h2o/h2o_data/``).
"""

import json
import zipfile

import numpy as np
import pytest

DATA = "tests/data/ref_mojo"
GBM_ZIP = f"{DATA}/gbm_variable_importance.zip"
GLM_ZIP = f"{DATA}/glm_model.zip"

# exact values from the fixture's own experimental/modelDetails.json
GBM_TRAIN_LOGLOSS = 0.2675723908575812
GBM_TRAIN_MSE = 0.07338612397264782
GBM_TRAIN_AUC = 0.9801618150931445


def _prostate_Xy():
    import csv
    with open(f"{DATA}/prostate.csv") as f:
        rows = list(csv.DictReader(f))
    feats = ["AGE", "RACE", "DPROS", "DCAPS", "PSA", "VOL", "GLEASON"]
    X = np.array([[float(r[c]) for c in feats] for r in rows], np.float64)
    y = np.array([int(r["CAPSULE"]) for r in rows])
    return X, y


def test_gbm_ref_mojo_row_identical_scoring():
    """All 380 training rows score to the fixture's own stored training
    metrics at 1e-8 — i.e. the bytecode walk is row-identical."""
    from h2o3_tpu.genmodel.mojo_ref import load_ref_mojo

    m = load_ref_mojo(GBM_ZIP)
    assert (m.algo, m.n_groups, m.family) == ("gbm", 50, "bernoulli")
    X, y = _prostate_Xy()
    p = m.score(X)
    assert p.shape == (380, 2)
    p1 = np.clip(p[:, 1], 1e-15, 1 - 1e-15)
    logloss = float(-np.mean(y * np.log(p1) + (1 - y) * np.log(1 - p1)))
    mse = float(np.mean((y - p[:, 1]) ** 2))
    assert logloss == pytest.approx(GBM_TRAIN_LOGLOSS, abs=1e-8)
    assert mse == pytest.approx(GBM_TRAIN_MSE, abs=1e-8)


def test_gbm_ref_mojo_na_routing():
    """NaN features route through naSplitDir without error and stay valid."""
    from h2o3_tpu.genmodel.mojo_ref import load_ref_mojo

    m = load_ref_mojo(GBM_ZIP)
    X, _ = _prostate_Xy()
    Xna = X[:20].copy()
    Xna[::2, 4] = np.nan            # PSA (the top split feature)
    Xna[1::3, 6] = np.nan           # GLEASON
    p = m.score(Xna)
    assert np.isfinite(p).all()
    assert ((p >= 0) & (p <= 1)).all()
    assert np.allclose(p.sum(axis=1), 1.0, atol=1e-12)


def test_generic_imports_reference_gbm_end_to_end():
    """h2o.import_mojo on a real H2O-3 zip: predict + model_performance."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.genmodel.generic import import_mojo

    X, y = _prostate_Xy()
    cols = {n: X[:, j].astype(np.float32) for j, n in enumerate(
        ["AGE", "RACE", "DPROS", "DCAPS", "PSA", "VOL", "GLEASON"])}
    cols["CAPSULE"] = y.astype(np.float32)
    fr = Frame.from_arrays(cols)

    model = import_mojo(GBM_ZIP)
    assert model.output["source_algo"] == "gbm"
    assert model.response_column == "CAPSULE"
    assert model.response_domain == ("0", "1")

    preds = model.predict(fr)
    assert preds.names == ["predict", "p0", "p1"]
    p1 = preds.vec("p1").to_numpy()
    # wire path is f32; parity at f32 resolution
    pc = np.clip(p1.astype(np.float64), 1e-15, 1 - 1e-15)
    ll = float(-np.mean(y * np.log(pc) + (1 - y) * np.log(1 - pc)))
    assert ll == pytest.approx(GBM_TRAIN_LOGLOSS, abs=1e-5)

    perf = model.model_performance(fr)
    assert float(perf.logloss) == pytest.approx(GBM_TRAIN_LOGLOSS, abs=1e-5)
    # reference AUC uses the 400-bin AUC2 threshold table; ours is exact —
    # agreement only to the binning resolution
    assert float(perf.auc) == pytest.approx(GBM_TRAIN_AUC, abs=3e-3)


def test_glm_ref_mojo_scoring_semantics():
    """GLM v1.00 MOJO: beta layout (cats|nums|intercept), catOffsets
    indexing, and mean imputation — hand-computed per GlmMojoModel.java."""
    from h2o3_tpu.genmodel.mojo_ref import load_ref_mojo

    m = load_ref_mojo(GLM_ZIP)
    assert (m.family, m.link, m.cats, m.nums) == ("gaussian", "identity", 1, 5)
    b = m.beta
    X = np.array([[3, 2.0, 1.0, 15.0, 10.0, 7.0],
                  [np.nan, np.nan, 2.0, 1.4, 0.0, 6.0],     # imputation row
                  [99, 1.0, 1.0, 1.0, 1.0, 1.0]])           # level out of range
    p = m.score(X)
    want0 = b[3] + b[7] * 2.0 + b[8] * 1.0 + b[9] * 15.0 + b[10] * 10.0 \
        + b[11] * 7.0 + b[12]
    want1 = b[int(m.cat_modes[0])] + b[7] * m.num_means[0] + b[8] * 2.0 \
        + b[9] * 1.4 + b[10] * 0.0 + b[11] * 6.0 + b[12]
    want2 = 0.0 + b[7] * 1.0 + b[8] * 1.0 + b[9] * 1.0 + b[10] * 1.0 \
        + b[11] * 1.0 + b[12]   # cat beta skipped when ival >= offset bound
    np.testing.assert_allclose(p, [want0, want1, want2], rtol=0, atol=1e-12)


def test_format_detection():
    from h2o3_tpu.genmodel.mojo_ref import is_reference_mojo

    assert is_reference_mojo(GBM_ZIP)
    assert is_reference_mojo(GLM_ZIP)
    assert not is_reference_mojo(f"{DATA}/prostate.csv")     # not a zip


def test_unsupported_algo_clear_error(tmp_path):
    from h2o3_tpu.genmodel.mojo_ref import load_ref_mojo

    p = tmp_path / "weird.zip"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("model.ini", "[info]\nalgo = svm\nmojo_version = 1.00\n"
                                "n_features = 2\nn_classes = 1\n"
                                "supervised = false\nn_columns = 2\n"
                                "[columns]\na\nb\n[domains]\n")
    with pytest.raises(ValueError, match="svm"):
        load_ref_mojo(str(p))


def test_fixture_metrics_provenance():
    """The asserted ground-truth numbers really are the fixture's own."""
    with zipfile.ZipFile(GBM_ZIP) as z:
        tm = json.loads(z.read("experimental/modelDetails.json"))[
            "output"]["training_metrics"]
    assert tm["logloss"] == GBM_TRAIN_LOGLOSS
    assert tm["MSE"] == GBM_TRAIN_MSE
    assert tm["AUC"] == GBM_TRAIN_AUC


# -- stacked ensemble + kmeans fixtures (round 4) ----------------------------

ENS_ZIP = f"{DATA}/ensemble_binomial.zip"
KMEANS_ZIP = f"{DATA}/kmeans_model.zip"


def _prostate_ens_X(m):
    """Rows encoded through the ENSEMBLE's own domains (RACE/DPROS are
    categorical in this fixture's training frame)."""
    import csv
    with open(f"{DATA}/prostate.csv") as f:
        rows = list(csv.DictReader(f))
    names = m.columns[: m.n_features]
    X = np.zeros((len(rows), m.n_features))
    for j, c in enumerate(names):
        dom = m.domains[j]
        for i, r in enumerate(rows):
            X[i, j] = (dom.index(r[c]) if dom and r[c] in dom
                       else len(dom) if dom else float(r[c]))
    y = np.array([int(r["CAPSULE"]) for r in rows])
    return X, y


def test_stacked_ensemble_ref_mojo():
    """Nested-submodel import (MultiModelMojoReader layout): a GLM
    metalearner over GBM + 2 DRF base models. The fixture was trained on an
    uncommitted 304-row split, so its stored metrics are not reproducible;
    what IS exact: the ensemble must equal the metalearner formula applied
    to the base-model predictions (wiring + per-submodel column remapping),
    and the full-data AUC must reflect a working model. The tree bytecode
    itself is pinned row-identically by the 1.40 GBM fixture above."""
    from h2o3_tpu.genmodel.mojo_ref import load_ref_mojo

    m = load_ref_mojo(ENS_ZIP)
    assert m.algo == "stackedensemble"
    assert [b.algo for b in m.base_models] == ["gbm", "drf", "drf"]
    assert m.metalearner.algo == "glm"
    X, y = _prostate_ens_X(m)
    p = m.score(X)
    assert p.shape == (380, 2)

    # exact internal consistency: metalearner(GLM) over base p1 columns
    base = np.stack([b.score(X[:, mp])[:, 1]
                     for b, mp in zip(m.base_models, m.mappings)], 1)
    want = m.metalearner.score(base)
    np.testing.assert_allclose(p, want, rtol=0, atol=1e-12)

    # model quality: trained on 80% of these rows; must separate well
    order = np.argsort(p[:, 1])
    ranks = np.empty(380)
    ranks[order] = np.arange(1, 381)
    npos = y.sum()
    auc = (ranks[y > 0].sum() - npos * (npos + 1) / 2) / (npos * (380 - npos))
    assert auc > 0.9, auc


def test_drf_submodel_sane():
    """The DRF path (average of per-tree votes, binomial complement —
    DrfMojoModel.java:38-50) on a real reference DRF artifact."""
    import zipfile as zf

    from h2o3_tpu.genmodel.mojo_ref import _load_from_zip, load_ref_mojo

    with zf.ZipFile(ENS_ZIP) as z:
        drf = _load_from_zip(z, "models/DRF/DRF_model_R_1510601497952_1131/")
    assert drf.algo == "drf" and drf.n_groups == 30
    m = load_ref_mojo(ENS_ZIP)
    X, y = _prostate_ens_X(m)
    p = drf.score(X[:, m.mappings[1]])
    assert ((p >= 0) & (p <= 1)).all()
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-12)
    # directional sanity: higher p1 for positives on average
    assert p[y > 0, 1].mean() > p[y == 0, 1].mean() + 0.15


def test_kmeans_ref_mojo():
    from h2o3_tpu.genmodel.mojo_ref import load_ref_mojo

    km = load_ref_mojo(KMEANS_ZIP)
    assert km.algo == "kmeans" and km.standardize
    k, nf = km.centers.shape
    assert nf == 2 and list(km.is_cat) == [False, True]    # AGE + cat RACE
    rng = np.random.default_rng(5)
    X = np.stack([rng.normal(66, 8, 200),
                  rng.integers(0, 3, 200).astype(float)], 1)
    cl = km.score(X)
    assert cl.shape == (200,)
    assert set(np.unique(cl)) <= set(range(k))
    # assignment really is nearest-center: standardized Euclidean on AGE,
    # 0/1 mismatch on the categorical RACE (GenModel.KMeans_distance)
    a = (X[:, 0] - km.means[0]) * km.mults[0]
    d2 = ((a[:, None] - km.centers[None, :, 0]) ** 2
          + (X[:, 1][:, None] != km.centers[None, :, 1]))
    np.testing.assert_array_equal(cl, np.argmin(d2, axis=1))


def test_isolation_forest_ref_mojo(tmp_path):
    """IsolationForest import, validated against a HAND-ASSEMBLED artifact:
    the tree blobs are built byte-by-byte per the writer format
    (nodeType/colId/naSplitDir/split + inline leaf floats, little-endian),
    so the decoder and the (max-sum)/(max-min) score normalization
    (IsolationForestMojoModel.java:27-42) are checked independently."""
    import struct

    from h2o3_tpu.genmodel.mojo_ref import load_ref_mojo

    def split_node(col, thresh, left_leaf, right_leaf):
        # nodeType 0x70: lmask=48 (left child is an inline leaf float),
        # rmask bit -> right child is an inline leaf float; NA goes left (2)
        return (struct.pack("<BHB", 0x70, col, 2)
                + struct.pack("<f", thresh)
                + struct.pack("<f", left_leaf)
                + struct.pack("<f", right_leaf))

    ini = "\n".join([
        "[info]", "algo = isolationforest", "mojo_version = 1.30",
        "category = AnomalyDetection", "supervised = false",
        "n_features = 2", "n_classes = 1", "n_columns = 2", "n_domains = 0",
        "n_trees = 2", "n_trees_per_class = 1",
        "min_path_length = 2", "max_path_length = 8",
        "default_threshold = 0.5",
        "[columns]", "f0", "f1", "[domains]", ""])
    p = tmp_path / "isofor.zip"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("model.ini", ini)
        z.writestr("trees/t00_000.bin", split_node(0, 0.5, 2.0, 3.0))
        z.writestr("trees/t00_001.bin", split_node(1, 0.0, 1.0, 4.0))

    m = load_ref_mojo(str(p))
    assert m.algo == "isolationforest" and m.n_groups == 2
    X = np.array([[0.0, -1.0],      # left (2.0) + left (1.0)  -> sum 3
                  [1.0, 1.0],       # right (3.0) + right (4.0) -> sum 7
                  [np.nan, 1.0]])   # NA left (2.0) + right (4.0) -> sum 6
    out = m.score(X)
    np.testing.assert_allclose(out[:, 0], [(8 - 3) / 6, (8 - 7) / 6,
                                           (8 - 6) / 6], atol=1e-12)
    np.testing.assert_allclose(out[:, 1], [1.5, 3.5, 3.0], atol=1e-12)


def test_isolation_forest_through_generic_wrapper(tmp_path):
    """The real user path: h2o.import_mojo -> predict gives the artifact's
    own [predict, mean_length] frame; _score_raw stays 1-D per the Model
    contract (code-review finding)."""
    import struct

    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.genmodel.generic import import_mojo

    def split_node(col, thresh, left_leaf, right_leaf):
        return (struct.pack("<BHB", 0x70, col, 2)
                + struct.pack("<f", thresh)
                + struct.pack("<f", left_leaf)
                + struct.pack("<f", right_leaf))

    ini = "\n".join([
        "[info]", "algo = isolationforest", "mojo_version = 1.30",
        "category = AnomalyDetection", "supervised = false",
        "n_features = 2", "n_classes = 1", "n_columns = 2", "n_domains = 0",
        "n_trees = 1", "n_trees_per_class = 1",
        "min_path_length = 1", "max_path_length = 4",
        "[columns]", "f0", "f1", "[domains]", ""])
    p = tmp_path / "iso.zip"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("model.ini", ini)
        z.writestr("trees/t00_000.bin", split_node(0, 0.5, 1.0, 3.0))

    model = import_mojo(str(p))
    fr = Frame.from_arrays({"f0": np.float32([0.0, 1.0]),
                            "f1": np.float32([0.0, 0.0])})
    out = model.predict(fr)
    assert out.names == ["predict", "mean_length"]
    np.testing.assert_allclose(out.vec("predict").to_numpy(),
                               [(4 - 1) / 3, (4 - 3) / 3], atol=1e-6)
    raw = np.asarray(model._score_raw(fr))
    assert raw.ndim == 1                       # Model contract


def test_multinomial_ensemble_ref_mojo():
    """Multinomial SE import: GLM-multinomial metalearner (flat per-class
    beta blocks, GlmMultinomialMojoModel.glmScore0) over per-class base
    probabilities; wiring asserted exact against the formula."""
    import csv

    from h2o3_tpu.genmodel.mojo_ref import load_ref_mojo

    m = load_ref_mojo(f"{DATA}/ensemble_multinomial.zip")
    assert m.nclasses == 3 and m.metalearner.family == "multinomial"

    with open(f"{DATA}/prostate.csv") as f:
        rows = list(csv.DictReader(f))
    names = m.columns[: m.n_features]
    X = np.zeros((len(rows), m.n_features))
    for j, c in enumerate(names):
        dom = m.domains[j]
        for i, r in enumerate(rows):
            X[i, j] = (dom.index(r[c]) if dom and r[c] in dom
                       else len(dom) if dom else float(r[c]))
    p = m.score(X)
    assert p.shape == (380, 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-12)

    # exact wiring: per-class base probs -> metalearner softmax
    K = 3
    base = np.zeros((380, len(m.base_models) * K))
    for i, (b, mp) in enumerate(zip(m.base_models, m.mappings)):
        base[:, i * K:(i + 1) * K] = b.score(X[:, mp])
    np.testing.assert_allclose(p, m.metalearner.score(base),
                               rtol=0, atol=1e-12)

    # independent arithmetic for the metalearner on one row: eta_c =
    # beta[c*P : (c+1)*P] over [nums | intercept] (cats=0 in this fixture)
    g = m.metalearner
    P = len(g.beta) // K
    row = base[7]
    eta = np.array([g.beta[c * P: c * P + len(row)] @ row
                    + g.beta[(c + 1) * P - 1] for c in range(K)])
    want = np.exp(eta - eta.max())
    want /= want.sum()
    np.testing.assert_allclose(p[7], want, atol=1e-12)


def test_multinomial_glm_with_categoricals(tmp_path):
    """The categorical branch of multinomial GLM scoring (level-0 skip,
    catOffsets shift, per-class beta blocks) against hand arithmetic —
    the committed fixture has cats=0, so this path needs its own artifact."""
    from h2o3_tpu.genmodel.mojo_ref import load_ref_mojo

    # 1 categorical (3 levels, use_all_factor_levels), 1 numeric, 3 classes:
    # P = 3 (cat) + 1 (num) + 1 (intercept) = 5; beta = 3 blocks of 5
    beta = [0.1, 0.2, 0.3, 1.0, -0.5,     # class 0
            0.4, 0.5, 0.6, -1.0, 0.25,    # class 1
            0.0, 0.7, 0.8, 0.5, 0.0]      # class 2
    ini = "\n".join([
        "[info]", "algo = glm", "mojo_version = 1.00",
        "category = Multinomial", "supervised = true",
        "n_features = 2", "n_classes = 3", "n_columns = 3", "n_domains = 2",
        "family = multinomial", "link = multinomial",
        "use_all_factor_levels = true", "cats = 1",
        "cat_offsets = [0, 3]", "nums = 1", "mean_imputation = false",
        f"beta = {beta}",
        "[columns]", "c", "x", "y",
        "[domains]", "0: 3 d000.txt", "2: 3 d001.txt", ""])
    p = tmp_path / "glm_multi.zip"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("model.ini", ini)
        z.writestr("domains/d000.txt", "a\nb\nc\n")
        z.writestr("domains/d001.txt", "r0\nr1\nr2\n")

    m = load_ref_mojo(str(p))
    X = np.array([[0.0, 2.0],     # level a
                  [2.0, -1.0],    # level c
                  [7.0, 1.0]])    # out-of-range level -> cat beta skipped
    got = m.score(X)
    B = np.array(beta).reshape(3, 5)
    for r, (lvl, xnum) in enumerate([(0, 2.0), (2, -1.0), (None, 1.0)]):
        eta = np.array([(B[k, lvl] if lvl is not None else 0.0)
                        + B[k, 3] * xnum + B[k, 4] for k in range(3)])
        want = np.exp(eta - eta.max())
        want /= want.sum()
        np.testing.assert_allclose(got[r], want, atol=1e-12, err_msg=str(r))
