"""Frame/Vec/rollups tests (reference: h2o-core fvec tests, ``VecTest.java``,
``RollupStatsTest.java`` semantics)."""

import numpy as np
import pytest

import jax

from h2o3_tpu import Frame, Vec, VecType
from h2o3_tpu.frame.parse import parse_raw
from h2o3_tpu.frame.vec import padded_len


def test_cloud_size():
    assert len(jax.devices()) == 8  # virtual cloud formed


def test_vec_from_numpy_numeric():
    v = Vec.from_numpy(np.array([1.0, 2.5, np.nan, 4.0]))
    assert v.type is VecType.NUM
    assert v.nrows == 4
    assert v.plen == padded_len(4)
    np.testing.assert_allclose(v.to_numpy()[:2], [1.0, 2.5])


def test_rollups_match_numpy(rng):
    x = rng.normal(size=1000).astype(np.float32)
    x[::17] = np.nan
    v = Vec.from_numpy(x)
    r = v.rollups()
    valid = x[~np.isnan(x)]
    assert r.na_cnt == int(np.isnan(x).sum())
    np.testing.assert_allclose(r.min, valid.min(), rtol=1e-6)
    np.testing.assert_allclose(r.max, valid.max(), rtol=1e-6)
    np.testing.assert_allclose(r.mean, valid.mean(), rtol=1e-5)
    np.testing.assert_allclose(r.sigma, valid.std(ddof=1), rtol=1e-4)
    assert not r.is_int


def test_rollups_int_detection():
    v = Vec.from_numpy(np.array([1, 2, 3, 4, 5]))
    assert v.type is VecType.INT
    assert v.rollups().is_int
    assert v.rollups().nzero == 0
    assert v.mean() == 3.0


def test_categorical_domain_sorted():
    v = Vec.from_numpy(np.array(["b", "a", "c", "a", None], dtype=object))
    assert v.type is VecType.CAT
    assert v.domain == ("a", "b", "c")
    assert v.cardinality() == 3
    codes = v.to_numpy()
    np.testing.assert_array_equal(codes, [1, 0, 2, 0, -1])
    assert v.na_cnt() == 1


def test_frame_from_arrays_and_matrix(rng):
    f = Frame.from_arrays({
        "x": rng.normal(size=100),
        "y": np.arange(100),
        "c": np.array(["a", "b"] * 50, dtype=object),
    })
    assert f.shape == (100, 3)
    assert f.types == {"x": "real", "y": "int", "c": "enum"}
    m = f.matrix(["x", "y"])
    assert m.shape == (f.plen, 2)
    mask = np.asarray(jax.device_get(f.row_mask()))
    assert mask.sum() == 100


def test_frame_column_ops(rng):
    f = Frame.from_arrays({"a": np.arange(10), "b": np.arange(10) * 2.0})
    sub = f[["b"]]
    assert sub.names == ["b"]
    f.add("c", Vec.from_numpy(np.ones(10)))
    assert f.ncols == 3
    f.remove("a")
    assert f.names == ["b", "c"]
    with pytest.raises(KeyError):
        f.vec("nope")


def test_parse_raw_csv():
    f = parse_raw("a,b,c\n1,2.5,x\n2,,y\n3,1.5,x\n")
    assert f.shape == (3, 3)
    assert f.types["a"] == "int"
    assert f.types["b"] == "real"
    assert f.types["c"] == "enum"
    assert f.vec("b").na_cnt() == 1


def test_to_pandas_roundtrip():
    f = parse_raw("num,cat\n1.5,dog\n2.5,cat\n,dog\n")
    df = f.to_pandas()
    assert df["cat"].tolist() == ["dog", "cat", "dog"]
    assert np.isnan(df["num"].iloc[2])


def test_vec_sharding_spans_devices(rng):
    v = Vec.from_numpy(rng.normal(size=640))
    devs = {s.device for s in v.data.addressable_shards}
    assert len(devs) == 8  # rows actually distributed across the virtual cloud


def test_time_column_roundtrip():
    """TIME precision: epoch ms overflow float32, so exact values live host-side
    and device data is offset-shifted (review finding regression test)."""
    import pandas as pd
    df = pd.DataFrame({"t": pd.to_datetime(
        ["2026-07-29 12:00:00.123", "2026-07-29 12:00:01.456", None])})
    f = Frame.from_pandas(df)
    assert f.types["t"] == "time"
    out = f.to_pandas()["t"]
    assert out.iloc[0] == pd.Timestamp("2026-07-29 12:00:00.123")
    assert pd.isna(out.iloc[2])
    rel = np.asarray(jax.device_get(f.vec("t").data))[:2]
    np.testing.assert_allclose(rel, [0.0, 1333.0])


def test_sigma_large_mean(rng):
    """float32 naive sum-of-squares would give ~3x error here (review finding)."""
    v = Vec.from_numpy(rng.normal(10000.0, 1.0, 10000))
    assert abs(v.sigma() - 1.0) < 0.05


def test_datetime_via_from_numpy():
    """Vec.from_numpy on raw datetime64 must hit the TIME path (review regression)."""
    v = Vec.from_numpy(np.array(["2020-01-01", "2020-01-02"], dtype="datetime64[ns]"))
    assert v.type is VecType.TIME
    ms = v.to_numpy()
    assert ms[1] - ms[0] == 86400_000.0


def test_frame_add_duplicate_rejected(rng):
    f = Frame.from_arrays({"a": np.arange(5)})
    with pytest.raises(ValueError, match="duplicate"):
        f.add("a", Vec.from_numpy(np.arange(5)))


def test_arff_parse(tmp_path):
    """Reference: water/parser/ARFFParser — typed header + CSV data."""
    p = tmp_path / "weather.arff"
    p.write_text("""% comment
@relation weather
@attribute outlook {sunny, overcast, rainy}
@attribute temperature numeric
@attribute windy {TRUE, FALSE}
@attribute play {yes, no}
@data
sunny,85,FALSE,no
overcast,83,FALSE,yes
rainy,70,TRUE,?
""")
    from h2o3_tpu.frame.parse import import_file
    fr = import_file(str(p))
    assert fr.names == ["outlook", "temperature", "windy", "play"]
    assert fr.vec("outlook").domain == ("sunny", "overcast", "rainy")
    assert fr.vec("temperature").is_numeric
    assert float(fr.vec("temperature").mean()) == pytest.approx((85+83+70)/3)
    lab = fr.vec("play").labels()
    assert list(lab) == ["no", "yes", None]


def test_import_file_uri_routing(tmp_path):
    """PersistManager-style scheme dispatch: gated cloud backends raise
    informative errors; file:// works."""
    from h2o3_tpu.frame.parse import import_file
    p = tmp_path / "d.csv"
    p.write_text("a,b\n1,2\n3,4\n")
    fr = import_file(f"file://{p}")
    assert fr.nrows == 2
    # cloud schemes route to the real backends (persist/cloud.py) which
    # demand credentials up front rather than failing mid-transfer
    with pytest.raises(ValueError, match="credentials"):
        import_file("s3://bucket/x.csv")
    with pytest.raises(ValueError, match="unknown URI scheme"):
        import_file("ftp://host/x.csv")
