"""Persistence tests: model/frame round-trips, CSV export, grid recovery
(reference test model: ``h2o-py/tests/testdir_misc/pyunit_save_load_model.py``,
``h2o-core/src/test/java/hex/faulttolerance/``)."""

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import Frame
from h2o3_tpu.models import GBM, GLM
from h2o3_tpu.orchestration import GridSearch
from h2o3_tpu.persist import Recovery


def _frame(rng, n=600):
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - 0.5 * X[:, 1] + 0.2 * rng.normal(size=n)) > 0
    return Frame.from_arrays({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
        "g": rng.choice(["u", "v"], size=n),
        "y": np.array(["yes" if t else "no" for t in y], dtype=object),
    })


def test_frame_roundtrip(rng, tmp_path):
    f = _frame(rng)
    h2o.save_frame(f, str(tmp_path / "snap"))
    g = h2o.load_frame(str(tmp_path / "snap"), key="restored")
    assert g.names == f.names and g.nrows == f.nrows
    np.testing.assert_allclose(g.vec("a").to_numpy(), f.vec("a").to_numpy())
    assert g.vec("g").domain == f.vec("g").domain
    np.testing.assert_array_equal(g.vec("g").to_numpy(), f.vec("g").to_numpy())
    assert h2o.DKV.get("restored") is g


def test_frame_roundtrip_time_str(tmp_path):
    from h2o3_tpu.frame.types import VecType
    ts = np.array(["2024-01-01T00:00:00", "2024-06-15T12:30:00"],
                  dtype="datetime64[ms]")
    f = Frame.from_arrays({"t": ts}, types={"t": VecType.TIME})
    f.add("s", h2o.Vec(None, VecType.STR, 2,
                       host_values=np.array(["hello", None], dtype=object)))
    h2o.save_frame(f, str(tmp_path / "snap"))
    g = h2o.load_frame(str(tmp_path / "snap"))
    np.testing.assert_allclose(g.vec("t").to_numpy(), f.vec("t").to_numpy())
    assert g.vec("s").host_values.tolist() == ["hello", None]


def test_export_csv(rng, tmp_path):
    f = _frame(rng, n=50)
    p = str(tmp_path / "out.csv")
    h2o.export_file(f, p)
    g = h2o.import_file(p)
    assert g.nrows == 50
    np.testing.assert_allclose(g.vec("a").to_numpy(),
                               f.vec("a").to_numpy(), rtol=1e-5)


def test_model_roundtrip_glm(rng, tmp_path):
    f = _frame(rng)
    m = GLM(family="binomial").train(y="y", training_frame=f)
    p = h2o.save_model(m, str(tmp_path / "glm.bin"))
    h2o.DKV.clear()
    m2 = h2o.load_model(p)
    assert m2.key == m.key
    np.testing.assert_allclose(
        np.asarray(m2._score_raw(f)), np.asarray(m._score_raw(f)), atol=1e-6)
    assert h2o.DKV.get(m.key) is m2
    c1, c2 = m.coef(), m2.coef()
    assert c1.keys() == c2.keys()


def test_model_roundtrip_gbm(rng, tmp_path):
    f = _frame(rng)
    m = GBM(ntrees=5, max_depth=3).train(y="y", training_frame=f)
    p = h2o.save_model(m, str(tmp_path / "gbm.bin"))
    m2 = h2o.load_model(p)
    pred1 = m.predict(f).vec("pyes").to_numpy()
    pred2 = m2.predict(f).vec("pyes").to_numpy()
    np.testing.assert_allclose(pred1, pred2, atol=1e-6)


def test_grid_recovery_resume(rng, tmp_path):
    f = _frame(rng, n=400)
    rdir = str(tmp_path / "rec")
    hyper = {"max_depth": [2, 3, 4]}

    # simulate a crash after 2 models: budget cuts the first run short
    gs1 = GridSearch(GBM, hyper, grid_id="g1", recovery_dir=rdir,
                     search_criteria={"max_models": 2}, ntrees=3)
    g1 = gs1.train(y="y", training_frame=f)
    assert len(g1.models) == 2

    # "restart": a new search over the same dir resumes, skipping built points
    gs2 = GridSearch(GBM, hyper, grid_id="g1", recovery_dir=rdir, ntrees=3)
    g2 = gs2.train(y="y", training_frame=f)
    assert len(g2.models) == 3
    depths = sorted(m.output["hyper_values"]["max_depth"] for m in g2.models)
    assert depths == [2, 3, 4]

    rec = Recovery(rdir)
    assert not rec.resuming   # done() marked complete


def test_grid_recovery_resume_parallel(rng, tmp_path):
    """Recovery + overlapped builds (round 4): a budget-stopped parallel
    grid resumes under parallelism — including a resumed run whose
    max_models budget must count the RECOVERED models (the parallel gate's
    len(models) + in-flight accounting) — and completes the space once."""
    f = _frame(rng, n=400)
    rdir = str(tmp_path / "recp")
    hyper = {"max_depth": [2, 3, 4, 5]}

    gs1 = GridSearch(GBM, hyper, grid_id="gp", recovery_dir=rdir,
                     search_criteria={"max_models": 2}, parallelism=2,
                     ntrees=3)
    g1 = gs1.train(y="y", training_frame=f)
    assert len(g1.models) == 2

    # resume UNDER a budget: 2 recovered + at most 1 new build
    gs2 = GridSearch(GBM, hyper, grid_id="gp", recovery_dir=rdir,
                     search_criteria={"max_models": 3}, parallelism=3,
                     ntrees=3)
    g2 = gs2.train(y="y", training_frame=f)
    assert len(g2.models) == 3

    gs3 = GridSearch(GBM, hyper, grid_id="gp", recovery_dir=rdir,
                     parallelism=3, ntrees=3)
    g3 = gs3.train(y="y", training_frame=f)
    assert len(g3.models) == 4
    depths = sorted(m.output["hyper_values"]["max_depth"] for m in g3.models)
    assert depths == [2, 3, 4, 5]
    assert not Recovery(rdir).resuming
