"""GLM completions: offset_column, ordinal family, interactions.

Reference: GLMModel.GLMParameters (_offset, Family.ordinal, _interactions).
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.glm import GLM


def test_glm_offset_column(rng):
    n = 800
    x = rng.normal(size=n).astype(np.float32)
    off = rng.normal(size=n).astype(np.float32) * 2
    logit = 1.5 * x + off + 0.2
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    fr = Frame.from_arrays({
        "x": x, "off": off,
        "y": np.array(["no", "yes"], dtype=object)[y]})

    m = GLM(family="binomial", offset_column="off", lambda_=0.0).train(
        y="y", training_frame=fr)
    # offset must NOT be a feature; slope recovered near truth
    assert m.output["coef_names"] == ["x"]
    assert m.coef()["x"] == pytest.approx(1.5, abs=0.3)

    # without the offset the slope absorbs nothing of it (weaker fit)
    fr2 = Frame.from_arrays({
        "x": x, "y": np.array(["no", "yes"], dtype=object)[y]})
    m2 = GLM(family="binomial", lambda_=0.0).train(y="y", training_frame=fr2)
    assert m.model_performance(fr).logloss < m2.model_performance(fr2).logloss

    # scoring without the offset column fails loudly
    with pytest.raises(ValueError, match="offset"):
        m.predict(fr2)


def test_glm_ordinal_family(rng):
    n = 1500
    x1 = rng.normal(size=n).astype(np.float32)
    x2 = rng.normal(size=n).astype(np.float32)
    latent = 2.0 * x1 - 1.0 * x2 + rng.logistic(size=n)
    codes = np.digitize(latent, [-1.5, 1.5])     # 3 ordered levels
    fr = Frame.from_arrays({
        "x1": x1, "x2": x2,
        "y": np.array(["l0_low", "l1_mid", "l2_high"], dtype=object)[codes]})

    m = GLM(family="ordinal", standardize=False, max_iterations=50).train(
        y="y", training_frame=fr)
    # proportional-odds slopes match the generating model
    c = dict(zip(m.output["coef_names"], np.asarray(m.output["beta"])))
    assert c["x1"] == pytest.approx(2.0, abs=0.4)
    assert c["x2"] == pytest.approx(-1.0, abs=0.35)
    th = np.asarray(m.output["ordinal_theta"])
    assert th[0] < th[1]                          # ordered thresholds

    pred = m.predict(fr)
    assert pred.vec("predict").domain == ("l0_low", "l1_mid", "l2_high")
    probs = np.stack([pred.vec(f"p{d}").to_numpy()
                      for d in ("l0_low", "l1_mid", "l2_high")], 1)
    np.testing.assert_allclose(probs.sum(1), 1.0, atol=1e-4)
    acc = (pred.vec("predict").to_numpy() == codes).mean()
    assert acc > 0.6, acc

    with pytest.raises(ValueError, match="3 ordered"):
        GLM(family="ordinal").train(y="y", training_frame=Frame.from_arrays({
            "x": x1, "y": np.array(["a", "b"], dtype=object)[codes.clip(0, 1)]}))


def test_glm_interactions(rng):
    n = 1200
    a = rng.normal(size=n).astype(np.float32)
    b = rng.normal(size=n).astype(np.float32)
    y = (1.0 * a + 0.5 * b + 2.0 * a * b
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays({"a": a, "b": b, "y": y})

    plain = GLM(family="gaussian").train(y="y", training_frame=fr)
    inter = GLM(family="gaussian", interactions=["a", "b"]).train(
        y="y", training_frame=fr)
    assert "a_b" in inter.output["coef_names"]
    assert inter.coef()["a_b"] == pytest.approx(2.0, abs=0.1)
    # interaction model fits what the additive model cannot
    assert inter.model_performance(fr).rmse < 0.5 * plain.model_performance(fr).rmse

    # scoring re-applies the expansion transparently
    pred = inter.predict(fr).vec("predict").to_numpy()
    assert np.sqrt(np.mean((pred - y) ** 2)) < 0.2


def test_glm_cat_num_interaction(rng):
    n = 900
    g = rng.choice(["u", "v"], size=n)
    x = rng.normal(size=n).astype(np.float32)
    slope = np.where(g == "u", 2.0, -1.0)
    y = (slope * x + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays({"g": g, "x": x, "y": y})

    m = GLM(family="gaussian", interactions=["g", "x"]).train(
        y="y", training_frame=fr)
    pred = m.predict(fr).vec("predict").to_numpy()
    assert np.sqrt(np.mean((pred - y) ** 2)) < 0.2


def test_glm_interaction_scoring_missing_level(rng):
    """A scoring batch that lacks a training level must still produce every
    interaction design column (review regression)."""
    n = 600
    g = rng.choice(["a", "b", "c"], size=n)
    x = rng.normal(size=n).astype(np.float32)
    slope = {"a": 2.0, "b": -1.0, "c": 0.5}
    y = (np.array([slope[s] for s in g]) * x
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays({"g": g, "x": x, "y": y})
    m = GLM(family="gaussian", interactions=["g", "x"]).train(
        y="y", training_frame=fr)

    sub = Frame.from_arrays({           # only levels a, b present
        "g": np.array(["a", "b", "a"], dtype=object),
        "x": np.float32([1.0, 1.0, -2.0])})
    pred = m.predict(sub).vec("predict").to_numpy()
    np.testing.assert_allclose(pred, [2.0, -1.0, -4.0], atol=0.3)


def test_glm_ordinal_rejects_interactions():
    with pytest.raises(ValueError, match="ordinal"):
        GLM(family="ordinal", interactions=["a", "b"]).train(
            y="y", training_frame=Frame.from_arrays({
                "a": np.float32([1, 2, 3, 4]), "b": np.float32([1, 2, 3, 4]),
                "y": np.array(["l0", "l1", "l2", "l0"], dtype=object)}))
