"""R client slice (reference: h2o-r/h2o-package/R/).

With Rscript in the image the real package runs end-to-end; without it, the
contract test replays the exact HTTP/1.1 byte sequences the R client emits
(hand-rolled socket HTTP, urlencoded bodies) so the server-side contract is
pinned either way.
"""

import os
import shutil
import socket
import subprocess
import sys
import urllib.parse

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api import H2OServer
from h2o3_tpu.utils.registry import DKV

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def server():
    s = H2OServer(port=0).start()
    yield s
    s.stop()


def _csv(tmp_path, rng, n=400):
    x = rng.normal(size=(n, 3))
    y = np.where(x[:, 0] - x[:, 1] > 0, "yes", "no")
    lines = ["a,b,c,y"] + [f"{r[0]:.4f},{r[1]:.4f},{r[2]:.4f},{lbl}"
                           for r, lbl in zip(x, y)]
    p = tmp_path / "r_train.csv"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


@pytest.mark.skipif(shutil.which("Rscript") is None, reason="no R in image")
def test_r_client_end_to_end(server, tmp_path, rng):
    csv = _csv(tmp_path, rng)
    proc = subprocess.run(
        ["Rscript", os.path.join(REPO, "clients", "r", "run_smoke.R"),
         server.url, csv],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "R_CLIENT_SMOKE_OK" in proc.stdout


def _raw_http(server, method, path, body=None):
    """Byte-for-byte what clients/r/h2o3tpu .http() sends."""
    payload = ""
    ctype = ""
    if body is not None:
        payload = urllib.parse.urlencode(body)
        ctype = "Content-Type: application/x-www-form-urlencoded\r\n"
    req = (f"{method} {path} HTTP/1.1\r\n"
           f"Host: {server.host}:{server.port}\r\n"
           "Connection: close\r\n" + ctype +
           f"Content-Length: {len(payload.encode())}\r\n\r\n{payload}")
    with socket.create_connection((server.host, server.port)) as sk:
        sk.sendall(req.encode())
        chunks = []
        while True:
            b = sk.recv(65536)
            if not b:
                break
            chunks.append(b)
    resp = b"".join(chunks).decode()
    head, _, body_txt = resp.partition("\r\n\r\n")
    status = int(head.split(" ")[1])
    import json
    try:
        return status, json.loads(body_txt)
    except json.JSONDecodeError:
        return status, body_txt


def test_r_wire_contract(server, tmp_path, rng):
    """The exact request sequence run_smoke.R performs, over raw sockets."""
    csv = _csv(tmp_path, rng)

    st, cloud = _raw_http(server, "GET", "/3/Cloud")
    assert st == 200 and cloud["cloud_healthy"]

    st, imp = _raw_http(server, "POST", "/3/ImportFiles",
                        {"path": csv, "destination_frame": "r_train"})
    assert st == 200 and imp["destination_frames"] == ["r_train"]

    st, split = _raw_http(server, "POST", "/3/SplitFrame",
                          {"dataset": "r_train", "ratios": "[0.8]",
                           "destination_frames": '["r_tr","r_te"]'})
    assert st == 200
    # poll like .poll_job
    import time
    for _ in range(100):
        st, job = _raw_http(server, "GET",
                            f"/3/Jobs/{split['key']['name']}")
        if job["jobs"][0]["status"] == "DONE":
            break
        time.sleep(0.1)
    assert job["jobs"][0]["status"] == "DONE"

    st, tr = _raw_http(server, "POST", "/3/ModelBuilders/gbm",
                       {"training_frame": "r_tr", "response_column": "y",
                        "ntrees": 5, "max_depth": 3})
    assert st == 200
    jkey = tr["job"]["key"]["name"]
    for _ in range(300):
        st, job = _raw_http(server, "GET", f"/3/Jobs/{jkey}")
        if job["jobs"][0]["status"] in ("DONE", "FAILED"):
            break
        time.sleep(0.2)
    assert job["jobs"][0]["status"] == "DONE", job
    model_id = job["jobs"][0]["dest"]["name"]

    st, mm = _raw_http(server, "POST",
                       f"/3/ModelMetrics/models/{model_id}/frames/r_te")
    assert st == 200 and mm["model_metrics"][0]["auc"] > 0.7

    st, pred = _raw_http(server, "POST",
                         f"/3/Predictions/models/{model_id}/frames/r_te")
    assert st == 200
    pkey = pred["predictions_frame"]["name"]
    st, fr = _raw_http(server, "GET", f"/3/Frames/{pkey}")
    labels = [c["label"] for c in fr["frames"][0]["columns"]]
    assert "predict" in labels

    st, _ = _raw_http(server, "DELETE", "/3/DKV")
    assert st == 200
    assert "r_tr" not in DKV


def test_r_package_sources_complete():
    """The shipped package exports every verb the smoke script uses."""
    pkg = os.path.join(REPO, "clients", "r", "h2o3tpu")
    ns = open(os.path.join(pkg, "NAMESPACE")).read()
    code = open(os.path.join(pkg, "R", "h2o3tpu.R")).read()
    for fn in ("h2o.init", "h2o.connect", "h2o.importFile", "h2o.gbm",
               "h2o.glm", "h2o.predict", "h2o.performance", "h2o.splitFrame",
               "h2o.auc", "h2o.removeAll", "h2o.compute",
               "h2o.profilerCapture", "h2o.profilerCaptures",
               "h2o.workers", "h2o.health", "h2o.incidents", "h2o.incident",
               "h2o.diagnosticsBundle"):
        assert f"export({fn})" in ns, fn
        assert f"{fn} <- function" in code, fn


def _poll(server, key, tries=300, delay=0.2):
    import time
    for _ in range(tries):
        st, job = _raw_http(server, "GET", f"/3/Jobs/{key}")
        if job["jobs"][0]["status"] in ("DONE", "FAILED"):
            return job["jobs"][0]
        time.sleep(delay)
    raise TimeoutError(key)


def test_r_wire_contract_round3(server, tmp_path, rng):
    """Round-3 R verbs (VERDICT r2 item 9): xgboost, grid, automl +
    leaderboard, saveModel/loadModel, stackedEnsemble — exact byte
    sequences the R package emits."""
    csv = _csv(tmp_path, rng)
    st, _ = _raw_http(server, "POST", "/3/ImportFiles",
                      {"path": csv, "destination_frame": "r3_train"})
    assert st == 200

    # h2o.xgboost
    st, tr = _raw_http(server, "POST", "/3/ModelBuilders/xgboost",
                       {"training_frame": "r3_train", "response_column": "y",
                        "ntrees": 4, "max_depth": 3})
    assert st == 200
    xgb_id = _poll(server, tr["job"]["key"]["name"])["dest"]["name"]

    # h2o.scoreHistory via model JSON
    st, mj = _raw_http(server, "GET", f"/3/Models/{xgb_id}")
    sh = mj["models"][0]["output"]["scoring_history"]
    assert sh["rowcount"] == 4 and sh["columns"][0]["name"] == "timestamp"

    # h2o.grid: urlencoded JSON hyper_parameters exactly as .json_obj emits
    st, g = _raw_http(server, "POST", "/99/Grid/gbm",
                      {"training_frame": "r3_train", "response_column": "y",
                       "ntrees": 3,
                       "hyper_parameters": '{"max_depth":[2,3]}'})
    assert st == 200
    grid_id = _poll(server, g["job"]["key"]["name"])["dest"]["name"]
    st, gg = _raw_http(server, "GET", f"/99/Grids/{grid_id}")
    assert st == 200 and len(gg["model_ids"]) == 2

    # h2o.automl (flat form) + state + leaderboard with extensions
    st, aml = _raw_http(server, "POST", "/99/AutoMLBuilder",
                        {"training_frame": "r3_train", "response_column": "y",
                         "max_models": 2, "nfolds": 0, "seed": 1,
                         "include_algos": '["GLM","GBM"]',
                         "project_name": "r3_aml"})
    assert st == 200 and aml["build_control"]["project_name"] == "r3_aml"
    _poll(server, aml["job"]["key"]["name"], tries=600)
    st, state = _raw_http(server, "GET", "/99/AutoML/r3_aml")
    assert st == 200 and state["project_name"] == "r3_aml"
    assert len(state["leaderboard"]["models"]) >= 2
    st, lb = _raw_http(server, "GET",
                       "/99/Leaderboards/r3_aml?extensions=ALL")
    names = [c["name"] for c in lb["table"]["columns"]]
    assert "algo" in names and "model_id" in names

    # h2o.saveModel / h2o.loadModel
    import urllib.parse as up
    dest = str(tmp_path / "saved_model")
    st, sv = _raw_http(server, "GET",
                       f"/99/Models.bin/{xgb_id}?dir="
                       f"{up.quote(dest, safe='')}")
    assert st == 200 and sv["dir"]
    st, _ = _raw_http(server, "DELETE", f"/3/Models/{xgb_id}")
    st, ld = _raw_http(server, "POST", "/99/Models.bin/", {"dir": sv["dir"]})
    assert st == 200
    assert ld["models"][0]["model_id"]["name"] == xgb_id

    # h2o.stackedEnsemble: bracket-list base_models (unquoted, R style)
    ids = []
    for seed in (1, 2):
        st, tr = _raw_http(server, "POST", "/3/ModelBuilders/gbm",
                           {"training_frame": "r3_train",
                            "response_column": "y", "ntrees": 3,
                            "max_depth": 2, "nfolds": 3, "seed": seed,
                            "keep_cross_validation_predictions": "true"})
        ids.append(_poll(server, tr["job"]["key"]["name"])["dest"]["name"])
    st, se = _raw_http(server, "POST", "/3/ModelBuilders/stackedensemble",
                       {"training_frame": "r3_train", "response_column": "y",
                        "base_models": f"[{ids[0]},{ids[1]}]"})
    assert st == 200
    se_id = _poll(server, se["job"]["key"]["name"])["dest"]["name"]
    st, mm = _raw_http(server, "POST",
                       f"/3/ModelMetrics/models/{se_id}/frames/r3_train")
    assert st == 200 and mm["model_metrics"][0]["auc"] > 0.7


REF_H2O_R = "/root/reference/h2o-r/h2o-package"


@pytest.mark.skipif(shutil.which("Rscript") is None, reason="no R in image")
def test_real_h2o_r_package_flow(server, tmp_path, rng):
    """The ACTUAL h2o-r package (reference h2o-r/h2o-package, 99 kLoC)
    against this server: connect, importFile, gbm/glm, predict,
    performance. Auto-activates on any host with Rscript (VERDICT r3
    missing #2); rc=42 = R deps unavailable -> skip."""
    if not os.path.isdir(REF_H2O_R):
        pytest.skip("reference h2o-r checkout not present")
    csv = _csv(tmp_path, rng)
    proc = subprocess.run(
        ["Rscript", os.path.join(REPO, "tests", "scripts", "h2o_r_flow.R"),
         server.url, csv, REF_H2O_R],
        cwd=REPO, capture_output=True, text=True, timeout=900)
    if proc.returncode == 42:
        pytest.skip(f"h2o-r deps unavailable: {proc.stdout[-300:]}")
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    assert "REAL h2o-r flow: OK" in proc.stdout


def test_r_wire_contract_round4(server, tmp_path, rng):
    """Round-4 verbs: long-tail estimators, h2o.import_mojo (generic
    builder), h2o.varimp — the exact requests the R package emits."""
    import time

    csv = _csv(tmp_path, rng)
    st, imp = _raw_http(server, "POST", "/3/ImportFiles",
                        {"path": csv, "destination_frame": "r4_train"})
    assert st == 200

    def _train(algo, body):
        st, tr = _raw_http(server, "POST", f"/3/ModelBuilders/{algo}", body)
        assert st == 200, tr
        job = _poll(server, tr["job"]["key"]["name"])
        assert job["status"] == "DONE", job
        return job["dest"]["name"]

    # a couple of long-tail estimator verbs over the same machinery
    iso = _train("isotonicregression",
                 {"training_frame": "r4_train", "response_column": "a",
                  "x": '["b"]'})
    assert iso
    dt = _train("decisiontree",
                {"training_frame": "r4_train", "response_column": "y",
                 "max_depth": 3})

    # h2o.varimp reads output.variable_importances off the model payload
    gbm = _train("gbm", {"training_frame": "r4_train",
                         "response_column": "y", "ntrees": 3})
    st, mj = _raw_http(server, "GET", f"/3/Models/{gbm}")
    vi = mj["models"][0]["output"].get("variable_importances")
    assert vi and vi["rowcount"] >= 1

    # h2o.import_mojo -> POST /3/ModelBuilders/generic with a path
    gen = _train("generic",
                 {"path": os.path.join(REPO, "tests", "data", "ref_mojo",
                                       "gbm_variable_importance.zip")})
    st, gj = _raw_http(server, "GET", f"/3/Models/{gen}")
    assert gj["models"][0]["algo"] == "generic"

    st, _ = _raw_http(server, "DELETE", "/3/DKV")
    assert st == 200


def test_r_wire_contract_round5(server, tmp_path, rng):
    """Round-5: the generated full-signature verbs (zzz_estimators_gen.R)
    ship only changed params over the same urlencoded wire; replay their
    exact payloads for a GBM with fold_column, a CoxPH with stop_column,
    and a GLM with missing_values_handling."""
    csv = _csv(tmp_path, rng)
    st, _ = _raw_http(server, "POST", "/3/ImportFiles",
                      {"path": csv, "destination_frame": "r5_train"})
    assert st == 200
    # add a fold column via rapids (what h2o-r's as.h2o + := would do)
    st, _ = _raw_http(server, "POST", "/99/Rapids", {
        "ast": "(assign r5_train (append r5_train "
               "(% (seq_len 400) 3) \"fold\"))"})
    assert st == 200

    def _train(algo, body):
        st, tr = _raw_http(server, "POST", f"/3/ModelBuilders/{algo}", body)
        assert st == 200, tr
        job = _poll(server, tr["job"]["key"]["name"])
        assert job["status"] == "DONE", job
        return job["dest"]["name"]

    gbm = _train("gbm", {"training_frame": "r5_train",
                         "response_column": "y", "ntrees": "3",
                         "fold_column": "fold"})
    st2, mj = _raw_http(server, "GET", f"/3/Models/{gbm}")
    assert mj["models"][0]["output"]["cross_validation_metrics"]
    cox_csv = tmp_path / "r5_cox.csv"
    x0 = rng.normal(size=200)
    t = -np.log(rng.random(200)) / np.exp(0.5 * x0)
    cox_csv.write_text("x0,time,event\n" + "\n".join(
        f"{a:.4f},{b:.4f},1" for a, b in zip(x0, t)) + "\n")
    st, _ = _raw_http(server, "POST", "/3/ImportFiles",
                      {"path": str(cox_csv), "destination_frame": "r5_cox"})
    cox = _train("coxph", {"training_frame": "r5_cox",
                           "response_column": "event",
                           "stop_column": "time", "x": '["x0"]'})
    assert cox
    glm = _train("glm", {"training_frame": "r5_train",
                         "response_column": "y",
                         "missing_values_handling": "Skip",
                         "lambda_": "0.0"})
    assert glm
    st, _ = _raw_http(server, "DELETE", "/3/DKV")


def test_r_wire_contract_compute(server):
    """ISSUE 10 R verbs: h2o.compute (GET /3/Compute), h2o.profilerCapture
    (POST /3/Profiler/capture?duration_ms=N) and h2o.profilerCaptures —
    exact byte sequences the R package emits."""
    st, snap = _raw_http(server, "GET", "/3/Compute")
    assert st == 200
    assert snap["__meta"]["schema_type"] == "ComputeV3"
    assert "sites" in snap and "loops" in snap
    st, rec = _raw_http(server, "POST", "/3/Profiler/capture?duration_ms=60")
    assert st == 200 and rec["capture_id"].startswith("cap_")
    st, caps = _raw_http(server, "GET", "/3/Profiler/captures")
    assert st == 200
    assert any(c["capture_id"] == rec["capture_id"] for c in caps["captures"])


def test_r_wire_contract_ops_plane(server):
    """ISSUE 15 R verbs: h2o.health (GET /3/Health), h2o.incidents /
    h2o.incident (GET /3/Incidents[/{id}]), and h2o.diagnosticsBundle —
    whose downloader GETs /3/Diagnostics/bundle (utils::download.file
    cannot POST; the route serves both)."""
    st, health = _raw_http(server, "GET", "/3/Health")
    assert st == 200
    assert health["__meta"]["schema_type"] == "HealthV3"
    assert health["status"] in ("healthy", "degraded", "unhealthy")
    assert set(health["subsystems"]) == {"elastic", "serving", "memory",
                                         "compute", "dispatch"}
    from h2o3_tpu.utils.incidents import INCIDENTS
    iid = INCIDENTS.open("serving_shed_rate", "serving", "degraded",
                         "overload", 0.5, 0.05)
    try:
        st, incs = _raw_http(server, "GET", "/3/Incidents")
        assert st == 200
        assert any(i["id"] == iid for i in incs["incidents"])
        st, one = _raw_http(server, "GET", f"/3/Incidents/{iid}")
        assert st == 200 and one["rule"] == "serving_shed_rate"
    finally:
        INCIDENTS.reset()
    # the bundle route answers GET with a gzip tar (R's download.file is
    # a plain GET; binary body — fetched here via urllib, not the
    # text-decoding raw socket helper)
    import urllib.request
    with urllib.request.urlopen(
            f"http://{server.host}:{server.port}/3/Diagnostics/bundle") as r:
        assert r.status == 200
        assert r.headers["Content-Type"] == "application/gzip"
        assert r.read()[:2] == b"\x1f\x8b"          # gzip magic
