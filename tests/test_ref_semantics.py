"""Reference-semantics golden suite (VERDICT r4 next #4).

Each test encodes one behavioral contract asserted by the reference's own
pyunit corpus (``/root/reference/h2o-py/tests/``), re-expressed on
synthetic data so it runs without a JVM.  These are the semantics a
migrating H2O-3 user relies on — weights-as-replication, NA routing,
fold assignment, offsets, missing-value modes, reproducibility — not
dataset-specific numbers.  Where the contract has a closed form (GLM),
the expected value is computed independently with numpy.

Existing suites cover accuracy vs sklearn (test_accuracy_1m,
test_golden_parity) and exact reference artifacts (test_mojo_ref*);
this file covers the reference's *parameter semantics*.
"""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models.deeplearning import DeepLearning
from h2o3_tpu.models.gbm import GBM, DRF
from h2o3_tpu.models.glm import GLM
from h2o3_tpu.models.kmeans import KMeans


def _bin_frame(rng, n=400, weights=None, key=None):
    X = rng.normal(size=(n, 4)).astype(np.float32)
    logit = 1.5 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2]
    y = rng.random(n) < 1 / (1 + np.exp(-logit))
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = np.where(y, "yes", "no").astype(object)
    if weights is not None:
        cols["w"] = weights.astype(np.float32)
    return Frame.from_arrays(cols, key=key)


def _reg_frame(rng, n=400, weights=None):
    X = rng.normal(size=(n, 4)).astype(np.float32)
    yv = 2 * X[:, 0] - X[:, 1] + 0.1 * rng.normal(size=n)
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = yv.astype(np.float32)
    if weights is not None:
        cols["w"] = weights.astype(np.float32)
    return Frame.from_arrays(cols)


# -- weights are replication (pyunit_weights_gbm.py, .../glm) ---------------

class TestWeightsAreReplication:
    """``pyunit_weights_gbm.py``: a row with weight 2 must train exactly
    like that row appearing twice."""

    def test_gbm_regression(self, rng):
        n = 300
        X = rng.normal(size=(n, 4)).astype(np.float32)
        yv = (2 * X[:, 0] - X[:, 1] + 0.1 * rng.normal(size=n)).astype(np.float32)
        dup = np.concatenate([np.arange(n), np.arange(0, n, 2)])  # evens twice
        w = np.where(np.arange(n) % 2 == 0, 2.0, 1.0)
        f_dup = Frame.from_arrays(
            {**{f"x{i}": X[dup, i] for i in range(4)}, "y": yv[dup]})
        f_w = Frame.from_arrays(
            {**{f"x{i}": X[:, i] for i in range(4)}, "y": yv,
             "w": w.astype(np.float32)})
        m1 = GBM(ntrees=5, max_depth=4, min_rows=4, seed=20).train(
            y="y", training_frame=f_dup)
        m2 = GBM(ntrees=5, max_depth=4, min_rows=4, seed=20,
                 weights_column="w").train(y="y", training_frame=f_w)
        p1 = m1.predict(f_w).vec("predict").to_numpy()[:n]
        p2 = m2.predict(f_w).vec("predict").to_numpy()[:n]
        assert np.abs(p1 - p2).max() < 1e-4

    def test_glm_binomial(self, rng):
        n = 400
        fr = _bin_frame(rng, n)
        dup = np.concatenate([np.arange(n), np.arange(0, n, 2)])
        f_dup = Frame.from_arrays({c: fr.vec(c).to_numpy()[dup]
                                   if c != "y" else
                                   fr.vec("y").labels()[dup]
                                   for c in fr.names})
        w = np.where(np.arange(n) % 2 == 0, 2.0, 1.0).astype(np.float32)
        f_w = Frame.from_arrays({**{c: fr.vec(c).to_numpy() for c in fr.names
                                    if c != "y"},
                                 "y": fr.vec("y").labels(), "w": w})
        m1 = GLM(family="binomial", lambda_=0.0).train(
            y="y", training_frame=f_dup)
        m2 = GLM(family="binomial", lambda_=0.0, weights_column="w").train(
            y="y", training_frame=f_w)
        c1, c2 = m1.coef(), m2.coef()
        b1 = np.array([c1[k] for k in sorted(c1)])
        b2 = np.array([c2[k] for k in sorted(c2)])
        assert np.abs(b1 - b2).max() < 1e-3


# -- bernoulli GBM basics (pyunit_bernoulli_gbm.py) -------------------------

def test_gbm_bernoulli_probabilities(rng):
    fr = _bin_frame(rng)
    m = GBM(ntrees=20, max_depth=3, seed=1).train(y="y", training_frame=fr)
    pred = m.predict(fr)
    n = fr.nrows
    p_no = pred.vec("pno").to_numpy()[:n]
    p_yes = pred.vec("pyes").to_numpy()[:n]
    assert np.allclose(p_no + p_yes, 1.0, atol=1e-5)
    assert ((p_yes >= 0) & (p_yes <= 1)).all()
    assert m.training_metrics.auc > 0.85
    # labels follow the model's decision threshold on p_yes
    labels = pred.vec("predict").labels()[:n]
    thr = getattr(m, "_default_threshold", 0.5)
    assert (labels == np.where(p_yes >= thr, "yes", "no")).all()


# -- constant response (pyunit_constant_response_gbm.py) --------------------

def test_gbm_constant_response(rng):
    """The reference trains on a constant response (regression) and
    predicts exactly that constant."""
    n = 128
    X = rng.normal(size=(n, 3)).astype(np.float32)
    fr = Frame.from_arrays({"a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
                            "y": np.full(n, 7.25, np.float32)})
    m = GBM(ntrees=5, max_depth=3, seed=1).train(y="y", training_frame=fr)
    p = m.predict(fr).vec("predict").to_numpy()[:n]
    assert np.abs(p - 7.25).max() < 1e-5


# -- reproducibility (pyunit_PUBDEV_7578_gbm_reproducibility.py) ------------

def test_gbm_reproducible_same_seed(rng):
    fr = _bin_frame(rng)
    kw = dict(ntrees=10, max_depth=3, sample_rate=0.7,
              col_sample_rate=0.7)
    p = [GBM(seed=42, **kw).train(y="y", training_frame=fr)
         .predict(fr).vec("pyes").to_numpy()[: fr.nrows] for _ in range(2)]
    assert np.array_equal(p[0], p[1])
    p3 = GBM(seed=43, **kw).train(y="y", training_frame=fr) \
        .predict(fr).vec("pyes").to_numpy()[: fr.nrows]
    assert not np.array_equal(p[0], p3)


def test_dl_reproducible_same_seed(rng):
    """``pyunit_mnist_reproducible...``: reproducible single-node DL —
    identical predictions for identical seeds."""
    fr = _bin_frame(rng, n=200)
    kw = dict(hidden=[8], epochs=3, mini_batch_size=32)
    p = [DeepLearning(seed=7, **kw).train(y="y", training_frame=fr)
         .predict(fr).vec("pyes").to_numpy()[: fr.nrows] for _ in range(2)]
    assert np.array_equal(p[0], p[1])


# -- checkpoint (pyunit_checkpoint_gives_equal_model_summary.py) ------------

def test_gbm_checkpoint_equals_straight_run(rng):
    """5 trees + checkpointed 5 more must equal one straight 10-tree
    train (same seed, no sampling)."""
    fr = _reg_frame(rng)
    half = GBM(ntrees=5, max_depth=3, seed=9).train(y="y", training_frame=fr)
    resumed = GBM(ntrees=10, max_depth=3, seed=9, checkpoint=half).train(
        y="y", training_frame=fr)
    straight = GBM(ntrees=10, max_depth=3, seed=9).train(
        y="y", training_frame=fr)
    n = fr.nrows
    pr = resumed.predict(fr).vec("predict").to_numpy()[:n]
    ps = straight.predict(fr).vec("predict").to_numpy()[:n]
    assert np.abs(pr - ps).max() < 1e-5


# -- quantile distribution (pyunit gbm quantile tests) ----------------------

def test_gbm_quantile_coverage(rng):
    """distribution='quantile' with alpha=0.8: ~80% of training targets
    fall at or below the prediction."""
    n = 600
    X = rng.normal(size=(n, 3)).astype(np.float32)
    yv = (X[:, 0] + rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays({"a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
                            "y": yv})
    m = GBM(ntrees=40, max_depth=3, learn_rate=0.2, seed=3,
            distribution="quantile", quantile_alpha=0.8).train(
        y="y", training_frame=fr)
    p = m.predict(fr).vec("predict").to_numpy()[:n]
    cover = float((yv <= p).mean())
    assert 0.7 < cover < 0.92, cover


# -- NA routing (reference NAs-learn-a-direction semantics) -----------------

class TestNARouting:
    """``hex/tree/DHistogram`` NA semantics: missing values get their own
    split direction, so NA-ness itself is learnable signal."""

    def test_numeric_na_is_signal(self, rng):
        n = 400
        x = rng.normal(size=n).astype(np.float32)
        is_na = rng.random(n) < 0.4
        x[is_na] = np.nan
        fr = Frame.from_arrays({
            "x": x, "noise": rng.normal(size=n).astype(np.float32),
            "y": np.where(is_na, "yes", "no").astype(object)})
        m = GBM(ntrees=10, max_depth=2, seed=1).train(
            y="y", training_frame=fr)
        assert m.training_metrics.auc > 0.99

    def test_categorical_na_is_signal(self, rng):
        n = 400
        lv = rng.choice(["a", "b", "c"], size=n).astype(object)
        is_na = rng.random(n) < 0.4
        lv[is_na] = None
        fr = Frame.from_arrays({
            "c": lv, "noise": rng.normal(size=n).astype(np.float32),
            "y": np.where(is_na, "yes", "no").astype(object)})
        m = GBM(ntrees=10, max_depth=2, seed=1).train(
            y="y", training_frame=fr)
        assert m.training_metrics.auc > 0.99

    def test_na_rows_still_score(self, rng):
        fr = _bin_frame(rng, n=200)
        m = GBM(ntrees=5, max_depth=3, seed=1).train(
            y="y", training_frame=fr)
        x0 = fr.vec("x0").to_numpy().copy()
        x0[:50] = np.nan
        test = Frame.from_arrays({
            "x0": x0, **{f"x{i}": fr.vec(f"x{i}").to_numpy()
                         for i in range(1, 4)}})
        p = m.predict(test).vec("pyes").to_numpy()[: test.nrows]
        assert np.isfinite(p).all()


# -- multinomial (pyunit_bernoulli/multinomial + PUBDEV_7269) ---------------

def test_gbm_multinomial_rows_sum_to_one(rng):
    n = 450
    X = rng.normal(size=(n, 2)).astype(np.float32)
    cls = np.argmax(np.stack([X[:, 0], X[:, 1], -X[:, 0] - X[:, 1]]), 0)
    fr = Frame.from_arrays({
        "a": X[:, 0], "b": X[:, 1],
        "y": np.array(["red", "green", "blue"], object)[cls]})
    m = GBM(ntrees=20, max_depth=3, seed=5).train(y="y", training_frame=fr)
    pred = m.predict(fr)
    P = np.stack([pred.vec(f"p{d}").to_numpy()[:n]
                  for d in m.response_domain], 1)
    assert np.allclose(P.sum(1), 1.0, atol=1e-5)
    cm = m.training_metrics.confusion_matrix
    assert np.diag(cm).sum() / cm.sum() > 0.9


# -- calibration (pyunit_calibration_gbm.py) --------------------------------

def test_gbm_platt_calibration_outputs(rng):
    fr = _bin_frame(rng, key="cal_train")
    cal = _bin_frame(rng, key="cal_frame")
    m = GBM(ntrees=10, max_depth=3, seed=2, calibrate_model=True,
            calibration_frame=cal).train(y="y", training_frame=fr)
    pred = m.predict(fr)
    assert "cal_p1" in pred.names       # calibrated columns appended
    cp = pred.vec("cal_p1").to_numpy()[: fr.nrows]
    assert ((cp >= 0) & (cp <= 1)).all()
    # calibrated probs preserve the raw ranking (Platt is monotone)
    rp = pred.vec("pyes").to_numpy()[: fr.nrows]
    assert np.corrcoef(np.argsort(np.argsort(rp)),
                       np.argsort(np.argsort(cp)))[0, 1] > 0.999


# -- fold assignment & fold_column (pyunit_cv_nfolds_gbm*.py) ---------------

class TestFoldAssignment:
    def test_fold_column_defines_folds(self, rng):
        """``pyunit_cv_cars_gbm.py`` fold_column mode: the explicit column
        partitions rows; CV metrics come from those holdouts."""
        n = 300
        fr0 = _bin_frame(rng, n)
        folds = (np.arange(n) % 3).astype(np.float32)
        fr = Frame.from_arrays({**{c: fr0.vec(c).to_numpy()
                                   for c in fr0.names if c != "y"},
                                "y": fr0.vec("y").labels(),
                                "fold": folds})
        m = GBM(ntrees=5, max_depth=3, seed=1, fold_column="fold").train(
            y="y", training_frame=fr)
        assert m.cross_validation_metrics is not None
        assert 0.5 < m.cross_validation_metrics.auc <= 1.0
        # the fold column must not be used as a feature
        assert "fold" not in m.output["x_cols"]

    def test_fold_column_matches_modulo(self, rng):
        """fold = row % 3 as a column reproduces fold_assignment=Modulo
        with nfolds=3 exactly."""
        n = 300
        fr0 = _bin_frame(rng, n)
        cols = {c: fr0.vec(c).to_numpy() for c in fr0.names if c != "y"}
        y = fr0.vec("y").labels()
        fr_a = Frame.from_arrays({**cols, "y": y,
                                  "fold": (np.arange(n) % 3).astype(np.float32)})
        fr_b = Frame.from_arrays({**cols, "y": y})
        m_a = GBM(ntrees=5, max_depth=3, seed=1, fold_column="fold").train(
            y="y", training_frame=fr_a)
        m_b = GBM(ntrees=5, max_depth=3, seed=1, nfolds=3,
                  fold_assignment="Modulo").train(y="y", training_frame=fr_b)
        assert m_a.cross_validation_metrics.auc == pytest.approx(
            m_b.cross_validation_metrics.auc, abs=1e-6)

    def test_fold_column_misuse_rejected(self, rng):
        """Reference ModelBuilder.init: fold_column+nfolds is an error, a
        constant fold column is an error, stratified needs a categorical
        response, NA fold values are rejected."""
        n = 64
        fr0 = _bin_frame(rng, n)
        cols = {c: fr0.vec(c).to_numpy() for c in fr0.names if c != "y"}
        y = fr0.vec("y").labels()
        both = Frame.from_arrays({**cols, "y": y,
                                  "fold": (np.arange(n) % 3).astype(np.float32)})
        with pytest.raises(ValueError, match="not both"):
            GBM(ntrees=2, nfolds=3, fold_column="fold").train(
                y="y", training_frame=both)
        const = Frame.from_arrays({**cols, "y": y,
                                   "fold": np.zeros(n, np.float32)})
        with pytest.raises(ValueError, match="2 distinct"):
            GBM(ntrees=2, fold_column="fold").train(y="y",
                                                    training_frame=const)
        withna = Frame.from_arrays({**cols, "y": y, "fold": np.where(
            np.arange(n) < 4, np.nan, np.arange(n) % 3).astype(np.float32)})
        with pytest.raises(ValueError, match="missing"):
            GBM(ntrees=2, fold_column="fold").train(y="y",
                                                    training_frame=withna)
        reg = _reg_frame(rng, n=64)
        with pytest.raises(ValueError, match="categorical response"):
            GBM(ntrees=2, nfolds=3, fold_assignment="Stratified").train(
                y="y", training_frame=reg)

    def test_stratified_every_fold_sees_minority(self, rng):
        """FoldAssignment.Stratified: even a 10% minority class appears in
        every fold's holdout."""
        n = 300
        X = rng.normal(size=(n, 3)).astype(np.float32)
        y = np.where(np.arange(n) < 30, "pos", "neg").astype(object)
        fr = Frame.from_arrays({"a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
                                "y": y})
        b = GLM(family="binomial", nfolds=5, fold_assignment="Stratified")
        yvec = fr.vec("y")
        folds = np.asarray(b._fold_ids(fr, 5, yvec))[: n]
        codes = np.asarray(yvec.data)[:n]
        minority = int(codes.max())  # 2-level domain; pos is one code
        for k in range(5):
            hold = folds == k
            assert (codes[hold] == minority).sum() > 0
            assert (codes[hold] != minority).sum() > 0


# -- GLM closed forms (glm pyunits: offset, lambda, solvers) ----------------

class TestGLMSemantics:
    def test_gaussian_closed_form(self, rng):
        """lambda=0, standardize=False: coefficients are the least-squares
        solution (pyunit_glm_gaussian tests assert R's lm equivalence)."""
        n = 300
        X = rng.normal(size=(n, 3)).astype(np.float64)
        beta = np.array([1.5, -2.0, 0.5])
        yv = X @ beta + 3.0 + 0.05 * rng.normal(size=n)
        fr = Frame.from_arrays({"a": X[:, 0].astype(np.float32),
                                "b": X[:, 1].astype(np.float32),
                                "c": X[:, 2].astype(np.float32),
                                "y": yv.astype(np.float32)})
        m = GLM(family="gaussian", lambda_=0.0, standardize=False).train(
            y="y", training_frame=fr)
        A = np.column_stack([X, np.ones(n)])
        exact = np.linalg.lstsq(A, yv, rcond=None)[0]
        got = [m.coef()["a"], m.coef()["b"],
               m.coef()["c"], m.coef()["Intercept"]]
        assert np.abs(np.array(got) - exact).max() < 1e-3

    def test_offset_column_exact(self, rng):
        """pyunit offset tests: a gaussian fit with offset o equals the
        fit of (y - o); predictions add the offset back."""
        n = 300
        X = rng.normal(size=(n, 2)).astype(np.float64)
        off = rng.normal(size=n).astype(np.float64)
        yv = 2 * X[:, 0] - X[:, 1] + off + 0.05 * rng.normal(size=n)
        fr = Frame.from_arrays({"a": X[:, 0].astype(np.float32),
                                "b": X[:, 1].astype(np.float32),
                                "off": off.astype(np.float32),
                                "y": yv.astype(np.float32)})
        m = GLM(family="gaussian", lambda_=0.0, standardize=False,
                offset_column="off").train(y="y", training_frame=fr)
        A = np.column_stack([X, np.ones(n)])
        exact = np.linalg.lstsq(A, yv - off, rcond=None)[0]
        c = m.coef()
        got = np.array([c["a"], c["b"], c["Intercept"]])
        assert np.abs(got - exact).max() < 1e-3

    def test_lasso_strong_lambda_zeroes_coefficients(self, rng):
        """alpha=1 with a large lambda shrinks every coefficient to
        exactly zero (reference L1 soft-threshold semantics)."""
        fr = _reg_frame(rng)
        m = GLM(family="gaussian", alpha=1.0, lambda_=1e3).train(
            y="y", training_frame=fr)
        coefs = [v for k, v in m.coef().items() if k != "Intercept"]
        assert np.abs(np.array(coefs)).max() < 1e-6
        yv = fr.vec("y").to_numpy()[: fr.nrows]
        assert m.coef()["Intercept"] == pytest.approx(
            float(yv.mean()), abs=1e-3)

    def test_missing_skip_equals_subset_fit(self, rng):
        """missing_values_handling='Skip' fits exactly the NA-free rows
        (GLMParameters.MissingValuesHandling.Skip)."""
        n = 300
        X = rng.normal(size=(n, 2)).astype(np.float64)
        yv = (X[:, 0] - 2 * X[:, 1] + 0.05 * rng.normal(size=n))
        a = X[:, 0].copy()
        a[:60] = np.nan                       # 20% NA rows
        fr = Frame.from_arrays({"a": a.astype(np.float32),
                                "b": X[:, 1].astype(np.float32),
                                "y": yv.astype(np.float32)})
        sub = Frame.from_arrays({"a": X[60:, 0].astype(np.float32),
                                 "b": X[60:, 1].astype(np.float32),
                                 "y": yv[60:].astype(np.float32)})
        m_skip = GLM(family="gaussian", lambda_=0.0, standardize=False,
                     missing_values_handling="Skip").train(
            y="y", training_frame=fr)
        m_sub = GLM(family="gaussian", lambda_=0.0, standardize=False).train(
            y="y", training_frame=sub)
        cs, cb = m_skip.coef(), m_sub.coef()
        for k in ("a", "b", "Intercept"):
            assert cs[k] == pytest.approx(cb[k], abs=1e-4)
        # metrics cover the same reduced row set as the fit (reference:
        # Skip rows carry weight 0 in the metrics pass too)
        assert m_skip.training_metrics.mse == pytest.approx(
            m_sub.training_metrics.mse, rel=1e-3)

    def test_mean_imputation_differs_from_skip(self, rng):
        """Default MeanImputation keeps NA rows (imputed) — a different,
        documented estimator from Skip."""
        n = 300
        X = rng.normal(size=(n, 2)).astype(np.float64)
        yv = (X[:, 0] - 2 * X[:, 1] + 0.05 * rng.normal(size=n))
        a = X[:, 0].copy()
        a[:100] = np.nan
        fr = Frame.from_arrays({"a": a.astype(np.float32),
                                "b": X[:, 1].astype(np.float32),
                                "y": yv.astype(np.float32)})
        m_imp = GLM(family="gaussian", lambda_=0.0).train(
            y="y", training_frame=fr)
        m_skip = GLM(family="gaussian", lambda_=0.0,
                     missing_values_handling="Skip").train(
            y="y", training_frame=fr)
        assert m_imp.coef()["a"] != pytest.approx(
            m_skip.coef()["a"], abs=1e-6)


# -- DRF (pyunit drf tests) -------------------------------------------------

def test_drf_binomial_probability_complement(rng):
    fr = _bin_frame(rng)
    m = DRF(ntrees=15, max_depth=5, seed=4).train(y="y", training_frame=fr)
    pred = m.predict(fr)
    n = fr.nrows
    p0 = pred.vec("pno").to_numpy()[:n]
    p1 = pred.vec("pyes").to_numpy()[:n]
    assert np.allclose(p0 + p1, 1.0, atol=1e-5)
    assert m.training_metrics.auc > 0.8


# -- KMeans (kmeans pyunits) ------------------------------------------------

def test_kmeans_recovers_separated_blobs(rng):
    n = 300
    centers = np.array([[0, 0], [10, 10], [-10, 10]], np.float64)
    lab = rng.integers(0, 3, n)
    X = centers[lab] + rng.normal(size=(n, 2))
    fr = Frame.from_arrays({"a": X[:, 0].astype(np.float32),
                            "b": X[:, 1].astype(np.float32)})
    m = KMeans(k=3, seed=11, standardize=False).train(training_frame=fr)
    got = np.stack(sorted(np.asarray(m.output["centers"]).tolist()))
    exp = np.stack(sorted(centers.tolist()))
    assert np.abs(got - exp).max() < 0.5
    # every row lands with its own blob-mates
    pred = m.predict(fr).vec("predict").to_numpy()[:n].astype(int)
    for c in range(3):
        assert len(np.unique(pred[lab == c])) == 1


# -- round-5 additions: offset, robust losses, structural params ------------

class TestGBMOffsetAndLosses:
    def test_gbm_gaussian_offset_equals_residual_fit(self, rng):
        """pyunit offset_gbm: a gaussian GBM with offset o fits y - o and
        adds o back at scoring time."""
        n = 300
        x = rng.normal(size=n).astype(np.float32)
        off = rng.normal(size=n).astype(np.float32)
        yv = (2 * x + off + 0.05 * rng.normal(size=n)).astype(np.float32)
        fr = Frame.from_arrays({"x": x, "off": off, "y": yv})
        fr_res = Frame.from_arrays({"x": x, "y": (yv - off)})
        # learn_rate=1: the only divergence between the two formulations
        # is the init constant c = f0 - f0', which every gaussian leaf
        # absorbs and lr=1 cancels after the first tree (at lr<1 it decays
        # geometrically as c(1-lr)^T — exact equivalence needs lr=1)
        kw = dict(ntrees=10, max_depth=3, seed=3, learn_rate=1.0)
        m_off = GBM(offset_column="off", **kw).train(y="y",
                                                     training_frame=fr)
        m_res = GBM(**kw).train(y="y", training_frame=fr_res)
        p_off = m_off.predict(fr).vec("predict").to_numpy()[:n]
        p_res = m_res.predict(fr_res).vec("predict").to_numpy()[:n] + off
        np.testing.assert_allclose(p_off, p_res, atol=1e-4)

    def test_huber_resists_outliers(self, rng):
        """distribution='huber': a handful of wild outliers must distort
        predictions far less than under gaussian loss (pyunit huber)."""
        n = 400
        x = rng.normal(size=n).astype(np.float32)
        yv = (2 * x).astype(np.float32)
        yv[:8] += 500.0                          # gross outliers
        fr = Frame.from_arrays({"x": x, "y": yv})
        clean = 2 * x[8:]
        kw = dict(ntrees=30, max_depth=3, learn_rate=0.2, seed=4)
        p_g = GBM(distribution="gaussian", **kw).train(
            y="y", training_frame=fr).predict(fr) \
            .vec("predict").to_numpy()[8:n]
        p_h = GBM(distribution="huber", huber_alpha=0.9, **kw).train(
            y="y", training_frame=fr).predict(fr) \
            .vec("predict").to_numpy()[8:n]
        err_g = float(np.abs(p_g - clean).mean())
        err_h = float(np.abs(p_h - clean).mean())
        assert err_h < 0.5 * err_g, (err_h, err_g)

    def test_min_split_improvement_prunes(self, rng):
        """A large min_split_improvement must yield a strictly simpler
        model (fewer effective leaves -> coarser predictions)."""
        fr = _reg_frame(rng)
        loose = GBM(ntrees=5, max_depth=5, seed=5,
                    min_split_improvement=0.0).train(y="y",
                                                     training_frame=fr)
        tight = GBM(ntrees=5, max_depth=5, seed=5,
                    min_split_improvement=1e6).train(y="y",
                                                     training_frame=fr)
        n = fr.nrows
        u_loose = len(np.unique(
            loose.predict(fr).vec("predict").to_numpy()[:n].round(5)))
        u_tight = len(np.unique(
            tight.predict(fr).vec("predict").to_numpy()[:n].round(5)))
        assert u_tight < u_loose

    def test_nbins_cats_buckets_levels(self, rng):
        """nbins_cats smaller than the cardinality forces range-grouped
        levels — the model coarsens but still trains (pyunit_bigcat)."""
        n = 600
        codes = rng.integers(0, 60, n)
        # parity signal: adjacent levels alternate classes, so RANGE
        # buckets (what nbins_cats=4 forces) cannot separate them while
        # per-level group splits (nbins_cats=64) can
        y = np.where(codes % 2 == 0, "a", "b").astype(object)
        fr = Frame.from_arrays({
            "c": np.array([f"l{c:02d}" for c in codes], object), "y": y})
        fine = GBM(ntrees=5, max_depth=3, seed=6, nbins_cats=64).train(
            y="y", training_frame=fr)
        coarse = GBM(ntrees=5, max_depth=3, seed=6, nbins_cats=4).train(
            y="y", training_frame=fr)
        n = fr.nrows
        pf = fine.predict(fr).vec("pa").to_numpy()[:n]
        pc = coarse.predict(fr).vec("pa").to_numpy()[:n]
        # 4 buckets over 60 levels MUST coarsen the model — identical
        # predictions would mean nbins_cats is ignored
        assert not np.allclose(pf, pc)
        assert fine.training_metrics.auc > coarse.training_metrics.auc
        assert coarse.training_metrics.auc > 0.5


class TestDRFSemantics:
    def test_mtries_minus_one_is_sqrt(self, rng):
        """DRF default mtries=-1 samples ~sqrt(F) features per split —
        with one dominant feature among many, per-tree feature sampling
        must still find it overall (pyunit drf defaults)."""
        n = 400
        X = rng.normal(size=(n, 9)).astype(np.float32)
        cols = {f"x{i}": X[:, i] for i in range(9)}
        cols["y"] = np.where(X[:, 0] > 0, "t", "f").astype(object)
        fr = Frame.from_arrays(cols)
        m = DRF(ntrees=20, max_depth=4, seed=7).train(y="y",
                                                      training_frame=fr)
        assert m.training_metrics.auc > 0.9
        # per-split feature sampling must actually happen: with all 9
        # features available every tree's first split would pick the
        # dominant x0, so single trees would agree everywhere; sqrt(9)=3
        # sampling makes some trees split elsewhere first
        m_all = DRF(ntrees=20, max_depth=4, seed=7, mtries=9).train(
            y="y", training_frame=fr)
        n = fr.vec("x0").nrows
        pa = m.predict(fr).vec("pt").to_numpy()[:n]
        pb = m_all.predict(fr).vec("pt").to_numpy()[:n]
        assert not np.allclose(pa, pb)

    def test_sample_rate_below_one_changes_trees(self, rng):
        fr = _bin_frame(rng)
        full = DRF(ntrees=5, max_depth=3, seed=8, sample_rate=1.0).train(
            y="y", training_frame=fr)
        boot = DRF(ntrees=5, max_depth=3, seed=8, sample_rate=0.5).train(
            y="y", training_frame=fr)
        n = fr.nrows
        p1 = full.predict(fr).vec("pyes").to_numpy()[:n]
        p2 = boot.predict(fr).vec("pyes").to_numpy()[:n]
        assert not np.allclose(p1, p2)


class TestGLMPlugValues:
    """GLMParameters.MissingValuesHandling.PlugValues: NA predictors
    impute to USER values (training and scoring) instead of means."""

    def test_plug_value_changes_fit_and_scoring(self, rng):
        n = 300
        a = rng.normal(size=n).astype(np.float64)
        b = rng.normal(size=n).astype(np.float64)
        yv = (a - 2 * b).astype(np.float32)
        a_na = a.copy()
        a_na[:60] = np.nan
        fr = Frame.from_arrays({"a": a_na.astype(np.float32),
                                "b": b.astype(np.float32), "y": yv})
        # equivalent explicit fill with the plug value 5.0
        filled = Frame.from_arrays({
            "a": np.where(np.isnan(a_na), 5.0, a_na).astype(np.float32),
            "b": b.astype(np.float32), "y": yv})
        m_plug = GLM(family="gaussian", lambda_=0.0, standardize=False,
                     missing_values_handling="PlugValues",
                     plug_values={"a": 5.0}).train(y="y", training_frame=fr)
        m_fill = GLM(family="gaussian", lambda_=0.0,
                     standardize=False).train(y="y", training_frame=filled)
        for k in ("a", "b", "Intercept"):
            assert m_plug.coef()[k] == pytest.approx(m_fill.coef()[k],
                                                     abs=1e-4)
        # scoring imputes with the plug too
        test = Frame.from_arrays({"a": np.array([np.nan], np.float32),
                                  "b": np.zeros(1, np.float32)})
        p = m_plug.predict(test).vec("predict").to_numpy()[0]
        exp = (m_plug.coef()["a"] * 5.0 + m_plug.coef()["Intercept"])
        assert p == pytest.approx(exp, abs=1e-4)

    def test_plug_values_frame_key_and_validation(self, rng):
        from h2o3_tpu.utils.registry import DKV
        n = 64
        fr = Frame.from_arrays({
            "a": rng.normal(size=n).astype(np.float32),
            "y": rng.normal(size=n).astype(np.float32)})
        DKV.put("plugs", Frame.from_arrays({"a": np.array([1.5], np.float32)}))
        m = GLM(family="gaussian", missing_values_handling="PlugValues",
                plug_values="plugs").train(y="y", training_frame=fr)
        assert m is not None
        with pytest.raises(ValueError, match="plug_values"):
            GLM(family="gaussian",
                missing_values_handling="PlugValues").train(
                y="y", training_frame=fr)
        with pytest.raises(ValueError, match="unknown numeric"):
            GLM(family="gaussian", missing_values_handling="PlugValues",
                plug_values={"zzz": 1.0}).train(y="y", training_frame=fr)

    def test_plug_frame_misuse_rejected(self, rng):
        from h2o3_tpu.utils.registry import DKV
        n = 64
        fr = Frame.from_arrays({
            "a": rng.normal(size=n).astype(np.float32),
            "y": rng.normal(size=n).astype(np.float32)})
        DKV.put("pv_bad", Frame.from_arrays(
            {"typo": np.array([1.0], np.float32)}))
        with pytest.raises(ValueError, match="unknown numeric"):
            GLM(family="gaussian", missing_values_handling="PlugValues",
                plug_values="pv_bad").train(y="y", training_frame=fr)
        DKV.put("pv_multi", Frame.from_arrays(
            {"a": np.arange(3, dtype=np.float32)}))
        with pytest.raises(ValueError, match="exactly 1 row"):
            GLM(family="gaussian", missing_values_handling="PlugValues",
                plug_values="pv_multi").train(y="y", training_frame=fr)

    def test_plug_values_mode_mismatch_and_nonfinite_rejected(self, rng):
        n = 64
        fr = Frame.from_arrays({
            "a": rng.normal(size=n).astype(np.float32),
            "y": rng.normal(size=n).astype(np.float32)})
        with pytest.raises(ValueError, match="requires "
                                             "missing_values_handling"):
            GLM(family="gaussian", plug_values={"a": 1.0}).train(
                y="y", training_frame=fr)
        with pytest.raises(ValueError, match="finite"):
            GLM(family="gaussian", missing_values_handling="PlugValues",
                plug_values={"a": float("nan")}).train(
                y="y", training_frame=fr)

    def test_binomial_double_trees(self, rng):
        """DRF.java binomial_double_trees: one tree per class instead of
        the single-tree complement — different forests, same task."""
        fr = _bin_frame(rng)
        n = fr.nrows
        single = DRF(ntrees=10, max_depth=4, seed=9).train(
            y="y", training_frame=fr)
        double = DRF(ntrees=10, max_depth=4, seed=9,
                     binomial_double_trees=True).train(
            y="y", training_frame=fr)
        assert double.output.get("trees_multi") is not None
        assert len(double.output["trees_multi"]) == 2
        p1 = single.predict(fr).vec("pyes").to_numpy()[:n]
        p2 = double.predict(fr).vec("pyes").to_numpy()[:n]
        assert np.allclose(
            double.predict(fr).vec("pno").to_numpy()[:n] + p2, 1.0,
            atol=1e-5)
        assert not np.allclose(p1, p2)       # genuinely different forests
        assert double.training_metrics.auc > 0.85
        # checkpoint across modes must refuse, not mis-stack trees
        with pytest.raises(ValueError, match="binomial_double"):
            DRF(ntrees=12, max_depth=4, seed=9, binomial_double_trees=True,
                checkpoint=single).train(y="y", training_frame=fr)

    def test_double_trees_checkpoint_reverse_direction_refused(self, rng):
        fr = _bin_frame(rng, n=128)
        double = DRF(ntrees=3, max_depth=3, seed=9,
                     binomial_double_trees=True).train(
            y="y", training_frame=fr)
        with pytest.raises(ValueError, match="binomial_double"):
            DRF(ntrees=5, max_depth=3, seed=9,
                checkpoint=double).train(y="y", training_frame=fr)


def test_cv_metrics_summary_table(rng):
    """ModelBuilder's cross_validation_metrics_summary: rows = metrics,
    columns = mean, sd, cv_{k}_valid — h2o-py renders it verbatim."""
    from h2o3_tpu.api import schemas
    fr = _bin_frame(rng, n=240)
    m = GBM(ntrees=5, max_depth=3, seed=1, nfolds=3).train(
        y="y", training_frame=fr)
    names, nfolds, rows = m.cv_metrics_summary
    assert nfolds == 3 and "auc" in names
    t = schemas.model_v3(m)["output"]["cross_validation_metrics_summary"]
    cols = [c["name"] for c in t["columns"]]
    assert cols == ["", "mean", "sd", "cv_1_valid", "cv_2_valid",
                    "cv_3_valid"]
    auc_row = [r for r in rows if r[0] == "auc"][0]
    per_fold = np.array(auc_row[3:])
    assert auc_row[1] == pytest.approx(per_fold.mean())
    assert auc_row[2] == pytest.approx(per_fold.std(ddof=1))
    # fold-column CV serves the summary too
    n = 240
    cols2 = {c: fr.vec(c).to_numpy() for c in fr.names if c != "y"}
    fr2 = Frame.from_arrays({**cols2, "y": fr.vec("y").labels(),
                             "fold": (np.arange(n) % 3).astype(np.float32)})
    m2 = GBM(ntrees=3, max_depth=3, seed=1, fold_column="fold").train(
        y="y", training_frame=fr2)
    assert m2.cv_metrics_summary[1] == 3
