"""Serving security: TLS, form login, pluggable authenticator
(VERDICT r2 item 8; reference: ``water/H2O.java:242-266``, ``h2o-security``)."""

import ssl
import subprocess
import urllib.error
import urllib.request

import pytest

from h2o3_tpu.api import H2OServer


@pytest.fixture(scope="module")
def cert(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    crt, key = d / "srv.crt", d / "srv.key"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(crt), "-days", "1",
         "-subj", "/CN=127.0.0.1"],
        check=True, capture_output=True)
    return str(crt), str(key)


def test_https_serving(cert):
    crt, key = cert
    s = H2OServer(port=0, ssl_certfile=crt, ssl_keyfile=key).start()
    try:
        assert s.url.startswith("https://")
        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        with urllib.request.urlopen(f"{s.url}/3/Cloud", context=ctx) as r:
            assert r.status == 200
        # plain http against the TLS port must fail
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://{s.host}:{s.port}/3/Cloud", timeout=3)
    finally:
        s.stop()


def test_h2o_py_connects_over_https(cert, tmp_path):
    """The REAL h2o-py client over https with a self-signed cert."""
    import os
    import sys
    crt, key = cert
    script = tmp_path / "flow.py"
    script.write_text(f"""
import sys, warnings
warnings.filterwarnings("ignore")
sys.path.insert(0, "/root/reference/h2o-py")
import os
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax; jax.config.update("jax_platforms", "cpu")
from h2o3_tpu.api import H2OServer
s = H2OServer(port=0, ssl_certfile={crt!r}, ssl_keyfile={key!r}).start()
import h2o
h2o.connect(url=s.url, verify_ssl_certificates=False,
            strict_version_check=False)
assert h2o.cluster().cloud_healthy
print("HTTPS_OK")
os._exit(0)
""")
    env = dict(os.environ)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "HTTPS_OK" in proc.stdout


def test_form_login_session_cookie():
    s = H2OServer(port=0, username="u", password="p").start()
    try:
        # no credentials → 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{s.url}/3/Cloud")
        assert ei.value.code == 401
        # the login page itself is reachable
        with urllib.request.urlopen(f"{s.url}/login") as r:
            assert b"form" in r.read()
        # bad form login → 401
        bad = urllib.parse.urlencode({"username": "u",
                                      "password": "wrong"}).encode()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                urllib.request.Request(f"{s.url}/login", data=bad))
        assert ei.value.code == 401
        # good form login → cookie grants access
        good = urllib.parse.urlencode({"username": "u",
                                       "password": "p"}).encode()
        with urllib.request.urlopen(
                urllib.request.Request(f"{s.url}/login", data=good)) as r:
            cookie = r.headers["Set-Cookie"].split(";")[0]
        req = urllib.request.Request(f"{s.url}/3/Cloud",
                                     headers={"Cookie": cookie})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        # logout invalidates the session
        urllib.request.urlopen(urllib.request.Request(
            f"{s.url}/logout", data=b"", headers={"Cookie": cookie}))
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 401
    finally:
        s.stop()


def test_pluggable_authenticator():
    """The LDAP-shaped hook: any (user, password) -> bool callable."""
    import base64
    calls = []

    def ldap_like(user, password):
        calls.append(user)
        return user == "dn=alice" and password == "s3cret"

    s = H2OServer(port=0, authenticator=ldap_like).start()
    try:
        tok = base64.b64encode(b"dn=alice:s3cret").decode()
        req = urllib.request.Request(
            f"{s.url}/3/Cloud", headers={"Authorization": f"Basic {tok}"})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        bad = base64.b64encode(b"dn=bob:nope").decode()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"{s.url}/3/Cloud", headers={"Authorization": f"Basic {bad}"}))
        assert ei.value.code == 401
        assert "dn=alice" in calls and "dn=bob" in calls
    finally:
        s.stop()
