"""PSVM tests (reference: hex/psvm — PSVMTest, PrimalDualIPMTest, ICF tests)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.psvm import PSVM, _icf


def _two_blobs(rng, n=240, sep=2.2):
    half = n // 2
    X = np.concatenate([rng.normal(-sep / 2, 1.0, size=(half, 2)),
                        rng.normal(sep / 2, 1.0, size=(n - half, 2))])
    y = np.array(["neg"] * half + ["pos"] * (n - half))
    idx = rng.permutation(n)
    return X[idx], y[idx]


def test_psvm_separable_blobs(rng):
    X, y = _two_blobs(rng)
    fr = Frame.from_arrays({"x0": X[:, 0].astype(np.float32),
                            "x1": X[:, 1].astype(np.float32), "y": y})
    m = PSVM(hyper_param=1.0, max_iterations=60, seed=1).train(y="y", training_frame=fr)
    assert m.output["svs_count"] > 0
    assert m.training_metrics.auc > 0.95
    preds = m.predict(fr)
    acc = (np.asarray(preds.vec("predict").to_numpy()) ==
           np.asarray(fr.vec("y").to_numpy())).mean()
    assert acc > 0.9


def test_psvm_nonlinear_circle(rng):
    # RBF kernel must solve a radially-separable problem a linear model can't
    n = 300
    X = rng.normal(size=(n, 2)).astype(np.float32)
    r = np.sqrt((X ** 2).sum(axis=1))
    y = np.where(r < 1.1, "in", "out")
    fr = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "y": y})
    m = PSVM(hyper_param=10.0, gamma=1.0, rank_ratio=0.3, max_iterations=80).train(
        y="y", training_frame=fr)
    assert m.training_metrics.auc > 0.95


def test_icf_approximates_kernel(rng):
    import jax.numpy as jnp
    n, d = 60, 3
    X = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    y = jnp.asarray(np.where(rng.random(n) < 0.5, -1.0, 1.0).astype(np.float32))
    gamma = 0.5
    H = _icf(X, y, rank=n, gamma=gamma)          # full rank → near-exact
    d2 = ((np.asarray(X)[:, None, :] - np.asarray(X)[None, :, :]) ** 2).sum(-1)
    Q = np.exp(-gamma * d2) * np.outer(np.asarray(y), np.asarray(y))
    err = np.abs(np.asarray(H @ H.T) - Q).max()
    assert err < 1e-3


def test_psvm_rejects_regression(rng):
    X = rng.normal(size=(50, 2)).astype(np.float32)
    fr = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1],
                            "y": rng.normal(size=50).astype(np.float32)})
    with pytest.raises(ValueError):
        PSVM().train(y="y", training_frame=fr)
