"""Metrics parity tests (reference: hex/AUC2, ModelMetrics* semantics)."""

import numpy as np
import pytest
import jax.numpy as jnp

from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.metrics import binomial_metrics, multinomial_metrics, regression_metrics


def _device(rng, arr):
    return Vec.from_numpy(np.asarray(arr, np.float32)).data


def test_auc_matches_sklearn(rng):
    n = 5000
    y = (rng.uniform(size=n) < 0.4).astype(np.float32)
    p = np.clip(y * 0.3 + rng.uniform(size=n) * 0.7, 0, 1).astype(np.float32)
    pd_, yd = _device(rng, p), _device(rng, y)
    mask = jnp.arange(pd_.shape[0]) < n
    m = binomial_metrics(pd_, yd, mask)
    from sklearn.metrics import roc_auc_score, log_loss
    # 400-bin histogram AUC is exact to ~1/400 (reference accepts this too)
    assert abs(m.auc - roc_auc_score(y, p)) < 0.004
    assert abs(m.logloss - log_loss(y, np.clip(p, 1e-15, 1 - 1e-15))) < 1e-5
    assert m.nobs == n
    assert m.confusion_matrix.sum() == n


def test_regression_metrics(rng):
    n = 3000
    y = rng.normal(size=n).astype(np.float32)
    pred = y + rng.normal(scale=0.5, size=n).astype(np.float32)
    yd, pd_ = _device(rng, y), _device(rng, pred)
    mask = jnp.arange(yd.shape[0]) < n
    m = regression_metrics(pd_, yd, mask)
    np.testing.assert_allclose(m.mse, ((pred - y) ** 2).mean(), rtol=1e-4)
    np.testing.assert_allclose(m.mae, np.abs(pred - y).mean(), rtol=1e-4)
    ss_res = ((pred - y) ** 2).sum()
    ss_tot = ((y - y.mean()) ** 2).sum()
    np.testing.assert_allclose(m.r2, 1 - ss_res / ss_tot, atol=1e-4)


def test_multinomial_metrics(rng):
    n, k = 2000, 4
    y = rng.integers(0, k, size=n).astype(np.float32)
    logits = rng.normal(size=(n, k)).astype(np.float32)
    logits[np.arange(n), y.astype(int)] += 2.0
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    from h2o3_tpu.frame.vec import padded_len
    plen = padded_len(n)
    P = np.zeros((plen, k), np.float32)
    P[:n] = probs
    yd = _device(rng, y)
    mask = jnp.arange(plen) < n
    m = multinomial_metrics(jnp.asarray(P), yd, mask, k)
    from sklearn.metrics import log_loss, confusion_matrix
    np.testing.assert_allclose(m.logloss, log_loss(y, probs, labels=list(range(k))), rtol=1e-4)
    np.testing.assert_array_equal(m.confusion_matrix, confusion_matrix(y, probs.argmax(1)))
    assert m.accuracy > 0.7


def test_gains_lift_table(rng):
    """Reference: hex/GainsLift.java — table invariants at the last row:
    cumulative data fraction 1.0, cumulative capture rate 1.0, cum lift 1.0."""
    import jax.numpy as jnp
    from h2o3_tpu.models.metrics import binomial_metrics

    n = 4000
    p = rng.random(n).astype(np.float32)
    y = (rng.random(n) < p).astype(np.float32)   # well-calibrated scores
    m = binomial_metrics(jnp.asarray(p), jnp.asarray(y), jnp.ones(n, bool))
    gl = m.gains_lift(groups=16)
    assert 10 <= len(gl) <= 16
    last = gl[-1]
    assert last["cumulative_data_fraction"] == pytest.approx(1.0, abs=1e-9)
    assert last["cumulative_capture_rate"] == pytest.approx(1.0, abs=1e-9)
    assert last["cumulative_lift"] == pytest.approx(1.0, abs=1e-6)
    # calibrated scores → top group lift well above 1, monotone-ish capture
    assert gl[0]["lift"] > 1.5
    assert m.ks > 0.3
    # KS column max matches the scalar KS metric up to binning
    assert max(r["kolmogorov_smirnov"] for r in gl) == pytest.approx(m.ks, abs=0.05)
