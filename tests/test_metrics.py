"""Metrics parity tests (reference: hex/AUC2, ModelMetrics* semantics)."""

import numpy as np
import pytest
import jax.numpy as jnp

from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.metrics import binomial_metrics, multinomial_metrics, regression_metrics


def _device(rng, arr):
    return Vec.from_numpy(np.asarray(arr, np.float32)).data


def test_auc_matches_sklearn(rng):
    n = 5000
    y = (rng.uniform(size=n) < 0.4).astype(np.float32)
    p = np.clip(y * 0.3 + rng.uniform(size=n) * 0.7, 0, 1).astype(np.float32)
    pd_, yd = _device(rng, p), _device(rng, y)
    mask = jnp.arange(pd_.shape[0]) < n
    m = binomial_metrics(pd_, yd, mask)
    from sklearn.metrics import roc_auc_score, log_loss
    # 400-bin histogram AUC is exact to ~1/400 (reference accepts this too)
    assert abs(m.auc - roc_auc_score(y, p)) < 0.004
    assert abs(m.logloss - log_loss(y, np.clip(p, 1e-15, 1 - 1e-15))) < 1e-5
    assert m.nobs == n
    assert m.confusion_matrix.sum() == n


def test_regression_metrics(rng):
    n = 3000
    y = rng.normal(size=n).astype(np.float32)
    pred = y + rng.normal(scale=0.5, size=n).astype(np.float32)
    yd, pd_ = _device(rng, y), _device(rng, pred)
    mask = jnp.arange(yd.shape[0]) < n
    m = regression_metrics(pd_, yd, mask)
    np.testing.assert_allclose(m.mse, ((pred - y) ** 2).mean(), rtol=1e-4)
    np.testing.assert_allclose(m.mae, np.abs(pred - y).mean(), rtol=1e-4)
    ss_res = ((pred - y) ** 2).sum()
    ss_tot = ((y - y.mean()) ** 2).sum()
    np.testing.assert_allclose(m.r2, 1 - ss_res / ss_tot, atol=1e-4)


def test_multinomial_metrics(rng):
    n, k = 2000, 4
    y = rng.integers(0, k, size=n).astype(np.float32)
    logits = rng.normal(size=(n, k)).astype(np.float32)
    logits[np.arange(n), y.astype(int)] += 2.0
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    from h2o3_tpu.frame.vec import padded_len
    plen = padded_len(n)
    P = np.zeros((plen, k), np.float32)
    P[:n] = probs
    yd = _device(rng, y)
    mask = jnp.arange(plen) < n
    m = multinomial_metrics(jnp.asarray(P), yd, mask, k)
    from sklearn.metrics import log_loss, confusion_matrix
    np.testing.assert_allclose(m.logloss, log_loss(y, probs, labels=list(range(k))), rtol=1e-4)
    np.testing.assert_array_equal(m.confusion_matrix, confusion_matrix(y, probs.argmax(1)))
    assert m.accuracy > 0.7


def test_gains_lift_table(rng):
    """Reference: hex/GainsLift.java — table invariants at the last row:
    cumulative data fraction 1.0, cumulative capture rate 1.0, cum lift 1.0."""
    import jax.numpy as jnp
    from h2o3_tpu.models.metrics import binomial_metrics

    n = 4000
    p = rng.random(n).astype(np.float32)
    y = (rng.random(n) < p).astype(np.float32)   # well-calibrated scores
    m = binomial_metrics(jnp.asarray(p), jnp.asarray(y), jnp.ones(n, bool))
    gl = m.gains_lift(groups=16)
    assert 10 <= len(gl) <= 16
    last = gl[-1]
    assert last["cumulative_data_fraction"] == pytest.approx(1.0, abs=1e-9)
    assert last["cumulative_capture_rate"] == pytest.approx(1.0, abs=1e-9)
    assert last["cumulative_lift"] == pytest.approx(1.0, abs=1e-6)
    # calibrated scores → top group lift well above 1, monotone-ish capture
    assert gl[0]["lift"] > 1.5
    assert m.ks > 0.3
    # KS column max matches the scalar KS metric up to binning
    assert max(r["kolmogorov_smirnov"] for r in gl) == pytest.approx(m.ks, abs=0.05)


def test_auc2_threshold_criteria(rng):
    """AUC2 ThresholdCriterion table (reference hex/AUC2.java:24-36):
    max-F1 from the table must match the sweep, and counts must be
    consistent at every threshold."""
    import numpy as np
    from h2o3_tpu.models.metrics import binomial_metrics
    import jax.numpy as jnp

    n = 2000
    y = (rng.random(n) < 0.4).astype(np.float32)
    p = np.clip(0.6 * y + 0.4 * rng.random(n), 0, 1).astype(np.float32)
    mm = binomial_metrics(jnp.asarray(p), jnp.asarray(y),
                          jnp.ones(n, bool))
    cols, rows = mm.threshold_table()
    assert len(rows) == 400 and cols[0] == "threshold"
    mcols, mrows = mm.max_criteria_and_metric_scores()
    names = [r[0] for r in mrows]
    for crit in ("max f1", "max f2", "max f0point5", "max accuracy",
                 "max absolute_mcc", "max min_per_class_accuracy",
                 "max mean_per_class_accuracy", "max tps", "max tns"):
        assert crit in names
    j = {c: i for i, c in enumerate(cols)}
    P = y.sum()
    N = n - P
    for r in rows[::37]:
        assert abs(r[j["tps"]] + r[j["fns"]] - P) < 1e-6
        assert abs(r[j["fps"]] + r[j["tns"]] - N) < 1e-6
    # max f1 row agrees with a direct sweep over the same histogram grid
    f1_max_tbl = next(r[2] for r in mrows if r[0] == "max f1")
    f1s = [r[j["f1"]] for r in rows]
    assert abs(f1_max_tbl - max(f1s)) < 1e-12


def test_coxph_concordance(rng):
    """Harrell's C (reference CoxPH.java:737) — Fenwick path vs brute force,
    and a discriminating model scores > 0.5."""
    import numpy as np
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.coxph import CoxPH

    n = 250
    x = rng.normal(size=n)
    t = rng.exponential(scale=np.exp(-0.9 * x))
    e = (rng.random(n) < 0.75).astype(np.float32)
    fr = Frame.from_arrays({"x": x.astype(np.float32),
                            "t": t.astype(np.float32), "e": e})
    m = CoxPH(stop_column="t").train(x=["x"], y="e", training_frame=fr)
    c = m.concordance()
    assert 0.6 < c <= 1.0
    lp = m.output["train_lp"]; tt = m.output["train_time"]
    ee = m.output["train_event"]
    conc = disc = tied = 0
    for i in range(n):
        if ee[i] <= 0:
            continue
        for k in range(n):
            if tt[i] < tt[k]:
                if lp[i] > lp[k]:
                    conc += 1
                elif lp[i] < lp[k]:
                    disc += 1
                else:
                    tied += 1
    assert abs(c - (conc + 0.5 * tied) / (conc + disc + tied)) < 1e-9


def test_scoring_history_tree_glm_dl(rng):
    """scoring_history is populated for iterative builders (VERDICT r2 §3:
    reference SharedTree.java:798 doScoringAndSaveModel)."""
    import numpy as np
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import GBM
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.kmeans import KMeans

    n = 600
    X = rng.normal(size=(n, 2))
    y = (X[:, 0] - X[:, 1] + 0.5 * rng.normal(size=n) > 0)
    fr = Frame.from_arrays({"a": X[:, 0], "b": X[:, 1],
                            "y": np.array(["n", "p"], dtype=object)[y.astype(int)]})
    m = GBM(ntrees=8, max_depth=3, seed=1).train(y="y", training_frame=fr)
    cols, rows = m.scoring_history
    assert [c[0] for c in cols][:4] == ["timestamp", "duration",
                                        "number_of_trees", "training_deviance"]
    assert len(rows) == 8
    assert rows[0][3] > rows[-1][3]          # deviance decreases

    g = GLM(family="binomial", lambda_=1e-3).train(y="y", training_frame=fr)
    gcols, grows = g.scoring_history
    assert [c[0] for c in gcols][2:] == ["iterations",
                                         "negative_log_likelihood", "objective"]
    assert len(grows) >= 1

    km = KMeans(k=2, seed=1).train(x=["a", "b"], training_frame=fr)
    kcols, krows = km.scoring_history
    assert kcols[-1][0] == "within_cluster_sum_of_squares" and len(krows) >= 1


def test_gbm_early_stopping_fused_semantics(rng):
    """Fused chunked early stopping reproduces per-tree ScoreKeeper
    semantics: stopping triggers, history length == kept trees, and
    retraining with ntrees=K(kept) yields the identical ensemble."""
    import numpy as np
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import GBM

    n = 1500
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - X[:, 1] > 0)
    tr = Frame.from_arrays({"a": X[:1000, 0], "b": X[:1000, 1], "c": X[:1000, 2],
                            "y": np.array(["n", "p"], dtype=object)[y[:1000].astype(int)]})
    va = Frame.from_arrays({"a": X[1000:, 0], "b": X[1000:, 1], "c": X[1000:, 2],
                            "y": np.array(["n", "p"], dtype=object)[y[1000:].astype(int)]})
    m = GBM(ntrees=150, max_depth=3, seed=5, stopping_rounds=3,
            stopping_tolerance=1e-3).train(y="y", training_frame=tr,
                                           validation_frame=va)
    k = len(m.output["trees"])
    assert k < 150
    assert len(m.scoring_history[1]) == k
    m2 = GBM(ntrees=k, max_depth=3, seed=5).train(y="y", training_frame=tr,
                                                  validation_frame=va)
    import jax
    for t1, t2 in zip(m.output["trees"], m2.output["trees"]):
        np.testing.assert_array_equal(np.asarray(jax.device_get(t1.feat)),
                                      np.asarray(jax.device_get(t2.feat)))
        np.testing.assert_allclose(np.asarray(jax.device_get(t1.leaf)),
                                   np.asarray(jax.device_get(t2.leaf)),
                                   rtol=1e-6)
