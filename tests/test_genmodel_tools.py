"""PrintMojo + EasyPredictModelWrapper (reference: h2o-genmodel
tools/PrintMojo.java, easy/EasyPredictModelWrapper.java)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.genmodel.tools import EasyPredictModelWrapper, print_mojo
from h2o3_tpu.models.gbm import GBM


@pytest.fixture
def cat_model(rng):
    n = 200
    x = rng.normal(size=n).astype(np.float32)
    cat = rng.choice(["a", "b", "c"], size=n).astype(object)
    logit = x + (cat == "a")
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    fr = Frame.from_arrays({"x": x, "cat": cat, "y": y.astype(object)})
    return GBM(ntrees=3, max_depth=3, seed=1).train(y="y",
                                                    training_frame=fr), fr


def test_print_mojo_dot_and_list(tmp_path, cat_model):
    m, _ = cat_model
    dot = print_mojo(m, fmt="dot")
    assert dot.count("digraph") == 3
    assert "x < " in dot or "∈" in dot
    assert "leaf = " in dot
    # also via a MOJO file path (the CLI path)
    path = str(tmp_path / "m.mojo")
    m.download_mojo(path)
    listing = print_mojo(path, fmt="list", max_trees=1)
    assert listing.startswith("tree 0")


def test_easy_predict_row_matches_frame(cat_model):
    m, fr = cat_model
    wrap = EasyPredictModelWrapper(m)
    preds = m.predict(fr)
    want_lab = preds.vec("predict").labels()
    want_p = np.asarray(preds.vec("pyes").to_numpy())
    xs = fr.vec("x").to_numpy()
    cats = fr.vec("cat").labels()
    one = wrap.predict({"x": float(xs[0]), "cat": cats[0]})
    assert one["label"] == want_lab[0]
    assert one["class_probabilities"]["yes"] == pytest.approx(
        float(want_p[0]), abs=1e-6)
    batch = wrap.predict_batch(
        [{"x": float(xs[i]), "cat": cats[i]} for i in range(5)])
    for i, b in enumerate(batch):
        assert b["label"] == want_lab[i]
    # missing + unseen level rows still score
    assert "label" in wrap.predict({"cat": "zzz"})
