"""C++ standalone MOJO scorer parity (reference: the h2o-genmodel Java
runtime scoring a MOJO outside the cluster — here ``native/mojo_scorer.cpp``
scores the v2 artifact with zero Python/JAX, proving the format is
language-neutral)."""

import shutil
import subprocess
import sys

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.genmodel.mojo import write_mojo
from h2o3_tpu.models.gbm import DRF, GBM

REPO = __file__.rsplit("/tests/", 1)[0]

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def scorer(tmp_path_factory):
    exe = tmp_path_factory.mktemp("mojo") / "mojo_score"
    subprocess.run(["g++", "-O2", "-std=c++17",
                    f"{REPO}/native/mojo_scorer.cpp", "-lz", "-o", str(exe)],
                   check=True, capture_output=True)
    return str(exe)


def _csv(path, cols: dict):
    names = list(cols)
    n = len(next(iter(cols.values())))
    with open(path, "w") as f:
        f.write(",".join(names) + "\n")
        for i in range(n):
            f.write(",".join("" if (isinstance(cols[c][i], float)
                                    and np.isnan(cols[c][i]))
                             else str(cols[c][i]) for c in names) + "\n")


def _run(scorer, mojo, csv):
    out = subprocess.run([scorer, mojo, csv], capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    return [l.split(",") for l in out.stdout.strip().splitlines()]


@pytest.fixture
def data(rng):
    n = 250
    # float32-exact values: the frame stores f32, the CSV must carry the
    # same numbers or threshold-boundary rows route differently
    x0 = rng.normal(size=n).astype(np.float32).astype(np.float64)
    x1 = rng.normal(size=n).astype(np.float32).astype(np.float64)
    x1[5] = np.nan                      # NA routing must match
    cat = rng.choice(["red", "green", "blue"], size=n).astype(object)
    return n, x0, x1, cat


def test_cpp_scorer_gbm_regression_with_cats(tmp_path, scorer, data, rng):
    n, x0, x1, cat = data
    t = x0 * 2 + (cat == "red") + 0.1 * rng.normal(size=n)
    fr = Frame.from_arrays({"x0": x0.astype(np.float32),
                            "x1": x1.astype(np.float32), "cat": cat,
                            "t": t.astype(np.float32)})
    m = GBM(ntrees=7, max_depth=4, seed=1).train(y="t", training_frame=fr)
    mojo = write_mojo(m, str(tmp_path / "m.mojo"))
    _csv(tmp_path / "d.csv", {"x0": x0, "x1": x1, "cat": cat})
    got = np.array([float(r[0]) for r in _run(scorer, mojo,
                                              str(tmp_path / "d.csv"))])
    want = np.asarray(m.predict(fr).vec("predict").to_numpy(), np.float64)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_cpp_scorer_gbm_binomial(tmp_path, scorer, data, rng):
    n, x0, x1, cat = data
    logit = 1.5 * x0 - np.nan_to_num(x1) + (cat == "blue")
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    fr = Frame.from_arrays({"x0": x0.astype(np.float32),
                            "x1": x1.astype(np.float32), "cat": cat,
                            "y": y.astype(object)})
    m = GBM(ntrees=6, max_depth=3, seed=2).train(y="y", training_frame=fr)
    mojo = write_mojo(m, str(tmp_path / "m.mojo"))
    _csv(tmp_path / "d.csv", {"x0": x0, "x1": x1, "cat": cat})
    rows = _run(scorer, mojo, str(tmp_path / "d.csv"))
    preds = m.predict(fr)
    want_p = np.asarray(preds.vec("pyes").to_numpy(), np.float64)
    got_p = np.array([float(r[2]) for r in rows])
    np.testing.assert_allclose(got_p, want_p, rtol=1e-5, atol=1e-6)
    want_lab = list(preds.vec("predict").labels())
    assert [r[0] for r in rows] == want_lab


def test_cpp_scorer_gbm_multinomial(tmp_path, scorer, rng):
    n = 240
    X = rng.normal(size=(n, 3)).astype(np.float32).astype(np.float64)
    y = np.array(["a", "b", "c"])[np.argmax(X + 0.3 * rng.normal(size=(n, 3)),
                                            axis=1)]
    fr = Frame.from_arrays({"x0": X[:, 0].astype(np.float32),
                            "x1": X[:, 1].astype(np.float32),
                            "x2": X[:, 2].astype(np.float32),
                            "y": y.astype(object)})
    m = GBM(ntrees=5, max_depth=3, seed=3).train(y="y", training_frame=fr)
    mojo = write_mojo(m, str(tmp_path / "m.mojo"))
    _csv(tmp_path / "d.csv", {"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2]})
    rows = _run(scorer, mojo, str(tmp_path / "d.csv"))
    preds = m.predict(fr)
    for k, dom in enumerate(["a", "b", "c"]):
        want = np.asarray(preds.vec(f"p{dom}").to_numpy(), np.float64)
        got = np.array([float(r[1 + k]) for r in rows])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_cpp_scorer_drf(tmp_path, scorer, data, rng):
    n, x0, x1, cat = data
    logit = x0 + (cat == "red")
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    fr = Frame.from_arrays({"x0": x0.astype(np.float32),
                            "x1": x1.astype(np.float32), "cat": cat,
                            "y": y.astype(object)})
    m = DRF(ntrees=6, max_depth=4, seed=4).train(y="y", training_frame=fr)
    mojo = write_mojo(m, str(tmp_path / "m.mojo"))
    _csv(tmp_path / "d.csv", {"x0": x0, "x1": x1, "cat": cat})
    rows = _run(scorer, mojo, str(tmp_path / "d.csv"))
    want_p = np.asarray(m.predict(fr).vec("pyes").to_numpy(), np.float64)
    got_p = np.array([float(r[2]) for r in rows])
    np.testing.assert_allclose(got_p, want_p, rtol=1e-5, atol=1e-6)


def test_cpp_scorer_unseen_level_routes_na(tmp_path, scorer, data, rng):
    n, x0, x1, cat = data
    t = x0 + (cat == "red")
    fr = Frame.from_arrays({"x0": x0.astype(np.float32), "cat": cat,
                            "t": t.astype(np.float32)})
    m = GBM(ntrees=4, max_depth=3, seed=5).train(y="t", training_frame=fr)
    mojo = write_mojo(m, str(tmp_path / "m.mojo"))
    # a level never seen in training maps to NA (reference: unseen levels
    # score as missing), plus an empty numeric cell
    _csv(tmp_path / "d.csv", {"x0": [0.5, np.nan], "cat": ["violet", "red"]})
    rows = _run(scorer, mojo, str(tmp_path / "d.csv"))
    assert len(rows) == 2 and all(np.isfinite(float(r[0])) for r in rows)
