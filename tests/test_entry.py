"""Gate-artifact robustness: the driver entry points must survive a sick or
absent TPU backend (VERDICT r3 weak #1 — round 3 lost BOTH proof artifacts
to one unavailable chip: ``dryrun_multichip`` hung 600 s because the parent
called ``jax.devices()``, and ``bench.py`` recorded a traceback).

Reference analog: the N-JVM localhost cloud always forms regardless of
cluster state (``scripts/multiNodeUtils.sh:21-26``).
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sick_env(n_cpu_flag: str | None = None) -> dict:
    """A driver-like env where initializing the default JAX backend FAILS:
    JAX_PLATFORMS names a platform that does not exist, so any parent-side
    ``jax.devices()`` raises immediately (simulating the round-3 wedged TPU
    without needing TPU hardware to be sick on cue)."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "sick_tpu_simulated"
    if n_cpu_flag:
        env["XLA_FLAGS"] = n_cpu_flag
    return env


def test_env_probe_never_inits_backend():
    import __graft_entry__ as g

    saved = {k: os.environ.get(k) for k in ("JAX_PLATFORMS", "XLA_FLAGS")}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        assert g._env_proves_cpu_devices(8)
        assert g._env_proves_cpu_devices(4)
        assert not g._env_proves_cpu_devices(16)
        os.environ["JAX_PLATFORMS"] = "tpu"
        assert not g._env_proves_cpu_devices(1)
        os.environ["JAX_PLATFORMS"] = "cpu"
        del os.environ["XLA_FLAGS"]
        assert not g._env_proves_cpu_devices(2)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_dryrun_completes_with_sick_backend():
    """dryrun_multichip must complete on the CPU-subprocess path in < 90 s
    even when the default backend is broken — the parent never initializes
    JAX, so the poisoned JAX_PLATFORMS is never even seen by a backend."""
    code = "import __graft_entry__ as g; g.dryrun_multichip(4)"
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=_sick_env(),
        capture_output=True, text=True, timeout=180)
    dt = time.perf_counter() - t0
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "weak_scaling" in proc.stdout
    assert dt < 90, f"dryrun took {dt:.0f}s with a sick backend"


def test_bench_smoke_falls_back_to_cpu_with_sick_backend():
    """bench.py must emit ONE parseable JSON line (rc=0) with an explicit
    backend_fallback annotation when the TPU backend cannot initialize."""
    env = _sick_env()
    env["H2O3TPU_BENCH_SMOKE"] = "1"
    # the sick platform plugin BLOCKS during discovery in this environment
    # (exactly the round-3 failure mode); don't wait the production 240 s
    env["H2O3TPU_BENCH_PREFLIGHT_TIMEOUT"] = "25"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    out = json.loads(line)
    assert out["metric"] == "gbm_hist_train_rows_per_sec_per_chip"
    assert out["value"] > 0
    assert "backend_fallback" in out["extra"], out["extra"]
    assert out["extra"]["backend"] == "cpu"
    # a fallback capture is a liveness probe, not evidence vs the per-chip
    # baseline: the ratio must be null so it can never be read as one
    assert out["vs_baseline"] is None
