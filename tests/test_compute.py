"""Compute observatory tests (ISSUE 10): XLA cost accounting, recompile
attribution with signature diffs, utilization-or-null on unknown backends,
the ``/3/Compute`` + ``/3/Profiler`` REST surface, per-site compile-cache
attribution, and the overhead contract (no device sync on the unsampled
dispatch path; traced-vs-off GLM wall time inside the tracer's envelope).
"""

import gzip
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from h2o3_tpu import Frame
from h2o3_tpu.api import H2OServer
from h2o3_tpu.api.client import H2OClient
from h2o3_tpu.models import GBM, GLM
from h2o3_tpu.utils import costs as costs_mod
from h2o3_tpu.utils.costs import (COSTS, accounted_jit, backend_peak,
                                  signature_diff)

# -- signatures and diffs -----------------------------------------------------


def _sig(*shapes, statics=None):
    return {"args": [{"shape": list(s), "dtype": "float32"} for s in shapes],
            "statics": statics or {}}


def test_signature_diff_names_changed_dimension():
    d = signature_diff(_sig((2048, 12)), _sig((3008, 12)))
    assert d == ["arg0.shape[0]: 2048 -> 3008"]


def test_signature_diff_names_dtype_rank_statics_and_arity():
    old = _sig((8, 4), statics={"k": "5"})
    new = {"args": [{"shape": [8, 4, 1], "dtype": "bfloat16"}],
           "statics": {"k": "9"}}
    d = signature_diff(old, new)
    assert "arg0.rank: 2 -> 3" in d
    assert "arg0.dtype: float32 -> bfloat16" in d
    assert "static k: 5 -> 9" in d
    d2 = signature_diff(_sig((4,)), _sig((4,), (4,)))
    assert "arg count: 1 -> 2" in d2


def test_backend_peak_table_and_unknown_kinds():
    assert backend_peak("TPU v5 lite chip")["name"] == "TPU v5e"
    assert backend_peak("TPU v5e")["flops_per_sec"] == pytest.approx(197e12)
    assert backend_peak("TPU v4")["name"] == "TPU v4"
    # unknown kinds (this CPU container, future chips): None, never 0,
    # never an exception
    assert backend_peak("cpu") is None
    assert backend_peak("Radical New Accelerator 9000") is None
    assert backend_peak() is None          # default backend here is CPU


# -- CostMeter recording ------------------------------------------------------


def test_recompile_event_only_on_new_signature():
    COSTS.clear()
    COSTS.record_compile("t:site", _sig((8, 2)), 0.5, 100.0, 400.0)
    # same signature again (fresh-lambda churn): counted, NOT a recompile
    COSTS.record_compile("t:site", _sig((8, 2)), 0.2, 100.0, 400.0)
    [site] = [s for s in COSTS.snapshot()["sites"] if s["site"] == "t:site"]
    assert site["compiles"] == 2
    assert len(site["signatures"]) == 1
    assert site["recompile_events"] == []
    assert site["compile_seconds"] == pytest.approx(0.7)
    # a genuinely new signature IS a recompile event, with the diff
    COSTS.record_compile("t:site", _sig((16, 2)), 0.1, 150.0, 500.0)
    [site] = [s for s in COSTS.snapshot()["sites"] if s["site"] == "t:site"]
    [ev] = site["recompile_events"]
    assert ev["diff"] == ["arg0.shape[0]: 8 -> 16"]
    assert COSTS.recompile_count() == 1


def test_observe_on_unknown_backend_reports_null_utilization():
    COSTS.clear()
    COSTS.record_compile("t:loop", _sig((8,)), 0.1, 1e6, 2e6, loop="toy")
    COSTS.observe("t:loop", 0.01)
    loops = COSTS.snapshot()["loops"]
    st = loops["toy"]
    assert st["achieved_flops_per_sec"] == pytest.approx(1e8)
    assert st["achieved_bytes_per_sec"] == pytest.approx(2e8)
    assert st["arithmetic_intensity"] == pytest.approx(0.5)
    # CPU is off the peak table: utilization is null — not 0, no exception
    assert st["utilization"] is None
    assert st["roofline"] is None
    assert COSTS.snapshot()["peak"] is None


# -- the accounted jit wrapper ------------------------------------------------


def test_accounted_jit_records_cost_and_recompile_diff():
    COSTS.clear()

    @accounted_jit("t:matmul", loop="toy_loop")
    def mm(a, b):
        return a @ b

    x = jnp.ones((32, 32), jnp.float32)
    np.testing.assert_allclose(mm(x, x), np.full((32, 32), 32.0))
    mm(x, x)                               # same signature: cached
    [site] = [s for s in COSTS.snapshot()["sites"] if s["site"] == "t:matmul"]
    assert site["compiles"] == 1           # one executable, reused
    assert site["loop"] == "toy_loop"
    assert site["flops"] and site["flops"] > 0
    assert site["bytes"] and site["bytes"] > 0
    assert site["compile_seconds"] > 0
    y = jnp.ones((64, 32), jnp.float32)
    mm(y, x)                               # shape change: recompile event
    [site] = [s for s in COSTS.snapshot()["sites"] if s["site"] == "t:matmul"]
    [ev] = site["recompile_events"]
    assert "arg0.shape[0]: 32 -> 64" in ev["diff"]


def test_accounted_jit_static_change_named_in_diff():
    COSTS.clear()

    @accounted_jit("t:statics", static_argnames=("k",))
    def scale(x, k):
        return x * k

    x = jnp.ones(8, jnp.float32)
    scale(x, k=2)
    scale(x, k=3)
    [site] = [s for s in COSTS.snapshot()["sites"] if s["site"] == "t:statics"]
    [ev] = site["recompile_events"]
    assert any(d.startswith("static k:") for d in ev["diff"])


def test_accounted_jit_nested_in_trace_falls_through():
    COSTS.clear()
    inner = accounted_jit("t:inner", lambda x: x * 2.0)

    @jax.jit
    def outer(x):
        return inner(x) + 1.0              # leaves are tracers here

    np.testing.assert_allclose(outer(jnp.ones(4)), np.full(4, 3.0))
    # the OUTER program owns the compile: the wrapper recorded nothing
    assert all(s["site"] != "t:inner" for s in COSTS.snapshot()["sites"])


def test_costs_off_bypasses_recording(monkeypatch):
    COSTS.clear()
    monkeypatch.setenv("H2O3TPU_COSTS_OFF", "1")
    w = accounted_jit("t:off", lambda x: x + 1.0)
    np.testing.assert_allclose(w(jnp.ones(4)), np.full(4, 2.0))
    assert COSTS.snapshot()["sites"] == []


def test_sampled_probe_attributes_executed_signature(monkeypatch):
    """A site holding several live signatures (full GBM chunk + remainder
    chunk) must rate each sampled execution against the cost of the
    signature that RAN, not the site's most recent compile."""
    COSTS.clear()
    monkeypatch.setenv("H2O3TPU_COSTS_SAMPLE", "1")   # sample every call
    w = accounted_jit("t:multi", lambda a: a @ a)
    small = jnp.ones((8, 8), jnp.float32)
    big = jnp.ones((64, 64), jnp.float32)
    w(small)
    w(big)                                 # big is now the LATEST compile
    [site] = [s for s in COSTS.snapshot()["sites"] if s["site"] == "t:multi"]
    by_shape = {tuple(s["signature"]["args"][0]["shape"]): s["flops"]
                for s in site["signatures"]}
    assert by_shape[(8, 8)] < by_shape[(64, 64)]
    seen = []
    orig = COSTS.observe
    monkeypatch.setattr(
        COSTS, "observe",
        lambda site, secs, flops=None, nbytes=None: seen.append(flops))
    w(small)                               # sampled: must carry SMALL's cost
    assert seen == [by_shape[(8, 8)]]
    monkeypatch.setattr(COSTS, "observe", orig)


def test_unsampled_dispatch_path_never_syncs(monkeypatch):
    """Cost accounting must not add a device sync on the unsampled path:
    the only sync the wrapper owns is the sampled achieved-FLOPs probe, and
    with the sample period pushed out of reach, zero ``block_until_ready``
    calls may happen across repeated dispatches."""
    COSTS.clear()
    w = accounted_jit("t:nosync", lambda x: x * 3.0)
    x = jnp.ones(16, jnp.float32)
    w(x)                                   # call 0: compiles + sampled probe
    monkeypatch.setenv("H2O3TPU_COSTS_SAMPLE", "1000000")
    real = jax.block_until_ready
    calls = []
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda v: (calls.append(1), real(v))[1])
    for _ in range(10):
        w(x)
    assert calls == []


def test_observe_folds_flops_into_active_mesh_slice():
    """Under an active slice lease the sampled FLOPs credit the slice's
    row in /3/Cloud's mesh_slices (achieved_flops) — the observatory's
    'where did the arithmetic run' half of the PR 9 utilization view."""
    from h2o3_tpu.orchestration import scheduler
    COSTS.clear()
    scheduler.SLICE_STATS.reset()
    COSTS.record_compile("t:sliced", _sig((8,)), 0.1, 5e5, 1e6, loop="toy")
    token = scheduler._ACTIVE_SLICE.set("full")
    try:
        COSTS.observe("t:sliced", 0.01)
    finally:
        scheduler._ACTIVE_SLICE.reset(token)
    try:
        [row] = [r for r in scheduler.SLICE_STATS.snapshot()["slices"]
                 if r["slice"] == "full"]
        assert row["achieved_flops"] == pytest.approx(5e5)
    finally:
        scheduler.SLICE_STATS.reset()


# -- per-site compile-cache attribution ---------------------------------------


def test_compile_cache_events_credit_active_site():
    from h2o3_tpu.utils import compile_cache
    base = compile_cache.stats()
    with COSTS.scope("fit:test_algo"):
        compile_cache._on_event("/jax/compilation_cache/cache_misses")
        compile_cache._on_event("/jax/compilation_cache/cache_hits")
    compile_cache._on_event("/jax/compilation_cache/cache_hits")
    st = compile_cache.stats()
    per = st["by_site"]["fit:test_algo"]
    base_per = (base["by_site"].get("fit:test_algo")
                or {"hits": 0, "misses": 0})
    assert per["misses"] - base_per["misses"] == 1
    assert per["hits"] - base_per["hits"] == 1
    unattr = st["by_site"]["(unattributed)"]["hits"] \
        - (base["by_site"].get("(unattributed)") or {"hits": 0})["hits"]
    assert unattr == 1


def test_model_fit_runs_under_site_scope(rng):
    """ModelBuilder.train wraps _fit in COSTS.scope(f"fit:{algo}") so cache
    events during a build credit the algo; verify the scope is live inside
    the fit by observing it from a map_reduce-adjacent hook."""
    seen = []

    class Probe(GLM):
        def _fit(self, job, frame, x, y, w):
            seen.append(COSTS.active_site())
            return super()._fit(job, frame, x, y, w)

    X = rng.normal(size=(256, 3))
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = X @ np.ones(3)
    Probe(family="gaussian").train(y="y", training_frame=Frame.from_arrays(cols))
    assert seen == ["fit:glm"]


# -- REST surface: /3/Compute acceptance --------------------------------------


@pytest.fixture(scope="module")
def server():
    s = H2OServer(port=0).start()
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return json.loads(r.read())


def _train_frame(nrows, ncols=4, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(nrows, ncols))
    cols = {f"x{i}": X[:, i] for i in range(ncols)}
    cols["y"] = np.where(X[:, 0] + 0.1 * rng.normal(size=nrows) > 0,
                         "yes", "no")
    return Frame.from_arrays(cols)


def test_compute_endpoint_acceptance(server):
    """The ISSUE 10 acceptance flow: a fresh GBM + GLM and a warmed scoring
    signature each show >= 1 executable with nonzero cost_analysis FLOPs /
    bytes and compile seconds; a deliberately shape-changed second GLM
    build records EXACTLY ONE recompile event whose diff names the changed
    dimension; on this CPU-only run utilization is null — not 0, and not
    an exception."""
    COSTS.clear()
    fr = _train_frame(600)
    gbm = GBM(ntrees=3, max_depth=3, model_id="cmp_gbm").train(
        y="y", training_frame=fr)
    GLM(family="binomial", lambda_=1e-4, model_id="cmp_glm").train(
        y="y", training_frame=fr)
    client = H2OClient(server.url)
    payload = [{f"x{i}": 0.5 for i in range(4)}] * 4
    client.score(gbm.key, payload)         # compile the scoring signature
    client.score(gbm.key, payload)         # ... and hit it warm

    snap = client.compute()
    sites = {s["site"]: s for s in snap["sites"]}
    for needed in ("gbm:boost_scan", "glm:irls_megastep", "score:gbm"):
        assert needed in sites, sorted(sites)
        s = sites[needed]
        assert s["compiles"] >= 1
        assert s["flops"] and s["flops"] > 0, needed
        assert s["bytes"] and s["bytes"] > 0, needed
        assert s["compile_seconds"] > 0
    # CPU-only: no peak row, every published loop utilization is null
    assert snap["peak"] is None
    assert snap["device_kind"] == "cpu"
    assert snap["loops"], "sampled probes should have published loops"
    for st in snap["loops"].values():
        assert st["utilization"] is None
        assert st["achieved_flops_per_sec"] > 0

    # deliberately shape-changed second build: wider X changes the IRLS
    # signature's feature dimension. (The first build may legitimately
    # record a device-set recompile — beta starts single-device before the
    # loop shards it — so assert on SHAPE-diff events specifically.)
    irls = sites["glm:irls_megastep"]
    assert not any(".shape[" in d for e in irls["recompile_events"]
                   for d in e["diff"]), irls["recompile_events"]
    GLM(family="binomial", lambda_=1e-4, model_id="cmp_glm2").train(
        y="y", training_frame=_train_frame(600, ncols=6))
    snap2 = _get(server, "/3/Compute")
    [irls] = [s for s in snap2["sites"] if s["site"] == "glm:irls_megastep"]
    # exactly ONE recompile event names the changed dimension — and it
    # names the RIGHT one (the feature dim we widened, 4 -> 6)
    shape_evs = [e for e in irls["recompile_events"]
                 if any(".shape[" in d for d in e["diff"])]
    assert len(shape_evs) == 1, irls["recompile_events"]
    assert any(d.startswith("arg0.shape[1]: 4 -> 6")
               for d in shape_evs[0]["diff"]), shape_evs[0]["diff"]
    assert snap2["recompile_events"] >= 1


def test_compute_schema_meta(server):
    snap = _get(server, "/3/Compute")
    assert snap["__meta"]["schema_type"] == "ComputeV3"
    assert {"backend", "sites", "loops", "signature_count"} <= set(snap)


# -- REST surface: profiler capture lifecycle ---------------------------------


def _post(server, path):
    req = urllib.request.Request(server.url + path, data=b"", method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_profiler_capture_roundtrip(server):
    rec = _post(server, "/3/Profiler/capture?duration_ms=120")
    assert rec["capture_id"].startswith("cap_")
    assert rec["artifact"] and rec["bytes"] > 0
    caps = _get(server, "/3/Profiler/captures")["captures"]
    assert any(c["capture_id"] == rec["capture_id"] for c in caps)
    # the artifact is a Perfetto-loadable gzip Chrome trace whose events
    # carry span-derived annotations (TraceAnnotation long_name)
    url = f"{server.url}/3/Profiler/captures/{rec['capture_id']}/download"
    with urllib.request.urlopen(url) as r:
        assert r.headers["Content-Type"] == "application/gzip"
        body = r.read()
    doc = json.loads(gzip.decompress(body))
    events = doc["traceEvents"]
    assert events
    assert any(e.get("args", {}).get("long_name") == "profiler:exercise"
               for e in events), "span-derived annotation missing"


def test_profiler_concurrent_capture_409(server):
    from h2o3_tpu.utils.profiling import PROFILER, CaptureBusy
    assert PROFILER._busy.acquire(blocking=False)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server, "/3/Profiler/capture?duration_ms=50")
        assert ei.value.code == 409
        err = json.loads(ei.value.read())
        assert err["http_status"] == 409
        assert "in progress" in err["msg"]
        with pytest.raises(CaptureBusy):
            PROFILER.capture(duration_ms=50)
    finally:
        PROFILER._busy.release()


def test_profiler_unknown_capture_download_404(server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(server, "/3/Profiler/captures/cap_nope/download")
    assert ei.value.code in (400, 404)


# -- overhead envelope --------------------------------------------------------


@pytest.mark.slow
def test_costs_overhead_within_tracer_envelope(rng, monkeypatch):
    """Accounted GLM build vs ``H2O3TPU_COSTS_OFF=1``, min-of-3 each:
    the observatory is held to the same <2% always-on envelope as the
    tracer (bench `_tracing_gate`). Sub-second CPU builds put 2% under
    scheduler noise, so the assertion carries a small absolute floor —
    the bench enforces the pure ratio at real scale."""
    import time

    X = rng.normal(size=(60_000, 8)).astype(np.float32)
    cols = {f"x{i}": X[:, i] for i in range(8)}
    cols["y"] = (X[:, 0] - 0.5 * X[:, 1]
                 + 0.1 * rng.normal(size=60_000)).astype(np.float32)
    fr = Frame.from_arrays(cols)

    def build():
        GLM(family="gaussian", lambda_=1e-4, max_iterations=12).train(
            y="y", training_frame=fr)

    def timed():
        t0 = time.perf_counter()
        build()
        return time.perf_counter() - t0

    build()                                # warm-up: compiles out of timing
    jax.effects_barrier()
    t_on = min(timed() for _ in range(3))
    monkeypatch.setenv("H2O3TPU_COSTS_OFF", "1")
    build()                                # warm the plain-jit path too
    jax.effects_barrier()
    t_off = min(timed() for _ in range(3))
    assert t_on <= t_off * 1.02 + 0.05, (t_on, t_off)
