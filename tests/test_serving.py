"""Scoring tier (serving/): compiled signatures, micro-batching, residency.

Acceptance (ISSUE 6): batched results bit-identical to unbatched
``Model.predict``; the second same-shape request compiles nothing
(scorer-cache hit counter); a forced-low-watermark run evicts the cold
model and keeps serving the hot one with 503/retry, never an OOM; a
thread-pool of concurrent clients on ``/3/Score`` coalesces into shared
device dispatches and every client gets its own correct slice back.
"""

import threading

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.serving import (SCORING, NotServable, ServiceUnavailable,
                              bucket_for, serving_schema)
from h2o3_tpu.utils.registry import DKV


@pytest.fixture(autouse=True)
def _reset_scoring():
    SCORING.reset()
    SCORING.budget_bytes = None
    yield
    SCORING.reset()
    SCORING.budget_bytes = None


@pytest.fixture
def frame(rng):
    n = 400
    X = rng.normal(size=(n, 3)).astype(np.float32)
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["c"] = np.array(["a" if v > 0 else "b" for v in X[:, 2]],
                         dtype=object)
    cols["y"] = np.where(X[:, 0] - X[:, 1] > 0, "yes", "no")
    fr = Frame.from_arrays(cols, key="serve_frame")
    DKV.put("serve_frame", fr)
    return fr


@pytest.fixture
def gbm(frame):
    from h2o3_tpu.models.gbm import GBM
    return GBM(ntrees=4, max_depth=3, seed=7,
               model_id="serve_gbm").train(y="y", training_frame=frame)


@pytest.fixture
def glm(frame):
    from h2o3_tpu.models.glm import GLM
    return GLM(family="binomial", lambda_=1e-4,
               model_id="serve_glm").train(y="y", training_frame=frame)


def _rows(frame, n, start=0):
    names = [c for c in frame.names if c != "y"]
    pdf = frame[names].to_pandas().iloc[start:start + n]
    return [{k: (v if isinstance(v, str) else float(v))
             for k, v in rec.items()}
            for rec in pdf.to_dict(orient="records")]


class TestSchemaAndBuckets:
    def test_bucket_for_powers_of_two(self):
        assert bucket_for(1) == 8 and bucket_for(8) == 8
        assert bucket_for(9) == 16 and bucket_for(100) == 128
        from h2o3_tpu.serving.scorer import MAX_BUCKET
        assert bucket_for(10 ** 9) == MAX_BUCKET

    def test_schema_tree_and_datainfo_paths(self, gbm, glm):
        st = serving_schema(gbm)
        assert st.cat_cols == ["c"] and set(st.num_cols) == {"x0", "x1", "x2"}
        sg = serving_schema(glm)
        assert sg.cat_cols == ["c"] and sg.domains["c"] == ("a", "b")

    def test_frame_is_not_servable(self, frame):
        with pytest.raises((NotServable, KeyError)):
            SCORING.score("serve_frame", [{"x0": 1.0}])

    def test_rows_as_lists_need_all_columns(self, gbm):
        schema = serving_schema(gbm)
        with pytest.raises(ValueError, match="lack model columns"):
            schema.adapt_rows([[1.0, 2.0]], columns=["x0", "x1"])


class TestBitIdentical:
    def test_batched_equals_predict(self, frame, gbm, glm):
        """/3/Score results must be bit-identical to the frame path."""
        rows = _rows(frame, 17)
        names = [c for c in frame.names if c != "y"]
        sub = Frame(names, [frame.vec(c) for c in names])
        for model in (gbm, glm):
            out = SCORING.score(model.key, rows)["predictions"]
            pred = model.predict(sub)
            got_p = np.asarray(out["pyes"], dtype=np.float32)
            want_p = np.asarray(pred.vec("pyes").to_numpy())[:17]
            assert np.array_equal(got_p, want_p), model.algo
            want_lbl = [str(v) for v in pred.vec("predict").labels()[:17]]
            assert out["predict"] == want_lbl

    def test_second_same_shape_request_hits_cache(self, frame, gbm):
        rows = _rows(frame, 5)
        SCORING.score(gbm.key, rows)
        stats0 = SCORING.cache.stats()
        assert stats0["misses"] >= 1
        SCORING.score(gbm.key, _rows(frame, 5, start=50))
        stats1 = SCORING.cache.stats()
        assert stats1["misses"] == stats0["misses"], \
            "second same-signature request must compile nothing"
        assert stats1["hits"] == stats0["hits"] + 1

    def test_oversized_request_slices_through_max_bucket(self, frame, gbm,
                                                         monkeypatch):
        import h2o3_tpu.serving.batcher as batcher_mod
        monkeypatch.setattr(batcher_mod, "MAX_BUCKET", 16)
        rows = _rows(frame, 40)
        out = SCORING.score(gbm.key, rows)
        assert len(out["predictions"]["predict"]) == 40
        names = [c for c in frame.names if c != "y"]
        pred = gbm.predict(Frame(names, [frame.vec(c) for c in names]))
        want = np.asarray(pred.vec("pyes").to_numpy())[:40]
        assert np.array_equal(
            np.asarray(out["predictions"]["pyes"], np.float32), want)

    def test_missing_and_unseen_values_score(self, frame, gbm):
        out = SCORING.score(gbm.key, [
            {"x0": 1.0, "x1": None, "x2": 0.5, "c": "a"},
            {"x0": 0.0, "x1": 2.0, "x2": -1.0, "c": "NEVER_SEEN"},
            {"x1": 1.0},
        ])
        assert len(out["predictions"]["predict"]) == 3

    def test_out_of_range_enum_code_treated_as_na(self, gbm):
        """A raw code past the domain is an UNSEEN value → NA, identical to
        an unknown label — never silently clamped to a training level."""
        schema = serving_schema(gbm)
        _num, cat = schema.adapt_rows([{"c": 7}, {"c": -5}, {"c": 1},
                                       {"c": "NOPE"}])
        assert cat[:, 0].tolist() == [-1, -1, 1, -1]

    def test_mixed_row_kinds_are_400_not_500(self, frame, gbm):
        with pytest.raises(ValueError, match="malformed"):
            SCORING.score(gbm.key, [{"x0": 1.0}, [1.0, 2.0, 3.0, 0]])

    def test_timed_out_request_withdraws_from_queue(self, frame, gbm,
                                                    monkeypatch):
        """A caller that gave up must not leave its rows behind to be
        dispatched anyway (overload amplification)."""
        import h2o3_tpu.serving.batcher as bm
        monkeypatch.setattr(bm, "SCORE_TIMEOUT_S", 0.05)
        entry = SCORING._admit(gbm.key)
        entry.batcher._window = 5.0          # hold the batch open
        try:
            with pytest.raises(ServiceUnavailable):
                SCORING.score(gbm.key, _rows(frame, 2))
            with entry.batcher._cond:
                assert entry.batcher._queue == []
        finally:
            entry.batcher._window = bm.window_s_from_env()
        monkeypatch.setattr(bm, "SCORE_TIMEOUT_S", 30.0)
        assert SCORING.score(gbm.key, _rows(frame, 2))["rows"] == 2


class TestConcurrency:
    def test_thread_pool_coalesces_and_slices_correctly(self, frame, gbm):
        """16 concurrent clients: every reply is that client's own rows
        (sliced out of shared batches) and at least one dispatch carried
        more than one request."""
        SCORING.score(gbm.key, _rows(frame, 4))           # warm the bucket
        nthreads, per = 16, 4
        outs: list = [None] * nthreads
        errs: list = []
        ready = threading.Barrier(nthreads)

        def work(i):
            try:
                ready.wait()
                outs[i] = SCORING.score(gbm.key, _rows(frame, per, start=i * per))
            except Exception as e:   # noqa: BLE001 — collected for the assert
                errs.append(e)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(nthreads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        names = [c for c in frame.names if c != "y"]
        pred = gbm.predict(Frame(names, [frame.vec(c) for c in names]))
        all_p = np.asarray(pred.vec("pyes").to_numpy())
        for i, out in enumerate(outs):
            got = np.asarray(out["predictions"]["pyes"], np.float32)
            assert np.array_equal(got, all_p[i * per:(i + 1) * per]), i
        assert max(o["batch_requests"] for o in outs) > 1, \
            "no dispatch coalesced concurrent requests"

    def test_multi_model_residency_serves_both(self, frame, gbm, glm):
        rows = _rows(frame, 3)
        outs = {}

        def work(key):
            outs[key] = SCORING.score(key, rows)

        threads = [threading.Thread(target=work, args=(k,))
                   for k in (gbm.key, glm.key, gbm.key, glm.key)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert set(outs) == {gbm.key, glm.key}
        resident = {r["model"] for r in SCORING.stats()["resident"]}
        assert resident == {gbm.key, glm.key}


class TestResidency:
    def test_forced_low_watermark_evicts_cold_keeps_hot(self, frame, gbm,
                                                        glm):
        """Budget below both artifacts: the cold model is LRU-evicted, the
        hot one keeps serving, and nothing OOMs."""
        rows = _rows(frame, 4)
        SCORING.score(glm.key, rows)
        glm_bytes = SCORING.stats()["resident"][0]["bytes"]
        from h2o3_tpu.utils.memory import value_kind_bytes
        gbm_bytes = value_kind_bytes(gbm)[1]
        SCORING.budget_bytes = max(glm_bytes, gbm_bytes) + 64   # fits one
        SCORING.score(gbm.key, rows)                  # admits gbm, evicts glm
        st = SCORING.stats()
        assert [r["model"] for r in st["resident"]] == [gbm.key]
        assert st["evictions"] == 1
        # hot model keeps serving after the eviction
        assert len(SCORING.score(gbm.key, rows)["predictions"]["predict"]) == 4
        # the evicted model re-admits (evicting the other right back)
        assert len(SCORING.score(glm.key, rows)["predictions"]["predict"]) == 4

    def test_model_bigger_than_budget_is_terminal_400(self, frame, gbm):
        SCORING.budget_bytes = 16            # can never fit: 400, not a
        with pytest.raises(NotServable):     # 503 a retrier loops on forever
            SCORING.score(gbm.key, _rows(frame, 2))

    def test_contention_returns_503_retry_not_oom(self, frame, gbm, glm):
        from h2o3_tpu.utils.memory import value_kind_bytes
        rows = _rows(frame, 2)
        SCORING.score(glm.key, rows)                    # glm resident
        glm_entry = SCORING._resident[glm.key]
        gbm_bytes = value_kind_bytes(gbm)[1]
        SCORING.budget_bytes = gbm_bytes + 64           # gbm fits ALONE
        with glm_entry.batcher._cond:
            glm_entry.batcher._dispatching = True       # glm is mid-batch
        try:
            with pytest.raises(ServiceUnavailable) as ei:
                SCORING.score(gbm.key, rows)            # can't evict busy glm
            assert ei.value.retry_after_ms > 0
        finally:
            with glm_entry.batcher._cond:
                glm_entry.batcher._dispatching = False
        SCORING.score(gbm.key, rows)                    # idle glm evicts now

    def test_infeasible_admission_evicts_nothing(self, frame, gbm, glm):
        """When eviction can never make room, the 503 must not destroy the
        working residents' warm signatures on the way out."""
        from h2o3_tpu.utils.memory import value_kind_bytes
        rows = _rows(frame, 2)
        SCORING.score(glm.key, rows)
        glm_entry = SCORING._resident[glm.key]
        with glm_entry.batcher._cond:
            glm_entry.batcher._dispatching = True       # busy: not evictable
        gbm_bytes = value_kind_bytes(gbm)[1]
        SCORING.budget_bytes = gbm_bytes + 64           # glm + gbm never fit
        try:
            with pytest.raises(ServiceUnavailable):
                SCORING.score(gbm.key, rows)
            assert [r["model"] for r in SCORING.stats()["resident"]] \
                == [glm.key], "infeasible admission must evict nothing"
        finally:
            with glm_entry.batcher._cond:
                glm_entry.batcher._dispatching = False

    def test_eviction_drops_compiled_signatures(self, frame, gbm):
        SCORING.score(gbm.key, _rows(frame, 4))
        assert SCORING.cache.stats()["signatures"] == 1
        assert SCORING.evict(gbm.key) is True
        assert SCORING.cache.stats()["signatures"] == 0

    def test_eviction_race_retries_transparently(self, frame, gbm):
        """A request that finds its batcher stopped (eviction won the race
        between admit and submit) must re-admit and succeed — never a
        client-visible server error."""
        entry = SCORING._admit(gbm.key)
        entry.batcher.stop()                 # simulate the racing eviction
        out = SCORING.score(gbm.key, _rows(frame, 3))
        assert len(out["predictions"]["predict"]) == 3

    def test_stale_resident_refreshes_after_reput(self, frame, gbm):
        rows = _rows(frame, 3)
        first = SCORING.score(gbm.key, rows)["predictions"]["pyes"]
        from h2o3_tpu.models.gbm import GBM
        retrained = GBM(ntrees=1, max_depth=2, seed=1,
                        model_id=gbm.key).train(y="y", training_frame=frame)
        out = SCORING.score(gbm.key, rows)["predictions"]["pyes"]
        pred = retrained.predict(frame)
        want = np.asarray(pred.vec("pyes").to_numpy())[:3]
        assert np.array_equal(np.asarray(out, np.float32), want)
        assert first != out


class TestRestSurface:
    @pytest.fixture
    def server(self):
        from h2o3_tpu.api import H2OServer
        s = H2OServer(port=0).start()
        yield s
        s.stop()

    @pytest.fixture
    def client(self, server):
        from h2o3_tpu.api import H2OClient
        return H2OClient(server.url)

    def test_rest_score_stress_and_trace(self, frame, gbm, client):
        """Thread-pool clients on the real endpoint: correct slices, a
        connected root→batch→dispatch trace, metrics recorded."""
        rows = _rows(frame, 4)
        client.score(gbm.key, rows)                   # warm
        nthreads = 8
        outs: list = [None] * nthreads
        errs: list = []

        def work(i):
            try:
                outs[i] = client.score(gbm.key, _rows(frame, 4, start=4 * i))
            except Exception as e:   # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(nthreads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        names = [c for c in frame.names if c != "y"]
        pred = gbm.predict(Frame(names, [frame.vec(c) for c in names]))
        all_p = np.asarray(pred.vec("pyes").to_numpy())
        for i, out in enumerate(outs):
            got = np.asarray(out["predictions"]["pyes"], np.float32)
            assert np.array_equal(got, all_p[4 * i:4 * (i + 1)]), i
        # a solo request is its batch's leader: its trace carries the
        # root -> score:batch -> score:dispatch tree (followers only ride)
        client.score(gbm.key, rows)
        trace = client.trace(client.last_trace_id)
        kinds = {sp["kind"] for sp in trace["spans"]}
        assert "serving" in kinds and "dispatch" in kinds
        snap = {m["name"]: m for m in client.metrics()
                if m["name"].startswith("h2o3_score") and not m["labels"]}
        assert snap["h2o3_score_batch_size_count"]["value"] >= 1

    def test_rest_503_and_stats(self, frame, gbm, glm, client):
        SCORING.budget_bytes = 16          # bigger-than-budget → terminal 400
        with pytest.raises(RuntimeError, match="400"):
            client.score(gbm.key, _rows(frame, 2))
        SCORING.budget_bytes = None
        client.score(glm.key, _rows(frame, 2))          # glm resident...
        glm_entry = SCORING._resident[glm.key]
        from h2o3_tpu.utils.memory import value_kind_bytes
        SCORING.budget_bytes = value_kind_bytes(gbm)[1] + 64
        with glm_entry.batcher._cond:
            glm_entry.batcher._dispatching = True       # ...and mid-batch
        try:
            with pytest.raises(RuntimeError, match="503"):
                client.score(gbm.key, _rows(frame, 2))  # contention → 503
        finally:
            with glm_entry.batcher._cond:
                glm_entry.batcher._dispatching = False
            SCORING.evict(glm.key)
        SCORING.budget_bytes = None
        client.score(gbm.key, _rows(frame, 2))
        st = client.serving()
        assert st["resident"][0]["model"] == gbm.key
        assert st["cache"]["misses"] >= 1
        assert client.serving_evict(gbm.key) is True
        assert client.serving()["resident"] == []

    def test_rest_unknown_model_404_bad_rows_400(self, client, frame, gbm):
        with pytest.raises(RuntimeError, match="404"):
            client.score("no_such_model", [{"x0": 1.0}])
        with pytest.raises(RuntimeError, match="400"):
            client.request("POST", f"/3/Score/{gbm.key}", {"rows": []})
        with pytest.raises(RuntimeError, match="400"):
            client.request("POST", f"/3/Score/{gbm.key}",
                           {"rows": '[{"x0":'})   # malformed JSON → 400
        with pytest.raises(RuntimeError, match="400"):
            client.score(gbm.key, [{"x0": {"nested": 1}}])   # bad cell → 400
