"""Regression tests for round-1 advisor findings (ADVICE.md)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame


def _binom_frame(rng, n=400):
    # numeric cols with sd != 1 so standardization scale bugs show, but well
    # enough conditioned that the unstandardized cross-check fit converges too
    x0 = rng.normal(0.0, 3.0, size=n).astype(np.float32)
    x1 = rng.normal(5.0, 0.5, size=n).astype(np.float32)
    logit = 0.6 * x0 - 1.5 * (x1 - 5.0)
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    return Frame.from_arrays({
        "x0": x0, "x1": x1,
        "y": np.array(["no", "yes"], dtype=object)[y],
    })


def test_glm_coef_table_se_scale(rng):
    """std_error must be on the SAME scale as coefficient: z == coef/se
    (ADVICE: SEs were left on the standardized scale)."""
    from h2o3_tpu.models.glm import GLM

    fr = _binom_frame(rng)
    m = GLM(family="binomial", lambda_=0.0, standardize=True,
            compute_p_values=True).train(y="y", training_frame=fr)
    for row in m.coef_table():
        if row["std_error"] > 0:
            assert row["z_value"] == pytest.approx(
                row["coefficient"] / row["std_error"], rel=1e-6), row

    # cross-check against the unstandardized fit: destandardized SEs must
    # agree (same MLE, same information matrix in original coordinates)
    m2 = GLM(family="binomial", lambda_=0.0, standardize=False,
             compute_p_values=True).train(y="y", training_frame=fr)
    se1 = {r["name"]: r["std_error"] for r in m.coef_table()}
    se2 = {r["name"]: r["std_error"] for r in m2.coef_table()}
    for name in se1:
        assert se1[name] == pytest.approx(se2[name], rel=5e-2), name


def test_gbm_valid_frame_early_stopping(rng):
    """stopping_rounds with a validation frame scores the held-out frame
    (ADVICE: stopping_metric was silently ignored)."""
    from h2o3_tpu.models.gbm import GBM

    tr, va = _binom_frame(rng, 400), _binom_frame(rng, 200)
    m = GBM(ntrees=30, max_depth=3, stopping_rounds=3,
            stopping_metric="logloss", seed=1).train(
        y="y", training_frame=tr, validation_frame=va)
    assert 1 <= len(m.output["trees"]) <= 30

    m_auc = GBM(ntrees=10, max_depth=3, stopping_rounds=2,
                stopping_metric="AUC", seed=1).train(
        y="y", training_frame=tr, validation_frame=va)
    assert 1 <= len(m_auc.output["trees"]) <= 10


def test_gbm_bad_stopping_metric_rejected(rng):
    from h2o3_tpu.models.gbm import GBM

    with pytest.raises(ValueError, match="stopping_metric"):
        GBM(ntrees=5, stopping_rounds=2, stopping_metric="bogus").train(
            y="y", training_frame=_binom_frame(rng))


def test_gbm_huber_weighted_delta(rng):
    """Huber delta uses a weighted quantile over w>0 rows only: an extra
    block of zero-weight rows must not change the model (ADVICE: padding
    rows biased delta toward 0)."""
    from h2o3_tpu.models.gbm import GBM

    n = 300
    x = rng.normal(size=n).astype(np.float32)
    y = (2.0 * x + rng.normal(scale=0.3, size=n)).astype(np.float32)
    y[:8] += 40.0   # outliers that huber should resist

    fr = Frame.from_arrays({"x": x, "y": y})
    m = GBM(ntrees=10, max_depth=3, distribution="huber", seed=3).train(
        y="y", training_frame=fr)
    pred = m.predict(fr).vec("predict").to_numpy()
    resid = np.median(np.abs(pred[8:] - y[8:]))
    assert resid < 1.0      # fits the bulk, not the outliers


def test_sql_distributed_order(tmp_path):
    """DISTRIBUTED fetch must reassemble the exact table (ADVICE: chunked
    LIMIT/OFFSET without ORDER BY can overlap/skip)."""
    import sqlite3

    from h2o3_tpu.frame.sql import import_sql_table

    db = tmp_path / "t.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (a INTEGER, b REAL)")
    conn.executemany("INSERT INTO t VALUES (?, ?)",
                     [(i, float(i) * 0.5) for i in range(97)])
    conn.commit()
    conn.close()

    fr = import_sql_table(f"sqlite:{db}", "t", fetch_mode="DISTRIBUTED",
                          num_chunks=5)
    a = fr.vec("a").to_numpy()
    assert fr.nrows == 97
    np.testing.assert_array_equal(np.sort(a), np.arange(97))


def test_uplift_dt_categorical_scoring_consistent(rng):
    """Round-2 ADVICE (high): UpliftDRF/DecisionTree trained on code-binned
    categoricals but scored via raw threshold traversal — training predictions
    and model.predict on the same frame must agree."""
    from h2o3_tpu.models.decision_tree import DecisionTree
    from h2o3_tpu.models.uplift import UpliftDRF

    n = 500
    cat = rng.integers(0, 5, size=n)
    x1 = rng.normal(size=n).astype(np.float32)
    # response depends non-monotonically on the category CODE, so ordinal
    # threshold routing at scoring time cannot match group-split training
    bump = np.array([3.0, -2.0, 1.5, -3.0, 2.5])[cat]
    y = (bump + 0.2 * x1 + rng.normal(scale=0.2, size=n)).astype(np.float32)
    fr = Frame.from_arrays({
        "c": np.array(list("abcde"), dtype=object)[cat],
        "x1": x1, "y": y,
    })
    m = DecisionTree(max_depth=4, seed=7).train(y="y", training_frame=fr)
    assert m.output.get("cat_card") is not None     # masked path is active
    pred = m.predict(fr).vec("predict").to_numpy()
    # with the group-split routing the tree separates the 5 category means
    for k in range(5):
        sel = cat == k
        assert abs(pred[sel].mean() - y[sel].mean()) < 0.5

    treat = rng.integers(0, 2, size=n)
    yy = (rng.random(n) < np.clip(0.3 + 0.3 * treat * (bump > 0), 0, 1))
    fr2 = Frame.from_arrays({
        "c": np.array(list("abcde"), dtype=object)[cat],
        "x1": x1,
        "treat": np.array(["no", "yes"], dtype=object)[treat],
        "y": np.array(["no", "yes"], dtype=object)[yy.astype(int)],
    })
    um = UpliftDRF(ntrees=10, max_depth=4, treatment_column="treat",
                   seed=7).train(y="y", training_frame=fr2)
    assert um.output.get("cat_card") is not None
    u = um.predict(fr2).vec("uplift_predict").to_numpy()
    # categories with a real treatment effect should rank above the rest
    assert u[bump > 0].mean() > u[bump <= 0].mean()


def test_session_remove_clears_dkv():
    """Round-2 ADVICE: Session.remove on a temp must also drop the DKV copy."""
    from h2o3_tpu.rapids.exec import Session
    from h2o3_tpu.utils.registry import DKV

    s = Session()
    fr = Frame.from_arrays({"a": np.arange(4, dtype=np.float32)})
    s.assign("tmp_xyz", fr)
    assert "tmp_xyz" in DKV
    s.remove("tmp_xyz")
    assert "tmp_xyz" not in DKV


# -- round-3 advisor findings -------------------------------------------------

def test_rectangle_assign_preserves_time_precision():
    """Assigning into a TIME column must keep the exact f64 epoch-ms host
    values and the ms-offset device encoding (ADVICE r3: rebuild via raw f32
    corrupted every row by up to ~131 s)."""
    from h2o3_tpu.frame.types import VecType
    from h2o3_tpu.rapids.advprims import rectangle_assign

    ts = np.array(["2024-01-01T00:00:00.123", "2024-01-02T03:04:05.678",
                   "2024-06-30T23:59:59.999"], dtype="datetime64[ms]")
    fr = Frame.from_arrays({"t": ts, "a": np.float32([1, 2, 3])},
                           types={"t": VecType.TIME})
    exact_ms = ts.astype(np.int64).astype(np.float64)
    new_ms = float(np.datetime64("2025-05-05T05:05:05.055", "ms").astype(np.int64))

    out = rectangle_assign(fr, new_ms, ["t"], [1])
    v = out.vec("t")
    assert v.type is VecType.TIME
    got = v.to_numpy()
    # unassigned rows: bit-exact ms (f32 roundtrip would be off by up to ~64ms)
    assert got[0] == exact_ms[0] and got[2] == exact_ms[2]
    assert got[1] == new_ms
    # device encoding stays relative: shifted values fit f32 exactly enough
    # that ms-resolution arithmetic (e.g. hour extraction) still works
    from h2o3_tpu.rapids import timeops
    assert timeops.hour(v).to_numpy().tolist() == [0.0, 5.0, 23.0]

    # frame-source assign: source TIME values must land as ABSOLUTE epoch ms
    # (device data is shifted by the SOURCE's offset — code-review finding)
    src_ts = np.array(["2030-12-25T12:00:00.001"], dtype="datetime64[ms]")
    src = Frame.from_arrays({"t": src_ts}, types={"t": VecType.TIME})
    out2 = rectangle_assign(out, src, ["t"], [0])
    got2 = out2.vec("t").to_numpy()
    assert got2[0] == float(src_ts.astype(np.int64)[0])
    assert got2[1] == new_ms and got2[2] == exact_ms[2]   # untouched rows exact


def test_custom_metric_label_uses_model_threshold():
    """Binomial custom-metric rows carry the model's threshold-based label,
    matching predict() (ADVICE r3: argmax disagreed with a reset threshold)."""
    from h2o3_tpu.utils.udf import metric_callable

    class LabelSum:
        def map(self, pred, act, w, o, model):
            return [pred[0]]
        def reduce(self, l, r):
            return [l[0] + r[0]]
        def metric(self, state):
            return state[0]

    preds = np.array([[0.4, 0.6], [0.95, 0.05], [0.2, 0.8]], np.float64)
    y = np.zeros(3)
    w = np.ones(3)

    class M:
        _default_threshold = 0.75
    fn = metric_callable(LabelSum(), "labelsum", model=M())
    # p1 >= 0.75 only for row 2 -> labels [0, 0, 1]
    assert fn(preds, y, w) == 1.0
    # no model / no threshold: argmax fallback -> labels [1, 0, 1]
    fn2 = metric_callable(LabelSum(), "labelsum")
    assert fn2(preds, y, w) == 2.0


def test_custom_dist_cid_allocation_thread_safe():
    """Concurrent registrations must never collide on a cid (ADVICE r3:
    len()+1 under the threaded REST server could hand two trains the same
    id, silently swapping gradients)."""
    import threading

    from h2o3_tpu.utils import udf as _udf

    ids, n_threads, per = [], 8, 25
    lock = threading.Lock()

    def worker():
        got = [_udf.register_custom_dist(object()) for _ in range(per)]
        with lock:
            ids.extend(got)

    ts = [threading.Thread(target=worker) for _ in range(n_threads)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len(set(ids)) == n_threads * per


def test_validation_custom_metric_weighted(rng):
    """A model trained with weights_column reports a WEIGHTED custom metric
    on the validation frame (ADVICE r3: weights=None dropped them), and a
    string-form func is not required for the validation path."""
    from h2o3_tpu.models.gbm import GBM

    def wsum(preds, y, w):
        return float(np.sum(w))

    def mk(n, wval):
        f = _binom_frame(rng, n)
        return Frame.from_arrays({
            "x0": f.vec("x0").to_numpy(), "x1": f.vec("x1").to_numpy(),
            "y": f.vec("y").labels(),
            "wt": np.full(n, wval, np.float32)})

    tr, va = mk(200, 1.0), mk(80, 2.5)
    m = GBM(ntrees=3, max_depth=3, seed=1, weights_column="wt",
            custom_metric_func=wsum).train(y="y", training_frame=tr,
                                           validation_frame=va)
    assert m.training_metrics.custom_metric_value == pytest.approx(200.0)
    assert m.validation_metrics.custom_metric_value == pytest.approx(80 * 2.5)
