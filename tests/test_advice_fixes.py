"""Regression tests for round-1 advisor findings (ADVICE.md)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame


def _binom_frame(rng, n=400):
    # numeric cols with sd != 1 so standardization scale bugs show, but well
    # enough conditioned that the unstandardized cross-check fit converges too
    x0 = rng.normal(0.0, 3.0, size=n).astype(np.float32)
    x1 = rng.normal(5.0, 0.5, size=n).astype(np.float32)
    logit = 0.6 * x0 - 1.5 * (x1 - 5.0)
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    return Frame.from_arrays({
        "x0": x0, "x1": x1,
        "y": np.array(["no", "yes"], dtype=object)[y],
    })


def test_glm_coef_table_se_scale(rng):
    """std_error must be on the SAME scale as coefficient: z == coef/se
    (ADVICE: SEs were left on the standardized scale)."""
    from h2o3_tpu.models.glm import GLM

    fr = _binom_frame(rng)
    m = GLM(family="binomial", lambda_=0.0, standardize=True,
            compute_p_values=True).train(y="y", training_frame=fr)
    for row in m.coef_table():
        if row["std_error"] > 0:
            assert row["z_value"] == pytest.approx(
                row["coefficient"] / row["std_error"], rel=1e-6), row

    # cross-check against the unstandardized fit: destandardized SEs must
    # agree (same MLE, same information matrix in original coordinates)
    m2 = GLM(family="binomial", lambda_=0.0, standardize=False,
             compute_p_values=True).train(y="y", training_frame=fr)
    se1 = {r["name"]: r["std_error"] for r in m.coef_table()}
    se2 = {r["name"]: r["std_error"] for r in m2.coef_table()}
    for name in se1:
        assert se1[name] == pytest.approx(se2[name], rel=5e-2), name


def test_gbm_valid_frame_early_stopping(rng):
    """stopping_rounds with a validation frame scores the held-out frame
    (ADVICE: stopping_metric was silently ignored)."""
    from h2o3_tpu.models.gbm import GBM

    tr, va = _binom_frame(rng, 400), _binom_frame(rng, 200)
    m = GBM(ntrees=30, max_depth=3, stopping_rounds=3,
            stopping_metric="logloss", seed=1).train(
        y="y", training_frame=tr, validation_frame=va)
    assert 1 <= len(m.output["trees"]) <= 30

    m_auc = GBM(ntrees=10, max_depth=3, stopping_rounds=2,
                stopping_metric="AUC", seed=1).train(
        y="y", training_frame=tr, validation_frame=va)
    assert 1 <= len(m_auc.output["trees"]) <= 10


def test_gbm_bad_stopping_metric_rejected(rng):
    from h2o3_tpu.models.gbm import GBM

    with pytest.raises(ValueError, match="stopping_metric"):
        GBM(ntrees=5, stopping_rounds=2, stopping_metric="bogus").train(
            y="y", training_frame=_binom_frame(rng))


def test_gbm_huber_weighted_delta(rng):
    """Huber delta uses a weighted quantile over w>0 rows only: an extra
    block of zero-weight rows must not change the model (ADVICE: padding
    rows biased delta toward 0)."""
    from h2o3_tpu.models.gbm import GBM

    n = 300
    x = rng.normal(size=n).astype(np.float32)
    y = (2.0 * x + rng.normal(scale=0.3, size=n)).astype(np.float32)
    y[:8] += 40.0   # outliers that huber should resist

    fr = Frame.from_arrays({"x": x, "y": y})
    m = GBM(ntrees=10, max_depth=3, distribution="huber", seed=3).train(
        y="y", training_frame=fr)
    pred = m.predict(fr).vec("predict").to_numpy()
    resid = np.median(np.abs(pred[8:] - y[8:]))
    assert resid < 1.0      # fits the bulk, not the outliers


def test_sql_distributed_order(tmp_path):
    """DISTRIBUTED fetch must reassemble the exact table (ADVICE: chunked
    LIMIT/OFFSET without ORDER BY can overlap/skip)."""
    import sqlite3

    from h2o3_tpu.frame.sql import import_sql_table

    db = tmp_path / "t.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (a INTEGER, b REAL)")
    conn.executemany("INSERT INTO t VALUES (?, ?)",
                     [(i, float(i) * 0.5) for i in range(97)])
    conn.commit()
    conn.close()

    fr = import_sql_table(f"sqlite:{db}", "t", fetch_mode="DISTRIBUTED",
                          num_chunks=5)
    a = fr.vec("a").to_numpy()
    assert fr.nrows == 97
    np.testing.assert_array_equal(np.sort(a), np.arange(97))
