"""Bindings codegen from live server metadata (reference: h2o-bindings/
bin/gen_python.py generating the h2o-py estimator classes)."""

import importlib.util

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api import H2OServer
from h2o3_tpu.utils.registry import DKV

GEN = __file__.rsplit("/tests/", 1)[0] + "/clients/bindings_gen.py"


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_generated_estimators_train(tmp_path, rng):
    generate = _load("bindings_gen", GEN).generate
    s = H2OServer(port=0).start()
    try:
        src = generate(s.url)
        mod_path = tmp_path / "estimators_gen.py"
        mod_path.write_text(src)
        mod = _load("estimators_gen", mod_path)
        assert hasattr(mod, "GbmEstimator") and hasattr(mod, "GlmEstimator")

        n = 200
        fr = Frame.from_arrays(
            {"a": rng.normal(size=n).astype(np.float32),
             "t": rng.normal(size=n).astype(np.float32)}, key="bind_fr")
        DKV.put(fr.key, fr)
        est = mod.GbmEstimator(url=s.url, ntrees=3, max_depth=2)
        est.train("bind_fr", y="t")
        assert est.model_json["algo"] == "gbm"
        with pytest.raises(ValueError, match="unknown parameters"):
            mod.GbmEstimator(url=s.url, bogus_param=1)
    finally:
        s.stop()


# -- R verb layer (VERDICT r4 next #7: generate, don't hand-write) ----------

class TestRGeneration:
    def _gen(self):
        bg = _load("bindings_gen", GEN)
        s = H2OServer(port=0).start()
        try:
            return bg.generate_r(s.url), bg.fetch_algo_meta(s.url)
        finally:
            s.stop()

    def test_committed_file_matches_regeneration(self):
        """clients/r/h2o3tpu/R/zzz_estimators_gen.R is the committed
        artifact of this generator against the current server — drift
        fails here."""
        src, _ = self._gen()
        committed = open(GEN.rsplit("/clients/", 1)[0]
                         + "/clients/r/h2o3tpu/R/zzz_estimators_gen.R").read()
        assert src == committed

    def test_every_algo_has_a_full_signature_verb(self):
        import re
        src, meta = self._gen()
        verbs = dict(re.findall(
            r"ModelBuilders/(\w+) — full server parameter surface\n"
            r"(h2o\.\w+) <- function", src))
        assert set(verbs) == set(meta)          # all 27+ algos covered
        for algo, m in meta.items():
            body_start = src.index(f"ModelBuilders/{algo} ")
            body = src[body_start: src.find("# POST", body_start + 10)
                       if src.find("# POST", body_start + 10) > 0
                       else len(src)]
            import re as _re
            for p in m.get("parameters", []):
                # every server param is an explicit formal AND shipped in
                # the params list (anchored: 'alpha' must not pass via
                # 'reg_alpha')
                assert _re.search(rf"(^|[\s(,]){_re.escape(p['name'])} =",
                                  body), (algo, p["name"])

    def test_unsupervised_verbs_lead_with_training_frame(self):
        src, meta = self._gen()
        for algo, m in meta.items():
            if m.get("supervised", True):
                continue
            i = src.index(f"ModelBuilders/{algo} ")
            sig = src[i: i + 400]
            assert "function(training_frame, x = NULL" in sig, algo

    def test_r_defaults_are_valid_literals(self):
        """No python reprs may leak into the R source (None/True/False)."""
        src, _ = self._gen()
        for bad in (" None", " True", " False", "float("):
            assert bad not in src, bad
