"""Bindings codegen from live server metadata (reference: h2o-bindings/
bin/gen_python.py generating the h2o-py estimator classes)."""

import importlib.util

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api import H2OServer
from h2o3_tpu.utils.registry import DKV

GEN = __file__.rsplit("/tests/", 1)[0] + "/clients/bindings_gen.py"


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_generated_estimators_train(tmp_path, rng):
    generate = _load("bindings_gen", GEN).generate
    s = H2OServer(port=0).start()
    try:
        src = generate(s.url)
        mod_path = tmp_path / "estimators_gen.py"
        mod_path.write_text(src)
        mod = _load("estimators_gen", mod_path)
        assert hasattr(mod, "GbmEstimator") and hasattr(mod, "GlmEstimator")

        n = 200
        fr = Frame.from_arrays(
            {"a": rng.normal(size=n).astype(np.float32),
             "t": rng.normal(size=n).astype(np.float32)}, key="bind_fr")
        DKV.put(fr.key, fr)
        est = mod.GbmEstimator(url=s.url, ntrees=3, max_depth=2)
        est.train("bind_fr", y="t")
        assert est.model_json["algo"] == "gbm"
        with pytest.raises(ValueError, match="unknown parameters"):
            mod.GbmEstimator(url=s.url, bogus_param=1)
    finally:
        s.stop()
