"""Accuracy parity on REAL datasets with committed golden metrics.

VERDICT r2 item 4 / SURVEY §4 tier 4 (``h2o-test-accuracy/``): every core
algorithm trains on vendored real data (``tests/data/*.csv`` — the classic
iris / breast-cancer / wine / diabetes tables, public-domain, exported from
scikit-learn's bundled copies) and must reproduce a committed golden metric
within tolerance AND stay within a band of an independent sklearn
implementation trained on the same split.
"""

import os

import numpy as np
import pytest

from h2o3_tpu.frame.parse import import_file

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def _split(fr, frac=0.8, seed=42):
    """Deterministic row split through our frame API."""
    rng = np.random.default_rng(seed)
    n = fr.nrows
    idx = rng.permutation(n)
    cut = int(n * frac)
    import pandas as pd
    df = fr.to_pandas()
    from h2o3_tpu.frame.frame import Frame
    return (Frame.from_pandas(df.iloc[idx[:cut]].reset_index(drop=True)),
            Frame.from_pandas(df.iloc[idx[cut:]].reset_index(drop=True)),
            df, idx, cut)


@pytest.fixture(scope="module")
def breast():
    return _split(import_file(os.path.join(DATA, "breast_cancer.csv")))


@pytest.fixture(scope="module")
def iris():
    return _split(import_file(os.path.join(DATA, "iris.csv")))


@pytest.fixture(scope="module")
def wine():
    return _split(import_file(os.path.join(DATA, "wine.csv")))


@pytest.fixture(scope="module")
def diabetes():
    return _split(import_file(os.path.join(DATA, "diabetes.csv")))


def _xy(df, idx, cut):
    X = df.drop(columns=["target"]).to_numpy(dtype=np.float64)
    y = df["target"].to_numpy()
    return (X[idx[:cut]], y[idx[:cut]], X[idx[cut:]], y[idx[cut:]])


def test_gbm_breast_cancer_auc(breast):
    """GOLDEN: GBM test AUC on breast-cancer ≥ 0.985 (measured 0.99+)."""
    tr, te, df, idx, cut = breast
    from h2o3_tpu.models.gbm import GBM
    m = GBM(ntrees=60, max_depth=4, learn_rate=0.1, seed=7).train(
        y="target", training_frame=tr)
    auc = m.model_performance(te).auc
    assert auc >= 0.985, auc

    # independent implementation on the same split
    from sklearn.ensemble import HistGradientBoostingClassifier
    from sklearn.metrics import roc_auc_score
    Xtr, ytr, Xte, yte = _xy(df, idx, cut)
    sk = HistGradientBoostingClassifier(max_iter=60, max_depth=4,
                                        random_state=7).fit(Xtr, ytr)
    pos = list(sk.classes_).index("malignant")
    sk_auc = roc_auc_score(yte == "malignant",
                           sk.predict_proba(Xte)[:, pos])
    assert auc >= sk_auc - 0.02, (auc, sk_auc)


def test_xgboost_breast_cancer_auc(breast):
    """GOLDEN: XGBoost-config test AUC ≥ 0.985."""
    tr, te, *_ = breast
    from h2o3_tpu.models.xgboost import XGBoost
    m = XGBoost(ntrees=60, max_depth=4, learn_rate=0.2, reg_lambda=1.0,
                seed=7).train(y="target", training_frame=tr)
    auc = m.model_performance(te).auc
    assert auc >= 0.985, auc


def test_glm_breast_cancer_vs_sklearn(breast):
    """GOLDEN: GLM logloss within 0.03 of sklearn LogisticRegression (same
    L2), AUC ≥ 0.99."""
    tr, te, df, idx, cut = breast
    from h2o3_tpu.models.glm import GLM
    m = GLM(family="binomial", lambda_=1e-2, alpha=0.0).train(
        y="target", training_frame=tr)
    mm = m.model_performance(te)
    assert mm.auc >= 0.99, mm.auc

    from sklearn.linear_model import LogisticRegression
    from sklearn.metrics import log_loss
    from sklearn.preprocessing import StandardScaler
    Xtr, ytr, Xte, yte = _xy(df, idx, cut)
    sc = StandardScaler().fit(Xtr)
    n = len(ytr)
    sk = LogisticRegression(C=1.0 / (1e-2 * n), max_iter=5000).fit(
        sc.transform(Xtr), ytr)
    pos = list(sk.classes_).index("malignant")
    sk_ll = log_loss(yte == "malignant",
                     sk.predict_proba(sc.transform(Xte))[:, pos])
    assert mm.logloss <= sk_ll + 0.03, (mm.logloss, sk_ll)


def test_drf_iris_accuracy(iris):
    """GOLDEN: DRF test accuracy on iris ≥ 0.90 (measured ~0.97)."""
    tr, te, df, idx, cut = iris
    from h2o3_tpu.models.gbm import DRF
    m = DRF(ntrees=40, max_depth=8, seed=7).train(y="target",
                                                  training_frame=tr)
    pred = m.predict(te)
    labels = np.asarray(pred.vec("predict").labels())
    acc = (labels == np.asarray(te.vec("target").labels())).mean()
    assert acc >= 0.90, acc


def test_gbm_wine_multinomial_logloss(wine):
    """GOLDEN: multinomial GBM test logloss on wine ≤ 0.25, accuracy ≥ 0.9."""
    tr, te, *_ = wine
    from h2o3_tpu.models.gbm import GBM
    m = GBM(ntrees=40, max_depth=3, seed=7).train(y="target",
                                                  training_frame=tr)
    mm = m.model_performance(te)
    assert mm.logloss <= 0.25, mm.logloss
    assert mm.accuracy >= 0.9, mm.accuracy


def test_glm_diabetes_rmse(diabetes):
    """GOLDEN: gaussian GLM test RMSE on diabetes ≤ 57 (sklearn Ridge gets
    ~55.6 on this split; OLS family parity)."""
    tr, te, df, idx, cut = diabetes
    from h2o3_tpu.models.glm import GLM
    m = GLM(family="gaussian", lambda_=1e-4).train(y="target",
                                                   training_frame=tr)
    rmse = m.model_performance(te).rmse
    assert rmse <= 57.0, rmse

    from sklearn.linear_model import Ridge
    Xtr, ytr, Xte, yte = _xy(df, idx, cut)
    sk = Ridge(alpha=1e-4).fit(Xtr, ytr.astype(float))
    sk_rmse = float(np.sqrt(np.mean(
        (sk.predict(Xte) - yte.astype(float)) ** 2)))
    assert rmse <= sk_rmse * 1.05, (rmse, sk_rmse)


def test_gbm_diabetes_rmse(diabetes):
    """GOLDEN: GBM regression test RMSE on diabetes ≤ 62."""
    tr, te, *_ = diabetes
    from h2o3_tpu.models.gbm import GBM
    m = GBM(ntrees=80, max_depth=3, learn_rate=0.05, seed=7).train(
        y="target", training_frame=tr)
    rmse = m.model_performance(te).rmse
    assert rmse <= 62.0, rmse


def test_deeplearning_wine_accuracy(wine):
    """GOLDEN: DL test accuracy on wine ≥ 0.90 (standardized MLP)."""
    tr, te, *_ = wine
    from h2o3_tpu.models.deeplearning import DeepLearning
    m = DeepLearning(hidden=[32, 32], epochs=60, seed=7).train(
        y="target", training_frame=tr)
    pred = m.predict(te)
    labels = np.asarray(pred.vec("predict").labels())
    acc = (labels == np.asarray(te.vec("target").labels())).mean()
    assert acc >= 0.90, acc


def test_kmeans_iris_ari(iris):
    """GOLDEN: KMeans(3) on iris recovers species with ARI ≥ 0.6
    (the classic ~0.73 petal-geometry clustering)."""
    tr, te, df, idx, cut = iris
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.kmeans import KMeans
    full = Frame.from_pandas(df)
    feats = [c for c in full.names if c != "target"]
    m = KMeans(k=3, seed=7, standardize=False).train(x=feats,
                                                     training_frame=full)
    assign = m.predict(full).vec("predict").to_numpy()
    from sklearn.metrics import adjusted_rand_score
    ari = adjusted_rand_score(df["target"].to_numpy(), assign)
    assert ari >= 0.6, ari
