"""DeepLearning tests (reference test model: h2o-py
``testdir_algos/deeplearning/pyunit_*`` — smoke + accuracy contracts)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models import AutoEncoder, DeepLearning


def _blobs(rng, n=1500, nclass=3):
    # fixed well-separated centers (pairwise distance 6·√2 ≫ unit noise)
    centers = 6.0 * np.eye(4)[:nclass]
    yi = rng.integers(0, nclass, size=n)
    X = centers[yi] + rng.normal(size=(n, 4))
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = np.array([f"c{c}" for c in yi], dtype=object)
    return Frame.from_arrays(cols)


def test_dl_multinomial_accuracy(rng):
    f = _blobs(rng)
    m = DeepLearning(hidden=[16], epochs=20, seed=7,
                     mini_batch_size=64).train(y="y", training_frame=f)
    assert m.training_metrics.accuracy > 0.95, m.training_metrics
    assert m.training_metrics.logloss < 0.3
    pred = m.predict(f)
    assert pred.names[0] == "predict"
    assert pred.ncols == 4  # predict + 3 class probs


def test_dl_binomial_auc(rng):
    n = 1200
    X = rng.normal(size=(n, 3))
    p = 1 / (1 + np.exp(-(2 * X[:, 0] - X[:, 1])))
    y = (rng.uniform(size=n) < p).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = np.array(["no", "yes"], dtype=object)[y]
    f = Frame.from_arrays(cols)
    m = DeepLearning(hidden=[8], epochs=15, seed=3,
                     mini_batch_size=64).train(y="y", training_frame=f)
    assert m.training_metrics.auc > 0.85


def test_dl_regression(rng):
    n = 1500
    X = rng.normal(size=(n, 3))
    y = np.sin(X[:, 0]) + 0.5 * X[:, 1] ** 2 + 0.1 * rng.normal(size=n)
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = y
    f = Frame.from_arrays(cols)
    m = DeepLearning(hidden=[32, 32], epochs=40, seed=1,
                     mini_batch_size=64).train(y="y", training_frame=f)
    # nonlinear fn a linear model can't fit: check well below response variance
    assert m.training_metrics.rmse < 0.5 * np.std(y)


def test_dl_momentum_sgd_path(rng):
    f = _blobs(rng, n=900)
    m = DeepLearning(hidden=[16], epochs=15, seed=7, adaptive_rate=False,
                     rate=0.05, momentum_start=0.5, momentum_stable=0.9,
                     momentum_ramp=5000, mini_batch_size=64,
                     ).train(y="y", training_frame=f)
    assert m.training_metrics.accuracy > 0.9


def test_dl_dropout_and_maxout(rng):
    f = _blobs(rng, n=900)
    m = DeepLearning(hidden=[32], epochs=15, seed=7,
                     activation="MaxoutWithDropout",
                     hidden_dropout_ratios=[0.2], input_dropout_ratio=0.05,
                     mini_batch_size=64).train(y="y", training_frame=f)
    assert m.training_metrics.accuracy > 0.85


def test_dl_l2_and_max_w2_constrain_weights(rng):
    f = _blobs(rng, n=600)
    m = DeepLearning(hidden=[16], epochs=10, seed=7, l2=1e-3, max_w2=1.0,
                     mini_batch_size=64).train(y="y", training_frame=f)
    W0 = np.asarray(m.output["params"]["W"][0])
    assert (W0 * W0).sum(axis=0).max() <= 1.0 + 1e-4


def test_dl_categorical_features(rng):
    n = 1000
    g = rng.integers(0, 4, size=n)
    x = rng.normal(size=n)
    y = np.array([0.0, 2.0, -1.0, 4.0])[g] + x + 0.1 * rng.normal(size=n)
    f = Frame.from_arrays({
        "g": np.array([f"g{i}" for i in g], dtype=object),
        "x": x, "y": y})
    m = DeepLearning(hidden=[16], epochs=30, seed=2,
                     mini_batch_size=64).train(y="y", training_frame=f)
    assert m.training_metrics.rmse < 0.5


def test_autoencoder_anomaly(rng):
    n = 800
    X = rng.normal(size=(n, 6))
    X[:5] += 12.0  # planted outliers
    f = Frame.from_arrays({f"x{i}": X[:, i] for i in range(6)})
    m = AutoEncoder(hidden=[3], epochs=30, seed=4,
                    mini_batch_size=64).train(training_frame=f)
    mse = m.anomaly(f).vec("Reconstruction.MSE").to_numpy()
    # outliers must rank in the top by reconstruction error
    top = np.argsort(mse)[-5:]
    assert len(set(top) & set(range(5))) >= 4
    recon = m.predict(f)
    assert recon.ncols == 6


def test_dl_validation_frame(rng):
    f = _blobs(rng, n=1200)
    tr = Frame.from_arrays({n: f.vec(n).to_numpy()[:800] if not f.vec(n).is_categorical
                            else np.asarray(f.to_pandas()[n][:800], dtype=object)
                            for n in f.names})
    va = Frame.from_arrays({n: f.vec(n).to_numpy()[800:] if not f.vec(n).is_categorical
                            else np.asarray(f.to_pandas()[n][800:], dtype=object)
                            for n in f.names})
    m = DeepLearning(hidden=[16], epochs=15, seed=7,
                     mini_batch_size=64).train(y="y", training_frame=tr,
                                               validation_frame=va)
    assert m.validation_metrics is not None
    assert m.validation_metrics.accuracy > 0.9


def test_dl_rejects_crossentropy_for_regression(rng):
    n = 200
    f = Frame.from_arrays({"x": rng.normal(size=n), "y": rng.normal(size=n)})
    with pytest.raises(ValueError, match="CrossEntropy"):
        DeepLearning(hidden=[8], epochs=1, loss="CrossEntropy",
                     ).train(y="y", training_frame=f)


def test_dl_rejects_dropout_ratios_without_dropout_activation(rng):
    f = _blobs(rng, n=200)
    with pytest.raises(ValueError, match="WithDropout"):
        DeepLearning(hidden=[8], epochs=1, activation="Rectifier",
                     hidden_dropout_ratios=[0.5]).train(y="y", training_frame=f)
