"""Checkpoint/resume + segment models tests
(reference: SharedTree.java:144 checkpoint, DeepLearning.java:348,
hex/segments/SegmentModelsBuilder)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import DRF, GBM, DeepLearning


def _binfr(rng, n=400):
    X = rng.normal(size=(n, 4)).astype(np.float32)
    logit = X[:, 0] * 1.5 - X[:, 1]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = y
    return Frame.from_arrays(cols)


def test_gbm_checkpoint_matches_straight_run(rng):
    fr = _binfr(rng)
    full = GBM(ntrees=10, max_depth=3, seed=5).train(y="y", training_frame=fr)
    half = GBM(ntrees=5, max_depth=3, seed=5).train(y="y", training_frame=fr)
    resumed = GBM(ntrees=10, max_depth=3, seed=5, checkpoint=half).train(
        y="y", training_frame=fr)
    assert len(resumed.output["trees"]) == 10
    # same seed + same fold-in schedule → identical ensemble as the full run
    p_full = np.asarray(full.predict(fr).vec("pyes").to_numpy())
    p_res = np.asarray(resumed.predict(fr).vec("pyes").to_numpy())
    np.testing.assert_allclose(p_full, p_res, atol=1e-5)


def test_gbm_checkpoint_validation(rng):
    fr = _binfr(rng)
    half = GBM(ntrees=5, max_depth=3, seed=5).train(y="y", training_frame=fr)
    with pytest.raises(ValueError, match="ntrees must exceed"):
        GBM(ntrees=5, max_depth=3, checkpoint=half).train(y="y", training_frame=fr)
    with pytest.raises(ValueError, match="max_depth"):
        GBM(ntrees=8, max_depth=4, checkpoint=half).train(y="y", training_frame=fr)


def test_drf_checkpoint_extends(rng):
    fr = _binfr(rng)
    half = DRF(ntrees=4, max_depth=4, seed=5).train(y="y", training_frame=fr)
    resumed = DRF(ntrees=8, max_depth=4, seed=5, checkpoint=half).train(
        y="y", training_frame=fr)
    assert resumed.output["ntrees"] == 8
    assert resumed.training_metrics.auc > 0.5


def test_gbm_multinomial_checkpoint(rng):
    n = 300
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = np.array(["a", "b", "c"])[np.argmax(
        np.stack([X[:, 0], X[:, 1], X[:, 2]], 1) + rng.normal(scale=0.3, size=(n, 3)), 1)]
    fr = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2], "y": y})
    half = GBM(ntrees=3, max_depth=3, seed=2).train(y="y", training_frame=fr)
    resumed = GBM(ntrees=6, max_depth=3, seed=2, checkpoint=half).train(
        y="y", training_frame=fr)
    assert len(resumed.output["trees_multi"][0]) == 6


def test_dl_checkpoint_continues(rng):
    fr = _binfr(rng, n=256)
    m1 = DeepLearning(hidden=[8], epochs=2, seed=3).train(y="y", training_frame=fr)
    m2 = DeepLearning(hidden=[8], epochs=2, seed=3, checkpoint=m1).train(
        y="y", training_frame=fr)
    assert m2.training_metrics is not None
    with pytest.raises(ValueError, match="topology"):
        DeepLearning(hidden=[16], epochs=1, checkpoint=m1).train(
            y="y", training_frame=fr)


def test_train_segments(rng):
    n = 400
    seg = rng.choice(["s1", "s2"], size=n)
    X = rng.normal(size=(n, 3)).astype(np.float32)
    logit = np.where(seg == "s1", X[:, 0] * 2, -X[:, 0] * 2)
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    fr = Frame.from_arrays({"seg": seg, "x0": X[:, 0], "x1": X[:, 1],
                            "x2": X[:, 2], "y": y})
    sm = GBM(ntrees=5, max_depth=3, seed=1).train_segments(
        segments=["seg"], y="y", training_frame=fr)
    assert len(sm) == 2
    f = sm.as_frame()
    assert set(f.names) >= {"seg", "model_id", "status"}
    assert all(s == "SUCCEEDED" for s in f.vec("status").to_numpy())
    m1 = sm.get_model(seg="s1")
    assert m1 is not None
    # segment models learned OPPOSITE signs of x0 — check they disagree
    m2 = sm.get_model(seg="s2")
    probe = Frame.from_arrays({"x0": np.array([2.0], np.float32),
                               "x1": np.array([0.0], np.float32),
                               "x2": np.array([0.0], np.float32)})
    p1 = float(m1.predict(probe).vec("pyes").to_numpy()[0])
    p2 = float(m2.predict(probe).vec("pyes").to_numpy()[0])
    assert p1 > 0.5 > p2


def test_train_segments_failure_status(rng):
    n = 60
    seg = np.array(["ok"] * 50 + ["tiny"] * 10)
    # 'tiny' segment has a single-class response → binomial GBM on it is fine;
    # instead make the tiny segment fail via all-NA response
    y = np.concatenate([rng.choice(["a", "b"], size=50), np.array([None] * 10)])
    x0 = rng.normal(size=n).astype(np.float32)
    fr = Frame.from_arrays({"seg": seg, "x0": x0,
                            "y": np.array(y, dtype=object)})
    sm = GBM(ntrees=2, max_depth=2).train_segments(
        segments=["seg"], y="y", training_frame=fr)
    by_seg = {r["segment"]["seg"]: r for r in sm.rows}
    assert by_seg["ok"]["status"] == "SUCCEEDED"
