"""Second algo wave: TargetEncoder, RuleFit, DecisionTree, Aggregator, Grep
(reference test model: ``h2o-py/tests/testdir_algos/{targetencoder,rulefit,...}``)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models import (Aggregator, DecisionTree, Grep, RuleFit,
                             TargetEncoder)


@pytest.fixture
def te_frame(rng):
    n = 2000
    g = rng.choice(["a", "b", "c", "d"], size=n, p=[0.4, 0.3, 0.2, 0.1])
    base = {"a": 0.8, "b": 0.5, "c": 0.3, "d": 0.1}
    y = (rng.uniform(size=n) < np.array([base[c] for c in g]))
    return Frame.from_arrays({
        "g": g.astype(object),
        "x": rng.normal(size=n),
        "y": np.array(["yes" if t else "no" for t in y], dtype=object),
    }), base


def test_target_encoder_means(te_frame):
    f, base = te_frame
    te = TargetEncoder(columns=["g"]).train(x=["g", "x"], y="y", training_frame=f)
    out = te.transform(f)
    assert "g_te" in out.names
    enc = out.vec("g_te").to_numpy()
    labels = f.vec("g").labels()
    for lev, expected in base.items():
        got = enc[labels == lev].mean()
        assert abs(got - expected) < 0.06, (lev, got, expected)


def test_target_encoder_blending(te_frame):
    f, base = te_frame
    te = TargetEncoder(columns=["g"], blending=True, inflection_point=1e6) \
        .train(x=["g"], y="y", training_frame=f)
    enc = te.transform(f).vec("g_te").to_numpy()
    prior = te.output["prior"]
    # with a huge inflection point every level shrinks to the prior
    assert np.allclose(enc, prior, atol=1e-3)


def test_target_encoder_kfold_loo(te_frame):
    f, _ = te_frame
    for leak in ("KFold", "LeaveOneOut"):
        te = TargetEncoder(columns=["g"], data_leakage_handling=leak, nfolds=3) \
            .train(x=["g"], y="y", training_frame=f)
        tr = te.transform(f, as_training=True)
        ho = te.transform(f, as_training=False)
        a = tr.vec("g_te").to_numpy()
        b = ho.vec("g_te").to_numpy()
        assert not np.allclose(a, b)       # OOF stats differ from full stats
        assert abs(a.mean() - b.mean()) < 0.05


def test_target_encoder_unseen_level(te_frame):
    f, _ = te_frame
    te = TargetEncoder(columns=["g"]).train(x=["g"], y="y", training_frame=f)
    f2 = Frame.from_arrays({"g": np.array(["a", "zzz"], dtype=object)})
    enc = te.transform(f2).vec("g_te").to_numpy()
    assert enc[1] == pytest.approx(te.output["prior"], abs=1e-5)


def test_rulefit_binomial(rng):
    n = 1500
    X = rng.normal(size=(n, 4))
    y = ((X[:, 0] > 0.5) & (X[:, 1] < 0.0)) | (X[:, 2] > 1.2)
    f = Frame.from_arrays({f"x{i}": X[:, i] for i in range(4)}
                          | {"y": np.array(["t" if v else "f" for v in y],
                                           dtype=object)})
    m = RuleFit(max_rule_length=3, rule_generation_ntrees=8, lambda_=1e-3) \
        .train(y="y", training_frame=f)
    assert m.training_metrics.auc > 0.9
    imp = m.rule_importance()
    assert len(imp) > 0
    # the learned rules mention the truly-informative features
    joined = " ".join(r for r, _ in imp[:10])
    assert "x0" in joined or "x2" in joined


def test_rulefit_regression(rng):
    n = 1000
    X = rng.normal(size=(n, 3))
    y = 2.0 * (X[:, 0] > 0) + X[:, 1] + rng.normal(scale=0.1, size=n)
    f = Frame.from_arrays({f"x{i}": X[:, i] for i in range(3)} | {"y": y})
    m = RuleFit(model_type="rules_and_linear", rule_generation_ntrees=6) \
        .train(y="y", training_frame=f)
    assert m.training_metrics.r2 > 0.8


def test_decision_tree(rng):
    n = 1000
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] > 0.3)
    f = Frame.from_arrays({f"x{i}": X[:, i] for i in range(3)}
                          | {"y": np.array(["p" if v else "n" for v in y],
                                           dtype=object)})
    m = DecisionTree(max_depth=4).train(y="y", training_frame=f)
    acc = (m.predict(f).vec("predict").to_numpy() == y.astype(int)).mean()
    assert acc > 0.95
    assert m.training_metrics.auc > 0.95

    # regression tree: leaf = node mean
    fr = Frame.from_arrays({"x": X[:, 0], "y": 3.0 * (X[:, 0] > 0)})
    mr = DecisionTree(max_depth=2).train(y="y", training_frame=fr)
    assert mr.training_metrics.rmse < 0.4


def test_aggregator(rng):
    n = 2000
    X = np.concatenate([rng.normal(size=(n // 2, 2)),
                        rng.normal(size=(n // 2, 2)) + 8.0])
    f = Frame.from_arrays({"a": X[:, 0], "b": X[:, 1]})
    m = Aggregator(target_num_exemplars=50).train(training_frame=f)
    out = m.aggregated_frame
    assert 2 <= out.nrows <= 50
    counts = out.vec("counts").to_numpy()
    assert counts.sum() == pytest.approx(n)
    # exemplars cover both clusters
    a = out.vec("a").to_numpy()
    assert (a < 4).any() and (a > 4).any()


def test_grep():
    f = Frame.from_arrays({"s": np.array(
        ["error: disk full", "ok", "error: oom", None], dtype=object)})
    m = Grep(regex=r"error: (\w+)").train(x=["s"], training_frame=f)
    out = m.matches
    assert out.nrows == 2
    assert out.vec("row").to_numpy().tolist() == [0.0, 2.0]
    assert list(out.vec("match").host_values) == ["error: disk", "error: oom"]
