"""Streaming ingest + out-of-core frames (docs/INGEST.md).

Reference behaviors under test: the overlapped chunked parse
(``ParseDataset``'s setup-sample + chunk MRTask shape), compressed chunk
encodings with decompress-on-access (``NewChunk`` codec choice /
``Chunk.atd``), and Cleaner-driven spill with transparent fault-in
(``water/Cleaner.java`` + ``water/Value.java`` spill state).
"""

import gzip
import os
import threading

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.utils.registry import DKV


def _write_csv(path, nrows, rng, gz=False, cats=("aa", "bb", "cc")):
    lines = ["xi,yf,cat"]
    xi = rng.integers(-40, 90, size=nrows)
    yf = rng.normal(size=nrows)
    cs = [cats[i % len(cats)] for i in range(nrows)]
    for a, b, c in zip(xi, yf, cs):
        lines.append(f"{a},{b:.6f},{c}")
    text = "\n".join(lines) + "\n"
    if gz:
        with gzip.open(path, "wt") as f:
            f.write(text)
    else:
        with open(path, "w") as f:
            f.write(text)
    return xi.astype(np.float32), yf.astype(np.float32), cs


# -- streaming chunked parse -------------------------------------------------


@pytest.mark.parametrize("gz", [False, True])
def test_stream_parse_matches_eager(tmp_path, rng, gz):
    from h2o3_tpu.ingest import stream_import
    p = str(tmp_path / ("t.csv.gz" if gz else "t.csv"))
    xi, yf, cs = _write_csv(p, 3000, rng, gz=gz)
    fr = stream_import(p, key="s.hex", chunk_rows=512)
    assert fr.nrows == 3000 and fr.ncols == 3
    assert fr.types == {"xi": "int", "yf": "real", "cat": "enum"}
    np.testing.assert_array_equal(fr.vec("xi").to_numpy(), xi)
    assert list(fr.vec("cat").labels()) == cs
    # bit-exact against the eager pandas path (the parity reference)
    from h2o3_tpu.frame.parse import import_file
    fe = import_file(p, key="se.hex")
    np.testing.assert_array_equal(fr.vec("yf").to_numpy(),
                                  fe.vec("yf").to_numpy())
    np.testing.assert_allclose(fr.vec("yf").to_numpy(), yf, atol=1e-6)
    assert DKV.get("s.hex") is fr
    # the parse ran chunked, with bounded transient memory between stages
    st = fr._ingest_stats
    assert st["chunks"] >= 5 and st["rows"] == 3000
    assert st["inflight_peak_bytes"] < st["bytes_in"]


def test_stream_parse_compresses(tmp_path, rng):
    from h2o3_tpu.ingest import stream_import
    p = str(tmp_path / "c.csv")
    _write_csv(p, 4000, rng)
    fr = stream_import(p, key="c.hex", chunk_rows=1024)
    # xi spans < 256 integral values -> i8; cat cardinality 3 -> dict8;
    # yf is fractional -> f32 identity
    assert fr.vec("xi").compressed.codec == "i8"
    assert fr.vec("cat").compressed.codec == "dict8"
    assert fr.vec("yf").compressed.codec == "f32"
    assert fr._ingest_stats["compression_ratio"] > 1.5


def test_promote_and_reparse(tmp_path):
    """A chunk past the inference sample that breaks a numeric guess forces
    one bounded restart with the column categorical."""
    from h2o3_tpu.ingest import stream_import
    lines = ["a,b"] + [f"{i},{i * 2}" for i in range(1500)] \
        + ["surprise,3000"] + [f"{i},{i}" for i in range(50)]
    p = tmp_path / "p.csv"
    p.write_text("\n".join(lines) + "\n")
    fr = stream_import(str(p), key="p.hex", chunk_rows=256)
    assert fr.nrows == 1551
    assert fr.types["a"] == "enum" and fr.types["b"] == "int"
    assert fr._ingest_stats["restarts"] == 1
    assert "surprise" in fr.vec("a").domain
    # k columns breaking in the SAME chunk ride one restart, not k
    lines2 = ["m,n"] + [f"{i},{i}" for i in range(1000)] + ["uh,oh"]
    p2 = tmp_path / "p2.csv"
    p2.write_text("\n".join(lines2) + "\n")
    fr2 = stream_import(str(p2), key="p2.hex", chunk_rows=256)
    assert fr2.types == {"m": "enum", "n": "enum"}
    assert fr2._ingest_stats["restarts"] == 1


def test_import_file_routes_streaming(tmp_path, rng, monkeypatch):
    """``import_file`` routes through the pipeline behind
    H2O3TPU_INGEST_STREAMING, and parse is a real Job with row/byte
    progress."""
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.models.job import Job
    p = str(tmp_path / "r.csv")
    xi, _, _ = _write_csv(p, 2000, rng)
    monkeypatch.setenv("H2O3TPU_INGEST_STREAMING", "1")
    fr = import_file(p, key="r.hex")
    assert hasattr(fr, "_ingest_stats") and fr.nrows == 2000
    np.testing.assert_array_equal(fr.vec("xi").to_numpy(), xi)
    jobs = [v for _k, v in DKV.raw_items() if isinstance(v, Job)
            and v.description.startswith("Parse")]
    assert jobs and jobs[-1].status == Job.DONE
    assert jobs[-1].progress == 1.0
    assert "rows" in jobs[-1].progress_msg and "bytes" in jobs[-1].progress_msg
    # off switch: the eager path produces a frame with no ingest stats
    monkeypatch.setenv("H2O3TPU_INGEST_STREAMING", "0")
    fr2 = import_file(p, key="r2.hex")
    assert not hasattr(fr2, "_ingest_stats")


# -- compressed chunk encodings ----------------------------------------------


def test_encode_roundtrip_widths():
    from h2o3_tpu.ingest.encode import encode_codes, encode_numeric
    # i8: small-span integral with NA
    v = np.array([10, 11, np.nan, 137, 10], np.float32)
    ch = encode_numeric(v)
    assert ch.codec == "i8" and ch.nbytes == 5
    np.testing.assert_array_equal(ch.decode(), v)
    # i16: span past 255
    v2 = np.arange(0, 40000, 13, dtype=np.float32)
    ch2 = encode_numeric(v2)
    assert ch2.codec == "i16"
    np.testing.assert_array_equal(ch2.decode(), v2)
    # fractional -> identity
    v3 = np.array([0.5, 1.25, np.nan], np.float32)
    assert encode_numeric(v3).codec == "f32"
    np.testing.assert_array_equal(encode_numeric(v3).decode(), v3)
    # huge integral values past float32's exact-int range -> identity wins
    v4 = np.array([2.0**25, 2.0**25 + 2], np.float32)
    np.testing.assert_array_equal(encode_numeric(v4).decode(), v4)
    # dict widths follow cardinality; CAT_NA (-1) survives every width
    codes = np.array([0, 1, -1, 2], np.int32)
    assert encode_codes(codes, 3).codec == "dict8"
    assert encode_codes(codes, 300).codec == "dict16"
    assert encode_codes(codes, 70000).codec == "dict32"
    np.testing.assert_array_equal(encode_codes(codes, 300).decode(), codes)


def test_lazy_decompress_and_view_drop(tmp_path, rng):
    """A compressed Vec's device array is a derived view: materialized on
    first access, droppable by the Cleaner, rebuilt on the next access —
    and accounting never forces a materialization."""
    from h2o3_tpu.ingest import stream_import
    p = str(tmp_path / "l.csv")
    xi, _, _ = _write_csv(p, 2048, rng)
    fr = stream_import(p, key="l.hex", chunk_rows=512)
    v = fr.vec("xi")
    assert not v.device_resident
    nb_cold = v.nbytes                      # compressed payload only
    assert nb_cold == v.compressed.nbytes
    _ = v.data                              # decompress-on-access
    assert v.device_resident
    assert v.nbytes > nb_cold               # device view now accounted too
    freed = fr.drop_device_views()
    assert freed > 0 and not v.device_resident
    np.testing.assert_array_equal(v.to_numpy(), xi)   # host decode path
    _ = v.data
    assert v.device_resident                # rebuilt on demand


def test_cleaner_drops_views_before_spilling(tmp_path, rng):
    """Tier-1 eviction: under budget pressure the Cleaner frees derived
    device views of compressed frames before writing anything to disk."""
    from h2o3_tpu.ingest import stream_import
    from h2o3_tpu.utils.cleaner import CLEANER, disable_cleaner, enable_cleaner
    p = str(tmp_path / "v.csv")
    _write_csv(p, 4096, rng)
    try:
        fr = stream_import(p, key="v.hex", chunk_rows=1024)
        for name in fr.names:
            _ = fr.vec(name).data           # materialize every view
        resident = fr.nbytes
        # budget between compressed-only and fully-materialized size
        enable_cleaner(resident - 1000, ice_root=str(tmp_path / "ice"))
        spilled = CLEANER.sweep()
        assert spilled == []                # view drops sufficed
        assert CLEANER.stats()["view_drops"] >= 1
        assert any(not v.device_resident for v in fr.vecs)
        with DKV._lock:
            assert isinstance(DKV._store["v.hex"], Frame)   # never stubbed
    finally:
        disable_cleaner()


# -- spill accounting + races ------------------------------------------------


def _mk_frame(key, rng, n=4096, ncols=4):
    f = Frame.from_arrays(
        {f"c{i}": rng.normal(size=n).astype(np.float32)
         for i in range(ncols)}, key=key)
    DKV.put(key, f)
    return f


def test_spilled_kind_reconciles_memory_view(tmp_path, rng):
    """ISSUE 14 satellite: a SwappedFrame stub must not vanish from
    /3/Memory — its on-disk bytes register under the `spilled` kind and the
    stub stays in the top-keys view."""
    from h2o3_tpu.utils.cleaner import (SwappedFrame, disable_cleaner,
                                        enable_cleaner)
    from h2o3_tpu.utils.memory import MEMORY
    try:
        enable_cleaner(150_000, ice_root=str(tmp_path))
        _mk_frame("fr_a", rng)
        _mk_frame("fr_b", rng)
        DKV.get("fr_b")
        _mk_frame("fr_c", rng)              # over budget -> LRU (fr_a) spills
        with DKV._lock:
            stub = DKV._store["fr_a"]
        assert isinstance(stub, SwappedFrame) and stub.disk_bytes > 0
        summary = MEMORY.summary(refresh=True)
        by_kind = summary["dkv"]["by_kind"]
        assert by_kind.get("spilled", 0) == stub.disk_bytes
        assert any(r["key"] == "fr_a" and r["kind"] == "spilled"
                   for r in summary["top_keys"])
        sp = summary["spill"]
        assert sp["spill_count"] >= 1 and sp["spilled_disk_bytes"] > 0
        assert any(r["key"] == "fr_a" for r in sp["spilled_keys"])
    finally:
        disable_cleaner()


def test_raw_value_spill_and_fault_in(tmp_path, rng):
    """Per-value spill beyond frames: a cold RawFile payload spills to the
    ice_root behind a SwappedValue stub and faults back in on access."""
    from h2o3_tpu.frame.parse import RawFile
    from h2o3_tpu.utils.cleaner import (CLEANER, SwappedValue,
                                        disable_cleaner, enable_cleaner)
    try:
        enable_cleaner(150_000, ice_root=str(tmp_path))
        payload = bytes(rng.integers(0, 256, size=120_000, dtype=np.uint8))
        DKV.put("up1", RawFile(payload, name="big.csv"))
        _mk_frame("fr_hot", rng)            # pushes the cold raw key out
        with DKV._lock:
            stub = DKV._store.get("up1")
        assert isinstance(stub, SwappedValue)
        assert stub.disk_bytes == len(payload)
        back = DKV["up1"]                   # transparent fault-in
        assert isinstance(back, RawFile) and back.data == payload
        assert back.name == "big.csv"
        st = CLEANER.stats()
        assert st["restore_count"] >= 1
    finally:
        disable_cleaner()


def test_dkv_get_races_cleaner_sweep(tmp_path, rng):
    """ISSUE 14 satellite: concurrent DKV.get racing Cleaner.sweep on the
    same key — resolve-vs-swap interleaving must never hand a stub to a
    caller."""
    from h2o3_tpu.utils.cleaner import disable_cleaner, enable_cleaner
    try:
        # budget fits ~1 frame: every get of one key tends to spill the other
        enable_cleaner(70_000, ice_root=str(tmp_path))
        want = {}
        for k in ("race_a", "race_b"):
            want[k] = _mk_frame(k, rng).vec("c0").to_numpy().copy()
        errors: list = []
        stop = threading.Event()

        def hammer(key):
            try:
                while not stop.is_set():
                    got = DKV.get(key)
                    assert isinstance(got, Frame), f"stub escaped: {got!r}"
                    np.testing.assert_allclose(
                        got.vec("c0").to_numpy(), want[key], rtol=1e-6)
            except BaseException as e:   # noqa: BLE001 — surfaced below
                errors.append(e)
                stop.set()

        threads = [threading.Thread(target=hammer, args=(k,), daemon=True)
                   for k in want for _ in range(2)]
        for t in threads:
            t.start()
        for _ in range(40):
            if stop.is_set():
                break
            stop.wait(timeout=0.05)
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        assert not errors, errors[0]
    finally:
        disable_cleaner()


# -- structured import errors ------------------------------------------------


def test_import_file_missing_path_raises_structured():
    from h2o3_tpu.frame.parse import import_file
    with pytest.raises(FileNotFoundError, match="no such file"):
        import_file("/definitely/not/here.csv")
    with pytest.raises(IsADirectoryError, match="directory"):
        import_file("/tmp")


def test_import_files_bad_path_is_400_not_500():
    """POST /3/ImportFiles on a nonexistent path must reply a structured
    400 H2OErrorV3 (and the client maps it to FileNotFoundError), never a
    500 traceback."""
    import json
    import urllib.error
    import urllib.request

    from h2o3_tpu.api import H2OServer
    from h2o3_tpu.api.client import H2OClient
    s = H2OServer(port=0).start()
    try:
        body = b"path=%2Fno%2Fsuch%2Ffile.csv"
        req = urllib.request.Request(f"{s.url}/3/ImportFiles", data=body,
                                     method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 400
        payload = json.loads(ei.value.read().decode())
        assert payload["__meta"]["schema_type"] == "H2OErrorV3"
        assert "no such file" in payload["msg"]
        with pytest.raises(FileNotFoundError):
            H2OClient(s.url).import_file("/no/such/file.csv")
    finally:
        s.stop()


# -- end-to-end out-of-core proof --------------------------------------------


def test_glm_bit_identity_streaming_vs_eager(tmp_path, rng):
    """The acceptance contract: a compressed, lazily-materialized,
    spill-cycled frame trains/predicts bit-identically to the eager
    resident path."""
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.ingest import stream_import
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.utils.cleaner import disable_cleaner, enable_cleaner
    n = 3000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    yb = (1 / (1 + np.exp(-(0.5 * x1 - 0.8 * x2)))
          > rng.uniform(size=n))
    lines = ["x1,x2,y"] + [
        f"{a:.6f},{b:.6f},{'yes' if c else 'no'}"
        for a, b, c in zip(x1, x2, yb)]
    p = tmp_path / "g.csv"
    p.write_text("\n".join(lines) + "\n")
    fs = stream_import(str(p), key="gs.hex", chunk_rows=512)
    fe = import_file(str(p), key="ge.hex")
    try:
        # force a full spill/fault-in cycle through the streamed frame
        # (sweeps run on put — drive one explicitly, then fault back in)
        from h2o3_tpu.utils.cleaner import CLEANER, SwappedFrame
        enable_cleaner(1, ice_root=str(tmp_path / "ice"))
        spilled = CLEANER.sweep()
        assert "gs.hex" in spilled
        with DKV._lock:
            assert isinstance(DKV._store["gs.hex"], SwappedFrame)
        fs_back = DKV["gs.hex"]
    finally:
        disable_cleaner()
    kw = dict(family="binomial", lambda_=1e-4, max_iterations=20, seed=7)
    ms = GLM(**kw).train(y="y", training_frame=fs_back)
    me = GLM(**kw).train(y="y", training_frame=fe)
    ps = ms.predict(fs_back).vec("pyes").to_numpy()
    pe = me.predict(fe).vec("pyes").to_numpy()
    assert np.array_equal(ps, pe), \
        f"max divergence {np.abs(ps - pe).max()}"


def test_multi_member_gzip_reads_every_member(tmp_path):
    """Concatenated gzip members (pigz, log rotation, `cat a.gz b.gz`) are
    one valid stream: the incremental gunzip must restart across member
    boundaries, matching the eager gzip-module path."""
    from h2o3_tpu.ingest import stream_import
    a = gzip.compress(b"x,y\n1,10\n2,20\n")
    b = gzip.compress(b"3,30\n4,40\n5,50\n")
    p = tmp_path / "multi.csv.gz"
    p.write_bytes(a + b)
    fr = stream_import(str(p), key="mm.hex", chunk_rows=64)
    assert fr.nrows == 5
    np.testing.assert_array_equal(fr.vec("x").to_numpy(), [1, 2, 3, 4, 5])
    np.testing.assert_array_equal(fr.vec("y").to_numpy(),
                                  [10, 20, 30, 40, 50])


def test_quoted_header_names(tmp_path):
    """Header names quoted around the separator parse through the same
    CSV reader as the data rows, not a naive split."""
    from h2o3_tpu.ingest import stream_import
    p = tmp_path / "q.csv"
    p.write_text('"last,first",age\n"x",1\n"y",2\n')
    fr = stream_import(str(p), key="q.hex")
    assert fr.names == ["last,first", "age"]
    np.testing.assert_array_equal(fr.vec("age").to_numpy(), [1, 2])


def test_removed_spilled_key_deletes_snapshot(tmp_path, rng):
    """DKV.remove of a spilled key must delete the on-disk snapshot —
    frame snapshots are directories, and leaking them grows the ice_root
    without bound over a long-running server."""
    from h2o3_tpu.utils.cleaner import (SwappedFrame, disable_cleaner,
                                        enable_cleaner)
    ice = tmp_path / "ice"
    try:
        enable_cleaner(150_000, ice_root=str(ice))
        _mk_frame("gone_a", rng)
        _mk_frame("gone_b", rng)
        _mk_frame("gone_c", rng)            # forces a spill
        with DKV._lock:
            stubs = [v for v in DKV._store.values()
                     if isinstance(v, SwappedFrame)]
        assert stubs and all(os.path.exists(s.path) for s in stubs)
        for s in stubs:
            DKV.remove(s.key)
        assert not any(os.path.exists(s.path) for s in stubs)
        # restore path also retires the consumed snapshot
        _mk_frame("gone_d", rng)
        _mk_frame("gone_e", rng)
        with DKV._lock:
            stub = next(v for v in DKV._store.values()
                        if isinstance(v, SwappedFrame))
        _ = DKV[stub.key]                   # fault-in
        assert not os.path.exists(stub.path)
    finally:
        disable_cleaner()


def test_quoted_embedded_newlines(tmp_path):
    """RFC-4180: a quoted field may contain embedded newlines — record
    splitting is quote-aware, so such files parse identically to the
    eager path instead of tearing records in two."""
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.ingest import stream_import
    p = tmp_path / "nl.csv"
    p.write_text('txt,v\n"line1\nline2",5\n"plain",7\n"a\nb\nc",9\n')
    fr = stream_import(str(p), key="nl.hex")
    fe = import_file(str(p), key="nle.hex")
    assert fr.nrows == fe.nrows == 3
    np.testing.assert_array_equal(fr.vec("v").to_numpy(),
                                  fe.vec("v").to_numpy())
    assert list(fr.vec("txt").labels()) == list(fe.vec("txt").labels())


def test_forced_numeric_bad_tokens_become_na(tmp_path):
    """A USER-forced numeric column never promotes: unparseable tokens
    coerce to NA (h2o-py col_types semantics); only guessed columns
    restart."""
    from h2o3_tpu.ingest import stream_import
    lines = ["x,v"] + [f"{i},{i}" for i in range(300)] + ["oops,300"]
    p = tmp_path / "na.csv"
    p.write_text("\n".join(lines) + "\n")
    fr = stream_import(str(p), key="na.hex", chunk_rows=64,
                       col_types={"x": "numeric"})
    assert fr.types["x"] in ("int", "real")
    assert fr._ingest_stats["restarts"] == 0
    got = fr.vec("x").to_numpy()
    assert np.isnan(got[300]) and got[299] == 299


def test_to_numpy_returns_fresh_array(tmp_path, rng):
    """Mutating a to_numpy() result must never corrupt the compressed
    host payload (the identity codec decodes to the payload itself)."""
    from h2o3_tpu.ingest import stream_import
    p = str(tmp_path / "mut.csv")
    _write_csv(p, 256, rng)
    fr = stream_import(p, key="mut.hex", chunk_rows=64)
    want = fr.vec("yf").to_numpy().copy()
    arr = fr.vec("yf").to_numpy()
    arr[:] = 0.0
    np.testing.assert_array_equal(fr.vec("yf").to_numpy(), want)


def test_header_edge_cases_match_eager(tmp_path):
    """A column literally named 'NA' keeps its name (header parses without
    NA filtering) and duplicate names mangle pandas-style (x, x.1)."""
    from h2o3_tpu.frame.parse import import_file
    from h2o3_tpu.ingest import stream_import
    p = tmp_path / "h.csv"
    p.write_text("NA,x,x\n1,2,3\n4,5,6\n")
    fr = stream_import(str(p), key="h.hex")
    fe = import_file(str(p), key="he.hex")
    assert fr.names == fe.names == ["NA", "x", "x.1"]
    np.testing.assert_array_equal(fr.vec("NA").to_numpy(),
                                  fe.vec("NA").to_numpy())


def test_wide_integral_span_still_types_int(tmp_path):
    """An integral column whose span exceeds the i16 codec falls back to
    the f32 payload but must still TYPE as int (the eager _guess_type
    contract) — typing follows the values, not the achieved codec."""
    from h2o3_tpu.ingest import stream_import
    lines = ["id"] + [str(i * 7) for i in range(20000)]
    p = tmp_path / "w.csv"
    p.write_text("\n".join(lines) + "\n")
    fr = stream_import(str(p), key="w.hex", chunk_rows=4096)
    assert fr.vec("id").compressed.codec == "f32"   # span > i16
    assert fr.types["id"] == "int"


def test_cancelled_parse_raises_not_none(tmp_path, rng):
    """A parse job cancelled mid-stream surfaces a structured error from
    import_file — never a silent None (which became a 500 at REST)."""
    from h2o3_tpu.frame.parse import import_file
    p = str(tmp_path / "c.csv")
    _write_csv(p, 5000, rng)
    from h2o3_tpu.models import job as jobmod
    orig_init = jobmod.Job.__init__

    def cancelled_init(self, *a, **kw):
        orig_init(self, *a, **kw)
        if self.description.startswith("Parse"):
            self.cancel()                    # cancel before the first chunk

    os.environ["H2O3TPU_INGEST_STREAMING"] = "1"
    try:
        jobmod.Job.__init__ = cancelled_init
        with pytest.raises(ValueError, match="cancelled"):
            import_file(p, key="cx.hex")
    finally:
        jobmod.Job.__init__ = orig_init
        os.environ.pop("H2O3TPU_INGEST_STREAMING", None)


def test_stream_parse_col_types_override(tmp_path):
    """h2o-py style col_types force a column categorical up front — no
    promote restart needed."""
    from h2o3_tpu.ingest import stream_import
    lines = ["zip,v"] + [f"{94000 + i % 5},{i}" for i in range(400)]
    p = tmp_path / "z.csv"
    p.write_text("\n".join(lines) + "\n")
    fr = stream_import(str(p), key="z.hex", chunk_rows=128,
                       col_types={"zip": "enum"})
    assert fr.types["zip"] == "enum"
    assert fr.vec("zip").cardinality() == 5
    assert fr._ingest_stats["restarts"] == 0
    fr2 = stream_import(str(p), key="z2.hex", chunk_rows=128,
                        col_types={"zip": VecType.CAT})
    assert fr2.types["zip"] == "enum"
