"""POJO-equivalent codegen tests: generated standalone numpy source must
reproduce the model's predictions (reference: water/codegen POJO parity
tests in h2o-py pyunits)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.genmodel.codegen import generate_pojo
from h2o3_tpu.models import DRF, GBM, GLM, KMeans


def _exec_module(src: str):
    ns: dict = {}
    exec(compile(src, "<pojo>", "exec"), ns)
    return ns


def _tree_X(fr, model):
    """Assemble the raw matrix the generated module expects (cat codes)."""
    cols = []
    for c in model.output["x_cols"]:
        v = fr.vec(c)
        x = np.asarray(v.to_numpy(), np.float64)
        if v.is_categorical:
            x = np.where(x < 0, np.nan, x)
        cols.append(x)
    return np.stack(cols, axis=1)


@pytest.fixture
def binfr(rng):
    n = 300
    X = rng.normal(size=(n, 3)).astype(np.float32)
    cat = rng.choice(["a", "b", "c"], size=n)
    logit = 1.5 * X[:, 0] - X[:, 1] + (cat == "a")
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    return Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
                              "cat": cat, "y": y})


def test_gbm_pojo_roundtrip(binfr):
    m = GBM(ntrees=8, max_depth=3, seed=1).train(y="y", training_frame=binfr)
    ns = _exec_module(generate_pojo(m))
    got = ns["score_batch"](_tree_X(binfr, m))
    want = np.stack([binfr.nrows * [0.0], np.asarray(
        m.predict(binfr).vec("pyes").to_numpy())], 1)[:, 1]
    np.testing.assert_allclose(got[:, 1], want, atol=1e-5)
    # row API: first row agrees
    row = {c: (binfr.vec(c).labels()[0] if binfr.vec(c).is_categorical
               else float(binfr.vec(c).to_numpy()[0]))
           for c in m.output["x_cols"]}
    one = ns["score"](row)
    np.testing.assert_allclose(one[1], want[0], atol=1e-5)


def test_gbm_multinomial_pojo(rng):
    n = 300
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = np.array(["a", "b", "c"])[np.argmax(X + rng.normal(scale=0.5, size=(n, 3)), 1)]
    fr = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2], "y": y})
    m = GBM(ntrees=5, max_depth=3, seed=2).train(y="y", training_frame=fr)
    ns = _exec_module(generate_pojo(m))
    got = ns["score_batch"](_tree_X(fr, m))
    for k, d in enumerate(m.response_domain):
        want = np.asarray(m.predict(fr).vec(f"p{d}").to_numpy())
        np.testing.assert_allclose(got[:, k], want, atol=1e-5)


def test_drf_regression_pojo(rng):
    n = 300
    X = rng.normal(size=(n, 3)).astype(np.float32)
    yv = (2 * X[:, 0] - X[:, 1] + rng.normal(scale=0.2, size=n)).astype(np.float32)
    fr = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2], "y": yv})
    m = DRF(ntrees=6, max_depth=5, seed=3).train(y="y", training_frame=fr)
    ns = _exec_module(generate_pojo(m))
    got = ns["score_batch"](_tree_X(fr, m))
    want = np.asarray(m.predict(fr).vec("predict").to_numpy())
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_glm_pojo_roundtrip(binfr):
    m = GLM(family="binomial", lambda_=0.0).train(y="y", training_frame=binfr)
    ns = _exec_module(generate_pojo(m))
    # raw matrix ordered CAT_COLS + NUM_COLS
    di = m.data_info
    cols = []
    for c in di.cat_cols + di.num_cols:
        v = binfr.vec(c)
        x = np.asarray(v.to_numpy(), np.float64)
        if v.is_categorical:
            x = np.where(x < 0, np.nan, x)
        cols.append(x)
    X = np.stack(cols, axis=1)
    got = ns["score_batch"](X)
    want = np.asarray(m.predict(binfr).vec("pyes").to_numpy())
    np.testing.assert_allclose(got[:, 1], want, atol=1e-5)


def test_kmeans_pojo(rng):
    n = 200
    X = np.concatenate([rng.normal(-3, 1, size=(n // 2, 2)),
                        rng.normal(3, 1, size=(n // 2, 2))]).astype(np.float32)
    fr = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1]})
    m = KMeans(k=2, seed=4).train(training_frame=fr)
    ns = _exec_module(generate_pojo(m))
    got = ns["score_batch"](X.astype(np.float64))
    want = np.asarray(m.predict(fr).vec("predict").to_numpy()).astype(int)
    assert (got == want).mean() > 0.99


def test_unsupported_algo_raises(rng):
    from h2o3_tpu.models import NaiveBayes
    X = rng.normal(size=(60, 2)).astype(np.float32)
    y = np.where(X[:, 0] > 0, "p", "q")
    fr = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "y": y})
    m = NaiveBayes().train(y="y", training_frame=fr)
    with pytest.raises(ValueError, match="no standalone codegen"):
        generate_pojo(m)
