"""Test harness: simulate a multi-chip TPU cloud with 8 virtual CPU devices.

Reference test strategy (SURVEY.md §4): H2O tests boot N JVMs on localhost and
block in ``TestUtil.stall_till_cloudsize(n)`` until the cloud forms. The TPU
equivalent is N virtual devices on one host via
``--xla_force_host_platform_device_count`` — same API as real chips, so every
sharding/collective path is exercised.

Env vars MUST be set before jax is imported anywhere in the process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The environment's sitecustomize registers the TPU backend unconditionally;
# override it after import so tests run on the virtual CPU cloud.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _clear_dkv():
    yield
    from h2o3_tpu.utils.registry import DKV
    DKV.clear()


@pytest.fixture(autouse=True)
def _clear_flight():
    """The flight recorder is a process-global accumulator: real RSS
    growth sampled across a long suite run fills the trend window, and
    any default-rules HealthEvaluator in a later test would then open a
    genuine (but noise, here) trend incident. Same isolation contract
    as _clear_dkv."""
    yield
    from h2o3_tpu.utils.flight import FLIGHT
    FLIGHT.reset()


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches():
    """Free compiled executables between test modules: a long single-process
    run accumulates hundreds of live XLA CPU executables, which eventually
    segfaults the LLVM JIT mid-compile (observed deterministically around the
    ~500th compile). Shapes rarely repeat across modules, so the recompile
    cost is negligible."""
    yield
    # AccountedJit wrappers (utils/costs.py) hold AOT executables the global
    # cache clear cannot see — drop them too, same segfault guard
    from h2o3_tpu.utils.costs import COSTS
    COSTS.clear_executables()
    jax.clear_caches()


@pytest.fixture
def rng():
    return np.random.default_rng(42)


# -- test tiers (VERDICT r2 weak #8: full-suite wall-clock keeps growing) ----
# smoke tier: `pytest -m "not full" tests/` (< ~3 min); full tier adds the
# heavy end-to-end modules (real-client flows, closures, device parity).
FULL_TIER = {
    "test_h2o_py_compat", "test_multiprocess", "test_rapids_closure",
    "test_orchestration", "test_device_parity", "test_glm_completions",
    "test_golden_parity", "test_deeplearning", "test_binfmt_cleaner",
    "test_algos3", "test_psvm", "test_glrm_losses", "test_tls_auth",
    "test_mojo_v2", "test_r_client",
}


def pytest_configure(config):
    config.addinivalue_line("markers", "full: heavy end-to-end tier")
    config.addinivalue_line("markers", "slow: long-running test")


def pytest_collection_modifyitems(config, items):
    for it in items:
        if it.module.__name__ in FULL_TIER:
            it.add_marker(pytest.mark.full)


def pytest_sessionfinish(session, exitstatus):
    """Witness self-validation drop: when a run is armed with
    ``H2O3TPU_LOCKWITNESS=1`` and names a report file via
    ``H2O3TPU_LOCKWITNESS_REPORT``, write the witnessed acquisition
    record plus its diff against the static DLK graph. The lock-order
    gate in test_lockwitness.py runs a subset of this suite exactly this
    way and asserts the diff is empty (no dynamic inversions, no edges
    the static analyzer missed)."""
    report_path = os.environ.get("H2O3TPU_LOCKWITNESS_REPORT", "")
    if not report_path or os.environ.get("H2O3TPU_LOCKWITNESS") != "1":
        return
    import json
    import pathlib

    from h2o3_tpu.tools.core import PackageIndex
    from h2o3_tpu.tools.lockorder import analyze
    from h2o3_tpu.utils.lockwitness import WITNESS

    import h2o3_tpu
    pkg_root = pathlib.Path(h2o3_tpu.__file__).resolve().parent
    graph = analyze(PackageIndex.scan(pkg_root))
    doc = WITNESS.report()
    doc.update(WITNESS.validate(graph.edge_pairs(), graph.lock_ids()))
    pathlib.Path(report_path).write_text(json.dumps(doc, indent=1) + "\n")
