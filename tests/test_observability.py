"""Telemetry layer tests: MetricsRegistry semantics, LogRing, OpenMetrics
export, the observability REST surface, and the route-coverage smoke sweep
(reference: water/util/Log + LogsHandler, WaterMeter*, TimelineHandler)."""

import json
import re
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.api import H2OServer
from h2o3_tpu.api.client import H2OClient
from h2o3_tpu.utils.telemetry import (DEFAULT_BUCKETS, LogRing,
                                      MetricsRegistry, install_log_ring)

# -- MetricsRegistry semantics (fresh registries: global METRICS accumulates
#    across the whole test process, so assertions there are delta-based) ----


def test_counter_inc_and_labels():
    reg = MetricsRegistry()
    c = reg.counter("reqs", "requests", ("route",))
    c.labels(route="/a").inc()
    c.labels(route="/a").inc(2)
    c.labels(route="/b").inc()
    vals = {s["labels"]["route"]: s["value"] for s in reg.snapshot()}
    assert vals == {"/a": 3, "/b": 1}
    with pytest.raises(ValueError):
        c.labels(route="/a").inc(-1)          # counters are monotone
    with pytest.raises(ValueError):
        c.labels(wrong="x")                   # label schema enforced


def test_registration_idempotent_but_type_checked():
    reg = MetricsRegistry()
    a = reg.counter("x", "first")
    b = reg.counter("x", "second")
    assert a is b                             # same family back
    with pytest.raises(ValueError):
        reg.gauge("x")                        # type mismatch refused


def test_gauge_set_inc_dec():
    reg = MetricsRegistry()
    g = reg.gauge("keys")
    g.set(10)
    g.inc(5)
    g.dec(3)
    [s] = reg.snapshot()
    assert s["value"] == 12 and s["type"] == "gauge"


def test_histogram_buckets_sum_count_minmax():
    reg = MetricsRegistry()
    h = reg.histogram("lat", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    rows = {s["name"]: s for s in reg.snapshot() if "le" not in s["labels"]}
    assert rows["lat_count"]["value"] == 5
    assert rows["lat_sum"]["value"] == pytest.approx(56.05)
    assert rows["lat_min"]["value"] == pytest.approx(0.05)
    assert rows["lat_max"]["value"] == pytest.approx(50.0)
    buckets = {s["labels"]["le"]: s["value"] for s in reg.snapshot()
               if "le" in s["labels"]}
    # cumulative per OpenMetrics: le=0.1 → 1, le=1 → 3, le=10 → 4, +Inf → 5
    assert buckets == {"0.1": 1, "1": 3, "10": 4, "+Inf": 5}


def test_concurrent_increments_are_exact():
    reg = MetricsRegistry()
    c = reg.counter("hits")
    h = reg.histogram("obs", buckets=DEFAULT_BUCKETS)

    def worker():
        for _ in range(2000):
            c.inc()
            h.observe(0.01)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    samples = {s["name"]: s["value"] for s in reg.snapshot()
               if "le" not in s["labels"]}
    assert samples["hits_total"] == 8 * 2000
    assert samples["obs_count"] == 8 * 2000


def test_openmetrics_text_shape():
    reg = MetricsRegistry()
    reg.counter("c", "a counter", ("k",)).labels(k='va"l\\ue').inc()
    reg.gauge("g").set(2.5)
    reg.histogram("h", buckets=(1.0,)).observe(0.5)
    text = reg.to_openmetrics()
    assert text.endswith("# EOF\n")
    assert "# TYPE c counter" in text
    assert re.search(r'^c_total\{k="va\\"l\\\\ue"\} 1$', text, re.M)
    assert "# TYPE g gauge" in text and "\ng 2.5\n" in text
    assert '\nh_bucket{le="1"} 1\n' in text
    assert '\nh_bucket{le="+Inf"} 1\n' in text
    assert "\nh_count 1\n" in text and "\nh_sum 0.5\n" in text


def test_openmetrics_hostile_label_values_conform():
    """ISSUE 15 satellite: label-value escaping per the exposition format
    — backslash, double-quote, and line feed escape (in that order: the
    escape char first), and each exposition line stays one physical line
    whatever the label value carries."""
    reg = MetricsRegistry()
    hostile = 'back\\slash "quote"\nnewline'
    reg.counter("c", "", ("k",)).labels(k=hostile).inc()
    text = reg.to_openmetrics()
    line = [ln for ln in text.splitlines() if ln.startswith("c_total")][0]
    assert line == 'c_total{k="back\\\\slash \\"quote\\"\\nnewline"} 1'
    # the escaped value round-trips: unescape recovers the original
    m = re.search(r'c_total\{k="((?:[^"\\]|\\.)*)"\}', line)
    unescaped = m.group(1).replace("\\n", "\n").replace('\\"', '"') \
                          .replace("\\\\", "\\")
    assert unescaped == hostile
    # every line of the exposition is parseable as comment/sample/EOF
    for ln in text.splitlines():
        assert ln.startswith("#") or re.fullmatch(
            r'\S+(\{[^{}]*\})? \S+', ln), f"malformed line: {ln!r}"


def test_openmetrics_help_escapes_backslash_newline_only():
    """HELP text defines only \\\\ and \\n escapes — a \\\" in HELP is an
    invalid sequence strict OpenMetrics parsers reject, so quotes must
    pass through verbatim (they are only special inside label values)."""
    reg = MetricsRegistry()
    reg.counter("c", 'help with "quotes", a \\ and\na newline')
    text = reg.to_openmetrics()
    [help_line] = [ln for ln in text.splitlines()
                   if ln.startswith("# HELP c ")]
    assert help_line == '# HELP c help with "quotes", a \\\\ and\\na newline'
    assert '\\"' not in help_line


def test_histogram_rejects_nan_negative_and_counts_drops():
    """ISSUE 15 satellite: a NaN observation poisons _sum (and every
    percentile read) irreversibly, a negative corrupts it silently —
    both drop and account in h2o3_telemetry_rejected_total{where}."""
    import math

    reg = MetricsRegistry()
    h = reg.histogram("h2o3_test_seconds", buckets=(1.0,))
    h.observe(0.5)
    h.observe(float("nan"))
    h.observe(-3.0)
    h.observe(float("inf"))
    h.observe(0.25)
    child = h.labels()
    assert child.count == 2 and child.sum == 0.75
    assert math.isfinite(child.sum)
    assert child.counts == [2, 0]                  # nothing leaked to +Inf
    rej = reg.counter("h2o3_telemetry_rejected", "", ("where",))
    assert rej.labels(where="h2o3_test_seconds").value == 3
    # the exposition stays NaN-free (no SAMPLE renders NaN; the rejected
    # counter's HELP legitimately mentions the word)
    assert not [ln for ln in reg.to_openmetrics().splitlines()
                if not ln.startswith("#") and ln.endswith(" NaN")]


# -- LogRing ----------------------------------------------------------------

# MM-dd HH:mm:ss.SSS pid thread LEVEL logger: msg (thread names may contain
# spaces, e.g. "Thread-14 (process_request_thread)")
H2O_LINE = re.compile(r"^\d\d-\d\d \d\d:\d\d:\d\d\.\d\d\d \d+ .+ "
                      r"(DEBUG|INFO|WARNI?N?G?|ERROR|CRITICAL)\s*"
                      r"h2o3_tpu(\.\S+)?: .")


def test_log_ring_format_capacity_and_levels():
    import logging
    ring = LogRing(capacity=4)
    logger = logging.Logger("h2o3_tpu.test")   # detached: no global handlers
    logger.addHandler(ring)
    for i in range(6):
        logger.info("line %d", i)
    logger.warning("boom")
    lines = ring.lines()
    assert len(lines) == 4                     # ring wrapped
    assert all(H2O_LINE.match(ln) for ln in lines)
    assert ring.lines(logging.WARNING) == [lines[-1]]
    assert "boom" in lines[-1]


def test_install_log_ring_idempotent():
    import logging
    r1 = install_log_ring()
    r2 = install_log_ring()
    assert r1 is r2
    handlers = [h for h in logging.getLogger("h2o3_tpu").handlers
                if isinstance(h, LogRing)]
    assert len(handlers) == 1


# -- REST surface -----------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    s = H2OServer(port=0).start()
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def exercised(server, tmp_path_factory):
    """Drive real traffic through the stack once per module: a REST parse,
    a map_reduce dispatch, and a REST model build."""
    import jax.numpy as jnp
    from h2o3_tpu.ops.map_reduce import map_reduce

    csv = tmp_path_factory.mktemp("obs") / "obs.csv"
    rng = np.random.default_rng(3)
    x = rng.normal(size=200)
    csv.write_text("x,y\n" + "\n".join(
        f"{v:.4f},{3 * v + rng.normal() * .1:.4f}" for v in x))
    client = H2OClient(server.url)
    frame_key = client.import_file(str(csv))

    def shard_total(shard):
        return shard.sum()

    map_reduce(shard_total, jnp.ones(64, jnp.float32))
    model = client.train("glm", frame_key, y="y")
    return client, frame_key, model


def test_openmetrics_endpoint_populated(server, exercised):
    """Acceptance: /metrics serves OpenMetrics text with a route-latency
    histogram, map_reduce dispatch counters, and parse byte counters — all
    populated by real traffic."""
    with urllib.request.urlopen(server.url + "/metrics") as r:
        assert "openmetrics-text" in r.headers["Content-Type"]
        text = r.read().decode()
    assert text.endswith("# EOF\n")
    assert "# TYPE h2o3_request_duration_seconds histogram" in text
    lat = re.search(r'h2o3_request_duration_seconds_count\{route="/3/'
                    r'ImportFiles",method="POST"\} (\d+)', text)
    assert lat and int(lat.group(1)) >= 1
    mr = re.search(r'h2o3_mapreduce_dispatches_total\{fn="shard_total"\} '
                   r'(\d+)', text)
    assert mr and int(mr.group(1)) >= 1
    pb = re.search(r"^h2o3_parse_bytes_total (\d+)", text, re.M)
    assert pb and int(pb.group(1)) > 0
    assert re.search(r'h2o3_model_builds_total\{algo="glm"\} \d+', text)
    assert re.search(r"^h2o3_dkv_keys \d+", text, re.M)


def test_metrics_json_snapshot(server, exercised):
    out = _get(server, "/3/Metrics")
    assert out["__meta"]["schema_type"] == "MetricsV3"
    rows = out["metrics"]
    assert rows and all(set(r) == {"name", "type", "labels", "value"}
                        for r in rows)
    names = {r["name"] for r in rows}
    assert "h2o3_requests_total" in names
    assert "h2o3_parse_rows_total" in names


def test_client_accessors(server, exercised):
    client = exercised[0]
    assert any(s["name"] == "h2o3_requests_total" for s in client.metrics())
    assert "# EOF" in client.metrics_text()
    assert any(e["kind"] == "collective" for e in client.timeline())
    assert H2O_LINE.match(client.logs().splitlines()[0])


def test_logs_endpoint_serves_real_lines(server, exercised):
    # write a known line through the reference's log-and-echo route
    body = urllib.parse.urlencode({"message": "obs-test-sentinel"}).encode()
    urllib.request.urlopen(urllib.request.Request(
        server.url + "/3/LogAndEcho", data=body, method="POST"))
    out = _get(server, "/3/Logs")
    lines = out["log"].splitlines()
    assert lines and all(H2O_LINE.match(ln) for ln in lines)
    assert any("obs-test-sentinel" in ln for ln in lines)
    # reference-parity file route (h2o-py get_log); warn file filters INFO out
    noded = _get(server, "/3/Logs/nodes/0/files/info")
    assert noded["name"] == "info" and "obs-test-sentinel" in noded["log"]
    warn = _get(server, "/3/Logs/nodes/0/files/warn")
    assert "obs-test-sentinel" not in warn["log"]
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(server, "/3/Logs/nodes/0/files/nope")
    assert ei.value.code == 404


def test_timeline_carries_dispatch_and_model_build(server, exercised):
    kinds = {e["kind"] for e in _get(server, "/3/Timeline")["events"]}
    assert "collective" in kinds      # map_reduce dispatch
    assert "model" in kinds           # ModelBuilder fit wall-time
    assert "iteration" in kinds       # GLM IRLS loop


def test_jstack_and_watermeters(server):
    js = _get(server, "/3/JStack")
    assert any(t["name"] == "MainThread" for t in js["traces"])
    cpu = _get(server, "/3/WaterMeterCpuTicks/0")
    assert "cpu" in cpu["cpu_ticks"]
    io = _get(server, "/3/WaterMeterIo")
    assert isinstance(io["persist_stats"], dict)


def test_watermeter_cpu_ticks_schema(server):
    """Dedicated WaterMeterCpuTicks coverage (reference: reads /proc/stat):
    aggregate + per-cpu rows of non-negative monotone tick counters."""
    out = _get(server, "/3/WaterMeterCpuTicks/0")
    assert out["__meta"]["schema_type"] == "WaterMeterCpuTicksV3"
    ticks = out["cpu_ticks"]
    assert "cpu" in ticks                      # the aggregate row
    assert any(k != "cpu" and k.startswith("cpu") for k in ticks)
    for row in ticks.values():
        assert len(row) == 7                   # user..softirq fields
        assert all(isinstance(v, int) and v >= 0 for v in row)
    # ticks only go up: a second sample's aggregate is >= the first's
    again = _get(server, "/3/WaterMeterCpuTicks/0")["cpu_ticks"]
    assert all(b >= a for a, b in zip(ticks["cpu"], again["cpu"]))
    # the node index is a path param; other indices serve the same process
    assert _get(server, "/3/WaterMeterCpuTicks/1")["cpu_ticks"]


def test_watermeter_io_counters(server, tmp_path_factory):
    """Dedicated WaterMeterIo coverage (reference: reads /proc/self/io):
    byte counters that advance when the persist layer writes."""
    out = _get(server, "/3/WaterMeterIo")
    assert out["__meta"]["schema_type"] == "WaterMeterIoV3"
    stats = out["persist_stats"]
    if not stats:                  # /proc/self/io absent in some sandboxes
        pytest.skip("/proc/self/io not readable here")
    # sandboxed kernels vary on field spelling ("rchar" vs a truncated
    # first line); the contract is: non-negative int counters including a
    # write-char counter
    assert "wchar" in stats
    assert all(isinstance(v, int) and v >= 0 for v in stats.values())
    # drive real write traffic, then the write counter must not regress
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.persist.frame_io import save_frame
    fr = Frame.from_arrays({"a": np.arange(5000, dtype=np.float32)})
    save_frame(fr, str(tmp_path_factory.mktemp("iometer") / "fr"))
    again = _get(server, "/3/WaterMeterIo")["persist_stats"]
    assert again["wchar"] >= stats["wchar"]


def test_logs_level_param_filters_ring(server):
    """Satellite: /3/Logs?level=... filters the LogRing by severity
    (reference LogsHandler's per-level files); no param = unfiltered."""
    import logging
    logger = logging.getLogger("h2o3_tpu")
    logger.info("level-param-info-sentinel")
    logger.warning("level-param-warn-sentinel")
    unfiltered = _get(server, "/3/Logs")["log"]
    assert "level-param-info-sentinel" in unfiltered
    assert "level-param-warn-sentinel" in unfiltered
    warn = _get(server, "/3/Logs?level=warn")["log"]
    assert "level-param-warn-sentinel" in warn
    assert "level-param-info-sentinel" not in warn
    # numeric levels work too (logging.ERROR = 40 filters warnings out)
    err = _get(server, "/3/Logs?level=40")["log"]
    assert "level-param-warn-sentinel" not in err
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(server, "/3/Logs?level=bogus")
    assert ei.value.code == 404


def test_profiler_excludes_its_own_thread(server):
    prof = _get(server, "/3/Profiler?depth=3")
    assert prof["stacktraces"], "profiler must still see other threads"
    assert not any("r_profiler" in st for st in prof["stacktraces"])


def test_fault_injection_counts_surface_as_metrics(server, monkeypatch):
    import jax.numpy as jnp
    from h2o3_tpu.ops.map_reduce import map_reduce
    from h2o3_tpu.utils.timeline import FaultInjected, inject_faults

    # retries disabled: the drop passes through unchanged and injects
    # EXACTLY one fault (retry semantics have their own tests in
    # tests/test_chaos.py)
    monkeypatch.setenv("H2O3TPU_DISPATCH_RETRIES", "0")

    def before():
        m = re.search(r'h2o3_faults_injected_total\{kind="drop"\} (\d+)',
                      _text())
        return int(m.group(1)) if m else 0

    def _text():
        with urllib.request.urlopen(server.url + "/metrics") as r:
            return r.read().decode()

    n0 = before()
    with inject_faults(drop_rate=1.0):
        with pytest.raises(FaultInjected):
            map_reduce(lambda s: s.sum(), jnp.ones(16, jnp.float32))
    assert before() == n0 + 1


def test_request_metrics_label_by_pattern_not_path(server, exercised):
    _, frame_key, _ = exercised
    try:
        # the frame may have been swept by the per-test DKV clear; a 404 on
        # the matched route still records the route-pattern label
        _get(server, f"/3/Frames/{frame_key}")
    except urllib.error.HTTPError:
        pass
    _get(server, "/3/WaterMeterCpuTicks/0")
    with urllib.request.urlopen(server.url + "/metrics") as r:
        text = r.read().decode()
    assert re.search(r'h2o3_requests_total\{route="/3/Frames/\(\[\^/\]\+\)"',
                     text)
    # regex classes render as placeholders, not mangled literals ("d+")
    assert 'route="/3/WaterMeterCpuTicks/{n}"' in text
    assert frame_key not in text      # raw keys never become label values


# -- route-coverage smoke sweep (CI guard for the dead-handler bug class) ---


def test_every_parameterless_get_route_is_not_5xx(server):
    """GET every parameterless GET route; anything ≥500 is a dead handler
    (the /3/Logs bug class: a route wired to state that doesn't exist)."""
    from h2o3_tpu.api.server import _ROUTES
    failures = []
    for pat, method, fn in _ROUTES:
        if method != "GET" or "(" in pat:
            continue
        # \d+ routes get a concrete path so the handler actually runs
        # (a literal "d+" path would 404 at the router and hide a dead
        # handler); \. unescapes to the literal dot
        path = pat.replace(r"\d+", "0").replace("\\", "")
        try:
            with urllib.request.urlopen(server.url + path) as r:
                code = r.status
        except urllib.error.HTTPError as e:
            code = e.code
        if code >= 500:
            failures.append((path, code, fn.__name__))
    assert not failures, f"dead GET handlers: {failures}"
