"""map_reduce substrate tests (reference: ``MRTaskTest.java``, ``KVTest.java``)."""

import numpy as np
import jax.numpy as jnp

from h2o3_tpu import Frame
from h2o3_tpu.ops.map_reduce import map_reduce, map_cols, segment_sum_cols


def test_map_reduce_sum(rng):
    f = Frame.from_arrays({"x": rng.normal(size=1000)})
    x = f.vec("x").data
    mask = f.row_mask()
    total = map_reduce(lambda xs, ms: jnp.where(ms, xs, 0.0).sum(), x, mask)
    np.testing.assert_allclose(float(total), f.vec("x").to_numpy().sum(), rtol=1e-5)


def test_map_reduce_histogram(rng):
    """Per-shard fixed-shape partial (a histogram) psum-reduced — the GBM pattern."""
    x = rng.uniform(0, 1, size=2000).astype(np.float32)
    f = Frame.from_arrays({"x": x})
    data, mask = f.vec("x").data, f.row_mask()

    def histo(xs, ms):
        bins = jnp.clip((xs * 10).astype(jnp.int32), 0, 9)
        return segment_sum_cols(jnp.where(ms, 1.0, 0.0), jnp.where(ms, bins, -1), 10)

    h = map_reduce(histo, data, mask)
    expected = np.histogram(x, bins=10, range=(0, 1))[0]
    np.testing.assert_array_equal(np.asarray(h).astype(int), expected)


def test_map_reduce_gram(rng):
    """Distributed X'X — the GLM pattern."""
    X = rng.normal(size=(512, 4)).astype(np.float32)
    f = Frame.from_arrays({f"c{i}": X[:, i] for i in range(4)})
    m = f.matrix()
    mask = f.row_mask()
    gram = map_reduce(lambda M, ms: jnp.einsum("ij,ik->jk", jnp.where(ms[:, None], M, 0), M), m, mask)
    np.testing.assert_allclose(np.asarray(gram), X.T @ X, rtol=2e-4, atol=1e-3)


def test_map_cols_elementwise(rng):
    f = Frame.from_arrays({"x": rng.normal(size=100)})
    y = map_cols(lambda a: a * 2 + 1, f.vec("x").data)
    np.testing.assert_allclose(np.asarray(y)[:100], f.vec("x").to_numpy() * 2 + 1, rtol=1e-6)


def test_segment_sum_drops_negative_ids():
    vals = jnp.ones(6)
    ids = jnp.array([0, 1, -1, 1, 2, -1])
    out = segment_sum_cols(vals, ids, 3)
    np.testing.assert_array_equal(np.asarray(out), [1, 2, 1])
