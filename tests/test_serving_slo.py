"""SLO-adaptive serving (ISSUE 13): latency-budget batching, priority
shedding, and the slice-leased scoring replica pool.

Acceptance pins:

- with no SLO configured, serving output is bit-identical to the PR 6
  fixed-window path (``mode == "fixed"``, window == base, predictions
  equal ``Model.predict``);
- replica slice leases come from :class:`MeshScheduler` and release
  cleanly on evict/shutdown — no leaked slices;
- shedding is accounted (``h2o3_score_shed_total{reason,priority}`` +
  the ``GET /3/Score`` ``shed`` block), low priority first;
- the batcher window is resolved at CONSTRUCTION, not module import
  (the ``WINDOW_S`` ENV001 regression).
"""

import threading
import time

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.serving import SCORING, ServiceUnavailable, Shed, SLOController
from h2o3_tpu.serving.slo import LatencyRing, clamp_priority
from h2o3_tpu.utils.registry import DKV


@pytest.fixture(autouse=True)
def _reset_scoring():
    SCORING.reset()
    SCORING.budget_bytes = None
    yield
    SCORING.reset()
    SCORING.budget_bytes = None


@pytest.fixture
def frame(rng):
    n = 400
    X = rng.normal(size=(n, 3)).astype(np.float32)
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = np.where(X[:, 0] - X[:, 1] > 0, "yes", "no")
    fr = Frame.from_arrays(cols, key="slo_frame")
    DKV.put("slo_frame", fr)
    return fr


@pytest.fixture
def gbm(frame):
    from h2o3_tpu.models.gbm import GBM
    return GBM(ntrees=4, max_depth=3, seed=7,
               model_id="slo_gbm").train(y="y", training_frame=frame)


def _rows(frame, n, start=0):
    names = [c for c in frame.names if c != "y"]
    pdf = frame[names].to_pandas().iloc[start:start + n]
    return [{k: float(v) for k, v in rec.items()}
            for rec in pdf.to_dict(orient="records")]


class TestController:
    def test_ring_percentiles(self):
        ring = LatencyRing(size=64)
        assert ring.percentile(99) is None          # cold ring: no signal
        for v in range(1, 101):
            ring.record(v / 1000.0)
        assert ring.percentile(50) == pytest.approx(0.064, abs=0.015)
        assert ring.percentile(99) >= 0.099

    def test_ring_rejects_nan_negative_and_counts_drops(self):
        """ISSUE 15 satellite: a NaN in the ring makes sorted() a partial
        order — every percentile read downstream would steer the SLO
        controller off garbage. Invalid latencies drop and account in
        h2o3_telemetry_rejected_total{where=latency_ring}."""
        from h2o3_tpu.utils.telemetry import METRICS
        rejected = METRICS.counter("h2o3_telemetry_rejected", "",
                                   ("where",)).labels(where="latency_ring")
        before = rejected.value
        ring = LatencyRing(size=64)
        for v in range(1, 101):
            ring.record(v / 1000.0)
        p99_clean = ring.percentile(99)
        ring.record(float("nan"))
        ring.record(-1.0)
        ring.record(float("inf"))
        assert rejected.value == before + 3
        assert ring.count == 100                    # drops never landed
        assert ring.percentile(99) == p99_clean     # signal unpoisoned

    def test_no_target_is_fixed_window_and_never_sheds(self):
        c = SLOController(base_window_s=0.002, slo_ms=None)
        assert not c.active
        for _ in range(20):
            c.record_latency(10.0)                  # terrible latencies
            c.record_dispatch(10.0, 4096)
        assert c.window_s(queued_rows=10 ** 6) == 0.002
        c.admit(0, queued_rows=10 ** 6, n_rows=64)  # must not raise
        assert c.snapshot()["mode"] == "fixed"

    def test_violating_p99_narrows_hard(self):
        c = SLOController(base_window_s=0.004, slo_ms=10.0)
        for _ in range(16):
            c.record_latency(0.02)                  # p99 = 20ms > 10ms SLO
        w0 = c.window_s(0)
        assert w0 < 0.004
        assert c.window_s(0) < w0                   # keeps narrowing
        assert c.narrowed >= 2

    def test_queue_growth_widens_capped_at_quarter_slo(self):
        c = SLOController(base_window_s=0.001, slo_ms=100.0)
        for _ in range(16):
            c.record_latency(0.006)                 # healthy (p99 6% of SLO)
        c.record_dispatch(0.001, rows=8)            # last dispatch: 8 rows
        w = 0.0
        for _ in range(64):
            w = c.window_s(queued_rows=4096)        # queue grew past 8
        assert w > 0.001
        assert w <= 100.0 / 1e3 / 4.0 + 1e-12       # SLO/4 cap
        assert c.widened > 0

    def test_headroom_narrows_gently_with_floor(self):
        c = SLOController(base_window_s=0.004, slo_ms=1000.0)
        for _ in range(16):
            c.record_latency(0.001)                 # massive headroom
        c.record_dispatch(0.001, rows=4096)         # queue never "grows"
        for _ in range(200):
            c.window_s(queued_rows=0)
        assert c.current_window_s() == pytest.approx(0.004 / 16.0)

    def test_admit_sheds_low_priority_first(self):
        c = SLOController(base_window_s=0.001, slo_ms=10.0, max_bucket=64)
        c.record_dispatch(0.030, rows=64)           # 30ms per dispatch EMA
        # ~2 dispatches queued ahead -> est ~60ms+ vs 10ms budget
        with pytest.raises(Shed) as ei:
            c.admit(0, queued_rows=64, n_rows=16)
        assert ei.value.reason == "overload"
        assert ei.value.retry_after_ms >= 100
        with pytest.raises(Shed):
            c.admit(3, queued_rows=64, n_rows=16)   # 4x budget still < est
        c.admit(9, queued_rows=64, n_rows=16)       # 10x budget: admitted
        assert c.shed_count == 2

    def test_per_model_override_beats_env(self, monkeypatch):
        monkeypatch.setenv("H2O3TPU_SCORE_SLO_MS", "50")
        c = SLOController(base_window_s=0.001)
        assert c.slo_ms == 50.0
        c.set_target(200.0)
        assert c.slo_ms == 200.0
        c.set_target(None)                          # None leaves it alone
        assert c.slo_ms == 200.0

    def test_clamp_priority(self):
        assert clamp_priority(None) == 5
        assert clamp_priority(-3) == 0
        assert clamp_priority(42) == 9
        assert clamp_priority("7") == 7
        assert clamp_priority("nope") == 5


class TestWindowConstruction:
    def test_window_resolved_at_construction_not_import(self, frame, gbm,
                                                        monkeypatch):
        """The WINDOW_S regression (ISSUE 13 satellite): a late env change
        must be honored by the next batcher, not silently ignored because
        the module captured the env at import."""
        from h2o3_tpu.serving.batcher import ModelBatcher
        monkeypatch.setenv("H2O3TPU_SCORE_WINDOW_MS", "7.5")
        entry = SCORING._admit(gbm.key)     # admitted under the new env
        try:
            assert entry.batcher._window == pytest.approx(7.5e-3)
            assert entry.slo.base_window_s == pytest.approx(7.5e-3)
            monkeypatch.setenv("H2O3TPU_SCORE_WINDOW_MS", "0.25")
            b2 = ModelBatcher(entry)
            try:
                assert b2._window == pytest.approx(0.25e-3)
            finally:
                b2.stop()
        finally:
            SCORING.reset()

    def test_no_slo_output_bit_identical_to_fixed_window_path(self, frame,
                                                              gbm):
        """ISSUE 13 acceptance: no SLO configured -> the PR 6 path,
        bit-identical predictions and a fixed window."""
        rows = _rows(frame, 17)
        out = SCORING.score(gbm.key, rows)["predictions"]
        entry = SCORING._resident[gbm.key]
        snap = entry.slo.snapshot()
        assert snap["mode"] == "fixed" and snap["target_ms"] is None
        assert entry.slo.current_window_s() == entry.slo.base_window_s
        names = [c for c in frame.names if c != "y"]
        pred = gbm.predict(Frame(names, [frame.vec(c) for c in names]))
        want = np.asarray(pred.vec("pyes").to_numpy())[:17]
        assert np.array_equal(np.asarray(out["pyes"], np.float32), want)
        assert "shed" not in {s["reason"] for s in SCORING.stats()["shed"]}


class TestShedding:
    def test_overloaded_low_priority_sheds_503_high_serves(self, frame, gbm):
        from h2o3_tpu.utils import telemetry as _tm
        rows = _rows(frame, 4)
        SCORING.score(gbm.key, rows, slo_ms=10.0)     # admit + set target
        entry = SCORING._resident[gbm.key]
        # fake a saturated tier: ~50ms per dispatch against a 10ms SLO —
        # beyond priority 1's 20ms budget, inside priority 9's 100ms one
        # (set the EMA directly: the warm-up dispatch above seeded it with
        # its compile wall, and one record_dispatch only moves it by 0.3)
        with entry.slo._lock:
            entry.slo._ema_dispatch_s = 0.05
        shed0 = _tm.SCORE_SHED.labels(reason="overload", priority="1").value
        with pytest.raises(ServiceUnavailable) as ei:
            SCORING.score(gbm.key, rows, priority=1)
        assert ei.value.retry_after_ms >= 100
        assert _tm.SCORE_SHED.labels(reason="overload",
                                     priority="1").value == shed0 + 1
        st = SCORING.stats()
        assert {"reason": "overload", "priority": 1, "count": 1} in st["shed"]
        assert st["shed_total"] >= 1
        # the same load admits priority 9 (10x budget tolerance)
        out = SCORING.score(gbm.key, rows, priority=9)
        assert len(out["predictions"]["predict"]) == 4
        assert out["priority"] == 9

    def test_timeout_shed_is_accounted(self, frame, gbm, monkeypatch):
        import h2o3_tpu.serving.batcher as bm
        from h2o3_tpu.utils import telemetry as _tm
        monkeypatch.setattr(bm, "SCORE_TIMEOUT_S", 0.05)
        entry = SCORING._admit(gbm.key)
        entry.batcher._window = 5.0              # hold the batch open
        t0 = _tm.SCORE_SHED.labels(reason="timeout", priority="5").value
        try:
            with pytest.raises(ServiceUnavailable):
                SCORING.score(gbm.key, _rows(frame, 2))
        finally:
            entry.batcher._window = entry.slo.base_window_s
        assert _tm.SCORE_SHED.labels(reason="timeout",
                                     priority="5").value == t0 + 1

    def test_withdrawer_losing_to_eviction_gets_evicted_not_timeout(
            self, frame, gbm, monkeypatch):
        """ISSUE 13 satellite, the deterministic interleave: the caller
        TIMES OUT first (enters the withdraw path) but the eviction has
        already drained the queue — ``remove`` misses, and the caller
        must surface the retryable :class:`Evicted` (-> 503 upstream),
        not a timeout blamed on the device, and never hang."""
        from h2o3_tpu.serving.batcher import Evicted, ModelBatcher
        import h2o3_tpu.serving.batcher as bm
        monkeypatch.setattr(bm, "SCORE_TIMEOUT_S", 0.05)
        entry = SCORING._admit(gbm.key)
        b = entry.batcher
        b._window = 30.0                         # batch never dispatches
        errs: list = []

        def caller():
            try:
                b.submit(*entry.schema.adapt_rows(_rows(frame, 2)), 2)
            except BaseException as e:   # noqa: BLE001 — asserted below
                errs.append(e)

        t = threading.Thread(target=caller)
        t.start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:       # wait for the enqueue
            with b._cond:
                if b._queue:
                    break
            time.sleep(0.005)
        # stop()'s exact body, but ordered UNDER the condvar — acquired
        # BEFORE the caller's timeout fires and held across it, so the
        # withdrawer blocks at the lock and deterministically loses: by
        # the time it gets in, the queue is drained AND its pending failed
        with b._cond:
            time.sleep(0.1)                      # caller times out, parks
            b._stopped = True                    # on acquiring this lock
            victims = list(b._queue)
            b._queue.clear()
            for p in victims:
                ModelBatcher._fail(p, Evicted("evicted mid-queue"))
            b._cond.notify_all()
        t.join(timeout=10.0)
        assert not t.is_alive(), "withdraw+eviction must never hang"
        assert len(victims) == 1, "the pending must not be dropped"
        assert len(errs) == 1
        assert isinstance(errs[0], Evicted), errs[0]
        # the service layer maps Evicted to re-admit -> a fresh batcher
        # serves (or a persistent loss 503s); either way the tier lives
        # (normal ceiling restored: the fresh batcher cold-compiles)
        monkeypatch.setattr(bm, "SCORE_TIMEOUT_S", 30.0)
        out = SCORING.score(gbm.key, _rows(frame, 2))
        assert len(out["predictions"]["predict"]) == 2

    def test_withdraw_racing_real_eviction_stays_retryable(self, frame, gbm,
                                                           monkeypatch):
        """The same interleave with the REAL ``stop()`` racing the
        timeout: whichever side wins, the caller gets a clean result or a
        retryable 503 — never a hang, never a server error."""
        import h2o3_tpu.serving.batcher as bm
        monkeypatch.setattr(bm, "SCORE_TIMEOUT_S", 0.1)
        entry = SCORING._admit(gbm.key)
        entry.batcher._window = 30.0             # batch never dispatches
        errs: list = []

        def caller():
            try:
                SCORING.score(gbm.key, _rows(frame, 2))
            except BaseException as e:   # noqa: BLE001 — asserted below
                errs.append(e)

        t = threading.Thread(target=caller)
        t.start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:       # wait for the enqueue
            with entry.batcher._cond:
                if entry.batcher._queue:
                    break
            time.sleep(0.005)
        entry.batcher.stop()                     # eviction races the wait
        t.join(timeout=15.0)
        assert not t.is_alive(), "withdraw+eviction must never hang"
        # Evicted -> transparent re-admit (success) or a retryable 503;
        # anything else (500s, TimeoutError leaking raw) is a regression
        assert errs == [] or isinstance(errs[0], ServiceUnavailable), errs
        with entry.batcher._cond:
            assert entry.batcher._queue == [], "dropped _Pending left behind"
        monkeypatch.setattr(bm, "SCORE_TIMEOUT_S", 30.0)
        out = SCORING.score(gbm.key, _rows(frame, 2))
        assert len(out["predictions"]["predict"]) == 2


class TestReplicaPool:
    def test_leases_come_from_scheduler_and_release(self, frame, gbm):
        """ISSUE 13 acceptance: replica slice leases come from
        MeshScheduler and release cleanly on evict/shutdown."""
        from h2o3_tpu.orchestration.scheduler import MeshScheduler
        import jax
        sched = MeshScheduler(slices=2)
        if sched.n < 2:
            pytest.skip("needs a multi-device (virtual) mesh")
        assert sched.free_count() == 2
        SCORING.configure_replicas(2, scheduler=sched)
        try:
            assert sched.free_count() == 0        # both slices leased
            pool = SCORING.pool
            reps = pool.replicas
            assert len(reps) == 2
            devsets = [set(r.devices) for r in reps]
            assert devsets[0].isdisjoint(devsets[1]), \
                "replicas must hold DISJOINT slices"
            assert set().union(*devsets) == \
                {d.id for d in jax.devices()}
            out = SCORING.score(gbm.key, _rows(frame, 4))
            assert out["replica"] in {r.label for r in reps}
            # evicting the model drops per-replica seats but NOT leases
            assert SCORING.evict(gbm.key) is True
            assert sched.free_count() == 0
            for r in reps:
                assert r.cache.stats()["signatures"] == 0
        finally:
            SCORING.reset()                        # shuts the pool down
        assert sched.free_count() == 2, "leases leaked past shutdown"

    def test_replica_path_matches_predict(self, frame, gbm):
        from h2o3_tpu.orchestration.scheduler import MeshScheduler
        SCORING.configure_replicas(2, scheduler=MeshScheduler(slices=2))
        try:
            rows = _rows(frame, 9)
            out = SCORING.score(gbm.key, rows)["predictions"]
            names = [c for c in frame.names if c != "y"]
            pred = gbm.predict(Frame(names, [frame.vec(c) for c in names]))
            want = np.asarray(pred.vec("pyes").to_numpy())[:9]
            assert np.array_equal(np.asarray(out["pyes"], np.float32), want)
        finally:
            SCORING.reset()

    def test_least_loaded_routing(self, frame, gbm):
        from h2o3_tpu.serving.replicas import ReplicaPool
        pool = ReplicaPool(2, scheduler=None)
        try:
            r0, r1 = pool.replicas
            assert pool.route() is r0              # tie: lowest rid
            with r0._lock:                         # fake load on r0
                pass
            r0.record_dispatch(0.0, 0, 0.0)        # accounting only
            # real load: queued rows
            entry = SCORING._admit(gbm.key)
            b = r0.batcher_for(entry)
            b._window = 5.0
            done = threading.Event()

            def enqueue():
                try:
                    b.submit(np.zeros((4, 3), np.float32),
                             np.full((4, 0), -1, np.int32), 4)
                except Exception:   # noqa: BLE001 — stop() fails it at exit
                    pass
                finally:
                    done.set()

            t = threading.Thread(target=enqueue, daemon=True)
            t.start()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and r0.load() == 0:
                time.sleep(0.005)
            assert r0.load() > 0
            assert pool.route() is r1              # r0 is loaded now
            b.stop()
            done.wait(timeout=5.0)
        finally:
            pool.shutdown()
            SCORING.reset()

    def test_precompile_warms_fresh_replica(self, frame, gbm):
        """Speculative bucket pre-compile at admission: after the warm
        thread joins, the replica's first request is a pure cache hit."""
        from h2o3_tpu.serving.replicas import ScoringReplica
        rep = ScoringReplica(99, scheduler=None)
        try:
            entry = SCORING._admit(gbm.key)
            rep.precompile(entry, buckets=(8, 16)).join(timeout=120)
            st = rep.cache.stats()
            assert st["signatures"] == 2
            misses0 = st["misses"]
            b = rep.batcher_for(entry)
            p = b.submit(*entry.schema.adapt_rows(_rows(frame, 4)), 4)
            assert p.result is not None
            st = rep.cache.stats()
            assert st["misses"] == misses0, \
                "first request on a pre-compiled replica must not compile"
            assert st["hits"] >= 1
        finally:
            rep.stop()
            SCORING.reset()

    def test_scale_up_on_queue_wait_and_down_when_idle(self, frame, gbm):
        from h2o3_tpu.serving.replicas import ReplicaPool
        pool = ReplicaPool(1, scheduler=None, max_replicas=3)
        try:
            assert len(pool.replicas) == 1
            assert pool.maybe_scale(None) is None          # no SLO: no scaling
            for _ in range(8):
                pool.observe_wait(0.5)                     # 500ms >> 25% of SLO
            pool._last_scale = 0.0                         # bypass cooldown
            assert pool.maybe_scale(100.0) == "up"
            assert len(pool.replicas) == 2
            assert pool.scale_ups == 1
            for _ in range(16):
                pool.observe_wait(0.0)                     # idle
            pool._last_scale = 0.0
            assert pool.maybe_scale(100.0) == "down"
            assert len(pool.replicas) == 1
            assert pool.scale_downs == 1
        finally:
            pool.shutdown()

    def test_scale_up_respects_mfu_ceiling(self, monkeypatch):
        from h2o3_tpu.serving import replicas as rmod
        pool = rmod.ReplicaPool(1, scheduler=None, max_replicas=3)
        try:
            monkeypatch.setattr(rmod.ReplicaPool, "mfu_headroom",
                                lambda self: False)
            for _ in range(8):
                pool.observe_wait(0.5)
            pool._last_scale = 0.0
            assert pool.maybe_scale(100.0) is None, \
                "no MFU headroom -> adding replicas cannot help"
            assert len(pool.replicas) == 1
        finally:
            pool.shutdown()

    def test_pool_capped_at_scheduler_slices(self):
        from h2o3_tpu.orchestration.scheduler import MeshScheduler
        from h2o3_tpu.serving.replicas import ReplicaPool
        sched = MeshScheduler(slices=2)
        if sched.n < 2:
            pytest.skip("needs a multi-device (virtual) mesh")
        pool = ReplicaPool(5, scheduler=sched)     # ask for more than slices
        try:
            assert len(pool.replicas) == 2         # an extra would park
            assert pool.max_replicas == 2
        finally:
            pool.shutdown()
        assert sched.free_count() == sched.n

    def test_evicted_entry_seat_is_not_resurrected(self, frame, gbm):
        """A score() racing an eviction between admit and routing must
        hit Evicted (-> transparent re-admit), never silently re-create
        a seat for the dropped model in the replica's cache."""
        from h2o3_tpu.serving.batcher import Evicted
        SCORING.configure_replicas(1)
        try:
            SCORING.score(gbm.key, _rows(frame, 2))
            entry = SCORING._resident[gbm.key]
            rep = SCORING.pool.replicas[0]
            assert SCORING.evict(gbm.key) is True
            assert entry.stopped
            assert rep.cache.stats()["signatures"] == 0
            with pytest.raises(Evicted):
                rep.batcher_for(entry)             # the stale-entry path
            assert rep.cache.stats()["signatures"] == 0
            # the service path re-admits a FRESH entry and serves
            out = SCORING.score(gbm.key, _rows(frame, 2))
            assert len(out["predictions"]["predict"]) == 2
        finally:
            SCORING.reset()

    def test_teardown_repoints_residents_at_local_seat(self, frame, gbm):
        """configure_replicas(0) must re-point already-resident models at
        a fresh local batcher — an entry left holding the shut-down pool
        would 500 on every subsequent request."""
        SCORING.configure_replicas(1)
        try:
            out = SCORING.score(gbm.key, _rows(frame, 3))
            assert out.get("replica") is not None
            SCORING.configure_replicas(0)          # tear the pool down
            assert SCORING.pool is None
            entry = SCORING._resident[gbm.key]
            assert entry.pool is None and entry.batcher is not None
            out = SCORING.score(gbm.key, _rows(frame, 3))
            assert len(out["predictions"]["predict"]) == 3
            assert "replica" not in out
        finally:
            SCORING.reset()

    def test_scaled_up_replica_defers_routing_while_warming(self, frame,
                                                            gbm):
        """A fresh replica must not win least-loaded routing (load 0)
        while its speculative pre-compiles are still running — its first
        requests would pay cold compiles inside someone's budget."""
        from h2o3_tpu.serving.replicas import ReplicaPool
        pool = ReplicaPool(2, scheduler=None)
        try:
            r0, r1 = pool.replicas
            with r1._lock:
                r1._warming = 1                    # pre-compiles in flight
            assert pool.route() is r0, "warming replica must not serve"
            with r1._lock:
                r1._warming = 0
            assert pool.route() in (r0, r1)        # warm again: eligible
            with r0._lock, r1._lock:
                r0._warming = r1._warming = 1      # ALL warming: serve anyway
            assert pool.route() is r0
        finally:
            pool.shutdown()

    def test_env_knob_arms_pool_after_reset(self, frame, gbm, monkeypatch):
        monkeypatch.setenv("H2O3TPU_SCORE_REPLICAS", "2")
        SCORING.reset()                            # re-arms the env check
        try:
            out = SCORING.score(gbm.key, _rows(frame, 3))
            assert out.get("replica") is not None
            assert SCORING.pool is not None
            assert len(SCORING.pool.replicas) >= 1
        finally:
            monkeypatch.delenv("H2O3TPU_SCORE_REPLICAS")
            SCORING.reset()
            assert SCORING.pool is None


class TestRestSurface:
    @pytest.fixture
    def server(self):
        from h2o3_tpu.api import H2OServer
        s = H2OServer(port=0).start()
        yield s
        s.stop()

    @pytest.fixture
    def client(self, server):
        from h2o3_tpu.api import H2OClient
        return H2OClient(server.url)

    def test_priority_and_slo_params_roundtrip(self, frame, gbm, client):
        out = client.score(gbm.key, _rows(frame, 3), priority=7, slo_ms=500)
        assert out["priority"] == 7
        st = client.serving()
        row = next(r for r in st["resident"] if r["model"] == gbm.key)
        assert row["slo"]["target_ms"] == 500.0
        assert row["slo"]["mode"] == "adaptive"
        assert st["shed"] == [] and st["shed_total"] == 0

    def test_shed_is_503_with_retry_after_and_accounted(self, frame, gbm,
                                                        client):
        client.score(gbm.key, _rows(frame, 2), slo_ms=10)
        entry = SCORING._resident[gbm.key]
        entry.slo.record_dispatch(5.0, rows=4096)   # saturate the estimator
        with pytest.raises(RuntimeError, match="503"):
            client.score(gbm.key, _rows(frame, 2), priority=0)
        st = client.serving()
        assert st["shed_total"] >= 1
        assert any(s["reason"] == "overload" and s["priority"] == 0
                   for s in st["shed"])
        text = client.metrics_text()
        assert "h2o3_score_shed_total" in text

    def test_serving_view_carries_replicas(self, frame, gbm, client):
        from h2o3_tpu.orchestration.scheduler import MeshScheduler
        SCORING.configure_replicas(2, scheduler=MeshScheduler(slices=2))
        try:
            client.score(gbm.key, _rows(frame, 2))
            st = client.serving()
            assert st["replicas"]["count"] == len(SCORING.pool.replicas)
            rep = st["replicas"]["replicas"][0]
            assert {"replica", "slice", "devices", "busy_seconds",
                    "queue_wait_seconds", "models"} <= set(rep)
        finally:
            SCORING.reset()

    def test_bad_priority_is_400(self, frame, gbm, client):
        with pytest.raises(RuntimeError, match="400"):
            client.request("POST", f"/3/Score/{gbm.key}",
                           {"rows": [{"x0": 1.0}], "priority": "high"})
