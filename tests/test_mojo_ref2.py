"""Long-tail reference-MOJO importer parity (VERDICT r4 next #3).

The reference repo commits NO MOJO zips for these families (the only
committed artifacts are trees/GLM/KMeans/SE/XGBoost — verified by an
exhaustive ``find``), and this image has no JVM to mint them.  So each
fixture here is a zip SYNTHESIZED to the writer's documented format
(``DeepLearningMojoWriter.java``, ``PCAMojoWriter.java``,
``GlrmMojoWriter.java``, ``CoxPHMojoWriter.java``,
``Word2VecMojoWriter.java``, ``RuleFitMojoWriter.java``,
``TargetEncoderMojoWriter.java``, ``IsotonicRegressionMojoWriter.java``
+ ``AbstractMojoWriter.java`` for the shared kv/blob grammar), and every
expected value is computed by INDEPENDENT math in the test body (explicit
per-row loops following the scoring spec, or closed-form algebra) — never
by calling the reader's own vectorized code path on both sides.
"""

import io
import math
import struct
import zipfile

import numpy as np
import pytest

from h2o3_tpu.genmodel.mojo_ref import load_ref_mojo


# -- fixture builder: the writer side of the MOJO grammar --------------------

def _fmt(v) -> str:
    """AbstractMojoWriter.writekv: value.toString(); java arrays print as
    ``[a, b, c]`` (Arrays.toString), booleans as true/false."""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (list, tuple, np.ndarray)):
        return "[" + ", ".join(str(float(x)) if isinstance(x, (float, np.floating))
                               else str(int(x)) for x in v) + "]"
    return str(v)


def _mojo_zip(algo: str, columns, domains, info: dict, blobs: dict | None = None,
              texts: dict | None = None, supervised=True, n_classes=1,
              extra_ini: str = "") -> bytes:
    """Assemble a model.ini + domains/ + blobs zip in the reference layout
    (ModelMojoReader.java:286-333 grammar)."""
    n_features = len(columns) - (1 if supervised else 0)
    base = {
        "h2o_version": "3.46.0.1", "mojo_version": info.pop("mojo_version", "1.00"),
        "algo": algo, "algorithm": algo,
        "endianness": "LITTLE_ENDIAN", "category": "Unknown",
        "uuid": "1234567890", "supervised": supervised,
        "n_features": n_features, "n_classes": n_classes,
        "n_columns": len(columns),
        "n_domains": sum(d is not None for d in domains),
        "balance_classes": False, "default_threshold": 0.5,
    }
    base.update(info)
    lines = ["[info]"] + [f"{k} = {_fmt(v)}" for k, v in base.items()]
    if extra_ini:                      # extra kv entries (still [info])
        lines += [ln for ln in extra_ini.splitlines() if ln]
    lines += ["", "[columns]"] + list(columns) + ["", "[domains]"]
    dom_files = {}
    di = 0
    for ci, d in enumerate(domains):
        if d is not None:
            fname = f"d{di:03d}.txt"
            lines.append(f"{ci}: {len(d)} {fname}")
            dom_files[f"domains/{fname}"] = "\n".join(d) + "\n"
            di += 1
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("model.ini", "\n".join(lines) + "\n")
        for name, text in dom_files.items():
            z.writestr(name, text)
        for name, text in (texts or {}).items():
            z.writestr(name, text)
        for name, blob in (blobs or {}).items():
            z.writestr(name, blob)
    return buf.getvalue()


def _be_d(arr) -> bytes:
    """ByteBuffer.putDouble stream (big-endian)."""
    return np.asarray(arr, np.float64).astype(">f8").tobytes()


def _load(zip_bytes: bytes):
    return load_ref_mojo(zip_bytes)


def _splice_submodel(parent: bytes, sub: bytes, prefix: str) -> bytes:
    """Embed a submodel zip under ``prefix`` inside the parent archive
    (MultiModelMojoReader nested layout)."""
    buf = io.BytesIO(parent)
    with zipfile.ZipFile(buf, "a") as zp, zipfile.ZipFile(io.BytesIO(sub))             as zs:
        for name in zs.namelist():
            zp.writestr(prefix + name, zs.read(name))
    return buf.getvalue()


# -- DeepLearning ------------------------------------------------------------

class TestDeepLearningMojo:
    def _fixture(self, activation="Tanh", family="gaussian", n_classes=1,
                 dropout=None, norm_resp=False):
        # columns: 1 cat (3 levels), 2 nums, response
        rng = np.random.default_rng(3)
        units = [5, 4, n_classes if n_classes > 1 else 1]
        # cat_offsets [0, 3]: 3 one-hot slots (use_all_factor_levels=true)
        w0 = rng.normal(size=units[1] * units[0]).round(3)
        b0 = rng.normal(size=units[1]).round(3)
        w1 = rng.normal(size=units[2] * units[1]).round(3)
        b1 = rng.normal(size=units[2]).round(3)
        info = {
            "mojo_version": "1.10",
            "mini_batch_size": 1, "nums": 2, "cats": 1,
            "cat_offsets": [0, 3], "norm_mul": [0.5, 2.0],
            "norm_sub": [1.0, -1.0],
            "use_all_factor_levels": True, "activation": activation,
            "distribution": family, "mean_imputation": False,
            "neural_network_sizes": units,
            "hidden_dropout_ratios": dropout or [0.0, 0.0],
            "weight_layer0": w0, "bias_layer0": b0,
            "weight_layer1": w1, "bias_layer1": b1,
            "_genmodel_encoding": "AUTO",
        }
        if norm_resp:
            info["norm_resp_mul"] = [0.25]
            info["norm_resp_sub"] = [10.0]
        domains = [["a", "b", "c"], None, None,
                   [str(i) for i in range(n_classes)] if n_classes > 1
                   else None]
        zb = _mojo_zip("deeplearning", ["cat", "x1", "x2", "y"], domains,
                       info, n_classes=n_classes)
        return _load(zb), (w0, b0, w1, b1)

    @staticmethod
    def _act(name, z):
        if name == "Tanh":
            return 1.0 - 2.0 / (1.0 + math.exp(2.0 * z))
        if name == "Rectifier":
            return 0.5 * (z + abs(z))
        if name == "ExpRectifier":
            return z if z >= 0 else math.exp(z) - 1
        raise AssertionError(name)

    def _expected_row(self, row, w0, b0, w1, b1, activation, units):
        """Independent scalar fprop per GenModel.setInput +
        NeuralNetwork.formNNInputs: one-hot cat, standardized nums."""
        cat, x1, x2 = row
        inp = [0.0] * units[0]
        if math.isnan(cat):
            inp[2] = 1.0                       # NA -> last level of block
        else:
            inp[int(cat)] = 1.0
        for j, x in enumerate((x1, x2)):
            s = 0.0 if math.isnan(x) else (x - [1.0, -1.0][j]) * [0.5, 2.0][j]
            inp[3 + j] = s
        w0f = np.float32(w0)                   # convertDouble2Float
        h = []
        base = activation.replace("WithDropout", "")
        for r in range(units[1]):
            z = sum(float(w0f[r * units[0] + c]) * inp[c]
                    for c in range(units[0])) + b0[r]
            h.append(self._act(base, z))
        w1f = np.float32(w1)
        out = [sum(float(w1f[r * units[1] + c]) * h[c]
                   for c in range(units[1])) + b1[r]
               for r in range(units[2])]
        return out

    def test_regression_forward_exact(self):
        m, (w0, b0, w1, b1) = self._fixture()
        X = np.array([[0, 2.0, 0.5], [2, -1.0, 3.0], [np.nan, np.nan, 1.0]])
        got = m.score(X)
        for r in range(3):
            (exp,) = self._expected_row(X[r], w0, b0, w1, b1, "Tanh", [5, 4, 1])
            assert got[r] == pytest.approx(exp, rel=1e-6)

    def test_binomial_softmax_and_threshold(self):
        m, (w0, b0, w1, b1) = self._fixture(activation="Rectifier",
                                            family="bernoulli", n_classes=2)
        X = np.array([[1, 0.3, -0.7], [0, -2.0, 0.1]])
        got = m.score(X)
        assert got.shape == (2, 2)
        for r in range(2):
            z = self._expected_row(X[r], w0, b0, w1, b1, "Rectifier", [5, 4, 2])
            e = np.exp(np.array(z) - max(z))
            p = e / e.sum()
            assert got[r] == pytest.approx(p, rel=1e-6)
        assert np.allclose(got.sum(axis=1), 1.0)

    def test_dropout_scaling_and_poisson_link(self):
        m, (w0, b0, w1, b1) = self._fixture(activation="TanhWithDropout",
                                            family="poisson",
                                            dropout=[0.5, 0.0])
        X = np.array([[1, 1.0, 1.0]])
        z = self._expected_row(X[0], w0, b0, w1, b1, "TanhWithDropout",
                               [5, 4, 1])
        # hidden outputs scale by (1 - ratio) BEFORE the next layer; redo
        # the final layer on scaled hiddens
        h = []
        for r in range(4):
            s = sum(float(np.float32(w0)[r * 5 + c]) *
                    [0.0, 1.0, 0.0, 0.0, 4.0][c] for c in range(5)) + b0[r]
            h.append(self._act("Tanh", s) * 0.5)
        out = sum(float(np.float32(w1)[c]) * h[c] for c in range(4)) + b1[0]
        assert m.score(X)[0] == pytest.approx(min(1e19, math.exp(out)),
                                              rel=1e-6)
        del z

    def test_response_unscaling(self):
        m, (w0, b0, w1, b1) = self._fixture(norm_resp=True)
        X = np.array([[0, 0.0, 0.0]])
        (raw,) = self._expected_row(X[0], w0, b0, w1, b1, "Tanh", [5, 4, 1])
        assert m.score(X)[0] == pytest.approx(raw / 0.25 + 10.0, rel=1e-6)


# -- PCA ---------------------------------------------------------------------

class TestPCAMojo:
    def _fixture(self, use_all=True):
        # 1 cat (2 levels) + 2 nums, k=2; eigenvector rows: cat levels
        # then nums (permutation maps model col order)
        eig = np.array([[1.0, 0.5], [-1.0, 2.0],   # cat level rows
                        [2.0, 0.0], [0.0, 3.0]])   # num rows
        if not use_all:
            eig = eig[1:]                           # level 0 dropped
        info = {
            "k": 2, "use_all_factor_levels": use_all,
            "permutation": [0, 1, 2], "ncats": 1, "nnums": 2,
            "normSub": [1.0, 2.0], "normMul": [2.0, 0.5],
            "catOffsets": [0, 2] if use_all else [0, 1],
            "eigenvector_size": len(eig),
        }
        zb = _mojo_zip("pca", ["cat", "x1", "x2"],
                       [["u", "v"], None, None], info,
                       blobs={"eigenvectors_raw": _be_d(eig.ravel())},
                       supervised=False)
        return _load(zb), eig

    def test_projection(self):
        m, eig = self._fixture()
        X = np.array([[0, 3.0, 4.0], [1, 1.0, 2.0]])
        got = m.score(X)
        for r, (cat, x1, x2) in enumerate(X):
            exp = eig[int(cat)] + (x1 - 1.0) * 2.0 * eig[2] \
                + (x2 - 2.0) * 0.5 * eig[3]
            assert got[r] == pytest.approx(exp, rel=1e-12)

    def test_na_and_unseen_level_skip(self):
        m, eig = self._fixture()
        got = m.score(np.array([[np.nan, 1.0, 2.0], [7, 1.0, 2.0]]))
        # standardized nums are exactly 0 -> only the (skipped) cat remains
        assert got[0] == pytest.approx([0.0, 0.0])
        assert got[1] == pytest.approx([0.0, 0.0])

    def test_level_drop_without_all_factor_levels(self):
        m, eig = self._fixture(use_all=False)
        got = m.score(np.array([[0, 1.0, 2.0], [1, 1.0, 2.0]]))
        assert got[0] == pytest.approx([0.0, 0.0])      # level 0 dropped
        assert got[1] == pytest.approx(eig[0])           # level 1 -> row 0


# -- GLRM --------------------------------------------------------------------

class TestGlrmMojo:
    def _fixture(self, regularization="None", gammax=0.0, seed=42):
        # rank 2, 3 numeric columns, quadratic loss; Y rows orthogonal so
        # the optimum has closed form x* = a Y^T (Y Y^T)^-1
        Y = np.array([[1.0, 0.0, 1.0], [0.0, 1.0, -1.0]])
        info = {
            "mojo_version": "1.10",
            "initialization": "SVD", "regularizationX": regularization,
            "regularizationY": "None", "gammaX": gammax, "gammaY": 0.0,
            "ncolX": 2, "seed": seed, "reverse_transform": False,
            "cols_permutation": [0, 1, 2], "num_categories": 0,
            "num_numeric": 3, "norm_sub": [0.0, 0.0, 0.0],
            "norm_mul": [1.0, 1.0, 1.0], "transposed": False,
            "ncolA": 3, "ncolY": 3, "nrowY": 2,
            "num_levels_per_category": [], "catOffsets": [0],
        }
        zb = _mojo_zip("glrm", ["x1", "x2", "x3"], [None, None, None], info,
                       blobs={"archetypes": _be_d(Y.ravel())},
                       texts={"losses": "Quadratic\nQuadratic\nQuadratic\n"},
                       supervised=False)
        return _load(zb), Y

    def test_x_solve_reconstructs(self):
        m, Y = self._fixture()
        A = np.array([[2.0, -1.0, 3.0], [0.5, 0.5, 0.0]])
        X = m.score(A)
        # closed-form least-squares target
        exp = A @ Y.T @ np.linalg.inv(Y @ Y.T)
        assert X == pytest.approx(exp, abs=5e-4)
        assert np.abs(X @ Y - A).max() < 1e-3

    def test_deterministic_per_seed(self):
        m, _ = self._fixture()
        A = np.array([[1.0, 2.0, 3.0]])
        assert np.array_equal(m.score(A), m.score(A))

    def test_nonneg_regularizer_projects(self):
        m, Y = self._fixture(regularization="NonNegative", gammax=0.1)
        A = np.array([[-5.0, -5.0, 0.0]])   # optimum wants negative x
        X = m.score(A)
        assert (X >= 0).all()

    def test_missing_cells_skipped(self):
        m, Y = self._fixture()
        A = np.array([[2.0, np.nan, np.nan]])
        X = m.score(A)
        # only column 0 constrains: x0*1 + x1*0 = 2 -> x0 ~ 2 (x1 free-ish)
        assert X[0, 0] == pytest.approx(2.0, abs=1e-2)


# -- CoxPH -------------------------------------------------------------------

class TestCoxPHMojo:
    def _fixture(self, strata=False):
        # 1 cat (3 levels, level 0 dropped), 2 nums
        coef = np.array([0.5, -0.25, 1.5, 2.0])  # [catL1, catL2, num1, num2]
        x_mean_cat = np.array([[0.3, 0.2]])
        x_mean_num = np.array([[1.0, -1.0]])
        info = {
            "coef": coef, "cats": 1, "cat_offsets": [0, 2],
            "use_all_factor_levels": False,
            "num_numerical_columns": 2, "num_offsets": [2, 3],
            "strata_count": 0,
            "x_mean_cat_size1": 1, "x_mean_cat_size2": 2,
            "x_mean_num_size1": 1, "x_mean_num_size2": 2,
        }
        columns = ["cat", "n1", "n2", "y"]
        domains = [["a", "b", "c"], None, None, None]
        if strata:
            info.update(strata_count=2, strata_0=[0.0], strata_1=[1.0],
                        x_mean_cat_size1=2, x_mean_num_size1=2)
            x_mean_cat = np.array([[0.3, 0.2], [0.1, 0.6]])
            x_mean_num = np.array([[1.0, -1.0], [0.0, 2.0]])
            columns = ["s", "cat", "n1", "n2", "y"]
            domains = [["p", "q"], ["a", "b", "c"], None, None, None]
        zb = _mojo_zip("coxph", columns, domains, info,
                       blobs={"x_mean_cat": _be_d(x_mean_cat.ravel()),
                              "x_mean_num": _be_d(x_mean_num.ravel())})
        return _load(zb), coef, x_mean_cat, x_mean_num

    def test_linear_predictor(self):
        m, coef, xc, xn = self._fixture()
        lp_base = xc[0] @ coef[:2] + xn[0] @ coef[2:]
        X = np.array([[0, 1.0, 2.0],     # level 0 dropped -> no cat coef
                      [1, 0.0, 0.0],     # level 1 -> coef[0]
                      [2, -1.0, 1.0]])   # level 2 -> coef[1]
        got = m.score(X)
        exp = [1.0 * 1.5 + 2.0 * 2.0 - lp_base,
               0.5 - lp_base,
               -0.25 - 1.5 + 2.0 - lp_base]
        assert got == pytest.approx(exp, rel=1e-12)

    def test_na_cat_gives_nan(self):
        m, *_ = self._fixture()
        assert math.isnan(m.score(np.array([[np.nan, 1.0, 1.0]]))[0])

    def test_strata_lookup(self):
        m, coef, xc, xn = self._fixture(strata=True)
        lp0 = xc[0] @ coef[:2] + xn[0] @ coef[2:]
        lp1 = xc[1] @ coef[:2] + xn[1] @ coef[2:]
        X = np.array([[0, 1, 1.0, 0.0],   # stratum 0, cat level 1
                      [1, 1, 1.0, 0.0]])  # stratum 1, same features
        got = m.score(X)
        assert got[0] == pytest.approx(0.5 + 1.5 - lp0, rel=1e-12)
        assert got[1] == pytest.approx(0.5 + 1.5 - lp1, rel=1e-12)
        assert got[0] - got[1] == pytest.approx(lp1 - lp0, rel=1e-9)

    def test_unseen_or_na_stratum_is_nan_not_crash(self):
        m, *_ = self._fixture(strata=True)
        X = np.array([[np.nan, 1, 1.0, 0.0],   # NA stratum
                      [7, 1, 1.0, 0.0],        # unseen stratum
                      [0, 1, 1.0, 0.0]])       # healthy row
        got = m.score(X)
        assert math.isnan(got[0]) and math.isnan(got[1])
        assert not math.isnan(got[2])


# -- Word2Vec ----------------------------------------------------------------

class TestWord2VecMojo:
    def _fixture(self):
        words = ["king", "queen", "apple"]
        vecs = np.array([[1.0, 0.0, 0.5, 0.0],
                         [0.9, 0.1, 0.4, 0.0],
                         [-1.0, 0.2, 0.0, 0.8]], np.float32)
        info = {"vec_size": 4, "vocab_size": 3}
        zb = _mojo_zip("word2vec", ["text"], [None], info,
                       blobs={"vectors": vecs.astype(">f4").tobytes()},
                       texts={"vocabulary": "\n".join(words) + "\n"},
                       supervised=False)
        return _load(zb), words, vecs

    def test_lookup_and_unknown(self):
        m, words, vecs = self._fixture()
        assert m.transform0("queen") == pytest.approx(vecs[1])
        assert m.transform0("banana") is None
        out = m.transform(["apple", "nope", "king"])
        assert out[0] == pytest.approx(vecs[2])
        assert np.isnan(out[1]).all()
        assert out[2] == pytest.approx(vecs[0])

    def test_synonyms_ranked_by_cosine(self):
        m, *_ = self._fixture()
        syn = m.find_synonyms("king", 2)
        assert list(syn)[0] == "queen"

    def test_predict_refuses(self):
        m, *_ = self._fixture()
        with pytest.raises(ValueError, match="transform"):
            m.predict(None)


# -- Isotonic ----------------------------------------------------------------

class TestIsotonicMojo:
    def _fixture(self):
        tx = np.array([0.0, 0.2, 0.6, 1.0])
        ty = np.array([0.1, 0.1, 0.7, 0.9])
        def blob(a):
            return struct.pack(">i", len(a)) + _be_d(a)
        info = {"calib_min_x": 0.0, "calib_max_x": 1.0}
        zb = _mojo_zip("isotonicregression", ["x", "y"], [None, None], info,
                       blobs={"calib/thresholds_x": blob(tx),
                              "calib/thresholds_y": blob(ty)})
        return _load(zb), tx, ty

    def test_interpolation_and_clip(self):
        m, tx, ty = self._fixture()
        X = np.array([[0.2], [0.4], [-5.0], [5.0], [np.nan]])
        got = m.score(X)
        assert got[0] == pytest.approx(0.1)
        assert got[1] == pytest.approx(0.4)      # midpoint of 0.1 and 0.7
        assert got[2] == pytest.approx(0.1)      # clipped to min_x
        assert got[3] == pytest.approx(0.9)      # clipped to max_x
        assert math.isnan(got[4])


# -- RuleFit -----------------------------------------------------------------

class TestRuleFitMojo:
    def _fixture(self, model_type=1):
        """RULES_AND_LINEAR gaussian RuleFit: depth=1, ntrees=1, two
        complementary rules on x1 (the two leaves of a stump), nested GLM
        with one rule variable (categorical domain = rule names) + x1."""
        # GLM submodel: a RULES_AND_LINEAR fit sees [M0T0 (cat), x1, y];
        # a RULES-only fit was trained on just the rule column
        rules_only = model_type == 2
        if rules_only:
            glm_info = {
                "family": "gaussian", "link": "identity",
                "beta": [0.7, -0.3, 1.0],    # [ruleL0, ruleL1, icpt]
                "cats": 1, "cat_offsets": [0, 2], "nums": 0,
                "use_all_factor_levels": True, "mean_imputation": False,
            }
            glm_cols = ["M0T0", "y"]
        else:
            glm_info = {
                "family": "gaussian", "link": "identity",
                "beta": [0.7, -0.3, 2.0, 1.0],  # [ruleL0, ruleL1, x1, icpt]
                "cats": 1, "cat_offsets": [0, 2], "nums": 1,
                "use_all_factor_levels": True, "mean_imputation": False,
            }
            glm_cols = ["M0T0", "x1", "y"]
        rule_dom = ["M0T0N1", "M0T0N2"]
        # parent rules kv
        rules_ini = "\n".join([
            "num_rules_M0T0 = 2",
            # rule 0: x1 < 1.5  (var M0T0N1)
            "num_conditions_rule_id_0_0_0 = 1",
            "feature_index_0_0_0_0 = 0", "type_0_0_0_0 = 1",
            "num_treshold0_0_0_0 = 1.5", "operator_0_0_0_0 = 0",
            "feature_name_0_0_0_0 = x1", "nas_included_0_0_0_0 = true",
            "language_condition0_0_0_0 = (x1 < 1.5 or NA)",
            "prediction_value_rule_id_0_0_0 = 0.0",
            "language_rule_rule_id_0_0_0 = r1",
            "coefficient_rule_id_0_0_0 = 0.7",
            "var_name_rule_id_0_0_0 = M0T0N1",
            "support_rule_id_0_0_0 = 0.5",
            # rule 1: x1 >= 1.5 (var M0T0N2); condition ids are
            # {condId}_{ruleId} (RuleFitMojoWriter.java:119)
            "num_conditions_rule_id_0_0_1 = 1",
            "feature_index_0_0_0_1 = 0", "type_0_0_0_1 = 1",
            "num_treshold0_0_0_1 = 1.5", "operator_0_0_0_1 = 1",
            "feature_name_0_0_0_1 = x1", "nas_included_0_0_0_1 = false",
            "language_condition0_0_0_1 = (x1 >= 1.5)",
            "prediction_value_rule_id_0_0_1 = 1.0",
            "language_rule_rule_id_0_0_1 = r2",
            "coefficient_rule_id_0_0_1 = -0.3",
            "var_name_rule_id_0_0_1 = M0T0N2",
            "support_rule_id_0_0_1 = 0.5",
        ]) + "\n"
        parent_info = {
            "linear_model": "glm-1", "model_type": model_type,
            "depth": 1, "ntrees": 1,
            "data_from_rules_codes_len": 0,
            "linear_names_len": 1 if rules_only else 2,
            "linear_names_0": "M0T0",
            **({} if rules_only else {"linear_names_1": "x1"}),
            "submodel_count": 1, "submodel_key_0": "glm-1",
            "submodel_dir_0": "models/m1/",
        }
        parent = _mojo_zip("rulefit", ["x1", "y"], [None, None], parent_info,
                           extra_ini=rules_ini)
        sub = _mojo_zip("glm", glm_cols,
                        [rule_dom] + [None] * (len(glm_cols) - 1), glm_info)
        return _load(_splice_submodel(parent, sub, "models/m1/"))

    def test_rules_and_linear_scoring(self):
        m = self._fixture()
        # rule fires -> GLM cat level = domain index of the fired var;
        # + linear x1 term; + intercept
        X = np.array([[1.0], [2.0], [np.nan]])
        got = m.score(X)
        # x1=1.0: rule M0T0N1 (idx 0) -> beta 0.7; x1 kept: 2.0*1.0; +1
        assert got[0] == pytest.approx(0.7 + 2.0 * 1.0 + 1.0)
        # x1=2.0: rule M0T0N2 (idx 1) -> -0.3; 2*2; +1
        assert got[1] == pytest.approx(-0.3 + 2.0 * 2.0 + 1.0)
        # NaN: rule 0 has NAs included -> fires; x1 NaN -> GLM sees NaN num
        # with no imputation -> Java NaN propagates; numpy matches
        assert math.isnan(got[2])

    def test_rules_only_model(self):
        m = self._fixture(model_type=2)
        # RULES: the linear input is just the rule column, mapped by name
        X = np.array([[1.0], [9.0]])
        got = m.score(X)
        assert got[0] == pytest.approx(0.7 + 1.0)
        assert got[1] == pytest.approx(-0.3 + 1.0)


# -- TargetEncoder -----------------------------------------------------------

class TestTargetEncoderMojo:
    def _fixture(self, blending=False, has_na=True, nclasses=2):
        enc_lines = ["[city]"]
        if nclasses <= 2:
            # categories 0..2 (2 = NA bucket): num den
            enc_lines += ["0 = 4.0 8.0", "1 = 1.0 4.0", "2 = 3.0 3.0"]
        else:
            for cat in range(3):
                for tc in (1, 2):
                    enc_lines.append(f"{cat} = {cat + tc}.0 10.0 {tc}")
        te = "feature_engineering/target_encoding/"
        texts = {
            te + "encoding_map.ini": "\n".join(enc_lines) + "\n",
            te + "te_column_name_to_missing_values_presence.ini":
                f"city = {1 if has_na else 0}\n",
            te + "input_encoding_columns_map.ini":
                "[from]\ncity\n[to]\ncity\n",
            te + "input_output_columns_map.ini":
                "[from]\ncity\n[to]\ncity_te\n",
        }
        info = {"with_blending": blending, "non_predictors": "y",
                "keep_original_categorical_columns": True}
        if blending:
            info.update(inflection_point=5.0, smoothing=1.0)
        zb = _mojo_zip("targetencoder", ["city", "y"],
                       [["nyc", "sf", "la"], ["no", "yes"]], info,
                       texts=texts, n_classes=nclasses)
        return _load(zb)

    def test_posterior_means(self):
        from h2o3_tpu.frame.frame import Frame
        m = self._fixture()
        fr = Frame.from_arrays({"city": np.array(["nyc", "sf"], object)})
        out = m.transform(fr)
        te = out.vec("city_te").to_numpy()[:2]
        assert te[0] == pytest.approx(4.0 / 8.0)
        assert te[1] == pytest.approx(1.0 / 4.0)

    def test_na_uses_na_bucket_or_prior(self):
        from h2o3_tpu.frame.frame import Frame
        fr = Frame.from_arrays({"city": np.array(["nyc", None], object)})
        m = self._fixture(has_na=True)
        te = m.transform(fr).vec("city_te").to_numpy()[:2]
        assert te[1] == pytest.approx(3.0 / 3.0)        # NA bucket
        m2 = self._fixture(has_na=False)
        te2 = m2.transform(fr).vec("city_te").to_numpy()[:2]
        prior = (4.0 + 1.0 + 3.0) / (8.0 + 4.0 + 3.0)
        assert te2[1] == pytest.approx(prior)

    def test_blending(self):
        from h2o3_tpu.frame.frame import Frame
        m = self._fixture(blending=True)
        fr = Frame.from_arrays({"city": np.array(["sf"], object)})
        te = m.transform(fr).vec("city_te").to_numpy()[0]
        prior = 8.0 / 15.0
        lam = 1.0 / (1.0 + math.exp((5.0 - 4) / 1.0))
        assert te == pytest.approx(lam * 0.25 + (1 - lam) * prior)

    def test_source_column_replaced_unless_kept(self):
        from h2o3_tpu.frame.frame import Frame
        fr = Frame.from_arrays({"city": np.array(["nyc"], object)})
        kept = self._fixture()                  # keep_original=True fixture
        assert "city" in kept.transform(fr).names
        dropped = self._fixture()
        dropped.keep_original = False
        out = dropped.transform(fr)
        assert "city" not in out.names and "city_te" in out.names

    def test_multiclass_encodes_nminus1(self):
        from h2o3_tpu.frame.frame import Frame
        m = self._fixture(nclasses=3)
        fr = Frame.from_arrays({"city": np.array(["nyc"], object)})
        out = m.transform(fr)
        # legacy naming comes from inout mapping: single 'city_te' name in
        # the mapping, remaining class col synthesized
        cols = [c for c in out.names if c.endswith("_te")]
        assert len(cols) == 2
        v1 = out.vec(cols[0]).to_numpy()[0]
        assert v1 == pytest.approx((0 + 1) / 10.0)      # cat 0, class 1


# -- Generic integration -----------------------------------------------------

def test_generic_scores_dl_mojo(tmp_path):
    m = TestDeepLearningMojo()
    model, _ = m._fixture(activation="Rectifier", family="bernoulli",
                          n_classes=2)
    # round-trip through the Generic import surface
    from h2o3_tpu.frame.frame import Frame
    fr = Frame.from_arrays({
        "cat": np.array(["a", "b", "c"], object),
        "x1": np.array([0.1, -0.5, 2.0], np.float32),
        "x2": np.array([1.0, 0.0, -1.0], np.float32)})
    pred = model.predict(fr)
    assert "predict" in pred.names
    p = pred.vec("p1").to_numpy()[: fr.nrows]
    assert ((p >= 0) & (p <= 1)).all()


# -- XGBoost (REAL reference artifacts) --------------------------------------

class TestXGBoostMojo:
    """Unlike the synthesized fixtures above, these two zips are the
    reference's own committed MOJOs
    (``h2o-genmodel-extensions/xgboost/src/test/resources/hex/genmodel/
    algos/xgboost/xgboost_java.zip`` and ``xgboost.zip``), so the
    regression test is row-identical ground truth: the artifact's
    ``experimental/modelDetails.json`` stores the exact training MSE on
    prostate.csv (already a committed fixture)."""

    STORED_TRAIN_MSE = 3.3232581458216086      # modelDetails.json, 380 rows

    def test_regression_row_identical_to_stored_metrics(self):
        import csv
        m = load_ref_mojo("tests/data/ref_mojo/xgboost_prostate_age.zip")
        assert m.algo == "xgboost"
        assert m.booster["objective"] == "reg:squarederror"
        assert len(m.booster["trees"]) == 50
        rows = list(csv.DictReader(open("tests/data/ref_mojo/prostate.csv")))
        feats = m.columns[: m.n_features]
        X = np.array([[float(r[c]) for c in feats] for r in rows])
        y = np.array([float(r["AGE"]) for r in rows])
        mse = float(np.mean((m.score(X) - y) ** 2))
        # f32 leaf accumulation vs the stored f64 metric: ~1e-6 relative
        assert mse == pytest.approx(self.STORED_TRAIN_MSE, abs=1e-4)

    def test_multinomial_sparse_model_loads_and_scores_simplex(self):
        m = load_ref_mojo("tests/data/ref_mojo/xgboost_multinomial.zip")
        assert m.nclasses == 3 and m.sparse
        assert m.booster["objective"] == "multi:softprob"
        rng = np.random.default_rng(0)
        X = np.zeros((8, m.n_features))
        X[:, :3] = rng.integers(0, 2, (8, 3)).astype(float)
        X[:, 3:] = rng.normal(size=(8, m.n_features - 3))
        X[0, 5] = np.nan                       # NA num takes default path
        P = m.score(X)
        assert P.shape == (8, 3)
        assert np.allclose(P.sum(1), 1.0, atol=1e-6)
        assert np.isfinite(P).all()

    def test_na_routes_to_default_child(self):
        m = load_ref_mojo("tests/data/ref_mojo/xgboost_prostate_age.zip")
        X = np.full((1, m.n_features), np.nan)   # all-NA row still scores
        p = m.score(X)
        assert np.isfinite(p).all()


# -- ExtendedIsolationForest -------------------------------------------------

class TestExtendedIsoForMojo:
    def _fixture(self):
        """One 2-dim EIF tree (extension level 1): root splits on
        dot(row - p, n); left leaf isolates 1 row, right leaf holds 6."""
        k = 2
        def node(num, n, p):
            return struct.pack("<iB", num, ord("N")) + \
                np.asarray(n, "<f8").tobytes() + np.asarray(p, "<f8").tobytes()
        def leaf(num, rows):
            return struct.pack("<iB", num, ord("L")) + struct.pack("<i", rows)
        blob = struct.pack("<i", k) + \
            node(0, [1.0, 0.0], [0.5, 0.0]) + leaf(1, 1) + leaf(2, 6)
        zb = _mojo_zip("extendedisolationforest", ["a", "b"], [None, None],
                       {"ntrees": 1, "sample_size": 7},
                       blobs={"trees/t00.bin": blob}, supervised=False)
        return _load(zb)

    def test_path_lengths_and_anomaly_score(self):
        m = self._fixture()
        X = np.array([[0.0, 0.0],    # (0-0.5)*1 <= 0 -> left leaf, 1 row
                      [2.0, 0.0]])   # right leaf, 6 rows
        out = m.score(X)
        import math as _m
        c = lambda n: 0.0 if n < 2 else (1.0 if n == 2 else
            2 * (_m.log(n - 1) + 0.5772156649) - 2 * (n - 1) / n)
        pl0, pl1 = 1 + c(1), 1 + c(6)
        assert out[0, 1] == pytest.approx(pl0)
        assert out[1, 1] == pytest.approx(pl1)
        assert out[0, 0] == pytest.approx(2 ** (-pl0 / c(7)))
        # the isolated row is MORE anomalous
        assert out[0, 0] > out[1, 0]


# -- less-traveled importer paths -------------------------------------------

class TestMultinomialRuleFit:
    def _fixture(self):
        """3-class RULES-only RuleFit: per class one rule pair on x1
        (varName grammar M{i}T{j}N{node}_{class}); multinomial GLM
        submodel with 3 one-rule-column features M0T0C0/C1/C2."""
        classes = ["lo", "mid", "verylo"]    # 'verylo' suffix-overlaps 'lo'
        rule_lines = ["num_rules_M0T0 = 6"]
        doms = {f"M0T0C{k}": [] for k in range(3)}
        rid = 0
        for k, cls in enumerate(classes):
            for op_, thr, node in [(0, 0.0, 1), (1, 0.0, 2)]:
                # the two leaves of an x1<0 stump
                var = f"M0T0N{node}_{cls}"
                doms[f"M0T0C{k}"].append(var)
                cid = f"0_0_0_{rid}"
                rule_lines += [
                    f"num_conditions_rule_id_0_0_{rid} = 1",
                    f"feature_index_{cid} = 0", f"type_{cid} = 1",
                    f"num_treshold{cid} = {thr}", f"operator_{cid} = {op_}",
                    f"feature_name_{cid} = x1",
                    f"nas_included_{cid} = false",
                    f"language_condition{cid} = c",
                    f"prediction_value_rule_id_0_0_{rid} = 0.0",
                    f"language_rule_rule_id_0_0_{rid} = r",
                    f"coefficient_rule_id_0_0_{rid} = 0.1",
                    f"var_name_rule_id_0_0_{rid} = {var}",
                    f"support_rule_id_0_0_{rid} = 0.5",
                ]
                rid += 1
        # multinomial GLM over the 3 rule columns, with DISTINCT winners:
        # class 0 ('lo') keys on its N1 rule (x1 < 0), class 1 ('mid') on
        # its N2 rule (x1 >= 0), class 2 ('verylo') never — so a grouping
        # regression (e.g. 'lo' absorbing 'verylo' rules) flips argmax.
        # P = 6 cat one-hots + intercept = 7
        beta = [[0.0] * 7 for _ in range(3)]
        beta[0][0] = 3.0     # col M0T0C0 level 0 (its N1 var)
        beta[1][3] = 3.0     # col M0T0C1 level 1 (its N2 var)
        glm_info = {
            "family": "multinomial", "link": "multinomial",
            "beta": [b for blk in beta for b in blk],
            "cats": 3, "cat_offsets": [0, 2, 4, 6], "nums": 0,
            "use_all_factor_levels": True, "mean_imputation": False,
        }
        sub = _mojo_zip("glm", ["M0T0C0", "M0T0C1", "M0T0C2", "y"],
                        [doms["M0T0C0"], doms["M0T0C1"], doms["M0T0C2"],
                         classes], glm_info, n_classes=3)
        parent_info = {
            "linear_model": "glm-1", "model_type": 2,
            "depth": 1, "ntrees": 1, "data_from_rules_codes_len": 0,
            "linear_names_len": 3, "linear_names_0": "M0T0C0",
            "linear_names_1": "M0T0C1", "linear_names_2": "M0T0C2",
            "submodel_count": 1, "submodel_key_0": "glm-1",
            "submodel_dir_0": "models/m1/",
        }
        parent = _mojo_zip("rulefit", ["x1", "y"],
                           [None, classes], parent_info,
                           extra_ini="\n".join(rule_lines) + "\n",
                           n_classes=3)
        return _load(_splice_submodel(parent, sub, "models/m1/"))

    def test_class_grouping_not_confused_by_suffix_overlap(self):
        m = self._fixture()
        P = m.score(np.array([[-1.0], [1.0]]))
        assert P.shape == (2, 3)
        assert np.allclose(P.sum(1), 1.0)
        # exact softmax: the keyed class gets logit 3, the others 0
        e3 = np.exp(3.0)
        hot = e3 / (e3 + 2.0)
        cold = 1.0 / (e3 + 2.0)
        np.testing.assert_allclose(P[0], [hot, cold, cold], rtol=1e-6)
        np.testing.assert_allclose(P[1], [cold, hot, cold], rtol=1e-6)


class TestTargetEncoderInteractions:
    def test_interaction_column_encoding(self):
        """TE over a 2-column interaction: category = searchsorted of the
        mixed-radix code in the stored interaction domain
        (TargetEncoderMojoModel.interactionValue)."""
        te = "feature_engineering/target_encoding/"
        # domains: a in {p,q} (card 2), b in {u,v} (card 2); interaction
        # codes: a + 3*b (multiplier card+1); training saw (p,u)=0,
        # (q,u)=1, (p,v)=3 -> interaction domain [0, 1, 3]
        texts = {
            te + "encoding_map.ini":
                "[a_b]\n0 = 2.0 4.0\n1 = 1.0 2.0\n2 = 3.0 4.0\n",
            te + "te_column_name_to_missing_values_presence.ini":
                "a_b = 0\n",
            te + "input_encoding_columns_map.ini":
                "[from]\na\nb\n[to]\na_b\n[to_domain]\n0\n1\n3\n",
            te + "input_output_columns_map.ini":
                "[from]\na\nb\n[to]\na_b_te\n",
        }
        zb = _mojo_zip("targetencoder", ["a", "b", "y"],
                       [["p", "q"], ["u", "v"], ["no", "yes"]],
                       {"with_blending": False, "non_predictors": "y",
                        "keep_original_categorical_columns": True},
                       texts=texts, n_classes=2)
        m = _load(zb)
        from h2o3_tpu.frame.frame import Frame
        fr = Frame.from_arrays({
            "a": np.array(["p", "q", "p", "q"], object),
            "b": np.array(["u", "u", "v", "v"], object)})
        out = m.transform(fr).vec("a_b_te").to_numpy()[:4]
        prior = (2.0 + 1.0 + 3.0) / (4.0 + 2.0 + 4.0)
        assert out[0] == pytest.approx(2.0 / 4.0)   # code 0 -> cat 0
        assert out[1] == pytest.approx(1.0 / 2.0)   # code 1 -> cat 1
        assert out[2] == pytest.approx(3.0 / 4.0)   # code 3 -> cat 2
        assert out[3] == pytest.approx(prior)       # code 4 unseen -> prior


class TestDeepLearningMaxout:
    def test_maxout_weight_layout(self):
        """Maxout k=2: wValues[maxK*(row*inSize+col)+k], bias[maxK*row+k],
        output = max over k (NeuralNetwork.formNNInputsMaxOut)."""
        in_size, out_size, k = 2, 2, 2
        rng = np.random.default_rng(8)
        w0 = rng.normal(size=out_size * in_size * k).round(3)
        b0 = rng.normal(size=out_size * k).round(3)
        w1 = rng.normal(size=out_size).round(3)
        b1 = rng.normal(size=1).round(3)
        info = {
            "mojo_version": "1.10", "mini_batch_size": 1,
            "nums": 2, "cats": 0, "cat_offsets": [0],
            "norm_mul": [1.0, 1.0], "norm_sub": [0.0, 0.0],
            "use_all_factor_levels": True, "activation": "Maxout",
            "distribution": "gaussian", "mean_imputation": False,
            "neural_network_sizes": [2, 2, 1],
            "hidden_dropout_ratios": [0.0, 0.0],
            "weight_layer0": w0, "bias_layer0": b0,
            "weight_layer1": w1, "bias_layer1": b1,
            "_genmodel_encoding": "AUTO",
        }
        m = _load(_mojo_zip("deeplearning", ["x1", "x2", "y"],
                            [None, None, None], info))
        x = np.array([[0.7, -1.2]])
        # independent scalar computation of the Java layout
        h = []
        for r in range(out_size):
            zs = []
            for kk in range(k):
                z = sum(np.float32(w0[k * (r * in_size + c) + kk]) * x[0, c]
                        for c in range(in_size)) + b0[k * r + kk]
                zs.append(z)
            h.append(max(zs))
        exp = sum(np.float32(w1[c]) * h[c] for c in range(out_size)) + b1[0]
        assert m.score(x)[0] == pytest.approx(float(exp), rel=1e-5)
