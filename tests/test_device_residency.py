"""Advmath prims must not download row-scale data (VERDICT r3 weak #4).

The comm-audit trick applied to Rapids: intercept every device→host hop
(``jax.device_get`` and ``parallel.distributed.fetch``) during prim
evaluation on a 200k-row frame sharded over the 8-device virtual cloud and
assert the largest transfer is result-sized, not frame-sized.
"""

import numpy as np
import pytest

import jax

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.rapids import advprims as ap

N = 200_000
ROW_SCALE = N // 4          # anything this big counts as a frame download


@pytest.fixture(scope="module")
def big(module_rng=None):
    rng = np.random.default_rng(42)
    fr = Frame.from_arrays({
        "a": rng.normal(size=N).astype(np.float32),
        "b": rng.normal(size=N).astype(np.float32),
        "c": (rng.normal(size=N) + 0.5 * rng.normal(size=N)).astype(np.float32),
        "g": rng.integers(0, 50, N).astype(np.float32),
    })
    assert len(fr.vec("a").data.addressable_shards) == 8   # really sharded
    return fr


class _HopMeter:
    def __init__(self):
        self.max_elems = 0

    def record(self, out):
        for leaf in jax.tree_util.tree_leaves(out):
            if hasattr(leaf, "size"):
                self.max_elems = max(self.max_elems, int(leaf.size))


@pytest.fixture
def meter(monkeypatch):
    m = _HopMeter()
    real_get = jax.device_get

    def spy_get(x):
        m.record(x)
        return real_get(x)

    from h2o3_tpu.parallel import distributed
    real_fetch = distributed.fetch

    def spy_fetch(x):
        m.record(x)
        return real_fetch(x)

    monkeypatch.setattr(jax, "device_get", spy_get)
    monkeypatch.setattr(distributed, "fetch", spy_fetch)
    monkeypatch.setattr(ap, "fetch", spy_fetch)
    return m


def test_cor_device_resident(big, meter):
    out = ap.cor(big[["a", "b", "c"]], method="Pearson")
    C = np.stack([out.vec(c).to_numpy() for c in out.names], 1)
    assert C.shape == (3, 3)
    np.testing.assert_allclose(np.diag(C), 1.0, atol=1e-5)
    # ground truth on host
    X = np.stack([np.asarray(jax.device_get(big.vec(c).data))[:N]
                  for c in ("a", "b", "c")], 1)
    np.testing.assert_allclose(C, np.corrcoef(X, rowvar=False),
                               rtol=0, atol=2e-4)


def test_cor_no_frame_download(big, meter):
    ap.cor(big[["a", "b", "c"]], method="Pearson")
    assert meter.max_elems <= 16, \
        f"cor transferred {meter.max_elems} elements to the host"
    meter.max_elems = 0
    ap.cor(big[["a", "b", "c"]], method="Spearman")
    assert meter.max_elems <= 16


def test_rank_within_group_device_resident(big, meter):
    out = ap.rank_within_group_by(big, ["g"], ["a"], new_col="rk")
    # group-id construction hops group-count metadata (~n_groups elements);
    # column VALUES must stay on device
    assert meter.max_elems <= 4096, \
        f"rank transferred {meter.max_elems} elements during eval"
    rk = out.vec("rk")
    assert rk.data is not None                      # device column
    # correctness vs pandas-style groupby rank on host
    g = np.asarray(jax.device_get(big.vec("g").data))[:N]
    a = np.asarray(jax.device_get(big.vec("a").data))[:N]
    got = rk.to_numpy()[:N]
    for grp in (0, 7, 49):
        sel = g == grp
        order = np.argsort(a[sel], kind="stable")
        want = np.empty(sel.sum())
        want[order] = np.arange(1, sel.sum() + 1)
        np.testing.assert_array_equal(got[sel], want)


def test_dedup_and_fill_transfer_bounds(big, meter):
    # dedup on the 50-level group column: transfers the pick list (~plen
    # ints, one per row is the padded index vector) but must not pull
    # column VALUES; bound = index vector + result columns
    small = big[["g"]]
    out = ap.drop_duplicates(small, by=["g"], keep="first")
    assert out.nrows == 50
    # fillna: all compute on device; no host hop at all during eval
    meter.max_elems = 0
    filled = ap.fillna(big, "forward", maxlen=2)
    assert meter.max_elems == 0, \
        f"fillna transferred {meter.max_elems} elements"
    assert filled.vec("a").data is not None


def test_fillna_semantics_device():
    fr = Frame.from_arrays({
        "x": np.float32([np.nan, 1, np.nan, np.nan, np.nan, 5]),
        "k": np.float32([9, np.nan, np.nan, 2, np.nan, np.nan]),
    })
    f1 = ap.fillna(fr, "forward", maxlen=2)
    np.testing.assert_array_equal(
        f1.vec("x").to_numpy(), np.float32([np.nan, 1, 1, 1, np.nan, 5]))
    np.testing.assert_array_equal(
        f1.vec("k").to_numpy(), np.float32([9, 9, 9, 2, 2, 2]))
    f2 = ap.fillna(fr, "backward", maxlen=1)
    np.testing.assert_array_equal(
        f2.vec("x").to_numpy(), np.float32([1, 1, np.nan, np.nan, 5, 5]))


def test_perfect_auc_large_no_overflow(big):
    """npos*nneg > 2^31 must not wrap (code-review finding: int32 counts)."""
    rng = np.random.default_rng(0)
    from h2o3_tpu.frame.vec import Vec
    p = Vec.from_numpy(rng.random(N).astype(np.float32))
    y = Vec.from_numpy((rng.random(N) < 0.5).astype(np.float32))
    auc = ap.perfect_auc(p, y)
    assert 0.45 < auc < 0.55, auc
