# Drive the REAL h2o-r package (the reference's 99 kLoC R client) against a
# running h2o3_tpu server — the R-side analog of tests/scripts/h2o_py_flow.py.
#
# Usage: Rscript h2o_r_flow.R <server_url> <train_csv> <h2o_r_package_dir>
#
# Exit codes: 0 = flow green; 42 = R dependencies (RCurl/jsonlite) or the
# package install are unavailable on this host (callers treat as SKIP);
# anything else = real failure.
#
# Reference entry points exercised: h2o-r/h2o-package/R/connection.R
# (h2o.connect), frame.R (h2o.importFile/as.data.frame), gbm.R, glm.R,
# models.R (predict/h2o.performance/h2o.auc).

args <- commandArgs(trailingOnly = TRUE)
if (length(args) < 3) {
  cat("need <server_url> <train_csv> <h2o_r_dir>\n"); quit(status = 2)
}
url <- args[[1]]; csv <- args[[2]]; pkg_dir <- args[[3]]

have <- function(p) requireNamespace(p, quietly = TRUE)
if (!have("RCurl") || !have("jsonlite")) {
  cat("SKIP: RCurl/jsonlite not installed\n"); quit(status = 42)
}

# ALWAYS install the reference checkout into a private lib (never trust a
# pre-installed CRAN h2o — this test proves THE reference package works)
lib <- file.path(tempdir(), "h2o_r_lib")
dir.create(lib, showWarnings = FALSE)
rc <- system2("R", c("CMD", "INSTALL", "--no-docs", "--no-multiarch",
                     paste0("--library=", lib), pkg_dir),
              stdout = TRUE, stderr = TRUE)
if (!is.null(attr(rc, "status")) && attr(rc, "status") != 0) {
  cat("SKIP: R CMD INSTALL of h2o-r failed on this host\n")
  cat(tail(rc, 20), sep = "\n"); quit(status = 42)
}
.libPaths(c(lib, .libPaths()))
suppressMessages(library(h2o, lib.loc = lib))

parts <- regmatches(url, regexec("^https?://([^:/]+):([0-9]+)", url))[[1]]
conn <- h2o.connect(ip = parts[[2]], port = as.integer(parts[[3]]))

fr <- h2o.importFile(csv, destination_frame = "r_train")
stopifnot(nrow(fr) > 0)
fr$y <- as.factor(fr$y)

gbm <- h2o.gbm(y = "y", training_frame = fr, ntrees = 5, max_depth = 3,
               seed = 1)
perf <- h2o.performance(gbm, train = TRUE)
auc <- h2o.auc(perf)
cat(sprintf("GBM train AUC: %.4f\n", auc))
stopifnot(is.finite(auc), auc > 0.5)

pred <- h2o.predict(gbm, fr)
stopifnot(nrow(pred) == nrow(fr))

glm <- h2o.glm(y = "y", training_frame = fr, family = "binomial",
               lambda = 1e-4)
gperf <- h2o.performance(glm, train = TRUE)
cat(sprintf("GLM train AUC: %.4f\n", h2o.auc(gperf)))
stopifnot(h2o.auc(gperf) > 0.5)

cat("REAL h2o-r flow: OK\n")
quit(status = 0)
