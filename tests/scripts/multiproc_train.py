"""Worker script for the N-process cloud integration tests.

Run via ``python -m h2o3_tpu.launch --fork N ...`` — each process joins the
cloud, verifies the spanning mesh, trains GBM + GLM on a frame row-sharded
ACROSS the processes, and writes its metrics to ``<outdir>/proc<i>.json``.
The parent test asserts both processes agree and match the single-process
result (the reference contract: the 4-JVM localhost cloud of
``multiNodeUtils.sh`` trains the same model as one JVM).
"""

import json
import os
import sys

import jax
import numpy as np

outdir = sys.argv[1]

nproc = jax.process_count()
assert nproc >= 2, nproc
assert len(jax.devices()) == 8, len(jax.devices())
assert len(jax.local_devices()) == 8 // nproc

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel.distributed import barrier, fetch
from h2o3_tpu.models.gbm import GBM
from h2o3_tpu.models.glm import GLM

rng = np.random.default_rng(9)
n = 400
cols = {f"x{i}": rng.normal(size=n).astype(np.float32) for i in range(4)}
cols["y"] = np.array(["no", "yes"], dtype=object)[
    (rng.random(n) < 1 / (1 + np.exp(-2 * cols["x0"]))).astype(int)]
fr = Frame.from_arrays(cols)

# the frame must really span both processes' devices
devs = {s.device for s in fr.vec("x0").data.addressable_shards}
assert len(devs) == 8 // nproc, devs
assert not fr.vec("x0").data.is_fully_addressable

# munge paths must survive cross-process shards (filter/gather/sort)
tr, te = fr.split_frame(ratios=[0.75], seed=4)
assert tr.nrows + te.nrows == n
srt = fr.sort("x0")
x0s = fetch(srt.vec("x0").data)[:n]
assert (np.diff(x0s) >= 0).all()

gbm = GBM(ntrees=3, max_depth=3, nbins=16, seed=2).train(y="y", training_frame=fr)
glm = GLM(family="binomial", lambda_=1e-3, seed=2).train(y="y", training_frame=fr)

pred = fetch(gbm.predict(fr).vec("pyes").data)[:n]

out = dict(
    process=jax.process_index(),
    gbm_logloss=float(gbm.training_metrics.logloss),
    gbm_auc=float(gbm.training_metrics.auc),
    glm_logloss=float(glm.training_metrics.logloss),
    glm_coef=[float(c) for c in np.asarray(glm.output["coef"])],
    pred_head=[float(p) for p in pred[:16]],
)
os.makedirs(outdir, exist_ok=True)
with open(os.path.join(outdir, f"proc{jax.process_index()}.json"), "w") as f:
    json.dump(out, f)

barrier("done")
print(f"proc {jax.process_index()} OK")
