"""Reference-client compatibility flow: the REAL unmodified h2o-py package
(from /root/reference/h2o-py) speaks to our server.

Covers the connect → import_file (ImportFilesMulti/ParseSetup/Parse/job
poll) → split_frame (Rapids session temps) → estimator.train (ModelBuilders
+ job poll + Models fetch) → predict (V4 Predictions) → model_performance
(ModelMetrics compute) → remove_all (DKV delete) call chain.
"""

import os
import sys
import warnings

warnings.filterwarnings("ignore")
sys.path.insert(0, "/root/reference/h2o-py")

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from h2o3_tpu.api import H2OServer

server = H2OServer(port=0).start()

import h2o
from h2o.estimators import H2OGradientBoostingEstimator

h2o.connect(url=server.url, strict_version_check=False)

csv = sys.argv[1]
rng = np.random.default_rng(3)
with open(csv, "w") as f:
    f.write("x1,x2,y\n" + "\n".join(
        f"{a:.3f},{b:.3f},{'yes' if a - b > 0 else 'no'}"
        for a, b in rng.normal(size=(300, 2))))

fr = h2o.import_file(csv)
assert fr.nrow == 300 and fr.ncol == 3, (fr.nrow, fr.ncol)
assert fr.types == {"x1": "real", "x2": "real", "y": "enum"}, fr.types

tr, te = fr.split_frame(ratios=[0.8], seed=1)
assert tr.nrow + te.nrow == 300

gbm = H2OGradientBoostingEstimator(ntrees=5, max_depth=3)
gbm.train(x=["x1", "x2"], y="y", training_frame=tr, validation_frame=te)

pred = gbm.predict(te)
assert pred.col_names == ["predict", "pno", "pyes"], pred.col_names
assert pred.nrow == te.nrow

perf = gbm.model_performance(te)
assert 0.7 < perf.auc() <= 1.0, perf.auc()

h2o.remove_all()
print("H2O_PY_COMPAT_OK")
# skip h2o-py's atexit session teardown (its ExprNode.__del__ chain assumes
# a live reference cluster shutdown endpoint)
import os
os._exit(0)
