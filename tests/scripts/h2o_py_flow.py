"""Reference-client compatibility flow: the REAL unmodified h2o-py package
(from /root/reference/h2o-py) speaks to our server.

Covers the connect → import_file (ImportFilesMulti/ParseSetup/Parse/job
poll) → split_frame (Rapids session temps) → estimator.train (ModelBuilders
+ job poll + Models fetch) → predict (V4 Predictions) → model_performance
(ModelMetrics compute) → remove_all (DKV delete) call chain.
"""

import os
import sys
import warnings

warnings.filterwarnings("ignore")
sys.path.insert(0, "/root/reference/h2o-py")

if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from h2o3_tpu.api import H2OServer

server = H2OServer(port=0).start()

import h2o
from h2o.estimators import H2OGradientBoostingEstimator

h2o.connect(url=server.url, strict_version_check=False)

csv = sys.argv[1]
rng = np.random.default_rng(3)
with open(csv, "w") as f:
    f.write("x1,x2,y\n" + "\n".join(
        f"{a:.3f},{b:.3f},{'yes' if a - b > 0 else 'no'}"
        for a, b in rng.normal(size=(300, 2))))

fr = h2o.import_file(csv)
assert fr.nrow == 300 and fr.ncol == 3, (fr.nrow, fr.ncol)
assert fr.types == {"x1": "real", "x2": "real", "y": "enum"}, fr.types

tr, te = fr.split_frame(ratios=[0.8], seed=1)
assert tr.nrow + te.nrow == 300

gbm = H2OGradientBoostingEstimator(ntrees=5, max_depth=3)
gbm.train(x=["x1", "x2"], y="y", training_frame=tr, validation_frame=te)

pred = gbm.predict(te)
assert pred.col_names == ["predict", "pno", "pyes"], pred.col_names
assert pred.nrow == te.nrow

perf = gbm.model_performance(te)
assert 0.7 < perf.auc() <= 1.0, perf.auc()
# AUC2 criteria tables + scoring history (VERDICT r2 items 5/6)
assert 0 < perf.F1()[0][1] <= 1.0
assert perf.find_threshold_by_max_metric("f2") >= 0.0
cm = perf.confusion_matrix().to_list()
assert len(cm) == 2 and len(cm[0]) == 2
sh = gbm.scoring_history()
assert sh is not None and len(sh) == 5 and "training_deviance" in sh.columns

# broader estimator surface
from h2o.estimators import (H2OGeneralizedLinearEstimator,
                            H2OKMeansEstimator,
                            H2ORandomForestEstimator)

glm = H2OGeneralizedLinearEstimator(family="binomial", lambda_=1e-3)
glm.train(x=["x1", "x2"], y="y", training_frame=tr)
assert 0.7 < glm.model_performance(te).auc() <= 1.0

drf = H2ORandomForestEstimator(ntrees=8, max_depth=4)
drf.train(x=["x1", "x2"], y="y", training_frame=tr)
assert 0.65 < drf.model_performance(te).auc() <= 1.0

km = H2OKMeansEstimator(k=3, seed=1)
km.train(x=["x1", "x2"], training_frame=tr)

# upload_file: POST /3/PostFile + ParseSetup + Parse on the raw key
up = h2o.upload_file(csv)
assert up.nrow == 300 and up.ncol == 3, (up.nrow, up.ncol)
assert up.types == {"x1": "real", "x2": "real", "y": "enum"}, up.types

# AutoML over the wire: POST /99/AutoMLBuilder + job poll + GET /99/AutoML
# + leaderboard/event-log TwoDimTable → H2OFrame round-trip
from h2o.automl import H2OAutoML, get_leaderboard

aml = H2OAutoML(max_models=3, seed=1, verbosity=None)
aml.train(y="y", training_frame=tr)
assert aml.leader is not None
lb = aml.leaderboard
assert lb.nrow >= 3, lb.nrow
assert lb.col_names[0] == "model_id" and "auc" in lb.col_names, lb.col_names
se_rows = [r for r in lb["model_id"].as_data_frame()["model_id"]
           if "StackedEnsemble" in r]
assert len(se_rows) >= 2, "AutoML must rank its two ensembles"
assert "start_epoch" in aml.training_info
lb_all = get_leaderboard(aml, extra_columns="ALL")   # GET /99/Leaderboards
assert "algo" in lb_all.col_names, lb_all.col_names
apred = aml.predict(te)
assert apred.nrow == te.nrow

# StackedEnsemble over the wire: POST /99/ModelBuilders/stackedensemble
from h2o.estimators import H2OStackedEnsembleEstimator

cv_gbm = H2OGradientBoostingEstimator(
    ntrees=5, max_depth=3, nfolds=3, seed=1,
    keep_cross_validation_predictions=True)
cv_gbm.train(x=["x1", "x2"], y="y", training_frame=tr)
cv_drf = H2ORandomForestEstimator(
    ntrees=5, max_depth=4, nfolds=3, seed=1,
    keep_cross_validation_predictions=True)
cv_drf.train(x=["x1", "x2"], y="y", training_frame=tr)
se = H2OStackedEnsembleEstimator(base_models=[cv_gbm, cv_drf])
se.train(x=["x1", "x2"], y="y", training_frame=tr)
assert se.metalearner() is not None
assert 0.7 < se.model_performance(te).auc() <= 1.0
assert se.predict(te).col_names == ["predict", "pno", "pyes"]

# custom UDF metric/distribution: h2o.upload_custom_metric zips generated
# source, uploads via POST /3/PutKey, and names it "python:key=module.Class"
# (reference water/udf; server execs the module against the shim interfaces)
reg = tr[["x1", "x2"]]
reg["t"] = tr["x1"] * 2 + tr["x2"]
mae_ref = h2o.upload_custom_metric(
    """class CustomMaeFunc:
    def map(self, pred, act, w, o, model):
        return [w * abs(act[0] - pred[0]), w]

    def reduce(self, l, r):
        return [l[0] + r[0], l[1] + r[1]]

    def metric(self, l):
        return l[0] / l[1]
""", class_name="CustomMaeFunc", func_name="mae")
cm_gbm = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=1,
                                      custom_metric_func=mae_ref)
cm_gbm.train(x=["x1", "x2"], y="t", training_frame=reg)
tm = cm_gbm._model_json["output"]["training_metrics"]
assert tm["custom_metric_name"] == "mae", tm.get("custom_metric_name")
assert tm["custom_metric_value"] > 0.0

dist_ref = h2o.upload_custom_distribution(
    """class CustomGaussianFunc:
    def link(self):
        return "identity"

    def init(self, w, o, y):
        return [w * (y - o), w]

    def gradient(self, y, f):
        return y - f

    def gamma(self, w, y, z, f):
        return [w * z, w]
""", class_name="CustomGaussianFunc", func_name="gauss")
cd_gbm = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=1,
                                      distribution="custom",
                                      custom_distribution_func=dist_ref)
cd_gbm.train(x=["x1", "x2"], y="t", training_frame=reg)
# the UDF above IS gaussian, so the custom path must reproduce the builtin
ref_gbm = H2OGradientBoostingEstimator(ntrees=4, max_depth=3, seed=1,
                                       distribution="gaussian")
ref_gbm.train(x=["x1", "x2"], y="t", training_frame=reg)
cd_rmse = cd_gbm.model_performance(reg).rmse()
ref_rmse = ref_gbm.model_performance(reg).rmse()
assert abs(cd_rmse - ref_rmse) < 0.02 * ref_rmse, (cd_rmse, ref_rmse)

# frame round-trips the client relies on
df = te.as_data_frame()
assert list(df.columns) == ["x1", "x2", "y"] and len(df) == te.nrow
fr2 = h2o.get_frame(fr.frame_id)
assert fr2.nrow == 300
assert fr.frame_id in h2o.ls()["key"].tolist()

# MOJO round-trip over the wire (round 4): export this server's artifact,
# re-import it via h2o.import_mojo AND h2o.upload_mojo (the Generic
# builder), and assert identical scoring — then import a REAL H2O-3
# reference MOJO fixture the same way
import tempfile
mojo_dir = tempfile.mkdtemp()
mojo_path = gbm.download_mojo(mojo_dir)
reimported = h2o.import_mojo(mojo_path)
p_orig = gbm.predict(te).as_data_frame()
p_back = reimported.predict(te).as_data_frame()
assert (abs(p_orig["pyes"] - p_back["pyes"]) < 1e-5).all()

uploaded = h2o.upload_mojo(mojo_path)
p_up = uploaded.predict(te).as_data_frame()
assert (abs(p_orig["pyes"] - p_up["pyes"]) < 1e-5).all()

ref_fixture = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "data", "ref_mojo",
    "gbm_variable_importance.zip")
if os.path.exists(ref_fixture):
    legacy = h2o.upload_mojo(ref_fixture)
    pros = h2o.import_file(os.path.join(os.path.dirname(ref_fixture),
                                        "prostate.csv"))
    lp = legacy.predict(pros).as_data_frame()
    assert len(lp) == pros.nrow and "p1" in lp.columns

# round 5: parameter-semantics features through the REAL client — an
# explicit fold column, Skip missing handling, and an imported reference
# XGBoost MOJO (native boosterBytes parser server-side)
fr_fold = fr.cbind(fr.kfold_column(n_folds=3, seed=42))
fr_fold.columns = ["x1", "x2", "y", "fold"]
gbm_fold = H2OGradientBoostingEstimator(ntrees=3, max_depth=3, seed=2,
                                        fold_column="fold")
gbm_fold.train(x=["x1", "x2"], y="y", training_frame=fr_fold)
cvm = gbm_fold.model_performance(xval=True)
assert 0.0 < cvm.auc() <= 1.0

glm_skip = H2OGeneralizedLinearEstimator(
    family="binomial", missing_values_handling="Skip", lambda_=0.0)
glm_skip.train(x=["x1", "x2"], y="y", training_frame=tr)
assert glm_skip.auc() > 0.5

xgb_fixture = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "data", "ref_mojo",
    "xgboost_prostate_age.zip")
if os.path.exists(xgb_fixture):
    xgb_legacy = h2o.upload_mojo(xgb_fixture)
    pros2 = h2o.import_file(os.path.join(
        os.path.dirname(xgb_fixture), "prostate.csv"))
    xp = xgb_legacy.predict(pros2).as_data_frame()
    mse = ((xp["predict"] - pros2.as_data_frame()["AGE"]) ** 2).mean()
    assert abs(mse - 3.3232581458216086) < 1e-3, mse

h2o.remove_all()
print("H2O_PY_COMPAT_OK")
# skip h2o-py's atexit session teardown (its ExprNode.__del__ chain assumes
# a live reference cluster shutdown endpoint)
import os
os._exit(0)
