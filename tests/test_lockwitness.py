"""Runtime lock-order witness (utils/lockwitness.py) — unit tests plus
the static/dynamic cross-validation gate.

The gate is the payoff of the shared identity contract: a witness-armed
subprocess runs a thread-heavy tier-1 subset (the DKV.get-vs-sweep race
hammer, the timeline, the elastic membership suite), the conftest
``pytest_sessionfinish`` hook writes the witnessed acquisition record,
and this suite asserts zero dynamic order inversions AND zero dynamic
edges absent from the static DLK graph — i.e. the runtime behaves, and
``tools/lockorder.py``'s call-graph has not gone stale.
"""

import json
import os
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from h2o3_tpu.utils import lockwitness
from h2o3_tpu.utils.lockwitness import WITNESS


@pytest.fixture(autouse=True)
def _fresh_witness():
    WITNESS.reset()
    yield
    WITNESS.reset()


@pytest.fixture
def armed(monkeypatch):
    monkeypatch.setenv("H2O3TPU_LOCKWITNESS", "1")


# -- factories ---------------------------------------------------------------

def test_unarmed_factories_return_raw_primitives(monkeypatch):
    """Unarmed (the default), the factories hand back plain threading
    primitives — zero wrapper overhead on every production hot path."""
    monkeypatch.delenv("H2O3TPU_LOCKWITNESS", raising=False)
    assert type(lockwitness.lock("t.l")) is type(threading.Lock())
    assert type(lockwitness.rlock("t.r")) is type(threading.RLock())
    assert isinstance(lockwitness.condition("t.c"), threading.Condition)
    assert not lockwitness.armed()


def test_arming_is_read_per_call_not_cached(monkeypatch):
    monkeypatch.delenv("H2O3TPU_LOCKWITNESS", raising=False)
    raw = lockwitness.lock("t.before")
    monkeypatch.setenv("H2O3TPU_LOCKWITNESS", "1")
    wrapped = lockwitness.lock("t.after")
    assert type(raw) is type(threading.Lock())
    assert wrapped.name == "t.after"


# -- recording ---------------------------------------------------------------

def test_armed_records_edges_and_acquisitions(armed):
    a, b = lockwitness.lock("t.a"), lockwitness.lock("t.b")
    with a:
        with b:
            pass
    assert WITNESS.acquisitions() == 2
    assert WITNESS.edges() == {("t.a", "t.b"): 1}
    assert WITNESS.inversions() == []


def test_inversion_detected(armed):
    a, b = lockwitness.lock("t.a"), lockwitness.lock("t.b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert WITNESS.inversions() == [("t.a", "t.b")]


def test_reentrant_rlock_records_no_self_edge(armed):
    r = lockwitness.rlock("t.r")
    with r:
        with r:
            pass
    assert WITNESS.acquisitions() == 2
    assert WITNESS.edges() == {}


def test_out_of_order_release_keeps_remaining_stack(armed):
    a, b = lockwitness.lock("t.a"), lockwitness.lock("t.b")
    c = lockwitness.lock("t.c")
    a.acquire(); b.acquire()
    a.release()            # hand-over-hand: a out from under b
    c.acquire()            # edge must come from b (still held), not a
    b.release(); c.release()
    assert ("t.b", "t.c") in WITNESS.edges()
    assert ("t.a", "t.c") not in WITNESS.edges()


def test_held_by_thread_live_and_cleared(armed):
    lk = lockwitness.lock("t.held")
    ident = threading.get_ident()
    with lk:
        assert WITNESS.held_by_thread()[ident] == ["t.held"]
    assert ident not in WITNESS.held_by_thread()


def test_per_thread_stacks_are_independent(armed):
    """A lock held in another thread orders nothing for this one."""
    a, b = lockwitness.lock("t.a"), lockwitness.lock("t.b")
    holding = threading.Event()
    done = threading.Event()

    def holder():
        with a:
            holding.set()
            done.wait(timeout=5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert holding.wait(timeout=5)
    with b:              # concurrent with the other thread's a — no edge
        pass
    done.set()
    t.join(timeout=5)
    assert WITNESS.edges() == {}


def test_condition_records_identity_and_delegates_wait(armed):
    cv = lockwitness.condition("t.cv")
    ident = threading.get_ident()
    with cv:
        assert WITNESS.held_by_thread()[ident] == ["t.cv"]
        assert cv.wait(timeout=0.01) is False
        # the waiter still logically owns the lock after the wait
        assert WITNESS.held_by_thread()[ident] == ["t.cv"]
        cv.notify_all()
    assert ident not in WITNESS.held_by_thread()


def test_condition_over_existing_raw_lock(armed):
    """The KeyLocks pattern: a raw mutex wrapped by a witnessed condition
    — the condition's name is the one identity for every acquisition."""
    mu = threading.Lock()
    cv = lockwitness.condition("t.keycv", lock=mu)
    outer = lockwitness.lock("t.outer")
    with outer:
        with cv:
            pass
    assert WITNESS.edges() == {("t.outer", "t.keycv"): 1}


# -- reporting / validation --------------------------------------------------

def test_report_shape(armed):
    a, b = lockwitness.lock("t.a"), lockwitness.lock("t.b")
    with a:
        with b:
            pass
    doc = WITNESS.report()
    assert doc["acquisitions"] == 2
    assert doc["edges"] == ["t.a->t.b"]
    assert doc["edge_counts"] == {"t.a->t.b": 1}
    assert doc["inversions"] == []
    json.dumps(doc)  # must be JSON-serialisable as-is


def test_validate_against_static_graph(armed):
    a, b = lockwitness.lock("t.a"), lockwitness.lock("t.b")
    with a:
        with b:
            pass
    ok = WITNESS.validate({("t.a", "t.b")}, {"t.a", "t.b"})
    assert ok == {"missing_from_static": [], "unknown_locks": []}
    bad = WITNESS.validate(set(), set())
    assert bad["missing_from_static"] == ["t.a->t.b"]
    assert bad["unknown_locks"] == ["t.a", "t.b"]


def test_blackbox_threads_member_lists_held_locks(armed):
    from h2o3_tpu.utils import blackbox
    lk = lockwitness.lock("t.bb")
    ident = threading.get_ident()
    with lk:
        rows = json.loads(blackbox._member_threads().decode())
        me = [r for r in rows if r["thread_id"] == ident]
        assert me and me[0]["held_locks"] == ["t.bb"]
    rows = json.loads(blackbox._member_threads().decode())
    assert all(r["held_locks"] == [] for r in rows)


# -- the static/dynamic cross-validation gate --------------------------------

def test_witness_gate_on_tier1_subset(tmp_path):
    """Run a thread-heavy tier-1 subset with the witness armed and assert
    the run is deadlock-disciplined AND the static graph is a superset of
    everything witnessed (ISSUE 18 acceptance)."""
    report = tmp_path / "witness.json"
    tests_dir = Path(__file__).resolve().parent
    env = dict(os.environ)
    env.update({
        "H2O3TPU_LOCKWITNESS": "1",
        "H2O3TPU_LOCKWITNESS_REPORT": str(report),
        "JAX_PLATFORMS": "cpu",
    })
    proc = subprocess.run(
        [sys.executable, "-m", "pytest",
         str(tests_dir / "test_ingest.py")
         + "::test_dkv_get_races_cleaner_sweep",
         str(tests_dir / "test_timeline.py"),
         str(tests_dir / "test_elastic.py"),
         "-q", "-m", "not slow", "-p", "no:cacheprovider"],
        cwd=str(tests_dir.parent), env=env,
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(report.read_text())
    # the subset actually exercised witnessed locks across threads...
    assert doc["acquisitions"] > 100
    assert doc["edges"], "no nested acquisitions witnessed at all"
    # ...with zero dynamic lock-order inversions,
    assert doc["inversions"] == []
    # zero witnessed edges the static analyzer does not know,
    assert doc["missing_from_static"] == []
    # and zero witnessed locks outside the static inventory
    assert doc["unknown_locks"] == []
