"""Flight recorder + black box (ISSUE 17): fixed-memory retained time
series (ring bounds, rollup tiers, query surface), trend health rules
reading the record and stamping the tripping window into their incident,
clean degradation when the recorder is off/empty, and the black-box
post-mortem — wedge detection fires exactly once, orderly shutdown never
fires, the dump unpacks with every member and leaks no secrets
(docs/OBSERVABILITY.md "Flight recorder & post-mortems")."""

import io
import json
import os
import tarfile
import time
import urllib.request

import pytest

from h2o3_tpu.utils import blackbox as bb_mod
from h2o3_tpu.utils import flight as fl_mod
from h2o3_tpu.utils.blackbox import DUMP_MEMBERS, BlackBox
from h2o3_tpu.utils.flight import FLIGHT, FlightRecorder
from h2o3_tpu.utils.health import (DEGRADED, HealthEvaluator, default_rules,
                                   trend_window)
from h2o3_tpu.utils.incidents import IncidentLog

POSTMORTEM_MEMBERS = {"reason.json"} | {name for name, _ in DUMP_MEMBERS}


@pytest.fixture(autouse=True)
def _clean_flight():
    FLIGHT.reset()
    yield
    FLIGHT.stop()
    FLIGHT.reset()


def _trend_rules(*names):
    rules = [r for r in default_rules() if r.name.startswith("trend_")]
    if names:
        rules = [r for r in rules if r.name in names]
    return rules


def _fill(name, values, rec=FLIGHT, labels=None, rollup_at=None):
    for i, v in enumerate(values):
        rec.ingest(name, v, labels=labels, now=float(i))


# -- rings & rollup ----------------------------------------------------------

def test_raw_ring_is_bounded():
    rec = FlightRecorder(interval_s=1.0, raw_len=16, rollup_len=16,
                         rollup_secs=100.0, max_series=8)
    _fill("s", range(100), rec=rec)
    vals = rec.values("s")
    assert vals == [float(v) for v in range(84, 100)]   # last raw_len only
    assert rec.stats()["samples_total"] == 100


def test_rollup_windows_carry_min_max_mean_last():
    rec = FlightRecorder(interval_s=1.0, raw_len=8, rollup_len=16,
                         rollup_secs=4.0, max_series=8)
    _fill("s", [10, 2, 30, 4, 99], rec=rec)     # t=0..4; t=4 closes window
    [view] = rec.query("s")
    assert len(view["rollup"]) == 1
    w = view["rollup"][0]
    assert w["min"] == 2 and w["max"] == 30 and w["count"] == 4
    assert w["mean"] == pytest.approx(11.5) and w["last"] == 4
    # the raw tail still holds everything recent, including the opener
    # of the next pending window
    assert rec.values("s")[-1] == 99.0


def test_rollup_ring_is_bounded():
    rec = FlightRecorder(interval_s=1.0, raw_len=8, rollup_len=4,
                         rollup_secs=1.0, max_series=8)
    _fill("s", range(50), rec=rec)              # every sample closes a window
    [view] = rec.query("s")
    assert len(view["rollup"]) == 4


def test_max_series_overflow_counted_and_dropped():
    rec = FlightRecorder(interval_s=1.0, raw_len=8, rollup_len=8,
                         rollup_secs=30.0, max_series=4)
    for i in range(10):
        rec.ingest(f"s{i}", 1.0, now=0.0)
    st = rec.stats()
    assert st["series"] == 4
    assert st["dropped_series"] == 6
    assert rec.values("s9") == []               # dropped, never grown


# -- query surface -----------------------------------------------------------

def test_query_name_prefix_labels_subset_and_since():
    rec = FlightRecorder(interval_s=1.0, raw_len=16, rollup_len=8,
                         rollup_secs=30.0, max_series=16)
    _fill("app.requests", range(6), rec=rec, labels={"route": "/3/Score"})
    _fill("app.requests", range(6), rec=rec, labels={"route": "/3/Jobs"})
    _fill("app.errors", range(6), rec=rec)
    assert len(rec.query("app.")) == 3          # prefix match
    assert len(rec.query("app.requests")) == 2  # exact match, both labels
    [one] = rec.query("app.requests", labels={"route": "/3/Jobs"})
    assert one["labels"] == {"route": "/3/Jobs"}
    [late] = rec.query("app.errors", since=4.0)
    assert [v for _, v in late["samples"]] == [4.0, 5.0]
    assert rec.query("nope") == []


def test_values_and_window_absent_series_degrade():
    rec = FlightRecorder()
    assert rec.values("missing") == []
    assert rec.window("missing") is None


def test_window_carries_cadence():
    rec = FlightRecorder(interval_s=2.0, raw_len=8, rollup_len=8,
                         rollup_secs=30.0, max_series=8)
    _fill("s", [1, 2, 3], rec=rec)
    win = rec.window("s", last_n=2)
    assert [v for _, v in win["samples"]] == [2.0, 3.0]
    assert win["interval_s"] == 2.0 and win["rollup_secs"] == 30.0


def test_ingest_rejects_non_numeric_and_off(monkeypatch):
    rec = FlightRecorder()
    assert rec.ingest("s", "not-a-number") is False
    assert rec.ingest("s", None) is False
    monkeypatch.setenv("H2O3TPU_FLIGHT_OFF", "1")
    assert rec.ingest("s", 1.0) is False
    assert rec.sample_once() == 0
    assert rec.start() is False
    assert rec.stats()["series"] == 0


# -- sampler -----------------------------------------------------------------

def test_sample_once_snapshots_registry_and_derived():
    rec = FlightRecorder(interval_s=1.0, max_series=2048)
    wrote = rec.sample_once(now=1.0)
    assert wrote > 0
    names = rec.series_names()
    assert "derived.host_rss_bytes" in names    # straight from /proc
    assert any(n.startswith("h2o3_") for n in names)
    assert rec.values("derived.host_rss_bytes")[0] > 0


def test_sampler_thread_ticks_and_interval_resolves_at_start(monkeypatch):
    rec = FlightRecorder()
    assert rec.interval_s == 1.0
    # the ENV001 contract: the knob lands at start(), not construction
    monkeypatch.setenv("H2O3TPU_FLIGHT_INTERVAL_SECS", "0.05")
    assert rec.start() is True
    try:
        assert rec.interval_s == 0.05
        assert rec.start() is False             # idempotent while running
        deadline = time.monotonic() + 5.0
        while rec.ticks() < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert rec.ticks() >= 2
        assert rec.running()
    finally:
        rec.stop()
    assert not rec.running()


def test_interval_floor_prevents_busy_spin(monkeypatch):
    monkeypatch.setenv("H2O3TPU_FLIGHT_INTERVAL_SECS", "0.000001")
    assert FlightRecorder().interval_s == 0.05
    monkeypatch.setenv("H2O3TPU_FLIGHT_INTERVAL_SECS", "banana")
    assert FlightRecorder().interval_s == 1.0


# -- trend rules -------------------------------------------------------------

def test_trend_rules_silent_until_window_full():
    n = trend_window()
    _fill("derived.host_rss_bytes", [1e9 * (1 + 0.2 * i)
                                     for i in range(n - 1)])
    ilog = IncidentLog(capacity=8)
    ev = HealthEvaluator(interval_s=60, rules=_trend_rules(), incidents=ilog)
    v = ev.evaluate()
    assert v["status"] == "healthy" and v["findings"] == []
    assert ilog.opened_total() == 0


def test_rss_growth_trend_opens_one_windowed_incident():
    n = trend_window()
    _fill("derived.host_rss_bytes", [1e9 * (1 + 0.02 * i) for i in range(n)])
    ilog = IncidentLog(capacity=8)
    ev = HealthEvaluator(interval_s=60,
                         rules=_trend_rules("trend_rss_growth"),
                         incidents=ilog)
    v = ev.evaluate()
    assert v["status"] == DEGRADED
    [f] = v["findings"]
    assert f["rule"] == "trend_rss_growth" and f["observed"] > 0.05
    ev.evaluate()                               # steady state: edge holds
    assert ilog.opened_total() == 1
    [summary] = ilog.list(state="open")
    win = ilog.get(summary["id"])["context"]["flight_window"]
    assert win["name"] == "derived.host_rss_bytes"
    assert len(win["samples"]) >= 4             # the curve, not one number


def test_flat_rss_never_trips_trend():
    n = trend_window()
    _fill("derived.host_rss_bytes", [1e9] * n)
    ilog = IncidentLog(capacity=8)
    ev = HealthEvaluator(interval_s=60, rules=_trend_rules(), incidents=ilog)
    assert ev.evaluate()["status"] == "healthy"
    assert ilog.opened_total() == 0


def test_p99_creep_requires_near_slo_tail():
    n = trend_window()
    rules = _trend_rules("trend_p99_creep")
    # rising but far from the SLO: headroom, not danger
    _fill("derived.p99_slo_ratio", [0.1 + 0.02 * i for i in range(n)])
    ev = HealthEvaluator(interval_s=60, rules=rules,
                         incidents=IncidentLog(capacity=8))
    assert ev.evaluate()["findings"] == []
    FLIGHT.reset()
    # rising INTO the SLO: pages before the point rule would
    _fill("derived.p99_slo_ratio", [0.6 + (0.35 / n) * i for i in range(n)])
    ilog = IncidentLog(capacity=8)
    ev = HealthEvaluator(interval_s=60, rules=rules, incidents=ilog)
    v = ev.evaluate()
    assert [f["rule"] for f in v["findings"]] == ["trend_p99_creep"]
    assert ilog.opened_total() == 1


def test_shed_acceleration_second_difference():
    n = trend_window()
    rules = _trend_rules("trend_shed_accel")
    # steady shedding (constant rate): the point rule's business, not ours
    _fill("derived.score_shed_total", [10.0 * i for i in range(n)])
    ev = HealthEvaluator(interval_s=60, rules=rules,
                         incidents=IncidentLog(capacity=8))
    assert ev.evaluate()["findings"] == []
    FLIGHT.reset()
    # accelerating: second half sheds far more than the first
    _fill("derived.score_shed_total",
          [i * i * 4.0 for i in range(n)])
    ev = HealthEvaluator(interval_s=60, rules=rules,
                         incidents=IncidentLog(capacity=8))
    assert [f["rule"] for f in ev.evaluate()["findings"]] == \
        ["trend_shed_accel"]


def test_evaluator_pushes_rule_series_into_recorder():
    ev = HealthEvaluator(interval_s=60, incidents=IncidentLog(capacity=8))
    ev.evaluate()
    names = FLIGHT.series_names()
    assert any(n.startswith("health.rule.") for n in names)


# -- clean degradation (satellite c) -----------------------------------------

def test_incident_before_recorder_has_point_context():
    """An incident opened with an EMPTY recorder still captures the
    point-sample pillars — flight_window is None, nothing crashes."""
    ilog = IncidentLog(capacity=8)
    iid = ilog.open("compute_recompile_storm", "compute", DEGRADED,
                    "storm", 5.0, 2.0, series=[1, 2, 5])
    inc = ilog.get(iid)
    assert inc["context"]["flight_window"] is None
    assert inc["context"]["series"] == [1, 2, 5]
    assert "traces" in inc["context"]


def test_incident_with_flight_off_degrades(monkeypatch):
    n = trend_window()
    _fill("derived.host_rss_bytes", [1e9 * (1 + 0.02 * i) for i in range(n)])
    monkeypatch.setenv("H2O3TPU_FLIGHT_OFF", "1")
    # trend probes read nothing (values() path still works on retained
    # data, but a fresh process would hold none) and incident capture
    # must stay point-sample clean either way
    ilog = IncidentLog(capacity=8)
    iid = ilog.open("serving_shed_rate", "serving", DEGRADED,
                    "overload", 0.4, 0.05)
    assert ilog.get(iid)["context"] is not None


def test_trend_probes_not_applicable_with_recorder_off(monkeypatch):
    monkeypatch.setenv("H2O3TPU_FLIGHT_OFF", "1")
    FLIGHT.reset()
    ilog = IncidentLog(capacity=8)
    ev = HealthEvaluator(interval_s=60, rules=_trend_rules(), incidents=ilog)
    v = ev.evaluate()
    assert v["status"] == "healthy" and v["findings"] == []
    assert ilog.opened_total() == 0


# -- black box: heartbeats & watchdog ----------------------------------------

def test_wedge_detection_scales_with_period(monkeypatch):
    monkeypatch.setenv("H2O3TPU_BLACKBOX_STALL_SECS", "0.2")
    bb = BlackBox(dump_dir="/nonexistent-never-written")
    bb.stall_secs = 0.2
    bb.watch("loop", period_s=0.01)
    assert bb.wedged() is None                  # just stamped
    time.sleep(0.3)
    name, silence = bb.wedged()
    assert name == "loop" and silence >= 0.2
    bb.beat("loop")
    assert bb.wedged() is None                  # beat clears it
    bb.unwatch("loop")
    time.sleep(0.05)
    assert bb.wedged() is None                  # unwatched never wedges


def test_beat_to_unwatched_name_is_ignored():
    bb = BlackBox()
    bb.beat("never-watched")                    # must not KeyError or arm
    assert bb.wedged() is None


def test_watchdog_dumps_exactly_once_on_wedge(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3TPU_BLACKBOX_STALL_SECS", "0.2")
    monkeypatch.setenv("H2O3TPU_BLACKBOX_CHECK_SECS", "0.05")
    bb = BlackBox(dump_dir=str(tmp_path))
    assert bb.arm() is True
    assert bb.arm() is False                    # idempotent
    try:
        bb.watch("wedged_loop", period_s=0.01)  # never beats again
        deadline = time.monotonic() + 5.0
        while not bb.fired() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert bb.fired()
        time.sleep(0.3)                         # wedge persists; no refire
    finally:
        bb.disarm()
    dumps = os.listdir(tmp_path)
    assert len(dumps) == 1
    assert dumps[0].startswith("h2o3_postmortem_")
    with tarfile.open(tmp_path / dumps[0]) as tar:
        members = {m.name.split("/", 1)[1] for m in tar.getmembers()}
        assert members == POSTMORTEM_MEMBERS
        reason = json.loads(tar.extractfile(
            f"h2o3_postmortem/reason.json").read())
    assert reason["reason"] == "wedge:wedged_loop"
    assert reason["watched"]["wedged_loop"]["silence_s"] > 0.2


def test_clean_run_and_orderly_disarm_never_dump(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3TPU_BLACKBOX_STALL_SECS", "0.2")
    monkeypatch.setenv("H2O3TPU_BLACKBOX_CHECK_SECS", "0.05")
    bb = BlackBox(dump_dir=str(tmp_path))
    bb.arm()
    bb.watch("loop", period_s=0.05)
    t_end = time.monotonic() + 0.5
    while time.monotonic() < t_end:
        bb.beat("loop")
        time.sleep(0.02)
    bb.disarm()                                 # ORDERLY shutdown
    time.sleep(0.2)                             # watchdog is gone
    bb._on_exit()                               # atexit after disarm: no-op
    assert not bb.fired()
    assert os.listdir(tmp_path) == []


def test_exit_hook_dumps_only_while_armed(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3TPU_BLACKBOX_STALL_SECS", "30")
    bb = BlackBox(dump_dir=str(tmp_path))
    bb.arm()
    bb._on_exit()                               # exit WITHOUT disarm
    bb.disarm()
    assert bb.fired() and len(os.listdir(tmp_path)) == 1


def test_blackbox_off_never_arms(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3TPU_BLACKBOX_OFF", "1")
    bb = BlackBox(dump_dir=str(tmp_path))
    assert bb.arm() is False
    assert not bb.armed()


# -- black box: the dump -----------------------------------------------------

def _unpack(path):
    with tarfile.open(path) as tar:
        return {m.name.split("/", 1)[1]: tar.extractfile(m).read()
                for m in tar.getmembers()}


def test_dump_members_parse_and_fire_once(tmp_path):
    FLIGHT.ingest("derived.host_rss_bytes", 123.0, now=1.0)
    bb = BlackBox(dump_dir=str(tmp_path))
    path = bb.dump("unit-test", detail={"k": "v"})
    assert path and bb.last_dump() == path
    assert bb.dump("again") is None             # exactly once per instance
    members = _unpack(path)
    assert set(members) == POSTMORTEM_MEMBERS
    reason = json.loads(members["reason.json"])
    assert reason["reason"] == "unit-test" and reason["detail"] == {"k": "v"}
    assert reason["pid"] == os.getpid()
    flight = json.loads(members["flight.json"])
    assert any(s["name"] == "derived.host_rss_bytes"
               for s in flight["series"])
    threads = json.loads(members["threads.json"])
    assert any("MainThread" in t["name"] for t in threads)
    assert threads[0]["stack"]                  # formatted frames present
    json.loads(members["traces.json"])
    json.loads(members["incidents.json"])
    assert isinstance(json.loads(members["actions.json"]), list)
    json.loads(members["config.json"])


def test_dump_redacts_secrets_in_raw_bytes(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3TPU_ADMIN_PASSWORD", "hunter2")
    monkeypatch.setenv("H2O3TPU_LDAP_TOKEN", "s3cr3t-tok")
    bb = BlackBox(dump_dir=str(tmp_path))
    path = bb.dump("secrets-check")
    members = _unpack(path)
    cfg = json.loads(members["config.json"])
    assert cfg["H2O3TPU_ADMIN_PASSWORD"] == "[redacted]"
    raw = b"".join(members.values()) + open(path, "rb").read()
    assert b"hunter2" not in raw and b"s3cr3t-tok" not in raw


def test_dump_member_fault_isolated(tmp_path, monkeypatch):
    def sick():
        raise RuntimeError("registry on fire")
    patched = tuple(("flight.json", sick) if name == "flight.json"
                    else (name, fn) for name, fn in bb_mod.DUMP_MEMBERS)
    monkeypatch.setattr(bb_mod, "DUMP_MEMBERS", patched)
    bb = BlackBox(dump_dir=str(tmp_path))
    members = _unpack(bb.dump("sick-member"))
    assert "flight.json.error" in members
    assert b"registry on fire" in members["flight.json.error"]
    assert "threads.json" in members            # the rest still landed


def test_wedged_sweep_triggers_postmortem_via_fault_injection(
        tmp_path, monkeypatch):
    """The end-to-end wedge story: a FaultInjector stall on the health
    sweep seam starves the heartbeat the sweep loop stamps, and the
    watchdog dumps exactly one post-mortem."""
    from h2o3_tpu.utils.timeline import inject_faults
    monkeypatch.setenv("H2O3TPU_BLACKBOX_STALL_SECS", "0.2")
    monkeypatch.setenv("H2O3TPU_BLACKBOX_CHECK_SECS", "0.05")
    bb = BlackBox(dump_dir=str(tmp_path))
    monkeypatch.setattr(bb_mod, "BLACKBOX", bb)
    ev = HealthEvaluator(interval_s=0.05, rules=[],
                         incidents=IncidentLog(capacity=4))
    bb.arm()
    bb.watch("health_sweep", period_s=0.05)
    try:
        with inject_faults(site_rates={"health.sweep": {
                "stall_rate": 1.0, "stall_ms": 5_000}}):
            ev.start()
            deadline = time.monotonic() + 8.0
            while not bb.fired() and time.monotonic() < deadline:
                time.sleep(0.05)
    finally:
        ev.stop()
        bb.disarm()
    assert bb.fired()
    dumps = [f for f in os.listdir(tmp_path) if f.endswith(".tar.gz")]
    assert len(dumps) == 1
    members = _unpack(tmp_path / dumps[0])
    assert set(members) == POSTMORTEM_MEMBERS
    assert json.loads(members["reason.json"])["reason"] == \
        "wedge:health_sweep"


def test_clean_sweep_never_triggers_postmortem(tmp_path, monkeypatch):
    monkeypatch.setenv("H2O3TPU_BLACKBOX_STALL_SECS", "0.2")
    monkeypatch.setenv("H2O3TPU_BLACKBOX_CHECK_SECS", "0.05")
    bb = BlackBox(dump_dir=str(tmp_path))
    monkeypatch.setattr(bb_mod, "BLACKBOX", bb)
    ev = HealthEvaluator(interval_s=0.05, rules=[],
                         incidents=IncidentLog(capacity=4))
    bb.arm()
    bb.watch("health_sweep", period_s=0.05)
    try:
        ev.start()
        time.sleep(0.6)                         # many sweeps, many beats
    finally:
        ev.stop()
        bb.disarm()
    assert not bb.fired()
    assert os.listdir(tmp_path) == []


# -- REST + clients ----------------------------------------------------------

@pytest.fixture
def server(monkeypatch):
    monkeypatch.setenv("H2O3TPU_HEALTH_INTERVAL_SECS", "0.2")
    monkeypatch.setenv("H2O3TPU_FLIGHT_INTERVAL_SECS", "0.1")
    from h2o3_tpu.api import H2OServer
    s = H2OServer(port=0).start()
    yield s
    s.stop()


def _get_json(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return json.loads(r.read())


def test_server_starts_recorder_and_serves_timeseries(server):
    deadline = time.monotonic() + 5.0
    while FLIGHT.ticks() < 1 and time.monotonic() < deadline:
        time.sleep(0.05)
    out = _get_json(server, "/3/TimeSeries")
    assert out["__meta"]["schema_type"] == "TimeSeriesV3"
    assert out["running"]
    assert out["ticks"] >= 1
    assert any(s["name"] == "derived.host_rss_bytes" for s in out["series"])
    # name filter narrows to one series with samples
    one = _get_json(server, "/3/TimeSeries?name=derived.host_rss_bytes")
    assert len(one["series"]) == 1 and one["series"][0]["samples"]


def test_timeseries_label_and_since_filters(server):
    FLIGHT.ingest("unit.series", 1.0, labels={"k": "a"}, now=1.0)
    FLIGHT.ingest("unit.series", 2.0, labels={"k": "a"}, now=2.0)
    FLIGHT.ingest("unit.series", 9.0, labels={"k": "b"}, now=2.0)
    out = _get_json(server, "/3/TimeSeries?name=unit.series&labels=k%3Da")
    assert len(out["series"]) == 1
    assert [v for _, v in out["series"][0]["samples"]] == [1.0, 2.0]
    out = _get_json(server, "/3/TimeSeries?name=unit.series&since=1.5")
    assert all(t >= 1.5 for s in out["series"] for t, _ in s["samples"])


def test_timeseries_bad_params_are_400(server):
    for path in ("/3/TimeSeries?labels=notapair",
                 "/3/TimeSeries?since=banana"):
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(server.url + path)
        assert exc.value.code == 400


def test_python_client_timeseries_accessor(server):
    from h2o3_tpu.api.client import H2OClient
    client = H2OClient(server.url)
    FLIGHT.ingest("unit.client", 7.0, labels={"k": "a"}, now=3.0)
    out = client.timeseries(name="unit.client", labels={"k": "a"}, since=1.0)
    assert out["__meta"]["schema_type"] == "TimeSeriesV3"
    assert [v for _, v in out["series"][0]["samples"]] == [7.0]


def test_server_stop_stops_recorder_and_disarms_blackbox(monkeypatch):
    monkeypatch.setenv("H2O3TPU_FLIGHT_INTERVAL_SECS", "0.1")
    from h2o3_tpu.api import H2OServer
    from h2o3_tpu.utils.blackbox import BLACKBOX
    s = H2OServer(port=0).start()
    try:
        assert FLIGHT.running()
        assert BLACKBOX.armed()
    finally:
        s.stop()
    assert not FLIGHT.running()
    assert not BLACKBOX.armed()
    assert not BLACKBOX.fired()                 # orderly: no post-mortem


def test_flight_off_server_still_serves(monkeypatch):
    monkeypatch.setenv("H2O3TPU_FLIGHT_OFF", "1")
    from h2o3_tpu.api import H2OServer
    s = H2OServer(port=0).start()
    try:
        assert not FLIGHT.running()
        out = _get_json(s, "/3/TimeSeries")
        assert out["off"] and out["series"] == []
    finally:
        s.stop()
