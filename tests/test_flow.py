"""Flow notebook product depth (VERDICT r4 next #8).

Reference: ``h2o-web/`` Flow — assist cells for grids/AutoML, a frame
inspector with distribution sparklines, and ``.flow`` notebook documents.
No browser ships in this image, so the DOM layer is pinned two ways:
(1) every REST sequence a cell handler issues is replayed here verbatim
against a live server (the contract the JS speaks), and (2) the served
HTML is asserted to carry the cell handlers/converters these flows need.
A real-browser drive of the same journey runs wherever a WebView exists.
"""

import json
import re
import urllib.request

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api import H2OClient, H2OServer
from h2o3_tpu.api.flow import FLOW_HTML
from h2o3_tpu.utils.registry import DKV


@pytest.fixture
def server():
    s = H2OServer(port=0).start()
    yield s
    s.stop()


@pytest.fixture
def frame(rng):
    n = 300
    X = rng.normal(size=(n, 3))
    f = Frame.from_arrays({
        "a": X[:, 0].astype(np.float32), "b": X[:, 1].astype(np.float32),
        "c": rng.choice(["u", "v", "w"], size=n).astype(object),
        "y": np.where(X[:, 0] > 0, "yes", "no").astype(object)},
        key="flow_train")
    DKV.put("flow_train", f)
    return f


def _poll(c, job_key):
    return c._poll(job_key)


class TestFlowRestSequences:
    """The exact endpoint sequences the cell handlers call."""

    def test_frame_inspector_histograms(self, server, frame):
        c = H2OClient(server.url)
        out = c.request("GET", "/3/Frames/flow_train")
        cols = {col["label"]: col for col in out["frames"][0]["columns"]}
        # numeric sparkline: 20 fixed-stride bins summing to the non-NA rows
        bins = cols["a"]["histogram_bins"]
        assert len(bins) == 20
        assert sum(bins) == frame.nrows
        assert cols["a"]["histogram_stride"] > 0
        # categorical: per-level counts over the domain
        cbins = cols["c"]["histogram_bins"]
        assert len(cbins) == 3 and sum(cbins) == frame.nrows

    def test_build_grid_cell_sequence(self, server, frame):
        c = H2OClient(server.url)
        out = c.request("POST", "/99/Grid/gbm", dict(
            training_frame="flow_train", response_column="y",
            hyper_parameters=json.dumps({"max_depth": [2, 3],
                                         "ntrees": [3, 5]})))
        job = _poll(c, out["job"]["key"]["name"])
        assert job["status"] == "DONE"
        grid = c.request("GET", f"/99/Grids/{job['dest']['name']}")
        assert len(grid["model_ids"]) == 4
        # every listed model opens like the getModel cell does
        m0 = grid["model_ids"][0]["name"]
        mj = c.request("GET", f"/3/Models/{m0}")
        assert mj["models"][0]["output"]["training_metrics"]["auc"] > 0.5

    def test_automl_leaderboard_cell_sequence(self, server, frame):
        c = H2OClient(server.url)
        out = c.request("POST", "/99/AutoMLBuilder", dict(
            training_frame="flow_train", response_column="y",
            max_models=2, nfolds=0, project_name="flow_aml"))
        job = _poll(c, out["job"]["key"]["name"])
        assert job["status"] == "DONE"
        lb = c.request("GET", "/99/Leaderboards/flow_aml")
        assert lb["project_name"] == "flow_aml"
        assert len(lb["models"]) >= 2
        t = lb["table"]
        assert t["columns"] and len(t["data"][0]) == len(lb["models"])

    def test_import_train_inspect_predict_journey(self, server, tmp_path,
                                                  rng):
        """The full assist journey the DOM drives: importFiles →
        buildModel → getFrameSummary → predict → summary of preds."""
        n = 200
        x = rng.normal(size=n)
        p = tmp_path / "flow.csv"
        p.write_text("x,y\n" + "\n".join(
            f"{v:.4f},{'t' if v > 0 else 'f'}" for v in x) + "\n")
        c = H2OClient(server.url)
        imp = c.request("POST", "/3/ImportFiles",
                        {"path": str(p), "destination_frame": "flow_j"})
        assert imp["destination_frames"][0] == "flow_j"
        out = c.request("POST", "/3/ModelBuilders/gbm", dict(
            training_frame="flow_j", response_column="y", ntrees=3))
        job = _poll(c, out["job"]["key"]["name"])
        assert job["status"] == "DONE"
        summ = c.request("GET", "/3/Frames/flow_j")
        assert summ["frames"][0]["rows"] == n
        pred = c.request(
            "POST", f"/3/Predictions/models/{job['dest']['name']}"
                    "/frames/flow_j")
        pkey = pred["predictions_frame"]["name"]
        ps = c.request("GET", f"/3/Frames/{pkey}")
        names = [col["label"] for col in ps["frames"][0]["columns"]]
        assert names[0] == "predict"


class TestFlowDom:
    """The served page carries the handlers the sequences above back."""

    def test_served_page_has_all_cell_handlers(self, server):
        with urllib.request.urlopen(server.url + "/flow/index.html") as r:
            html = r.read().decode()
        for handler in ("buildGrid", "getGrid", "runAutoML",
                        "getLeaderboard", "sparkline", "importFlowFile",
                        "convertRefFlowCell", "histogram_bins"):
            assert handler in html, handler
        assert html == FLOW_HTML

    def test_ref_flow_conversion_regexes(self):
        """The converter's regexes (as shipped in the page) match the
        reference .flow command shapes they claim to."""
        pats = {
            "importFiles": r'importFiles\s*\[\s*"([^"]+)"',
            "buildModel": r'buildModel\s+[\'"](\w+)[\'"]\s*,\s*(\{[\s\S]*\})',
            "predict": r'predict\s+model:\s*[\'"]([^\'"]+)[\'"],?\s*'
                       r'frame:\s*[\'"]([^\'"]+)[\'"]',
        }
        # shapes straight out of reference Flow notebooks
        assert re.match(pats["importFiles"],
                        'importFiles [ "../smalldata/airlines.csv" ]')
        m = re.match(pats["buildModel"],
                     "buildModel 'gbm', {\"training_frame\":\"air\","
                     "\"response_column\":\"IsDepDelayed\"}")
        assert m and m.group(1) == "gbm"
        m = re.match(pats["predict"],
                     'predict model: "gbm-1", frame: "air"')
        assert m and m.group(2) == "air"
        # and the page embeds each one (JS-escaped)
        for key in ("importFiles\\s*\\[", "buildModel\\s+",
                    "predict\\s+model:"):
            assert key in FLOW_HTML, key
