"""Dispatch-count regression tests (ISSUE 7): the iterative hot paths pay
exactly ONE blocking host fetch per K-step megastep / per GBM chunk — a
future reintroduction of a per-iteration ``device_get`` fails here fast.

Counting strategy: ``jax.device_get`` is monkeypatched with a counting
wrapper for the duration of each fit (every blocking batched fetch in the
drivers goes through it), and the builders' ``_dispatch_audit`` — the same
record bench embeds as ``extra.dispatch_audit`` and gates on — pins the
loop-level accounting (iterations, host syncs, compiled dispatches).
"""

import numpy as np
import pytest

import jax


@pytest.fixture
def count_device_get(monkeypatch):
    """Count jax.device_get calls; the models modules call through the
    ``jax`` module attribute, so one patch covers every driver."""
    counter = {"n": 0}
    real = jax.device_get

    def counting(*args, **kwargs):
        counter["n"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(jax, "device_get", counting)
    return counter


def _glm_frame(rng, n=512, k=6):
    from h2o3_tpu.frame.frame import Frame
    X = rng.normal(size=(n, k)).astype(np.float32)
    logit = X[:, :3] @ np.array([0.9, -0.6, 0.3], np.float32)
    cols = {f"x{i}": X[:, i] for i in range(k)}
    cols["y"] = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "a", "b")
    cols["y3"] = rng.choice(["p", "q", "r"], size=n)
    cols["t"] = (X[:, 0] * 2 + 0.1 * rng.normal(size=n)).astype(np.float32)
    return Frame.from_arrays(cols), [f"x{i}" for i in range(k)]


def test_glm_irls_one_sync_per_megastep(rng, count_device_get, monkeypatch):
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models.model_base import megastep_k

    monkeypatch.setenv("H2O3TPU_MEGASTEP_K", "4")
    assert megastep_k() == 4
    fr, x = _glm_frame(rng)
    b = GLM(family="binomial", lambda_=1e-4, max_iterations=20)
    before = count_device_get["n"]
    m = b.train(y="y", training_frame=fr, x=x)
    total_gets = count_device_get["n"] - before

    audit = b._dispatch_audit["glm_irls"]
    iters = m.output["iterations"]
    assert audit["iterations"] == iters
    # exactly ONE blocking fetch per megastep: ceil(iterations / K)
    assert audit["host_syncs"] == -(-iters // 4)
    assert audit["syncs_per_iteration"] <= 1.0 / 4 + 0.26  # ragged last chunk
    # whole-fit guard: init + IRLS megasteps + post-fit reporting. A
    # reintroduced per-iteration fetch adds ~`iters` gets and fails this.
    assert total_gets < 10 + audit["host_syncs"] + iters / 2, (
        f"{total_gets} device_get calls for {iters} IRLS iterations — "
        "a per-iteration host sync came back")
    # scoring history survives the batched fetch: one deviance per iteration
    assert len(b._iter_devs) == iters


def test_glm_megastep_results_match_per_step_path(rng, monkeypatch):
    """K=8 megasteps vs K=1 (per-step semantics): identical coefficients,
    deviance, and reported iteration counts — the acceptance criterion for
    the device-resident convergence test."""
    from h2o3_tpu.models.glm import GLM

    fr, x = _glm_frame(rng)
    out = {}
    for k in ("1", "8"):
        monkeypatch.setenv("H2O3TPU_MEGASTEP_K", k)
        m = GLM(family="binomial", lambda_=1e-4, max_iterations=25).train(
            y="y", training_frame=fr, x=x)
        out[k] = (m.output["iterations"], m.output["residual_deviance"],
                  np.asarray(m.output["coef"]))
    assert out["1"][0] == out["8"][0]                 # same iteration count
    assert abs(out["1"][1] - out["8"][1]) < 1e-6 * max(abs(out["1"][1]), 1.0)
    np.testing.assert_allclose(out["1"][2], out["8"][2], atol=1e-6)


def test_glm_multinomial_one_sync_per_megastep(rng, count_device_get,
                                               monkeypatch):
    from h2o3_tpu.models.glm import GLM

    monkeypatch.setenv("H2O3TPU_MEGASTEP_K", "4")
    fr, x = _glm_frame(rng)
    b = GLM(family="multinomial", max_iterations=12)
    before = count_device_get["n"]
    m = b.train(y="y3", training_frame=fr, x=x)
    total_gets = count_device_get["n"] - before

    audit = b._dispatch_audit["glm_multinomial"]
    iters = m.output["iterations"]
    assert audit["iterations"] == iters
    assert audit["host_syncs"] == -(-iters // 4)
    assert total_gets < 10 + audit["host_syncs"] + iters / 2


def test_sparse_glm_one_sync_per_megastep(rng, count_device_get, monkeypatch):
    from h2o3_tpu.frame.sparse import SparseFrame, SparseMatrix
    from h2o3_tpu.frame.vec import Vec
    from h2o3_tpu.models.glm import GLM

    monkeypatch.setenv("H2O3TPU_MEGASTEP_K", "4")
    n, k = 256, 40
    rows = np.repeat(np.arange(n), 3).astype(np.int32)
    cols = rng.integers(0, k, size=3 * n).astype(np.int32)
    vals = rng.normal(size=3 * n).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    sf = SparseFrame(SparseMatrix.from_scipy_like(rows, cols, vals, n, k),
                     {"y": Vec.from_numpy(y)})
    b = GLM(family="binomial", lambda_=1e-3, max_iterations=12)
    before = count_device_get["n"]
    m = b.train(y="y", training_frame=sf)
    total_gets = count_device_get["n"] - before

    audit = b._dispatch_audit["glm_sparse_irls"]
    iters = m.output["iterations"]
    assert audit["iterations"] == iters
    assert audit["host_syncs"] == -(-iters // 4)
    assert total_gets < 10 + audit["host_syncs"] + iters / 2


def test_gbm_one_sync_per_chunk(rng, count_device_get):
    from h2o3_tpu.models.gbm import GBM

    fr, x = _glm_frame(rng, n=256)
    b = GBM(ntrees=12, max_depth=3, nbins=16, seed=1, trees_per_dispatch=4)
    before = count_device_get["n"]
    m = b.train(y="y", training_frame=fr, x=x)
    total_gets = count_device_get["n"] - before

    audit = b._dispatch_audit["gbm_round"]
    assert audit["iterations"] == 12                  # boosting rounds
    assert audit["host_syncs"] == 3                   # 12 trees / 4 per chunk
    assert m.output["ntrees"] == 12
    # f0 init + per-chunk heap fetches + metrics; NOT one per round
    assert total_gets < 10 + audit["host_syncs"] + 12 / 2


def test_gbm_auto_chunking_single_dispatch(rng, count_device_get):
    """Default sizing at test scale: the whole ensemble in ONE compiled
    dispatch and one heap fetch."""
    from h2o3_tpu.models.gbm import GBM

    fr, x = _glm_frame(rng, n=256)
    b = GBM(ntrees=10, max_depth=3, nbins=16, seed=1)
    b.train(y="y", training_frame=fr, x=x)
    assert b._dispatch_audit["gbm_round"]["host_syncs"] == 1


def test_gbm_trees_per_dispatch_validated(rng):
    from h2o3_tpu.models.gbm import GBM

    fr, x = _glm_frame(rng, n=128)
    with pytest.raises(ValueError, match="trees_per_dispatch"):
        GBM(ntrees=4, trees_per_dispatch=-1).train(
            y="y", training_frame=fr, x=x)


def test_dl_epochs_no_per_epoch_sync(rng, count_device_get, monkeypatch):
    from h2o3_tpu.models.deeplearning import DeepLearning

    monkeypatch.setenv("H2O3TPU_MEGASTEP_K", "4")
    fr, x = _glm_frame(rng, n=256)
    b = DeepLearning(hidden=[8], epochs=8, mini_batch_size=32, seed=3)
    before = count_device_get["n"]
    m = b.train(y="y", training_frame=fr, x=x)
    total_gets = count_device_get["n"] - before

    audit = b._dispatch_audit["dl_epoch"]
    assert audit["iterations"] == 8                   # epochs
    assert audit["device_dispatches"] == 2            # 8 epochs / K=4
    assert audit["host_syncs"] == 1                   # one post-loop fetch
    assert len(m.output["score_history"]) == 8        # per-epoch losses kept
    # loss series + samples_trained + metrics — never one get per epoch
    assert total_gets < 12


def test_dispatch_gauge_published(rng):
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.utils.telemetry import DISPATCHES_PER_ITER

    fr, x = _glm_frame(rng)
    GLM(family="binomial", lambda_=1e-4, max_iterations=10).train(
        y="y", training_frame=fr, x=x)
    vals = {labels["loop"]: child.value
            for labels, child in DISPATCHES_PER_ITER.children()}
    assert "glm_irls" in vals and 0 < vals["glm_irls"] <= 1.0
