"""Avro/XLSX ingestion + the Cleaner LRU spill.

Reference: h2o-parsers/h2o-avro-parser, water/parser/XlsParser.java,
water/Cleaner.java.
"""

import json
import os
import struct
import zipfile
import zlib

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.utils.registry import DKV


# -- tiny Avro writer (test-only): zigzag varints, one block ---------------

def _zz(n: int) -> bytes:
    n = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _avro_bytes(b: bytes) -> bytes:
    return _zz(len(b)) + b


def _write_avro(path, schema: dict, rows: list[dict], codec=b"null"):
    def encode_val(t, v):
        if isinstance(t, list):              # nullable union
            if v is None:
                return _zz(t.index("null"))
            other = [x for x in t if x != "null"][0]
            return _zz(t.index(other)) + encode_val(other, v)
        if t == "double":
            return struct.pack("<d", v)
        if t == "long":
            return _zz(int(v))
        if t == "string":
            return _avro_bytes(v.encode())
        if t == "boolean":
            return b"\x01" if v else b"\x00"
        raise ValueError(t)

    body = b"".join(
        b"".join(encode_val(f["type"], row[f["name"]])
                 for f in schema["fields"])
        for row in rows)
    if codec == b"deflate":
        comp = zlib.compressobj(9, zlib.DEFLATED, -15)
        body = comp.compress(body) + comp.flush()
    sync = b"S" * 16
    meta = {"avro.schema": json.dumps(schema).encode(), "avro.codec": codec}
    with open(path, "wb") as f:
        f.write(b"Obj\x01")
        f.write(_zz(len(meta)))
        for k, v in meta.items():
            f.write(_avro_bytes(k.encode()) + _avro_bytes(v))
        f.write(_zz(0))
        f.write(sync)
        f.write(_zz(len(rows)) + _zz(len(body)) + body + sync)


@pytest.mark.parametrize("codec", [b"null", b"deflate"])
def test_avro_ingest(tmp_path, codec):
    schema = {"type": "record", "name": "r", "fields": [
        {"name": "num", "type": "double"},
        {"name": "cnt", "type": "long"},
        {"name": "lbl", "type": "string"},
        {"name": "opt", "type": ["null", "double"]},
    ]}
    rows = [{"num": 1.5, "cnt": 7, "lbl": "a", "opt": 2.0},
            {"num": -0.5, "cnt": 9, "lbl": "b", "opt": None}]
    p = tmp_path / f"t_{codec.decode()}.avro"
    _write_avro(str(p), schema, rows, codec)

    from h2o3_tpu.frame.parse import import_file
    fr = import_file(str(p))
    assert fr.nrows == 2
    np.testing.assert_allclose(fr.vec("num").to_numpy(), [1.5, -0.5])
    np.testing.assert_allclose(fr.vec("cnt").to_numpy(), [7, 9])
    assert list(fr.vec("lbl").labels()) == ["a", "b"]
    opt = fr.vec("opt").to_numpy()
    assert opt[0] == 2.0 and np.isnan(opt[1])


def _write_xlsx(path, header, rows):
    def cell(ref, v):
        if isinstance(v, str):
            return f'<c r="{ref}" t="inlineStr"><is><t>{v}</t></is></c>'
        return f'<c r="{ref}"><v>{v}</v></c>'

    def colname(j):
        s = ""
        j += 1
        while j:
            j, r = divmod(j - 1, 26)
            s = chr(65 + r) + s
        return s

    all_rows = [header] + rows
    xml_rows = []
    for i, row in enumerate(all_rows, 1):
        cells = "".join(cell(f"{colname(j)}{i}", v)
                        for j, v in enumerate(row) if v is not None)
        xml_rows.append(f'<row r="{i}">{cells}</row>')
    sheet = ('<?xml version="1.0"?><worksheet xmlns='
             '"http://schemas.openxmlformats.org/spreadsheetml/2006/main">'
             f'<sheetData>{"".join(xml_rows)}</sheetData></worksheet>')
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("xl/worksheets/sheet1.xml", sheet)


def test_xlsx_ingest(tmp_path):
    p = tmp_path / "t.xlsx"
    _write_xlsx(str(p), ["x", "name", "v"],
                [[1.0, "foo", 10.5], [2.0, "bar", None], [3.0, "foo", -1.0]])
    from h2o3_tpu.frame.parse import import_file
    fr = import_file(str(p))
    assert fr.nrows == 3 and fr.ncols == 3
    np.testing.assert_allclose(fr.vec("x").to_numpy(), [1, 2, 3])
    v = fr.vec("v").to_numpy()
    assert v[0] == 10.5 and np.isnan(v[1]) and v[2] == -1.0
    assert list(fr.vec("name").labels()) == ["foo", "bar", "foo"]

    xls = tmp_path / "legacy.xls"
    xls.write_bytes(b"\xd0\xcf\x11\xe0junk")
    with pytest.raises(ValueError, match="xlsx"):
        import_file(str(xls))


def test_cleaner_lru_spill(tmp_path, rng):
    from h2o3_tpu.utils.cleaner import (CLEANER, SwappedFrame, disable_cleaner,
                                        enable_cleaner)

    def mk(key, n=4096):
        f = Frame.from_arrays(
            {f"c{i}": rng.normal(size=n).astype(np.float32)
             for i in range(4)}, key=key)
        DKV.put(key, f)
        return f

    try:
        # budget fits ~2 of the 3 frames (4 cols x 4096 rows x 4B ≈ 66KB)
        enable_cleaner(150_000, ice_root=str(tmp_path))
        a = mk("fr_a")
        b = mk("fr_b")
        want_a = a.vec("c0").to_numpy().copy()
        DKV.get("fr_b")                      # b is now most recent
        mk("fr_c")                           # over budget → LRU (a) spills

        with DKV._lock:
            raw = DKV._store["fr_a"]
        assert isinstance(raw, SwappedFrame)
        assert os.path.exists(raw.path)

        # transparent reload on access, content intact
        back = DKV["fr_a"]
        assert isinstance(back, Frame)
        np.testing.assert_allclose(back.vec("c0").to_numpy(), want_a,
                                   rtol=1e-6)
        # reloading a pushed something else out (still under budget)
        resident = [k for k, _ in CLEANER.resident_frames()]
        total = sum(CLEANER._frame_bytes(f)
                    for _, f in CLEANER.resident_frames())
        assert total <= 150_000, (resident, total)
    finally:
        disable_cleaner()
        DKV.clear()


def test_custom_metric_and_auth(rng):
    """Custom UDF metric (water/udf equivalent) + REST basic auth
    (H2O.java -hash_login equivalent)."""
    from h2o3_tpu.models.gbm import GBM

    n = 300
    x = rng.normal(size=n).astype(np.float32)
    y = (2 * x + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays({"x": x, "y": y})

    def mean_abs_err(preds, yv, w):
        ok = w > 0
        return float(np.abs(preds[ok] - yv[ok]).mean())

    m = GBM(ntrees=5, max_depth=3, seed=1,
            custom_metric_func=mean_abs_err).train(y="y", training_frame=fr)
    assert m.training_metrics.custom_metric_name == "mean_abs_err"
    assert 0 < m.training_metrics.custom_metric_value < 1.0

    # REST auth: wrong/absent credentials → 401; correct → 200
    import urllib.error
    import urllib.request

    from h2o3_tpu.api import H2OServer
    s = H2OServer(port=0, username="alice", password="s3cret").start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{s.url}/3/Cloud")
        assert ei.value.code == 401
        import base64
        tok = base64.b64encode(b"alice:s3cret").decode()
        req = urllib.request.Request(f"{s.url}/3/Cloud",
                                     headers={"Authorization": f"Basic {tok}"})
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
        # shutdown is likewise gated
        req = urllib.request.Request(f"{s.url}/3/Shutdown", data=b"",
                                     method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 401
        # HEAD is gated too (round-2 ADVICE: do_HEAD bypassed auth)
        req = urllib.request.Request(f"{s.url}/3/Cloud", method="HEAD")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 401
        req = urllib.request.Request(
            f"{s.url}/3/Cloud", method="HEAD",
            headers={"Authorization": f"Basic {tok}"})
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
    finally:
        s.stop()
