"""Pallas histogram kernel semantics, validated OFF-TPU via interpret mode
(the kernel itself only dispatches on real TPU — ``pallas_available`` gates
on backend — but its math must be checkable in CI; VERDICT r3 next #3).

Covers the MXU precision modes: "hilo" (2 bf16 passes, default), "hilo3"
(3 passes, f32-exact), "highest" (6-pass reference mode) — all against the
XLA segment-sum ground truth.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from h2o3_tpu.models.tree import _level_histograms
from h2o3_tpu.ops import pallas_hist


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    monkeypatch.setattr(pallas_hist, "_INTERPRET", True)
    pallas_hist.hist_pallas._clear_cache()
    yield
    pallas_hist.hist_pallas._clear_cache()


def _data(rng, R, F, B, N):
    binned = rng.integers(0, B + 1, size=(R, F)).astype(np.int16)
    node = rng.integers(-1, N, size=R).astype(np.int32)
    g = rng.normal(size=R).astype(np.float32)
    h = rng.random(R).astype(np.float32) + 0.1
    w = np.ones(R, np.float32)
    return (jnp.asarray(binned), jnp.asarray(node), jnp.asarray(g),
            jnp.asarray(h), jnp.asarray(w))


@pytest.mark.parametrize("mode,rtol", [("hilo", 5e-4), ("hilo3", 1e-5),
                                       ("highest", 1e-5)])
def test_kernel_matches_segment_sum(monkeypatch, mode, rtol, rng):
    monkeypatch.setattr(pallas_hist, "_MXU_MODE", mode)
    pallas_hist.hist_pallas._clear_cache()
    R, F, B, N = 4096, 7, 16, 8
    binned, node, g, h, w = _data(rng, R, F, B, N)
    want = _level_histograms(binned, node, g, h, w, N, B + 1)
    got = pallas_hist.hist_pallas(binned.T, node, g, h, w, N, B + 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=rtol, atol=rtol * 10)


def test_kernel_256_bins_and_multiblock(monkeypatch, rng):
    """256-bin (XGBoost config) layout and a node count spanning multiple
    node blocks both reduce to the same histograms."""
    monkeypatch.setattr(pallas_hist, "_MXU_MODE", "hilo")
    pallas_hist.hist_pallas._clear_cache()
    R, F, B, N = 2048, 3, 256, 128
    binned, node, g, h, w = _data(rng, R, F, B, N)
    want = _level_histograms(binned, node, g, h, w, N, B + 1)
    got = pallas_hist.hist_pallas(binned.T, node, g, h, w, N, B + 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-4, atol=5e-3)


def test_hilo_split_exactness():
    """hi+lo bf16 digits reconstruct f32 stats to 16-bit mantissa: the
    one-hot side contributes no error, so a single-row 'histogram' must
    reproduce each stat to ~1.5e-5 relative."""
    vals = np.float32([1.0, 1e-3, 123.456, -0.9999, 3.14159e4])
    for v in vals:
        hi = np.float32(jnp.bfloat16(v))
        lo = np.float32(jnp.bfloat16(np.float32(v) - hi))
        assert abs((hi + lo) - v) <= abs(v) * 2 ** -15
