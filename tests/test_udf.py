"""Custom metric / distribution UDFs over the wire (reference: water/udf/,
h2o-py ``h2o.upload_custom_metric`` / ``upload_custom_distribution``,
``h2o-py/h2o/h2o.py:2128,2230``).

The zips built here are byte-for-byte what h2o-py generates (same code
template, same ``import water.udf.CMetricFunc as MetricFunc`` wrapper line),
so passing these proves the real client's upload protocol works unmodified.
"""

import io
import urllib.request
import zipfile

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api import H2OServer
from h2o3_tpu.frame.parse import RawFile
from h2o3_tpu.models.gbm import GBM
from h2o3_tpu.utils import udf
from h2o3_tpu.utils.registry import DKV

# exactly h2o-py's _CFUNC_CODE_TEMPLATE output for a str-form metric
MAE_METRIC_SRC = """# Generated code
import water.udf.CMetricFunc as MetricFunc

class CustomMaeFunc:
    def map(self, pred, act, w, o, model):
        return [w * abs(act[0] - pred[0]), w]

    def reduce(self, l, r):
        return [l[0] + r[0], l[1] + r[1]]

    def metric(self, l):
        return l[0] / l[1]

class CustomMaeFuncWrapper(CustomMaeFunc, MetricFunc, object):
    pass
"""

# a gaussian-equivalent custom distribution: identical math to the builtin,
# so the custom pure_callback path must reproduce builtin results exactly
GAUSS_DIST_SRC = """# Generated code
import water.udf.CDistributionFunc as DistributionFunc

class CustomGaussianFunc:
    def link(self):
        return "identity"

    def init(self, w, o, y):
        return [w * (y - o), w]

    def gradient(self, y, f):
        return y - f

    def gamma(self, w, y, z, f):
        return [w * z, w]

class CustomGaussianFuncWrapper(CustomGaussianFunc, DistributionFunc, object):
    pass
"""


def _zip_bytes(fname: str, src: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        zf.writestr(fname, src)
    return buf.getvalue()


@pytest.fixture
def reg_frame(rng):
    n = 300
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = (X[:, 0] * 2 - X[:, 1] + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays(
        {"a": X[:, 0], "b": X[:, 1], "c": X[:, 2], "y": y}, key="udf_train")
    DKV.put(fr.key, fr)
    return fr


def test_metric_udf_loads_and_matches_numpy(reg_frame):
    DKV.put("mae", RawFile(_zip_bytes("metrics.py", MAE_METRIC_SRC),
                           name="func.jar"))
    m = GBM(ntrees=5, max_depth=3, seed=1,
            custom_metric_func="python:mae=metrics.CustomMaeFuncWrapper"
            ).train(y="y", training_frame=reg_frame)
    mm = m.training_metrics
    assert mm.custom_metric_name == "mae"
    preds = np.asarray(m.predict(reg_frame).vec("predict").data)[:reg_frame.nrows]
    yv = np.asarray(reg_frame.vec("y").data)[:reg_frame.nrows]
    assert mm.custom_metric_value == pytest.approx(
        float(np.abs(yv - preds).mean()), rel=1e-5)


def test_custom_distribution_reproduces_gaussian(reg_frame):
    DKV.put("gauss_dist", RawFile(_zip_bytes("distributions.py",
                                             GAUSS_DIST_SRC), name="func.jar"))
    ref = GBM(ntrees=8, max_depth=3, seed=7).train(y="y",
                                                   training_frame=reg_frame)
    cus = GBM(ntrees=8, max_depth=3, seed=7, distribution="custom",
              custom_distribution_func=(
                  "python:gauss_dist=distributions.CustomGaussianFuncWrapper")
              ).train(y="y", training_frame=reg_frame)
    pr = np.asarray(ref.predict(reg_frame).vec("predict").data)
    pc = np.asarray(cus.predict(reg_frame).vec("predict").data)
    np.testing.assert_allclose(pc, pr, rtol=2e-4, atol=2e-4)
    assert cus.output["custom_link"] == "identity"


def test_custom_distribution_requires_func(reg_frame):
    with pytest.raises(ValueError, match="custom_distribution_func"):
        GBM(ntrees=2, distribution="custom").train(y="y",
                                                   training_frame=reg_frame)


def test_bad_udf_references(reg_frame):
    with pytest.raises(ValueError, match="malformed"):
        udf.load_cfunc("not-a-ref")
    with pytest.raises(KeyError, match="PutKey"):
        udf.load_cfunc("python:absent=m.C")
    DKV.put("notzip", RawFile(b"plain bytes", name="x"))
    with pytest.raises(Exception):
        udf.load_cfunc("python:notzip=m.C")


def _multipart(data: bytes, filename: str) -> tuple[bytes, str]:
    boundary = "babecafe"
    body = (f"--{boundary}\r\nContent-Disposition: form-data; "
            f'name="file"; filename="{filename}"\r\n'
            "Content-Type: application/octet-stream\r\n\r\n"
            ).encode() + data + f"\r\n--{boundary}--\r\n".encode()
    return body, f"multipart/form-data; boundary={boundary}"


def test_putkey_route_and_rest_custom_metric(reg_frame):
    """The full wire loop: upload the UDF zip via POST /3/PutKey (h2o-py
    ``_put_key``), then train over REST with the reference string; the model
    JSON must carry the custom metric (ADVICE r2: schemas must not clobber
    it)."""
    s = H2OServer(port=0).start()
    try:
        body, ctype = _multipart(_zip_bytes("metrics.py", MAE_METRIC_SRC),
                                 "func.jar")
        req = urllib.request.Request(
            s.url + "/3/PutKey?destination_key=rest_mae", data=body,
            headers={"Content-Type": ctype}, method="POST")
        import json
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read())["destination_key"] == "rest_mae"
        assert isinstance(DKV["rest_mae"], RawFile)

        from h2o3_tpu.api import H2OClient
        c = H2OClient(s.url)
        model = c.train(
            "gbm", reg_frame.key, y="y", ntrees=3, max_depth=3, seed=1,
            custom_metric_func="python:rest_mae=metrics.CustomMaeFuncWrapper")
        mm = model["output"]["training_metrics"]
        assert mm["custom_metric_name"] == "rest_mae"
        assert mm["custom_metric_value"] > 0.0
    finally:
        s.stop()


def test_custom_distribution_log_link_scores_in_response_space(rng):
    """A log-link custom distribution's tracked deviance must be computed on
    linkinv(F), not the raw margin (review r3); and training must run with
    stopping enabled (the fused tracker path)."""
    n = 300
    x = rng.normal(size=n).astype(np.float32)
    lam = np.exp(0.5 * x)
    t = rng.poisson(lam).astype(np.float32)
    fr = Frame.from_arrays({"x": x, "t": t}, key="udf_pois")
    DKV.put(fr.key, fr)
    DKV.put("pois_dist", RawFile(_zip_bytes("distributions.py", """\
import water.udf.CDistributionFunc as DistributionFunc
class P:
    def link(self):
        return "log"
    def init(self, w, o, y):
        return [w * y, w]
    def gradient(self, y, f):
        import math
        return y - math.exp(min(f, 30.0))
    def gamma(self, w, y, z, f):
        import math
        return [w * z, w * math.exp(min(f, 30.0))]
class PWrapper(P, DistributionFunc, object):
    pass
"""), name="func.jar"))
    ref = GBM(ntrees=10, max_depth=3, seed=3, distribution="poisson",
              stopping_rounds=2, stopping_metric="deviance"
              ).train(y="t", training_frame=fr)
    cus = GBM(ntrees=10, max_depth=3, seed=3, distribution="custom",
              stopping_rounds=2, stopping_metric="deviance",
              custom_distribution_func="python:pois_dist=distributions.PWrapper"
              ).train(y="t", training_frame=fr)
    pr = np.asarray(ref.predict(fr).vec("predict").data)[:n]
    pc = np.asarray(cus.predict(fr).vec("predict").data)[:n]
    # the UDF IS poisson: predictions must be in response space and close
    assert pc.min() >= 0.0
    np.testing.assert_allclose(pc, pr, rtol=0.15, atol=0.3)


def test_tie_aware_auc_stopping_metric(rng):
    """Fused AUC tracker handles tied scores exactly (reference ScoreKeeper
    half-credit semantics; verdict r2 weak #6)."""
    import jax.numpy as jnp

    from sklearn.metrics import roc_auc_score

    from h2o3_tpu.models.gbm import _metric_device
    p = np.round(rng.random(400), 1).astype(np.float32)   # heavy ties
    y = (rng.random(400) < p).astype(np.float32)
    w = rng.random(400).astype(np.float32)
    got = -float(_metric_device("AUC", "drf_prob", jnp.asarray(p),
                                jnp.asarray(y), jnp.asarray(w), 0))
    want = roc_auc_score(y, p, sample_weight=w)
    assert got == pytest.approx(want, abs=1e-5)


def test_metric_udf_on_validation_frame(reg_frame, rng):
    """The reference computes custom metrics for every scored frame
    (CMetricScoringTask) — validation metrics must carry it too."""
    DKV.put("mae2", RawFile(_zip_bytes("metrics.py", MAE_METRIC_SRC),
                            name="func.jar"))
    n = 100
    vf = Frame.from_arrays(
        {"a": rng.normal(size=n).astype(np.float32),
         "b": rng.normal(size=n).astype(np.float32),
         "c": rng.normal(size=n).astype(np.float32),
         "y": rng.normal(size=n).astype(np.float32)}, key="udf_valid")
    DKV.put(vf.key, vf)
    m = GBM(ntrees=4, max_depth=3, seed=1,
            custom_metric_func="python:mae2=metrics.CustomMaeFuncWrapper"
            ).train(y="y", training_frame=reg_frame, validation_frame=vf)
    vm = m.validation_metrics
    assert vm.custom_metric_name == "mae2"
    preds = np.asarray(m.predict(vf).vec("predict").data)[:n]
    yv = np.asarray(vf.vec("y").data)[:n]
    assert vm.custom_metric_value == pytest.approx(
        float(np.abs(yv - preds).mean()), rel=1e-5)
