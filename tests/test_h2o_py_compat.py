"""The REAL h2o-py client (reference checkout, unmodified) against our server.

VERDICT round-1 'done' criterion for the REST sweep: reference client code
runs against the server unmodified. Subprocess-isolated because h2o-py keeps
a module-global connection.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
H2O_PY = "/root/reference/h2o-py"


@pytest.mark.slow
@pytest.mark.skipif(not os.path.isdir(H2O_PY), reason="reference h2o-py absent")
def test_real_h2o_py_client_flow(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "scripts", "h2o_py_flow.py"),
         str(tmp_path / "hp.csv")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "H2O_PY_COMPAT_OK" in proc.stdout, proc.stdout[-2000:]
