"""IsolationForest / ExtendedIsolationForest tests (reference test model:
h2o-py ``testdir_algos/isoforest/pyunit_*``, ``isoforextended/pyunit_*``)."""

import numpy as np

from h2o3_tpu import Frame
from h2o3_tpu.models import ExtendedIsolationForest, IsolationForest


def _anomaly_data(rng, n=800, n_out=20):
    X = rng.normal(size=(n, 4))
    X[:n_out] += 8.0  # planted outliers
    return Frame.from_arrays({f"x{j}": X[:, j] for j in range(4)}), n_out


def test_isofor_flags_outliers(rng):
    f, n_out = _anomaly_data(rng)
    m = IsolationForest(ntrees=60, seed=7).train(training_frame=f)
    pred = m.predict(f)
    assert pred.names == ["predict", "mean_length"]
    score = pred.vec("predict").to_numpy()
    assert score.min() >= 0.0 and score.max() <= 1.0
    # the planted outliers should dominate the top-scoring rows
    top = np.argsort(-score)[:n_out]
    assert len(set(top) & set(range(n_out))) >= n_out * 3 // 4
    # outliers isolate faster: shorter mean path length
    ml = pred.vec("mean_length").to_numpy()
    assert ml[:n_out].mean() < ml[n_out:].mean()


def test_isofor_sample_size_and_depth(rng):
    f, _ = _anomaly_data(rng, n=300)
    m = IsolationForest(ntrees=10, sample_size=64, max_depth=5, seed=1,
                        ).train(training_frame=f)
    assert m.output["ntrees"] == 10
    assert m.output["max_path_length"] > m.output["min_path_length"]


def test_eif_flags_outliers(rng):
    f, n_out = _anomaly_data(rng)
    m = ExtendedIsolationForest(ntrees=80, extension_level=1, seed=7,
                                ).train(training_frame=f)
    pred = m.predict(f)
    assert pred.names == ["anomaly_score", "mean_length"]
    score = pred.vec("anomaly_score").to_numpy()
    assert (score > 0).all() and (score < 1).all()
    top = np.argsort(-score)[:n_out]
    assert len(set(top) & set(range(n_out))) >= n_out * 3 // 4


def test_eif_extension_level_0_matches_axis_parallel_semantics(rng):
    f, _ = _anomaly_data(rng, n=200)
    m = ExtendedIsolationForest(ntrees=20, extension_level=0, seed=3,
                                ).train(training_frame=f)
    # every split normal has exactly one non-zero coordinate
    normals = np.asarray(m.output["normals"])
    sp = np.asarray(m.output["is_split"])
    nz = (normals != 0).sum(axis=2)
    assert (nz[sp] == 1).all()
