"""Golden tests for the rapids prim closure (reference: ast/prims families;
each prim checked against numpy/pandas/scipy)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.types import VecType
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.rapids import advprims as ap
from h2o3_tpu.rapids.exec import Session, rapids
from h2o3_tpu.utils.registry import DKV


@pytest.fixture
def fr(rng):
    n = 200
    f = Frame.from_arrays({
        "a": rng.normal(size=n).astype(np.float32),
        "b": (2 * rng.normal(size=n) + 1).astype(np.float32),
        "c": rng.choice(["u", "v", "w"], size=n),
    }, key="clos")
    DKV.put("clos", f)
    return f


def test_cor_pearson_spearman(fr):
    out = ap.cor(fr)
    a = fr.vec("a").to_numpy()
    b = fr.vec("b").to_numpy()
    want = np.corrcoef(np.stack([a, b]))[0, 1]
    got = out.vec("b").to_numpy()[0]
    assert got == pytest.approx(want, abs=1e-5)

    from scipy.stats import spearmanr
    s = ap.cor(fr, method="Spearman").vec("b").to_numpy()[0]
    assert s == pytest.approx(spearmanr(a, b).statistic, abs=1e-5)


def test_distance_measures(rng):
    X = Frame.from_arrays({"x": np.float32([0, 3]), "y": np.float32([0, 4])})
    Y = Frame.from_arrays({"x": np.float32([0, 1]), "y": np.float32([0, 0])})
    d = ap.distance(X, Y, "l2")
    np.testing.assert_allclose(d.vec(0).to_numpy(), [0, 5], atol=1e-5)
    np.testing.assert_allclose(d.vec(1).to_numpy(), [1, np.sqrt(4 + 16)],
                               atol=1e-4)
    d1 = ap.distance(X, Y, "l1")
    np.testing.assert_allclose(d1.vec(0).to_numpy(), [0, 7], atol=1e-5)


def test_moments_vs_scipy(rng):
    from scipy.stats import kurtosis as sk_kurt, skew as sk_skew
    a = rng.gamma(2.0, size=500).astype(np.float32)
    v = Vec.from_numpy(a)
    assert ap.kurtosis(v) == pytest.approx(
        sk_kurt(a, fisher=False, bias=True), rel=1e-4)
    assert ap.skewness(v) == pytest.approx(
        sk_skew(a, bias=False), rel=1e-3)


def test_kfold_columns(fr):
    k = ap.kfold_column(fr, 5, seed=1).to_numpy()
    assert set(np.unique(k)) <= set(range(5))
    mk = ap.modulo_kfold_column(fr, 4).to_numpy()
    np.testing.assert_array_equal(mk, np.arange(fr.nrows) % 4)
    sk = ap.stratified_kfold_column(fr.vec("c"), 3, seed=2).to_numpy()
    codes = fr.vec("c").to_numpy()
    for cls in range(3):
        per = np.bincount(sk[codes == cls].astype(int), minlength=3)
        assert per.max() - per.min() <= 1     # balanced within class


def test_stratified_split(fr):
    sp = ap.stratified_split(fr.vec("c"), 0.25, seed=3)
    assert sp.domain == ("train", "test")
    codes = fr.vec("c").to_numpy()
    s = sp.to_numpy()
    for cls in range(3):
        frac = (s[codes == cls] == 1).mean()
        assert 0.15 < frac < 0.35


def test_mode_and_nlevels(fr):
    codes = fr.vec("c").to_numpy()
    want = np.bincount(codes).argmax()
    assert ap.mode(fr.vec("c")) == float(want)
    assert ap.nlevels(fr.vec("c")) == 3.0


def test_drop_duplicates():
    f = Frame.from_arrays({
        "k": np.float32([1, 2, 1, 3, 2]),
        "v": np.float32([10, 20, 30, 40, 50])})
    out = ap.drop_duplicates(f, by=["k"])
    assert out.nrows == 3
    np.testing.assert_array_equal(np.sort(out.vec("v").to_numpy()),
                                  [10, 20, 40])
    last = ap.drop_duplicates(f, by=["k"], keep="last")
    np.testing.assert_array_equal(np.sort(last.vec("v").to_numpy()),
                                  [30, 40, 50])


def test_matrix_ops(rng):
    A = rng.normal(size=(4, 3)).astype(np.float32)
    B = rng.normal(size=(3, 2)).astype(np.float32)
    fa = Frame.from_arrays({f"c{i}": A[:, i] for i in range(3)})
    fb = Frame.from_arrays({f"c{i}": B[:, i] for i in range(2)})
    got = np.stack([ap.mmult(fa, fb).vec(i).to_numpy() for i in range(2)], 1)
    np.testing.assert_allclose(got, A @ B, rtol=1e-5)
    t = ap.transpose(fa)
    got_t = np.stack([t.vec(i).to_numpy() for i in range(4)], 1)
    np.testing.assert_allclose(got_t, A.T, rtol=1e-6)


def test_fillna_forward_limit():
    a = np.float32([1, np.nan, np.nan, np.nan, 5, np.nan])
    f = Frame.from_arrays({"a": a})
    out = ap.fillna(f, "forward", maxlen=2).vec("a").to_numpy()
    np.testing.assert_array_equal(np.isnan(out),
                                  [False, False, False, True, False, False])
    assert out[1] == 1 and out[2] == 1 and out[5] == 5
    back = ap.fillna(f, "backward", maxlen=1).vec("a").to_numpy()
    assert back[3] == 5 and np.isnan(back[2]) and np.isnan(back[5])


def test_na_omit_filter_na_cols():
    f = Frame.from_arrays({
        "a": np.float32([1, np.nan, 3, 4]),
        "b": np.float32([1, 2, 3, 4])})
    assert ap.na_omit(f).nrows == 3
    assert ap.filter_na_cols(f, 0.2) == [1.0]
    assert ap.filter_na_cols(f, 0.5) == [0.0, 1.0]


def test_rank_within_group_by():
    f = Frame.from_arrays({
        "g": np.float32([0, 0, 0, 1, 1]),
        "v": np.float32([3, 1, 2, 9, 5])})
    out = ap.rank_within_group_by(f, ["g"], ["v"])
    np.testing.assert_array_equal(out.vec("rank").to_numpy(),
                                  [3, 1, 2, 2, 1])


def test_relevel_and_domains(fr):
    v = fr.vec("c")
    r = ap.relevel(v, "w")
    assert r.domain[0] == "w"
    np.testing.assert_array_equal(r.labels(), v.labels())  # values unchanged
    rf = ap.relevel_by_freq(v)
    counts = np.bincount(rf.to_numpy(), minlength=3)
    assert (np.diff(counts) <= 0).all()     # domain ordered by freq desc
    sd = ap.set_domain(v, ["x1", "x2", "x3"])
    assert sd.domain == ("x1", "x2", "x3")
    sl = ap.set_level(v, "v")
    assert set(np.unique(sl.to_numpy())) == {1}
    al = ap.append_levels(v, ["z"])
    assert al.domain == ("u", "v", "w", "z")


def test_reducer_na_variants():
    v = Vec.from_numpy(np.float32([1, 2, np.nan]))
    ok = Vec.from_numpy(np.float32([1, 2, 3]))
    assert np.isnan(ap.max_na(v)) and ap.max_na(ok) == 3.0
    assert np.isnan(ap.sum_na(v)) and ap.sum_na(ok) == 6.0
    assert ap.na_cnt(v) == 1.0
    f = Frame.from_arrays({"a": np.float32([1, np.nan])})
    assert ap.any_na(f) is True
    a = np.float32([1, 2, 3, 4, 100])
    assert ap.mad(Vec.from_numpy(a)) == pytest.approx(
        1.4826 * np.median(np.abs(a - np.median(a))))


def test_topn_and_sumaxis(rng):
    a = np.arange(100, dtype=np.float32)
    f = Frame.from_arrays({"a": a, "b": a * 2})
    top = ap.topn(f, "a", 10.0, "top")
    np.testing.assert_array_equal(np.sort(top.vec("a").to_numpy()),
                                  np.arange(90, 100))
    rowsum = ap.sum_axis(f, axis=1).vec("sum").to_numpy()
    np.testing.assert_allclose(rowsum, a * 3, rtol=1e-6)


def test_repeaters():
    np.testing.assert_allclose(ap.seq(1, 7, 2).to_numpy(), [1, 3, 5, 7])
    np.testing.assert_allclose(ap.seq_len(4).to_numpy(), [1, 2, 3, 4])
    v = Vec.from_numpy(np.float32([1, 2]))
    np.testing.assert_allclose(ap.rep_len(v, 5).to_numpy(), [1, 2, 1, 2, 1])


def test_search_prims(fr):
    m = ap.match(fr.vec("c"), ["v", "w"]).to_numpy()
    lab = fr.vec("c").labels()
    want = np.array([{"v": 1, "w": 2}.get(s, np.nan) for s in lab])
    np.testing.assert_array_equal(np.isnan(m), np.isnan(want))
    np.testing.assert_array_equal(m[~np.isnan(m)], want[~np.isnan(want)])

    v = Vec.from_numpy(np.float32([0, 1, 0, 2]))
    np.testing.assert_array_equal(ap.which(v).to_numpy(), [1, 3])

    f = Frame.from_arrays({"a": np.float32([1, 9]), "b": np.float32([5, 2])})
    wm = ap.which_max(f, axis=1).vec("which").to_numpy()
    np.testing.assert_array_equal(wm, [1, 0])


def test_string_prims():
    v = Vec.from_numpy(np.array(["abcabc", "xyz", None], dtype=object),
                       type=VecType.STR)
    cm = ap.count_matches(v, "abc").to_numpy()
    assert cm[0] == 2 and cm[1] == 0 and np.isnan(cm[2])

    a = Vec.from_numpy(np.array(["kitten", "abc"], dtype=object), type=VecType.STR)
    b = Vec.from_numpy(np.array(["sitting", "abc"], dtype=object), type=VecType.STR)
    d = ap.str_distance(a, b, "lv").to_numpy()
    np.testing.assert_array_equal(d, [3, 0])

    docs = Frame.from_arrays({"t": np.array(["a b", "c"], dtype=object)})
    toks = ap.tokenize(docs, r"\s")
    got = [x for x in toks.vec("token").host_values]
    assert got == ["a", "b", None, "c", None]


def test_timeseries_prims(rng):
    v = Vec.from_numpy(np.float32([1, 4, 9, 16]))
    d = ap.difflag1(v).to_numpy()
    assert np.isnan(d[0])
    np.testing.assert_allclose(d[1:], [3, 5, 7])

    X = rng.normal(size=(5, 32)).astype(np.float32)
    f = Frame.from_arrays({f"t{i}": X[:, i] for i in range(32)})
    out = ap.isax(f, num_words=4, max_cardinality=4)
    assert out.nrows == 5 and out.names[0] == "iSax_index"
    codes = np.stack([out.vec(f"c{j}").to_numpy() for j in range(4)], 1)
    assert codes.min() >= 0 and codes.max() <= 3


def test_perfect_auc():
    from sklearn.metrics import roc_auc_score
    rng = np.random.default_rng(0)
    p = rng.random(300).astype(np.float32)
    y = (rng.random(300) < p).astype(np.float32)
    got = ap.perfect_auc(Vec.from_numpy(p), Vec.from_numpy(y))
    assert got == pytest.approx(roc_auc_score(y, p), abs=1e-6)


def test_rapids_ast_dispatch(fr):
    """The new prims resolve through the lisp AST surface too."""
    s = Session()
    assert rapids("(kurtosis (cols clos 'a') 1)", s) > 1.0
    out = rapids("(difflag1 (cols clos 'a'))", s)
    assert out.nrows == fr.nrows
    assert rapids("(naCnt (cols clos 'a'))", s) == 0.0
    sq = rapids("(seq 1 5 2)", s)
    np.testing.assert_allclose(sq.vec(0).to_numpy(), [1, 3, 5])
    t = rapids("(t clos)", s)
    assert t.nrows == 3     # one transposed row per source column
    m = rapids("(% (cols clos 'a') 2)", s)
    assert m.nrows == fr.nrows


def test_apply_and_math_prims(fr):
    out = ap.apply_margin(fr[["a", "b"]], 1, "sum")
    a = fr.vec("a").to_numpy() + fr.vec("b").to_numpy()
    np.testing.assert_allclose(out.vec("sum").to_numpy(), a, rtol=1e-5)

    from h2o3_tpu.rapids import ops
    v = Vec.from_numpy(np.float32([0.5, 1.5]))
    np.testing.assert_allclose(ops.math_op("cospi", v).to_numpy(),
                               np.cos(np.pi * np.float32([0.5, 1.5])),
                               atol=1e-6)
    from scipy.special import polygamma
    np.testing.assert_allclose(ops.math_op("trigamma", v).to_numpy(),
                               polygamma(1, [0.5, 1.5]).astype(np.float32),
                               rtol=1e-4)


def test_alias_and_time_prims(fr):
    s = Session()
    out = rapids("(replaceall (cols clos 'c') 'u' 'X' False)", s)
    assert "X" in set(x for x in out.vec(0).labels() if x)
    ap2 = rapids("(append clos (cols clos 'a') 'a2')", s)
    assert "a2" in ap2.names
    assert rapids("(getTimeZone)", s) == "UTC"
    zones = rapids("(listTimeZones)", s)
    assert "UTC" in zones
    mo = rapids("(moment 2020 2 29 12 0 0 0)", s)
    import pandas as pd
    assert pd.Timestamp(mo.to_pandas()["time"][0]) == pd.Timestamp(
        "2020-02-29T12:00:00")


def test_grouped_permute():
    f = Frame.from_arrays({
        "grp": np.float32([1, 1, 1, 2, 2]),
        "id": np.float32([10, 11, 12, 20, 21]),
        "side": np.array(["D", "D", "C", "D", "C"], dtype=object),
        "amt": np.float32([5, 7, 3, 2, 9])})
    out = ap.grouped_permute(f, "id", ["grp"], "side", "amt")
    assert out.names == ["grp", "In", "Out", "InAmnt", "OutAmnt"]
    rows = {tuple(out.vec(n).to_numpy()[i] for n in out.names)
            for i in range(out.nrows)}
    # group 1: In {10:5, 11:7} x Out {12:3}; group 2: In {20:2} x Out {21:9}
    assert rows == {(1, 10, 12, 5, 3), (1, 11, 12, 7, 3), (2, 20, 21, 2, 9)}
