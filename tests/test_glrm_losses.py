"""GLRM generalized losses + regularizers (reference: hex/glrm/GLRM.java,
GlrmLoss.java, GlrmRegularizer.java)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.decomposition import GLRM, _prox

import jax.numpy as jnp


def _lowrank(rng, n=120, m=8, k=2, noise=0.05):
    A = rng.normal(size=(n, k)).astype(np.float32)
    Y = rng.normal(size=(k, m)).astype(np.float32)
    return A @ Y + noise * rng.normal(size=(n, m)).astype(np.float32)


def test_absolute_huber_losses_fit(rng):
    X = _lowrank(rng)
    # heavy outliers: robust losses should reconstruct the bulk better
    Xc = X.copy()
    Xc[:6, 0] += 50.0
    fr = Frame.from_arrays({f"c{i}": Xc[:, i] for i in range(X.shape[1])})
    for loss in ("Absolute", "Huber"):
        m = GLRM(k=2, loss=loss, max_iterations=300, seed=1).train(
            training_frame=fr)
        R = np.asarray(m.output["x_factor"] @ m.output["archetypes"])[:X.shape[0]]
        resid = np.abs(R[6:] - X[6:, :])
        assert np.median(resid) < 0.5, (loss, np.median(resid))


def test_poisson_loss_fit(rng):
    lam = np.exp(_lowrank(rng, noise=0.0) * 0.5)
    counts = rng.poisson(lam).astype(np.float32)
    fr = Frame.from_arrays({f"c{i}": counts[:, i]
                            for i in range(counts.shape[1])})
    m = GLRM(k=2, loss="Poisson", max_iterations=300, seed=2).train(
        training_frame=fr)
    U = np.asarray(m.output["x_factor"] @ m.output["archetypes"])[:lam.shape[0]]
    # exp(u) estimates lambda: correlation with the true rate
    cor = np.corrcoef(np.exp(U).ravel(), lam.ravel())[0, 1]
    assert cor > 0.6, cor


def test_hinge_logistic_binary(rng):
    U = _lowrank(rng, noise=0.0)
    B = (U > 0).astype(np.float32)
    fr = Frame.from_arrays({f"c{i}": B[:, i] for i in range(B.shape[1])})
    for loss in ("Hinge", "Logistic"):
        m = GLRM(k=2, loss=loss, max_iterations=300, seed=3).train(
            training_frame=fr)
        Uh = np.asarray(m.output["x_factor"] @ m.output["archetypes"])[:B.shape[0]]
        acc = ((Uh > 0) == (B > 0)).mean()
        assert acc > 0.85, (loss, acc)


def test_categorical_multi_loss(rng):
    n = 150
    z = rng.normal(size=(n, 2)).astype(np.float32)
    # two clusters of categorical behavior driven by the latent factor
    lab = np.where(z[:, 0] > 0, "hi", "lo")
    fr = Frame.from_arrays({
        "cat": lab.astype(object),
        "num": (2 * z[:, 0] + 0.1 * rng.normal(size=n)).astype(np.float32)})
    m = GLRM(k=1, multi_loss="Categorical", max_iterations=200, seed=4).train(
        training_frame=fr)
    U = np.asarray(m.output["x_factor"] @ m.output["archetypes"])[:n]
    # block argmax recovers the level (Categorical mimpute)
    pred_level = U[:, :2].argmax(axis=1)
    codes = fr.vec("cat").to_numpy()
    acc = (pred_level == codes).mean()
    assert acc > 0.9, acc


def test_ordinal_multi_loss(rng):
    n = 200
    z = rng.normal(size=n).astype(np.float32)
    lvl = np.digitize(z, [-0.5, 0.5])      # 3 ordered levels
    fr = Frame.from_arrays({
        "o": np.array(["l0", "l1", "l2"], dtype=object)[lvl],
        "num": (z + 0.05 * rng.normal(size=n)).astype(np.float32)})
    m = GLRM(k=1, multi_loss="Ordinal", max_iterations=200, seed=5).train(
        training_frame=fr)
    U = np.asarray(m.output["x_factor"] @ m.output["archetypes"])[:n]
    # Ordinal mimpute: count of thresholds passed
    pred = (U[:, :2] >= 1.0).sum(axis=1)
    codes = fr.vec("o").to_numpy()
    assert abs(np.corrcoef(pred, codes)[0, 1]) > 0.7


def test_loss_by_col_override(rng):
    X = _lowrank(rng)
    cols = {f"c{i}": X[:, i] for i in range(X.shape[1])}
    fr = Frame.from_arrays(cols)
    m = GLRM(k=2, loss="Quadratic", loss_by_col=["Absolute"],
             loss_by_col_idx=[0], max_iterations=100, seed=6).train(
        training_frame=fr)
    assert m.output["objective"] > 0
    with pytest.raises(ValueError, match="unknown loss"):
        GLRM(k=2, loss="Bogus").train(training_frame=fr)


def test_l1_regularizer_sparsifies(rng):
    X = _lowrank(rng)
    fr = Frame.from_arrays({f"c{i}": X[:, i] for i in range(X.shape[1])})
    m = GLRM(k=4, loss="Absolute", regularization_x="L1", gamma_x=2.0,
             max_iterations=200, seed=7).train(training_frame=fr)
    A = np.asarray(m.output["x_factor"])[:X.shape[0]]
    assert (np.abs(A) < 1e-6).mean() > 0.2     # L1 zeroes a chunk of A


def test_prox_operators():
    Z = jnp.asarray(np.float32([[3.0, -1.0, 0.5], [-2.0, 2.0, 0.0]]))
    np.testing.assert_allclose(_prox(Z, "L1", 1.0),
                               [[2.0, 0.0, 0.0], [-1.0, 1.0, 0.0]])
    np.testing.assert_allclose(_prox(Z, "NonNegative", 1.0),
                               [[3.0, 0.0, 0.5], [-0.0, 2.0, 0.0]])
    os_ = np.asarray(_prox(Z, "OneSparse", 1.0))
    assert (os_ > 0).sum(axis=1).tolist() == [1, 1]
    uo = np.asarray(_prox(Z, "UnitOneSparse", 1.0))
    np.testing.assert_allclose(uo.sum(axis=1), [1.0, 1.0])
    sx = np.asarray(_prox(Z, "Simplex", 1.0))
    np.testing.assert_allclose(sx.sum(axis=1), [1.0, 1.0], atol=1e-5)
    assert (sx >= 0).all()
    q = np.asarray(_prox(Z, "Quadratic", 0.5))
    np.testing.assert_allclose(q, np.asarray(Z) / 2.0)


def test_quadratic_exact_path_unchanged(rng):
    X = _lowrank(rng)
    fr = Frame.from_arrays({f"c{i}": X[:, i] for i in range(X.shape[1])})
    m = GLRM(k=2, max_iterations=50, seed=8).train(training_frame=fr)
    R = np.asarray(m.output["x_factor"] @ m.output["archetypes"])[:X.shape[0]]
    assert np.sqrt(np.mean((R - X) ** 2)) < 0.1
