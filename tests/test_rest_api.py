"""REST API tests — server + client round-trips (reference test model:
``h2o-py/tests/testdir_apis/``)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api import H2OClient, H2OServer
from h2o3_tpu.utils.registry import DKV


@pytest.fixture
def server():
    s = H2OServer(port=0).start()   # ephemeral port
    yield s
    s.stop()


@pytest.fixture
def client(server):
    return H2OClient(server.url)


@pytest.fixture
def bin_frame(rng):
    n = 400
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - X[:, 1] > 0)
    f = Frame.from_arrays({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
        "y": np.array(["yes" if t else "no" for t in y], dtype=object)},
        key="train_frame")
    DKV.put("train_frame", f)
    return f


def test_cloud(client):
    st = client.cloud_status()
    assert st["cloud_healthy"] and st["cloud_size"] >= 1
    assert st["__meta"]["schema_type"] == "CloudV3"


def test_import_and_frames(client, rng, tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("x,y\n1,2\n3,4\n5,6\n")
    key = client.import_file(str(p))
    fr = client.frame(key)
    assert fr["rows"] == 3 and fr["column_count"] == 2
    cols = {c["label"]: c for c in fr["columns"]}
    assert cols["x"]["mean"] == pytest.approx(3.0)
    assert any(f["frame_id"]["name"] == key for f in client.frames())
    client.rm(key)
    with pytest.raises(RuntimeError, match="404"):
        client.frame(key)


def test_train_poll_predict(client, bin_frame):
    model = client.train("gbm", "train_frame", y="y", ntrees=5, max_depth=3)
    assert model["algo"] == "gbm"
    auc = model["output"]["training_metrics"]["auc"]
    assert auc > 0.8
    key = model["model_id"]["name"]
    pred_key = client.predict(key, "train_frame")
    pf = DKV[pred_key]
    assert pf.nrows == bin_frame.nrows
    assert "predict" in pf.names


def test_train_glm_params_coerced(client, bin_frame):
    model = client.train("glm", "train_frame", y="y", family="binomial",
                         lambda_=0.0, max_iterations=20)
    assert model["output"]["training_metrics"]["auc"] > 0.9
    pars = {p["name"]: p["actual_value"] for p in model["parameters"]}
    assert pars["family"] == "binomial"
    assert pars["max_iterations"] == 20


def test_rapids_endpoint(client, bin_frame):
    out = client.rapids("(sum (cols train_frame 'a'))")
    ref = float(np.nansum(bin_frame.vec("a").to_numpy()))
    assert out["scalar"] == pytest.approx(ref, rel=1e-4)
    out = client.rapids("(+ (cols train_frame 'a') 1)", id="shifted")
    assert out["key"]["name"] == "shifted"
    assert DKV["shifted"].nrows == bin_frame.nrows


def test_grid_endpoint(client, bin_frame):
    g = client.grid("gbm", "train_frame", "y",
                    hyper_parameters={"max_depth": [2, 3]}, ntrees=3)
    assert len(g["model_ids"]) == 2


def test_unknown_route_and_algo(client, bin_frame):
    with pytest.raises(RuntimeError, match="404"):
        client.request("GET", "/3/NoSuchThing")
    with pytest.raises(RuntimeError, match="unknown algorithm"):
        client.train("levenshtein", "train_frame", y="y")


def test_error_does_not_kill_server(client, bin_frame):
    with pytest.raises(RuntimeError):
        client.train("glm", "train_frame", y="nope")
    # server still alive
    assert client.cloud_status()["cloud_healthy"]


def test_flow_ui_served(server):
    import urllib.request
    with urllib.request.urlopen(server.url + "/") as r:
        body = r.read().decode()
        assert r.headers["Content-Type"].startswith("text/html")
    assert "h2o3-tpu Flow" in body
    assert "/3/Cloud" in body
    with urllib.request.urlopen(server.url + "/flow/index.html") as r:
        assert r.status == 200


def test_init_connect_cluster_shutdown():
    """h2o-py session surface: init() boots a node, cluster() reports,
    connect() attaches, shutdown() tears down."""
    import h2o3_tpu.session as hc
    hc.shutdown()                      # clean slate
    client = hc.init(port=0)
    st = hc.cluster()
    assert st["cloud_size"] >= 1
    c2 = hc.connect(client.url if hasattr(client, "url") else hc._server.url)
    assert c2.cloud_status()["cloud_healthy"]
    hc.shutdown()
    import pytest as _pytest
    with _pytest.raises(RuntimeError):
        hc.cluster()


def test_client_upload_file(client, tmp_path):
    """H2OClient.upload_file ships a client-local csv via POST /3/PostFile
    + Parse (the h2o.upload_file flow; remote-server safe)."""
    p = tmp_path / "up.csv"
    p.write_text("x,y\n1,2\n3,4\n5,6\n")
    key = client.upload_file(str(p), destination_frame="uploaded_fr")
    fr = client.frame(key)
    assert key == "uploaded_fr" and fr["rows"] == 3


def test_flow_notebook_assist_and_plots(server):
    """Round-4 Flow: cell notebook with assist templates, command help,
    and inline SVG chart code (reference h2o-web Flow product surface)."""
    import urllib.request
    with urllib.request.urlopen(server.url + "/") as r:
        body = r.read().decode()
    for marker in ("assist", "runCell", "buildModel", "plot varimp",
                   "svgLine", "svgBar", "getFrameSummary",
                   "NodePersistentStorage/notebook", "shift+enter"):
        assert marker in body, marker


def test_model_payload_variable_importances(client, bin_frame):
    """output.variable_importances TwoDimTable (h2o-py model.varimp())."""
    out = client.train("gbm", "train_frame", y="y", ntrees=3, max_depth=3)
    vi = out["output"].get("variable_importances")
    assert vi is not None
    names = [c["name"] for c in vi["columns"]]
    assert names == ["variable", "relative_importance", "scaled_importance",
                     "percentage"]
    assert vi["rowcount"] >= 1
