"""Explanation module + sklearn adapter tests
(reference: h2o-py explanation/_explain.py, h2o-py/h2o/sklearn)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import GBM, GLM


@pytest.fixture
def binfr(rng):
    n = 400
    X = rng.normal(size=(n, 3)).astype(np.float32)
    cat = rng.choice(["u", "v"], size=n)
    logit = 2.0 * X[:, 0] - X[:, 1] + (cat == "u")
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    return Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
                              "cat": cat, "y": y})


def test_partial_dependence(binfr):
    from h2o3_tpu.explanation import partial_dependence
    m = GBM(ntrees=10, max_depth=3, seed=1).train(y="y", training_frame=binfr)
    tables = partial_dependence(m, binfr, ["x0", "cat"], nbins=8)
    t0 = tables[0]
    assert t0.names == ["x0", "mean_response", "stddev_response",
                        "std_error_mean_response"]
    assert t0.nrows == 8
    resp = t0.vec("mean_response").to_numpy()
    # x0 drives the logit up → PD curve increases end-to-end
    assert resp[-1] > resp[0] + 0.1
    tcat = tables[1]
    assert tcat.nrows == 2     # two category levels


def test_ice(binfr):
    from h2o3_tpu.explanation import ice
    m = GBM(ntrees=5, max_depth=3, seed=1).train(y="y", training_frame=binfr)
    curves = ice(m, binfr, "x0", nbins=5, max_rows=10)
    assert curves.nrows == 50
    assert set(curves.names) == {"row", "x0", "response"}


def test_shap_summary_and_heatmaps(binfr):
    from h2o3_tpu.explanation import (explain, model_correlation, shap_summary,
                                      varimp_heatmap)
    m1 = GBM(ntrees=10, max_depth=3, seed=1).train(y="y", training_frame=binfr)
    m2 = GLM(family="binomial", lambda_=0.0).train(y="y", training_frame=binfr)
    rows = shap_summary(m1, binfr)
    assert rows[0][0] in ("x0", "x1", "cat")   # signal features dominate
    hm = varimp_heatmap([m1, m2])
    assert set(hm["columns"]) == {"x0", "x1", "x2", "cat"}
    assert len(hm["matrix"]) == 2
    mc = model_correlation([m1, m2], binfr)
    C = np.array(mc["matrix"])
    assert C.shape == (2, 2)
    assert C[0, 1] > 0.7       # both models learned the same signal
    bundle = explain([m1, m2], binfr)
    assert "model_correlation" in bundle
    assert m1.key in bundle["models"]
    assert "shap_summary" in bundle["models"][m1.key]


def test_sklearn_classifier(rng):
    from h2o3_tpu.sklearn_adapter import H2OGradientBoostingClassifier
    n = 300
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    clf = H2OGradientBoostingClassifier(ntrees=10, max_depth=3, seed=1)
    assert clf.get_params()["ntrees"] == 10
    clf.fit(X, y)
    acc = clf.score(X, y)
    assert acc > 0.85
    proba = clf.predict_proba(X)
    assert proba.shape == (n, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)
    assert set(clf.predict(X)) <= {"0", "1"}


def test_sklearn_regressor_and_setparams(rng):
    from h2o3_tpu.sklearn_adapter import H2OGeneralizedLinearRegressor
    n = 200
    X = rng.normal(size=(n, 3))
    y = 2 * X[:, 0] - X[:, 2] + rng.normal(scale=0.1, size=n)
    reg = H2OGeneralizedLinearRegressor(lambda_=0.0)
    reg.set_params(max_iterations=20)
    reg.fit(X, y)
    assert reg.score(X, y) > 0.95


def test_permutation_varimp(rng):
    """Reference: AstPermutationVarImp / model.permutation_importance."""
    from h2o3_tpu.explanation import permutation_varimp
    from h2o3_tpu.models.gbm import GBM

    n = 600
    x1 = rng.normal(size=n).astype(np.float32)     # strong signal
    x2 = rng.normal(size=n).astype(np.float32)     # weak signal
    x3 = rng.normal(size=n).astype(np.float32)     # noise
    y = (3 * x1 + 0.5 * x2 + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays({"x1": x1, "x2": x2, "x3": x3, "y": y})
    m = GBM(ntrees=20, max_depth=4, seed=1).train(y="y", training_frame=fr)

    rows = permutation_varimp(m, fr, metric="rmse", seed=2)
    order = [r["variable"] for r in rows]
    assert order[0] == "x1"                        # dominant feature first
    imp = {r["variable"]: r["relative_importance"] for r in rows}
    assert imp["x1"] > imp["x2"] > imp["x3"] - 1e-6
    assert rows[0]["scaled_importance"] == pytest.approx(1.0)
    assert sum(r["percentage"] for r in rows) == pytest.approx(1.0, abs=1e-6)


def test_permutation_varimp_rapids_contract(rng):
    """The AstPermutationVarImp wire shape: (model frame metric n_samples
    n_repeats features seed) → Variable + capitalized columns."""
    from h2o3_tpu.models.gbm import GBM
    from h2o3_tpu.rapids.exec import Session, rapids
    from h2o3_tpu.utils.registry import DKV

    n = 300
    x1 = rng.normal(size=n).astype(np.float32)
    x2 = rng.normal(size=n).astype(np.float32)
    y = (2 * x1 + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays({"x1": x1, "x2": x2, "y": y}, key="pvi_fr")
    DKV.put("pvi_fr", fr)
    m = GBM(ntrees=10, max_depth=3, seed=1, model_id="pvi_m").train(
        y="y", training_frame=fr)
    DKV.put("pvi_m", m)

    s = Session()
    out = rapids("(PermutationVarImp 'pvi_m' pvi_fr 'AUTO' 100 1 [] 5)", s)
    assert out.names[:2] == ["Variable", "Relative Importance"]
    assert "Scaled Importance" in out.names and "Percentage" in out.names
    assert list(out.vec("Variable").host_values[:1]) == ["x1"]

    reps = rapids("(PermutationVarImp 'pvi_m' pvi_fr 'rmse' -1 3 [] 5)", s)
    assert reps.names == ["Variable", "Run 1", "Run 2", "Run 3"]
