"""Models-family rapids prims + RectangleAssign (reference:
``water/rapids/ast/prims/models/``, ``assign/AstRectangleAssign.java``)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import GBM
from h2o3_tpu.models.glm import GLM
from h2o3_tpu.rapids.exec import rapids
from h2o3_tpu.utils.registry import DKV


@pytest.fixture
def binfr(rng):
    n = 300
    X = rng.normal(size=(n, 2)).astype(np.float32)
    sex = rng.choice(["m", "f"], size=n, p=[0.6, 0.4])
    logit = 1.5 * X[:, 0] - X[:, 1] + 0.5 * (sex == "m")
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    fr = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "sex": sex,
                            "y": y}, key="rmfr")
    DKV.put(fr.key, fr)
    return fr


@pytest.fixture
def models(binfr):
    g = GBM(ntrees=5, max_depth=3, seed=1, model_id="rm_gbm").train(
        y="y", training_frame=binfr)
    l = GLM(family="binomial", lambda_=1e-3, model_id="rm_glm").train(
        y="y", training_frame=binfr)
    return g, l


def test_make_leaderboard(binfr, models):
    lb = rapids("(makeLeaderboard ['rm_gbm' 'rm_glm'] rmfr 'auc' [] 'AUTO')")
    assert lb.nrows == 2
    assert "model_id" in lb.names and "auc" in lb.names
    aucs = lb.vec("auc").to_numpy()
    assert aucs[0] >= aucs[1]          # sorted best-first


def test_reset_threshold_changes_predictions(binfr, models):
    g, _ = models
    old = rapids("(model.reset.threshold rm_gbm 0.95)")
    assert old == pytest.approx(0.5)
    m = DKV["rm_gbm"]
    preds = m.predict(binfr)
    p = np.asarray(preds.vec("pyes").to_numpy())
    lab = preds.vec("predict").labels()
    assert all((lbl == "yes") == (pi >= 0.95) for lbl, pi in zip(lab, p))
    rapids("(model.reset.threshold rm_gbm 0.5)")


def test_result_frame_model_selection(binfr, rng):
    n = binfr.nrows
    t = (binfr.vec("x0").to_numpy() * 2 + rng.normal(size=n) * 0.1)
    fr = Frame.from_arrays({"x0": binfr.vec("x0").to_numpy(),
                            "x1": binfr.vec("x1").to_numpy(),
                            "t": t.astype(np.float32)}, key="msfr")
    DKV.put(fr.key, fr)
    from h2o3_tpu.models.model_selection import ModelSelection
    ModelSelection(mode="maxr", max_predictor_number=2,
                   model_id="rm_ms").train(y="t", training_frame=fr)
    res = rapids("(result rm_ms)")
    assert isinstance(res, Frame) and res.nrows >= 1


def test_transform_prim_target_encoder(binfr):
    from h2o3_tpu.models.target_encoder import TargetEncoder
    TargetEncoder(model_id="rm_te").train(
        x=["sex"], y="y", training_frame=binfr)
    out = rapids("(transform rm_te rmfr)")
    assert isinstance(out, Frame)
    assert any("sex" in nm and nm != "sex" for nm in out.names)


def test_fairness_metrics(binfr, models):
    out = rapids("(fairnessMetrics rm_gbm rmfr ['sex'] ['m'] 'yes')")
    assert out.nrows == 2
    assert "air" in out.names and "auc" in out.names
    sexes = list(out.vec("sex").host_values)
    air = out.vec("air").to_numpy()
    assert air[sexes.index("m")] == pytest.approx(1.0)   # reference group
    assert np.isfinite(out.vec("p_value").to_numpy()).all()


def test_java_scoring_parity_prim(binfr, models):
    ok = rapids("(model.testJavaScoring rm_gbm rmfr '' 1e-4)")
    assert ok == 1.0


def test_rectangle_assign_scalar_and_mask(binfr):
    out = rapids("(:= rmfr 99 [0] [0 1 2])")
    assert np.allclose(out.vec("x0").to_numpy()[:3], 99)
    assert out.vec("x0").to_numpy()[3] != 99
    # boolean-mask rows via a predicate expression, all columns of col-set
    out2 = rapids("(:= rmfr 7 [1] (> (cols rmfr [0]) 98))")
    x1 = out2.vec("x1").to_numpy()
    x0 = out2.vec("x0").to_numpy()
    assert np.allclose(x1[x0 > 98], 7)


def test_rectangle_assign_categorical_and_frame_src(binfr):
    out = rapids("(:= rmfr 'f' [2] [0 1])")
    assert list(out.vec("sex").labels()[:2]) == ["f", "f"]
    # frame source, slice height
    src = Frame.from_arrays({"v": np.float32([5.0, 6.0])}, key="rmsrc")
    DKV.put(src.key, src)
    out2 = rapids("(:= rmfr rmsrc [0] [4 5])")
    assert np.allclose(out2.vec("x0").to_numpy()[4:6], [5.0, 6.0])


def test_rename_is_a_dkv_key_rename(rng):
    """AstRename (mungers/AstRename.java:20-46): (rename "old" "new")
    re-keys a DKV object — NOT a column rename (that is colnames=)."""
    import numpy as np
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.rapids import rapids
    from h2o3_tpu.utils.registry import DKV

    fr = Frame.from_arrays({"a": rng.normal(size=8).astype(np.float32)},
                           key="rn_old")
    DKV.put("rn_old", fr)
    rapids('(rename "rn_old" "rn_new")')
    assert "rn_old" not in DKV and "rn_new" in DKV
    assert DKV["rn_new"].names == ["a"]
    assert DKV["rn_new"].key == "rn_new"
    import pytest
    with pytest.raises(KeyError, match="unknown key"):
        rapids('(rename "rn_missing" "x")')
    DKV.remove("rn_new")
