"""parallel/distributed.py single-process fallback paths (ISSUE 12
satellite): fetch on fully-addressable arrays, process identity, idempotent
shutdown, the double-init guard, and the retry-wrapped allgather — the
paths only exercised incidentally by tests/scripts/multiproc_train.py
before. (The real N-process cloud is covered by test_multiprocess.py.)"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from h2o3_tpu.parallel import distributed as dist


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("H2O3TPU_DISPATCH_BACKOFF_MS", "1")


@pytest.fixture
def _reset_init_state(monkeypatch):
    """Simulate the coordinator-init lifecycle without touching the real
    jax.distributed runtime (initializing it would wedge the test
    process waiting for peers)."""
    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setattr(jax.distributed, "shutdown", lambda: None)
    monkeypatch.setattr(dist, "_initialized", False)
    monkeypatch.setattr(dist, "_init_args", None)
    yield calls
    dist._initialized = False
    dist._init_args = None


# -- single-process fallbacks -------------------------------------------------

def test_process_identity_single_process():
    assert dist.process_count() == 1
    assert dist.process_index() == 0
    assert not dist.is_multiprocess()


def test_fetch_fully_addressable_device_array():
    x = jnp.arange(16, dtype=jnp.float32)
    out = dist.fetch(x)
    assert isinstance(out, np.ndarray)
    np.testing.assert_array_equal(out, np.arange(16, dtype=np.float32))


def test_fetch_row_sharded_array_single_process():
    from h2o3_tpu.parallel.mesh import row_sharding
    x = jax.device_put(np.arange(32, dtype=np.float32), row_sharding())
    np.testing.assert_array_equal(dist.fetch(x),
                                  np.arange(32, dtype=np.float32))


def test_fetch_non_jax_values_pass_through():
    np.testing.assert_array_equal(dist.fetch(np.array([1.0, 2.0])),
                                  [1.0, 2.0])
    np.testing.assert_array_equal(dist.fetch([3, 4]), [3, 4])


def test_barrier_is_noop_single_process():
    dist.barrier("test")     # must not require a multihost runtime


def test_shutdown_idempotent():
    # never initialized: both calls are no-ops, no raise
    dist.shutdown_distributed()
    dist.shutdown_distributed()


def test_init_single_process_installs_mesh_only():
    # all-None args: no coordinator, just (re)install the default mesh
    dist.init_distributed()
    from h2o3_tpu.parallel.mesh import global_mesh
    assert global_mesh().shape["rows"] == len(jax.devices())


# -- double-init guard --------------------------------------------------------

def test_reinit_same_coordinator_args_is_idempotent(_reset_init_state):
    calls = _reset_init_state
    dist.init_distributed("10.0.0.1:1234", num_processes=2, process_id=0)
    assert len(calls) == 1 and dist._initialized
    dist.init_distributed("10.0.0.1:1234", num_processes=2, process_id=0)
    assert len(calls) == 1               # no second initialize


def test_reinit_different_coordinator_args_raises(_reset_init_state):
    dist.init_distributed("10.0.0.1:1234", num_processes=2, process_id=0)
    with pytest.raises(RuntimeError, match="different\\s+coordinator"):
        dist.init_distributed("10.0.0.2:9999", num_processes=4,
                              process_id=1)
    # different local device bindings are a different configuration too
    with pytest.raises(RuntimeError, match="different\\s+coordinator"):
        dist.init_distributed("10.0.0.1:1234", num_processes=2,
                              process_id=0, local_device_ids=[2, 3])
    # the live cloud is untouched by the rejected re-init
    assert dist._init_args == ("10.0.0.1:1234", 2, 0, None)
    dist.shutdown_distributed()
    assert not dist._initialized and dist._init_args is None


# -- retry-wrapped allgather --------------------------------------------------

def test_allgather_retries_transient_failures(monkeypatch):
    """fetch()'s cross-host gather runs under the PR 8 dispatch-retry
    budget: a transient failure is absorbed, not surfaced (it was the one
    cross-host dispatch with no retry path)."""
    from jax.experimental import multihost_utils
    attempts = []

    def flaky(arr, tiled=True):
        attempts.append(1)
        if len(attempts) == 1:
            raise RuntimeError("transient DCN hiccup")
        return np.asarray(arr)

    monkeypatch.setattr(multihost_utils, "process_allgather", flaky)
    out = dist._allgather(np.array([1.0, 2.0], np.float32))
    np.testing.assert_array_equal(out, [1.0, 2.0])
    assert len(attempts) == 2            # failed once, retried, succeeded


def test_allgather_exhaustion_raises_structured(monkeypatch):
    from jax.experimental import multihost_utils

    from h2o3_tpu.ops.map_reduce import DispatchFailed

    monkeypatch.setenv("H2O3TPU_DISPATCH_RETRIES", "1")

    def dead(arr, tiled=True):
        raise RuntimeError("link down")

    monkeypatch.setattr(multihost_utils, "process_allgather", dead)
    with pytest.raises(DispatchFailed) as ei:
        dist._allgather(np.array([1.0], np.float32))
    assert ei.value.fn == "allgather"
    assert len(ei.value.history) == 2    # first try + 1 retry


def test_allgather_faults_injectable(monkeypatch):
    """The chaos harness reaches the allgather site like every other
    dispatch site (site name: 'allgather'): injected drops ride the retry
    loop and an all-drops run exhausts into DispatchFailed with the
    FaultInjected attempt history."""
    from jax.experimental import multihost_utils

    from h2o3_tpu.ops.map_reduce import DispatchFailed
    from h2o3_tpu.utils.timeline import inject_faults

    monkeypatch.setenv("H2O3TPU_DISPATCH_RETRIES", "2")
    monkeypatch.setattr(multihost_utils, "process_allgather",
                        lambda arr, tiled=True: np.asarray(arr))
    with inject_faults(site_rates={"allgather": {"drop_rate": 1.0}}) as inj:
        with pytest.raises(DispatchFailed) as ei:
            dist._allgather(np.array([7.0], np.float32))
    assert inj.dropped == 3              # first try + 2 retries, all dropped
    assert all("FaultInjected" in h["error"] for h in ei.value.history)
