"""Chaos harness — fault tolerance end to end (ISSUE 8).

Reference: H2O-3 survives production because its substrate is defensive:
``-random_udp_drop`` (water/H2O.java:446) exercises an RPC retry path, jobs
carry deadlines, and ``hex/faulttolerance/Recovery.java`` snapshots long
jobs so a restart resumes instead of restarting. These tests drive the
TPU-native equivalents: dispatch retry/backoff absorbing injected drops
(results within 1e-6 of the fault-free run — exact, in fact, since retried
dispatches are functional re-runs), job deadlines terminating runaway
builds as CANCELLED with partial results, auto-checkpointed builds resuming
bit-identically, and process-fatal ``crash`` faults proving the resume
paths survive a real kill (subprocess tests, marked slow).
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import GBM
from h2o3_tpu.models.glm import GLM
from h2o3_tpu.models.job import JobCancelled
from h2o3_tpu.ops.map_reduce import DispatchFailed, map_reduce
from h2o3_tpu.utils.timeline import FaultInjector, inject_faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("H2O3TPU_DISPATCH_BACKOFF_MS", "1")


def _binfr(rng, n=500, key=None):
    X = rng.normal(size=(n, 5)).astype(np.float32)
    logit = X[:, 0] * 1.5 - X[:, 1] + 0.3 * X[:, 2]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
    cols = {f"x{i}": X[:, i] for i in range(5)}
    cols["y"] = y
    return Frame.from_arrays(cols, key=key)


def _raw(model, fr):
    import jax
    return np.asarray(jax.device_get(model._score_raw(fr)))


# -- dispatch retry/backoff ---------------------------------------------------

def test_retry_absorbs_drops_and_marks_span(rng):
    import jax.numpy as jnp

    from h2o3_tpu.utils.tracing import TRACER
    x = jnp.asarray(rng.normal(size=32).astype(np.float32))
    with TRACER.span("chaos_root", root=True) as root:
        tid = root.trace_id
        # seed chosen so at least one drop fires before a success
        with inject_faults(drop_rate=0.6, seed=3) as inj:
            out = map_reduce(lambda s: s.sum(), x)
    assert abs(float(out) - float(np.sum(np.asarray(x)))) < 1e-4
    assert inj.dropped >= 1          # faults were injected AND absorbed
    trace = TRACER.get_trace(tid)
    retried = [s for s in trace["spans"] if s["status"] == "retried"]
    assert retried and retried[0]["attrs"]["retries"] == inj.dropped


def test_retry_exhaustion_raises_structured_dispatch_failed(rng):
    import jax.numpy as jnp

    from h2o3_tpu.utils.telemetry import DISPATCH_RETRIES
    exhausted0 = DISPATCH_RETRIES.labels(fn="map_reduce",
                                         outcome="exhausted").value
    with inject_faults(drop_rate=1.0):
        with pytest.raises(DispatchFailed) as ei:
            map_reduce(lambda s: s.sum(),
                       jnp.ones(16, jnp.float32))
    e = ei.value
    assert e.fn == "map_reduce"
    assert len(e.history) == 4       # 1 attempt + default 3 retries
    assert all("FaultInjected" in h["error"] for h in e.history)
    assert all("backoff_ms" in h for h in e.history[:-1])
    assert DISPATCH_RETRIES.labels(fn="map_reduce",
                                   outcome="exhausted").value \
        == exhausted0 + 1


def test_retries_land_on_the_job_and_jobv3(rng):
    from h2o3_tpu.api import schemas
    fr = _binfr(rng)
    b = GBM(ntrees=4, max_depth=2, seed=1)
    with inject_faults(drop_rate=0.5, seed=11) as inj:
        b.train(y="y", training_frame=fr)
    assert inj.dropped >= 1
    assert b.job.retries == inj.dropped
    v3 = schemas.job_v3(b.job.key, b.job)
    assert v3["retries"] == inj.dropped
    assert v3["auto_recoverable"] is False
    assert v3["max_runtime_secs"] == 0.0


def test_exhausted_budget_records_retry_history_on_job(rng):
    fr = _binfr(rng)
    b = GBM(ntrees=4, max_depth=2, seed=1)
    with pytest.raises(DispatchFailed):
        with inject_faults(site_rates={"gbm_chunk": {"drop_rate": 1.0}}):
            b.train(y="y", training_frame=fr)
    assert b.job.status == "FAILED"
    assert b.job.retry_history and len(b.job.retry_history) == 4


# -- chaos gate: builds complete with parity under faults ---------------------

def test_gbm_completes_exactly_under_drop_injection(rng):
    fr = _binfr(rng)
    clean = GBM(ntrees=8, max_depth=3, seed=5,
                trees_per_dispatch=2).train(y="y", training_frame=fr)
    with inject_faults(drop_rate=0.3, seed=29) as inj:
        faulted = GBM(ntrees=8, max_depth=3, seed=5,
                      trees_per_dispatch=2).train(y="y", training_frame=fr)
    assert inj.dropped >= 1
    # retried dispatches are functional re-runs: parity is EXACT (the 1e-6
    # acceptance bound holds with margin zero)
    np.testing.assert_allclose(_raw(clean, fr), _raw(faulted, fr), atol=1e-6)


def test_glm_completes_exactly_under_drop_and_delay(rng):
    fr = _binfr(rng)
    clean = GLM(family="binomial", lambda_=1e-4,
                max_iterations=12).train(y="y", training_frame=fr)
    with inject_faults(drop_rate=0.3, delay_rate=0.3, delay_ms=2,
                       seed=31) as inj:
        faulted = GLM(family="binomial", lambda_=1e-4,
                      max_iterations=12).train(y="y", training_frame=fr)
    assert inj.dropped + inj.delayed >= 1
    np.testing.assert_allclose(_raw(clean, fr), _raw(faulted, fr), atol=1e-6)


def test_automl_completes_under_fault_injection(rng):
    from h2o3_tpu.orchestration import AutoML
    fr = _binfr(rng, n=300)
    # parallelism=2 (un-pinned): overlapped builds now lease DISJOINT mesh
    # slices from the MeshScheduler, so the two builds' collectives
    # rendezvous on separate device sets and can no longer wedge each
    # other (the hazard that used to force parallelism=1 here). Parity
    # stays exact: same-size slices run the same deterministic programs.
    clean = AutoML(max_models=2, nfolds=0, seed=7, parallelism=2)
    clean.train(y="y", training_frame=fr)
    with inject_faults(drop_rate=0.05, delay_rate=0.1, delay_ms=1, seed=13):
        chaotic = AutoML(max_models=2, nfolds=0, seed=7, parallelism=2)
        chaotic.train(y="y", training_frame=fr)
    assert len(chaotic.leaderboard) == len(clean.leaderboard)
    for mc, mf in zip(clean.leaderboard.models,
                      chaotic.leaderboard.models):
        a = float(mc.training_metrics.auc)
        b = float(mf.training_metrics.auc)
        assert abs(a - b) < 1e-6


# -- job deadlines ------------------------------------------------------------

def test_gbm_deadline_cancels_and_keeps_built_trees(rng):
    from h2o3_tpu.utils.telemetry import JOB_DEADLINE_EXCEEDED
    n0 = JOB_DEADLINE_EXCEEDED._default().value
    fr = _binfr(rng)
    b = GBM(ntrees=500, max_depth=3, seed=1, trees_per_dispatch=2,
            max_runtime_secs=0.8)
    m = b.train(y="y", training_frame=fr)
    assert b.job.status == "CANCELLED"
    assert b.job.deadline_exceeded
    assert "max_runtime_secs" in b.job.progress_msg
    assert 0 < m.output["ntrees"] < 500       # partial trees KEPT
    assert m.training_metrics is not None     # finalized despite the cancel
    assert JOB_DEADLINE_EXCEEDED._default().value == n0 + 1


def test_glm_deadline_terminates_as_cancelled(rng):
    fr = _binfr(rng)
    b = GLM(family="binomial", lambda_=1e-4, max_iterations=5000,
            max_runtime_secs=1e-4)
    with pytest.raises(JobCancelled, match="max_runtime_secs"):
        b.train(y="y", training_frame=fr)
    assert b.job.status == "CANCELLED"
    assert b.job.deadline_exceeded


def test_drf_deadline_cancels_before_forest_launch(rng):
    """DRF grows its whole forest in ONE fused program: the deadline is
    checked at the dispatch boundary, so an expired budget cancels before
    the program launches (docs/RELIABILITY.md)."""
    from h2o3_tpu.models.gbm import DRF
    fr = _binfr(rng)
    b = DRF(ntrees=50, max_depth=3, seed=1, max_runtime_secs=1e-4)
    with pytest.raises(JobCancelled, match="max_runtime_secs"):
        b.train(y="y", training_frame=fr)
    assert b.job.status == "CANCELLED"
    assert b.job.deadline_exceeded


def test_dart_deadline_keeps_built_trees(rng):
    """DART rounds run as a host loop, so it keeps grown trees on deadline
    like the other tree builders (partial model, job CANCELLED)."""
    from h2o3_tpu.models.xgboost import XGBoost
    fr = _binfr(rng)
    b = XGBoost(booster="dart", ntrees=4000, max_depth=3, seed=1,
                rate_drop=0.2, max_runtime_secs=1.0)
    m = b.train(y="y", training_frame=fr)
    assert b.job.status == "CANCELLED"
    assert b.job.deadline_exceeded
    assert 0 < m.output["ntrees"] < 4000      # partial trees KEPT
    assert m.training_metrics is not None


def test_deadline_surfaces_in_job_v3(rng):
    from h2o3_tpu.api import schemas
    fr = _binfr(rng)
    b = GBM(ntrees=500, max_depth=3, seed=1, trees_per_dispatch=2,
            max_runtime_secs=0.8)
    b.train(y="y", training_frame=fr)
    v3 = schemas.job_v3(b.job.key, b.job)
    assert v3["status"] == "CANCELLED"
    assert v3["deadline_exceeded"] is True
    assert v3["max_runtime_secs"] == 0.8


# -- auto-checkpointed builds -------------------------------------------------

def test_gbm_auto_checkpoint_resumes_bit_identical(rng, tmp_path,
                                                   monkeypatch):
    monkeypatch.setenv("H2O3TPU_CHECKPOINT_EVERY", "4")
    fr = _binfr(rng)
    rdir = str(tmp_path / "rec")
    clean = GBM(ntrees=12, max_depth=3, seed=1,
                trees_per_dispatch=4).train(y="y", training_frame=fr)
    # interruption: the SECOND chunk's dispatch exhausts its retry budget
    # (drop_rate=1.0 armed after one success) — the build dies after the
    # first snapshot landed, like a crash between checkpoints
    with pytest.raises(DispatchFailed):
        with inject_faults(site_rates={"gbm_chunk": {"drop_rate": 1.0,
                                                     "after": 1}}):
            GBM(ntrees=12, max_depth=3, seed=1, trees_per_dispatch=4,
                auto_recovery_dir=rdir).train(y="y", training_frame=fr)
    assert os.path.exists(os.path.join(rdir, "model_snapshot.bin"))
    resumed = GBM(ntrees=12, max_depth=3, seed=1, trees_per_dispatch=4,
                  auto_recovery_dir=rdir).train(y="y", training_frame=fr)
    assert resumed.output["ntrees"] == 12
    # per-tree PRNG replay + sequential margin fold: BIT-identical trees
    for i, (tc, tr) in enumerate(zip(clean.output["trees"],
                                     resumed.output["trees"])):
        for ch in ("feat", "thresh_bin", "thresh_val", "na_left",
                   "is_split", "leaf"):
            assert np.array_equal(np.asarray(getattr(tc, ch)),
                                  np.asarray(getattr(tr, ch))), (i, ch)
    # success retires the snapshot: the next run trains fresh
    assert not os.path.exists(os.path.join(rdir, "model_snapshot.bin"))


def test_deadline_cancelled_build_leaves_resumable_snapshot(rng, tmp_path,
                                                            monkeypatch):
    monkeypatch.setenv("H2O3TPU_CHECKPOINT_EVERY", "2")
    fr = _binfr(rng)
    rdir = str(tmp_path / "rec")
    b = GBM(ntrees=500, max_depth=3, seed=1, trees_per_dispatch=2,
            max_runtime_secs=0.8, auto_recovery_dir=rdir)
    m = b.train(y="y", training_frame=fr)
    assert b.job.status == "CANCELLED"
    # CANCELLED keeps the snapshot (only DONE retires it) and the job
    # advertises recoverability
    assert os.path.exists(os.path.join(rdir, "model_snapshot.bin"))
    from h2o3_tpu.api import schemas
    v3 = schemas.job_v3(b.job.key, b.job)
    assert v3["auto_recoverable"] is True
    assert v3["auto_recovery_dir"] == rdir
    with open(os.path.join(rdir, "build_recovery.json")) as fh:
        state = json.load(fh)
    assert state["progress"] >= m.output["ntrees"] - 1
    assert state["target"] == 500


def test_snapshot_with_different_params_is_not_resumed(rng, tmp_path,
                                                       monkeypatch):
    monkeypatch.setenv("H2O3TPU_CHECKPOINT_EVERY", "2")
    fr = _binfr(rng)
    rdir = str(tmp_path / "rec")
    with pytest.raises(DispatchFailed):
        with inject_faults(site_rates={"gbm_chunk": {"drop_rate": 1.0,
                                                     "after": 1}}):
            GBM(ntrees=8, max_depth=3, seed=1, trees_per_dispatch=2,
                auto_recovery_dir=rdir).train(y="y", training_frame=fr)
    # different depth: the stale snapshot must be IGNORED, not resumed
    # into a differently-shaped ensemble
    m = GBM(ntrees=4, max_depth=2, seed=1,
            auto_recovery_dir=rdir).train(y="y", training_frame=fr)
    ref = GBM(ntrees=4, max_depth=2, seed=1).train(y="y", training_frame=fr)
    np.testing.assert_allclose(_raw(m, fr), _raw(ref, fr), atol=0)


def test_auto_checkpoint_tolerates_callable_params(rng, tmp_path,
                                                   monkeypatch):
    """An unpicklable custom_metric_func (lambda) must not poison the
    snapshot: the artifact drops callables, and the fingerprint encodes
    them by NAME (str() would embed a process-specific address, silently
    breaking every cross-process resume)."""
    from h2o3_tpu.persist.recovery import _params_fingerprint
    # two distinct lambdas (distinct addresses, same qualname) fingerprint
    # identically — the address never reaches the fingerprint
    assert _params_fingerprint({"custom_metric_func": lambda a: a}) == \
        _params_fingerprint({"custom_metric_func": lambda a: a + 1})

    monkeypatch.setenv("H2O3TPU_CHECKPOINT_EVERY", "4")
    fr = _binfr(rng)
    rdir = str(tmp_path / "rec")

    def cmf(preds, yv, w):
        return float(np.sum(w))

    with pytest.raises(DispatchFailed):
        with inject_faults(site_rates={"gbm_chunk": {"drop_rate": 1.0,
                                                     "after": 1}}):
            GBM(ntrees=12, max_depth=3, seed=1, trees_per_dispatch=4,
                auto_recovery_dir=rdir,
                custom_metric_func=cmf).train(y="y", training_frame=fr)
    # the lambda didn't fail the snapshot write: chunk 1's checkpoint landed
    assert os.path.exists(os.path.join(rdir, "model_snapshot.bin"))
    # and it is RESUMABLE by a like-configured builder (fingerprint matches
    # even though the stored params dropped the callable)
    from h2o3_tpu.persist.recovery import BuildRecovery
    resumer = GBM(ntrees=12, max_depth=3, seed=1, trees_per_dispatch=4,
                  auto_recovery_dir=rdir, custom_metric_func=cmf)
    snap = BuildRecovery(rdir).load_snapshot(resumer.params)
    assert snap is not None and snap.output["ntrees"] == 4
    m = resumer.train(y="y", training_frame=fr)
    assert m.output["ntrees"] == 12
    assert getattr(m.training_metrics, "custom_metric_value", None) is not None
    ref = GBM(ntrees=12, max_depth=3, seed=1,
              trees_per_dispatch=4).train(y="y", training_frame=fr)
    np.testing.assert_allclose(_raw(m, fr), _raw(ref, fr), atol=0)


def test_rest_deadline_metadata_survives_no_partial_builders(rng):
    """The REST job must carry deadline evidence even when the builder
    keeps NO partial results (GLM raises JobCancelled): pollers need to
    distinguish a deadline kill from a user cancel."""
    import time as _t

    from h2o3_tpu.api import H2OClient, H2OServer
    from h2o3_tpu.utils.registry import DKV
    fr = _binfr(rng, key="chaos_rest_fr")
    DKV.put("chaos_rest_fr", fr)
    s = H2OServer(port=0).start()
    try:
        c = H2OClient(s.url)
        out = c.request("POST", "/3/ModelBuilders/glm",
                        {"training_frame": "chaos_rest_fr",
                         "response_column": "y", "family": "binomial",
                         "max_iterations": 5000,
                         "max_runtime_secs": 1e-4})
        jk = out["job"]["key"]["name"]
        for _ in range(600):
            j = c.job(jk)
            if j["status"] in ("DONE", "FAILED", "CANCELLED"):
                break
            _t.sleep(0.05)
        assert j["status"] == "CANCELLED"
        assert j["deadline_exceeded"] is True
        assert "max_runtime_secs" in j["progress_msg"]
    finally:
        s.stop()


def test_auto_recoverable_only_advertised_where_snapshots_exist(rng,
                                                                tmp_path):
    """auto_recoverable must be a PROMISE, not an echo of the param: a
    builder that never writes snapshots (GLM) ignores auto_recovery_dir,
    so a client trusting the flag never restarts into a from-scratch
    build."""
    from h2o3_tpu.api import schemas
    fr = _binfr(rng)
    b = GLM(family="binomial", lambda_=1e-4, max_iterations=3,
            auto_recovery_dir=str(tmp_path / "glm_rec"))
    b.train(y="y", training_frame=fr)
    v3 = schemas.job_v3(b.job.key, b.job)
    assert v3["auto_recoverable"] is False
    assert v3["auto_recovery_dir"] is None


def test_zero_tree_partial_scores_and_resumes(rng):
    """A deadline that trips before the FIRST chunk yields a legal
    zero-tree model (the partial-keep path supports it): it must score as
    the null model (f0 only) and must be resumable as a checkpoint without
    crashing the margin fold. Constructed directly — the deadline hitting
    exactly inside that window is not schedulable deterministically."""
    from h2o3_tpu.models.gbm import GBMModel
    from h2o3_tpu.models.model_base import ModelParameters
    fr = _binfr(rng)
    ref = GBM(ntrees=6, max_depth=3, seed=1).train(y="y", training_frame=fr)
    zero = GBMModel(
        key="zero_cp", params=ModelParameters(ref.params),
        data_info=None, response_column="y",
        response_domain=ref.response_domain,
        output=dict(trees=[], edges=ref.output["edges"],
                    f0=ref.output["f0"], learn_rate=0.1,
                    distribution="bernoulli",
                    x_cols=ref.output["x_cols"],
                    feat_domains=ref.output["feat_domains"], ntrees=0))
    p0 = _raw(zero, fr)
    assert np.isfinite(p0).all()              # null-model probabilities
    resumed = GBM(ntrees=6, max_depth=3, seed=1,
                  checkpoint=zero).train(y="y", training_frame=fr)
    np.testing.assert_allclose(_raw(resumed, fr), _raw(ref, fr), atol=0)


def test_zero_round_multinomial_partial_scores(rng):
    from h2o3_tpu.models.gbm import GBMModel
    from h2o3_tpu.models.model_base import ModelParameters
    n = 300
    X = rng.normal(size=(n, 3)).astype(np.float32)
    lab = np.array(["a", "b", "c"])[np.argmax(
        np.stack([X[:, 0], X[:, 1], X[:, 2]], 1), 1)]
    fr = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
                            "y": lab})
    ref = GBM(ntrees=3, max_depth=3, seed=2).train(y="y", training_frame=fr)
    zero = GBMModel(
        key="zero_cp_multi", params=ModelParameters(ref.params),
        data_info=None, response_column="y",
        response_domain=ref.response_domain,
        output=dict(trees_multi=[[], [], []], edges=ref.output["edges"],
                    f0_multi=ref.output["f0_multi"], learn_rate=0.1,
                    distribution="multinomial",
                    x_cols=ref.output["x_cols"],
                    feat_domains=ref.output["feat_domains"], ntrees=0))
    probs = _raw(zero, fr)
    assert probs.shape == (fr.plen, 3) and np.isfinite(probs).all()


# -- FaultInjector thread-safety ----------------------------------------------

def test_fault_injector_is_thread_safe():
    """Satellite: unlocked RNG draws + counter increments under-counted
    faults when chaos ran under windowed_parallel — the injected-fault
    count must equal the raised-fault count exactly."""
    inj = FaultInjector(drop_rate=0.5, seed=9)
    raised = [0] * 8

    def hammer(i):
        from h2o3_tpu.utils.timeline import FaultInjected
        for _ in range(500):
            try:
                inj.maybe_fault("hammer")
            except FaultInjected:
                raised[i] += 1

    threads = [threading.Thread(target=hammer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert inj.dropped == sum(raised)
    assert inj._site_calls["hammer"] == 8 * 500


def test_site_rates_scope_faults_to_one_call_site(rng):
    import jax.numpy as jnp
    x = jnp.ones(16, jnp.float32)
    with inject_faults(site_rates={"elsewhere": {"drop_rate": 1.0}}) as inj:
        out = map_reduce(lambda s: s.sum(), x)   # map_reduce not targeted
    assert float(out) == 16.0 and inj.dropped == 0


# -- crash kind: process-fatal, resume across a REAL kill (slow) --------------

def _run_crash_script(body: str, tmp_path) -> subprocess.CompletedProcess:
    script = textwrap.dedent(body)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["H2O3TPU_DISPATCH_BACKOFF_MS"] = "1"
    return subprocess.run([sys.executable, "-c", script], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=600)


_CRASH_PRELUDE = """
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from h2o3_tpu.frame.frame import Frame
rng = np.random.default_rng(42)
n = 500
X = rng.normal(size=(n, 5)).astype(np.float32)
logit = X[:, 0] * 1.5 - X[:, 1] + 0.3 * X[:, 2]
y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "yes", "no")
cols = {f"x{i}": X[:, i] for i in range(5)}
cols["y"] = y
fr = Frame.from_arrays(cols)
"""


@pytest.mark.slow
def test_crash_kind_kills_process_and_gbm_resumes_bit_identical(rng,
                                                                tmp_path):
    """Tentpole (d): a ``crash`` fault is PROCESS-FATAL (os._exit mid-build,
    the kill -9 scenario). The restarted process resumes from the
    auto-checkpoint and produces bit-identical final trees."""
    rdir = str(tmp_path / "rec")
    crash = _run_crash_script(_CRASH_PRELUDE + f"""
import os
os.environ["H2O3TPU_CHECKPOINT_EVERY"] = "4"
from h2o3_tpu.models.gbm import GBM
from h2o3_tpu.utils import timeline
timeline.FAULTS = timeline.FaultInjector(
    site_rates={{"gbm_chunk": {{"crash_after": 2}}}})
GBM(ntrees=12, max_depth=3, seed=1, trees_per_dispatch=4,
    auto_recovery_dir={rdir!r}).train(y="y", training_frame=fr)
print("UNREACHABLE")
""", tmp_path)
    assert crash.returncode == 86, (crash.stdout, crash.stderr[-2000:])
    assert "UNREACHABLE" not in crash.stdout
    assert os.path.exists(os.path.join(rdir, "model_snapshot.bin"))

    resume = _run_crash_script(_CRASH_PRELUDE + f"""
import os, json
os.environ["H2O3TPU_CHECKPOINT_EVERY"] = "4"
import jax
from h2o3_tpu.models.gbm import GBM
clean = GBM(ntrees=12, max_depth=3, seed=1,
            trees_per_dispatch=4).train(y="y", training_frame=fr)
resumed = GBM(ntrees=12, max_depth=3, seed=1, trees_per_dispatch=4,
              auto_recovery_dir={rdir!r}).train(y="y", training_frame=fr)
identical = all(
    np.array_equal(np.asarray(getattr(tc, ch)), np.asarray(getattr(tr, ch)))
    for tc, tr in zip(clean.output["trees"], resumed.output["trees"])
    for ch in ("feat", "thresh_bin", "thresh_val", "na_left", "is_split",
               "leaf"))
print(json.dumps({{"ntrees": resumed.output["ntrees"],
                   "identical": identical}}))
""", tmp_path)
    assert resume.returncode == 0, resume.stderr[-2000:]
    out = json.loads(resume.stdout.strip().splitlines()[-1])
    assert out == {"ntrees": 12, "identical": True}


@pytest.mark.slow
def test_grid_crash_resume_skips_built_combos_and_matches_leaderboard(
        rng, tmp_path):
    """Satellite: kill a grid search mid-combo (chaos ``crash``), restart
    from the recovery dir — already-built combos are skipped and the final
    leaderboard matches an uninterrupted run."""
    rdir = str(tmp_path / "grid_rec")
    crash = _run_crash_script(_CRASH_PRELUDE + f"""
from h2o3_tpu.orchestration.grid import GridSearch
from h2o3_tpu.models.gbm import GBM
from h2o3_tpu.utils import timeline
timeline.FAULTS = timeline.FaultInjector(
    site_rates={{"gbm_chunk": {{"crash_after": 3}}}})
GridSearch(GBM, {{"max_depth": [2, 3, 4]}}, grid_id="chaos_grid",
           recovery_dir={rdir!r}, ntrees=3, seed=1).train(
    y="y", training_frame=fr)
print("UNREACHABLE")
""", tmp_path)
    assert crash.returncode == 86, (crash.stdout, crash.stderr[-2000:])

    resume = _run_crash_script(_CRASH_PRELUDE + f"""
import json
from h2o3_tpu.orchestration.grid import GridSearch
from h2o3_tpu.models.gbm import GBM
from h2o3_tpu.persist.recovery import Recovery
rec = Recovery({rdir!r})
pre_built = len(rec._state["built"])
g = GridSearch(GBM, {{"max_depth": [2, 3, 4]}}, grid_id="chaos_grid",
               recovery_dir={rdir!r}, ntrees=3, seed=1).train(
    y="y", training_frame=fr)
ref = GridSearch(GBM, {{"max_depth": [2, 3, 4]}}, grid_id="ref_grid",
                 ntrees=3, seed=1).train(y="y", training_frame=fr)
lb = [round(float(m.training_metrics.auc), 9) for m in g.sorted_models()]
lb_ref = [round(float(m.training_metrics.auc), 9)
          for m in ref.sorted_models()]
print(json.dumps({{"pre_built": pre_built, "models": len(g.models),
                   "depths": sorted(m.output["hyper_values"]["max_depth"]
                                    for m in g.models),
                   "match": lb == lb_ref}}))
""", tmp_path)
    assert resume.returncode == 0, resume.stderr[-2000:]
    out = json.loads(resume.stdout.strip().splitlines()[-1])
    # the crash landed mid-3rd-build: ≥1 combo was recovered from disk,
    # the space completed once, and the leaderboard matches fault-free
    assert out["pre_built"] >= 1
    assert out["models"] == 3 and out["depths"] == [2, 3, 4]
    assert out["match"] is True
