"""Cloud persist backends against in-process fake services.

VERDICT r2 missing #7: S3/GCS were guidance-raising stubs; the zero-egress
image can still exercise the REAL wire protocols (SigV4 signing, GCS JSON
API, WebHDFS) against a local HTTP fake via the endpoint overrides —
exactly how the backends point at minio/interop gateways in production.
"""

import hashlib
import hmac
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.frame.parse import import_file
from h2o3_tpu.persist.frame_io import export_file

ACCESS, SECRET = "AKIDTEST", "testsecret"


class _FakeCloud(BaseHTTPRequestHandler):
    """One handler speaking enough S3 + GCS + WebHDFS to round-trip blobs."""

    store: dict[str, bytes] = {}
    sigv4_seen: list[str] = []

    def log_message(self, *a):
        pass

    def _key(self):
        return urllib.parse.urlparse(self.path).path

    def do_GET(self):
        p = urllib.parse.urlparse(self.path)
        auth = self.headers.get("Authorization", "")
        if auth.startswith("AWS4-HMAC-SHA256"):
            type(self).sigv4_seen.append(auth)
            if not self._verify_sigv4("GET", b""):
                self.send_error(403, "SignatureDoesNotMatch")
                return
        key = p.path
        if p.path.startswith("/storage/v1/b/"):      # GCS JSON download
            if "Bearer " not in auth:
                self.send_error(401)
                return
            parts = p.path.split("/")
            key = f"/gcs/{parts[4]}/{urllib.parse.unquote(parts[6])}"
        data = self.store.get(key)
        if data is None:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length") or 0)
        data = self.rfile.read(length)
        auth = self.headers.get("Authorization", "")
        if auth.startswith("AWS4-HMAC-SHA256"):
            type(self).sigv4_seen.append(auth)
            if not self._verify_sigv4("PUT", data):
                self.send_error(403, "SignatureDoesNotMatch")
                return
        self.store[self._key()] = data
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_POST(self):       # GCS JSON upload
        p = urllib.parse.urlparse(self.path)
        length = int(self.headers.get("Content-Length") or 0)
        data = self.rfile.read(length)
        q = urllib.parse.parse_qs(p.query)
        name = q.get("name", ["obj"])[0]
        bucket = p.path.split("/")[5]
        self.store[f"/gcs/{bucket}/{name}"] = data
        body = json.dumps({"name": name}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _verify_sigv4(self, method: str, payload: bytes) -> bool:
        """Recompute the AWS SigV4 signature server-side — the test proves
        the client signs correctly, not just that it sends a header."""
        auth = self.headers["Authorization"]
        amz_date = self.headers["x-amz-date"]
        datestamp = amz_date[:8]
        region = auth.split("/")[2]
        payload_hash = hashlib.sha256(payload).hexdigest()
        if self.headers.get("x-amz-content-sha256") != payload_hash:
            return False
        host = self.headers["Host"]
        canonical_headers = (f"host:{host}\n"
                             f"x-amz-content-sha256:{payload_hash}\n"
                             f"x-amz-date:{amz_date}\n")
        signed = "host;x-amz-content-sha256;x-amz-date"
        canonical = "\n".join([method, urllib.parse.quote(self._key()), "",
                               canonical_headers, signed, payload_hash])
        scope = f"{datestamp}/{region}/s3/aws4_request"
        to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                             hashlib.sha256(canonical.encode()).hexdigest()])

        def hm(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = hm(hm(hm(hm(b"AWS4" + SECRET.encode(), datestamp), region),
                  "s3"), "aws4_request")
        want = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        return f"Signature={want}" in auth


@pytest.fixture
def fake_cloud(monkeypatch):
    _FakeCloud.store = {}
    _FakeCloud.sigv4_seen = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeCloud)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    monkeypatch.setenv("H2O3TPU_S3_ENDPOINT", url)
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", ACCESS)
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", SECRET)
    monkeypatch.setenv("H2O3TPU_GCS_ENDPOINT", url)
    monkeypatch.setenv("H2O3TPU_GCS_TOKEN", "fake-token")
    yield srv
    srv.shutdown()
    srv.server_close()


def test_s3_export_import_roundtrip(fake_cloud, rng):
    fr = Frame.from_arrays({"a": rng.normal(size=20).astype(np.float32),
                            "b": rng.normal(size=20).astype(np.float32)})
    export_file(fr, "s3://mybucket/data/train.csv")
    assert _FakeCloud.sigv4_seen, "PUT must be SigV4-signed"
    back = import_file("s3://mybucket/data/train.csv")
    assert back.nrows == 20 and back.names == ["a", "b"]
    np.testing.assert_allclose(back.vec("a").to_numpy(),
                               fr.vec("a").to_numpy(), rtol=1e-5)


def test_s3_bad_signature_rejected(fake_cloud, monkeypatch):
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "wrong")
    fr = Frame.from_arrays({"a": np.arange(4, dtype=np.float32)})
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as ei:
        export_file(fr, "s3://mybucket/x.csv")
    assert ei.value.code == 403


def test_gcs_export_import_roundtrip(fake_cloud, rng):
    fr = Frame.from_arrays({"x": rng.normal(size=10).astype(np.float32)})
    export_file(fr, "gs://gbucket/dir/part.csv")
    back = import_file("gs://gbucket/dir/part.csv")
    assert back.nrows == 10 and back.names == ["x"]


def test_missing_credentials_guidance(monkeypatch):
    monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
    monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
    with pytest.raises(ValueError, match="AWS_ACCESS_KEY_ID"):
        import_file("s3://bucket/key.csv")
