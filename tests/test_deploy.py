"""Deployment artifacts (reference: h2o-helm/ + h2o-k8s/).

No helm binary ships in this image, so the chart is validated
structurally: parseable Chart/values, and every ``.Values.*`` path the
templates reference must exist in values.yaml (the drift that breaks
``helm install`` at render time).
"""

import os
import re

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHART = os.path.join(REPO, "deploy", "helm", "h2o3tpu")


def test_chart_metadata():
    c = yaml.safe_load(open(os.path.join(CHART, "Chart.yaml")))
    assert c["apiVersion"] == "v2"
    assert c["name"] == "h2o3tpu"
    assert c["version"]


def test_values_parse_and_defaults():
    v = yaml.safe_load(open(os.path.join(CHART, "values.yaml")))
    assert v["cloud"]["nodeCount"] >= 1
    assert v["rest"]["port"] == 54321
    assert v["tpu"]["chipsPerHost"] >= 1


def test_every_template_value_exists():
    v = yaml.safe_load(open(os.path.join(CHART, "values.yaml")))

    def has_path(d, path):
        for part in path:
            if not isinstance(d, dict) or part not in d:
                return False
            d = d[part]
        return True

    tdir = os.path.join(CHART, "templates")
    refs = set()
    for fn in os.listdir(tdir):
        src = open(os.path.join(tdir, fn)).read()
        refs |= {tuple(m.split(".")) for m in
                 re.findall(r"\.Values\.([A-Za-z0-9_.]+)", src)}
    assert refs, "templates reference no values?"
    missing = [r for r in refs if not has_path(v, r)]
    assert not missing, missing


def test_statefulset_wires_the_launcher():
    src = open(os.path.join(CHART, "templates", "statefulset.yaml")).read()
    for needle in ("h2o3_tpu.launch", "--coordinator", "--num-processes",
                   "--process-id", "--serve", "google.com/tpu",
                   "pod-index", "readinessProbe"):
        assert needle in src, needle
    # LDAP block is value-gated
    assert "--ldap-login" in src and "if .Values.auth.ldapUrl" in src


def test_plain_k8s_yaml_still_valid():
    docs = list(yaml.safe_load_all(
        open(os.path.join(REPO, "deploy", "k8s",
                          "h2o3tpu-statefulset.yaml"))))
    kinds = {d["kind"] for d in docs if d}
    assert {"Service", "StatefulSet"} <= kinds
