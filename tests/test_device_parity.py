"""1-device vs N-device numerical equivalence.

Reference contract: an H2O model trained on a 1-node cloud and on a 4-JVM
localhost cloud (``multiNodeUtils.sh:21-26``) produces the same model given
the same seed — the MRTask reduces are commutative-associative and the row
partitioning does not change the math. The TPU equivalent: the same frame
sharded over a 1-device mesh and an 8-device mesh must yield the same trees /
coefficients / metrics (within float tolerance — reduction order differs).
"""

import jax
import numpy as np
import pytest

from jax.sharding import Mesh

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel.mesh import ROWS, mesh_context


def _make_data(rng, n=512):
    cols = {f"x{i}": rng.normal(size=n).astype(np.float32) for i in range(5)}
    cols["cat"] = rng.choice(["a", "b", "c"], size=n)
    cols["y"] = rng.choice(["no", "yes"], size=n)
    return cols


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), axis_names=(ROWS,))


def _train_on_mesh(n_dev, cols, builder_fn):
    with mesh_context(_mesh(n_dev)):
        fr = Frame.from_arrays(cols)
        return builder_fn(fr)


@pytest.mark.parametrize("n_dev", [2, 8])
def test_gbm_device_count_parity(rng, n_dev):
    from h2o3_tpu.models.gbm import GBM

    cols = _make_data(rng)

    def build(fr):
        m = GBM(ntrees=5, max_depth=4, nbins=32, learn_rate=0.2, seed=7).train(
            y="y", training_frame=fr)
        preds = m.predict(fr)
        return (np.asarray(preds.vec("pyes").to_numpy()),
                m.training_metrics.logloss, m.training_metrics.auc)

    p1, ll1, auc1 = _train_on_mesh(1, cols, build)
    pn, lln, aucn = _train_on_mesh(n_dev, cols, build)

    np.testing.assert_allclose(p1, pn, rtol=1e-4, atol=1e-5)
    assert abs(ll1 - lln) < 1e-5
    assert abs(auc1 - aucn) < 1e-6


def test_glm_device_count_parity(rng):
    from h2o3_tpu.models.glm import GLM

    cols = _make_data(rng)

    def build(fr):
        m = GLM(family="binomial", lambda_=1e-3, seed=5).train(
            y="y", training_frame=fr)
        return np.asarray(m.output["coef"]), m.training_metrics.logloss

    c1, ll1 = _train_on_mesh(1, cols, build)
    c8, ll8 = _train_on_mesh(8, cols, build)

    np.testing.assert_allclose(c1, c8, rtol=1e-3, atol=1e-4)
    assert abs(ll1 - ll8) < 1e-5


def test_kmeans_device_count_parity(rng):
    from h2o3_tpu.models.kmeans import KMeans

    cols = {f"x{i}": rng.normal(size=256).astype(np.float32) for i in range(4)}

    def build(fr):
        m = KMeans(k=3, seed=11, max_iterations=10).train(training_frame=fr)
        return np.sort(np.asarray(m.output["centers"]), axis=0)

    c1 = _train_on_mesh(1, cols, build)
    c8 = _train_on_mesh(8, cols, build)
    np.testing.assert_allclose(c1, c8, rtol=1e-4, atol=1e-4)
