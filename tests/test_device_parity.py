"""1-device vs N-device numerical equivalence.

Reference contract: an H2O model trained on a 1-node cloud and on a 4-JVM
localhost cloud (``multiNodeUtils.sh:21-26``) produces the same model given
the same seed — the MRTask reduces are commutative-associative and the row
partitioning does not change the math. The TPU equivalent: the same frame
sharded over a 1-device mesh and an 8-device mesh must yield the same trees /
coefficients / metrics (within float tolerance — reduction order differs).
"""

import jax
import numpy as np
import pytest

from jax.sharding import Mesh

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.parallel.mesh import ROWS, mesh_context


def _make_data(rng, n=512):
    cols = {f"x{i}": rng.normal(size=n).astype(np.float32) for i in range(5)}
    cols["cat"] = rng.choice(["a", "b", "c"], size=n)
    cols["y"] = rng.choice(["no", "yes"], size=n)
    return cols


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), axis_names=(ROWS,))


def _train_on_mesh(n_dev, cols, builder_fn):
    with mesh_context(_mesh(n_dev)):
        fr = Frame.from_arrays(cols)
        return builder_fn(fr)


@pytest.mark.parametrize("n_dev", [2, 8])
def test_gbm_device_count_parity(rng, n_dev):
    from h2o3_tpu.models.gbm import GBM

    cols = _make_data(rng)

    def build(fr):
        m = GBM(ntrees=5, max_depth=4, nbins=32, learn_rate=0.2, seed=7).train(
            y="y", training_frame=fr)
        preds = m.predict(fr)
        return (np.asarray(preds.vec("pyes").to_numpy()),
                m.training_metrics.logloss, m.training_metrics.auc)

    p1, ll1, auc1 = _train_on_mesh(1, cols, build)
    pn, lln, aucn = _train_on_mesh(n_dev, cols, build)

    np.testing.assert_allclose(p1, pn, rtol=1e-4, atol=1e-5)
    assert abs(ll1 - lln) < 1e-5
    assert abs(auc1 - aucn) < 1e-6


def test_glm_device_count_parity(rng):
    from h2o3_tpu.models.glm import GLM

    cols = _make_data(rng)

    def build(fr):
        m = GLM(family="binomial", lambda_=1e-3, seed=5).train(
            y="y", training_frame=fr)
        return np.asarray(m.output["coef"]), m.training_metrics.logloss

    c1, ll1 = _train_on_mesh(1, cols, build)
    c8, ll8 = _train_on_mesh(8, cols, build)

    np.testing.assert_allclose(c1, c8, rtol=1e-3, atol=1e-4)
    assert abs(ll1 - ll8) < 1e-5


def test_kmeans_device_count_parity(rng):
    from h2o3_tpu.models.kmeans import KMeans

    cols = {f"x{i}": rng.normal(size=256).astype(np.float32) for i in range(4)}

    def build(fr):
        m = KMeans(k=3, seed=11, max_iterations=10).train(training_frame=fr)
        return np.sort(np.asarray(m.output["centers"]), axis=0)

    c1 = _train_on_mesh(1, cols, build)
    c8 = _train_on_mesh(8, cols, build)
    np.testing.assert_allclose(c1, c8, rtol=1e-4, atol=1e-4)


# -- the full trainable-algo parity matrix (VERDICT r4 next #6) -------------

def _pred_col(m, fr, col):
    return np.asarray(m.predict(fr).vec(col).to_numpy())[: fr.nrows]


def test_deeplearning_device_count_parity(rng):
    from h2o3_tpu.models.deeplearning import DeepLearning

    cols = _make_data(rng, n=256)

    def build(fr):
        m = DeepLearning(hidden=[8], epochs=2, mini_batch_size=64,
                         seed=3).train(y="y", training_frame=fr)
        return _pred_col(m, fr, "pyes")

    np.testing.assert_allclose(_train_on_mesh(1, cols, build),
                               _train_on_mesh(8, cols, build),
                               rtol=1e-3, atol=1e-4)


def test_pca_svd_glrm_device_count_parity(rng):
    from h2o3_tpu.models.decomposition import GLRM, PCA, SVD

    cols = {f"x{i}": rng.normal(size=256).astype(np.float32)
            for i in range(5)}

    def build(fr):
        pca = PCA(k=3, transform="DEMEAN", seed=1).train(training_frame=fr)
        svd = SVD(nv=3, transform="NONE", seed=1).train(training_frame=fr)
        glrm = GLRM(k=2, max_iterations=30, seed=3).train(training_frame=fr)
        return (np.abs(np.asarray(pca.output["eigenvectors"])),
                np.asarray(svd.output["d"]),
                float(glrm.output["objective"]))

    e1, d1, o1 = _train_on_mesh(1, cols, build)
    e8, d8, o8 = _train_on_mesh(8, cols, build)
    np.testing.assert_allclose(e1, e8, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(d1, d8, rtol=1e-4)
    assert abs(o1 - o8) / max(abs(o1), 1e-9) < 1e-2


def test_coxph_device_count_parity(rng):
    from h2o3_tpu.models import CoxPH

    n = 256
    X = rng.normal(size=(n, 2)).astype(np.float32)
    lp = 0.8 * X[:, 0] - 0.5 * X[:, 1]
    time = (-np.log(rng.random(n)) / np.exp(lp)).astype(np.float32)
    cols = {"x0": X[:, 0], "x1": X[:, 1], "time": time,
            "event": np.ones(n, np.float32)}

    def build(fr):
        m = CoxPH(stop_column="time").train(x=["x0", "x1"], y="event",
                                            training_frame=fr)
        c = m.coefficients()
        return np.array([c["x0"], c["x1"]])

    np.testing.assert_allclose(_train_on_mesh(1, cols, build),
                               _train_on_mesh(8, cols, build),
                               rtol=1e-3, atol=1e-4)


def test_psvm_device_count_parity(rng):
    from h2o3_tpu.models.psvm import PSVM

    n = 256
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = np.where(X[:, 0] - X[:, 1] > 0, "pos", "neg").astype(object)
    cols = {"a": X[:, 0], "b": X[:, 1], "c": X[:, 2], "y": y}

    def build(fr):
        m = PSVM(hyper_param=1.0, max_iterations=40, seed=1).train(
            y="y", training_frame=fr)
        return _pred_col(m, fr, "predict")

    p1 = _train_on_mesh(1, cols, build)
    p8 = _train_on_mesh(8, cols, build)
    assert (p1 == p8).mean() > 0.98     # decision boundary parity


def test_word2vec_device_count_parity(rng):
    from h2o3_tpu.frame.types import VecType
    from h2o3_tpu.models import Word2Vec

    topics = [["cat", "dog", "pet"], ["car", "bus", "road"]]
    words = []
    for _ in range(200):
        t = topics[rng.integers(0, 2)]
        words += [t[rng.integers(0, 3)] for _ in range(5)] + [None]
    arr = np.array(words, dtype=object)

    def build_w2v(n_dev):
        with mesh_context(_mesh(n_dev)):
            fr = Frame.from_arrays({"words": arr},
                                   types={"words": VecType.STR})
            m = Word2Vec(vec_size=8, min_word_freq=2, epochs=5,
                         seed=11).train(training_frame=fr)
            syn = m.find_synonyms("cat", 2)
            return set(syn)

    assert build_w2v(1) == build_w2v(8)


def test_naive_bayes_device_count_parity(rng):
    from h2o3_tpu.models.naive_bayes import NaiveBayes

    cols = _make_data(rng, n=256)

    def build(fr):
        m = NaiveBayes(laplace=1.0).train(y="y", training_frame=fr)
        return _pred_col(m, fr, "pyes")

    np.testing.assert_allclose(_train_on_mesh(1, cols, build),
                               _train_on_mesh(8, cols, build),
                               rtol=1e-4, atol=1e-5)


def test_isotonic_device_count_parity(rng):
    from h2o3_tpu.models import IsotonicRegression

    n = 256
    x = rng.normal(size=n).astype(np.float32)
    cols = {"x": x, "y": (x + 0.3 * rng.normal(size=n)).astype(np.float32)}

    def build(fr):
        m = IsotonicRegression().train(x=["x"], y="y", training_frame=fr)
        return _pred_col(m, fr, "predict")

    np.testing.assert_allclose(_train_on_mesh(1, cols, build),
                               _train_on_mesh(8, cols, build),
                               rtol=1e-5, atol=1e-6)
