"""Tree feature parity: monotone constraints, interaction constraints,
probability calibration.

Reference: ``hex/tree/Constraints.java:7`` (monotone),
``BranchInteractionConstraints.java`` (interaction),
``hex/tree/CalibrationHelper.java:18`` (Platt / isotonic calibration).
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gbm import GBM


def _mono_data(rng, n=800):
    x0 = rng.uniform(-2, 2, n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    # y increases with x0 on average but with enough noise that an
    # unconstrained tree produces local decreases
    y = (x0 + 1.5 * np.sin(3 * x0) + 0.5 * x1
         + rng.normal(scale=0.5, size=n)).astype(np.float32)
    return Frame.from_arrays({"x0": x0, "x1": x1, "y": y})


def _pd_curve(model, lo=-2.0, hi=2.0, k=41):
    grid = np.linspace(lo, hi, k, dtype=np.float32)
    fr = Frame.from_arrays({
        "x0": grid, "x1": np.zeros(k, np.float32)})
    return model.predict(fr).vec("predict").to_numpy()


def test_monotone_increasing_constraint(rng):
    fr = _mono_data(rng)
    un = GBM(ntrees=30, max_depth=4, seed=1).train(y="y", training_frame=fr)
    con = GBM(ntrees=30, max_depth=4, seed=1,
              monotone_constraints={"x0": 1}).train(y="y", training_frame=fr)

    curve_un = _pd_curve(un)
    curve_con = _pd_curve(con)
    # constrained: predictions never decrease along x0
    assert (np.diff(curve_con) >= -1e-5).all(), np.diff(curve_con).min()
    # the data's wiggles make the unconstrained model non-monotone
    assert (np.diff(curve_un) < -1e-4).any()
    # and the constrained model still learns the overall trend
    assert curve_con[-1] - curve_con[0] > 1.0


def test_monotone_decreasing_constraint(rng):
    fr = _mono_data(rng)
    neg = Frame.from_arrays({
        "x0": fr.vec("x0").to_numpy(),
        "x1": fr.vec("x1").to_numpy(),
        "y": -fr.vec("y").to_numpy()})
    con = GBM(ntrees=30, max_depth=4, seed=1,
              monotone_constraints={"x0": -1}).train(y="y", training_frame=neg)
    curve = _pd_curve(con)
    assert (np.diff(curve) <= 1e-5).all()


def test_monotone_validation(rng):
    fr = Frame.from_arrays({
        "x": rng.normal(size=50).astype(np.float32),
        "c": rng.choice(["a", "b"], size=50),
        "y": rng.normal(size=50).astype(np.float32)})
    with pytest.raises(ValueError, match="categorical"):
        GBM(ntrees=2, monotone_constraints={"c": 1}).train(
            y="y", training_frame=fr)
    with pytest.raises(ValueError, match="non-feature"):
        GBM(ntrees=2, monotone_constraints={"zzz": 1}).train(
            y="y", training_frame=fr)


def test_interaction_constraints(rng):
    n = 600
    a = rng.normal(size=n).astype(np.float32)
    b = rng.normal(size=n).astype(np.float32)
    c = rng.normal(size=n).astype(np.float32)
    y = (a * b + 0.3 * c + rng.normal(scale=0.1, size=n)).astype(np.float32)
    fr = Frame.from_arrays({"a": a, "b": b, "c": c, "y": y})

    m = GBM(ntrees=10, max_depth=4, seed=2,
            interaction_constraints=[["a", "b"]]).train(
        y="y", training_frame=fr)
    # walk every tree: under any path that used 'a' or 'b', only {a, b}
    # may appear; under 'c' (singleton), only 'c'
    groups = {0: {0, 1}, 1: {0, 1}, 2: {2}}
    for tree in m.output["trees"]:
        feat = np.asarray(tree.feat)
        is_sp = np.asarray(tree.is_split)

        def walk(i, allowed):
            if i >= len(feat) or not is_sp[i]:
                return
            f = int(feat[i])
            assert allowed is None or f in allowed, (i, f, allowed)
            nxt = groups[f] if allowed is None else (allowed & groups[f])
            walk(2 * i + 1, nxt)
            walk(2 * i + 2, nxt)

        walk(0, None)


def test_platt_calibration(rng):
    n = 1200
    x = rng.normal(size=(n, 3)).astype(np.float32)
    logit = 1.5 * x[:, 0] - x[:, 1]
    y = rng.random(n) < 1 / (1 + np.exp(-logit))
    cols = {f"x{i}": x[:, i] for i in range(3)}
    cols["y"] = np.array(["no", "yes"], dtype=object)[y.astype(int)]
    fr = Frame.from_arrays(cols)
    cal = Frame.from_arrays({k: v[:400] for k, v in cols.items()})

    m = GBM(ntrees=20, max_depth=3, seed=3, calibrate_model=True,
            calibration_frame=cal).train(y="y", training_frame=fr)
    assert m.output["calibration"]["method"] == "PlattScaling"
    pred = m.predict(fr)
    assert "cal_p0" in pred.names and "cal_p1" in pred.names
    cp1 = pred.vec("cal_p1").to_numpy()
    cp0 = pred.vec("cal_p0").to_numpy()
    np.testing.assert_allclose(cp0 + cp1, 1.0, atol=1e-5)
    assert ((cp1 >= 0) & (cp1 <= 1)).all()
    # calibrated probs should correlate with the raw ones
    p1 = pred.vec("pyes").to_numpy()
    assert np.corrcoef(p1, cp1)[0, 1] > 0.9


def test_isotonic_calibration(rng):
    n = 800
    x = rng.normal(size=(n, 2)).astype(np.float32)
    y = rng.random(n) < 1 / (1 + np.exp(-2 * x[:, 0]))
    cols = {"x0": x[:, 0], "x1": x[:, 1],
            "y": np.array(["no", "yes"], dtype=object)[y.astype(int)]}
    fr = Frame.from_arrays(cols)

    m = GBM(ntrees=10, max_depth=3, seed=4, calibrate_model=True,
            calibration_frame=fr,
            calibration_method="IsotonicRegression").train(
        y="y", training_frame=fr)
    pred = m.predict(fr)
    cp1 = pred.vec("cal_p1").to_numpy()
    p1 = pred.vec("pyes").to_numpy()
    # isotonic map preserves order
    o = np.argsort(p1)
    assert (np.diff(cp1[o]) >= -1e-9).all()


def test_calibration_validation(rng):
    fr = Frame.from_arrays({
        "x": rng.normal(size=50).astype(np.float32),
        "y": rng.normal(size=50).astype(np.float32)})
    with pytest.raises(ValueError, match="binomial"):
        GBM(ntrees=2, calibrate_model=True, calibration_frame=fr).train(
            y="y", training_frame=fr)
