"""Ops plane (ISSUE 15): health rules, incident detection, diagnostic
bundles — rule semantics over monkeypatched registries, incident
auto-capture under the PR 8 chaos harness (each injected fault class
opens exactly ONE incident of the right rule class with non-empty
context), the REST/client surface, and the one-call bundle round trip
(docs/OBSERVABILITY.md "Health & incidents")."""

import io
import json
import tarfile
import threading
import time
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.utils import health as hm
from h2o3_tpu.utils.health import (DEGRADED, HEALTHY, UNHEALTHY,
                                   HealthEvaluator, diagnostic_bundle,
                                   redacted_config)
from h2o3_tpu.utils.incidents import IncidentLog
from h2o3_tpu.utils.registry import DKV


def _evaluator(**kw):
    """An isolated evaluator: its own incident log, fast interval."""
    kw.setdefault("interval_s", 0.1)
    kw.setdefault("incidents", IncidentLog(capacity=16))
    return HealthEvaluator(**kw)


def _findings_by_rule(verdict):
    return {f["rule"]: f for f in verdict["findings"]}


# -- verdict shape / clean state ---------------------------------------------

def test_clean_registries_read_healthy():
    ev = _evaluator()
    v = ev.evaluate()
    assert v["status"] == HEALTHY and v["healthy"] is True
    assert v["findings"] == []
    assert set(v["subsystems"]) == set(hm.SUBSYSTEMS)
    assert all(s["status"] == HEALTHY for s in v["subsystems"].values())
    # the rule catalog rides along with thresholds + env knobs
    assert {r["rule"] for r in v["rules"]} >= {
        "elastic_heartbeat_gap", "serving_p99_slo", "memory_spill_thrash",
        "compute_recompile_storm", "dispatch_retry_exhaustion"}
    assert all(r["env"].startswith("H2O3TPU_HEALTH_") for r in v["rules"])


def test_finding_carries_rule_value_threshold(monkeypatch):
    """Every finding names the tripping rule, the observed value, and the
    threshold — the ISSUE's no-bare-boolean contract."""
    monkeypatch.setattr(hm, "_elastic_rows", lambda: [
        {"state": "ACTIVE", "last_heartbeat_ago_ms": 99_000.0}])
    monkeypatch.setenv("H2O3TPU_HEALTH_HEARTBEAT_GAP_SECS", "30")
    ev = _evaluator()
    v = ev.evaluate()
    assert v["status"] == UNHEALTHY
    assert v["subsystems"]["elastic"]["status"] == UNHEALTHY
    f = _findings_by_rule(v)["elastic_heartbeat_gap"]
    assert f["observed"] == 99.0
    assert f["threshold"] == 30.0
    assert f["severity"] == UNHEALTHY
    assert "elastic_heartbeat_gap" in f["message"]


def test_heartbeat_gap_ignores_ejected_workers(monkeypatch):
    """An EJECTED worker's silence is the state machine doing its job —
    only live states (ACTIVE/SUSPECT/JOINING) rate against the lease."""
    monkeypatch.setattr(hm, "_elastic_rows", lambda: [
        {"state": "EJECTED", "last_heartbeat_ago_ms": 9e6},
        {"state": "ACTIVE", "last_heartbeat_ago_ms": 10.0}])
    assert _evaluator().evaluate()["status"] == HEALTHY


def test_suspect_dwell_trips_on_streak_not_blip(monkeypatch):
    rows = [{"state": "SUSPECT", "last_heartbeat_ago_ms": 10.0}]
    monkeypatch.setattr(hm, "_elastic_rows", lambda: rows)
    ev = _evaluator()
    v1 = ev.evaluate()            # streak 1: not past the 1-sweep default
    assert "elastic_suspect_dwell" not in _findings_by_rule(v1)
    v2 = ev.evaluate()            # streak 2: dwelling
    f = _findings_by_rule(v2)["elastic_suspect_dwell"]
    assert f["observed"] == 2.0 and f["severity"] == DEGRADED
    rows[:] = [{"state": "ACTIVE", "last_heartbeat_ago_ms": 10.0}]
    v3 = ev.evaluate()            # recovery resets the streak
    assert v3["status"] == HEALTHY


def test_serving_rules_rate_shed_and_p99(monkeypatch):
    stats = {"shed_total": 0, "resident": [
        {"model": "m", "slo": {"target_ms": 50.0, "p99_ms": 20.0}}]}
    monkeypatch.setattr(hm, "_serving_stats", lambda: stats)
    monkeypatch.setattr(hm, "_score_requests_total", lambda: 100.0)
    ev = _evaluator()
    assert ev.evaluate()["status"] == HEALTHY      # baseline window
    # window 2: 40 of 100 admissions shed → rate 0.4 (the request counter
    # already includes sheds as status=error — service.score counts the
    # ServiceUnavailable on its way out, so the denominator is the
    # all-status delta alone, NOT shed+delta); p99 blows the SLO
    stats["shed_total"] = 40
    stats["resident"][0]["slo"]["p99_ms"] = 75.0
    monkeypatch.setattr(hm, "_score_requests_total", lambda: 200.0)
    v = ev.evaluate()
    by = _findings_by_rule(v)
    assert by["serving_shed_rate"]["observed"] == 0.4
    assert by["serving_p99_slo"]["observed"] == 1.5
    assert v["subsystems"]["serving"]["status"] == UNHEALTHY  # p99 wins


def test_shed_rate_total_overload_reads_one(monkeypatch):
    """100% shed must read 1.0, not saturate at 0.5 — the double-count
    regression: every shed already rides in the request counter."""
    stats = {"shed_total": 0, "resident": []}
    monkeypatch.setattr(hm, "_serving_stats", lambda: stats)
    monkeypatch.setattr(hm, "_score_requests_total", lambda: 0.0)
    ev = _evaluator()
    ev.evaluate()                                  # baseline
    stats["shed_total"] = 50
    monkeypatch.setattr(hm, "_score_requests_total", lambda: 50.0)
    f = _findings_by_rule(ev.evaluate())["serving_shed_rate"]
    assert f["observed"] == 1.0


def test_mfu_collapse_only_on_rated_backends(monkeypatch):
    loops = {"glm_irls": {"utilization": None, "samples": 50}}
    monkeypatch.setattr(hm, "_compute_loops", lambda: loops)
    ev = _evaluator()
    assert ev.evaluate()["status"] == HEALTHY      # null util never trips
    loops["glm_irls"] = {"utilization": 0.001, "samples": 50}
    f = _findings_by_rule(ev.evaluate())["compute_mfu_collapse"]
    assert f["observed"] == 0.001 and f["threshold"] == 0.02


def test_window_deltas_baseline_on_first_sweep(monkeypatch):
    """Pre-existing counter totals must never page a fresh evaluator —
    the first sweep baselines, movement pages."""
    total = [50.0]
    monkeypatch.setattr(hm, "_recompile_total", lambda: total[0])
    ev = _evaluator()
    assert ev.evaluate()["status"] == HEALTHY      # 50 pre-existing: quiet
    total[0] = 51.0
    assert ev.evaluate()["status"] == HEALTHY      # +1 under threshold 2
    total[0] = 60.0
    f = _findings_by_rule(ev.evaluate())["compute_recompile_storm"]
    assert f["observed"] == 9.0 and f["subsystem"] == "compute"


def test_probe_failure_degrades_not_crashes(monkeypatch):
    def boom():
        raise RuntimeError("registry sick")
    monkeypatch.setattr(hm, "_cleaner_stats", boom)
    v = _evaluator().evaluate()
    assert v["status"] == DEGRADED
    f = _findings_by_rule(v)["memory_spill_thrash"]
    assert "probe failed" in f["message"] and f["observed"] is None


def test_failed_probe_does_not_resolve_open_incident(monkeypatch):
    """A probe that starts raising is blindness, not recovery: the rule's
    open incident must stay open (and re-trips after the probe heals must
    not mint a duplicate)."""
    stats = {"shed_total": 0, "resident": [
        {"model": "m", "slo": {"target_ms": 50.0, "p99_ms": 90.0}}]}
    monkeypatch.setattr(hm, "_serving_stats", lambda: stats)
    ev = _evaluator()
    ev.evaluate()                                  # p99 1.8x SLO → open
    [inc] = ev.incidents.list()
    assert inc["rule"] == "serving_p99_slo" and inc["status"] == "open"

    def boom():
        raise RuntimeError("registry sick")
    monkeypatch.setattr(hm, "_serving_stats", boom)
    v = ev.evaluate()                              # probe fails this sweep
    assert "probe failed" in _findings_by_rule(v)["serving_p99_slo"]["message"]
    [inc] = ev.incidents.list()
    assert inc["status"] == "open"                 # NOT falsely resolved
    monkeypatch.setattr(hm, "_serving_stats", lambda: stats)
    ev.evaluate()                                  # heals, still tripping
    assert len(ev.incidents.list()) == 1           # same incident, no dupe
    stats["resident"][0]["slo"]["p99_ms"] = 10.0
    ev.evaluate()                                  # genuine recovery
    [inc] = ev.incidents.list()
    assert inc["status"] == "resolved"


# -- incidents ---------------------------------------------------------------

def test_incident_dedupe_resolve_and_reopen(monkeypatch):
    total = [0.0]
    monkeypatch.setattr(hm, "_ejections_total", lambda: total[0])
    ev = _evaluator()
    ev.evaluate()                                  # baseline
    total[0] = 1.0
    ev.evaluate()                                  # rising edge → open
    total[0] = 2.0
    ev.evaluate()                                  # still tripping → repeat
    incs = ev.incidents.list()
    assert len(incs) == 1
    assert incs[0]["rule"] == "elastic_ejections"
    assert incs[0]["status"] == "open" and incs[0]["repeats"] == 2
    ev.evaluate()                                  # no movement → resolve
    incs = ev.incidents.list()
    assert incs[0]["status"] == "resolved"
    assert incs[0]["resolved_ms"] is not None
    total[0] = 3.0
    ev.evaluate()                                  # new edge → NEW incident
    assert len(ev.incidents.list()) == 2


def test_incident_context_capture_and_series():
    log = IncidentLog(capacity=8)
    iid = log.open("compute_recompile_storm", "compute", DEGRADED,
                   "storm", 7.0, 2.0, series=[1.0, 3.0, 7.0])
    rec = log.get(iid)
    ctx = rec["context"]
    assert ctx["series"] == [1.0, 3.0, 7.0]
    assert isinstance(ctx["logs"], list)
    assert isinstance(ctx["traces"], list)
    assert "top_keys" in ctx["memory"]
    assert "loops" in ctx["compute"]
    with pytest.raises(KeyError):
        log.get("inc_nope")


def test_incident_ring_bounded():
    log = IncidentLog(capacity=4)
    for i in range(7):
        log.open(f"rule_{i}", "memory", DEGRADED, "m", i, 0)
        log.resolve(f"rule_{i}")
    incs = log.list()
    assert len(incs) == 4
    assert [i["rule"] for i in incs] == ["rule_6", "rule_5", "rule_4",
                                        "rule_3"]
    assert log.opened_total() == 7                 # monotonic, not ring size


def test_ring_eviction_spares_open_incidents():
    """Eviction takes the oldest RESOLVED record — an ongoing episode
    must keep its id (a mid-episode eviction would re-count
    h2o3_incidents_total when the still-tripping rule re-opens)."""
    log = IncidentLog(capacity=4)
    ongoing = log.open("serving_shed_rate", "serving", DEGRADED,
                       "overload", 0.4, 0.05)      # stays OPEN throughout
    for i in range(6):                             # 6 flapping rules churn
        log.open(f"flap_{i}", "memory", DEGRADED, "m", i, 0)
        log.resolve(f"flap_{i}")
    assert log.get(ongoing)["status"] == "open"    # survived the churn
    # the still-tripping rule folds into the SAME record, no new id
    assert log.open("serving_shed_rate", "serving", DEGRADED,
                    "overload", 0.5, 0.05) == ongoing
    assert log.get(ongoing)["repeats"] == 2
    assert log.opened_total() == 7                 # one open per episode


def test_compute_incident_fires_single_flight_profile(monkeypatch):
    """H2O3TPU_INCIDENT_PROFILE=1: a compute-class incident enriches
    itself with one bounded profiler capture (skipped, never queued, when
    the profiler is busy)."""
    monkeypatch.setenv("H2O3TPU_INCIDENT_PROFILE", "1")
    log = IncidentLog(capacity=4)
    iid = log.open("compute_recompile_storm", "compute", DEGRADED,
                   "storm", 9.0, 2.0)
    deadline = time.monotonic() + 20.0
    cap = None
    while time.monotonic() < deadline:
        cap = log.get(iid)["context"].get("profiler_capture")
        if cap is not None:
            break
        time.sleep(0.05)
    assert cap is not None and cap.startswith("cap_")


# -- chaos harness: each injected fault class → exactly one incident ---------

def test_injected_retry_exhaustion_opens_one_dispatch_incident(monkeypatch):
    import jax.numpy as jnp

    from h2o3_tpu.ops.map_reduce import DispatchFailed, map_reduce
    from h2o3_tpu.utils.timeline import inject_faults
    monkeypatch.setenv("H2O3TPU_DISPATCH_BACKOFF_MS", "1")
    ev = _evaluator()
    ev.evaluate()                                  # baseline the window
    with inject_faults(drop_rate=1.0):
        with pytest.raises(DispatchFailed):
            map_reduce(lambda s: s.sum(), jnp.ones(16, jnp.float32))
    v = ev.evaluate()
    assert v["subsystems"]["dispatch"]["status"] == UNHEALTHY
    f = _findings_by_rule(v)["dispatch_retry_exhaustion"]
    assert f["observed"] >= 1.0
    incs = ev.incidents.list()
    assert len(incs) == 1 and incs[0]["rule"] == "dispatch_retry_exhaustion"
    ctx = ev.incidents.get(incs[0]["id"])["context"]
    assert ctx["logs"] or ctx["traces"] or ctx["memory"]  # non-empty capture
    assert ctx["series"]


@pytest.mark.slow
def test_stalled_elastic_worker_opens_one_elastic_incident(rng, monkeypatch):
    """A worker stalled dead mid-build (PR 12 chaos `stall`) decays the
    membership — the ejection lands as exactly one elastic-class
    incident with correlated context."""
    from h2o3_tpu.models.deeplearning import DeepLearning
    from h2o3_tpu.parallel import elastic
    from h2o3_tpu.utils.timeline import inject_faults
    monkeypatch.setenv("H2O3TPU_DISPATCH_BACKOFF_MS", "1")
    monkeypatch.setenv("H2O3TPU_ELASTIC_ROUND_DEADLINE_SECS", "2.0")
    monkeypatch.setenv("H2O3TPU_ELASTIC_LEASE_SECS", "1.0")
    X = rng.normal(size=(512, 6)).astype(np.float32)
    cols = {f"x{i}": X[:, i] for i in range(6)}
    cols["y"] = np.where(rng.random(512) < 0.5, "yes", "no")
    fr = Frame.from_arrays(cols)
    ev = _evaluator()
    ev.evaluate()                                  # baseline the window
    try:
        with inject_faults(worker_rates={1: {"stall_rate": 1.0,
                                             "stall_ms": 60_000,
                                             "after": 4}}):
            b = DeepLearning(hidden=[8], epochs=3, elastic=2,
                             local_steps=1, mini_batch_size=64, seed=5)
            b.train(y="y", training_frame=fr)
        assert b.job.workers_ejected == 1
        v = ev.evaluate()
        f = _findings_by_rule(v)["elastic_ejections"]
        assert f["observed"] == 1.0 and f["subsystem"] == "elastic"
        elastic_incs = [i for i in ev.incidents.list()
                        if i["subsystem"] == "elastic"]
        assert len(elastic_incs) == 1
        assert elastic_incs[0]["rule"] == "elastic_ejections"
        ctx = ev.incidents.get(elastic_incs[0]["id"])["context"]
        assert ctx["series"] and isinstance(ctx["logs"], list)
    finally:
        elastic.drain(60.0)


def test_forced_spill_thrash_opens_one_memory_incident(tmp_path, rng):
    """A working set thrashing through the Cleaner (spill → fault-in →
    spill, PR 14) trips memory_spill_thrash exactly once."""
    from h2o3_tpu.utils.cleaner import disable_cleaner, enable_cleaner

    def mk(key):
        f = Frame.from_arrays(
            {f"c{i}": rng.normal(size=4096).astype(np.float32)
             for i in range(4)}, key=key)
        DKV.put(key, f)
        return f

    try:
        # budget fits ~1 frame: each get of one key spills the other
        enable_cleaner(70_000, ice_root=str(tmp_path))
        mk("thrash_a")
        mk("thrash_b")
        ev = _evaluator()
        ev.evaluate()                              # baseline post-setup
        for _ in range(8):
            DKV.get("thrash_a")
            DKV.get("thrash_b")
        v = ev.evaluate()
        f = _findings_by_rule(v)["memory_spill_thrash"]
        assert f["observed"] > f["threshold"]
        mem_incs = [i for i in ev.incidents.list()
                    if i["subsystem"] == "memory"]
        assert len(mem_incs) == 1
        assert mem_incs[0]["rule"] == "memory_spill_thrash"
        assert ev.incidents.get(mem_incs[0]["id"])["context"]["series"]
    finally:
        disable_cleaner()


# -- the sweep thread --------------------------------------------------------

def test_sweep_thread_runs_and_stops_bounded():
    ev = _evaluator(interval_s=0.05)
    assert ev.start() is True
    assert ev.start() is False                     # idempotent
    deadline = time.monotonic() + 10.0
    while ev.sweeps() < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert ev.sweeps() >= 2
    t0 = time.monotonic()
    ev.stop()
    assert time.monotonic() - t0 < 5.0
    assert not ev.running()


def test_health_off_disables(monkeypatch):
    monkeypatch.setenv("H2O3TPU_HEALTH_OFF", "1")
    ev = _evaluator()
    assert ev.start() is False
    v = ev.verdict()
    assert v["status"] == "disabled" and v["healthy"] is None
    assert ev.sweeps() == 0                        # never evaluated


def test_verdict_evaluates_inline_without_thread():
    ev = _evaluator()
    v = ev.verdict()
    assert v["sweep"] == 1 and v["status"] == HEALTHY


def test_stop_during_sweep_drains_without_incident(monkeypatch):
    """A stop() landing while a sweep is mid-probe DRAINS the sweep: the
    abort seam between a probe's return and its incident open means the
    dying thread can never open an incident (which nothing would ever
    resolve) after shutdown. The probe here blocks until stop() is already
    pending, then returns data that WOULD trip elastic_heartbeat_gap."""
    entered = threading.Event()
    release = threading.Event()

    def blocking_rows():
        entered.set()
        release.wait(timeout=10.0)
        return [{"state": "ACTIVE", "last_heartbeat_ago_ms": 9e6}]

    monkeypatch.setattr(hm, "_elastic_rows", blocking_rows)
    monkeypatch.setenv("H2O3TPU_HEALTH_HEARTBEAT_GAP_SECS", "1")
    ev = _evaluator(interval_s=0.01)
    assert ev.start() is True
    assert entered.wait(timeout=10.0)

    stopper = threading.Thread(target=ev.stop)
    stopper.start()
    # stop() clears the thread slot (under the lock, with _stop set)
    # before joining — once running() is False the abort flag is up and
    # the probe may return its poison
    deadline = time.monotonic() + 10.0
    while ev.running() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert not ev.running()
    release.set()
    stopper.join(timeout=10.0)
    assert not stopper.is_alive()

    # the drained sweep opened nothing and never counted as a thread
    # sweep (it returned None before the counter)
    assert ev.incidents.list() == []
    assert ev.thread_sweeps() == 0


def test_inline_evaluate_unaffected_by_abort_seam(monkeypatch):
    """evaluate() without an abort callable (the inline/REST path) still
    trips and opens incidents exactly as before the drain fix."""
    monkeypatch.setattr(hm, "_elastic_rows", lambda: [
        {"state": "ACTIVE", "last_heartbeat_ago_ms": 9e6}])
    monkeypatch.setenv("H2O3TPU_HEALTH_HEARTBEAT_GAP_SECS", "1")
    ev = _evaluator()
    v = ev.evaluate()
    assert v is not None and v["status"] == UNHEALTHY
    assert [r["rule"] for r in ev.incidents.list()] == \
        ["elastic_heartbeat_gap"]


# -- bundle ------------------------------------------------------------------

BUNDLE_MEMBERS = {
    "metrics.json", "metrics.prom", "traces.json", "memory.json",
    "compute.json", "health.json", "incidents.json", "actions.json",
    "timeseries.json", "logs.txt", "hardware.json", "config.json"}


def _unpack(data: bytes) -> dict:
    tar = tarfile.open(fileobj=io.BytesIO(data), mode="r:gz")
    return {m.name.split("/", 1)[1]: tar.extractfile(m).read()
            for m in tar.getmembers()}


def test_bundle_contains_all_pillars_and_redacts_secrets(monkeypatch):
    monkeypatch.setenv("H2O3TPU_ADMIN_PASSWORD", "hunter2")
    monkeypatch.setenv("H2O3TPU_LDAP_TOKEN", "s3cr3t-tok")
    monkeypatch.setenv("H2O3TPU_MEGASTEP_K", "4")
    ev = _evaluator()
    ev.incidents.open("compute_recompile_storm", "compute", DEGRADED,
                      "storm", 5.0, 2.0)
    data, fname = diagnostic_bundle(ev)
    assert fname.startswith("h2o3_diagnostics_") and fname.endswith(".tar.gz")
    members = _unpack(data)
    assert set(members) == BUNDLE_MEMBERS
    # all four pillar snapshots parse and carry their signature keys
    assert isinstance(json.loads(members["metrics.json"]), list)
    assert members["metrics.prom"].rstrip().endswith(b"# EOF")
    assert "traces" in json.loads(members["traces.json"])
    assert "dkv" in json.loads(members["memory.json"])
    assert "loops" in json.loads(members["compute.json"])
    health = json.loads(members["health.json"])
    assert health["status"] in ("healthy", "degraded", "unhealthy")
    incidents = json.loads(members["incidents.json"])
    assert incidents and incidents[0]["rule"] == "compute_recompile_storm"
    assert incidents[0]["context"] is not None
    assert isinstance(json.loads(members["actions.json"]), list)
    ts = json.loads(members["timeseries.json"])
    assert "stats" in ts and "series" in ts
    cfg = json.loads(members["config.json"])
    assert cfg["H2O3TPU_ADMIN_PASSWORD"] == "[redacted]"
    assert cfg["H2O3TPU_LDAP_TOKEN"] == "[redacted]"
    assert cfg["H2O3TPU_MEGASTEP_K"] == "4"        # knobs ship in clear
    assert b"hunter2" not in data and b"s3cr3t-tok" not in data


def test_redacted_config_name_patterns(monkeypatch):
    monkeypatch.setenv("H2O3TPU_S3_ACCESS_KEY", "AKIAxxx")
    monkeypatch.setenv("H2O3TPU_TLS_CERT", "pem-blob")
    monkeypatch.setenv("H2O3TPU_HEALTH_INTERVAL_SECS", "5")
    monkeypatch.setenv("HOME_SECRET", "outside-prefix")   # not shipped at all
    cfg = redacted_config()
    assert cfg["H2O3TPU_S3_ACCESS_KEY"] == "[redacted]"
    assert cfg["H2O3TPU_TLS_CERT"] == "[redacted]"
    assert cfg["H2O3TPU_HEALTH_INTERVAL_SECS"] == "5"
    assert "HOME_SECRET" not in cfg


# -- REST + clients ----------------------------------------------------------

@pytest.fixture
def server(monkeypatch):
    monkeypatch.setenv("H2O3TPU_HEALTH_INTERVAL_SECS", "0.2")
    from h2o3_tpu.api import H2OServer
    s = H2OServer(port=0).start()
    yield s
    s.stop()


def _get_json(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return json.loads(r.read())


def test_rest_health_round_trip(server):
    out = _get_json(server, "/3/Health")
    assert out["__meta"]["schema_type"] == "HealthV3"
    assert out["status"] == "healthy"
    assert set(out["subsystems"]) == set(hm.SUBSYSTEMS)
    assert out["rules"]                            # catalog served


def test_rest_incidents_round_trip(server):
    from h2o3_tpu.utils.incidents import INCIDENTS
    iid = INCIDENTS.open("serving_shed_rate", "serving", DEGRADED,
                         "overload", 0.4, 0.05)
    try:
        out = _get_json(server, "/3/Incidents")
        assert out["__meta"]["schema_type"] == "IncidentsV3"
        assert any(i["id"] == iid for i in out["incidents"])
        one = _get_json(server, f"/3/Incidents/{iid}")
        assert one["__meta"]["schema_type"] == "IncidentV3"
        assert one["rule"] == "serving_shed_rate"
        assert one["context"] and "logs" in one["context"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(server.url + "/3/Incidents/inc_nope")
        assert ei.value.code == 404
    finally:
        INCIDENTS.reset()


def test_rest_bundle_and_python_client(server, tmp_path):
    from h2o3_tpu.api.client import H2OClient
    client = H2OClient(server.url)
    h = client.health()
    assert h["status"] == "healthy"
    assert client.incidents() == [] or isinstance(client.incidents(), list)
    path = client.diagnostics_bundle(str(tmp_path / "diag.tar.gz"))
    members = _unpack(open(path, "rb").read())
    assert set(members) == BUNDLE_MEMBERS
    # POST and GET serve the same artifact class (R's downloader GETs)
    req = urllib.request.Request(server.url + "/3/Diagnostics/bundle",
                                 method="POST")
    with urllib.request.urlopen(req) as r:
        assert r.headers["Content-Type"] == "application/gzip"
        assert set(_unpack(r.read())) == BUNDLE_MEMBERS


def test_server_runs_and_stops_global_evaluator(monkeypatch):
    monkeypatch.setenv("H2O3TPU_HEALTH_INTERVAL_SECS", "0.1")
    from h2o3_tpu.api import H2OServer
    from h2o3_tpu.utils.health import HEALTH
    s = H2OServer(port=0).start()
    try:
        assert HEALTH.running()
    finally:
        s.stop()
    assert not HEALTH.running()


def test_metric_counts_incident_opens():
    from h2o3_tpu.utils.incidents import INCIDENTS_TOTAL
    child = INCIDENTS_TOTAL.labels(rule="memory_leak_growth",
                                   subsystem="memory")
    before = child.value
    log = IncidentLog(capacity=4)
    log.open("memory_leak_growth", "memory", DEGRADED, "leak", 1, 0)
    log.open("memory_leak_growth", "memory", DEGRADED, "leak", 2, 0)  # repeat
    assert child.value == before + 1               # opens count, repeats don't
