"""Sparse path: COO frames, matrix-free sparse GLM, SVMLight end-to-end.

Reference: CXIChunk sparse codecs + SVMLightParser; SURVEY.md §7 hard (c).
"""

import numpy as np
import pytest

from h2o3_tpu.frame.sparse import SparseFrame, SparseMatrix, parse_svmlight_sparse
from h2o3_tpu.frame.vec import Vec
from h2o3_tpu.models.glm import GLM

import jax.numpy as jnp


def _random_sparse(rng, n, k, nnz_per_row, beta=None):
    rows, cols, vals = [], [], []
    for r in range(n):
        cs = rng.choice(k, size=nnz_per_row, replace=False)
        for c in cs:
            rows.append(r)
            cols.append(c)
            vals.append(rng.normal())
    X = SparseMatrix.from_scipy_like(np.asarray(rows), np.asarray(cols),
                                     np.asarray(vals), n, k)
    return X


def test_sparse_products_match_dense(rng):
    X = _random_sparse(rng, 60, 40, 5)
    D = np.asarray(X.to_dense())
    v = rng.normal(size=40).astype(np.float32)
    u = rng.normal(size=60).astype(np.float32)
    np.testing.assert_allclose(np.asarray(X.matvec(jnp.asarray(v))), D @ v,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(X.rmatvec(jnp.asarray(u))), D.T @ u,
                               rtol=1e-4, atol=1e-5)
    w = rng.random(60).astype(np.float32)
    np.testing.assert_allclose(np.asarray(X.col_sq_weighted(jnp.asarray(w))),
                               (w[:, None] * D * D).sum(0), rtol=1e-4,
                               atol=1e-5)


def test_sparse_glm_vs_sklearn(rng):
    n, k = 2000, 300
    X = _random_sparse(rng, n, k, 8)
    D = np.asarray(X.to_dense())
    true_beta = np.zeros(k)
    true_beta[:10] = rng.normal(size=10) * 2
    logits = D @ true_beta + 0.3
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float32)

    sf = SparseFrame(X, {"y": Vec.from_numpy(y)})
    m = GLM(family="binomial", lambda_=1e-3, max_iterations=30).train(
        y="y", training_frame=sf)
    assert m.output["sparse"] is True

    from sklearn.linear_model import LogisticRegression
    from sklearn.metrics import roc_auc_score
    sk = LogisticRegression(C=1.0 / (1e-3 * n), max_iter=200).fit(D, y)
    sk_auc = roc_auc_score(y, sk.decision_function(D))
    assert m.training_metrics.auc == pytest.approx(sk_auc, abs=2e-3)
    ours = np.asarray(m.output["beta"])[:-1]
    cor = np.corrcoef(ours, sk.coef_[0])[0, 1]
    assert cor > 0.98, cor


def test_sparse_glm_gaussian_poisson(rng):
    n, k = 1000, 100
    X = _random_sparse(rng, n, k, 6)
    D = np.asarray(X.to_dense())
    beta = rng.normal(size=k) * 0.3
    yg = (D @ beta + 0.1 * rng.normal(size=n)).astype(np.float32)
    sf = SparseFrame(X, {"y": Vec.from_numpy(yg)})
    m = GLM(family="gaussian", lambda_=1e-4).train(y="y", training_frame=sf)
    pred = m.predict(sf).vec("predict").to_numpy()
    assert np.corrcoef(pred, yg)[0, 1] > 0.98

    lam = np.exp(np.clip(0.3 * (D @ beta), -3, 3))
    yp = rng.poisson(lam).astype(np.float32)
    sfp = SparseFrame(X, {"y": Vec.from_numpy(yp)})
    mp = GLM(family="poisson", lambda_=1e-4, max_iterations=30).train(
        y="y", training_frame=sfp)
    predp = mp.predict(sfp).vec("predict").to_numpy()
    assert np.corrcoef(predp, lam)[0, 1] > 0.5


def test_wide_sparse_10k_fits(rng):
    """The VERDICT 'done' criterion: a 10k-wide sparse train FITS (the
    densified path would need rows*10k*4B dense HBM plus 128-lane padding)."""
    n, k = 5000, 10_000
    X = _random_sparse(rng, n, k, 10)
    informative = rng.choice(k, 40, replace=False)
    bt = np.zeros(k)
    bt[informative] = rng.normal(size=40) * 3
    D_logit = np.zeros(n)
    # sparse logit without densifying in the test either
    d = np.asarray(X.data)[:X.nnz]
    r = np.asarray(X.row)[:X.nnz]
    c = np.asarray(X.col)[:X.nnz]
    np.add.at(D_logit, r, d * bt[c])
    y = (rng.random(n) < 1 / (1 + np.exp(-D_logit))).astype(np.float32)

    sf = SparseFrame(X, {"y": Vec.from_numpy(y)})
    assert sf.density() < 0.002
    m = GLM(family="binomial", lambda_=1e-3, max_iterations=20).train(
        y="y", training_frame=sf)
    assert m.training_metrics.auc > 0.7, m.training_metrics.auc


def test_svmlight_sparse_end_to_end(tmp_path, rng):
    lines = []
    for i in range(300):
        xa, xb = rng.normal(), rng.normal()
        label = 1 if xa - xb > 0 else -1
        # wide indices force the sparse route through import_file too
        lines.append(f"{label} 7:{xa:.4f} 4321:{xb:.4f}")
    path = tmp_path / "wide.svm"
    path.write_text("\n".join(lines) + "\n")

    sf = parse_svmlight_sparse(str(path))
    # sklearn's auto one-based shift: columns 7 and 4321 → width 4322 or the
    # shifted equivalent; either way both features survive
    assert isinstance(sf, SparseFrame) and sf.X.nnz == 600
    m = GLM(family="binomial", max_iterations=20).train(
        y="C0", training_frame=sf)
    assert m.training_metrics.auc > 0.95

    from h2o3_tpu.frame.parse import import_file
    auto = import_file(str(path))
    assert isinstance(auto, SparseFrame)     # >1000 cols stays sparse
