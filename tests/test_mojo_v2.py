"""MOJO v2 (pickle-free) round-trips across the algo families.

VERDICT r2 item 5: the artifact must be loadable with zero unpickling
(reference: ``hex/genmodel/ModelMojoReader.java`` — ini + named binary
blobs, never Java serialization).
"""

import json
import zipfile

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.genmodel import MojoModel


def _roundtrip(model, frame, tmp_path, name):
    p = model.download_mojo(str(tmp_path / f"{name}.mojo"))
    # pickle-free guarantee: only ini/json/npz members, and the npz loads
    # with allow_pickle=False (done inside MojoModel.load)
    with zipfile.ZipFile(p) as z:
        names = set(z.namelist())
        assert names == {"model.ini", "structure.json", "arrays.npz"}, names
        json.loads(z.read("structure.json"))      # pure JSON
    mojo = MojoModel.load(p)
    got = np.asarray(mojo._score_raw(frame))
    want = np.asarray(model._score_raw(frame))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    return mojo


@pytest.fixture
def bin_frame(rng):
    n = 400
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - X[:, 1] > 0)
    cat = rng.integers(0, 4, size=n)
    return Frame.from_arrays({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
        "g": np.array(list("wxyz"), dtype=object)[cat],
        "y": np.array(["no", "yes"], dtype=object)[y.astype(int)]})


def test_mojo_v2_gbm(bin_frame, tmp_path, rng):
    from h2o3_tpu.models.gbm import GBM
    m = GBM(ntrees=5, max_depth=3, seed=1).train(y="y",
                                                 training_frame=bin_frame)
    mojo = _roundtrip(m, bin_frame, tmp_path, "gbm")
    assert mojo.algo == "gbm" and mojo.nclasses == 2


def test_mojo_v2_drf_multinomial(tmp_path, rng):
    from h2o3_tpu.models.gbm import DRF
    n = 400
    X = rng.normal(size=(n, 2))
    y = np.argmax(np.stack([X[:, 0], -X[:, 1], X[:, 0] * 0], 1), axis=1)
    fr = Frame.from_arrays({"a": X[:, 0], "b": X[:, 1],
                            "y": np.array(["u", "v", "w"], dtype=object)[y]})
    m = DRF(ntrees=6, max_depth=4, seed=1).train(y="y", training_frame=fr)
    _roundtrip(m, fr, tmp_path, "drf")


def test_mojo_v2_xgboost(bin_frame, tmp_path):
    from h2o3_tpu.models.xgboost import XGBoost
    m = XGBoost(ntrees=5, max_depth=3, seed=1).train(
        y="y", training_frame=bin_frame)
    _roundtrip(m, bin_frame, tmp_path, "xgb")


def test_mojo_v2_glm(bin_frame, tmp_path):
    from h2o3_tpu.models.glm import GLM
    m = GLM(family="binomial", lambda_=1e-3).train(y="y",
                                                   training_frame=bin_frame)
    _roundtrip(m, bin_frame, tmp_path, "glm")


def test_mojo_v2_deeplearning(bin_frame, tmp_path):
    from h2o3_tpu.models.deeplearning import DeepLearning
    m = DeepLearning(hidden=[8], epochs=2, seed=1).train(
        y="y", training_frame=bin_frame)
    _roundtrip(m, bin_frame, tmp_path, "dl")


def test_mojo_v2_kmeans(bin_frame, tmp_path):
    from h2o3_tpu.models.kmeans import KMeans
    m = KMeans(k=3, seed=1).train(x=["a", "b", "c"],
                                  training_frame=bin_frame)
    _roundtrip(m, bin_frame, tmp_path, "km")


def test_mojo_v2_isotonic(tmp_path, rng):
    from h2o3_tpu.models.isotonic import IsotonicRegression
    n = 300
    x = rng.normal(size=n).astype(np.float32)
    y = (x + 0.2 * rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays({"x": x, "y": y})
    m = IsotonicRegression().train(x=["x"], y="y", training_frame=fr)
    _roundtrip(m, fr, tmp_path, "iso")


def test_mojo_v2_stackedensemble(bin_frame, tmp_path):
    from h2o3_tpu.models.gbm import GBM
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.orchestration import StackedEnsemble
    common = dict(nfolds=3, keep_cross_validation_predictions=True, seed=1)
    m1 = GBM(ntrees=5, max_depth=3, **common).train(y="y",
                                                    training_frame=bin_frame)
    m2 = GLM(family="binomial", **common).train(y="y",
                                                training_frame=bin_frame)
    se = StackedEnsemble(base_models=[m1, m2]).train(y="y",
                                                     training_frame=bin_frame)
    _roundtrip(se, bin_frame, tmp_path, "se")


def test_mojo_v1_pickle_refused(bin_frame, tmp_path):
    """A legacy pickle-payload artifact must be refused by default."""
    import configparser
    import io
    import pickle

    from h2o3_tpu.models.gbm import GBM
    from h2o3_tpu.persist.model_io import host_copy
    m = GBM(ntrees=2, max_depth=2, seed=1).train(y="y",
                                                 training_frame=bin_frame)
    ini = configparser.ConfigParser()
    ini["info"] = {"format": "h2o3_tpu_mojo", "version": "1.0",
                   "algorithm": "gbm", "n_classes": "2"}
    buf = io.StringIO()
    ini.write(buf)
    p = tmp_path / "legacy.mojo"
    with zipfile.ZipFile(p, "w") as z:
        z.writestr("model.ini", buf.getvalue())
        z.writestr("payload.bin", pickle.dumps(host_copy(m)))
    with pytest.raises(ValueError, match="pickle-payload"):
        MojoModel.load(str(p))
    mojo = MojoModel.load(str(p), allow_legacy=True)
    assert mojo.algo == "gbm"
