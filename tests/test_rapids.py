"""Rapids layer tests — munging/math/string/time ops validated against pandas
(reference test model: ``h2o-py/tests/testdir_munging/``)."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.rapids import (cut, hist, ifelse, melt, merge, ops, pivot,
                             rapids, rbind, sort, strings, table, timeops,
                             unique)


@pytest.fixture
def df(rng):
    n = 500
    return pd.DataFrame({
        "g": rng.choice(["a", "b", "c"], size=n),
        "h": rng.choice(["x", "y"], size=n),
        "v": rng.normal(size=n),
        "w": rng.integers(0, 100, size=n).astype(float),
    })


def _frame(df):
    return Frame.from_pandas(df)


# -- elementwise / math ------------------------------------------------------

def test_vec_arithmetic(rng):
    a = rng.normal(size=100)
    b = rng.normal(size=100) + 2.0
    f = Frame.from_arrays({"a": a, "b": b})
    va, vb = f.vec("a"), f.vec("b")
    np.testing.assert_allclose((va + vb).to_numpy(), a + b, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose((va * 2 - 1).to_numpy(), a * 2 - 1, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose((1 / vb).to_numpy(), 1 / b, rtol=1e-5)
    np.testing.assert_allclose((va > vb).to_numpy(), (a > b).astype(float))
    np.testing.assert_allclose(ops.log(vb).to_numpy(), np.log(b), rtol=1e-5)
    np.testing.assert_allclose(ops.cumsum(va).to_numpy()[:100],
                               np.cumsum(a), rtol=1e-4, atol=1e-4)


def test_vec_na_propagation():
    f = Frame.from_arrays({"a": np.array([1.0, np.nan, 3.0])})
    v = f.vec("a")
    out = (v + 1).to_numpy()
    assert out[0] == 2.0 and np.isnan(out[1]) and out[2] == 4.0
    assert v.isna().to_numpy().tolist()[:3] == [0.0, 1.0, 0.0]
    assert ops.vsum(v) == 4.0
    assert ops.vmean(v) == 2.0


def test_cat_compare():
    f = Frame.from_arrays({"g": np.array(["a", "b", "a"], dtype=object)})
    eq = (f.vec("g") == "a").to_numpy()
    assert eq.tolist() == [1.0, 0.0, 1.0]


def test_ifelse_and_cut(rng):
    x = rng.normal(size=200)
    f = Frame.from_arrays({"x": x})
    v = f.vec("x")
    out = ifelse(v > 0, v, 0.0).to_numpy()
    np.testing.assert_allclose(out, np.maximum(x, 0.0), rtol=1e-6)
    c = cut(v, [-10, 0, 10])
    codes = c.to_numpy()
    np.testing.assert_array_equal(codes, (x > 0).astype(np.int32))


def test_quantile(rng):
    x = rng.normal(size=4000)
    f = Frame.from_arrays({"x": x})
    q = f.quantile(probs=[0.25, 0.5, 0.75]).to_pandas()
    np.testing.assert_allclose(q["x"], np.quantile(x, [0.25, 0.5, 0.75]),
                               atol=1e-3)


def test_hist(rng):
    x = rng.normal(size=1000)
    counts, edges = hist(Frame.from_arrays({"x": x}).vec("x"), breaks=10)
    ref, _ = np.histogram(x, bins=edges)
    np.testing.assert_allclose(counts, ref)


# -- sort / filter -----------------------------------------------------------

def test_sort(df):
    f = _frame(df)
    got = sort(f, ["g", "v"]).to_pandas()
    ref = df.sort_values(["g", "v"], kind="stable").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, ref, rtol=1e-5, check_dtype=False)


def test_sort_descending(df):
    f = _frame(df)
    got = f.sort("v", ascending=False).to_pandas()
    ref = df.sort_values("v", ascending=False).reset_index(drop=True)
    np.testing.assert_allclose(got["v"], ref["v"], rtol=1e-6)


def test_filter(df):
    f = _frame(df)
    got = f[f.vec("v") > 0].to_pandas()
    ref = df[df["v"] > 0].reset_index(drop=True)
    pd.testing.assert_frame_equal(got, ref, rtol=1e-5, check_dtype=False)
    assert got.shape[0] == ref.shape[0]


# -- group-by ----------------------------------------------------------------

def test_group_by(df):
    f = _frame(df)
    got = f.group_by("g").mean("v").sum("w").count().get_frame().to_pandas()
    ref = df.groupby("g").agg(mean_v=("v", "mean"), sum_w=("w", "sum"),
                              nrow=("v", "size")).reset_index()
    np.testing.assert_array_equal(got["g"], ref["g"])
    np.testing.assert_allclose(got["mean_v"], ref["mean_v"], rtol=1e-5)
    np.testing.assert_allclose(got["sum_w"], ref["sum_w"], rtol=1e-5)
    np.testing.assert_allclose(got["nrow"], ref["nrow"])


def test_group_by_multikey_median_sd(df):
    f = _frame(df)
    got = f.group_by(["g", "h"]).median("v").sd("v").get_frame().to_pandas()
    ref = df.groupby(["g", "h"])["v"].agg(["median", "std"]).reset_index()
    np.testing.assert_allclose(got["median_v"], ref["median"], rtol=1e-5)
    np.testing.assert_allclose(got["sd_v"], ref["std"], rtol=1e-4)


def test_group_by_numeric_key(rng):
    k = rng.integers(0, 5, size=300).astype(float)
    v = rng.normal(size=300)
    f = Frame.from_arrays({"k": k, "v": v})
    got = f.group_by("k").mean("v").get_frame().to_pandas()
    ref = pd.DataFrame({"k": k, "v": v}).groupby("k")["v"].mean().reset_index()
    np.testing.assert_allclose(got["k"], ref["k"])
    np.testing.assert_allclose(got["mean_v"], ref["v"], rtol=1e-5)


# -- merge -------------------------------------------------------------------

def test_merge_inner(rng):
    left = pd.DataFrame({"k": rng.integers(0, 20, 200).astype(float),
                         "a": rng.normal(size=200)})
    right = pd.DataFrame({"k": np.arange(10).astype(float),
                          "b": np.arange(10) * 10.0})
    got = merge(_frame(left), _frame(right)).to_pandas()
    ref = left.merge(right, on="k", how="inner")
    assert got.shape[0] == ref.shape[0]
    gs = got.sort_values(["k", "a"]).reset_index(drop=True)
    rs = ref.sort_values(["k", "a"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(gs, rs, rtol=1e-5, check_dtype=False)


def test_merge_left_and_duplicates(rng):
    left = pd.DataFrame({"k": np.array(["a", "b", "c", "d"], dtype=object),
                         "a": [1.0, 2.0, 3.0, 4.0]})
    right = pd.DataFrame({"k": np.array(["a", "a", "b"], dtype=object),
                          "b": [10.0, 11.0, 20.0]})
    got = merge(_frame(left), _frame(right), all_x=True).to_pandas()
    ref = left.merge(right, on="k", how="left")
    assert got.shape[0] == ref.shape[0] == 5
    gs = got.sort_values(["k", "b"]).reset_index(drop=True)
    rs = ref.sort_values(["k", "b"]).reset_index(drop=True)
    np.testing.assert_array_equal(gs["k"], rs["k"])
    np.testing.assert_allclose(gs["b"].to_numpy(np.float64),
                               rs["b"].to_numpy(np.float64))


def test_merge_outer_keys():
    left = pd.DataFrame({"k": np.array(["a", "b"], dtype=object), "a": [1.0, 2.0]})
    right = pd.DataFrame({"k": np.array(["b", "z"], dtype=object), "b": [5.0, 9.0]})
    got = merge(_frame(left), _frame(right), all_x=True, all_y=True).to_pandas()
    assert set(got["k"]) == {"a", "b", "z"}
    row_z = got[got["k"] == "z"].iloc[0]
    assert np.isnan(row_z["a"]) and row_z["b"] == 9.0


# -- rbind / unique / table / pivot / melt ----------------------------------

def test_rbind_domain_union():
    f1 = Frame.from_arrays({"g": np.array(["a", "b"], dtype=object), "x": [1.0, 2.0]})
    f2 = Frame.from_arrays({"g": np.array(["c", "a"], dtype=object), "x": [3.0, 4.0]})
    out = rbind(f1, f2)
    assert out.nrows == 4
    assert out.vec("g").domain == ("a", "b", "c")
    assert out.vec("g").labels().tolist() == ["a", "b", "c", "a"]
    np.testing.assert_allclose(out.vec("x").to_numpy(), [1, 2, 3, 4])


def test_unique_and_table(df):
    f = _frame(df)
    u = unique(f, ["g"]).to_pandas()
    assert sorted(u["g"]) == sorted(df["g"].unique())
    t = table(f, ["g"]).to_pandas()
    ref = df["g"].value_counts().sort_index()
    np.testing.assert_allclose(t.sort_values("g")["nrow"], ref.values)


def test_pivot(df):
    f = _frame(df)
    got = pivot(f, index="g", column="h", value="v", agg="mean").to_pandas()
    ref = df.pivot_table(index="g", columns="h", values="v",
                         aggfunc="mean").reset_index()
    for lev in ("x", "y"):
        np.testing.assert_allclose(got[lev], ref[lev], rtol=1e-5)


def test_melt(df):
    f = _frame(df)
    got = melt(f, id_vars=["g"], value_vars=["v", "w"]).to_pandas()
    assert got.shape[0] == 2 * len(df)
    assert set(got["variable"]) == {"v", "w"}
    vs = got[got["variable"] == "v"]["value"].to_numpy()
    np.testing.assert_allclose(np.sort(vs), np.sort(df["v"]), rtol=1e-6)


def test_group_by_na_key_count():
    f = Frame.from_arrays({"k": np.array([1.0, 1.0, np.nan, np.nan, np.nan]),
                           "v": [1.0, 2.0, 3.0, 4.0, 5.0]})
    got = f.group_by("k").count().mean("v").get_frame().to_pandas()
    # NA keys form their own group (reference AstGroup) and count all rows
    assert sorted(got["nrow"]) == [2.0, 3.0]


def test_impute_grouped_and_categorical():
    from h2o3_tpu.rapids import impute
    f = Frame.from_arrays({
        "g": np.array(["a", "a", "b", "b", "b"], dtype=object),
        "x": np.array([1.0, np.nan, 10.0, 20.0, np.nan]),
        "c": np.array(["u", None, "v", "v", None], dtype=object),
    })
    impute(f, "x", method="mean", by=["g"])
    np.testing.assert_allclose(f.vec("x").to_numpy(), [1, 1, 10, 20, 15])
    impute(f, "c", method="mode")
    assert f.vec("c").is_categorical and f.vec("c").domain == ("u", "v")
    assert f.vec("c").labels().tolist() == ["u", "v", "v", "v", "v"]


def test_impute_grouped_all_na_group_falls_back():
    from h2o3_tpu.rapids import impute
    f = Frame.from_arrays({
        "g": np.array(["a", "a", "b", "b"], dtype=object),
        "x": np.array([np.nan, np.nan, 5.0, 7.0]),
    })
    impute(f, "x", method="mean", by=["g"])
    np.testing.assert_allclose(f.vec("x").to_numpy(), [6, 6, 5, 7])


# -- strings / time ----------------------------------------------------------

def test_string_ops():
    f = Frame.from_arrays({"s": np.array(["  Foo ", "BAR", "baz qux"], dtype=object)})
    v = f.vec("s")
    assert v.is_categorical   # short string columns factorize to CAT
    assert strings.toupper(v).labels().tolist() == ["  FOO ", "BAR", "BAZ QUX"]
    assert strings.trim(v).labels().tolist() == ["Foo", "BAR", "baz qux"]
    assert strings.nchar(v).to_numpy().tolist() == [6.0, 3.0, 7.0]
    assert strings.gsub(v, "a", "@").labels().tolist() == ["  Foo ", "BAR", "b@z qux"]
    assert strings.grep(v, "ba", ignore_case=True).to_numpy().tolist() == [0.0, 1.0, 1.0]
    parts = strings.strsplit(v, r"\s+")
    assert parts[0].host_values.tolist() == ["", "BAR", "baz"]


def test_time_ops():
    ts = np.array(["2024-02-29T13:45:30", "1999-12-31T23:59:59"],
                  dtype="datetime64[ms]")
    f = Frame.from_arrays({"t": ts}, types={"t": __import__(
        "h2o3_tpu.frame.types", fromlist=["VecType"]).VecType.TIME})
    v = f.vec("t")
    assert timeops.year(v).to_numpy().tolist() == [2024.0, 1999.0]
    assert timeops.month(v).to_numpy().tolist() == [2.0, 12.0]
    assert timeops.day(v).to_numpy().tolist() == [29.0, 31.0]
    assert timeops.hour(v).to_numpy().tolist() == [13.0, 23.0]
    assert timeops.day_of_week(v).to_numpy().tolist() == [3.0, 4.0]  # Thu, Fri


def test_time_arithmetic_cross_offsets():
    from h2o3_tpu.frame.types import VecType
    # two TIME columns with very different minima → different device offsets
    s = np.array(["2024-01-01T00:00:00", "2024-01-02T00:00:00"],
                 dtype="datetime64[ms]")
    e = np.array(["1999-06-01T00:00:00", "2024-01-02T06:00:00"],
                 dtype="datetime64[ms]")
    f = Frame.from_arrays({"s": s, "e": e},
                          types={"s": VecType.TIME, "e": VecType.TIME})
    dur = (f.vec("e") - f.vec("s")).to_numpy()
    expected = (e - s).astype("timedelta64[ms]").astype(np.float64)
    np.testing.assert_allclose(dur, expected, rtol=1e-6)
    # absolute-epoch scalar comparison
    cutoff = float(np.datetime64("2024-01-01T12:00:00", "ms").astype(np.int64))
    gt = (f.vec("s") > cutoff).to_numpy()
    assert gt.tolist() == [0.0, 1.0]


def test_merge_on_time_key():
    from h2o3_tpu.frame.types import VecType
    lt = np.array(["2024-01-01", "2024-03-01"], dtype="datetime64[ms]")
    rt = np.array(["2024-03-01", "2030-01-01"], dtype="datetime64[ms]")
    left = Frame.from_arrays({"t": lt, "a": [1.0, 2.0]}, types={"t": VecType.TIME})
    right = Frame.from_arrays({"t": rt, "b": [10.0, 20.0]}, types={"t": VecType.TIME})
    got = merge(left, right, by=["t"]).to_pandas()
    assert got.shape[0] == 1
    assert got["a"][0] == 2.0 and got["b"][0] == 10.0


def test_as_date_and_mktime():
    f = Frame.from_arrays({"s": np.array(["2020-01-15", "2021-06-30"], dtype=object)})
    t = timeops.as_date(f.vec("s"), "yyyy-MM-dd")
    assert timeops.year(t).to_numpy().tolist() == [2020.0, 2021.0]
    assert timeops.day(t).to_numpy().tolist() == [15.0, 30.0]
    y = Frame.from_arrays({"y": [2020.0, 2021.0], "m": [1.0, 6.0], "d": [15.0, 30.0]})
    t2 = timeops.mktime(y.vec("y"), y.vec("m"), y.vec("d"))
    np.testing.assert_allclose(t2.to_numpy(), t.to_numpy())


# -- rapids expression engine ------------------------------------------------

def test_rapids_exec(rng):
    from h2o3_tpu.utils.registry import DKV
    x = rng.normal(size=50)
    f = Frame.from_arrays({"a": x, "b": x * 2})
    DKV.put("fr1", f)
    out = rapids("(+ (cols fr1 'a') 1)")
    np.testing.assert_allclose(out.vecs[0].to_numpy(), x + 1, rtol=1e-6)
    assert rapids("(sum (cols fr1 'a'))") == pytest.approx(x.sum(), rel=1e-4)
    assert rapids("(nrow fr1)") == 50.0
    sub = rapids("(rows fr1 (> (cols fr1 'a') 0))")
    assert sub.nrows == int((x > 0).sum())
    tmp = rapids("(tmp= t1 (* (cols fr1 'b') 2))")
    np.testing.assert_allclose(tmp.vecs[0].to_numpy(), x * 4, rtol=1e-6)


def test_rapids_extended_prims():
    """Wider AST coverage (reference: ast/prims/{string,time,advmath,mungers})."""
    from h2o3_tpu.rapids.exec import Session, rapids
    from h2o3_tpu.utils.registry import DKV
    import pandas as pd

    fr = Frame.from_arrays({
        "txt": np.array(["Apple pie", "banana Split", "Cherry"], dtype=object),
        "x": np.array([1.0, 2.0, 16.0], np.float32),
        "t": np.array(["2024-03-05 10:30:00", "2023-12-31 23:59:59",
                       "2020-01-01 00:00:00"], dtype="datetime64[ns]"),
    }, key="rfr")
    DKV.put("rfr", fr)
    s = Session()

    up = rapids('(toupper (cols rfr "txt"))', s)
    assert list(up.vecs[0].labels()) == ["APPLE PIE", "BANANA SPLIT", "CHERRY"]

    n = rapids('(nchar (cols rfr "txt"))', s)
    assert list(n.vecs[0].to_numpy()) == [9.0, 12.0, 6.0]

    g = rapids('(gsub (cols rfr "txt") "a" "_")', s)
    assert g.vecs[0].labels()[1] == "b_n_n_ Split"

    sp = rapids('(strsplit (cols rfr "txt") " ")', s)
    assert sp.ncols == 2

    yr = rapids('(year (cols rfr "t"))', s)
    assert list(yr.vecs[0].to_numpy()) == [2024.0, 2023.0, 2020.0]
    mo = rapids('(month (cols rfr "t"))', s)
    assert list(mo.vecs[0].to_numpy()) == [3.0, 12.0, 1.0]

    cs = rapids('(cumsum (cols rfr "x"))', s)
    assert list(cs.vecs[0].to_numpy()) == [1.0, 3.0, 19.0]

    cf = rapids('(as.character (cols rfr "x"))', s)
    assert cf.vecs[0].type.name == "STR"

    isna = rapids('(is.na (cols rfr "x"))', s)
    assert list(isna.vecs[0].to_numpy()) == [0.0, 0.0, 0.0]

    cn = rapids('(colnames rfr)', s)
    assert cn == ["txt", "x", "t"]

    q = rapids('(quantile rfr [0.5])', s)
    assert q is not None
