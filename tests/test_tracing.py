"""Distributed request tracing tests: span trees, contextvar propagation
through jobs and build pools, W3C traceparent round trips, straggler
attribution, Perfetto export, trace-store bounds, and the TimeLine epoch /
fault-injection satellites (reference: water/TimeLine + TimelineHandler)."""

import json
import threading
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.api import H2OServer
from h2o3_tpu.api.client import H2OClient
from h2o3_tpu.utils import tracing
from h2o3_tpu.utils.tracing import (TRACER, Tracer, critical_path,
                                    format_traceparent, parse_traceparent,
                                    span_tree, to_chrome_trace)

# -- traceparent parsing -----------------------------------------------------


def test_traceparent_round_trip():
    ctx = tracing.SpanContext("ab" * 16, "cd" * 8)
    hdr = format_traceparent(ctx)
    assert hdr == f"00-{'ab' * 16}-{'cd' * 8}-01"
    back = parse_traceparent(hdr)
    assert back.trace_id == ctx.trace_id and back.span_id == ctx.span_id


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-short-span-01",
    f"00-{'0' * 32}-{'cd' * 8}-01",        # all-zero trace id
    f"00-{'ab' * 16}-{'0' * 16}-01",       # all-zero span id
    f"ff-{'ab' * 16}-{'cd' * 8}-01",       # forbidden version
])
def test_traceparent_rejects_invalid(bad):
    assert parse_traceparent(bad) is None


# -- tracer core -------------------------------------------------------------


def test_span_tree_and_critical_path():
    tr = Tracer(capacity=8)
    with tr.span("root", kind="server", root=True) as root:
        tid = root.trace_id
        with tr.span("fast", kind="work"):
            pass
        with tr.span("slow", kind="work"):
            with tr.span("inner", kind="work"):
                pass
    trace = tr.get_trace(tid)
    assert trace["nspans"] == 4 and trace["status"] == "ok"
    roots = span_tree(trace)
    assert len(roots) == 1 and roots[0]["name"] == "root"
    assert {c["name"] for c in roots[0]["children"]} == {"fast", "slow"}
    cp = [e["name"] for e in critical_path(trace)]
    assert cp[0] == "root" and cp[-1] == "inner"


def test_child_spans_silent_without_active_trace():
    tr = Tracer(capacity=4)
    with tr.span("orphan", kind="work") as s:   # no root, no active trace
        assert s is None
    assert tr.list_traces() == []


def test_trace_off_env_disables_roots(monkeypatch):
    monkeypatch.setenv("H2O3TPU_TRACE_OFF", "1")
    tr = Tracer(capacity=4)
    with tr.span("root", kind="server", root=True) as s:
        assert s is None
    assert tr.list_traces() == []


def test_trace_store_ring_eviction():
    tr = Tracer(capacity=4)
    ids = []
    for i in range(7):
        with tr.span(f"t{i}", root=True) as s:
            ids.append(s.trace_id)
    done = tr.list_traces()
    assert len(done) == 4                       # ring bound
    assert [t["name"] for t in done] == ["t6", "t5", "t4", "t3"]  # newest 1st
    with pytest.raises(KeyError):
        tr.get_trace(ids[0])                    # oldest evicted


def test_retention_bridges_root_end_to_worker_start():
    """A Job-style hand-off: the root span ends before the worker begins —
    the captured context must keep the trace open until the worker span
    ends, then finalize it as ONE connected trace."""
    tr = Tracer(capacity=4)
    with tr.span("request", kind="server", root=True) as root:
        tid = root.trace_id
        token = tracing._CURRENT.set(root.context)
        ctx = tr.capture()
        tracing._CURRENT.reset(token)
    assert ctx is not None
    assert tr.get_trace(tid).get("in_progress")   # retained: still open
    assert all(t["trace_id"] != tid for t in tr.list_traces())
    with tr.adopt(ctx, "job:late", kind="job") as jspan:
        assert jspan.parent_id == root.span_id
    trace = tr.get_trace(tid)
    assert {s["name"] for s in trace["spans"]} == {"request", "job:late"}


def test_get_trace_serves_newest_record_for_shared_trace_id():
    """Same-traceparent callers produce several completed records under
    one trace_id; lookups must serve the newest (the substantive one)."""
    tr = Tracer(capacity=8)
    ctx = tracing.SpanContext("ab" * 16, "cd" * 8)
    with tr.span("first", root=True, parent=ctx):
        pass
    with tr.span("second", root=True, parent=ctx):
        with tr.span("work"):
            pass
    got = tr.get_trace("ab" * 16)
    assert {s["name"] for s in got["spans"]} == {"second", "work"}


def test_open_trace_eviction_spares_retained_traces():
    """The open-trace cap must prefer victims nobody retains: evicting a
    Job-retained trace would let the late adopt() recreate the entry and
    emit a duplicate record."""
    tr = Tracer(capacity=16, max_open=2)
    with tr.span("held", root=True) as held:
        held_tid = held.trace_id
        token = tracing._CURRENT.set(held.context)
        ctx = tr.capture()                       # pending retention
        tracing._CURRENT.reset(token)
    # two more open traces push past max_open=2; the retained one survives
    spans = [tr.begin(f"open{i}", root=True) for i in range(3)]
    with tr.adopt(ctx, "job:late", kind="job"):
        pass
    trace = tr.get_trace(held_tid)               # ONE record, connected
    assert {s["name"] for s in trace["spans"]} == {"held", "job:late"}
    assert not trace.get("in_progress")
    for s in spans:
        tr.end(s)


def test_exception_marks_span_error():
    tr = Tracer(capacity=4)
    with pytest.raises(RuntimeError):
        with tr.span("boom", root=True) as s:
            tid = s.trace_id
            raise RuntimeError("nope")
    trace = tr.get_trace(tid)
    assert trace["status"] == "error"
    assert trace["spans"][0]["attrs"]["exception"].startswith("RuntimeError")


# -- chrome trace export -----------------------------------------------------


def test_chrome_export_schema_and_nesting():
    tr = Tracer(capacity=4)
    with tr.span("root", root=True) as root:
        tid = root.trace_id
        with tr.span("a"):
            with tr.span("b"):
                pass
        with tr.span("c"):
            pass
    chrome = to_chrome_trace(tr.get_trace(tid))
    assert chrome["displayTimeUnit"] == "ms"
    xs = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    metas = [e for e in chrome["traceEvents"] if e["ph"] == "M"]
    assert len(xs) == 4
    assert any(m["name"] == "process_name" for m in metas)
    for e in xs:
        assert {"ph", "ts", "dur", "pid", "tid", "name", "cat",
                "args"} <= set(e)
        assert e["ts"] >= 0 and e["dur"] > 0
    # nesting consistency: within one (pid, tid) lane, complete events
    # sorted by ts must properly nest (no partial overlap)
    by_lane: dict = {}
    for e in xs:
        by_lane.setdefault((e["pid"], e["tid"]), []).append(e)
    for lane in by_lane.values():
        lane.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for e in lane:
            while stack and e["ts"] >= stack[-1]["ts"] + stack[-1]["dur"]:
                stack.pop()
            if stack:
                assert e["ts"] + e["dur"] <= \
                    stack[-1]["ts"] + stack[-1]["dur"] + 1e-6
            stack.append(e)


# -- map_reduce partition spans + straggler attribution ----------------------


def test_dispatch_records_partition_spans_and_straggler_attrs(rng, monkeypatch):
    """Full-fidelity partition tracing rides behind H2O3TPU_TRACE_PARTITIONS=1
    (ISSUE 7): with it set, every traced dispatch syncs and stamps shard
    readiness sub-spans + straggler attrs."""
    import jax.numpy as jnp

    from h2o3_tpu.ops.map_reduce import map_reduce

    monkeypatch.setenv("H2O3TPU_TRACE_PARTITIONS", "1")
    x = jnp.asarray(rng.normal(size=64).astype(np.float32))

    def total(shard):
        return shard.sum()

    with TRACER.span("mr_root", root=True) as root:
        tid = root.trace_id
        map_reduce(total, x)
    trace = TRACER.get_trace(tid)
    dispatch = [s for s in trace["spans"] if s["kind"] == "dispatch"]
    parts = [s for s in trace["spans"] if s["kind"] == "partition"]
    assert len(dispatch) == 1 and parts
    d = dispatch[0]
    assert d["name"] == "map_reduce:total"
    assert d["parent_id"] == root.span_id
    for key in ("part_dur_min_ns", "part_dur_max_ns", "straggler",
                "straggler_device"):
        assert key in d["attrs"]
    assert all(p["parent_id"] == d["span_id"] for p in parts)
    assert len(parts) == d["attrs"]["partitions"]
    assert d["attrs"]["sampled"] is True


def test_unsampled_dispatch_skips_partition_spans(rng, monkeypatch):
    """Without H2O3TPU_TRACE_PARTITIONS, an UNSAMPLED traced dispatch must
    not serialize on per-shard readiness: the dispatch span records (the
    tree stays connected) but no partition sub-spans, no straggler attrs,
    and no blocking sync ride along."""
    import sys

    import jax.numpy as jnp

    from h2o3_tpu.ops.map_reduce import map_reduce

    mr = sys.modules["h2o3_tpu.ops.map_reduce"]
    monkeypatch.delenv("H2O3TPU_TRACE_PARTITIONS", raising=False)
    monkeypatch.setattr(mr, "_SAMPLE_EVERY", 10 ** 9)
    next(mr._dispatch_seq)            # burn seq 0 — never the sampled slot
    x = jnp.asarray(rng.normal(size=64).astype(np.float32))

    with TRACER.span("mr_async_root", root=True) as root:
        tid = root.trace_id
        map_reduce(lambda s: s.sum(), x)
    trace = TRACER.get_trace(tid)
    dispatch = [s for s in trace["spans"] if s["kind"] == "dispatch"]
    parts = [s for s in trace["spans"] if s["kind"] == "partition"]
    assert len(dispatch) == 1 and parts == []
    d = dispatch[0]
    assert d["attrs"]["sampled"] is False
    assert "straggler" not in d["attrs"]


def test_straggler_attribution_names_the_slow_shard_not_the_last():
    """Readiness times from sequential blocking are cumulative (monotone),
    so argmax of the raw durations would ALWAYS name the last shard; the
    attribution must key on the incremental wait — where readiness jumps."""
    from h2o3_tpu.ops.map_reduce import _shard_waits

    t0 = 1_000
    # shard 2 straggles: readiness jumps 1_000 → 9_000 there; shards 3-7
    # were already done and add ~nothing
    ends = [1_500, 2_000, 9_000, 9_010, 9_020, 9_030, 9_040, 9_050]
    waits = _shard_waits(ends, t0)
    assert waits.index(max(waits)) == 2
    assert waits[0] == 500 and waits[2] == 7_000 and waits[-1] == 10


def test_effective_nobs_reflects_skip_rows(rng):
    """The per-build map_reduce rollup must count the weights the fit
    actually used: GLM Skip zeroes NA-row weights, so those rows must not
    appear in effective_nobs."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.glm import GLM

    n = 100
    x = rng.normal(size=n).astype(np.float32)
    x[:20] = np.nan                            # 20 rows unusable under Skip
    y = 3 * np.nan_to_num(x) + rng.normal(size=n).astype(np.float32) * 0.1
    fr = Frame.from_arrays({"x": x, "y": y})
    m = GLM(lambda_=0.0, missing_values_handling="Skip").train(
        y="y", training_frame=fr)
    assert m.output["effective_nobs"] == n - 20
    m2 = GLM(lambda_=0.0).train(y="y", training_frame=fr)  # MeanImputation
    assert m2.output["effective_nobs"] == n


def test_fault_injection_marks_span_status(rng, monkeypatch):
    """Satellite: injected drops/delays must surface on the active span —
    fault-injection runs are visible in trace trees."""
    import jax.numpy as jnp

    # retries off: the drop must surface as FaultInjected and leave the
    # span in error state (the retried/absorbed path is covered in
    # tests/test_chaos.py)
    monkeypatch.setenv("H2O3TPU_DISPATCH_RETRIES", "0")

    from h2o3_tpu.ops.map_reduce import map_reduce
    from h2o3_tpu.utils.timeline import FaultInjected, inject_faults

    x = jnp.asarray(rng.normal(size=32).astype(np.float32))

    with TRACER.span("delay_root", root=True) as root:
        tid = root.trace_id
        with inject_faults(delay_ms=3, delay_rate=1.0):
            map_reduce(lambda s: s.sum(), x)
    trace = TRACER.get_trace(tid)
    delayed = [s for s in trace["spans"] if s["status"] == "delayed"]
    assert delayed and delayed[0]["kind"] == "dispatch"
    assert delayed[0]["attrs"]["delay_ns"] > 0
    assert trace["status"] == "delayed"

    with TRACER.span("drop_root", root=True) as root:
        tid = root.trace_id
        with inject_faults(drop_rate=1.0):
            with pytest.raises(FaultInjected):
                map_reduce(lambda s: s.sum(), x)
    trace = TRACER.get_trace(tid)
    errs = [s for s in trace["spans"] if s["status"] == "error"]
    assert errs and any("drop:map_reduce" == s["attrs"].get("fault")
                        for s in errs)
    assert trace["status"] == "error"


# -- TimeLine epoch + fault duration satellites ------------------------------


def test_timeline_clear_epoch_drops_stale_events():
    from h2o3_tpu.utils.timeline import TimeLine

    tl = TimeLine(size=8)
    for i in range(5):
        tl.record("test", f"old{i}")
    tl.clear()
    assert tl.snapshot() == []               # nothing stale served
    tl.record("test", "new0")
    tl.record("test", "new1")
    whats = [e["what"] for e in tl.snapshot()]
    assert whats == ["new0", "new1"]         # old-epoch slots invisible


def test_timeline_clear_is_race_safe_under_hammer():
    from h2o3_tpu.utils.timeline import TimeLine

    tl = TimeLine(size=32)
    stop = threading.Event()
    bad: list = []

    def reader():
        while not stop.is_set():
            for e in tl.snapshot():
                if not e["what"].startswith("ep"):
                    bad.append(e)

    th = threading.Thread(target=reader)
    th.start()
    for epoch in range(50):
        for i in range(40):                  # wraps the ring each epoch
            tl.record("test", f"ep{epoch}_{i}")
        tl.clear()
    stop.set()
    th.join()
    assert not bad


def test_delay_fault_records_true_duration(rng):
    import jax.numpy as jnp

    from h2o3_tpu.ops.map_reduce import map_reduce
    from h2o3_tpu.utils.timeline import TIMELINE, inject_faults

    TIMELINE.clear()
    x = jnp.asarray(rng.normal(size=32).astype(np.float32))
    with inject_faults(delay_ms=5, delay_rate=1.0) as inj:
        map_reduce(lambda s: s.sum(), x)
    assert inj.delayed == 1
    faults = [e for e in TIMELINE.snapshot() if e["kind"] == "fault"]
    assert faults and faults[0]["what"] == "delay:map_reduce"
    assert faults[0]["dur_ns"] >= 5_000_000   # the TRUE stall, not 0


# -- REST surface ------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    s = H2OServer(port=0).start()
    yield s
    s.stop()


def _get(server, path, headers=None):
    req = urllib.request.Request(server.url + path, headers=headers or {})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read()), dict(r.headers)


def test_response_carries_traceparent_and_trace_completes(server):
    _, headers = _get(server, "/3/Capabilities")
    tp = parse_traceparent(headers.get("traceparent"))
    assert tp is not None
    trace = TRACER.get_trace(tp.trace_id)
    assert trace["name"] == "GET /3/Capabilities"   # renamed to the pattern
    [root] = [s for s in trace["spans"] if s["parent_id"] is None]
    assert root.get("attrs", {}).get("http_status") == 200


def test_polling_routes_are_ephemeral(server):
    """High-frequency GETs (job polls, /metrics scrapes) must not churn
    the completed-trace ring — they propagate a traceparent but their
    finished traces are discarded."""
    _, headers = _get(server, "/3/Ping")
    tp = parse_traceparent(headers["traceparent"])
    assert tp is not None                      # propagation still works
    import time
    time.sleep(0.05)
    with pytest.raises(KeyError):
        TRACER.get_trace(tp.trace_id)          # ...but nothing was stored
    assert all(t["trace_id"] != tp.trace_id for t in TRACER.list_traces())


def test_incoming_traceparent_joins_callers_trace(server):
    caller = f"00-{'ab' * 16}-{'cd' * 8}-01"
    _, headers = _get(server, "/3/Ping", headers={"traceparent": caller})
    tp = parse_traceparent(headers["traceparent"])
    assert tp.trace_id == "ab" * 16           # joined, not re-minted
    assert tp.span_id != "cd" * 8             # our root span, fresh id
    trace = TRACER.get_trace("ab" * 16)
    [root] = [s for s in trace["spans"] if s["kind"] == "server"]
    assert root["parent_id"] == "cd" * 8      # caller's span is our parent


def test_concurrent_requests_get_distinct_trace_ids(server):
    """Contextvar isolation under the server's thread-per-request model:
    parallel requests must never share a trace."""
    results: list = []
    lock = threading.Lock()

    def hit():
        _, headers = _get(server, "/3/Ping")
        with lock:
            results.append(parse_traceparent(headers["traceparent"]).trace_id)

    threads = [threading.Thread(target=hit) for _ in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 16 and len(set(results)) == 16


def test_health_polling_routes_are_ephemeral(server):
    """ISSUE 15 satellite: the ops-plane polling endpoints (/3/Health,
    /3/Incidents) are scraped like /metrics and /3/Jobs — a health
    scraper must not churn the completed-trace ring. Propagation still
    works: each reply carries a traceparent, and sending one records the
    call in the caller's trace as usual."""
    import time
    for path in ("/3/Health", "/3/Incidents"):
        _, headers = _get(server, path)
        tp = parse_traceparent(headers["traceparent"])
        assert tp is not None                  # propagation still works
        time.sleep(0.05)
        with pytest.raises(KeyError):
            TRACER.get_trace(tp.trace_id)      # ...but nothing was stored
        assert all(t["trace_id"] != tp.trace_id
                   for t in TRACER.list_traces())
    # an explicit caller traceparent opts the call INTO recording
    caller = f"00-{'5e' * 16}-{'7a' * 8}-01"
    _, headers = _get(server, "/3/Health", headers={"traceparent": caller})
    assert parse_traceparent(headers["traceparent"]).trace_id == "5e" * 16
    trace = TRACER.get_trace("5e" * 16)
    assert any(s["name"] == "GET /3/Health" for s in trace["spans"])


def test_unmatched_routes_are_ephemeral(server):
    """A scanner hitting unknown paths must not churn the trace ring."""
    import urllib.error
    req = urllib.request.Request(server.url + "/no/such/route")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req)
    tp = parse_traceparent(ei.value.headers.get("traceparent"))
    assert tp is not None
    import time
    time.sleep(0.05)
    assert all(t["trace_id"] != tp.trace_id for t in TRACER.list_traces())


def test_traces_endpoints_and_client_accessors(server):
    client = H2OClient(server.url)
    client.request("GET", "/3/Capabilities")
    tid = client.last_trace_id
    assert tid
    summaries = client.traces()
    assert any(t["trace_id"] == tid for t in summaries)
    assert all("spans" not in t for t in summaries)   # list stays light
    full = client.trace(tid)
    assert full["trace_id"] == tid and full["critical_path"]
    assert full["tree"][0]["name"] == "GET /3/Capabilities"
    export = client.trace_export(tid)
    assert "traceEvents" in export
    with pytest.raises(RuntimeError, match="404"):
        client.trace("f" * 32)


def test_rest_to_job_to_partition_trace_is_connected(server, tmp_path,
                                                     monkeypatch):
    """Tentpole: one connected span tree spanning REST → Job (worker
    thread) → model fit → map_reduce dispatch → partition spans (partition
    sub-spans need H2O3TPU_TRACE_PARTITIONS=1 since the async-dispatch
    refactor — sampled-only by default)."""
    monkeypatch.setenv("H2O3TPU_TRACE_PARTITIONS", "1")
    client = H2OClient(server.url)
    rng = np.random.default_rng(7)
    x = rng.normal(size=200)
    csv = tmp_path / "t.csv"
    csv.write_text("x,y\n" + "\n".join(
        f"{v:.4f},{3 * v + rng.normal() * .1:.4f}" for v in x))
    frame_key = client.import_file(str(csv))
    out = client.request("POST", "/3/ModelBuilders/glm",
                         {"training_frame": frame_key, "response_column": "y"})
    tid = client.last_trace_id
    assert out["job"]["trace_id"] == tid      # pollers correlate via JobV3
    client._poll(out["job"]["key"]["name"])
    trace = _wait_trace(tid)
    kinds = {s["kind"] for s in trace["spans"]}
    assert {"server", "job", "model", "iteration", "dispatch",
            "partition"} <= kinds
    ids = {s["span_id"] for s in trace["spans"]}
    roots = [s for s in trace["spans"] if s["parent_id"] is None]
    assert len(roots) == 1                    # ONE connected tree
    assert all(s["parent_id"] in ids for s in trace["spans"]
               if s["parent_id"] is not None)
    assert client.trace(tid)["critical_path"]


def _wait_trace(trace_id, timeout=10.0):
    """The job span closes slightly after the job flips DONE; poll the
    tracer until the trace finalizes."""
    import time
    deadline = time.time() + timeout
    while True:
        try:
            trace = TRACER.get_trace(trace_id)
            if not trace.get("in_progress"):
                return trace
        except KeyError:
            pass
        if time.time() > deadline:
            raise AssertionError(f"trace {trace_id} never completed")
        time.sleep(0.05)


@pytest.mark.slow
def test_automl_trace_acceptance(server, tmp_path, monkeypatch):
    """Acceptance: a completed REST AutoML run yields ONE connected span
    tree spanning REST → leaderboard jobs → per-model map_reduce partition
    spans, with a non-empty critical path and at least one straggler
    attribution attr; its Perfetto export is valid Chrome trace JSON."""
    monkeypatch.setenv("H2O3TPU_TRACE_PARTITIONS", "1")
    client = H2OClient(server.url)
    rng = np.random.default_rng(11)
    n = 150
    X = rng.normal(size=(n, 3))
    y = np.where(X[:, 0] + 0.5 * X[:, 1] > 0, "a", "b")
    csv = tmp_path / "aml.csv"
    csv.write_text("x0,x1,x2,y\n" + "\n".join(
        f"{r[0]:.4f},{r[1]:.4f},{r[2]:.4f},{lab}"
        for r, lab in zip(X, y)))
    frame_key = client.import_file(str(csv))
    out = client.request("POST", "/99/AutoMLBuilder",
                         {"training_frame": frame_key, "response_column": "y",
                          "max_models": 2, "nfolds": 0,
                          "project_name": "trace_accept"})
    tid = client.last_trace_id
    client._poll(out["job"]["key"]["name"], poll_secs=0.3)
    trace = _wait_trace(tid, timeout=30.0)

    kinds = {s["kind"] for s in trace["spans"]}
    assert {"server", "job", "orchestration", "build", "model",
            "dispatch", "partition"} <= kinds
    ids = {s["span_id"] for s in trace["spans"]}
    roots = [s for s in trace["spans"] if s["parent_id"] is None]
    assert len(roots) == 1, "AutoML trace must be ONE connected tree"
    assert all(s["parent_id"] in ids for s in trace["spans"]
               if s["parent_id"] is not None)
    full = client.trace(tid)
    assert full["critical_path"], "critical path must be non-empty"
    assert any("straggler" in s["attrs"] for s in trace["spans"]), \
        "at least one straggler-attribution attr"

    export = client.trace_export(tid)
    assert json.loads(json.dumps(export))     # valid JSON round trip
    xs = [e for e in export["traceEvents"] if e["ph"] == "X"]
    assert xs and all({"ph", "ts", "dur", "pid", "tid", "name"} <= set(e)
                      for e in xs)
