"""KMeans / PCA / SVD / GLRM / NaiveBayes tests (reference test model:
h2o-py ``testdir_algos/{kmeans,pca,svd,glrm,naivebayes}/pyunit_*``)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models import GLRM, KMeans, NaiveBayes, PCA, SVD


def _cluster_data(rng, n=900):
    centers = np.array([[0, 0], [10, 0], [0, 10]], float)
    yi = rng.integers(0, 3, size=n)
    X = centers[yi] + rng.normal(size=(n, 2))
    return Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1]}), X, yi, centers


# -- KMeans ------------------------------------------------------------------

def test_kmeans_recovers_centers(rng):
    f, X, yi, centers = _cluster_data(rng)
    m = KMeans(k=3, standardize=False, seed=1, max_iterations=20,
               ).train(training_frame=f)
    got = np.sort(m.centers(), axis=0)
    want = np.sort(centers, axis=0)
    np.testing.assert_allclose(got, want, atol=0.3)
    assert m.tot_withinss() < m.totss()
    assert abs(m.totss() - (m.tot_withinss() + m.betweenss())) < 1e-3 * m.totss()


def test_kmeans_predict_partitions(rng):
    f, X, yi, _ = _cluster_data(rng)
    m = KMeans(k=3, seed=1).train(training_frame=f)
    pred = m.predict(f).vec("predict").to_numpy()
    # each true cluster maps to one predicted label (purity ~ 1)
    purity = 0
    for c in range(3):
        labs, cnts = np.unique(pred[yi == c], return_counts=True)
        purity += cnts.max()
    assert purity / len(yi) > 0.98


@pytest.mark.parametrize("init", ["Random", "PlusPlus", "Furthest"])
def test_kmeans_inits(rng, init):
    f, *_ = _cluster_data(rng, n=600)
    m = KMeans(k=3, init=init, seed=5).train(training_frame=f)
    assert m.tot_withinss() / m.totss() < 0.1


def test_kmeans_standardize_destandardizes_centers(rng):
    n = 500
    x0 = rng.normal(scale=100.0, size=n)
    x1 = rng.normal(scale=0.01, size=n)
    f = Frame.from_arrays({"x0": x0, "x1": x1})
    m = KMeans(k=2, standardize=True, seed=1).train(training_frame=f)
    c = m.centers()
    assert np.abs(c[:, 0]).max() > 1.0  # back on the raw scale


# -- PCA ---------------------------------------------------------------------

def test_pca_matches_numpy(rng):
    n = 400
    Z = rng.normal(size=(n, 3)) @ np.array([[3, 0, 0], [1, 1, 0], [0, 0, 0.2]])
    f = Frame.from_arrays({f"x{i}": Z[:, i] for i in range(3)})
    m = PCA(k=3, transform="DEMEAN").train(training_frame=f)
    Zc = Z - Z.mean(axis=0)
    cov = Zc.T @ Zc / (n - 1)
    evals = np.sort(np.linalg.eigvalsh(cov))[::-1]
    np.testing.assert_allclose(m.output["eigenvalues"], evals, rtol=0.02)
    # scores should reproduce the variance structure
    S = m.predict(f)
    s0 = S.vec("PC1").to_numpy()
    assert abs(np.var(s0, ddof=1) - evals[0]) / evals[0] < 0.05


def test_pca_transform_standardize(rng):
    n = 300
    Z = np.column_stack([rng.normal(scale=100, size=n), rng.normal(size=n)])
    f = Frame.from_arrays({"a": Z[:, 0], "b": Z[:, 1]})
    m = PCA(k=2, transform="STANDARDIZE").train(training_frame=f)
    # standardized: total variance = #cols
    assert abs(m.output["total_variance"] - 2.0) < 0.1


# -- SVD ---------------------------------------------------------------------

def test_svd_matches_numpy(rng):
    n = 300
    Z = rng.normal(size=(n, 4))
    f = Frame.from_arrays({f"x{i}": Z[:, i] for i in range(4)})
    m = SVD(nv=4, transform="NONE").train(training_frame=f)
    # singular values of the padded device matrix equal those of Z
    ref = np.linalg.svd(Z, compute_uv=False)
    np.testing.assert_allclose(np.sort(m.output["d"]), np.sort(ref), rtol=0.01)
    U = m.predict(f)
    u1 = U.vec("u1").to_numpy()
    assert abs(np.linalg.norm(u1) - 1.0) < 0.05


# -- GLRM --------------------------------------------------------------------

def test_glrm_low_rank_reconstruction(rng):
    n, k = 400, 2
    A = rng.normal(size=(n, k))
    Y = rng.normal(size=(k, 5))
    X = A @ Y + 0.01 * rng.normal(size=(n, 5))
    f = Frame.from_arrays({f"x{i}": X[:, i] for i in range(5)})
    m = GLRM(k=2, max_iterations=50, seed=3).train(training_frame=f)
    R = m.predict(f)
    rec = np.column_stack([R.vec(i).to_numpy() for i in range(5)])[:n]
    rel = np.linalg.norm(rec - X) / np.linalg.norm(X)
    assert rel < 0.05, rel
    arch = m.archetypes()
    assert arch.shape == (2, 5)
    T = m.transform_frame(f)
    assert T.ncols == 2


def test_glrm_missing_values_imputation(rng):
    n, k = 300, 2
    A = rng.normal(size=(n, k))
    Y = rng.normal(size=(k, 4))
    X = A @ Y
    Xo = X.copy()
    miss = rng.uniform(size=X.shape) < 0.2
    Xo[miss] = np.nan
    f = Frame.from_arrays({f"x{i}": Xo[:, i] for i in range(4)})
    m = GLRM(k=2, max_iterations=80, seed=3).train(training_frame=f)
    R = m.predict(f)
    rec = np.column_stack([R.vec(i).to_numpy() for i in range(4)])[:n]
    # imputed cells should approximate the true low-rank values
    err = np.abs(rec[miss] - X[miss]).mean()
    scale = np.abs(X[miss]).mean()
    assert err < 0.2 * scale, (err, scale)


def test_glrm_nonneg_regularizer(rng):
    X = np.abs(rng.normal(size=(200, 4)))
    f = Frame.from_arrays({f"x{i}": X[:, i] for i in range(4)})
    m = GLRM(k=2, regularization_x="NonNegative", regularization_y="NonNegative",
             gamma_x=0.01, gamma_y=0.01, max_iterations=30, init="Random",
             seed=3).train(training_frame=f)
    assert m.archetypes().min() >= 0.0
    assert np.asarray(m.output["x_factor"]).min() >= 0.0


# -- NaiveBayes --------------------------------------------------------------

def test_naive_bayes_gaussian(rng):
    n = 1200
    yi = rng.integers(0, 2, size=n)
    X = np.where(yi[:, None] == 1, 2.5, -2.5) + rng.normal(size=(n, 3))
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = np.array(["a", "b"], dtype=object)[yi]
    f = Frame.from_arrays(cols)
    m = NaiveBayes().train(y="y", training_frame=f)
    assert m.training_metrics.auc > 0.99


def test_naive_bayes_categorical_laplace(rng):
    n = 1000
    yi = rng.integers(0, 2, size=n)
    # feature correlated with class
    g = np.where(rng.uniform(size=n) < 0.8, yi, 1 - yi)
    f = Frame.from_arrays({
        "g": np.array(["u", "v"], dtype=object)[g],
        "y": np.array(["a", "b"], dtype=object)[yi]})
    m = NaiveBayes(laplace=1.0).train(y="y", training_frame=f)
    acc = (m.predict(f).vec("predict").to_numpy()[:n] == yi).mean()
    assert acc > 0.75
    probs = np.exp(np.asarray(m.output["cat_logp"][0]))
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=0.01)


def test_naive_bayes_mixed_with_missing(rng):
    n = 800
    yi = rng.integers(0, 2, size=n)
    x = np.where(yi == 1, 2.0, -2.0) + rng.normal(size=n)
    x[rng.uniform(size=n) < 0.1] = np.nan
    g = np.where(rng.uniform(size=n) < 0.7, yi, 1 - yi)
    garr = np.array(["u", "v"], dtype=object)[g]
    garr[rng.uniform(size=n) < 0.1] = None
    f = Frame.from_arrays({"x": x, "g": garr,
                           "y": np.array(["a", "b"], dtype=object)[yi]})
    m = NaiveBayes().train(y="y", training_frame=f)
    assert m.training_metrics.auc > 0.9


def test_kmeans_estimate_k_finds_three_clusters(rng):
    # 6-D so the reference cutoff min(0.02 + 10/n + 2.5/F^2, 0.8) ~ 0.10;
    # in 2-D even perfectly separated symmetric clusters cannot beat it
    n = 900
    centers = np.zeros((3, 6))
    centers[0, 0] = centers[1, 1] = centers[2, 2] = 20.0
    yi = rng.integers(0, 3, size=n)
    X = centers[yi] + rng.normal(size=(n, 6))
    f = Frame.from_arrays({f"x{j}": X[:, j] for j in range(6)})
    m = KMeans(k=8, estimate_k=True, standardize=False, max_iterations=20,
               ).train(training_frame=f)
    assert m.output["centers_std"].shape[0] == 3


def test_pca_normalize_uses_range_not_sigma(rng):
    n = 400
    x0 = rng.uniform(-1, 1, size=n)
    x1 = rng.uniform(-100, 100, size=n)
    f = Frame.from_arrays({"x0": x0, "x1": x1})
    m = PCA(k=2, transform="NORMALIZE").train(training_frame=f)
    di = m.data_info
    rng0 = x0.max() - x0.min()
    rng1 = x1.max() - x1.min()
    np.testing.assert_allclose(di.num_mul, [1 / rng0, 1 / rng1], rtol=1e-5)


def test_pca_unsupported_method_raises(rng):
    f, *_ = _cluster_data(rng, n=120)
    with pytest.raises(NotImplementedError):
        PCA(k=1, pca_method="Power").train(training_frame=f)
