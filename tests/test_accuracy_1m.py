"""Large-scale accuracy parity (VERDICT r3 weak #5; reference tier-4
harness ``h2o-test-accuracy/``).

Pins model QUALITY at the scale the perf story is told at: 1M-row
HIGGS-shaped training against scikit-learn's CPU reference implementations
(HistGradientBoosting = the ``tree_method=hist`` family the reference's
XGBoost rides; LogisticRegression for GLM). Zero-egress image, so the data
is synthetic but nonlinear (interaction + quadratic terms) — a broken
histogram/split/leaf path shows up as an AUC gap far above the pinned
tolerance, which a toy 600-row iris test can never expose.

Measured baseline at pinning time: sklearn HGB 0.81211, this GBM 0.81280
(delta +0.0007); tolerance leaves 3e-3 headroom for platform jitter.
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame

N_TRAIN = 1_000_000
N_TEST = 200_000
TOL = 3e-3


def _higgs_like(n, seed):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 28)).astype(np.float32)
    logit = (X[:, :4] @ np.float32([1.2, -0.8, 0.5, 0.3])
             + 0.6 * X[:, 4] * X[:, 5] - 0.4 * X[:, 6] ** 2 + 0.4)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.int32)
    return X, y


def _frame(X, y):
    cols = {f"x{i}": X[:, i] for i in range(X.shape[1])}
    cols["y"] = np.where(y == 1, "s", "b")
    return Frame.from_arrays(cols)


@pytest.fixture(scope="module")
def data():
    Xtr, ytr = _higgs_like(N_TRAIN, 1)
    Xte, yte = _higgs_like(N_TEST, 2)
    return Xtr, ytr, Xte, yte


def test_gbm_1m_auc_parity_vs_sklearn_hist(data):
    from sklearn.ensemble import HistGradientBoostingClassifier
    from sklearn.metrics import roc_auc_score

    from h2o3_tpu.models.gbm import GBM

    Xtr, ytr, Xte, yte = data
    hgb = HistGradientBoostingClassifier(
        max_iter=30, max_depth=6, max_bins=64, learning_rate=0.1,
        early_stopping=False, random_state=0)
    hgb.fit(Xtr, ytr)
    sk_auc = roc_auc_score(yte, hgb.predict_proba(Xte)[:, 1])

    m = GBM(ntrees=30, max_depth=6, nbins=64, learn_rate=0.1, seed=7).train(
        y="y", training_frame=_frame(Xtr, ytr))
    perf = m.model_performance(_frame(Xte, yte))
    auc = float(perf.auc)
    assert sk_auc > 0.78                      # the task is actually learnable
    assert auc >= sk_auc - TOL, \
        f"GBM holdout AUC {auc:.5f} vs sklearn hist {sk_auc:.5f}"


def test_glm_1m_auc_parity_vs_sklearn_logreg(data):
    from sklearn.linear_model import LogisticRegression
    from sklearn.metrics import roc_auc_score

    from h2o3_tpu.models.glm import GLM

    Xtr, ytr, Xte, yte = data
    lr = LogisticRegression(C=1e4, max_iter=200)
    lr.fit(Xtr[:: 5], ytr[:: 5])              # logreg converges fine on 200k
    sk_auc = roc_auc_score(yte, lr.predict_proba(Xte)[:, 1])

    m = GLM(family="binomial", lambda_=1e-6, max_iterations=30).train(
        y="y", training_frame=_frame(Xtr, ytr))
    perf = m.model_performance(_frame(Xte, yte))
    auc = float(perf.auc)
    assert auc >= sk_auc - 1e-3, \
        f"GLM holdout AUC {auc:.5f} vs sklearn logreg {sk_auc:.5f}"
