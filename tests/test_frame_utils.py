"""CreateFrame / interaction / tf_idf / rebalance tests
(reference: hex/createframe, fvec/CreateInteractions, hex/tfidf,
fvec/RebalanceDataSet)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.frame.utils import create_frame, interaction, rebalance, tf_idf


def test_create_frame_types_and_counts():
    fr = create_frame(rows=500, cols=10, categorical_fraction=0.3,
                      integer_fraction=0.2, binary_fraction=0.1,
                      factors=5, missing_fraction=0.05, has_response=True,
                      response_factors=3, seed=11)
    assert fr.nrows == 500
    assert fr.ncols == 11                      # response + 10
    t = fr.types
    assert t["response"] == "enum"
    assert sum(1 for v in t.values() if v == "enum") == 4   # 3 cats + response
    # missing values present at roughly the requested rate
    na = sum(fr.vec(c).na_cnt() for c in fr.names if c != "response")
    assert na > 0


def test_create_frame_constant():
    fr = create_frame(rows=100, cols=3, randomize=False, value=7.0,
                      categorical_fraction=0, integer_fraction=0,
                      binary_fraction=0, missing_fraction=0, seed=1)
    assert np.allclose(fr.vec("C1").to_numpy(), 7.0)


def test_interaction_pairwise():
    fr = Frame.from_arrays({
        "a": np.array(["x", "x", "y", "y", "x"]),
        "b": np.array(["1", "2", "1", "2", "1"]),
        "c": np.array(["p", "p", "q", "p", "p"]),
    })
    out = interaction(fr, ["a", "b", "c"], pairwise=True)
    assert out.names == ["a_b", "a_c", "b_c"]
    lab = out.vec("a_b").labels()
    assert list(lab) == ["x_1", "x_2", "y_1", "y_2", "x_1"]


def test_interaction_max_factors_and_na():
    fr = Frame.from_arrays({
        "a": np.array(["x", "x", "x", "y", "z", None], dtype=object),
        "b": np.array(["1", "1", "2", "1", "2", "1"], dtype=object),
    })
    out = interaction(fr, ["a", "b"], max_factors=2)
    v = out.vec("a_b")
    assert "other" in v.domain
    assert len(v.domain) == 3                  # 2 kept + other
    assert v.labels()[5] is None               # NA component → NA interaction


def test_tf_idf():
    fr = Frame.from_arrays({
        "doc": np.array([0, 0, 1, 1, 1], np.float32),
        "word": np.array(["cat", "cat", "cat", "dog", "dog"], dtype=object),
    })
    out = tf_idf(fr, "doc", "word", preprocess=False)
    rows = {(float(d), w): (tf, idf) for d, w, tf, idf in zip(
        out.vec("doc").to_numpy(), out.vec("word").to_numpy(),
        out.vec("TF").to_numpy(), out.vec("IDF").to_numpy())}
    assert rows[(0.0, "cat")][0] == 2.0
    assert rows[(1.0, "dog")][0] == 2.0
    # idf = log((N+1)/(df+1)); cat appears in both docs → log(3/3)=0
    assert rows[(0.0, "cat")][1] == pytest.approx(0.0)
    assert rows[(1.0, "dog")][1] == pytest.approx(np.log(3 / 2), rel=1e-5)


def test_tf_idf_preprocess_splits_text():
    fr = Frame.from_arrays({
        "doc": np.array([0, 1], np.float32),
        "text": np.array(["the cat sat", "the dog"], dtype=object),
    })
    out = tf_idf(fr, "doc", "text", preprocess=True, case_sensitive=False)
    words = set(out.vec("text").to_numpy())
    assert words == {"the", "cat", "sat", "dog"}


def test_rebalance_preserves_data(rng):
    fr = Frame.from_arrays({
        "x": rng.normal(size=37).astype(np.float32),
        "c": rng.choice(["a", "b"], size=37),
    })
    rb = rebalance(fr)
    assert rb.nrows == 37
    np.testing.assert_allclose(rb.vec("x").to_numpy(), fr.vec("x").to_numpy())
    assert list(rb.vec("c").labels()) == list(fr.vec("c").labels())


def test_import_sql_table(tmp_path, rng):
    """SQL ingest (reference: water/jdbc SQLManager; h2o-py import_sql_table)."""
    import sqlite3
    db = tmp_path / "t.db"
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE pts (x REAL, label TEXT)")
    rows = [(float(i) / 10, "a" if i % 2 else "b") for i in range(50)]
    conn.executemany("INSERT INTO pts VALUES (?, ?)", rows)
    conn.commit(); conn.close()

    from h2o3_tpu.frame.sql import import_sql_select, import_sql_table
    fr = import_sql_table(f"sqlite:{db}", "pts")
    assert fr.nrows == 50 and set(fr.names) == {"x", "label"}
    assert fr.vec("x").mean() == pytest.approx(2.45, abs=1e-5)
    assert fr.vec("label").type.name in ("CAT", "STR")

    fr2 = import_sql_table(f"sqlite:{db}", "pts", fetch_mode="DISTRIBUTED",
                           num_chunks=3)
    assert fr2.nrows == 50

    fr3 = import_sql_select(f"sqlite:{db}", "SELECT x FROM pts WHERE x > 2.0")
    assert fr3.nrows == 29     # x in {2.1 … 4.9}

    with pytest.raises(ValueError, match="unsupported connection url"):
        import_sql_table("postgres://h", "pts")
