"""Timeline / profiling / fault-injection tests (reference: water/TimeLine,
JStackCollectorTask, -random_udp_drop fault injection)."""

import json
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.api import H2OServer
from h2o3_tpu.utils.timeline import (TIMELINE, FaultInjected, TimeLine,
                                     cpu_ticks, inject_faults, jstack)


def test_ring_buffer_wraps():
    tl = TimeLine(size=8)
    for i in range(20):
        tl.record("test", f"e{i}")
    evs = tl.snapshot()
    assert len(evs) == 8
    assert evs[0]["what"] == "e12"     # oldest surviving
    assert evs[-1]["what"] == "e19"
    ns = [e["ns"] for e in evs]
    assert ns == sorted(ns)


def test_map_reduce_records_events(rng):
    import jax.numpy as jnp
    from h2o3_tpu.ops.map_reduce import map_reduce
    TIMELINE.clear()
    x = jnp.asarray(rng.normal(size=64).astype(np.float32))

    def total(shard):
        return shard.sum()

    map_reduce(total, x)
    evs = TIMELINE.snapshot()
    assert any(e["kind"] == "collective" and e["what"] == "total" for e in evs)


def test_jstack_sees_main_thread():
    traces = jstack()
    names = [t["name"] for t in traces]
    assert "MainThread" in names
    main = next(t for t in traces if t["name"] == "MainThread")
    assert "test_jstack_sees_main_thread" in main["stack"]


def test_cpu_ticks_reads_proc():
    t = cpu_ticks()
    assert "cpu" in t and len(t["cpu"]) >= 4


def test_fault_injection_drop(rng, monkeypatch):
    import jax.numpy as jnp
    from h2o3_tpu.ops.map_reduce import map_reduce
    # retries disabled: the drop must pass through as exactly ONE injected
    # fault (retry absorption has its own coverage in tests/test_chaos.py)
    monkeypatch.setenv("H2O3TPU_DISPATCH_RETRIES", "0")
    x = jnp.asarray(rng.normal(size=64).astype(np.float32))
    with inject_faults(drop_rate=1.0) as inj:
        with pytest.raises(FaultInjected):
            map_reduce(lambda s: s.sum(), x)
    assert inj.dropped == 1
    # outside the context the fault machinery is off
    map_reduce(lambda s: s.sum(), x)


def test_fault_injection_job_carries_failure(rng, monkeypatch):
    """A dropped collective inside training surfaces as a failed Job, not a
    crashed process: UDP drops ARE retried now, so a 100% drop rate
    exhausts the budget into a structured DispatchFailed — which the Job
    carries like any other build failure."""
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.models import Job
    from h2o3_tpu.ops.map_reduce import DispatchFailed
    monkeypatch.setenv("H2O3TPU_DISPATCH_BACKOFF_MS", "1")
    n = 128
    X = rng.normal(size=(n, 2)).astype(np.float32)
    y = np.where(X[:, 0] > 0, "a", "b")
    fr = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "y": y})
    # rollups on the response ran at frame build; inject now
    builder = GLM(family="binomial", lambda_=0.0)
    with inject_faults(drop_rate=1.0):
        try:
            builder.train(y="y", training_frame=fr)
            trained = True
        except (FaultInjected, DispatchFailed):
            trained = False
    # whether GLM's path used explicit map_reduce or implicit jnp reductions,
    # the process must survive; a clean retrain must then succeed
    m = GLM(family="binomial", lambda_=0.0).train(y="y", training_frame=fr)
    assert m.training_metrics.auc > 0.9
    assert trained in (True, False)


@pytest.fixture
def server():
    s = H2OServer(port=0).start()
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return json.loads(r.read())


def test_rest_observability_endpoints(server):
    tl = _get(server, "/3/Timeline")
    assert tl["__meta"]["schema_type"] == "TimelineV3"
    js = _get(server, "/3/JStack")
    assert any("MainThread" == t["name"] for t in js["traces"])
    prof = _get(server, "/3/Profiler?depth=2")
    assert prof["counts"] and prof["stacktraces"]
    cpu = _get(server, "/3/WaterMeterCpuTicks/0")
    assert "cpu" in cpu["cpu_ticks"]
    io = _get(server, "/3/WaterMeterIo")
    assert isinstance(io["persist_stats"], dict)


def test_scope_temp_key_cleanup():
    """Scope (reference water/Scope.java): keys created inside are removed
    at exit unless kept; nesting hands kept keys to the outer scope."""
    import numpy as np
    from h2o3_tpu import Frame
    from h2o3_tpu.utils import scope
    from h2o3_tpu.utils.registry import DKV

    def put(name):
        DKV.put(name, Frame.from_arrays({"a": np.arange(3, dtype=np.float32)}))

    with scope.scope("kept"):
        put("kept")
        put("tmp1")
        with scope.scope():
            put("tmp2")
        assert "tmp2" not in DKV          # inner scope cleaned up
        assert "tmp1" in DKV
    assert "tmp1" not in DKV
    assert "kept" in DKV                  # explicitly kept survives
    DKV.remove("kept")


def test_nps_notebook_roundtrip(tmp_path):
    """NodePersistentStorage (reference water/api/NodePersistentStorage):
    Flow notebooks save/list/load/delete across server instances."""
    import json
    import os
    import urllib.request

    from h2o3_tpu.api import H2OServer

    os.environ["H2O3TPU_NPS_DIR"] = str(tmp_path)
    try:
        s = H2OServer(port=0).start()
        try:
            doc = json.dumps({"version": 1, "fields": {"path": "/d.csv"}})
            urllib.request.urlopen(urllib.request.Request(
                f"{s.url}/3/NodePersistentStorage/notebook/myflow",
                data=doc.encode(), method="POST",
                headers={"Content-Type": "application/json"}))
            with urllib.request.urlopen(
                    f"{s.url}/3/NodePersistentStorage/notebook") as r:
                lst = json.loads(r.read())
            assert [e["name"] for e in lst["entries"]] == ["myflow"]
        finally:
            s.stop()
        # persistence survives a server restart (disk-backed)
        s2 = H2OServer(port=0).start()
        try:
            with urllib.request.urlopen(
                    f"{s2.url}/3/NodePersistentStorage/notebook/myflow") as r:
                back = json.loads(r.read())
            assert back["fields"]["path"] == "/d.csv"
            urllib.request.urlopen(urllib.request.Request(
                f"{s2.url}/3/NodePersistentStorage/notebook/myflow",
                method="DELETE"))
            with urllib.request.urlopen(
                    f"{s2.url}/3/NodePersistentStorage/notebook") as r:
                assert json.loads(r.read())["entries"] == []
        finally:
            s2.stop()
    finally:
        del os.environ["H2O3TPU_NPS_DIR"]
