"""Extensions SPI + listener service (reference: water/ExtensionManager.java,
AbstractH2OExtension.java, ListenerService.java, RestApiExtension)."""

import json
import os
import sys
import urllib.request

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api import H2OServer
from h2o3_tpu.models.gbm import GBM
from h2o3_tpu.utils import extensions as ext
from h2o3_tpu.utils.registry import DKV


@pytest.fixture(autouse=True)
def clean_registry():
    ext.reset()
    yield
    ext.reset()


def _frame(rng, key="ext_fr"):
    fr = Frame.from_arrays(
        {"a": rng.normal(size=120).astype(np.float32),
         "y": rng.normal(size=120).astype(np.float32)}, key=key)
    DKV.put(fr.key, fr)
    return fr


def test_listener_receives_model_events(rng):
    events = []
    ext.add_listener(lambda e, **kw: events.append((e, kw)))
    fr = _frame(rng)
    GBM(ntrees=2, max_depth=2).train(y="y", training_frame=fr)
    names = [e for e, _ in events]
    assert "model_build_start" in names and "model_build_end" in names
    end = [kw for e, kw in events if e == "model_build_end"][0]
    assert end["algo"] == "gbm" and end["model"] in DKV


def test_broken_listener_does_not_break_training(rng):
    def bad(e, **kw):
        raise RuntimeError("boom")
    ext.add_listener(bad)
    fr = _frame(rng, "ext_fr2")
    m = GBM(ntrees=2, max_depth=2).train(y="y", training_frame=fr)
    assert m.training_metrics is not None


class _ProbeExt(ext.H2OExtension):
    name = "probe"

    def __init__(self):
        self.inited = 0
        self.events = []

    def init(self):
        self.inited += 1

    def routes(self):
        def handler(h):
            h._reply({"__meta": {"schema_type": "ProbeV3"}, "probe": "ok"})
        return [(r"/3/Probe", "GET", handler)]

    def on_event(self, event, **info):
        self.events.append(event)


def test_extension_rest_route_and_capabilities():
    probe = ext.register(_ProbeExt())
    s = H2OServer(port=0).start()
    try:
        assert probe.inited == 1
        assert "cloud_up" in probe.events
        with urllib.request.urlopen(s.url + "/3/Probe") as r:
            assert json.loads(r.read())["probe"] == "ok"
        with urllib.request.urlopen(s.url + "/3/Capabilities") as r:
            caps = json.loads(r.read())["capabilities"]
        assert {"name": "probe", "module": "extension"} in caps
    finally:
        s.stop()


def test_broken_extension_init_is_disabled():
    class Bad(ext.H2OExtension):
        name = "bad"

        def init(self):
            raise RuntimeError("no")

    ext.register(Bad())
    ext.init_all()
    assert all(e.name != "bad" for e in ext.extensions())


def test_env_discovery(tmp_path):
    """$H2O3TPU_EXTENSIONS modules are imported and self-register (the
    ServiceLoader analog)."""
    mod = tmp_path / "my_h2o_ext.py"
    mod.write_text(
        "from h2o3_tpu.utils import extensions as ext\n"
        "class E(ext.H2OExtension):\n"
        "    name = 'from-env'\n"
        "ext.register(E())\n")
    sys.path.insert(0, str(tmp_path))
    os.environ["H2O3TPU_EXTENSIONS"] = "my_h2o_ext"
    try:
        ext.load_env_extensions()
        assert any(e.name == "from-env" for e in ext.extensions())
    finally:
        sys.path.remove(str(tmp_path))
        del os.environ["H2O3TPU_EXTENSIONS"]
        sys.modules.pop("my_h2o_ext", None)
