"""Elastic local-SGD training (ISSUE 12): membership state machine,
straggler/fault ejection, catch-up joins, quorum, determinism, and the
REST/telemetry surface (docs/RELIABILITY.md "Elastic training").

The chaos scenarios run at toy scale on the 8-virtual-device cloud; every
DL config shares one shape (n=512, hidden=[8], B=64, local_steps=1, k=2 slices) so the
`_train_epochs` megastep compiles once per device slice for the whole
module."""

import threading
import time

import numpy as np
import pytest

import jax

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.deeplearning import DeepLearning
from h2o3_tpu.models.job import Job
from h2o3_tpu.parallel import elastic
from h2o3_tpu.parallel.elastic import (ACTIVE, EJECTED, JOINING, SUSPECT,
                                       ELASTIC_STATS, ElasticGroup)
from h2o3_tpu.utils.timeline import (FaultInjected, FaultInjector,
                                     inject_faults, worker_scope)


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("H2O3TPU_DISPATCH_BACKOFF_MS", "1")


@pytest.fixture(autouse=True)
def _drain_workers():
    yield
    # a stall-released worker may still be finishing a discarded dispatch;
    # never let it bleed into the next test (or interpreter exit)
    elastic.drain(60.0)


def _frame(rng, n=512, key=None):
    X = rng.normal(size=(n, 6)).astype(np.float32)
    logit = X[:, :2] @ np.array([1.5, -1.0], np.float32)
    cols = {f"x{i}": X[:, i] for i in range(6)}
    cols["y"] = np.where(rng.random(n) < 1.0 / (1.0 + np.exp(-logit)),
                         "yes", "no")
    fr = Frame.from_arrays(cols, key=key)
    return fr


def _train(fr, *, elastic_k, epochs=2, local_steps=1, seed=5, **kw):
    b = DeepLearning(hidden=[8], epochs=epochs, elastic=elastic_k,
                     local_steps=local_steps, mini_batch_size=64,
                     seed=seed, **kw)
    model = b.train(y="y", training_frame=fr)
    return model, b


def _logloss(model, fr):
    raw = np.asarray(jax.device_get(model._score_raw(fr)))[: fr.nrows]
    y = np.asarray(jax.device_get(fr.vec("y").data))[: fr.nrows]
    p = np.clip(raw[np.arange(len(y)), y.astype(int)], 1e-7, 1.0)
    return float(-np.log(p).mean())


# -- determinism (acceptance: fixed membership reproducibility) --------------

def test_fixed_membership_determinism(rng):
    fr = _frame(rng)
    m1, b1 = _train(fr, elastic_k=2)
    m2, b2 = _train(fr, elastic_k=2)
    for a, b in zip(jax.tree.leaves(m1.output["params"]),
                    jax.tree.leaves(m2.output["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the loss series is averaged in wid order too — bit-equal, not close
    assert m1.output["score_history"] == m2.output["score_history"]
    el = m1.output["elastic"]
    assert el["rounds"] == 2 and el["ejections"] == []
    assert b1.job.workers_ejected == 0
    assert b1.job.status == Job.DONE
    # elastic differs from the single-program path by construction (local
    # SGD averages, SPMD averages per step) — the contract is determinism
    # at fixed membership, not parity with elastic=0


def test_elastic_metrics_and_workers_view(rng):
    fr = _frame(rng)
    m, b = _train(fr, elastic_k=2)
    rows = [r for r in ELASTIC_STATS.rows() if r["group"] == b.job.key]
    assert {r["worker"] for r in rows} == {0, 1}
    for r in rows:
        assert r["state"] == ACTIVE
        assert r["round"] == m.output["elastic"]["rounds"]
        assert r["last_heartbeat_ago_ms"] >= 0
        assert r["devices"] and r["shards"]
    from h2o3_tpu.utils.telemetry import METRICS
    names = {m_["name"]: m_ for m_ in METRICS.snapshot()}
    assert names["h2o3_elastic_rounds_total"]["value"] >= 2
    assert names["h2o3_elastic_workers"]["value"] >= 0


# -- chaos: kill 1 of k mid-epoch (ISSUE acceptance) -------------------------

def test_kill_one_worker_completes_with_ejection(rng, monkeypatch):
    """Stalling worker 1 dead mid-run must finish the build with
    workers_ejected=1 (reason: heartbeat), the dead worker's shard
    reassigned to the survivor, final quality within tolerance of the
    uninterrupted (k-1)-worker run, and the wall bounded far below the
    stall — the dead worker degrades throughput instead of stalling the
    cloud. (The strict slowdown < 1/k gate runs in bench `extra.elastic`
    on real hardware, where wall clocks mean something.)"""
    monkeypatch.setenv("H2O3TPU_ELASTIC_ROUND_DEADLINE_SECS", "2.0")
    monkeypatch.setenv("H2O3TPU_ELASTIC_LEASE_SECS", "1.0")
    fr = _frame(rng)
    # uninterrupted k-1 = 1 worker reference
    ref, _ = _train(fr, elastic_k=1, epochs=3)
    t0 = time.monotonic()
    # after=4 = one full round of sub-shard dispatches (n=512, k=2, B=64
    # → 4 sub-shards/worker): worker 1 stalls on its FIRST round-2
    # dispatch — round 1 carries the compile-grace deadline by design, so
    # deadline-clocked kills target round 2+
    with inject_faults(worker_rates={1: {"stall_rate": 1.0,
                                         "stall_ms": 60_000,
                                         "after": 4}}) as inj:
        m, b = _train(fr, elastic_k=2, epochs=3)
    wall = time.monotonic() - t0
    assert inj.stalled == 1
    assert b.job.status == Job.DONE
    assert b.job.workers_ejected == 1
    el = m.output["elastic"]
    assert el["shards_per_worker"] == 4
    assert el["ejections_by_reason"] == {"heartbeat": 1}
    assert el["per_worker"][1]["state"] == EJECTED
    # shard reassignment: the survivor picked up the dead worker's
    # sub-shards — full data coverage survives the ejection
    assert sorted(el["per_worker"][0]["shards"]) == list(range(8))
    # completed while the stalled worker was still held — killing 1 of k
    # cost bounded time, nowhere near the 60s stall
    assert wall < 45.0, f"kill cost {wall:.0f}s — the dead worker stalled us"
    # quality within tolerance of the uninterrupted k-1-worker run
    ll_killed, ll_ref = _logloss(m, fr), _logloss(ref, fr)
    assert ll_killed < max(1.5 * ll_ref, ll_ref + 0.1), \
        f"killed-run logloss {ll_killed:.3f} vs k-1 ref {ll_ref:.3f}"
    # the JobV3 surface carries the membership decay
    from h2o3_tpu.api import schemas
    jv = schemas.job_v3(b.job.key, b.job)
    assert jv["workers_ejected"] == 1


def test_retry_exhaustion_ejects_worker_not_build(rng, monkeypatch):
    """An exhausted dispatch-retry budget inside a worker's round is a
    MEMBERSHIP event (ops/map_reduce.ejection_scope): the worker ejects
    with reason retry_exhausted and the build completes on the survivor —
    not a FAILED job (the pre-elastic behavior)."""
    monkeypatch.setenv("H2O3TPU_DISPATCH_RETRIES", "1")
    fr = _frame(rng)
    with inject_faults(worker_rates={0: {"drop_rate": 1.0, "after": 1}}):
        m, b = _train(fr, elastic_k=2, epochs=3)
    assert b.job.status == Job.DONE
    assert b.job.workers_ejected == 1
    el = m.output["elastic"]
    assert el["ejections_by_reason"] == {"retry_exhausted": 1}
    assert el["ejections"][0]["worker"] == 0
    assert "DispatchFailed" in el["ejections"][0]["error"]
    # the map_reduce ejection hook recorded WHICH dispatch site burned
    # the budget — known at the site even if the exception gets wrapped
    assert el["ejections"][0]["site"] == "dl_epochs"
    assert sorted(el["per_worker"][1]["shards"]) == list(range(8))


def test_quorum_loss_cancels_with_partial(rng, monkeypatch):
    """Live workers below H2O3TPU_ELASTIC_MIN_WORKERS cancel the build
    through the Job.keep_partial path: the job reads CANCELLED and the
    last averaged model IS the partial result."""
    monkeypatch.setenv("H2O3TPU_ELASTIC_MIN_WORKERS", "2")
    monkeypatch.setenv("H2O3TPU_DISPATCH_RETRIES", "1")
    fr = _frame(rng)
    with inject_faults(worker_rates={0: {"drop_rate": 1.0, "after": 1}}):
        m, b = _train(fr, elastic_k=2, epochs=3)
    assert b.job.status == Job.CANCELLED
    assert b.job.workers_ejected == 1
    assert m is not None and m.output["elastic"]["rounds"] >= 1
    assert m.predict(fr).nrows == fr.nrows     # the partial model scores


# -- group-level state machine ----------------------------------------------

def _quick_group(k=3, **kw):
    kw.setdefault("round_deadline_secs", 0.5)
    kw.setdefault("lease_secs", 10.0)
    g = ElasticGroup(k, scheduler=None, **kw).start()
    # round 1 carries the compile-grace deadline by design; deadline
    # behavior under test starts at round 2
    g.run_round(1, {w: (lambda w=w: w) for w in g.live_workers()})
    return g


def test_straggler_suspect_then_catch_up_join():
    """A worker that blows the round deadline but keeps heartbeating goes
    SUSPECT; its late result is DISCARDED and it re-enters as a catch-up
    join, ACTIVE again at the next boundary."""
    g = _quick_group()
    try:
        slow_release = threading.Event()

        def slow():
            # straggle past the deadline, heartbeating all the way
            for _ in range(40):
                if slow_release.wait(timeout=0.05):
                    break
                g.heartbeat(2)
            return "late"

        r2 = g.run_round(2, {0: lambda: "a", 1: lambda: "b", 2: slow})
        assert set(r2) == {0, 1}               # slow missed the boundary
        assert g.membership()[2] == SUSPECT
        slow_release.set()
        # the late post lands, flips it to JOINING (result discarded)
        deadline = time.monotonic() + 5.0
        while g.membership()[2] != JOINING and time.monotonic() < deadline:
            time.sleep(0.02)
        assert g.membership()[2] == JOINING
        r3 = g.run_round(3, {0: lambda: "a", 1: lambda: "b"})
        assert set(r3) == {0, 1}
        assert g.membership()[2] == ACTIVE     # admitted at the boundary
        r4 = g.run_round(4, {w: (lambda w=w: w) for w in g.live_workers()})
        assert set(r4) == {0, 1, 2}
    finally:
        g.shutdown()


def test_oscillating_straggler_ejected_on_second_strike():
    """A worker slow enough to miss deadlines but fast enough to post late
    each time (miss → late-post → rejoin → miss) must not cycle forever:
    the strike counter survives the catch-up join, and the second
    consecutive deadline miss ejects it (docs: blows the deadline twice)."""
    g = _quick_group()
    try:
        def slow_once(release):
            def thunk():
                release.wait(timeout=1.2)      # ~2.4x the 0.5s deadline
                return "late"
            return thunk

        r2_gate = threading.Event()
        g.run_round(2, {0: lambda: "a", 1: slow_once(r2_gate),
                        2: lambda: "c"})
        assert g.membership()[1] == SUSPECT    # strike 1
        deadline = time.monotonic() + 5.0
        while g.membership()[1] != JOINING and time.monotonic() < deadline:
            time.sleep(0.02)
        assert g.membership()[1] == JOINING    # late post, catch-up join
        g.run_round(3, {0: lambda: "a", 2: lambda: "c"})
        assert g.membership()[1] == ACTIVE     # admitted — but on notice
        r4_gate = threading.Event()
        g.run_round(4, {0: lambda: "a", 1: slow_once(r4_gate),
                        2: lambda: "c"})
        # second consecutive miss: ejected outright, no oscillation
        assert g.membership()[1] == EJECTED
        assert g.ejections[0]["reason"] == "deadline"
    finally:
        g.shutdown()


def test_chronic_straggler_ejected_on_second_boundary():
    """SUSPECT + still missing at the NEXT boundary (lease fresh) ejects
    with reason `deadline` — one grace round, then membership moves on."""
    g = _quick_group()
    try:
        hold = threading.Event()

        def stuck():
            while not hold.wait(timeout=0.05):
                g.heartbeat(1)                  # alive, just way too slow
            return "way late"

        g.run_round(2, {0: lambda: "a", 1: stuck, 2: lambda: "c"})
        assert g.membership()[1] == SUSPECT
        g.run_round(3, {0: lambda: "a", 2: lambda: "c"})
        assert g.membership()[1] == EJECTED
        assert g.ejections[0]["reason"] == "deadline"
        # its shard was reassigned to a survivor
        owned = [s for w in (0, 2) for s in g.owned_shards(w)]
        assert sorted(owned) == [0, 1, 2]
    finally:
        hold.set()
        g.shutdown()


def test_dead_worker_ejected_by_heartbeat_lease():
    g = _quick_group(lease_secs=0.2)
    try:
        hold = threading.Event()
        g.run_round(2, {0: lambda: "a",
                        1: lambda: hold.wait(timeout=30) or "dead",
                        2: lambda: "c"})
        # silent past the 0.2s lease at a 0.5s deadline: gone immediately
        assert g.membership()[1] == EJECTED
        assert g.ejections[0]["reason"] == "heartbeat"
    finally:
        hold.set()
        g.shutdown()


def test_explicit_leave_and_rejoin_gets_shard_back():
    """eject() models a worker LEAVING; request_join() re-admits it at the
    next boundary with a shard stolen back from the most-loaded survivor
    (the catch-up clone is by construction: every round starts from the
    broadcast average)."""
    g = _quick_group()
    try:
        g.eject(2, reason="left")
        assert g.membership()[2] == EJECTED
        g.run_round(2, {w: (lambda w=w: w) for w in g.live_workers()})
        assert sorted(s for w in (0, 1) for s in g.owned_shards(w)) \
            == [0, 1, 2]
        g.request_join(2)
        assert g.membership()[2] == JOINING
        g.run_round(3, {w: (lambda w=w: w) for w in g.live_workers()})
        assert g.membership()[2] == ACTIVE
        assert len(g.owned_shards(2)) == 1     # stolen back from a donor
        assert sorted(s for w in (0, 1, 2) for s in g.owned_shards(w)) \
            == [0, 1, 2]
    finally:
        g.shutdown()


def test_summary_and_stats_rows_shape():
    g = _quick_group(k=2)
    try:
        g.run_round(2, {0: lambda: 1, 1: lambda: 2})
        s = g.summary()
        assert s["workers"] == 2 and s["live"] == 2 and s["rounds"] == 2
        rows = [r for r in ELASTIC_STATS.rows() if r["group"] == g.group_id]
        assert {r["worker"] for r in rows} == {0, 1}
        assert all(r["state"] == ACTIVE for r in rows)
    finally:
        g.shutdown()


# -- chaos harness satellites ------------------------------------------------

def test_stall_fault_is_bounded_and_releasable():
    inj = FaultInjector(stall_rate=1.0, stall_ms=30_000)
    done = threading.Event()

    def victim():
        inj.maybe_fault("site")
        done.set()

    t = threading.Thread(target=victim, daemon=True)
    t0 = time.monotonic()
    t.start()
    time.sleep(0.1)
    assert not done.is_set()                   # held on the gate
    inj.release_stalls()                       # bounded hold that RELEASES
    assert done.wait(timeout=5.0)
    assert time.monotonic() - t0 < 5.0
    assert inj.stalled == 1 and inj.delayed == 0


def test_worker_scoped_faults_hit_exactly_one_worker():
    inj = FaultInjector(worker_rates={1: {"drop_rate": 1.0}})
    with worker_scope(0):
        inj.maybe_fault("dl_epochs")           # peer runs clean
    with worker_scope(1):
        with pytest.raises(FaultInjected):
            inj.maybe_fault("dl_epochs")
    inj.maybe_fault("dl_epochs")               # unscoped context runs clean
    assert inj.dropped == 1


def test_worker_scoped_after_counts_that_workers_calls():
    inj = FaultInjector(worker_rates={1: {"drop_rate": 1.0, "after": 2}})
    with worker_scope(0):
        for _ in range(5):
            inj.maybe_fault("dl_epochs")       # advances only site counter
    with worker_scope(1):
        inj.maybe_fault("dl_epochs")           # worker call 1: armed=False
        inj.maybe_fault("dl_epochs")           # worker call 2: armed=False
        with pytest.raises(FaultInjected):
            inj.maybe_fault("dl_epochs")       # worker call 3: fires


# -- REST / clients ----------------------------------------------------------

def test_rest_elastic_build_and_workers_view(rng):
    from h2o3_tpu.api.client import H2OClient
    from h2o3_tpu.api.server import H2OServer
    from h2o3_tpu.utils.registry import DKV

    fr = _frame(rng, key="elastic_rest_fr")
    DKV.put(fr.key, fr)
    s = H2OServer(port=0).start()
    try:
        c = H2OClient(s.url)
        model = c.train("deeplearning", "elastic_rest_fr", y="y",
                        hidden=[8], epochs=2, elastic=2, local_steps=1,
                        mini_batch_size=64, seed=5)
        assert model["algo"] == "deeplearning"
        # /3/Cloud workers membership view round-trips through the client
        rows = c.workers()
        assert rows and {"worker", "group", "state", "round",
                         "last_heartbeat_ago_ms"} <= set(rows[0])
        assert any(r["state"] == ACTIVE for r in rows)
        # JobV3 carries workers_ejected (0 on a clean run)
        jobs = c.jobs()
        dl = [j for j in jobs if "deeplearning" in j["description"]]
        assert all(j["workers_ejected"] == 0 for j in dl)
        # the elastic metrics are live on /metrics
        text = c.metrics_text()
        assert "h2o3_elastic_rounds_total" in text
        assert "h2o3_elastic_workers" in text
    finally:
        s.stop()
