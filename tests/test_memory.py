"""Memory observability tests: MemoryMeter per-key accounting, host/device
sampling + watermarks, leak-detector semantics, per-span attribution, the
`/3/Memory` endpoint (reconciliation against frame chunk nbytes), real
numbers in `/3/Cloud`, and the client accessors (docs/OBSERVABILITY.md
"Memory")."""

import json
import re
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.api import H2OServer
from h2o3_tpu.api.client import H2OClient
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.utils.memory import (MEMORY, LeakDetector, array_tree_bytes,
                                   device_stats, host_stats, value_kind_bytes)
from h2o3_tpu.utils.registry import DKV


def _frame(nrows=2000, ncols=3, seed=0):
    rng = np.random.default_rng(seed)
    return Frame.from_arrays(
        {f"x{i}": rng.normal(size=nrows).astype(np.float32)
         for i in range(ncols)})


# -- byte measurement --------------------------------------------------------


def test_vec_and_frame_nbytes():
    fr = _frame(nrows=1000, ncols=2)
    for v in fr.vecs:
        # padded device chunk: plen rows of float32
        assert v.nbytes == v.plen * 4
    assert fr.nbytes == sum(v.nbytes for v in fr.vecs)


def test_frame_nbytes_counts_host_payloads():
    fr = Frame.from_arrays({"s": np.array(["a", "bb", "ccc"] * 10,
                                          dtype=object)})
    assert fr.nbytes > 0                      # host object array, no device


def test_value_kind_bytes_dispatch():
    fr = _frame()
    kind, b = value_kind_bytes(fr)
    assert kind == "frame" and b == fr.nbytes
    from h2o3_tpu.frame.parse import RawFile
    kind, b = value_kind_bytes(RawFile(b"x" * 100, name="f.csv"))
    assert kind == "raw" and b == 100
    from h2o3_tpu.models.job import Job
    kind, b = value_kind_bytes(Job("j"))
    assert kind == "job" and b == 0


def test_array_tree_bytes_walks_models():
    from h2o3_tpu.frame.vec import Vec
    fr = _frame(nrows=500, ncols=4, seed=1)
    y = (np.asarray(fr.vec("x0").to_numpy()) > 0)
    fr.add("y", Vec.from_numpy(np.where(y, "a", "b")))
    from h2o3_tpu.models.glm import GLM
    m = GLM(family="binomial", max_iterations=3).train(y="y",
                                                       training_frame=fr)
    kind, b = value_kind_bytes(m)
    assert kind == "model" and b > 0
    assert m.output["artifact_bytes"] == pytest.approx(b, rel=0.2)


# -- registration at put/remove ----------------------------------------------


def test_dkv_registration_keeps_totals_current():
    fr = _frame()
    DKV.put("memtest_frame", fr)
    total, by_kind, n = MEMORY.dkv_totals()
    assert by_kind.get("frame", 0) >= fr.nbytes
    assert any(r["key"] == "memtest_frame" and r["bytes"] == fr.nbytes
               for r in MEMORY.top_keys(50))
    DKV.remove("memtest_frame")
    assert all(r["key"] != "memtest_frame" for r in MEMORY.top_keys(50))


def test_refresh_catches_inplace_mutation():
    fr = _frame(nrows=1000, ncols=1)
    DKV.put("mut_frame", fr)
    b0 = next(r["bytes"] for r in MEMORY.top_keys(50)
              if r["key"] == "mut_frame")
    from h2o3_tpu.frame.vec import Vec
    fr.add("extra", Vec.from_numpy(np.zeros(1000, np.float32)))
    MEMORY.refresh()
    b1 = next(r["bytes"] for r in MEMORY.top_keys(50)
              if r["key"] == "mut_frame")
    assert b1 > b0


# -- host/device sampling + watermarks ---------------------------------------


def test_host_stats_reads_proc():
    h = host_stats()
    assert h["rss_bytes"] > 0
    assert h["rss_peak_bytes"] >= h["rss_bytes"] // 2
    assert h["total_bytes"] > h["available_bytes"] > 0


def test_device_stats_fallback_accounts_live_arrays():
    fr = _frame(nrows=4000, ncols=2, seed=2)
    d = device_stats()
    assert d["source"] in ("memory_stats", "live_arrays")
    assert d["bytes_in_use"] >= fr.nbytes
    assert d["devices"]


def test_watermarks_are_monotonic():
    MEMORY.sample()
    w0 = MEMORY.watermarks
    _fr = _frame(nrows=50_000, ncols=2, seed=3)
    MEMORY.sample()
    w1 = MEMORY.watermarks
    assert w1["device_peak_bytes"] >= w0["device_peak_bytes"]
    assert w1["host_rss_peak_bytes"] >= w0["host_rss_peak_bytes"]
    del _fr


# -- leak detector ------------------------------------------------------------


def test_leak_detector_flags_idle_growth_and_recovery():
    det = LeakDetector(sweeps=3, min_bytes=100)
    keyed = {"big": ("frame", 1000), "small": ("frame", 10)}
    det.observe(dict(keyed), {"big", "small"})
    for _ in range(3):
        det.observe(dict(keyed), set())       # nobody touches anything
    flagged = {f["key"]: f for f in det.report()}
    assert "big" in flagged and flagged["big"]["reasons"] == ["idle"]
    assert "small" not in flagged             # under the byte floor
    # an access resets the idle streak
    det.observe(dict(keyed), {"big"})
    assert not det.report()


def test_leak_detector_flags_monotone_growth():
    det = LeakDetector(sweeps=2, min_bytes=100)
    det.observe({"grow": ("frame", 100)}, {"grow"})
    det.observe({"grow": ("frame", 200)}, {"grow"})
    det.observe({"grow": ("frame", 300)}, {"grow"})
    [f] = det.report()
    assert f["key"] == "grow" and "growing" in f["reasons"]
    # removal drops the state entirely
    det.observe({}, set())
    assert not det.report()


def test_meter_leak_sweep_end_to_end():
    fr = _frame(nrows=200_000, ncols=2, seed=4)     # > 1 MiB floor
    DKV.put("leaky_frame", fr)
    sweeps = MEMORY.detector.sweeps
    for _ in range(sweeps + 1):
        MEMORY.leak_sweep()
    rep = MEMORY.leak_report()
    assert any(f["key"] == "leaky_frame" and "idle" in f["reasons"]
               for f in rep["flagged"])
    # a DKV get between sweeps resets the idle streak
    DKV.get("leaky_frame")
    MEMORY.leak_sweep()
    assert not any(f["key"] == "leaky_frame"
                   for f in MEMORY.leak_report()["flagged"])


def test_growth_detection_through_refresh_and_sweeps():
    """The bench gate's signal end-to-end: a key growing in place across
    interleaved refresh+sweep generations accumulates a growth streak and
    flags as 'growing' (bench.py gates exit 3 on exactly this)."""
    from h2o3_tpu.frame.vec import Vec
    fr = _frame(nrows=300_000, ncols=1, seed=11)     # above the byte floor
    DKV.put("grower", fr)
    MEMORY.leak_sweep()
    for i in range(MEMORY.detector.sweeps):
        fr.add(f"c{i}", Vec.from_numpy(np.zeros(300_000, np.float32)))
        MEMORY.refresh()
        MEMORY.leak_sweep()
    growing = [f for f in MEMORY.leak_report()["flagged"]
               if "growing" in f["reasons"]]
    assert any(f["key"] == "grower" for f in growing)
    # one static sweep resets the growth streak (why bench captures growth
    # BEFORE its post-hoc idle passes)
    MEMORY.leak_sweep()
    assert not any("growing" in f["reasons"]
                   for f in MEMORY.leak_report()["flagged"])


# -- per-span attribution -----------------------------------------------------


def test_glm_build_trace_root_carries_peak_device_bytes():
    from h2o3_tpu.models.glm import GLM
    from h2o3_tpu.utils import tracing as tr
    rng = np.random.default_rng(7)
    cols = {f"x{i}": rng.normal(size=800).astype(np.float32)
            for i in range(4)}
    cols["y"] = np.where(rng.random(800) > 0.5, "a", "b")
    fr = Frame.from_arrays(cols)
    with tr.TRACER.span("memtest:root", root=True) as root:
        GLM(family="binomial", max_iterations=4).train(y="y",
                                                       training_frame=fr)
    trace = tr.TRACER.get_trace(root.trace_id)
    root_span = next(s for s in trace["spans"] if s["name"] == "memtest:root")
    assert root_span["attrs"].get("peak_device_bytes", 0) > 0
    fit = next(s for s in trace["spans"] if s["name"] == "glm:fit")
    assert fit["attrs"]["peak_device_bytes"] > 0
    assert "device_bytes_delta" in fit["attrs"]
    assert fit["attrs"]["host_rss_bytes"] > 0
    # the root's rollup is the max over its builds' peaks
    assert root_span["attrs"]["peak_device_bytes"] >= \
        fit["attrs"]["peak_device_bytes"] * 0.99


# -- REST surface -------------------------------------------------------------


@pytest.fixture(scope="module")
def server():
    s = H2OServer(port=0).start()
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(server.url + path) as r:
        return json.loads(r.read())


def test_memory_endpoint_reconciles_with_frame_nbytes(server, tmp_path):
    """Acceptance: /3/Memory's top-N byte totals reconcile (±1%) against
    the sum of frame chunk nbytes for a parsed frame."""
    rng = np.random.default_rng(5)
    csv = tmp_path / "mem.csv"
    csv.write_text("a,b\n" + "\n".join(
        f"{v:.5f},{v * 2:.5f}" for v in rng.normal(size=3000)))
    client = H2OClient(server.url)
    key = client.import_file(str(csv))
    fr = DKV[key]
    expect = sum(v.nbytes for v in fr.vecs)
    mem = _get(server, "/3/Memory?top=50")
    assert mem["__meta"]["schema_type"] == "MemoryV3"
    row = next(r for r in mem["top_keys"] if r["key"] == key)
    assert row["kind"] == "frame"
    assert row["bytes"] == pytest.approx(expect, rel=0.01)
    assert mem["dkv"]["by_kind"]["frame"] >= expect
    assert mem["dkv"]["total_bytes"] >= expect
    assert mem["host"]["rss_bytes"] > 0
    assert mem["device"]["bytes_in_use"] >= expect
    assert mem["watermarks"]["host_rss_peak_bytes"] > 0
    assert set(mem["leaks"]) >= {"sweeps", "flagged", "min_bytes"}


def test_cloud_serves_real_memory_numbers(server):
    fr = _frame(nrows=5000, ncols=2, seed=6)
    DKV.put("cloud_mem_frame", fr)
    cloud = _get(server, "/3/Cloud")
    node = cloud["nodes"][0]
    assert node["max_mem"] > node["free_mem"] > 0
    assert node["mem_value_size"] >= fr.nbytes
    assert node["pojo_mem"] > 0               # RSS beyond DKV values
    assert node["num_keys"] >= 1
    assert node["pid"] > 0


def test_memory_gauges_in_openmetrics(server):
    fr = _frame(nrows=2000, ncols=2, seed=8)
    DKV.put("gauge_frame", fr)
    _get(server, "/3/Memory")                  # samples + refreshes gauges
    with urllib.request.urlopen(server.url + "/metrics") as r:
        text = r.read().decode()
    m = re.search(r'h2o3_dkv_bytes\{kind="frame"\} (\d+)', text)
    assert m and int(m.group(1)) >= fr.nbytes
    assert re.search(r"^h2o3_host_rss_bytes [1-9]", text, re.M)
    assert re.search(r"^h2o3_device_bytes_in_use [1-9]", text, re.M)
    assert re.search(r"^h2o3_host_rss_peak_bytes [1-9]", text, re.M)


def test_dkv_clear_zeroes_exported_gauges(server):
    """A DKV.clear must not leave h2o3_dkv_bytes gauges reporting the last
    resident bytes forever (dashboards alert on these)."""
    fr = _frame(nrows=2000, ncols=2, seed=10)
    DKV.put("clear_gauge_frame", fr)
    DKV.clear()
    with urllib.request.urlopen(server.url + "/metrics") as r:
        text = r.read().decode()
    m = re.search(r'h2o3_dkv_bytes\{kind="frame"\} (\d+)', text)
    assert m and int(m.group(1)) == 0


def test_memory_endpoint_rejects_bad_top(server):
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(server, "/3/Memory?top=abc")
    assert ei.value.code == 404               # client error, not a 500


def test_client_memory_jstack_profiler_accessors(server):
    client = H2OClient(server.url)
    mem = client.memory(top=3)
    assert len(mem["top_keys"]) <= 3
    assert any(t["name"] == "MainThread" for t in client.jstack())
    prof = client.profiler(depth=2)
    assert prof["stacktraces"] and prof["counts"]


def test_model_key_reports_artifact_bytes(server):
    rng = np.random.default_rng(9)
    cols = {f"x{i}": rng.normal(size=400).astype(np.float32)
            for i in range(3)}
    cols["y"] = np.where(rng.random(400) > 0.5, "a", "b")
    fr = Frame.from_arrays(cols)
    from h2o3_tpu.models.glm import GLM
    m = GLM(family="binomial", max_iterations=3).train(y="y",
                                                       training_frame=fr)
    mem = _get(server, "/3/Memory?top=100")
    row = next(r for r in mem["top_keys"] if r["key"] == m.key)
    assert row["kind"] == "model" and row["bytes"] > 0
