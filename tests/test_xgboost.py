"""XGBoost differentiation: DART, param aliases, by-node sampling, offset.

Reference: ``h2o-extensions/xgboost`` XGBoostParameters surface; DART per
Rashmi & Gilad-Bachrach (2015) as implemented by libxgboost.
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.xgboost import XGBoost


def _reg_frame(rng, n=600):
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = (x[:, 0] * 2 - x[:, 1] + 0.2 * rng.normal(size=n)).astype(np.float32)
    cols = {f"x{i}": x[:, i] for i in range(4)}
    cols["y"] = y
    return Frame.from_arrays(cols)


def _bin_frame(rng, n=600):
    x = rng.normal(size=(n, 3)).astype(np.float32)
    yb = rng.random(n) < 1 / (1 + np.exp(-(1.5 * x[:, 0] - x[:, 1])))
    cols = {f"x{i}": x[:, i] for i in range(3)}
    cols["y"] = np.array(["no", "yes"], dtype=object)[yb.astype(int)]
    return Frame.from_arrays(cols)


def test_xgb_param_aliases(rng):
    fr = _reg_frame(rng)
    m = XGBoost(ntrees=5, eta=0.2, max_bin=32, subsample=0.9,
                colsample_bytree=0.9, min_child_weight=2.0,
                min_split_loss=0.01, seed=1).train(y="y", training_frame=fr)
    assert m.params["learn_rate"] == 0.2
    assert m.params["nbins"] == 32
    assert m.params["sample_rate"] == 0.9
    assert m.algo == "xgboost"
    assert m.training_metrics.rmse < 1.0


def test_dart_trains_and_scores(rng):
    fr = _bin_frame(rng)
    m = XGBoost(ntrees=12, max_depth=3, booster="dart", rate_drop=0.3,
                one_drop=True, seed=2).train(y="y", training_frame=fr)
    assert len(m.output["trees"]) == 12
    assert len(m.output["dart_weights"]) == 12
    # renormalization really happened: not all weights equal eta
    assert len({round(w, 6) for w in m.output["dart_weights"]}) > 1
    assert m.training_metrics.auc > 0.85
    pred = m.predict(fr)
    p = pred.vec("pyes").to_numpy()
    assert ((p >= 0) & (p <= 1)).all()
    # training-cache metrics equal re-scored metrics (weights baked in)
    mm = m.model_performance(fr)
    assert abs(mm.auc - m.training_metrics.auc) < 1e-6


def test_dart_regression_and_forest_norm(rng):
    fr = _reg_frame(rng)
    m = XGBoost(ntrees=10, max_depth=3, booster="dart", rate_drop=0.2,
                normalize_type="forest", seed=3).train(
        y="y", training_frame=fr)
    assert m.training_metrics.rmse < 1.0


def test_colsample_bynode_folds(rng):
    fr = _reg_frame(rng)
    b = XGBoost(ntrees=5, colsample_bynode=0.5, colsample_bylevel=0.8, seed=4)
    assert b._effective_col_rate() == pytest.approx(0.4)
    m = b.train(y="y", training_frame=fr)
    # stored params keep the USER's values (no in-place folding)
    assert m.params["col_sample_rate"] == pytest.approx(0.8)
    assert m.params["col_sample_by_node"] == pytest.approx(0.5)
    # repeated training must not compound the rate
    b.train(y="y", training_frame=fr)
    assert b._effective_col_rate() == pytest.approx(0.4)


def test_offset_column(rng):
    n = 500
    x = rng.normal(size=n).astype(np.float32)
    off = np.where(x > 0, 2.0, -2.0).astype(np.float32)
    y = (3.0 * x + off + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays({"x": x, "off": off, "y": y})

    m = XGBoost(ntrees=20, max_depth=3, offset_column="off", seed=5).train(
        y="y", training_frame=fr)
    # offset column must not be used as a feature
    assert m.output["x_cols"] == ["x"]
    pred = m.predict(fr).vec("predict").to_numpy()
    assert np.sqrt(np.mean((pred - y) ** 2)) < 0.6
    # scoring without the offset column fails loudly
    fr2 = Frame.from_arrays({"x": x, "y": y})
    with pytest.raises(ValueError, match="offset"):
        m.predict(fr2)


def test_gblinear_rejected(rng):
    fr = _reg_frame(rng)
    with pytest.raises(ValueError, match="gblinear"):
        XGBoost(ntrees=2, booster="gblinear").train(y="y", training_frame=fr)
