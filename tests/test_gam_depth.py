"""GAM basis families: thin plate (1-D/2-D), monotone I-splines, knots.

Reference: hex/gam/GamSplines (CubicRegressionSplines, ThinPlate*, ISplines),
splines_non_negative, knot_ids.
"""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.gam import GAM

import jax.numpy as jnp


def _wavy(rng, n=600):
    x = rng.uniform(-3, 3, n).astype(np.float32)
    y = (np.sin(1.7 * x) + 0.3 * x + rng.normal(scale=0.1, size=n)).astype(np.float32)
    return Frame.from_arrays({"x": x, "y": y}), x, y


def _r2(pred, y):
    return 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)


def test_cr_spline_fit(rng):
    fr, x, y = _wavy(rng)
    m = GAM(gam_columns=["x"], num_knots=8, family="gaussian").train(
        y="y", training_frame=fr)
    pred = m.predict(fr).vec("predict").to_numpy()
    assert _r2(pred, y) > 0.9


def test_thin_plate_1d(rng):
    fr, x, y = _wavy(rng)
    m = GAM(gam_columns=["x"], bs=[1], num_knots=8, family="gaussian").train(
        y="y", training_frame=fr)
    pred = m.predict(fr).vec("predict").to_numpy()
    assert _r2(pred, y) > 0.9


def test_thin_plate_2d(rng):
    n = 800
    x1 = rng.uniform(-2, 2, n).astype(np.float32)
    x2 = rng.uniform(-2, 2, n).astype(np.float32)
    y = (np.sin(x1) * np.cos(x2) + 0.05 * rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays({"a": x1, "b": x2, "y": y})
    m = GAM(gam_columns=[["a", "b"]], bs=[1], num_knots=12,
            family="gaussian").train(y="y", training_frame=fr)
    pred = m.predict(fr).vec("predict").to_numpy()
    assert _r2(pred, y) > 0.85


def test_monotone_ispline(rng):
    n = 700
    x = rng.uniform(0, 4, n).astype(np.float32)
    # monotone signal with a flat stretch + noise that tempts overshoot
    y = (np.minimum(x, 2.0) ** 2 + rng.normal(scale=0.3, size=n)).astype(np.float32)
    fr = Frame.from_arrays({"x": x, "y": y})
    m = GAM(gam_columns=["x"], bs=[2], num_knots=8, family="gaussian",
            standardize=False).train(y="y", training_frame=fr)
    grid = np.linspace(0.05, 3.95, 80).astype(np.float32)
    gfr = Frame.from_arrays({"x": grid})
    pred = m.predict(gfr).vec("predict").to_numpy()
    # monotone non-decreasing fit
    assert (np.diff(pred) >= -1e-4).all(), np.diff(pred).min()
    # and still tracks the signal
    fit = m.predict(fr).vec("predict").to_numpy()
    assert _r2(fit, y) > 0.8


def test_user_knots_and_validation(rng):
    fr, x, y = _wavy(rng)
    kn = np.linspace(-2.5, 2.5, 6)
    m = GAM(gam_columns=["x"], num_knots=6, knot_ids={"x": kn},
            family="gaussian").train(y="y", training_frame=fr)
    np.testing.assert_allclose(m.output["knots"]["x"], kn, rtol=1e-6)

    with pytest.raises(ValueError, match="bs=1"):
        GAM(gam_columns=[["x", "x"]], bs=[0]).train(y="y", training_frame=fr)
    with pytest.raises(ValueError, match="unknown"):
        GAM(gam_columns=["x"], bs=[9]).train(y="y", training_frame=fr)


def test_glm_beta_constraints_direct(rng):
    """The GLM box-constraint machinery GAM rides on."""
    from h2o3_tpu.models.glm import GLM
    n = 400
    x1 = rng.normal(size=n).astype(np.float32)
    x2 = rng.normal(size=n).astype(np.float32)
    y = (2.0 * x1 - 1.5 * x2 + 0.1 * rng.normal(size=n)).astype(np.float32)
    fr = Frame.from_arrays({"x1": x1, "x2": x2, "y": y})

    m = GLM(family="gaussian",
            beta_constraints={"x1": (0.0, 1.0), "x2": (0.0, None)}).train(
        y="y", training_frame=fr)
    c = m.coef()
    assert 0.0 <= c["x1"] <= 1.0 + 1e-5
    assert c["x2"] >= -1e-6            # truth is -1.5; clamped at 0

    with pytest.raises(ValueError, match="unknown coefficients"):
        GLM(family="gaussian", beta_constraints={"zzz": (0, 1)}).train(
            y="y", training_frame=fr)
