"""Round-2 REST parity sweep — the routes the real h2o-py client traffics.

Reference registrations: ``water/api/RegisterV3Api.java``; client call sites
in ``h2o-py/h2o/h2o.py`` (parse_setup/split_frame/make_metrics/save_model/
load_model/remove_all/...).
"""

import json
import urllib.request

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api import H2OClient, H2OServer
from h2o3_tpu.utils.registry import DKV


@pytest.fixture
def server():
    s = H2OServer(port=0).start()
    yield s
    s.stop()


@pytest.fixture
def client(server):
    return H2OClient(server.url)


@pytest.fixture
def bin_frame(rng):
    n = 400
    X = rng.normal(size=(n, 3))
    y = X[:, 0] - X[:, 1] > 0
    f = Frame.from_arrays({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2],
        "y": np.array(["yes" if t else "no" for t in y], dtype=object)},
        key="pf")
    DKV.put("pf", f)
    return f


def test_ping_jobs_capabilities(client):
    assert client.ping()
    assert isinstance(client.jobs(), list)
    caps = client.request("GET", "/3/Capabilities")["capabilities"]
    assert any(c["name"] == "gbm" for c in caps)


def test_parse_setup(client, tmp_path):
    p = tmp_path / "d.csv"
    p.write_text("num,cat\n1,a\n2,b\n3,a\n")
    setup = client.parse_setup([str(p)])
    assert setup["number_columns"] == 2
    assert setup["column_names"] == ["num", "cat"]
    assert setup["column_types"] == ["Numeric", "Enum"]


def test_split_frame_exact(client, bin_frame):
    keys = client.split_frame("pf", [0.7], ["tr", "te"])
    tr, te = DKV["tr"], DKV["te"]
    # reference SplitFrame: EXACT contiguous split
    assert tr.nrows == 280 and te.nrows == 120
    assert keys == ["tr", "te"]


def test_library_split_frame_probabilistic(bin_frame):
    tr, te = bin_frame.split_frame(ratios=[0.75], seed=42)
    assert tr.nrows + te.nrows == bin_frame.nrows
    assert 0.6 < tr.nrows / bin_frame.nrows < 0.9
    # deterministic under a seed
    tr2, te2 = bin_frame.split_frame(ratios=[0.75], seed=42)
    assert tr2.nrows == tr.nrows


def test_model_metrics_routes(client, bin_frame):
    model = client.train("gbm", "pf", y="y", ntrees=3, max_depth=3)
    mkey = model["model_id"]["name"]
    mm = client.model_metrics(mkey, "pf")
    assert 0.5 <= mm["auc"] <= 1.0
    got = client.request("GET", f"/3/ModelMetrics/models/{mkey}")
    assert got["model_metrics"]


def test_make_metrics_from_predictions(client, bin_frame):
    model = client.train("gbm", "pf", y="y", ntrees=3, max_depth=3)
    pkey = client.predict(model["model_id"]["name"], "pf")
    out = client.request(
        "POST", f"/3/ModelMetrics/predictions_frame/{pkey}/actuals_frame/pf",
        {"response_column": "y"})
    assert out["model_metrics"][0]["auc"] > 0.5


def test_partial_dependence_route(client, bin_frame):
    model = client.train("gbm", "pf", y="y", ntrees=3, max_depth=3)
    pd = client.partial_dependence(model["model_id"]["name"], "pf",
                                   cols=["a"], nbins=5)
    assert pd and "a" in pd[0]["columns"]
    assert len(pd[0]["data"]["mean_response"]) == 5


def test_model_save_load_roundtrip(client, bin_frame, tmp_path):
    model = client.train("glm", "pf", y="y", family="binomial")
    mkey = model["model_id"]["name"]
    client.save_model(mkey, str(tmp_path))
    DKV.remove(mkey)
    back = client.load_model(str(tmp_path / mkey))
    assert back == mkey
    mm = client.model_metrics(back, "pf")
    assert mm["auc"] > 0.9


def test_mojo_pojo_download(client, bin_frame):
    model = client.train("gbm", "pf", y="y", ntrees=2, max_depth=2)
    mkey = model["model_id"]["name"]
    mojo = urllib.request.urlopen(f"{client.url}/3/Models/{mkey}/mojo").read()
    assert mojo[:2] == b"PK"            # zip magic
    pojo = urllib.request.urlopen(
        f"{client.url}/3/Models.java/{mkey}").read()
    assert b"def score0" in pojo or b"score" in pojo


def test_typeahead_and_find(client, bin_frame, tmp_path):
    (tmp_path / "x1.csv").write_text("a\n1\n")
    (tmp_path / "x2.csv").write_text("a\n1\n")
    hits = client.typeahead(str(tmp_path / "x"))
    assert len(hits) == 2
    out = client.request("GET", "/3/Find?key=pf&column=y&row=0&match=yes")
    assert out["next"] >= 0


def test_frame_detail_routes(client, bin_frame):
    cols = client.request("GET", "/3/Frames/pf/columns")["columns"]
    assert {c["label"] for c in cols} == {"a", "b", "c", "y"}
    summ = client.request("GET", "/3/Frames/pf/columns/a/summary")
    col = summ["frames"][0]["columns"][0]
    assert col["mean"] is not None and len(col["percentiles"]) > 0
    dom = client.request("GET", "/3/Frames/pf/columns/y/domain")["domain"][0]
    assert dom == ["no", "yes"]
    light = client.request("GET", "/3/Frames/pf/light")["frames"][0]
    assert light["rows"] == 400


def test_download_dataset(client, bin_frame):
    body = urllib.request.urlopen(
        f"{client.url}/3/DownloadDataset?frame_id=pf").read().decode()
    assert body.splitlines()[0] == "a,b,c,y"
    assert len(body.splitlines()) == 401


def test_frame_save_load_routes(client, bin_frame, tmp_path):
    client.request("POST", "/3/Frames/pf/save", {"dir": str(tmp_path)})
    DKV.remove("pf")
    client.request("POST", "/3/Frames/load",
                   {"dir": str(tmp_path / "pf"), "frame_id": "pf"})
    assert DKV["pf"].nrows == 400


def test_dkv_remove_all(client, bin_frame):
    client.remove_all()
    assert "pf" not in DKV


def test_missing_inserter(client, bin_frame):
    client.request("POST", "/3/MissingInserter",
                   {"dataset": "pf", "fraction": 0.5, "seed": 1})
    fr = DKV["pf"]
    na = int(fr.vec("a").rollups().na_cnt)
    assert 120 < na < 280


def test_create_frame_route(client):
    out = client.request("POST", "/3/CreateFrame",
                         {"rows": 50, "cols": 3, "dest": "cf1", "seed": 7})
    assert out["rows"] == 50 and DKV["cf1"].nrows == 50


def test_model_builders_metadata(client):
    mb = client.request("GET", "/3/ModelBuilders")["model_builders"]
    assert "gbm" in mb and "glm" in mb
    one = client.request("GET", "/3/ModelBuilders/gbm")["model_builders"]["gbm"]
    names = {p["name"] for p in one["parameters"]}
    assert "ntrees" in names and "learn_rate" in names


def test_session_and_misc(client):
    sid = client.request("GET", "/3/InitID")["session_key"]
    assert sid.startswith("_sid_")
    client.request("POST", "/3/SessionProperties",
                   {"key": "foo", "value": "bar"})
    got = client.request("GET", "/3/SessionProperties?key=foo")
    assert got["value"] == "bar"
    help_ = client.request("GET", "/99/Rapids/help")["syntax"]
    assert "cumsum" in help_ and "gsub" in help_
    eps = client.request("GET", "/3/Metadata/endpoints")["routes"]
    assert len(eps) > 50


def test_import_sql_route(client, tmp_path):
    import sqlite3
    db = tmp_path / "r.db"
    con = sqlite3.connect(db)
    con.execute("CREATE TABLE t (a REAL, b REAL)")
    con.executemany("INSERT INTO t VALUES (?,?)", [(i, i * 2.0) for i in range(9)])
    con.commit()
    con.close()
    out = client.request("POST", "/99/ImportSQLTable",
                         {"connection_url": f"sqlite:{db}", "table": "t"})
    assert DKV[out["dest"]["name"]].nrows == 9


def test_killminus3_and_metadata_endpoint_detail(server, client):
    """GET /3/KillMinus3 (reference RegisterV3Api:439 — thread dump, server
    keeps serving) + /3/Metadata/endpoints/{path|index} fetchRoute."""
    import json
    import urllib.request
    u = urllib.request.urlopen
    r = json.loads(u(server.url + "/3/KillMinus3").read())
    assert r["__meta"]["schema_type"] == "KillMinus3V3"
    assert json.loads(u(server.url + "/3/Cloud").read())["cloud_healthy"]
    byp = json.loads(u(server.url +
                       "/3/Metadata/endpoints/%2F3%2FCloud").read())
    assert byp["routes"][0]["url_pattern"] == "/3/Cloud"
    byi = json.loads(u(server.url + "/3/Metadata/endpoints/0").read())
    assert byi["routes"][0]["url_pattern"]
