"""GBM/DRF tests — quality parity vs sklearn on synthetic tasks (reference
model: h2o-py pyunit GBM/DRF suites)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models import GBM, DRF


def _friedman(rng, n=3000, noise=0.1):
    X = rng.uniform(size=(n, 5))
    y = (10 * np.sin(np.pi * X[:, 0] * X[:, 1]) + 20 * (X[:, 2] - 0.5) ** 2
         + 10 * X[:, 3] + 5 * X[:, 4] + rng.normal(scale=noise, size=n))
    cols = {f"x{i}": X[:, i] for i in range(5)}
    cols["y"] = y
    return Frame.from_arrays(cols), X, y


def _classif(rng, n=4000):
    X = rng.normal(size=(n, 5))
    logit = 2 * X[:, 0] - 1.5 * X[:, 1] * X[:, 1] + X[:, 2] * X[:, 3]
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logit))).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(5)}
    cols["y"] = np.where(y == 1, "Y", "N").astype(object)
    return Frame.from_arrays(cols), X, y


def test_gbm_regression_quality(rng):
    f, X, y = _friedman(rng)
    m = GBM(ntrees=50, max_depth=5, learn_rate=0.2, seed=1).train(y="y", training_frame=f)
    assert m.training_metrics.r2 > 0.97, m.training_metrics

    from sklearn.ensemble import HistGradientBoostingRegressor
    sk = HistGradientBoostingRegressor(max_iter=50, max_depth=5, learning_rate=0.2).fit(X, y)
    sk_r2 = sk.score(X, y)
    # within a few points of sklearn's hist-GBM on train R2
    assert m.training_metrics.r2 > sk_r2 - 0.05


def test_gbm_binomial_quality(rng):
    f, X, y = _classif(rng)
    m = GBM(ntrees=40, max_depth=4, learn_rate=0.2, seed=1).train(y="y", training_frame=f)
    from sklearn.ensemble import HistGradientBoostingClassifier
    from sklearn.metrics import roc_auc_score
    sk = HistGradientBoostingClassifier(max_iter=40, max_depth=4, learning_rate=0.2).fit(X, y)
    sk_auc = roc_auc_score(y, sk.predict_proba(X)[:, 1])
    assert m.training_metrics.auc > sk_auc - 0.02, (m.training_metrics, sk_auc)

    pred = m.predict(f)
    assert pred.names == ["predict", "pN", "pY"]
    p = pred.to_pandas()
    np.testing.assert_allclose(p["pN"] + p["pY"], 1.0, atol=1e-5)


def test_gbm_predict_new_frame_matches_train_path(rng):
    """Raw-threshold traversal on a fresh frame must equal binned traversal."""
    f, X, y = _friedman(rng, n=1000)
    m = GBM(ntrees=10, max_depth=4, seed=3).train(y="y", training_frame=f)
    again = Frame.from_arrays({**{f"x{i}": X[:, i] for i in range(5)}, "y": y})
    p1 = m.predict(f).vec("predict").to_numpy()
    p2 = m.predict(again).vec("predict").to_numpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-5)


def test_gbm_na_routing(rng):
    n = 2000
    x = rng.uniform(size=n)
    x[: n // 4] = np.nan
    y = np.where(np.isnan(x), 5.0, 2.0 * (x > 0.5))
    f = Frame.from_arrays({"x": x, "y": y})
    m = GBM(ntrees=20, max_depth=3, learn_rate=0.3, seed=1).train(y="y", training_frame=f)
    pred = m.predict(f).vec("predict").to_numpy()
    # NA rows must learn their own direction → near-5 predictions
    assert abs(pred[: n // 4].mean() - 5.0) < 0.3
    assert m.training_metrics.r2 > 0.95


def test_gbm_categorical_feature(rng):
    n = 3000
    g = rng.choice(["a", "b", "c", "d"], size=n)
    eff = {"a": 0.0, "b": 3.0, "c": -2.0, "d": 7.0}
    y = np.array([eff[v] for v in g]) + rng.normal(scale=0.1, size=n)
    f = Frame.from_arrays({"g": g.astype(object), "y": y})
    m = GBM(ntrees=30, max_depth=3, learn_rate=0.3, seed=1).train(y="y", training_frame=f)
    assert m.training_metrics.r2 > 0.98


def test_gbm_sampling_params(rng):
    f, X, y = _friedman(rng, n=1500)
    m = GBM(ntrees=30, sample_rate=0.7, col_sample_rate_per_tree=0.8, seed=5).train(
        y="y", training_frame=f)
    assert m.training_metrics.r2 > 0.9


def test_drf_regression(rng):
    f, X, y = _friedman(rng, n=2000)
    m = DRF(ntrees=30, max_depth=12, seed=1).train(y="y", training_frame=f)
    assert m.training_metrics.r2 > 0.85, m.training_metrics


def test_drf_binomial(rng):
    f, X, y = _classif(rng, n=2000)
    m = DRF(ntrees=30, max_depth=10, seed=1).train(y="y", training_frame=f)
    assert m.training_metrics.auc > 0.9, m.training_metrics
    pred = m.predict(f).to_pandas()
    assert ((pred["pY"] >= 0) & (pred["pY"] <= 1)).all()


def test_gbm_validation_frame(rng):
    f, _, _ = _friedman(rng, n=2000)
    fv, _, _ = _friedman(rng, n=500)
    m = GBM(ntrees=30, seed=1).train(y="y", training_frame=f, validation_frame=fv)
    assert m.validation_metrics.r2 > 0.9


def test_xgboost_vs_real_xgboost_semantics(rng):
    """Our XGBoost estimator vs sklearn HistGradientBoosting with matched
    lambda — quality parity on held-out data."""
    from h2o3_tpu.models import XGBoost
    f, X, y = _friedman(rng, n=3000)
    fv, Xv, yv = _friedman(rng, n=1000)
    m = XGBoost(ntrees=50, max_depth=6, learn_rate=0.3, seed=2).train(
        y="y", training_frame=f, validation_frame=fv)
    from sklearn.ensemble import HistGradientBoostingRegressor
    sk = HistGradientBoostingRegressor(max_iter=50, max_depth=6, learning_rate=0.3,
                                       l2_regularization=1.0).fit(X, y)
    sk_r2 = sk.score(Xv, yv)
    assert m.validation_metrics.r2 > sk_r2 - 0.03, (m.validation_metrics, sk_r2)


def test_xgboost_regularization_params(rng):
    from h2o3_tpu.models import XGBoost
    f, X, y = _friedman(rng, n=1500)
    m_hi = XGBoost(ntrees=10, gamma=1000.0, seed=1).train(y="y", training_frame=f)
    m_lo = XGBoost(ntrees=10, gamma=0.0, seed=1).train(y="y", training_frame=f)
    # huge gamma must prune aggressively -> worse train fit
    assert m_hi.training_metrics.mse > m_lo.training_metrics.mse


def test_gbm_bad_distribution(rng):
    f, _, _ = _friedman(rng, n=200)
    with pytest.raises(ValueError, match="unsupported distribution"):
        GBM(distribution="ordinal").train(y="y", training_frame=f)
    with pytest.raises(ValueError, match="categorical"):
        GBM(distribution="bernoulli").train(y="y", training_frame=f)


def test_drf_sample_rate_honored(rng):
    f, X, y = _friedman(rng, n=800)
    m_lo = DRF(ntrees=5, max_depth=6, sample_rate=0.05, seed=9).train(y="y", training_frame=f)
    m_hi = DRF(ntrees=5, max_depth=6, sample_rate=1.0, seed=9).train(y="y", training_frame=f)
    # tiny subsample -> visibly weaker fit (was silently ignored before)
    assert m_lo.training_metrics.mse != m_hi.training_metrics.mse


def test_drf_depth_validated(rng):
    f, _, _ = _friedman(rng, n=100)
    with pytest.raises(ValueError, match="max_depth"):
        DRF(max_depth=20).train(y="y", training_frame=f)


def test_gbm_multinomial(rng):
    n = 900
    centers = np.array([[0, 0], [6, 0], [0, 6]])
    yi = rng.integers(0, 3, size=n)
    X = centers[yi] + rng.normal(size=(n, 2))
    f = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1],
                           "y": np.array(["a", "b", "c"], dtype=object)[yi]})
    m = GBM(ntrees=20, max_depth=3, seed=1).train(y="y", training_frame=f)
    assert m.nclasses == 3
    pred = m.predict(f)
    assert pred.names == ["predict", "pa", "pb", "pc"]
    acc = (pred.vec("predict").to_numpy() == yi).mean()
    assert acc > 0.95
    assert m.training_metrics.logloss < 0.3


def test_drf_multinomial(rng):
    n = 900
    centers = np.array([[0, 0], [6, 0], [0, 6]])
    yi = rng.integers(0, 3, size=n)
    X = centers[yi] + rng.normal(size=(n, 2))
    f = Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1],
                           "y": np.array(["a", "b", "c"], dtype=object)[yi]})
    m = DRF(ntrees=20, max_depth=8, seed=1).train(y="y", training_frame=f)
    pred = m.predict(f)
    probs = np.stack([pred.vec(c).to_numpy() for c in ("pa", "pb", "pc")], axis=1)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    acc = (pred.vec("predict").to_numpy() == yi).mean()
    assert acc > 0.95


def test_pallas_hist_parity_with_segsum(rng):
    """The Pallas MXU histogram kernel must match the XLA segment_sum path
    (skipped off-TPU; the kernel only engages there)."""
    import jax
    import jax.numpy as jnp
    import pytest as _pytest
    from functools import partial
    if jax.default_backend() != "tpu":
        _pytest.skip("pallas kernel is TPU-only")
    from h2o3_tpu.models.tree import _level_histograms
    from h2o3_tpu.ops.pallas_hist import hist_pallas
    R, F, B, N = 10000, 5, 16, 8
    Bt = B + 1
    binned = jnp.asarray(rng.integers(0, Bt, size=(R, F)).astype(np.int32))
    node = jnp.asarray(rng.integers(-1, N, size=R).astype(np.int32))
    g = jnp.asarray(rng.normal(size=R).astype(np.float32))
    h = jnp.abs(jnp.asarray(rng.normal(size=R).astype(np.float32)))
    w = jnp.ones(R, jnp.float32)
    ref = jax.jit(partial(_level_histograms, n_nodes=N, n_bins_tot=Bt))(
        binned, node, g, h, w)
    got = hist_pallas(binned.T, node, g, h, w, N, Bt)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-3)


def test_gbm_distribution_families(rng):
    """Reference: hex/Distribution.java families — gamma/tweedie (log link),
    laplace/quantile/huber (robust)."""
    from h2o3_tpu.models import GBM
    from h2o3_tpu.frame.frame import Frame as _F
    n = 600
    X = rng.normal(size=(n, 3)).astype(np.float32)
    mu = np.exp(0.8 * X[:, 0] - 0.4 * X[:, 1] + 0.5)
    y_gamma = rng.gamma(shape=2.0, scale=mu / 2.0).astype(np.float32)
    fr = _F.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
                         "y": y_gamma})
    for dist in ("gamma", "tweedie"):
        m = GBM(ntrees=15, max_depth=3, distribution=dist, seed=1).train(
            y="y", training_frame=fr)
        pred = np.asarray(m.predict(fr).vec("predict").to_numpy())
        assert (pred > 0).all(), dist        # log link ⇒ positive predictions
        assert np.corrcoef(pred, mu)[0, 1] > 0.7, dist

    # robust losses on contaminated data: laplace/huber track the median
    y_out = (2 * X[:, 0] + rng.normal(scale=0.1, size=n)).astype(np.float32)
    y_out[:20] += 60.0                        # gross outliers
    fr2 = _F.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2],
                          "y": y_out})
    preds = {}
    for dist in ("gaussian", "laplace", "huber"):
        m = GBM(ntrees=25, max_depth=3, distribution=dist, seed=1).train(
            y="y", training_frame=fr2)
        preds[dist] = np.asarray(m.predict(fr2).vec("predict").to_numpy())
    clean = slice(20, None)
    err = {d: np.abs(preds[d][clean] - y_out[clean]).mean() for d in preds}
    assert err["laplace"] < err["gaussian"]
    assert err["huber"] < err["gaussian"]

    # quantile regression: alpha=0.9 predictions sit above alpha=0.1
    m_lo = GBM(ntrees=20, max_depth=3, distribution="quantile",
               quantile_alpha=0.1, seed=1).train(y="y", training_frame=fr2)
    m_hi = GBM(ntrees=20, max_depth=3, distribution="quantile",
               quantile_alpha=0.9, seed=1).train(y="y", training_frame=fr2)
    lo = np.asarray(m_lo.predict(fr2).vec("predict").to_numpy())
    hi = np.asarray(m_hi.predict(fr2).vec("predict").to_numpy())
    assert (hi >= lo - 1e-4).mean() > 0.95


def test_gbm_early_stopping(rng):
    """stopping_rounds (reference: ScoreKeeper.stopEarly): on an easy problem
    training halts well before ntrees once deviance plateaus."""
    from h2o3_tpu.models import GBM
    from h2o3_tpu.frame.frame import Frame as _F
    n = 400
    X = rng.normal(size=(n, 3)).astype(np.float32)
    # noisy signal: late trees improve the training deviance only marginally,
    # so the relative-tolerance plateau rule fires
    logit = 2.0 * X[:, 0]
    y = np.where(rng.random(n) < 1 / (1 + np.exp(-logit)), "a", "b")
    fr = _F.from_arrays({"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2], "y": y})
    m = GBM(ntrees=100, max_depth=3, stopping_rounds=3,
            stopping_tolerance=0.02, seed=1).train(y="y", training_frame=fr)
    assert m.output["ntrees"] < 100
    assert m.training_metrics.auc > 0.85
    # without stopping all trees grow
    m2 = GBM(ntrees=12, max_depth=3, seed=1).train(y="y", training_frame=fr)
    assert m2.output["ntrees"] == 12


def test_histogram_dispatch_mesh_fused_beats_pallas(monkeypatch):
    """ISSUE 13 satellite: on a multi-device mesh the fused shard_map+psum
    path must win the _histograms dispatch even when the Pallas kernel is
    available — hist_pallas is single-device, and running it over the
    global array would SKIP the per-level psum reduction (each shard's
    partial histogram would be mistaken for the total)."""
    from h2o3_tpu.models import tree as tree_mod
    from h2o3_tpu.ops import pallas_hist as ph

    calls = []
    monkeypatch.setattr(ph, "pallas_available",
                        lambda *a, **k: True)        # TPU-like container
    monkeypatch.setattr(ph, "hist_pallas",
                        lambda *a, **k: calls.append("pallas") or "pallas")
    monkeypatch.setattr(tree_mod, "_level_histograms_fused",
                        lambda *a, **k: calls.append("fused") or "fused")
    monkeypatch.setattr(tree_mod, "_level_histograms",
                        lambda *a, **k: calls.append("segsum") or "segsum")

    binned = np.zeros((8, 2), np.int32)
    args = (binned, binned.T, np.zeros(8, np.int32), np.zeros(8, np.float32),
            np.zeros(8, np.float32), np.ones(8, np.float32))
    # mesh present: the fused collective path MUST take precedence
    assert tree_mod._histograms(*args, 4, 17, mesh=object()) == "fused"
    # no mesh: the Pallas kernel is the fast single-device path
    assert tree_mod._histograms(*args, 4, 17, mesh=None) == "pallas"
    # no mesh, no pallas: segment_sum fallback
    monkeypatch.setattr(ph, "pallas_available", lambda *a, **k: False)
    assert tree_mod._histograms(*args, 4, 17) == "segsum"
    assert calls == ["fused", "pallas", "segsum"]
