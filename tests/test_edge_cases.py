"""Edge-case behavioral corpus (VERDICT r4 missing #7: the reference
specs behavior via 1,387 pyunits; the thin spots here were NA-heavy
frames, weird domains, and parameter interactions).

Each test pins a behavior a migrating user hits in the wild — not happy
paths (those live in the per-algo suites) but the frames that break
implementations: 90%-NA columns, thousand-level categoricals, unicode
levels, constant/extreme features, train/test domain drift.
"""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models.gbm import GBM, DRF
from h2o3_tpu.models.glm import GLM


class TestNAHeavyFrames:
    def test_gbm_90pct_na_feature_still_trains(self, rng):
        n = 400
        x = rng.normal(size=n).astype(np.float32)
        sparse = x.copy()
        sparse[rng.random(n) < 0.9] = np.nan
        fr = Frame.from_arrays({
            "mostly_na": sparse, "ok": x,
            "y": np.where(x > 0, "t", "f").astype(object)})
        m = GBM(ntrees=10, max_depth=3, seed=1).train(y="y",
                                                      training_frame=fr)
        assert m.training_metrics.auc > 0.9
        p = m.predict(fr).vec("pt").to_numpy()[:n]
        assert np.isfinite(p).all()

    def test_all_na_feature_is_inert(self, rng):
        """A 100%-NA column must neither crash nor influence the model
        (reference: DHistogram gives it no splittable mass)."""
        n = 256
        x = rng.normal(size=n).astype(np.float32)
        fr_with = Frame.from_arrays({
            "dead": np.full(n, np.nan, np.float32), "x": x,
            "y": (2 * x + 0.1 * rng.normal(size=n)).astype(np.float32)})
        fr_without = Frame.from_arrays({
            "x": fr_with.vec("x").to_numpy(),
            "y": fr_with.vec("y").to_numpy()})
        p_with = GBM(ntrees=5, max_depth=3, seed=2).train(
            y="y", training_frame=fr_with).predict(fr_with) \
            .vec("predict").to_numpy()[:n]
        p_without = GBM(ntrees=5, max_depth=3, seed=2).train(
            y="y", training_frame=fr_without).predict(fr_without) \
            .vec("predict").to_numpy()[:n]
        np.testing.assert_allclose(p_with, p_without, rtol=1e-5)

    def test_glm_all_rows_have_some_na_with_skip_errors_clearly(self, rng):
        """Skip with zero surviving rows must raise a real error, not
        return a garbage fit."""
        n = 64
        a = np.full(n, np.nan, np.float32)
        b = rng.normal(size=n).astype(np.float32)
        fr = Frame.from_arrays({"a": a, "b": b,
                                "y": b.astype(np.float32)})
        with pytest.raises(ValueError, match="removed every row"):
            GLM(family="gaussian", missing_values_handling="Skip").train(
                y="y", training_frame=fr)

    def test_na_response_rows_excluded_from_training(self, rng):
        """Rows with NA response carry no training weight (reference:
        response NA rows are skipped, not imputed)."""
        n = 300
        x = rng.normal(size=n).astype(np.float32)
        y = (3 * x).astype(np.float32)
        y_box = y.copy()
        # poison a block of responses; features there are adversarial
        y_box[:100] = np.nan
        fr = Frame.from_arrays({"x": x, "y": y_box})
        m = GLM(family="gaussian", lambda_=0.0, standardize=False).train(
            y="y", training_frame=fr)
        assert m.coef()["x"] == pytest.approx(3.0, abs=1e-2)


class TestWeirdDomains:
    def test_unicode_and_punctuated_levels_roundtrip(self, rng):
        n = 240
        levels = np.array(["naïve", "a,b", 'quo"te', "tab\tlevel", "ok"],
                          object)
        c = levels[rng.integers(0, len(levels), n)]
        fr = Frame.from_arrays({
            "c": c, "x": rng.normal(size=n).astype(np.float32),
            "y": np.where(c == "naïve", "yes", "no").astype(object)})
        m = GBM(ntrees=10, max_depth=3, seed=3).train(y="y",
                                                      training_frame=fr)
        assert m.training_metrics.auc > 0.99    # the level IS the signal
        pred = m.predict(fr)
        labels = pred.vec("predict").labels()[:n]
        assert set(labels) <= {"yes", "no"}

    def test_thousand_level_categorical(self, rng):
        """High-cardinality enum: group splits must bucket levels, not
        blow memory or time (reference nbins_cats semantics)."""
        n = 2000
        codes = rng.integers(0, 1000, n)
        y = np.where(codes % 2 == 0, "even", "odd").astype(object)
        fr = Frame.from_arrays({
            "big": np.array([f"lv{c:04d}" for c in codes], object),
            "noise": rng.normal(size=n).astype(np.float32), "y": y})
        m = GBM(ntrees=15, max_depth=5, seed=4).train(y="y",
                                                      training_frame=fr)
        # parity-of-level is learnable only through per-level bucketing;
        # anything above chance proves levels aren't being averaged away
        assert m.training_metrics.auc > 0.6

    def test_unseen_level_at_scoring_time(self, rng):
        n = 200
        tr_levels = np.array(["a", "b", "c"], object)
        c = tr_levels[rng.integers(0, 3, n)]
        fr = Frame.from_arrays({
            "c": c, "x": rng.normal(size=n).astype(np.float32),
            "y": np.where(c == "a", "t", "f").astype(object)})
        m = GBM(ntrees=5, max_depth=3, seed=5).train(y="y",
                                                     training_frame=fr)
        test = Frame.from_arrays({
            "c": np.array(["a", "zz_new", "b"], object),
            "x": np.zeros(3, np.float32)})
        p = m.predict(test).vec("pt").to_numpy()[:3]
        assert np.isfinite(p).all()     # unseen level routes like NA


class TestParameterInteractions:
    def test_weights_plus_nfolds(self, rng):
        """CV holdout masks must COMPOSE with user weights (both are
        weight masks in this design — the overlap is the risky path)."""
        n = 300
        x = rng.normal(size=n).astype(np.float32)
        w = rng.integers(1, 4, n).astype(np.float32)
        fr = Frame.from_arrays({
            "x": x, "w": w,
            "y": np.where(x > 0, "t", "f").astype(object)})
        m = GBM(ntrees=5, max_depth=3, seed=6, nfolds=3,
                weights_column="w").train(y="y", training_frame=fr)
        assert m.cross_validation_metrics is not None
        assert 0.5 < m.cross_validation_metrics.auc <= 1.0

    def test_checkpoint_plus_weights(self, rng):
        n = 240
        x = rng.normal(size=n).astype(np.float32)
        w = np.where(np.arange(n) % 2 == 0, 2.0, 1.0).astype(np.float32)
        fr = Frame.from_arrays({"x": x, "w": w,
                                "y": (2 * x).astype(np.float32)})
        half = GBM(ntrees=3, max_depth=3, seed=7, weights_column="w").train(
            y="y", training_frame=fr)
        full = GBM(ntrees=6, max_depth=3, seed=7, weights_column="w",
                   checkpoint=half).train(y="y", training_frame=fr)
        straight = GBM(ntrees=6, max_depth=3, seed=7,
                       weights_column="w").train(y="y", training_frame=fr)
        pr = full.predict(fr).vec("predict").to_numpy()[:n]
        ps = straight.predict(fr).vec("predict").to_numpy()[:n]
        np.testing.assert_allclose(pr, ps, atol=1e-5)

    def test_drf_sampling_with_tiny_frame(self, rng):
        """8-row frame: bootstrap sampling + min_rows must degrade to a
        sane model, not an exception or empty forest."""
        fr = Frame.from_arrays({
            "x": np.arange(8, dtype=np.float32),
            "y": np.array(["a", "b"] * 4, object)})
        m = DRF(ntrees=5, max_depth=3, seed=8).train(y="y",
                                                     training_frame=fr)
        p = m.predict(fr).vec("pa").to_numpy()[:8]
        assert np.isfinite(p).all()


class TestExtremeValues:
    def test_huge_magnitudes_bin_and_train(self, rng):
        n = 256
        x = (rng.normal(size=n) * 1e30).astype(np.float32)
        fr = Frame.from_arrays({
            "x": x, "y": np.where(x > 0, "t", "f").astype(object)})
        m = GBM(ntrees=5, max_depth=2, seed=9).train(y="y",
                                                     training_frame=fr)
        assert m.training_metrics.auc > 0.95

    def test_constant_feature_is_inert(self, rng):
        n = 200
        x = rng.normal(size=n).astype(np.float32)
        fr = Frame.from_arrays({
            "const": np.full(n, 3.14, np.float32), "x": x,
            "y": (x * 2).astype(np.float32)})
        m = GBM(ntrees=5, max_depth=3, seed=10).train(y="y",
                                                      training_frame=fr)
        vi = m.output.get("varimp")
        if vi:
            assert dict(vi).get("const", 0.0) == pytest.approx(0.0, abs=1e-9)

    def test_glm_near_collinear_features(self, rng):
        """x2 = x1 + tiny noise: IRLS must converge to finite
        coefficients (the reference's gram regularization path)."""
        n = 300
        x1 = rng.normal(size=n)
        x2 = x1 + 1e-4 * rng.normal(size=n)
        y = (x1 + 0.05 * rng.normal(size=n))
        fr = Frame.from_arrays({"a": x1.astype(np.float32),
                                "b": x2.astype(np.float32),
                                "y": y.astype(np.float32)})
        m = GLM(family="gaussian", lambda_=1e-6).train(y="y",
                                                       training_frame=fr)
        assert all(np.isfinite(v) for v in m.coef().values())
        p = m.predict(fr).vec("predict").to_numpy()[:n]
        assert np.corrcoef(p, y)[0, 1] > 0.99
