"""Multi-process cloud: 2 processes × 4 CPU devices = one 8-device mesh.

Reference: ``multiNodeUtils.sh:21-26`` boots a 4-JVM localhost cloud for the
Java test suite; training there must equal single-JVM training. Here the
launcher forks 2 processes that join via ``jax.distributed`` and train over
a frame sharded across BOTH processes.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_two_process_cloud(tmp_path):
    script = os.path.join(REPO, "tests", "scripts", "multiproc_train.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "h2o3_tpu.launch", "--fork", "2",
         "--devices-per-process", "4", "--port", "7455",
         script, str(tmp_path)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-4000:]

    with open(tmp_path / "proc0.json") as f:
        r0 = json.load(f)
    with open(tmp_path / "proc1.json") as f:
        r1 = json.load(f)

    # both controllers computed the SAME model (SPMD: identical programs,
    # identical reductions)
    assert r0["gbm_logloss"] == pytest.approx(r1["gbm_logloss"], abs=1e-7)
    assert r0["gbm_auc"] == pytest.approx(r1["gbm_auc"], abs=1e-7)
    assert r0["glm_logloss"] == pytest.approx(r1["glm_logloss"], abs=1e-7)
    np.testing.assert_allclose(r0["glm_coef"], r1["glm_coef"], rtol=1e-6)
    np.testing.assert_allclose(r0["pred_head"], r1["pred_head"], rtol=1e-6)

    # and it matches the single-process 8-device model on the same data/seed
    from h2o3_tpu.frame.frame import Frame
    from h2o3_tpu.models.gbm import GBM

    rng = np.random.default_rng(9)
    n = 400
    cols = {f"x{i}": rng.normal(size=n).astype(np.float32) for i in range(4)}
    cols["y"] = np.array(["no", "yes"], dtype=object)[
        (rng.random(n) < 1 / (1 + np.exp(-2 * cols["x0"]))).astype(int)]
    fr = Frame.from_arrays(cols)
    gbm = GBM(ntrees=3, max_depth=3, nbins=16, seed=2).train(
        y="y", training_frame=fr)
    assert r0["gbm_logloss"] == pytest.approx(
        float(gbm.training_metrics.logloss), abs=1e-5)


@pytest.mark.slow
def test_four_process_cloud(tmp_path):
    """The reference contract scales to 4 JVMs (``multiNodeUtils.sh``):
    4 processes x 2 devices must train the identical model too."""
    script = os.path.join(REPO, "tests", "scripts", "multiproc_train.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "h2o3_tpu.launch", "--fork", "4",
         "--devices-per-process", "2", "--port", "7457",
         script, str(tmp_path)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stderr[-4000:]
    rs = []
    for i in range(4):
        with open(tmp_path / f"proc{i}.json") as f:
            rs.append(json.load(f))
    for r in rs[1:]:
        assert rs[0]["gbm_logloss"] == pytest.approx(r["gbm_logloss"],
                                                     abs=1e-7)
        assert rs[0]["glm_logloss"] == pytest.approx(r["glm_logloss"],
                                                     abs=1e-7)
        np.testing.assert_allclose(rs[0]["glm_coef"], r["glm_coef"],
                                   rtol=1e-6)
        np.testing.assert_allclose(rs[0]["pred_head"], r["pred_head"],
                                   rtol=1e-6)
