"""Scoring-artifact + explainability tests: MOJO round-trip, Generic import,
TreeSHAP contributions, variable importances (reference test model:
``h2o-py/tests/testdir_misc/pyunit_mojo_model.py``, genmodel TreeSHAP suites)."""

import itertools

import numpy as np
import pytest

import h2o3_tpu as h2o
from h2o3_tpu import Frame
from h2o3_tpu.models import GBM, DRF, GLM


@pytest.fixture
def bin_frame(rng):
    n = 1200
    X = rng.normal(size=(n, 4))
    y = (1.5 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
         + 0.3 * rng.normal(size=n)) > 0
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = np.array(["yes" if t else "no" for t in y], dtype=object)
    return Frame.from_arrays(cols)


def test_mojo_roundtrip(bin_frame, tmp_path):
    m = GBM(ntrees=8, max_depth=3).train(y="y", training_frame=bin_frame)
    p = m.download_mojo(str(tmp_path / "model.mojo"))
    from h2o3_tpu.genmodel import MojoModel
    mojo = MojoModel.load(p)
    assert mojo.algo == "gbm" and mojo.nclasses == 2
    np.testing.assert_allclose(np.asarray(mojo._score_raw(bin_frame)),
                               np.asarray(m._score_raw(bin_frame)), atol=1e-6)


def test_generic_import(bin_frame, tmp_path):
    m = GLM(family="binomial").train(y="y", training_frame=bin_frame)
    p = m.download_mojo(str(tmp_path / "glm.mojo"))
    g = h2o.import_mojo(p)
    assert g.algo == "generic" and g.output["source_algo"] == "glm"
    pred = g.predict(bin_frame)
    ref = m.predict(bin_frame)
    np.testing.assert_allclose(pred.vec("pyes").to_numpy(),
                               ref.vec("pyes").to_numpy(), atol=1e-6)
    mm = g.model_performance(bin_frame)
    assert abs(mm.auc - m.training_metrics.auc) < 1e-6


def test_varimp(bin_frame):
    m = GBM(ntrees=20, max_depth=4).train(y="y", training_frame=bin_frame)
    vi = m.varimp()
    names = [r[0] for r in vi]
    # x0 has the strongest main effect
    assert names[0] == "x0"
    assert vi[0][2] == 1.0                      # scaled importance of top = 1
    assert abs(sum(r[3] for r in vi) - 1.0) < 1e-6   # percentages sum to 1


def test_contributions_additivity(bin_frame):
    import jax
    import scipy.special

    m = GBM(ntrees=10, max_depth=3, learn_rate=0.2) \
        .train(y="y", training_frame=bin_frame)
    contrib = m.predict_contributions(bin_frame)
    assert contrib.names == ["x0", "x1", "x2", "x3", "BiasTerm"]
    phi = np.column_stack([contrib.vec(c).to_numpy() for c in contrib.names])
    # local accuracy: contributions sum to the model's raw LOGIT margin
    p = m.predict(bin_frame).vec("pyes").to_numpy()
    logit = scipy.special.logit(np.clip(p, 1e-7, 1 - 1e-7))
    np.testing.assert_allclose(phi.sum(axis=1), logit, atol=1e-3)

    # DRF contributions sum to the predicted class-1 fraction
    mr = DRF(ntrees=10, max_depth=3).train(y="y", training_frame=bin_frame)
    cr = mr.predict_contributions(bin_frame)
    phir = np.column_stack([cr.vec(c).to_numpy() for c in cr.names])
    pr = mr.predict(bin_frame).vec("pyes").to_numpy()
    np.testing.assert_allclose(phir.sum(axis=1), pr, atol=1e-3)


def test_treeshap_matches_bruteforce(rng):
    """Exact parity with brute-force Shapley values on one small tree."""
    from h2o3_tpu.genmodel.treeshap import tree_shap

    n = 400
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = np.where(X[:, 0] > 0, 2.0, -1.0) + np.where(X[:, 1] > 0.5, 1.0, 0.0)
    f = Frame.from_arrays({f"x{i}": X[:, i] for i in range(3)} | {"y": y})
    m = GBM(ntrees=1, max_depth=2, learn_rate=1.0, min_rows=1.0) \
        .train(y="y", training_frame=f)
    tree = m.output["trees"][0]

    import jax
    feat = np.asarray(jax.device_get(tree.feat))
    tv = np.asarray(jax.device_get(tree.thresh_val))
    nal = np.asarray(jax.device_get(tree.na_left))
    isp = np.asarray(jax.device_get(tree.is_split))
    leaf = np.asarray(jax.device_get(tree.leaf)).astype(np.float64)
    cover = np.asarray(jax.device_get(tree.cover)).astype(np.float64)

    def cond_exp(x, known: set[int], node=0) -> float:
        """E[f(X) | X_known = x_known] under the tree's cover distribution."""
        if not isp[node]:
            return leaf[node]
        d = int(feat[node])
        l, r = 2 * node + 1, 2 * node + 2
        if d in known:
            go_l = (nal[node] if np.isnan(x[d]) else x[d] < tv[node])
            return cond_exp(x, known, l if go_l else r)
        wl = cover[l] / max(cover[node], 1e-12)
        return wl * cond_exp(x, known, l) + (1 - wl) * cond_exp(x, known, r)

    import math
    rows = X[:5]
    phi = tree_shap(tree, rows)
    F = 3
    for ri, x in enumerate(rows):
        for j in range(F):
            val = 0.0
            others = [k for k in range(F) if k != j]
            for size in range(F):
                for S in itertools.combinations(others, size):
                    wgt = (math.factorial(len(S)) * math.factorial(F - len(S) - 1)
                           / math.factorial(F))
                    val += wgt * (cond_exp(x, set(S) | {j}) - cond_exp(x, set(S)))
            assert abs(phi[ri, j] - val) < 1e-5, (ri, j, phi[ri, j], val)
