"""Native C++ CSV parser tests — parity against pandas on the same input
(reference test model: ``h2o-py/tests/testdir_parser/``)."""

import numpy as np
import pandas as pd
import pytest

from h2o3_tpu.native import get_lib, parse_csv_native

pytestmark = pytest.mark.skipif(get_lib() is None,
                                reason="native toolchain unavailable")


def test_parse_basic():
    data = b"a,b,c\n1,2.5,x\n3,NA,y\n-4.5,0,x\n"
    names, cols = parse_csv_native(data)
    assert names == ["a", "b", "c"]
    assert cols[0][0] == "num"
    np.testing.assert_allclose(cols[0][1], [1, 3, -4.5])
    assert np.isnan(cols[1][1][1])
    kind, codes, dom = cols[2]
    assert kind == "cat" and dom == ("x", "y")
    assert codes.tolist() == [0, 1, 0]


def test_parse_quotes_and_embedded():
    data = b'name,v\n"hello, world",1\n"say ""hi""",2\n"line\nbreak",3\n'
    names, cols = parse_csv_native(data)
    assert names == ["name", "v"]
    kind, codes, dom = cols[0]
    assert set(dom) == {"hello, world", 'say "hi"', "line\nbreak"}
    np.testing.assert_allclose(cols[1][1], [1, 2, 3])


def test_parse_mixed_numeric_in_cat():
    data = b"g\nred\n3\nred\nblue\n"
    _, cols = parse_csv_native(data)
    kind, codes, dom = cols[0]
    assert kind == "cat" and dom == ("3", "blue", "red")
    assert codes.tolist() == [2, 0, 2, 1]


def test_parse_mixed_keeps_exact_numeric_text():
    # distinct long numerics must stay distinct levels (no %g collapsing)
    data = b"g\n1234567\n1234568\nx\n3.10\n"
    _, cols = parse_csv_native(data)
    _, codes, dom = cols[0]
    assert set(dom) == {"1234567", "1234568", "x", "3.10"}


def test_parse_plus_prefix_and_na_tokens():
    data = b"v,s\n+3.5,-\n-2,na\n1e3,ok\n"
    _, cols = parse_csv_native(data)
    kind, arr = cols[0]
    assert kind == "num"
    np.testing.assert_allclose(arr, [3.5, -2.0, 1000.0])
    # '-' and 'na' are NOT missing (pandas parity) — they are levels
    kind, codes, dom = cols[1]
    assert set(dom) == {"-", "na", "ok"}


def test_parse_crlf_blank_lines():
    data = b"a,b\r\n1,2\r\n\r\n3,4\r\n"
    names, cols = parse_csv_native(data)
    np.testing.assert_allclose(cols[0][1], [1, 3])


def test_quoted_header_falls_back():
    data = b'"Revenue, USD",x\n1,2\n'
    assert parse_csv_native(data) is None   # caller falls back to pandas


def test_parse_parallel_matches_pandas(rng, tmp_path):
    n = 20_000
    df = pd.DataFrame({
        "x": rng.normal(size=n).round(6),
        "i": rng.integers(-1000, 1000, size=n),
        "g": rng.choice(["aa", "bb", "cc", "dd"], size=n),
    })
    # sprinkle NAs
    df.loc[df.sample(n=500, random_state=1).index, "x"] = np.nan
    p = tmp_path / "big.csv"
    df.to_csv(p, index=False)
    data = p.read_bytes()

    names, cols = parse_csv_native(data, nthreads=8)
    assert names == ["x", "i", "g"]
    ref = pd.read_csv(p)
    np.testing.assert_allclose(cols[0][1], ref["x"].to_numpy(), rtol=1e-9,
                               equal_nan=True)
    np.testing.assert_allclose(cols[1][1], ref["i"].to_numpy())
    _, codes, dom = cols[2]
    decoded = np.array(dom, dtype=object)[codes]
    np.testing.assert_array_equal(decoded, ref["g"].to_numpy(dtype=object))


def test_import_file_uses_native(rng, tmp_path):
    import h2o3_tpu as h2o
    n = 500
    df = pd.DataFrame({"x": rng.normal(size=n), "g": rng.choice(["u", "v"], n)})
    p = tmp_path / "f.csv"
    df.to_csv(p, index=False)
    fr = h2o.import_file(str(p))
    assert fr.nrows == n
    np.testing.assert_allclose(fr.vec("x").to_numpy(), df["x"], rtol=1e-6)
    assert fr.vec("g").domain == ("u", "v")
    assert fr.vec("g").labels().tolist() == list(df["g"])
