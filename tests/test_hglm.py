"""HGLM (mixed-effects GLM) — reference GLMModel.java HGLM surface."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.hglm import HGLM


def _mixed_data(rng, n_groups=20, per=60, sig_u=2.0, sig_e=0.5,
                rand_slope=False):
    g = np.repeat(np.arange(n_groups), per)
    x = rng.normal(size=n_groups * per).astype(np.float32)
    u0 = rng.normal(scale=sig_u, size=n_groups)
    y = 1.5 * x + 0.7 + u0[g]
    if rand_slope:
        u1 = rng.normal(scale=1.0, size=n_groups)
        y = y + u1[g] * x
    y = (y + rng.normal(scale=sig_e, size=len(g))).astype(np.float32)
    fr = Frame.from_arrays({
        "grp": np.array([f"g{i:02d}" for i in range(n_groups)],
                        dtype=object)[g],
        "x": x, "y": y})
    return fr, u0, g


def test_hglm_random_intercept(rng):
    fr, u0, g = _mixed_data(rng)
    m = HGLM(group_column="grp", max_iterations=60).train(
        y="y", training_frame=fr)

    # fixed effects recovered
    coef = dict(zip(m.output["coef_names"], m.output["coef"]))
    assert coef["x"] == pytest.approx(1.5, abs=0.1)
    # variance components near truth (sig_u^2=4, sig_e^2=0.25)
    assert m.output["sig_u"] == pytest.approx(4.0, rel=0.6)
    assert m.output["sig_e"] == pytest.approx(0.25, rel=0.4)
    # BLUPs track the simulated group intercepts (shrunken)
    u = np.array([m.ranef()[f"g{i:02d}"]["intercept"] for i in range(20)])
    assert np.corrcoef(u, u0)[0, 1] > 0.95

    # group-aware predictions beat fixed-only predictions
    pred = m.predict(fr).vec("predict").to_numpy()
    y = fr.vec("y").to_numpy()
    resid = np.sqrt(np.mean((pred - y) ** 2))
    assert resid < 0.7, resid

    from h2o3_tpu.models.glm import GLM
    plain = GLM(family="gaussian").train(y="y", x=["x"], training_frame=fr)
    plain_res = np.sqrt(np.mean(
        (plain.predict(fr).vec("predict").to_numpy() - y) ** 2))
    assert resid < 0.5 * plain_res


def test_hglm_random_slope(rng):
    fr, _, _ = _mixed_data(rng, rand_slope=True)
    m = HGLM(group_column="grp", random_columns=["x"],
             max_iterations=60).train(y="y", training_frame=fr)
    pred = m.predict(fr).vec("predict").to_numpy()
    y = fr.vec("y").to_numpy()
    assert np.sqrt(np.mean((pred - y) ** 2)) < 0.7
    # ranef carries both intercept and slope entries
    r = m.ranef()["g00"]
    assert set(r) == {"intercept", "x"}


def test_hglm_unseen_group_scores_fixed_only(rng):
    fr, _, _ = _mixed_data(rng)
    m = HGLM(group_column="grp", max_iterations=40).train(
        y="y", training_frame=fr)
    new = Frame.from_arrays({
        "grp": np.array(["zz_new"] * 4, dtype=object),
        "x": np.float32([0, 1, -1, 2])})
    pred = m.predict(new).vec("predict").to_numpy()
    coef = dict(zip(m.output["coef_names"], m.output["coef"]))
    want = coef["x"] * np.float32([0, 1, -1, 2]) + m.output["coef"][-1]
    np.testing.assert_allclose(pred, want, atol=1e-4)


def test_hglm_validation(rng):
    fr, _, _ = _mixed_data(rng, n_groups=4, per=10)
    with pytest.raises(ValueError, match="group_column"):
        HGLM().train(y="y", training_frame=fr)
    with pytest.raises(ValueError, match="categorical"):
        HGLM(group_column="x").train(y="y", training_frame=fr)
