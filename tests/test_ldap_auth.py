"""LDAP bind auth (VERDICT r4 next #10; reference ``water/H2O.java:242-266``
-ldap_login via JAAS LdapLoginModule).

A fake in-process LDAP server speaks just enough RFC 4511 — parse the BER
BindRequest, check DN + password, answer a BindResponse — to prove the
pure-Python client end-to-end, including the full REST stack gated behind
the authenticator.
"""

import base64
import json
import socket
import threading
import urllib.error
import urllib.request

import pytest

from h2o3_tpu.api.ldap_auth import (
    bind_request, ldap_authenticator, ldap_simple_bind, parse_bind_response,
)


class FakeLdapServer:
    """Accepts binds for one (dn, password) pair; 49 otherwise."""

    def __init__(self, dn: str, password: str):
        self.dn, self.password = dn, password
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(5)
        self.port = self.sock.getsockname()[1]
        self.seen: list[tuple[str, str]] = []
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self):
        from h2o3_tpu.api.ldap_auth import _read_tlv
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with conn:
                try:
                    data = conn.recv(8192)
                    _, msg, _ = _read_tlv(data, 0)
                    _, mid, pos = _read_tlv(msg, 0)         # messageID
                    _, op, _ = _read_tlv(msg, pos)           # BindRequest
                    _, _ver, p = _read_tlv(op, 0)
                    _, dn, p = _read_tlv(op, p)
                    _, pw, _ = _read_tlv(op, p)
                    dn, pw = dn.decode(), pw.decode()
                    self.seen.append((dn, pw))
                    ok = dn == self.dn and pw == self.password
                    code = 0 if ok else 49                   # invalidCredentials
                    body = (b"\x0a\x01" + bytes([code])      # resultCode
                            + b"\x04\x00\x04\x00")           # matchedDN, msg
                    resp = (b"\x61" + bytes([len(body)]) + body)
                    lm = b"\x02\x01" + mid + resp
                    conn.sendall(b"\x30" + bytes([len(lm)]) + lm)
                except Exception:
                    pass

    def close(self):
        self._stop = True
        self.sock.close()


@pytest.fixture
def ldap():
    s = FakeLdapServer("uid=alice,ou=people,dc=example,dc=org", "s3cret")
    yield s
    s.close()


def test_ber_roundtrip():
    req = bind_request(1, "uid=x,dc=y", "pw")
    # hand-decode: LDAPMessage { messageID, [APPLICATION 0] { 3, dn, pw } }
    from h2o3_tpu.api.ldap_auth import _read_tlv
    tag, msg, _ = _read_tlv(req, 0)
    assert tag == 0x30
    tag, mid, pos = _read_tlv(msg, 0)
    assert (tag, mid) == (0x02, b"\x01")
    tag, op, _ = _read_tlv(msg, pos)
    assert tag == 0x60
    _, ver, p = _read_tlv(op, 0)
    assert ver == b"\x03"
    _, dn, p = _read_tlv(op, p)
    assert dn == b"uid=x,dc=y"
    tag, pw, _ = _read_tlv(op, p)
    assert (tag, pw) == (0x80, b"pw")


def test_parse_bind_response_codes():
    ok = b"\x30\x0c\x02\x01\x01\x61\x07\x0a\x01\x00\x04\x00\x04\x00"
    bad = b"\x30\x0c\x02\x01\x01\x61\x07\x0a\x01\x31\x04\x00\x04\x00"
    assert parse_bind_response(ok) == 0
    assert parse_bind_response(bad) == 49
    with pytest.raises(ValueError):
        parse_bind_response(b"\x04\x02hi")


def test_simple_bind_against_fake_server(ldap):
    url = f"ldap://127.0.0.1:{ldap.port}"
    good = "uid=alice,ou=people,dc=example,dc=org"
    assert ldap_simple_bind(url, good, "s3cret")
    assert not ldap_simple_bind(url, good, "wrong")
    assert not ldap_simple_bind(url, "uid=bob,dc=example,dc=org", "s3cret")
    # RFC 4513: empty password must be rejected client-side
    assert not ldap_simple_bind(url, good, "")


def test_authenticator_templates_and_escapes(ldap):
    auth = ldap_authenticator(f"ldap://127.0.0.1:{ldap.port}",
                              "uid={},ou=people,dc=example,dc=org")
    assert auth("alice", "s3cret")
    assert not auth("alice", "nope")
    assert not auth("", "s3cret")
    # DN metacharacters in the login name must be escaped, not injected
    assert not auth("alice,ou=admins", "s3cret")
    assert any("\\," in dn for dn, _ in ldap.seen)


def test_connection_refused_rejects_closed():
    auth = ldap_authenticator("ldap://127.0.0.1:1",     # nothing listens
                              "uid={},dc=x")
    assert not auth("alice", "pw")


def test_rest_stack_behind_ldap(ldap):
    """The full contract: Basic credentials on the REST API resolve
    through the LDAP bind (reference: every request passes the JAAS
    login)."""
    from h2o3_tpu.api import H2OServer

    auth = ldap_authenticator(f"ldap://127.0.0.1:{ldap.port}",
                              "uid={},ou=people,dc=example,dc=org")
    s = H2OServer(port=0, authenticator=auth).start()
    try:
        def cloud(user, pw):
            req = urllib.request.Request(s.url + "/3/Cloud")
            cred = base64.b64encode(f"{user}:{pw}".encode()).decode()
            req.add_header("Authorization", f"Basic {cred}")
            with urllib.request.urlopen(req) as r:
                return r.status, json.loads(r.read())

        st, body = cloud("alice", "s3cret")
        assert st == 200 and body["cloud_healthy"]
        with pytest.raises(urllib.error.HTTPError) as e:
            cloud("alice", "wrong")
        assert e.value.code == 401
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(s.url + "/3/Cloud")   # no creds at all
        assert e.value.code == 401
    finally:
        s.stop()


def test_launch_flag_validation():
    from h2o3_tpu.launch import main
    with pytest.raises(SystemExit):
        main(["--serve", "--ldap-login", "ldap://x"])    # missing template
