"""Orchestration tests — grid search, leaderboard, stacked ensemble, AutoML
(reference test model: ``h2o-py/tests/testdir_algos/grid``,
``testdir_algos/stackedensemble``, ``testdir_algos/automl``)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models import GBM, GLM
from h2o3_tpu.orchestration import AutoML, GridSearch, Leaderboard, StackedEnsemble


def _binom_frame(rng, n=1200):
    X = rng.normal(size=(n, 4))
    logits = 1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(int)
    cols = {f"x{i}": X[:, i] for i in range(4)}
    cols["y"] = np.array(["yes" if v else "no" for v in y], dtype=object)
    return Frame.from_arrays(cols)


def _multi_frame(rng, n=1500):
    X = rng.normal(size=(n, 3))
    scores = np.stack([0.9 * X[:, 0], -0.7 * X[:, 1], 0.8 * X[:, 2]], axis=1)
    y = scores.argmax(axis=1)
    cols = {f"x{i}": X[:, i] for i in range(3)}
    cols["y"] = np.array([f"c{v}" for v in y], dtype=object)
    return Frame.from_arrays(cols), X, y


def test_glm_multinomial(rng):
    f, X, y = _multi_frame(rng)
    m = GLM(family="multinomial", lambda_=0.0).train(y="y", training_frame=f)
    assert m.nclasses == 3
    pred = m.predict(f)
    assert pred.vec("predict").labels()[0] in ("c0", "c1", "c2")
    acc = (pred.vec("predict").to_numpy() == y).mean()

    from sklearn.linear_model import LogisticRegression
    sk = LogisticRegression(max_iter=300).fit(X, y)
    sk_acc = (sk.predict(X) == y).mean()
    assert acc > sk_acc - 0.02, (acc, sk_acc)
    assert m.training_metrics.logloss < 0.6


def test_glm_non_negative_matches_nnls(rng):
    # correlated predictors: clipping the OLS solution is NOT the NNLS optimum,
    # so this catches a projected-IRLS that fails to re-solve
    n = 1000
    base = rng.normal(size=n)
    X = np.stack([base + 0.05 * rng.normal(size=n),
                  base + 0.05 * rng.normal(size=n),
                  rng.normal(size=n)], axis=1)
    y = 1.0 * X[:, 0] - 0.3 * X[:, 1] + rng.normal(scale=0.1, size=n)
    f = Frame.from_arrays({"a": X[:, 0], "b": X[:, 1], "c": X[:, 2], "y": y})
    m = GLM(family="gaussian", non_negative=True, standardize=False,
            max_iterations=50).train(y="y", training_frame=f)
    coef = m.coef()
    assert all(coef[k] >= 0.0 for k in ("a", "b", "c"))

    from scipy.optimize import nnls
    A = np.column_stack([X, np.ones(n)])
    # intercept unconstrained: shift so the reference solve is pure NNLS
    ref, _ = nnls(np.column_stack([X, np.ones(n), -np.ones(n)]),
                  y)
    ref_coefs = ref[:3]
    np.testing.assert_allclose([coef["a"], coef["b"], coef["c"]], ref_coefs,
                               atol=5e-3)


def test_glm_multinomial_binary_response(rng):
    n = 800
    X = rng.normal(size=(n, 2))
    y = (X[:, 0] - X[:, 1] > 0).astype(int)
    f = Frame.from_arrays({"a": X[:, 0], "b": X[:, 1],
                           "y": np.array(["n", "p"], dtype=object)[y]})
    m = GLM(family="multinomial").train(y="y", training_frame=f)
    assert m.nclasses == 2
    acc = (m.predict(f).vec("predict").to_numpy() == y).mean()
    assert acc > 0.95


def test_grid_search_cartesian(rng):
    f = _binom_frame(rng)
    gs = GridSearch(GBM, {"max_depth": [2, 4], "learn_rate": [0.1, 0.3]},
                    ntrees=5)
    grid = gs.train(y="y", training_frame=f)
    assert len(grid.models) == 4
    depths = sorted(m.output["hyper_values"]["max_depth"] for m in grid.models)
    assert depths == [2, 2, 4, 4]
    ranked = grid.sorted_models("auc")
    aucs = [m.training_metrics.auc for m in ranked]
    assert aucs == sorted(aucs, reverse=True)


def test_grid_search_random_budget(rng):
    f = _binom_frame(rng, n=600)
    gs = GridSearch(GBM, {"max_depth": [2, 3, 4, 5], "learn_rate": [0.1, 0.2, 0.3]},
                    search_criteria={"strategy": "RandomDiscrete",
                                     "max_models": 3, "seed": 7},
                    ntrees=3)
    grid = gs.train(y="y", training_frame=f)
    assert len(grid.models) == 3


def test_leaderboard_ranks(rng):
    f = _binom_frame(rng)
    lb = Leaderboard()
    m1 = GBM(ntrees=15, max_depth=4).train(y="y", training_frame=f)
    m2 = GLM(family="binomial").train(y="y", training_frame=f)
    lb.add(m1)
    lb.add(m2)
    assert len(lb) == 2
    # GBM captures the interaction term; GLM cannot
    assert lb.leader.algo == "gbm"
    lf = lb.as_frame()
    assert "auc" in lf.names and lf.nrows == 2


def test_stacked_ensemble_binomial(rng):
    f = _binom_frame(rng, n=1500)
    common = dict(nfolds=3, keep_cross_validation_predictions=True)
    m1 = GBM(ntrees=15, max_depth=4, **common).train(y="y", training_frame=f)
    m2 = GLM(family="binomial", **common).train(y="y", training_frame=f)
    se = StackedEnsemble(base_models=[m1, m2]).train(y="y", training_frame=f)
    assert se.training_metrics.auc >= min(m1.training_metrics.auc,
                                          m2.training_metrics.auc) - 0.01
    pred = se.predict(f)
    assert set(pred.names) == {"predict", "pno", "pyes"}
    meta_coef = se.output["metalearner"].coef()
    # AUTO metalearner is non-negative GLM (reference default)
    assert all(v >= 0 for k, v in meta_coef.items() if k != "Intercept")


def test_stacked_ensemble_requires_cv(rng):
    f = _binom_frame(rng, n=400)
    m = GBM(ntrees=3).train(y="y", training_frame=f)
    with pytest.raises(ValueError, match="keep_cross_validation_predictions"):
        StackedEnsemble(base_models=[m]).train(y="y", training_frame=f)


def test_automl_small(rng):
    f = _binom_frame(rng, n=800)
    aml = AutoML(max_models=4, nfolds=3, seed=1,
                 include_algos=["GLM", "GBM", "DRF", "STACKEDENSEMBLE"])
    leader = aml.train(y="y", training_frame=f)
    assert leader is not None
    assert len(aml.leaderboard) >= 4
    algos = {m.algo for m in aml.leaderboard.models}
    assert "gbm" in algos and "glm" in algos
    assert any("model" == s for _, _, s, _, _, _ in aml.event_log.events)
    # leaderboard sorted by AUC descending
    aucs = []
    for r in aml.leaderboard._sorted():
        aucs.append(r["auc"])
    assert aucs == sorted(aucs, reverse=True)


def test_automl_exploitation_and_te(rng):
    """Exploitation phase (lr-annealed incumbent) + TE preprocessing
    (reference: ModelingPlans exploitation, automl/preprocessing)."""
    from h2o3_tpu.orchestration.automl import AutoML

    n = 500
    levels = [f"city{i:02d}" for i in range(30)]     # high-cardinality enum
    city = rng.choice(levels, size=n)
    effect = {lv: rng.normal() for lv in levels}
    x1 = rng.normal(size=n).astype(np.float32)
    logit = np.array([effect[c] for c in city]) + x1
    y = rng.random(n) < 1 / (1 + np.exp(-logit))
    fr = Frame.from_arrays({
        "city": city, "x1": x1,
        "y": np.array(["no", "yes"], dtype=object)[y.astype(int)]})

    # max_models >= 5: smaller budgets deliberately skip the exploitation
    # reserve (round-3 WorkAllocations semantics) so the base plan isn't
    # starved — the annealing assertion needs a budget that reserves a slot
    aml = AutoML(max_models=5, nfolds=0, seed=7,
                 include_algos=["GBM", "STACKEDENSEMBLE"],
                 preprocessing=["target_encoding"],
                 exploitation_ratio=0.2)
    leader = aml.train(y="y", training_frame=fr)
    assert leader is not None
    events = " ".join(aml.event_log.as_list())
    assert "target-encoded" in events
    assert "lr-annealed" in events
